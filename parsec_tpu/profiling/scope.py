"""Request-scoped observability: end-to-end tracing, SLO metrics,
plan-vs-measured conformance.

Reference role: PaRSEC's PINS layer attributes cost to workers and task
classes; a serving runtime must attribute it to the work unit the USER
cares about — the request.  This module is that attribution layer:

  ScopeRegistry     one per Context.  Allocates request-scope ids
                    (stamped into taskpools via ptc_tp_set_scope, beside
                    the PR 9 QoS stamp), tracks each request's lifecycle
                    (submit -> admitted -> first token -> done), folds
                    per-tenant SLO histograms (TTFT, queue wait,
                    admission-to-done latency, tokens/s) + reject/shed
                    counters, and records plan-vs-measured conformance
                    at every pool retirement.  Exported through
                    Context.stats()["scope"] and — tenant-labelled —
                    through the PR 7 Prometheus endpoint.

  request_timeline  reconstructs ONE request's full multi-rank story
                    from a (merged) Trace: admission wait, lane/sched
                    wait, per-wave EXEC, page h2d, wire hops — a
                    PARTITION of the request's end-to-end latency (the
                    stages sum to it exactly; "lane_wait" is the
                    measured residual between the pool's wall window
                    and its attributed work).

  conformance       the always-on honesty signal ROADMAP item 5's
                    autotuner regresses against: per-pool ptc-plan
                    predictions (est_bytes, makespan lower bound, wire
                    byte bound, spill verdict) vs measured counters,
                    plus per-class calibration ratios (cost-model ns vs
                    the live metrics histograms' p50).

Clock note: request timestamps and trace events both read the NATIVE
ptc_now_ns clock (exported as ptc_clock_ns), so ticket times and
(rank-0-referenced, merged) trace spans live on one axis — the TSC fast
path's epoch drifts from CLOCK_MONOTONIC over long processes, so
time.monotonic_ns would misalign the windows by milliseconds.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from .metrics import _BUCKETS, Hist

__all__ = ["ScopeRegistry", "ScopeHist", "request_timeline"]


# ------------------------------------------------------------ histogram
def _bucket_of(v: int) -> int:
    """Python mirror of the native log2/3-sub-bit bucket index
    (runtime_internal.h ptc_met_bucket) — tenant histograms quantize
    exactly like the native per-class ones, so quantiles compare."""
    _SUB, _SUBBITS, _MAX_OCT = 8, 3, 45
    if v < _SUB:
        return 0 if v < 0 else int(v)
    oct_ = int(v).bit_length() - 1
    if oct_ >= _MAX_OCT:
        return _BUCKETS - 1
    sub = (int(v) >> (oct_ - _SUBBITS)) & (_SUB - 1)
    return _SUB + (oct_ - _SUBBITS) * _SUB + sub


class ScopeHist:
    """Small single-writer histogram over the native bucket scheme.
    Values are any positive integers (ns for latencies, integer
    tokens/s for rates); quantiles ride metrics.Hist's estimator."""

    __slots__ = ("count", "sum", "buckets")

    def __init__(self):
        self.count = 0
        self.sum = 0
        self.buckets = np.zeros(_BUCKETS, dtype=np.int64)

    def record(self, v) -> None:
        v = int(v)
        self.count += 1
        if v > 0:
            self.sum += v
        self.buckets[_bucket_of(v)] += 1

    def quantile(self, q: float) -> float:
        return Hist(0, -1, None, self.count, self.sum,
                    self.buckets).quantile(q)

    def summary(self) -> dict:
        return {"count": self.count, "sum": self.sum,
                "p50": round(self.quantile(0.50), 1),
                "p99": round(self.quantile(0.99), 1)}


# -------------------------------------------------------------- records
class _Request:
    __slots__ = ("scope_id", "tenant", "kind", "rid", "meta", "state",
                 "submitted_ns", "admitted_ns", "first_token_ns",
                 "done_ns", "tokens", "pools", "qos", "plan", "measured",
                 "class_names")

    def __init__(self, scope_id, tenant, kind, rid, meta):
        self.scope_id = scope_id
        self.tenant = tenant
        self.kind = kind
        self.rid = rid
        self.meta = meta
        self.state = "submitted"
        self.submitted_ns: Optional[int] = None
        self.admitted_ns: Optional[int] = None
        self.first_token_ns: Optional[int] = None
        self.done_ns: Optional[int] = None
        self.tokens = 0
        self.pools: List[int] = []          # native tp ids stamped
        self.class_names: List[str] = []     # class id -> name (per pool)
        self.qos: Optional[dict] = None      # last pool's QoS counters
        self.plan: Optional[dict] = None     # ptc-plan predictions
        self.measured: Optional[dict] = None


class _Tenant:
    __slots__ = ("slo_ms", "burn_threshold", "window", "counters",
                 "hists")

    def __init__(self, slo_ms=None, burn_threshold=0.5, window_n=128):
        self.slo_ms = slo_ms
        self.burn_threshold = float(burn_threshold)
        # sliding outcome window: True = SLO violated
        self.window: deque = deque(maxlen=int(window_n))
        self.counters = {"submitted": 0, "completed": 0, "failed": 0,
                         "rejected": 0, "slo_violations": 0,
                         "prefix_hits": 0, "prefix_misses": 0,
                         "spec_proposed": 0, "spec_accepted": 0,
                         "coll_waves": 0}
        self.hists = {"ttft_ns": ScopeHist(), "queue_wait_ns": ScopeHist(),
                      "latency_ns": ScopeHist(), "tokens_per_s": ScopeHist(),
                      # ptc-share: per-verify-wave draft acceptance, in
                      # whole percent (0..100) of proposed tokens
                      "spec_accept_pct": ScopeHist(),
                      # ptc-shard: per decode step, the critical-path
                      # exposure to the embedded tp all-reduce — local
                      # shard done -> reduced pre-logits delivered
                      "coll_wait_ns": ScopeHist()}


def _now_ns() -> int:
    """The NATIVE trace clock (ptc_now_ns), not time.monotonic_ns:
    request windows must align with trace span timestamps exactly, and
    the TSC fast path's epoch drifts from CLOCK_MONOTONIC by
    milliseconds over a long-lived process."""
    from .. import _native as N
    return int(N.lib.ptc_clock_ns())


# ------------------------------------------------------------- registry
class ScopeRegistry:
    """Per-context request-scope bookkeeping (see module docstring).
    Thread-safe: the serve pump, submitter threads, the engine driver
    and exporter scrapes all touch it concurrently."""

    def __init__(self, ctx, slo_window: int = 128):
        self.ctx = ctx
        self._lock = threading.Lock()
        self._next = 1
        self.slo_window = int(slo_window)
        self.requests: Dict[int, _Request] = {}
        self.tenants: Dict[str, _Tenant] = {}
        self._by_rid: Dict[object, int] = {}
        # decode-style shared pools: scope -> ordered member rids
        self._members: Dict[int, List[object]] = {}
        # conformance aggregates — EPOCHED (ptc-pilot): the fold-only
        # counters roll to a fresh generation every conformance_window
        # retired pools (one closed generation kept), so a long soak's
        # rollup reads the RECENT plan-vs-measured ratio in O(window)
        # state instead of a run-lifetime average the drift detector
        # could never move
        from ..utils import params as _mca
        try:
            self.conformance_window = int(
                _mca.get("scope.conformance_window"))
        except Exception:
            self.conformance_window = 2048
        self._conf_prev: Optional[dict] = None  # closed epoch fold
        self._conf_epochs = 0
        self._pools_done = 0
        self._pools_planned = 0
        self._unplanned = 0
        self._pred_wire_bytes = 0
        self._pred_est_bytes = 0
        self._makespan_ratios: deque = deque(maxlen=512)
        self._spill_pred_nonzero = 0
        self._per_class_cost: Dict[str, float] = {}  # last planned ns
        # structured decision log (fleet router placements, re-routes,
        # migrations): bounded ring so a long-lived router can't grow it
        self._events: deque = deque(maxlen=1024)
        try:
            self._comm_base = (ctx.comm_stats()["bytes_sent"]
                               if ctx.comm_enabled else 0)
        except Exception:
            self._comm_base = 0

    # -------------------------------------------------------- lifecycle
    def tenant(self, name: str, slo_ms=None, burn_threshold=None,
               ) -> _Tenant:
        """Get-or-create a tenant rollup; keyword args update config."""
        with self._lock:
            t = self.tenants.get(name)
            if t is None:
                t = self.tenants[name] = _Tenant(
                    window_n=self.slo_window)
            if slo_ms is not None:
                t.slo_ms = float(slo_ms)
            if burn_threshold is not None:
                t.burn_threshold = float(burn_threshold)
            return t

    def new_scope(self, tenant: str = "default", kind: str = "request",
                  rid=None, meta=None, members: Optional[list] = None,
                  ) -> int:
        """Allocate a scope id (sequential from 1 — SPMD-deterministic
        when allocation calls are SPMD).  `members` marks a SHARED pool
        (one continuous-batching decode step): an ordered rid list so
        EXEC spans' first local (the sequence lane) map back to
        requests."""
        self.tenant(tenant)
        with self._lock:
            sid = self._next
            self._next += 1
            r = _Request(sid, tenant, kind, rid, meta)
            r.submitted_ns = _now_ns()
            self.requests[sid] = r
            if rid is not None and kind == "request":
                self._by_rid[rid] = sid
            if members is not None:
                self._members[sid] = list(members)
            if kind == "request":
                self.tenants[tenant].counters["submitted"] += 1
        return sid

    def stamp(self, tp, scope_id: int) -> None:
        """Stamp a taskpool with the scope (native ptc_tp_set_scope,
        beside the QoS stamp) and remember the pool id."""
        tp.set_scope(scope_id)
        with self._lock:
            r = self.requests.get(scope_id)
            if r is not None:
                try:
                    r.pools.append(tp.tp_id)
                except Exception:
                    pass
                # class-id -> name table of the stamped pool: trace
                # class ids are PER POOL, so per-scope naming is the
                # only unambiguous one (request_timeline wave rows)
                try:
                    r.class_names = [tc.name for tc in tp.classes]
                except Exception:
                    pass

    def record_admitted(self, scope_id: int, t_ns: Optional[int] = None):
        with self._lock:
            r = self.requests.get(scope_id)
            if r is not None:
                r.admitted_ns = t_ns if t_ns is not None else _now_ns()
                r.state = "running"

    def record_first_token(self, scope_id: int,
                           t_ns: Optional[int] = None):
        """TTFT boundary (the engine calls this when a request's
        prefill produces its first output token)."""
        with self._lock:
            r = self.requests.get(scope_id)
            if r is None or r.first_token_ns is not None:
                return
            r.first_token_ns = t_ns if t_ns is not None else _now_ns()
            if r.submitted_ns is not None:
                self.tenants[r.tenant].hists["ttft_ns"].record(
                    r.first_token_ns - r.submitted_ns)

    def record_rejected(self, scope_id: int):
        with self._lock:
            r = self.requests.get(scope_id)
            if r is None:
                return
            r.state = "rejected"
            r.done_ns = _now_ns()
            self.tenants[r.tenant].counters["rejected"] += 1

    def record_pool_done(self, scope_id: int, qos: Optional[dict] = None,
                         plan: Optional[dict] = None,
                         measured: Optional[dict] = None):
        """One POOL retired under this scope: fold the plan-vs-measured
        conformance record (a request scope may span several pools; a
        shared decode-step scope is exactly one)."""
        ratio = None
        with self._lock:
            r = self.requests.get(scope_id)
            if r is not None:
                if qos is not None:
                    r.qos = qos
                if plan is not None:
                    r.plan = plan
                if measured is not None:
                    r.measured = measured
            self._pools_done += 1
            if plan:
                self._pools_planned += 1
                if plan.get("est_bytes"):
                    self._pred_est_bytes += int(plan["est_bytes"])
                self._pred_wire_bytes += int(
                    plan.get("wire_out_bound_sum", 0))
                lb = plan.get("makespan_lb_ns")
                wall = (measured or {}).get("wall_ns")
                if lb and wall and lb > 0:
                    ratio = wall / lb
                    self._makespan_ratios.append(ratio)
                if plan.get("spills_predicted"):
                    self._spill_pred_nonzero += 1
                for cls, ns in (plan.get("per_class_cost") or {}).items():
                    self._per_class_cost[cls] = float(ns)
            else:
                self._unplanned += 1
            if self.conformance_window > 0 and \
                    self._pools_done >= self.conformance_window:
                self._conf_roll_locked()
        # ptc-pilot: the pool boundary IS the controller's clock — one
        # observation per retired pool (ratio None when unplanned),
        # delivered OUTSIDE the registry lock (the controller logs its
        # decisions back through record_event)
        ctrl = getattr(self.ctx, "_controller", None)
        if ctrl is not None:
            try:
                ctrl.observe_pool(ratio)
            except Exception:
                pass

    def _conf_roll_locked(self):
        """Close the current conformance epoch: fold it into
        `_conf_prev` (replacing the older generation) and zero the live
        counters.  The comm baseline advances so the closed epoch owns
        exactly the bytes sent during it — conformance() then merges
        the two generations, keeping coverage/soundness recent AND
        bounded."""
        bytes_now = self._comm_base
        try:
            if self.ctx.comm_enabled:
                bytes_now = self.ctx.comm_stats()["bytes_sent"]
        except Exception:
            pass
        self._conf_prev = {
            "pools": self._pools_done,
            "planned": self._pools_planned,
            "unplanned": self._unplanned,
            "pred_wire": self._pred_wire_bytes,
            "pred_est": self._pred_est_bytes,
            "spill_pred": self._spill_pred_nonzero,
            "measured_wire": max(0, bytes_now - self._comm_base),
        }
        self._conf_epochs += 1
        self._pools_done = 0
        self._pools_planned = 0
        self._unplanned = 0
        self._pred_wire_bytes = 0
        self._pred_est_bytes = 0
        self._spill_pred_nonzero = 0
        self._comm_base = bytes_now

    def record_done(self, scope_id: int, state: str = "done",
                    tokens: int = 0):
        """REQUEST-terminal transition: feeds the tenant SLO histograms
        (latency, queue wait, tokens/s) and the sliding SLO window."""
        with self._lock:
            r = self.requests.get(scope_id)
            if r is None:
                return
            r.state = state
            r.done_ns = _now_ns()
            r.tokens += int(tokens)
            t = self.tenants[r.tenant]
            if r.kind != "request":
                return
            key = "completed" if state == "done" else "failed"
            t.counters[key] += 1
            if state == "done" and r.submitted_ns is not None:
                e2e = r.done_ns - r.submitted_ns
                t.hists["latency_ns"].record(e2e)
                if r.admitted_ns is not None:
                    t.hists["queue_wait_ns"].record(
                        r.admitted_ns - r.submitted_ns)
                if r.tokens > 0 and r.admitted_ns is not None:
                    dt = max(1, r.done_ns - r.admitted_ns)
                    t.hists["tokens_per_s"].record(
                        round(r.tokens * 1e9 / dt))
                if t.slo_ms is not None:
                    viol = e2e > t.slo_ms * 1e6
                    t.window.append(viol)
                    if viol:
                        t.counters["slo_violations"] += 1

    def record_prefix(self, tenant: str, hits: int, misses: int):
        """ptc-share: one prompt's prefix-cache outcome — `hits` pages
        mapped onto frozen shared pages, `misses` prefilled cold
        (per-tenant hit-rate feed for ptc_top + Prometheus)."""
        self.tenant(tenant)
        with self._lock:
            t = self.tenants[tenant]
            t.counters["prefix_hits"] += int(hits)
            t.counters["prefix_misses"] += int(misses)

    def record_spec(self, tenant: str, proposed: int, accepted: int):
        """ptc-share: one speculative verify wave's outcome — `accepted`
        of `proposed` draft tokens survived target verification.  Feeds
        the per-tenant acceptance-rate histogram (whole percent)."""
        self.tenant(tenant)
        with self._lock:
            t = self.tenants[tenant]
            t.counters["spec_proposed"] += int(proposed)
            t.counters["spec_accepted"] += int(accepted)
            if proposed > 0:
                t.hists["spec_accept_pct"].record(
                    round(100 * accepted / proposed))

    def record_coll_wait(self, tenant: str, wait_ns: int, n: int = 1):
        """ptc-shard: one tp pool's collective-wait exposure — the time
        between this rank's LAST local shard fold finishing and the
        all-reduced pre-logits arriving back (`n` sequences were served
        by the wave).  Feeds the per-tenant coll_wait histogram the
        ptc_top tenant table and Prometheus export surface."""
        self.tenant(tenant)
        with self._lock:
            t = self.tenants[tenant]
            t.counters["coll_waves"] += 1
            t.hists["coll_wait_ns"].record(max(0, int(wait_ns)))

    def record_event(self, kind: str, **fields):
        """ptc-route: one structured fleet decision — placement (with
        per-replica scores), re-route after a 503 flip, page-migration
        bundle.  Ring-buffered; `events()` snapshots for dashboards and
        the deterministic router tests (which assert on WHY a replica
        won, not just which one)."""
        ev = {"kind": str(kind), "t_ns": _now_ns()}
        ev.update(fields)
        with self._lock:
            self._events.append(ev)
        # ptc-blackbox: decision events are journal records too — the
        # ring above dies with the process, the journal does not
        jr = getattr(self.ctx, "_journal", None)
        if jr is not None:
            try:
                jr.record("scope_event", **ev)
            except Exception:
                pass

    def events(self, kind: Optional[str] = None) -> List[dict]:
        """Snapshot of the structured decision log, oldest first,
        optionally filtered by kind."""
        with self._lock:
            evs = list(self._events)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    @staticmethod
    def plan_summary(plan) -> dict:
        """Compress a ptc-plan result into the prediction record
        record_done consumes (analysis/plan.py Plan)."""
        out = {
            "est_bytes": plan.est_bytes(),
            "comm_bytes": plan.comm_bytes(),
            "wire_out_bound_sum": sum(plan.wire_out_bound(rk)
                                      for rk in plan.ranks()),
            "makespan_lb_ns": int(plan.makespan.get("lower_bound_ns", 0))
            if plan.makespan else 0,
            "cost_source": (plan.makespan or {}).get("cost_source"),
        }
        # per-class cost assumptions the makespan bound used — the
        # calibration baseline conformance() compares live p50s against
        try:
            cm = plan.makespan.get("per_class_cost")
            if cm:
                out["per_class_cost"] = dict(cm)
        except Exception:
            pass
        return out

    def conformance(self) -> dict:
        """Plan-vs-measured rollup — the stats()["scope"]["conformance"]
        namespace.  Soundness fields compare PREDICTED upper bounds
        against context-wide measured counters, so they are only
        asserted when every retired pool was planned (coverage 1.0):
        a single unplanned pool's traffic would falsely indict the
        bound."""
        with self._lock:
            pools = self._pools_done
            planned = self._pools_planned
            ratios = sorted(self._makespan_ratios)
            pred_wire = self._pred_wire_bytes
            pred_est = self._pred_est_bytes
            spill_pred = self._spill_pred_nonzero
            per_class_cost = dict(self._per_class_cost)
            prev = self._conf_prev
            epochs = self._conf_epochs
            comm_base = self._comm_base
        prev_wire = 0
        if prev is not None:
            # merge the closed generation: the rollup spans at most two
            # conformance windows, however long the run has been
            pools += prev["pools"]
            planned += prev["planned"]
            pred_wire += prev["pred_wire"]
            pred_est += prev["pred_est"]
            spill_pred += prev["spill_pred"]
            prev_wire = prev["measured_wire"]
        measured_wire = None
        comm_sound = None
        try:
            if self.ctx.comm_enabled:
                measured_wire = (self.ctx.comm_stats()["bytes_sent"]
                                 - comm_base) + prev_wire
        except Exception:
            pass
        coverage = planned / pools if pools else None
        if measured_wire is not None and pools and coverage == 1.0:
            comm_sound = bool(pred_wire >= measured_wire)
        peak = None
        res_sound = None
        try:
            ds = self.ctx.device_stats()
            peak = ds.get("cache_peak_bytes")
        except Exception:
            pass
        if peak and planned and coverage == 1.0 and pred_est:
            # every concurrent pool's residency <= the sum of predicts
            res_sound = bool(pred_est >= peak)
        # per-class calibration: live measured p50 vs the cost the
        # planner assumed — ~1.0 means the model is honest; the
        # autotuner (ROADMAP item 5) regresses against this ratio
        per_class = {}
        try:
            from .metrics import snapshot_histograms
            from .. import _native as N
            for h in snapshot_histograms(self.ctx):
                if h.kind == N.MET_EXEC and h.name and h.count > 0 and \
                        h.name in per_class_cost:
                    planned_ns = per_class_cost[h.name]
                    p50 = h.quantile(0.50)
                    per_class[h.name] = {
                        "planned_ns": round(planned_ns, 1),
                        "measured_p50_ns": round(p50, 1),
                        "ratio": round(p50 / planned_ns, 4)
                        if planned_ns > 0 else None,
                    }
        except Exception:
            pass
        return {
            "pools": pools,
            "planned": planned,
            "epochs": epochs,
            "coverage": round(coverage, 4) if coverage is not None
            else None,
            "makespan": {
                "n": len(ratios),
                "ratio_p50": round(ratios[len(ratios) // 2], 4)
                if ratios else None,
                "ratio_min": round(ratios[0], 4) if ratios else None,
            },
            "comm_bytes": {
                "predicted_sum": pred_wire,
                "measured": measured_wire,
                "sound": comm_sound,
            },
            "residency": {
                "predicted_sum": pred_est,
                "measured_peak": peak,
                "sound": res_sound,
            },
            "spills": {
                "pools_predicting_spills": spill_pred,
                "measured": (self._device_spills() if spill_pred or peak
                             else None),
            },
            "per_class": per_class,
        }

    def _device_spills(self):
        try:
            return int(self.ctx.device_stats().get("spills", 0))
        except Exception:
            return None

    # -------------------------------------------------------------- SLO
    def slo_status(self) -> dict:
        """Per-tenant SLO burn: the fraction of the last `slo_window`
        completed requests that blew the tenant's slo_ms.  `breached`
        (burn_rate >= burn_threshold) drives /healthz 503 and the
        watchdog's slo_burn event."""
        out = {}
        with self._lock:
            for name, t in self.tenants.items():
                if t.slo_ms is None:
                    continue
                n = len(t.window)
                burn = (sum(t.window) / n) if n else 0.0
                out[name] = {
                    "slo_ms": t.slo_ms,
                    "window_n": n,
                    "violations": t.counters["slo_violations"],
                    "burn_rate": round(burn, 4),
                    "breached": bool(n and burn >= t.burn_threshold),
                }
        return out

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        slo = self.slo_status()
        with self._lock:
            tenants = {}
            for name, t in self.tenants.items():
                row = dict(t.counters)
                for k, h in t.hists.items():
                    s = h.summary()
                    row[f"{k}_p50"] = s["p50"]
                    row[f"{k}_p99"] = s["p99"]
                    row[f"{k}_count"] = s["count"]
                tenants[name] = row
            n_req = sum(1 for r in self.requests.values()
                        if r.kind == "request")
            live = sum(1 for r in self.requests.values()
                       if r.state in ("submitted", "running"))
        return {
            "enabled": True,
            "scopes": self._next - 1,
            "requests": n_req,
            "live": live,
            "tenants": tenants,
            "slo": slo,
            "conformance": self.conformance(),
        }

    def live_scopes(self) -> List[dict]:
        """Every scope not yet terminal (submitted/running) with enough
        identity for a postmortem to name a dead rank's inflight
        requests — the ptc-blackbox checkpoint's `live_scopes` field."""
        with self._lock:
            return [{"scope_id": sid, "tenant": r.tenant, "kind": r.kind,
                     "rid": r.rid, "state": r.state}
                    for sid, r in self.requests.items()
                    if r.state in ("submitted", "running")]

    def tenant_export(self) -> dict:
        """Per-tenant counters + SPARSE histogram buckets (native
        log2/8-sub-bucket indices, so cross-replica merging is pure
        addition — the same fold as the fence-time MSG_METRICS peer
        snapshots).  The FleetView scrape input; rides /stats.json as
        `scope_hists` so remote replicas federate identically."""
        slo = self.slo_status()
        with self._lock:
            out = {}
            for name, t in self.tenants.items():
                hists = {}
                for k, h in t.hists.items():
                    if not h.count:
                        continue
                    nz = np.nonzero(h.buckets)[0]
                    hists[k] = {
                        "count": int(h.count), "sum": int(h.sum),
                        "buckets": [[int(i), int(h.buckets[i])]
                                    for i in nz]}
                out[name] = {"counters": dict(t.counters),
                             "hists": hists, "slo": slo.get(name)}
        return out

    def scope_legend(self) -> dict:
        """scope_id -> {tenant, kind, rid} — stamped into .ptt meta by
        take_trace so a flight dump names the requests it contains."""
        with self._lock:
            return {str(sid): {"tenant": r.tenant, "kind": r.kind,
                               "rid": r.rid}
                    for sid, r in self.requests.items()}

    def scope_of(self, rid) -> Optional[int]:
        with self._lock:
            return self._by_rid.get(rid)

    def request(self, rid) -> Optional[_Request]:
        sid = self.scope_of(rid)
        with self._lock:
            return self.requests.get(sid) if sid is not None else None

    # --------------------------------------------------------- timeline
    def request_scopes(self, rid) -> List[Tuple[int, Optional[int]]]:
        """All scopes carrying work for `rid`: its own request scope
        plus every shared (decode-step) scope listing it as a member —
        as (scope_id, member_index or None)."""
        out: List[Tuple[int, Optional[int]]] = []
        with self._lock:
            sid = self._by_rid.get(rid)
            if sid is not None:
                out.append((sid, None))
            for ssid, members in self._members.items():
                if rid in members:
                    out.append((ssid, members.index(rid)))
        return out

    def scope_timeline(self, trace, scope_id: int) -> dict:
        """Timeline of ONE scope (server-owned tickets with no rid):
        same stage partition as request_timeline."""
        with self._lock:
            r = self.requests.get(int(scope_id))
            if r is None:
                raise KeyError(f"unknown scope {scope_id}")
            names = {int(scope_id): r.class_names}
            sub, adm, done = r.submitted_ns, r.admitted_ns, r.done_ns
        tl = request_timeline(trace, [(int(scope_id), None)],
                              submitted_ns=sub, admitted_ns=adm,
                              done_ns=done, class_names=names)
        tl["tenant"] = r.tenant
        tl["state"] = r.state
        return tl

    def request_timeline(self, trace, rid) -> dict:
        """One request's end-to-end story off a (merged) Trace: the
        admission record + the stage partition of its latency.  See
        module-level request_timeline for the decomposition."""
        r = self.request(rid)
        if r is None:
            raise KeyError(f"unknown request {rid!r}")
        scopes = self.request_scopes(rid)
        with self._lock:
            names = {sid: self.requests[sid].class_names
                     for sid, _ in scopes if sid in self.requests}
        tl = request_timeline(
            trace, scopes,
            submitted_ns=r.submitted_ns, admitted_ns=r.admitted_ns,
            done_ns=r.done_ns, class_names=names)
        tl["rid"] = rid
        tl["tenant"] = r.tenant
        tl["state"] = r.state
        tl["tokens"] = r.tokens
        tl["first_token_ns"] = r.first_token_ns
        if r.first_token_ns is not None and r.submitted_ns is not None:
            tl["ttft_ms"] = round(
                (r.first_token_ns - r.submitted_ns) / 1e6, 3)
        return tl


# ------------------------------------------------------- timeline maths
def _union(iv: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    if not iv:
        return []
    iv = sorted(iv)
    out = [list(iv[0])]
    for b, e in iv[1:]:
        if b <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([b, e])
    return [(b, e) for b, e in out]


def _union_len(iv) -> int:
    return sum(e - b for b, e in iv)


def _subtract(iv, cut) -> List[Tuple[int, int]]:
    """iv \\ cut, both interval unions (sorted, disjoint)."""
    out = []
    ci = 0
    for b, e in iv:
        cur = b
        while ci < len(cut) and cut[ci][1] <= cur:
            ci += 1
        j = ci
        while j < len(cut) and cut[j][0] < e:
            cb, ce = cut[j]
            if cb > cur:
                out.append((cur, min(cb, e)))
            cur = max(cur, ce)
            if cur >= e:
                break
            j += 1
        if cur < e:
            out.append((cur, e))
    return out


def _clip(iv, w0, w1):
    return [(max(b, w0), min(e, w1)) for b, e in iv
            if min(e, w1) > max(b, w0)]


def request_timeline(trace, scopes, submitted_ns=None, admitted_ns=None,
                     done_ns=None, class_names=None) -> dict:
    """Stage partition of one request's latency over a (merged) Trace.

    `scopes` is a list of (scope_id, member_index or None): the
    request's own scope plus any shared continuous-batching scopes
    (member_index = the request's sequence lane — EXEC/RELEASE spans
    are filtered to locals[0] == member_index there, so one decode pool
    shared by 8 sequences attributes each lane's folds to the right
    request; shared h2d/wire stay attributed to every member they
    served, which is honest for staging shared pages).

    Stages (ns, over the [admitted, done] window on the rank-0 clock):
      admission_wait  submit -> admitted (queue + backpressure)
      exec            time-union of the request's EXEC spans
      h2d             device staging (H2D spans) outside exec
      coll_wait       wire-flow windows delivering ptc_coll_* collective
                      steps (ptc-shard tp all-reduce legs — flows whose
                      (src, corr) matches a KEY_COLL instant, the same
                      evidence critpath.lost_time uses), outside
                      exec+h2d
      wire            remaining matched wire-flow windows outside
                      exec+h2d+coll_wait
      lane_wait       the measured residual: window - the above — lane
                      queueing, scheduler boundaries, driver overhead
    By construction admission_wait + exec + h2d + coll_wait + wire +
    lane_wait == end-to-end latency (done - submitted): the partition
    identity the acceptance test pins.  Also returns the per-stage span
    lists and the wire hops (src, dst, bytes, latency_ns, coll flag).
    `class_names` maps scope_id -> [class names by id] (class ids are
    per pool; the registry passes each scope's own table)."""
    from .trace import (KEY_COLL, KEY_EXEC, KEY_H2D, KEY_RELEASE,
                        KEY_STREAM)

    def _cname(sid, cid):
        tbl = (class_names or {}).get(sid)
        if tbl and 0 <= cid < len(tbl):
            return tbl[cid]
        return trace._cname(cid)

    ex_iv: List[Tuple[int, int]] = []
    h2d_iv: List[Tuple[int, int]] = []
    wire_iv: List[Tuple[int, int]] = []
    coll_iv: List[Tuple[int, int]] = []
    hops: List[dict] = []
    waves: List[dict] = []
    ev_min, ev_max = None, None
    for sid, member in scopes:
        sub = trace.filter_scope(sid)
        if not len(sub.events):
            continue
        t = sub._spans_table()
        coll_keys = set()
        for row in t:
            key = int(row[2])
            b, e = int(row[7]), int(row[8])
            ev_min = b if ev_min is None else min(ev_min, b)
            ev_max = e if ev_max is None else max(ev_max, e)
            if key in (KEY_EXEC, KEY_RELEASE):
                if member is not None and int(row[4]) != member:
                    continue
                if key == KEY_EXEC:
                    ex_iv.append((b, e))
                    waves.append({"scope": sid,
                                  "class": _cname(sid, int(row[3])),
                                  "l0": int(row[4]), "l1": int(row[5]),
                                  "begin_ns": b, "dur_ns": e - b,
                                  "rank": int(row[0])})
            elif key in (KEY_H2D, KEY_STREAM):
                h2d_iv.append((b, e))
            elif key == KEY_COLL:
                # collective-step delivery instant: l0 = source rank,
                # l1 = correlation cookie — tags the matching wire flow
                coll_keys.add((int(row[4]), int(row[5])))
        fl = sub.flows()
        for row in fl:
            s, d, corr, nbytes, t_s, t_r, lat = (int(x) for x in row)
            is_coll = (s, corr) in coll_keys
            (coll_iv if is_coll else wire_iv).append((t_s, t_r))
            hops.append({"scope": sid, "src": s, "dst": d,
                         "bytes": nbytes, "latency_ns": lat,
                         "send_ns": t_s, "recv_ns": t_r,
                         "coll": is_coll})
    # window: the ticket's [admitted, done] when known, else the span
    # envelope (pure-trace mode)
    w0 = admitted_ns if admitted_ns is not None else ev_min
    w1 = done_ns if done_ns is not None else ev_max
    if w0 is None or w1 is None or w1 < w0:
        w0 = w0 if w0 is not None else 0
        w1 = max(w1 if w1 is not None else 0, w0)
    ex_u = _clip(_union(ex_iv), w0, w1)
    h2d_u = _subtract(_clip(_union(h2d_iv), w0, w1), ex_u)
    busy = _union([*ex_u, *h2d_u])
    coll_u = _subtract(_clip(_union(coll_iv), w0, w1), busy)
    busy_c = _union([*busy, *coll_u])
    wire_u = _subtract(_clip(_union(wire_iv), w0, w1), busy_c)
    exec_ns = _union_len(ex_u)
    h2d_ns = _union_len(h2d_u)
    coll_ns = _union_len(coll_u)
    wire_ns = _union_len(wire_u)
    window_ns = w1 - w0
    lane_ns = max(0, window_ns - exec_ns - h2d_ns - coll_ns - wire_ns)
    admission_ns = (w0 - submitted_ns) if (submitted_ns is not None and
                                           admitted_ns is not None) else 0
    waves.sort(key=lambda w: w["begin_ns"])
    stages = {"admission_wait_ns": admission_ns, "exec_ns": exec_ns,
              "h2d_ns": h2d_ns, "coll_wait_ns": coll_ns,
              "wire_ns": wire_ns, "lane_wait_ns": lane_ns}
    return {
        "scopes": [s for s, _ in scopes],
        "window_ns": window_ns,
        "e2e_ns": admission_ns + window_ns,
        "stages": stages,
        "stages_sum_ns": sum(stages.values()),
        "waves": waves,
        "wire_hops": hops,
    }
