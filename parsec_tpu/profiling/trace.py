"""Binary trace format + pandas trace tables + DOT grapher.

File format (".ptt", the dbp analog — parsec/parsec_binary_profile.h:45
magic "#PARSEC BINARY PROFILE" becomes "#PTCPROF"):
  bytes 0..7   magic b"#PTCPROF"
  bytes 8..11  version (u32 LE) = 2
  bytes 12..15 header length H (u32 LE)
  bytes 16..16+H  JSON header {rank, dictionary:{key:{name,color}}, meta}
  rest         int64 LE event words, 8 per event:
               (key, phase, class_id, l0, l1, worker, aux, t_ns)

Header v2 (distributed tracing): `meta` carries the rank's measured
clock offset to rank 0 (`clock_offset_ns`, PING/PONG midpoint estimate
with `clock_err_ns` = the winning sample's RTT), flight-recorder
provenance (`dropped_events`, `ring_bytes`), and COMM_SEND/COMM_RECV
events carry a flow-correlation id — (peer, cookie) in (l0, l1) — so
`Trace.merge` can align per-rank timelines and pair sends with their
deliveries across ranks (reference: the dbp merge's cross-rank clock
resolution + OTF2 message matching, parsec/profiling.c,
parsec/profiling_otf2.c).  v1 files still load (offset 0, no flows).
"""
import json
import struct
from typing import Dict, List, Optional

import numpy as np

KEY_EXEC = 0       # task body begin/end
KEY_RELEASE = 1    # release_deps begin/end
KEY_EDGE = 2       # dep edge, consecutive src(phase0)/dst(phase1) pair
KEY_COMM_SEND = 3  # per-frame activation send (instant span),
                   # l0 = destination rank, l1 = correlation cookie,
                   # aux = payload bytes
KEY_COMM_RECV = 4  # per-frame activation delivery (instant span),
                   # l0 = source rank, l1 = correlation cookie (matches
                   # the producer's COMM_SEND), aux = payload bytes
KEY_DEVICE = 5     # device dispatch call begin/end, l0 = lanes; the END
                   # event's aux = the wave's dispatch-time h2d stall ns
                   # (0 == prefetch-hit wave)
KEY_H2D = 6        # h2d staging span, l0 = bytes, l1 = device queue,
                   # aux = lane (0 dispatch-time stall, 1 prefetch lane)
KEY_STREAM = 7     # progressive-serve d2h span (writeback lane slicing a
                   # remote-pulled mirror), l0 = bytes, l1 = device queue
KEY_COLL = 8       # collective-step delivery on a ptc_coll_* task class
                   # (instant span, emitted ALONGSIDE the COMM_RECV of
                   # the same frame): l0 = source rank, l1 = correlation
                   # cookie, aux = payload bytes — the evidence behind
                   # the coll_wait lost-time bucket (critpath.lost_time)
KEY_SCOPE = 9      # request-scope flow tag (instant span, emitted
                   # alongside COMM_SEND on the producer and COMM_RECV
                   # on the consumer when the sending pool carries a
                   # scope stamp): l0 = source rank, l1 = correlation
                   # cookie, aux = scope_id — maps each wire flow back
                   # to the request it served.  EXEC/RELEASE spans of a
                   # scoped pool carry the scope in their aux word, and
                   # the device layer stamps dispatch-lane H2D spans'
                   # class slot with it (prefetch-lane/STREAM spans stay
                   # -1: overlapped staging is not request lost time).
                   # See profiling/scope.py.
KEY_INFLIGHT = 10  # crash-dump synthetic (ptc-blackbox): one instant
                   # span per OPEN EXEC body at fatal-signal / peer-loss
                   # dump time, built from the watchdog inflight slots
                   # inside the async-signal-safe crash writer.
                   # class = metrics class id (mid), l0 = worker,
                   # aux = scope_id, begin = the body's open timestamp.
                   # Never emitted on the normal path; ptc_postmortem
                   # reads these to name what a dead rank was executing.

_MAGIC = b"#PTCPROF"
_VERSION = 2
_LOADABLE_VERSIONS = (1, 2)

_DEFAULT_KEYS = {
    KEY_EXEC: ("EXEC", "#00ff00"),
    KEY_RELEASE: ("RELEASE_DEPS", "#0000ff"),
    KEY_EDGE: ("EDGE", "#888888"),
    KEY_COMM_SEND: ("COMM_SEND", "#ff0000"),
    KEY_COMM_RECV: ("COMM_RECV", "#ff8800"),
    KEY_DEVICE: ("DEVICE_DISPATCH", "#aa00ff"),
    KEY_H2D: ("DEVICE_H2D", "#00aaff"),
    KEY_STREAM: ("STREAM_D2H", "#ffaa00"),
    KEY_COLL: ("COLL_RECV", "#00ffcc"),
    KEY_SCOPE: ("SCOPE", "#ff00aa"),
    KEY_INFLIGHT: ("INFLIGHT", "#ff4444"),
}


class Dictionary:
    """Event-key registry (reference: parsec/profiling.c dictionary with
    name + color + typed info, consumed by pbt2ptt)."""

    def __init__(self):
        self.keys: Dict[int, dict] = {
            k: {"name": n, "color": c} for k, (n, c) in _DEFAULT_KEYS.items()}

    def add(self, key: int, name: str, color: str = "#cccccc"):
        self.keys[int(key)] = {"name": name, "color": color}
        return key

    def name(self, key: int) -> str:
        return self.keys.get(int(key), {}).get("name", f"KEY{key}")

    def to_json(self):
        return {str(k): v for k, v in self.keys.items()}

    @classmethod
    def from_json(cls, d):
        out = cls()
        for k, v in d.items():
            out.keys[int(k)] = dict(v)
        return out


class Trace:
    """An event table + dictionary for one or more ranks."""

    def __init__(self, events: np.ndarray, dictionary: Optional[Dictionary]
                 = None, rank: int = 0, meta: Optional[dict] = None,
                 class_names: Optional[List[str]] = None):
        assert events.ndim == 2 and events.shape[1] == 8, events.shape
        self.events = events.astype(np.int64, copy=False)
        self.dict = dictionary or Dictionary()
        self.rank = rank
        self.meta = meta or {}
        self.class_names = class_names or []
        # per-event rank column (merged traces carry several ranks)
        self.ranks = np.full(len(events), rank, dtype=np.int64)

    # ---------------------------------------------------------- file IO
    def save(self, path: str):
        header = json.dumps({
            "rank": self.rank, "dictionary": self.dict.to_json(),
            "meta": self.meta, "class_names": self.class_names,
        }).encode()
        with open(path, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<II", _VERSION, len(header)))
            f.write(header)
            f.write(self.events.astype("<i8").tobytes())

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path, "rb") as f:
            raw = f.read()
        if raw[:8] != _MAGIC:
            raise ValueError(f"{path}: not a ptt trace (bad magic)")
        ver, hlen = struct.unpack("<II", raw[8:16])
        if ver not in _LOADABLE_VERSIONS:
            raise ValueError(f"{path}: unsupported trace version {ver}")
        hdr = json.loads(raw[16:16 + hlen])
        ev = np.frombuffer(raw[16 + hlen:], dtype="<i8").reshape(-1, 8)
        return cls(ev.copy(), Dictionary.from_json(hdr["dictionary"]),
                   hdr.get("rank", 0), hdr.get("meta"),
                   hdr.get("class_names"))

    @classmethod
    def merge(cls, traces: List["Trace"], apply_offsets: bool = True,
              causal: bool = True) -> "Trace":
        """Merge per-rank traces into one causally-consistent timeline
        (the dbp-merge analog, now with cross-rank clock resolution).

        - Dictionaries and class_names are merged with CONFLICT
          DETECTION: the same key id (or class id) mapped to two
          different names raises ValueError instead of silently taking
          traces[0]'s — dynamic keys registered on one rank no longer
          mislabel merged events; a name present on only some ranks is
          adopted.
        - `apply_offsets` shifts each trace's timestamps by its
          `meta["clock_offset_ns"]` (the PING/PONG estimate against
          rank 0 taken at comm bring-up/fence), putting every rank on
          rank 0's clock.
        - `causal` then enforces the physical invariant the estimate
          can only approximate: every matched COMM_RECV begins at or
          after its COMM_SEND.  Residual violations first move whole
          ranks (difference-constraint relaxation), then clamp the few
          stragglers event-wise; the corrections applied are recorded in
          meta ("causal_shift_ns", "clamped_recvs").
        """
        dictionary = Dictionary()
        dictionary.keys = {}
        for t in traces:
            for k, v in t.dict.keys.items():
                k = int(k)
                have = dictionary.keys.get(k)
                if have is not None and have["name"] != v["name"]:
                    raise ValueError(
                        f"dictionary conflict merging rank {t.rank}: key "
                        f"{k} is {have['name']!r} on an earlier rank but "
                        f"{v['name']!r} here — register dynamic keys "
                        "identically on every rank")
                if have is None:
                    dictionary.keys[k] = dict(v)
        class_names: List[str] = []
        for t in traces:
            for i, nm in enumerate(t.class_names or []):
                if i < len(class_names):
                    if class_names[i] != nm:
                        raise ValueError(
                            f"class_names conflict merging rank {t.rank}: "
                            f"class {i} is {class_names[i]!r} on an "
                            f"earlier rank but {nm!r} here")
                else:
                    class_names.append(nm)
        offsets = {}
        evs = []
        for t in traces:
            e = t.events.copy()
            off = int(t.meta.get("clock_offset_ns", 0)) if apply_offsets \
                else 0
            if off:
                e[:, 7] += off
            offsets[int(t.rank)] = off
            evs.append(e)
        out = cls(np.concatenate(evs) if evs else
                  np.empty((0, 8), dtype=np.int64),
                  dictionary, traces[0].rank if traces else 0,
                  {"merged_ranks": [t.rank for t in traces],
                   "clock_offsets_ns": offsets},
                  class_names)
        out.ranks = np.concatenate([t.ranks for t in traces]) if traces \
            else out.ranks
        if causal:
            out._enforce_causality()
        return out

    def _enforce_causality(self, max_passes: int = 16):
        """Post-offset fix-up: recv-before-send across ranks is a clock
        artifact, never physics.  Pass 1..n relax whole-rank shifts (the
        difference-constraint system recv >= send per rank pair); an
        infeasible system — offset error larger than true wire latency,
        common on loopback where both are microseconds — falls back to
        clamping the violated recv instants to their send time."""
        shifts: Dict[int, int] = {}
        for _ in range(max_passes):
            fl = self._match_flows()
            viol = fl["send_ns"] - fl["recv_ns"]
            bad = viol > 0
            if not bad.any():
                break
            worst_dst = {}
            for dst in np.unique(fl["dst"][bad]):
                worst_dst[int(dst)] = int(
                    viol[bad & (fl["dst"] == dst)].max())
            # relax: shift each violated receiver's whole rank forward
            for dst, d in worst_dst.items():
                self.events[self.ranks == dst, 7] += d
                shifts[dst] = shifts.get(dst, 0) + d
        clamped = 0
        fl = self._match_flows()
        viol = fl["send_ns"] - fl["recv_ns"]
        bad = np.flatnonzero(viol > 0)
        for i in bad:
            ri = int(fl["recv_idx"][i])
            t_send = int(fl["send_ns"][i])
            self.events[ri, 7] = t_send
            # the paired instant END row rides directly after the begin
            if (ri + 1 < len(self.events)
                    and self.events[ri + 1, 0] == KEY_COMM_RECV
                    and self.events[ri + 1, 1] == 1
                    and self.events[ri + 1, 4] == self.events[ri, 4]):
                self.events[ri + 1, 7] = max(
                    int(self.events[ri + 1, 7]), t_send)
            clamped += 1
        if shifts:
            self.meta["causal_shift_ns"] = shifts
        self.meta["clamped_recvs"] = clamped

    # ----------------------------------------------------- trace tables
    def _spans_table(self) -> np.ndarray:
        """Vectorized begin/end pairing: an (n, 10) int64 table with
        columns (rank, worker, key, class_id, l0, l1, aux, begin_ns,
        end_ns, end_event_index), ordered like the historical per-event
        loop (by end-event position).  Pairing is per (rank, worker,
        key, class, l0, l1); the numpy fast path pairs each end with its
        immediate predecessor inside the group (the alternating-span
        common case — one pass, no Python loop); groups where that rule
        fails (nested same-signature spans) re-pair with the LIFO stack
        the old implementation used."""
        ev = self.events
        empty = np.empty((0, 10), dtype=np.int64)
        if not len(ev):
            return empty
        keep = ev[:, 0] != KEY_EDGE
        idx = np.flatnonzero(keep)
        if not len(idx):
            return empty
        e = ev[idx]
        rk = self.ranks[idx]
        sig = np.stack([rk, e[:, 5], e[:, 0], e[:, 2], e[:, 3], e[:, 4]],
                       axis=1)
        _, ginv = np.unique(sig, axis=0, return_inverse=True)
        ginv = ginv.reshape(-1)
        order = np.lexsort((np.arange(len(e)), ginv))
        g = ginv[order]
        ph = e[order, 1]
        ends = np.flatnonzero(ph == 1)
        ok = np.zeros(len(ends), dtype=bool)
        valid = ends > 0
        pv = ends[valid] - 1
        ok[valid] = (g[pv] == g[ends[valid]]) & (ph[pv] == 0)
        bad_groups = np.unique(g[ends[~ok]])
        pairs_b: List[np.ndarray] = []
        pairs_e: List[np.ndarray] = []
        good = ok.copy()
        if len(bad_groups):
            good &= ~np.isin(g[ends], bad_groups)
        ge = ends[good]
        pairs_b.append(order[ge - 1])
        pairs_e.append(order[ge])
        if len(bad_groups):
            # stack fallback, only for the (rare) nested groups
            fb_b, fb_e = [], []
            stacks: Dict[int, list] = {}
            for p in np.flatnonzero(np.isin(g, bad_groups)):
                i_e = order[p]
                if ph[p] == 0:
                    stacks.setdefault(int(g[p]), []).append(i_e)
                else:
                    st = stacks.get(int(g[p]))
                    if st:
                        fb_b.append(st.pop())
                        fb_e.append(i_e)
            pairs_b.append(np.asarray(fb_b, dtype=np.int64))
            pairs_e.append(np.asarray(fb_e, dtype=np.int64))
        bi = np.concatenate(pairs_b) if pairs_b else np.empty(0, np.int64)
        ei = np.concatenate(pairs_e) if pairs_e else np.empty(0, np.int64)
        if not len(ei):
            return empty
        eb, ee = e[bi], e[ei]
        table = np.column_stack([
            rk[ei], ee[:, 5], ee[:, 0], ee[:, 2], ee[:, 3], ee[:, 4],
            np.maximum(eb[:, 6], ee[:, 6]), eb[:, 7], ee[:, 7], idx[ei]])
        return table[np.argsort(table[:, 9], kind="stable")]

    def spans(self):
        """Pair begin/end events into spans — the single pairing rule
        shared by to_pandas and to_perfetto.  Yields tuples
        (rank, worker, key, class_id, l0, l1, aux, begin_ns, end_ns);
        EDGE events are excluded (use edges()/to_dot).  Pairing is per
        (rank, worker, key, class, l0, l1) with a begin stack; aux is the
        max of the begin/end words.  (Generator API preserved; the
        pairing itself is vectorized — see _spans_table.)"""
        for row in self._spans_table():
            yield tuple(int(x) for x in row[:9])

    def to_pandas(self):
        """Paired begin/end events -> one row per span (the reference's
        pbt2ptt "trace tables": tools/profiling/python/pbt2ptt.pyx).

        Returns a DataFrame with columns: rank, worker, key, name, class_id,
        class_name, l0, l1, aux, begin_ns, end_ns, dur_ns."""
        import pandas as pd
        t = self._spans_table()
        df = pd.DataFrame({
            "rank": t[:, 0], "worker": t[:, 1], "key": t[:, 2],
            "name": [self.dict.name(int(k)) for k in t[:, 2]],
            "class_id": t[:, 3],
            "class_name": [self._cname(int(c)) for c in t[:, 3]],
            "l0": t[:, 4], "l1": t[:, 5], "aux": t[:, 6],
            "begin_ns": t[:, 7], "end_ns": t[:, 8],
            "dur_ns": t[:, 8] - t[:, 7],
        })
        return df

    def _cname(self, cid: int) -> str:
        if 0 <= cid < len(self.class_names):
            return self.class_names[cid]
        return f"class{cid}"

    def edges(self):
        """EDGE pairs -> list of ((src_cid, l0, l1), (dst_cid, l0, l1))."""
        ev = self.events
        out = []
        i = 0
        n = len(ev)
        while i < n:
            if ev[i][0] == KEY_EDGE and ev[i][1] == 0 and i + 1 < n \
                    and ev[i + 1][0] == KEY_EDGE and ev[i + 1][1] == 1:
                s, d = ev[i], ev[i + 1]
                out.append(((int(s[2]), int(s[3]), int(s[4])),
                            (int(d[2]), int(d[3]), int(d[4]))))
                i += 2
            else:
                i += 1
        return out

    # ------------------------------------------------ flow correlation
    def _match_flows(self) -> Dict[str, np.ndarray]:
        """Pair COMM_SEND with COMM_RECV across ranks by the flow key
        (src_rank, correlation cookie) — the wire-v5 (l0, l1) stamps.
        Returns parallel arrays: src, dst, corr, bytes, send_ns,
        recv_ns, send_idx, recv_idx (begin-row indices into events)."""
        ev, rk = self.events, self.ranks
        nothing = {k: np.empty(0, dtype=np.int64) for k in
                   ("src", "dst", "corr", "bytes", "send_ns", "recv_ns",
                    "send_idx", "recv_idx")}
        si = np.flatnonzero((ev[:, 0] == KEY_COMM_SEND) & (ev[:, 1] == 0)
                            & (ev[:, 4] > 0))
        ri = np.flatnonzero((ev[:, 0] == KEY_COMM_RECV) & (ev[:, 1] == 0)
                            & (ev[:, 4] > 0) & (ev[:, 3] >= 0))
        if not len(si) or not len(ri):
            return nothing
        # flow key: src rank in the high bits, per-sender cookie low
        skey = (rk[si] << 44) | ev[si, 4]
        rkey = (ev[ri, 3] << 44) | ev[ri, 4]
        so = np.argsort(skey, kind="stable")
        skey_s = skey[so]
        pos = np.searchsorted(skey_s, rkey)
        pos_c = np.minimum(pos, len(skey_s) - 1)
        hit = skey_s[pos_c] == rkey
        rsel = np.flatnonzero(hit)
        if not len(rsel):
            return nothing
        s_at = si[so[pos_c[rsel]]]
        r_at = ri[rsel]
        return {
            "src": ev[r_at, 3], "dst": rk[r_at], "corr": ev[r_at, 4],
            "bytes": ev[s_at, 6], "send_ns": ev[s_at, 7],
            "recv_ns": ev[r_at, 7], "send_idx": s_at, "recv_idx": r_at,
        }

    def flows(self) -> np.ndarray:
        """Matched cross-rank messages: an (m, 7) int64 array with
        columns (src, dst, corr, bytes, send_ns, recv_ns, latency_ns).
        Requires a merged (or at least multi-rank) trace whose COMM
        events carry wire-v5 correlation ids."""
        m = self._match_flows()
        return np.column_stack([
            m["src"], m["dst"], m["corr"], m["bytes"], m["send_ns"],
            m["recv_ns"], m["recv_ns"] - m["send_ns"],
        ]) if len(m["src"]) else np.empty((0, 7), dtype=np.int64)

    def wire_latency(self):
        """Per-message wire latency table (pandas): one row per matched
        COMM_SEND -> COMM_RECV pair, post clock sync.  The per-(src,dst)
        aggregate of `latency_ns` is the measured wire cost the
        transfer-economics harness models."""
        import pandas as pd
        f = self.flows()
        return pd.DataFrame(f, columns=[
            "src", "dst", "corr", "bytes", "send_ns", "recv_ns",
            "latency_ns"])

    # -------------------------------------------------- request scopes
    def scope_flows(self) -> Dict:
        """(src_rank, corr) -> scope_id from the SCOPE flow tags —
        the map that attributes matched wire flows to requests.  Both
        the producer and the consumer emit the tag under the same key,
        so single-rank and merged traces resolve identically."""
        ev = self.events
        out: Dict = {}
        for i in np.flatnonzero((ev[:, 0] == KEY_SCOPE)
                                & (ev[:, 1] == 0)):
            out[(int(ev[i, 3]), int(ev[i, 4]))] = int(ev[i, 6])
        return out

    def scope_ids(self) -> List[int]:
        """Distinct request-scope ids present in this trace (EXEC aux
        stamps + SCOPE flow tags), sorted."""
        ev = self.events
        ids = set()
        ex = (ev[:, 0] == KEY_EXEC) & (ev[:, 1] == 0) & (ev[:, 6] > 0)
        ids.update(int(v) for v in np.unique(ev[ex, 6]))
        ids.update(self.scope_flows().values())
        ids.discard(0)
        return sorted(ids)

    def filter_scope(self, scope_id: int) -> "Trace":
        """The sub-trace of ONE request: EXEC/RELEASE spans whose aux
        carries `scope_id`, H2D/STREAM staging spans the device layer
        stamped with it (class slot), the COMM/COLL instants of its
        wire flows, its SCOPE tags, and the EDGE pairs between its own
        EXEC nodes.  Everything else — other tenants' pools, unscoped
        work — is dropped, so per-request critical_path()/lost_time()
        cannot conflate same-numbered classes across pools (class ids
        are per-pool)."""
        ev, rk = self.events, self.ranks
        sid = int(scope_id)
        keep = np.zeros(len(ev), dtype=bool)
        keep |= ((ev[:, 0] == KEY_EXEC) | (ev[:, 0] == KEY_RELEASE)) & \
            (ev[:, 6] == sid)
        keep |= ((ev[:, 0] == KEY_H2D) | (ev[:, 0] == KEY_STREAM)) & \
            (ev[:, 2] == sid)
        keep |= (ev[:, 0] == KEY_SCOPE) & (ev[:, 6] == sid)
        # wire flows of this scope: (src, corr) keys from the SCOPE tags
        fkeys = {k for k, v in self.scope_flows().items() if v == sid}
        if fkeys:
            send = ev[:, 0] == KEY_COMM_SEND
            recvish = (ev[:, 0] == KEY_COMM_RECV) | (ev[:, 0] == KEY_COLL)
            for i in np.flatnonzero(send):
                if (int(rk[i]), int(ev[i, 4])) in fkeys:
                    keep[i] = True
            for i in np.flatnonzero(recvish):
                if (int(ev[i, 3]), int(ev[i, 4])) in fkeys:
                    keep[i] = True
        # EDGE pairs whose src or dst is one of this scope's EXEC nodes
        nodes = {(int(e[2]), int(e[3]), int(e[4]))
                 for e in ev[(ev[:, 0] == KEY_EXEC) & (ev[:, 6] == sid)]}
        ei = np.flatnonzero((ev[:, 0] == KEY_EDGE) & (ev[:, 1] == 0))
        for i in ei:
            if i + 1 >= len(ev) or ev[i + 1, 0] != KEY_EDGE or \
                    ev[i + 1, 1] != 1:
                continue
            s = (int(ev[i, 2]), int(ev[i, 3]), int(ev[i, 4]))
            d = (int(ev[i + 1, 2]), int(ev[i + 1, 3]), int(ev[i + 1, 4]))
            if s in nodes or d in nodes:
                keep[i] = keep[i + 1] = True
        out = Trace(ev[keep].copy(), self.dict, self.rank,
                    dict(self.meta, scope=sid), self.class_names)
        out.ranks = rk[keep].copy()
        return out

    # -------------------------------------------------------- analysis
    def critical_path(self, **kw):
        """Executed-DAG critical path (see profiling.critpath): walks
        EDGE pairs weighted by EXEC span durations and returns the
        longest chain with per-class attribution."""
        from .critpath import critical_path
        return critical_path(self, **kw)

    def lost_time(self, **kw):
        """Per-(rank, worker) lost-time breakdown (compute / release /
        h2d stall / comm wait / idle) — see profiling.critpath."""
        from .critpath import lost_time
        return lost_time(self, **kw)

    def to_perfetto(self, path: Optional[str] = None):
        """Standard-tool sink: Chrome/Perfetto trace-event JSON (the
        reference ships an OTF2 writer, parsec/profiling_otf2.c, for
        Vampir/Score-P interop; Perfetto's trace-event format is the
        TPU-era equivalent — ui.perfetto.dev opens it directly).

        Spans become "X" complete events with pid=rank / tid=worker;
        COMM instant spans (begin==end) become "i" instant events, and
        matched send/recv pairs additionally emit "s"/"f" FLOW events so
        the UI draws arrows between ranks.  Returns the JSON object;
        writes it to `path` when given."""
        out = []
        for (rank, worker, key, cid, l0, l1, aux, t0, t1) in self.spans():
            name = (self._cname(cid) if key == KEY_EXEC and cid >= 0
                    else self.dict.name(key))
            rec = {
                "name": name,
                "cat": self.dict.name(key),
                "pid": rank,
                "tid": worker,
                "ts": t0 / 1e3,          # perfetto wants microseconds
                "args": {"l0": l0, "l1": l1, "bytes": aux},
            }
            if t1 == t0:
                rec["ph"] = "i"
                rec["s"] = "t"  # thread-scoped instant
            else:
                rec["ph"] = "X"
                rec["dur"] = (t1 - t0) / 1e3
            out.append(rec)
        for row in self.flows():
            src, dst, corr, nbytes, t_s, t_r, _lat = (int(x) for x in row)
            fid = f"{src}:{corr}"
            out.append({"ph": "s", "id": fid, "name": "msg", "cat": "comm",
                        "pid": src, "tid": -1, "ts": t_s / 1e3})
            out.append({"ph": "f", "bp": "e", "id": fid, "name": "msg",
                        "cat": "comm", "pid": dst, "tid": -1,
                        "ts": t_r / 1e3})
        doc = {"traceEvents": out, "displayTimeUnit": "ns"}
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    def counts(self) -> Dict[str, int]:
        """Event counts per key name — the cheap oracle used by trace
        assertions (reference: tests/profiling/check-comms.py)."""
        out: Dict[str, int] = {}
        for k in np.unique(self.events[:, 0]):
            out[self.dict.name(int(k))] = int(
                np.sum((self.events[:, 0] == k) & (self.events[:, 1] == 0)))
        return out


def take_trace(ctx, rank: Optional[int] = None,
               class_names: Optional[List[str]] = None,
               meta: Optional[dict] = None) -> Trace:
    """Drain a Context's native profiling buffers into a Trace.  The
    header meta is auto-stamped with the rank's clock-sync estimate and
    flight-recorder drop count so a later Trace.merge can align ranks
    without extra plumbing.  `rank` defaults to the context's rank."""
    m = dict(meta or {})
    if rank is None:
        rank = getattr(ctx, "myrank", 0)
    try:
        ck = ctx.comm_clock()
        if ck["measured"]:
            m.setdefault("clock_offset_ns", ck["offset_ns"])
            m.setdefault("clock_err_ns", ck["err_ns"])
    except Exception:
        pass
    try:
        m.setdefault("dropped_events", ctx.profile_dropped())
        m.setdefault("ring_bytes", ctx.profile_ring())
    except Exception:
        pass
    # request-scope legend (header stays v2: meta is free-form JSON) —
    # a flight-recorder dump names the requests its spans belong to
    try:
        reg = getattr(ctx, "_scope_registry", None)
        if reg is not None:
            legend = reg.scope_legend()
            if legend:
                m.setdefault("scopes", legend)
    except Exception:
        pass
    return Trace(ctx.profile_take(), rank=rank, class_names=class_names,
                 meta=m)


def _node_id(cid, l0, l1, cname):
    return f"{cname(cid)}_{l0}_{l1}"


def to_dot(trace: Trace, name: str = "dag") -> str:
    """Executed-DAG capture as DOT (reference:
    parsec/parsec_prof_grapher.c:86-135, the --parsec dot flag)."""
    lines = [f"digraph {name} {{"]
    seen = set()
    for (sc, sl0, sl1), (dc, dl0, dl1) in trace.edges():
        a = _node_id(sc, sl0, sl1, trace._cname)
        b = _node_id(dc, dl0, dl1, trace._cname)
        for nd in (a, b):
            if nd not in seen:
                seen.add(nd)
                lines.append(f'  "{nd}";')
        lines.append(f'  "{a}" -> "{b}";')
    lines.append("}")
    return "\n".join(lines)
