"""Binary trace format + pandas trace tables + DOT grapher.

File format (".ptt", the dbp analog — parsec/parsec_binary_profile.h:45
magic "#PARSEC BINARY PROFILE" becomes "#PTCPROF"):
  bytes 0..7   magic b"#PTCPROF"
  bytes 8..11  version (u32 LE) = 1
  bytes 12..15 header length H (u32 LE)
  bytes 16..16+H  JSON header {rank, dictionary:{key:{name,color}}, meta}
  rest         int64 LE event words, 8 per event:
               (key, phase, class_id, l0, l1, worker, aux, t_ns)
Per-rank files merge by concatenation of event tables (rank column added),
the same property the reference's dbp merge tooling relies on.
"""
import json
import struct
from typing import Dict, List, Optional

import numpy as np

KEY_EXEC = 0       # task body begin/end
KEY_RELEASE = 1    # release_deps begin/end
KEY_EDGE = 2       # dep edge, consecutive src(phase0)/dst(phase1) pair
KEY_COMM_SEND = 3  # per-target activation send (instant span), aux = bytes
KEY_COMM_RECV = 4  # per-target activation delivery (instant span)
KEY_DEVICE = 5     # device dispatch call begin/end, l0 = lanes; the END
                   # event's aux = the wave's dispatch-time h2d stall ns
                   # (0 == prefetch-hit wave)
KEY_H2D = 6        # h2d staging span, l0 = bytes, l1 = device queue,
                   # aux = lane (0 dispatch-time stall, 1 prefetch lane)
KEY_STREAM = 7     # progressive-serve d2h span (writeback lane slicing a
                   # remote-pulled mirror), l0 = bytes, l1 = device queue

_MAGIC = b"#PTCPROF"
_VERSION = 1

_DEFAULT_KEYS = {
    KEY_EXEC: ("EXEC", "#00ff00"),
    KEY_RELEASE: ("RELEASE_DEPS", "#0000ff"),
    KEY_EDGE: ("EDGE", "#888888"),
    KEY_COMM_SEND: ("COMM_SEND", "#ff0000"),
    KEY_COMM_RECV: ("COMM_RECV", "#ff8800"),
    KEY_DEVICE: ("DEVICE_DISPATCH", "#aa00ff"),
    KEY_H2D: ("DEVICE_H2D", "#00aaff"),
    KEY_STREAM: ("STREAM_D2H", "#ffaa00"),
}


class Dictionary:
    """Event-key registry (reference: parsec/profiling.c dictionary with
    name + color + typed info, consumed by pbt2ptt)."""

    def __init__(self):
        self.keys: Dict[int, dict] = {
            k: {"name": n, "color": c} for k, (n, c) in _DEFAULT_KEYS.items()}

    def add(self, key: int, name: str, color: str = "#cccccc"):
        self.keys[int(key)] = {"name": name, "color": color}
        return key

    def name(self, key: int) -> str:
        return self.keys.get(int(key), {}).get("name", f"KEY{key}")

    def to_json(self):
        return {str(k): v for k, v in self.keys.items()}

    @classmethod
    def from_json(cls, d):
        out = cls()
        for k, v in d.items():
            out.keys[int(k)] = dict(v)
        return out


class Trace:
    """An event table + dictionary for one or more ranks."""

    def __init__(self, events: np.ndarray, dictionary: Optional[Dictionary]
                 = None, rank: int = 0, meta: Optional[dict] = None,
                 class_names: Optional[List[str]] = None):
        assert events.ndim == 2 and events.shape[1] == 8, events.shape
        self.events = events.astype(np.int64, copy=False)
        self.dict = dictionary or Dictionary()
        self.rank = rank
        self.meta = meta or {}
        self.class_names = class_names or []
        # per-event rank column (merged traces carry several ranks)
        self.ranks = np.full(len(events), rank, dtype=np.int64)

    # ---------------------------------------------------------- file IO
    def save(self, path: str):
        header = json.dumps({
            "rank": self.rank, "dictionary": self.dict.to_json(),
            "meta": self.meta, "class_names": self.class_names,
        }).encode()
        with open(path, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<II", _VERSION, len(header)))
            f.write(header)
            f.write(self.events.astype("<i8").tobytes())

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path, "rb") as f:
            raw = f.read()
        if raw[:8] != _MAGIC:
            raise ValueError(f"{path}: not a ptt trace (bad magic)")
        ver, hlen = struct.unpack("<II", raw[8:16])
        if ver != _VERSION:
            raise ValueError(f"{path}: unsupported trace version {ver}")
        hdr = json.loads(raw[16:16 + hlen])
        ev = np.frombuffer(raw[16 + hlen:], dtype="<i8").reshape(-1, 8)
        return cls(ev.copy(), Dictionary.from_json(hdr["dictionary"]),
                   hdr.get("rank", 0), hdr.get("meta"),
                   hdr.get("class_names"))

    @classmethod
    def merge(cls, traces: List["Trace"]) -> "Trace":
        """Concatenate per-rank traces (the dbp-merge analog)."""
        out = cls(np.concatenate([t.events for t in traces]),
                  traces[0].dict, traces[0].rank,
                  {"merged_ranks": [t.rank for t in traces]},
                  traces[0].class_names)
        out.ranks = np.concatenate([t.ranks for t in traces])
        return out

    # ----------------------------------------------------- trace tables
    def spans(self):
        """Pair begin/end events into spans — the single pairing rule
        shared by to_pandas and to_perfetto.  Yields tuples
        (rank, worker, key, class_id, l0, l1, aux, begin_ns, end_ns);
        EDGE events are excluded (use edges()/to_dot).  Pairing is per
        (rank, worker, key, class, l0, l1) with a begin stack; aux is the
        max of the begin/end words."""
        ev = self.events
        open_spans: Dict[tuple, list] = {}
        for i in range(len(ev)):
            key, phase, cid, l0, l1, worker, aux, t = (int(x) for x in ev[i])
            if key == KEY_EDGE:
                continue
            rank = int(self.ranks[i])
            sig = (rank, worker, key, cid, l0, l1)
            if phase == 0:
                open_spans.setdefault(sig, []).append((aux, t))
            else:
                st = open_spans.get(sig)
                if st:
                    aux0, t0 = st.pop()
                    yield (rank, worker, key, cid, l0, l1, max(aux, aux0),
                           t0, t)

    def to_pandas(self):
        """Paired begin/end events -> one row per span (the reference's
        pbt2ptt "trace tables": tools/profiling/python/pbt2ptt.pyx).

        Returns a DataFrame with columns: rank, worker, key, name, class_id,
        class_name, l0, l1, aux, begin_ns, end_ns, dur_ns."""
        import pandas as pd
        rows = [(rank, worker, key, self.dict.name(key), cid,
                 self._cname(cid), l0, l1, aux, t0, t1, t1 - t0)
                for (rank, worker, key, cid, l0, l1, aux, t0, t1)
                in self.spans()]
        return pd.DataFrame(rows, columns=[
            "rank", "worker", "key", "name", "class_id", "class_name",
            "l0", "l1", "aux", "begin_ns", "end_ns", "dur_ns"])

    def _cname(self, cid: int) -> str:
        if 0 <= cid < len(self.class_names):
            return self.class_names[cid]
        return f"class{cid}"

    def edges(self):
        """EDGE pairs -> list of ((src_cid, l0, l1), (dst_cid, l0, l1))."""
        ev = self.events
        out = []
        i = 0
        n = len(ev)
        while i < n:
            if ev[i][0] == KEY_EDGE and ev[i][1] == 0 and i + 1 < n \
                    and ev[i + 1][0] == KEY_EDGE and ev[i + 1][1] == 1:
                s, d = ev[i], ev[i + 1]
                out.append(((int(s[2]), int(s[3]), int(s[4])),
                            (int(d[2]), int(d[3]), int(d[4]))))
                i += 2
            else:
                i += 1
        return out

    def to_perfetto(self, path: Optional[str] = None):
        """Standard-tool sink: Chrome/Perfetto trace-event JSON (the
        reference ships an OTF2 writer, parsec/profiling_otf2.c, for
        Vampir/Score-P interop; Perfetto's trace-event format is the
        TPU-era equivalent — ui.perfetto.dev opens it directly).

        Spans become "X" complete events with pid=rank / tid=worker;
        COMM instant spans (begin==end) become "i" instant events.
        Returns the JSON object; writes it to `path` when given."""
        out = []
        for (rank, worker, key, cid, l0, l1, aux, t0, t1) in self.spans():
            name = (self._cname(cid) if key == KEY_EXEC and cid >= 0
                    else self.dict.name(key))
            rec = {
                "name": name,
                "cat": self.dict.name(key),
                "pid": rank,
                "tid": worker,
                "ts": t0 / 1e3,          # perfetto wants microseconds
                "args": {"l0": l0, "l1": l1, "bytes": aux},
            }
            if t1 == t0:
                rec["ph"] = "i"
                rec["s"] = "t"  # thread-scoped instant
            else:
                rec["ph"] = "X"
                rec["dur"] = (t1 - t0) / 1e3
            out.append(rec)
        doc = {"traceEvents": out, "displayTimeUnit": "ns"}
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    def counts(self) -> Dict[str, int]:
        """Event counts per key name — the cheap oracle used by trace
        assertions (reference: tests/profiling/check-comms.py)."""
        out: Dict[str, int] = {}
        for k in np.unique(self.events[:, 0]):
            out[self.dict.name(int(k))] = int(
                np.sum((self.events[:, 0] == k) & (self.events[:, 1] == 0)))
        return out


def take_trace(ctx, rank: int = 0, class_names: Optional[List[str]] = None,
               meta: Optional[dict] = None) -> Trace:
    """Drain a Context's native profiling buffers into a Trace."""
    return Trace(ctx.profile_take(), rank=rank, class_names=class_names,
                 meta=meta)


def _node_id(cid, l0, l1, cname):
    return f"{cname(cid)}_{l0}_{l1}"


def to_dot(trace: Trace, name: str = "dag") -> str:
    """Executed-DAG capture as DOT (reference:
    parsec/parsec_prof_grapher.c:86-135, the --parsec dot flag)."""
    lines = [f"digraph {name} {{"]
    seen = set()
    for (sc, sl0, sl1), (dc, dl0, dl1) in trace.edges():
        a = _node_id(sc, sl0, sl1, trace._cname)
        b = _node_id(dc, dl0, dl1, trace._cname)
        for nd in (a, b):
            if nd not in seen:
                seen.add(nd)
                lines.append(f'  "{nd}";')
        lines.append(f'  "{a}" -> "{b}";')
    lines.append("}")
    return "\n".join(lines)
