"""PINS: pluggable instrumentation modules at the runtime's event points.

Reference: the PINS MCA framework (parsec/mca/pins/pins.h:26-54) — small
instrumentation modules (task_counter, task_profiler, print_steals, papi,
alperf) chain callbacks onto task lifecycle points, selected by the
`--mca pins <list>` parameter.  Here the native core exposes one
synchronous sink at the trace event points (native ptc_set_pins_cb, fired
from ptc_prof_push/ptc_prof_instant with the 8-word event record); a
PinsChain fans it out to the registered Python modules.  Disabled cost is
one relaxed load + branch per event point; enabling does NOT require
tracing to be on (and vice versa).

Selection mirrors the reference: the MCA param `runtime.pins` (env
`PTC_MCA_runtime_pins`) holds a comma-separated module-name list applied
at Context init, or modules are attached explicitly with enable_pins().
"""
from __future__ import annotations

import ctypes as C
import resource
import threading
from typing import Dict, List, Optional, Type

from .. import _native as N
from .trace import (KEY_COMM_RECV, KEY_COMM_SEND, KEY_DEVICE, KEY_EDGE,
                    KEY_EXEC, KEY_H2D, KEY_RELEASE, KEY_STREAM)

PINS_CB_T = N.PINS_CB_T


class PinsModule:
    """Base instrumentation module.  Override `mask` (bitmask of event
    keys to receive) and `on_event`.  on_event runs synchronously on
    worker/comm threads — keep it tiny and non-blocking.  Every native
    trace key is subscribable, including the device-pipeline keys
    (KEY_DEVICE dispatch waves, KEY_H2D staging with aux = lane,
    KEY_STREAM progressive-serve d2h slices) — the device manager pushes
    them through the same ptc_prof_event sink the worker events use."""

    name = "module"
    mask = (1 << KEY_EXEC) | (1 << KEY_RELEASE) | (1 << KEY_COMM_SEND) | \
           (1 << KEY_COMM_RECV) | (1 << KEY_DEVICE)

    def on_event(self, key: int, phase: int, class_id: int, l0: int,
                 l1: int, worker: int, aux: int, t_ns: int) -> None:
        raise NotImplementedError


class TaskCounter(PinsModule):
    """Per-class executed-task counts (reference: mca/pins/task_counter)."""

    name = "task_counter"
    mask = 1 << KEY_EXEC

    def __init__(self):
        self.counts: Dict[int, int] = {}
        # events arrive concurrently from every worker thread; dict RMW
        # spans bytecodes, so a GIL switch between load and store would
        # lose increments without the lock
        self._lock = threading.Lock()

    def on_event(self, key, phase, class_id, l0, l1, worker, aux, t_ns):
        if phase == 1:
            with self._lock:
                self.counts[class_id] = self.counts.get(class_id, 0) + 1

    @property
    def total(self) -> int:
        return sum(self.counts.values())


class TaskProfiler(PinsModule):
    """Per-(worker, class) execution-time accumulation (reference:
    mca/pins/task_profiler)."""

    name = "task_profiler"
    mask = 1 << KEY_EXEC

    def __init__(self):
        self._open: Dict[tuple, int] = {}
        self.stats: Dict[int, dict] = {}  # class_id -> count/total/min/max
        self._lock = threading.Lock()  # see TaskCounter

    def on_event(self, key, phase, class_id, l0, l1, worker, aux, t_ns):
        sig = (worker, class_id, l0, l1)
        with self._lock:
            if phase == 0:
                self._open[sig] = t_ns
                return
            t0 = self._open.pop(sig, None)
            if t0 is None:
                return
            d = t_ns - t0
            s = self.stats.setdefault(
                class_id,
                {"count": 0, "total_ns": 0, "min_ns": d, "max_ns": d})
            s["count"] += 1
            s["total_ns"] += d
            s["min_ns"] = min(s["min_ns"], d)
            s["max_ns"] = max(s["max_ns"], d)


class CommVolume(PinsModule):
    """Bytes + message counts by direction (reference: mca/pins/alperf's
    bandwidth accounting; the check-comms oracle counts the same events)."""

    name = "comm_volume"
    mask = (1 << KEY_COMM_SEND) | (1 << KEY_COMM_RECV)

    def __init__(self):
        self.sent_msgs = 0
        self.sent_bytes = 0
        self.recv_msgs = 0
        self.recv_bytes = 0
        self._lock = threading.Lock()  # see TaskCounter

    def on_event(self, key, phase, class_id, l0, l1, worker, aux, t_ns):
        with self._lock:
            if key == KEY_COMM_SEND:
                self.sent_msgs += 1
                self.sent_bytes += aux
            else:
                self.recv_msgs += 1
                self.recv_bytes += aux


class PrintSteals(PinsModule):
    """Reports per-worker steal counts when the chain uninstalls
    (reference: mca/pins/print_steals).  The counts themselves are native
    (Scheduler.steals, ticked inside select) — this module is the
    report-at-teardown role, so it subscribes to no events."""

    name = "print_steals"
    mask = 0

    def on_event(self, *a):  # pragma: no cover - mask=0, never called
        pass

    def on_uninstall(self, ctx) -> None:
        steals = ctx.worker_steals()
        import sys
        sys.stderr.write(
            f"ptc [pins] print_steals: per-worker steals {steals} "
            f"(total {sum(steals)})\n")


class HwCounters(PinsModule):
    """Per-class OS hardware/software counters over task execution spans
    (reference: mca/pins/papi, which reads PAPI event sets at the same
    hook points).  TPU VMs expose no PAPI; the portable equivalents are
    the per-THREAD rusage counters — user/system cpu-time, minor faults,
    voluntary + involuntary context switches — sampled at EXEC begin/end
    on the worker thread itself (RUSAGE_THREAD), so deltas attribute to
    exactly the sampled task.  Like the reference's papi module this is
    opt-in instrumentation: two getrusage syscalls per task (~1µs) — not
    for the ns/task hot-path benches."""

    name = "hwcounters"
    mask = 1 << KEY_EXEC

    def __init__(self):
        self._open: Dict[tuple, tuple] = {}
        # class_id -> [tasks, utime_us, stime_us, minflt, nvcsw, nivcsw]
        self.counters: Dict[int, list] = {}
        self._lock = threading.Lock()  # see TaskCounter

    @staticmethod
    def _sample():
        r = resource.getrusage(resource.RUSAGE_THREAD)
        return (int(r.ru_utime * 1e6), int(r.ru_stime * 1e6),
                r.ru_minflt, r.ru_nvcsw, r.ru_nivcsw)

    def on_event(self, key, phase, class_id, l0, l1, worker, aux, t_ns):
        sig = (worker, class_id, l0, l1)
        if phase == 0:
            with self._lock:
                self._open[sig] = self._sample()
            return
        now = self._sample()
        with self._lock:
            begin = self._open.pop(sig, None)
            if begin is None:
                return
            c = self.counters.setdefault(class_id, [0, 0, 0, 0, 0, 0])
            c[0] += 1
            for i in range(5):
                c[1 + i] += now[i] - begin[i]

    def report(self, class_names: Optional[Dict[int, str]] = None) -> str:
        rows = []
        with self._lock:
            items = sorted(self.counters.items())
        for cid, c in items:
            name = (class_names or {}).get(cid, f"class{cid}")
            rows.append(
                f"{name}: tasks={c[0]} utime={c[1]}us stime={c[2]}us "
                f"minflt={c[3]} vcsw={c[4]} ivcsw={c[5]}")
        return "\n".join(rows)

    def on_uninstall(self, ctx) -> None:
        import sys
        rep = self.report()
        if rep:
            sys.stderr.write("ptc [pins] hwcounters:\n" + rep + "\n")


class DeviceActivity(PinsModule):
    """Device-pipeline accounting at the PINS seam (the PR3/PR4 counters
    as a live instrumentation module): dispatch waves + lanes, h2d bytes
    split by lane (0 = dispatch-time stall, 1 = prefetch overlap), and
    progressive-serve d2h slices.  The same numbers Context.device_stats
    aggregates, but streamed per event — usable without tracing on."""

    name = "device_activity"
    mask = (1 << KEY_DEVICE) | (1 << KEY_H2D) | (1 << KEY_STREAM)

    def __init__(self):
        self.waves = 0
        self.lanes = 0
        self.stall_ns = 0          # DEVICE end-aux: dispatch h2d stall
        self.h2d_bytes = [0, 0]    # by lane: [dispatch-stall, prefetch]
        self.stream_slices = 0
        self.stream_bytes = 0
        self._lock = threading.Lock()  # see TaskCounter

    def on_event(self, key, phase, class_id, l0, l1, worker, aux, t_ns):
        with self._lock:
            if key == KEY_DEVICE:
                if phase == 1:
                    self.waves += 1
                    self.lanes += l0
                    self.stall_ns += aux
            elif key == KEY_H2D:
                if phase == 1:
                    self.h2d_bytes[1 if aux else 0] += l0
            elif key == KEY_STREAM:
                if phase == 1:
                    self.stream_slices += 1
                    self.stream_bytes += l0


class StragglerLog(PinsModule):
    """Top-k slowest task executions (class, locals, worker, duration) —
    the drill-down companion to the always-on latency histograms: the
    histogram says a class's p99 moved, this module says WHICH task
    instances sat in the tail.  Bounded memory (a k-entry leaderboard),
    so it can stay installed on long serving runs."""

    name = "straggler_log"
    mask = 1 << KEY_EXEC

    def __init__(self, k: int = 16):
        self.k = int(k)
        self._open: Dict[tuple, int] = {}
        self.slowest: List[tuple] = []  # (dur_ns, class_id, l0, l1, worker)
        self._floor = 0  # admission threshold once the board is full
        self._lock = threading.Lock()  # see TaskCounter

    def on_event(self, key, phase, class_id, l0, l1, worker, aux, t_ns):
        sig = (worker, class_id, l0, l1)
        with self._lock:
            if phase == 0:
                self._open[sig] = t_ns
                return
            t0 = self._open.pop(sig, None)
            if t0 is None:
                return
            d = t_ns - t0
            if len(self.slowest) >= self.k and d <= self._floor:
                return
            self.slowest.append((d, class_id, l0, l1, worker))
            self.slowest.sort(reverse=True)
            del self.slowest[self.k:]
            self._floor = self.slowest[-1][0] \
                if len(self.slowest) >= self.k else 0

    def report(self, class_names: Optional[Dict[int, str]] = None) -> str:
        with self._lock:
            rows = list(self.slowest)
        return "\n".join(
            f"{(class_names or {}).get(cid, f'class{cid}')}({l0},{l1}) "
            f"worker={w} {d / 1e6:.3f} ms"
            for d, cid, l0, l1, w in rows)


REGISTRY: Dict[str, Type[PinsModule]] = {
    TaskCounter.name: TaskCounter,
    TaskProfiler.name: TaskProfiler,
    CommVolume.name: CommVolume,
    PrintSteals.name: PrintSteals,
    HwCounters.name: HwCounters,
    DeviceActivity.name: DeviceActivity,
    StragglerLog.name: StragglerLog,
}


class PinsChain:
    """The installed module chain for one Context (reference: the
    pins module linked list walked at each event point)."""

    def __init__(self, ctx, modules: List[PinsModule]):
        self._ctx = ctx
        self.modules = list(modules)
        mask = 0
        for m in self.modules:
            mask |= m.mask
        self._mask = mask

        def _cb(user, words):
            w = words[:8]
            for m in self.modules:
                if (m.mask >> w[0]) & 1:
                    try:
                        m.on_event(*w)
                    except Exception:
                        # exceptions cannot cross the ctypes boundary; a
                        # raising module must not mute the rest of the
                        # chain (same guard as Taskpool._register_call)
                        import traceback
                        traceback.print_exc()

        self._cb = PINS_CB_T(_cb)
        # the trampoline must outlive the context, not just this chain: a
        # worker that loaded the pointer right before an uninstall may
        # still invoke it (see ptc_set_pins_cb ordering note)
        if not hasattr(ctx, "_pins_keepalive"):
            ctx._pins_keepalive = []
        ctx._pins_keepalive.append(self._cb)
        N.lib.ptc_set_pins_cb(ctx._ptr, self._cb, None, mask)

    def uninstall(self):
        # idempotent: a second call (explicit uninstall then Context
        # destroy) must not re-report or touch a freed native context
        if getattr(self, "_uninstalled", False):
            return
        self._uninstalled = True
        N.lib.ptc_set_pins_cb(self._ctx._ptr, C.cast(None, PINS_CB_T),
                              None, 0)
        for m in self.modules:
            hook = getattr(m, "on_uninstall", None)
            if hook is not None:
                try:
                    hook(self._ctx)
                except Exception:
                    import traceback
                    traceback.print_exc()
        self._ctx._pins_chain = None

    def __getitem__(self, name: str) -> PinsModule:
        for m in self.modules:
            if m.name == name:
                return m
        raise KeyError(name)


def enable_pins(ctx, *modules) -> PinsChain:
    """Install instrumentation modules on a Context.  Accepts PinsModule
    instances or registry names; returns the chain (also stored on
    ctx._pins_chain for keep-alive)."""
    insts: List[PinsModule] = []
    for m in modules:
        if isinstance(m, str):
            if m not in REGISTRY:
                raise KeyError(f"unknown pins module {m!r}; "
                               f"have {sorted(REGISTRY)}")
            insts.append(REGISTRY[m]())
        else:
            insts.append(m)
    chain = PinsChain(ctx, insts)
    ctx._pins_chain = chain
    return chain


def enable_from_param(ctx, spec: str) -> Optional[PinsChain]:
    names = [s.strip() for s in spec.split(",") if s.strip()]
    if not names:
        return None
    return enable_pins(ctx, *names)
