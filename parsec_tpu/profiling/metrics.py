"""Always-on runtime metrics: unified registry, exporters, health watchdog.

Reference role: PaRSEC ships always-on instrumentation (the PINS counter
modules) and live counter streaming (tools/aggregator_visu) alongside its
offline .prof traces.  The PR 5 tracing v2 work covered the offline half;
this module is the other half — the telemetry a serving stack assumes
exists before any QoS or admission-control work:

  MetricsRegistry   folds the native ptc_metrics histograms (per-class
                    EXEC duration, sampled release latency, h2d stall,
                    comm/coll rendezvous wait — log2 buckets with 8
                    linear sub-buckets per octave) with the counters
                    from Context.stats() into one namespaced model with
                    p50/p90/p99 estimates; exports Prometheus text
  MetricsExporter   stdlib http.server scrape endpoint
                    (PTC_MCA_runtime_metrics_port): /metrics prometheus
                    text, /stats.json raw counters, /healthz watchdog
  Watchdog          monitor thread (PTC_MCA_runtime_watchdog=<secs>):
                    stuck tasks (EXEC open past k*p99 per class),
                    starved workers, parked pulls not advancing, slow
                    ranks (fence-time clock-sync RTT outliers).  Every
                    detection emits a structured event into the metrics
                    stream and triggers a flight-recorder dump so the
                    incident leaves a post-mortem artifact.

The histograms are native and lock-free (native/core.cpp MetHist); this
module only snapshots and renders them — safe to call from any thread at
any frequency.
"""
from __future__ import annotations

import ctypes as C
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import _native as N

# bucket scheme constants (asserted against the native layout at import
# of the first registry — keep in sync with runtime_internal.h)
_SUBBITS = 3
_SUB = 1 << _SUBBITS
_MAX_OCT = 45
_BUCKETS = _SUB + (_MAX_OCT - _SUBBITS) * _SUB
_STRIDE = 4 + _BUCKETS

KIND_NAMES = N.MET_KIND_NAMES  # index == PTC_MET_* kind


def _check_layout():
    buf = (C.c_int64 * 4)()
    N.lib.ptc_metrics_layout(buf)
    assert buf[0] == len(KIND_NAMES) and buf[2] == _BUCKETS \
        and buf[3] == _SUBBITS, (
            "metrics bucket scheme drifted between native and Python: "
            f"native {list(buf)} vs python ({len(KIND_NAMES)}, -, "
            f"{_BUCKETS}, {_SUBBITS})")


def bucket_bounds(idx: int):
    """[lo, hi) nanosecond bounds of histogram bucket `idx`."""
    if idx < _SUB:
        return idx, idx + 1
    o = (idx - _SUB) // _SUB + _SUBBITS
    s = (idx - _SUB) % _SUB
    w = 1 << (o - _SUBBITS)
    lo = (1 << o) + s * w
    return lo, lo + w


class Hist:
    """One aggregated histogram record (kind, optional class name)."""

    __slots__ = ("kind", "mid", "name", "count", "sum_ns", "buckets")

    def __init__(self, kind, mid, name, count, sum_ns, buckets):
        self.kind = int(kind)
        self.mid = int(mid)
        self.name = name
        self.count = int(count)
        self.sum_ns = int(sum_ns)
        self.buckets = buckets  # np.int64[_BUCKETS]

    @property
    def kind_name(self) -> str:
        return KIND_NAMES[self.kind]

    @property
    def mean_ns(self) -> float:
        return self.sum_ns / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Estimated q-quantile in ns (linear interpolation inside the
        12.5%-wide bucket the rank lands in — <=~6% relative error)."""
        if self.count <= 0:
            return 0.0
        rank = q * self.count
        cum = 0
        for idx in range(_BUCKETS):
            c = int(self.buckets[idx])
            if c == 0:
                continue
            if cum + c >= rank:
                lo, hi = bucket_bounds(idx)
                frac = (rank - cum) / c
                return lo + frac * (hi - lo)
            cum += c
        lo, hi = bucket_bounds(_BUCKETS - 1)
        return float(hi)

    def summary(self) -> dict:
        return {
            "count": self.count,
            "sum_ns": self.sum_ns,
            "mean_ns": round(self.mean_ns, 1),
            "p50_ns": round(self.quantile(0.50), 1),
            "p90_ns": round(self.quantile(0.90), 1),
            "p99_ns": round(self.quantile(0.99), 1),
        }


def snapshot_histograms(ctx, merged: bool = False) -> List[Hist]:
    """Decode ptc_metrics_snapshot into Hist records.  merged=True folds
    the fence-time peer snapshots (meaningful on rank 0)."""
    _check_layout()
    max_classes = 0
    buf4 = (C.c_int64 * 4)()
    N.lib.ptc_metrics_layout(buf4)
    max_classes = int(buf4[1])
    cap = (max_classes + len(KIND_NAMES) + 1) * _STRIDE
    buf = (C.c_int64 * cap)()
    n = N.lib.ptc_metrics_snapshot(ctx._ptr, buf, cap, 1 if merged else 0)
    arr = np.ctypeslib.as_array(buf, shape=(cap,))[:n].copy()
    out: List[Hist] = []
    name_buf = C.create_string_buffer(256)
    for off in range(0, int(n), _STRIDE):
        kind, mid, count, sum_ns = (int(arr[off]), int(arr[off + 1]),
                                    int(arr[off + 2]), int(arr[off + 3]))
        name = None
        if kind == N.MET_EXEC and mid >= 0:
            k = N.lib.ptc_metrics_class_name(ctx._ptr, mid, name_buf, 256)
            if k > 0:
                name = name_buf.value.decode(errors="replace")
        out.append(Hist(kind, mid, name, count, sum_ns,
                        arr[off + 4:off + 4 + _BUCKETS]))
    return out


def _flatten_counters(prefix: str, obj, out: Dict[str, float]):
    """Numeric leaves of a stats dict -> flat metric names.  Lists,
    strings and None are skipped (per-worker vectors export poorly as
    unlabelled scalars; the JSON endpoint carries them verbatim)."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}_{k}" if prefix else str(k)
            _flatten_counters(key, v, out)
    elif isinstance(obj, bool):
        out[prefix] = 1 if obj else 0
    elif isinstance(obj, (int, float)):
        out[prefix] = obj


class MetricsRegistry:
    """Unified metrics model over one Context: native histograms +
    Context.stats() counters, rendered as a dict or Prometheus text."""

    def __init__(self, ctx):
        self.ctx = ctx
        _check_layout()

    # ------------------------------------------------------------ model
    def histograms(self, merged: bool = False) -> List[Hist]:
        return snapshot_histograms(self.ctx, merged=merged)

    def counters(self) -> Dict[str, float]:
        """Flattened numeric counters from the unified Context.stats()
        snapshot.  Ring-drop counts (trace_dropped_events) and comm
        stream `reaps` ride along — flight-recorder data loss and
        peer-loss cleanup are dashboard-visible, not trace-meta-only."""
        flat: Dict[str, float] = {}
        _flatten_counters("", self.ctx.stats(), flat)
        out = {}
        for k, v in flat.items():
            name = "ptc_" + k.strip("_").replace(".", "_")
            out[name] = v
        return out

    def snapshot(self, merged: bool = False) -> dict:
        """One namespaced model: histograms (per kind, EXEC per class)
        with quantile summaries + flattened counters."""
        hists: Dict[str, dict] = {k: {} for k in KIND_NAMES}
        for h in self.histograms(merged=merged):
            key = h.name if (h.kind == N.MET_EXEC and h.name) else "_"
            hists[h.kind_name][key] = h.summary()
        reg = getattr(self.ctx, "_scope_registry", None)
        try:
            scope_hists = reg.tenant_export() if reg is not None else {}
        except Exception:
            scope_hists = {}
        return {
            "t": time.time(),
            "rank": self.ctx.myrank,
            "merged": merged,
            "histograms": hists,
            "counters": self.counters(),
            # ptc-blackbox: per-tenant sparse-bucket export so a remote
            # FleetView federates /stats.json scrapes bit-identically
            # to in-process Server scrapes
            "scope_hists": scope_hists,
        }

    # ------------------------------------------------------- prometheus
    _HIST_FAMILY = {
        "exec": "ptc_task_exec_seconds",
        "release": "ptc_release_seconds",
        "h2d_stall": "ptc_h2d_stall_seconds",
        "comm_wait": "ptc_comm_wait_seconds",
        "coll_wait": "ptc_coll_wait_seconds",
    }

    def prometheus_text(self, merged: bool = False) -> str:
        """Prometheus exposition format: each histogram kind as a
        summary family (quantile labels + _sum/_count; EXEC labelled by
        class), each flattened counter as an untyped sample."""
        lines: List[str] = []
        by_kind: Dict[str, List[Hist]] = {}
        for h in self.histograms(merged=merged):
            by_kind.setdefault(h.kind_name, []).append(h)
        for kind, fam in self._HIST_FAMILY.items():
            hs = by_kind.get(kind)
            if not hs:
                continue
            lines.append(f"# HELP {fam} {kind} latency (ptc_metrics "
                         "log2-bucket histogram)")
            lines.append(f"# TYPE {fam} summary")
            for h in hs:
                lbl = ""
                if kind == "exec":
                    cls = (h.name or "_").replace('"', "'")
                    lbl = f'class="{cls}",'
                for q in (0.5, 0.9, 0.99):
                    v = h.quantile(q) / 1e9
                    lines.append(
                        f'{fam}{{{lbl}quantile="{q}"}} {v:.9g}')
                l2 = f"{{{lbl[:-1]}}}" if lbl else ""
                lines.append(f"{fam}_sum{l2} {h.sum_ns / 1e9:.9g}")
                lines.append(f"{fam}_count{l2} {h.count}")
        lines.extend(self._tenant_lines())
        for name, v in sorted(self.counters().items()):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {v:.9g}" if isinstance(v, float)
                         else f"{name} {v}")
        wd = getattr(self.ctx, "_watchdog", None)
        if wd is not None:
            lines.append("# TYPE ptc_watchdog_detections_total counter")
            lines.append(f"ptc_watchdog_detections_total {len(wd.events)}")
        fv = getattr(self.ctx, "_fleetview", None)
        if fv is not None:
            try:
                lines.extend(fv.prometheus_lines())
            except Exception:
                pass
        return "\n".join(lines) + "\n"


    # per-tenant SLO families (ptc-scope): the per-request metrics a
    # tenant dashboard alerts on, labelled tenant="..." — latencies in
    # seconds, tokens/s as-is
    _TENANT_FAMILIES = (
        ("ttft_ns", "ptc_tenant_ttft_seconds", 1e-9,
         "time to first token"),
        ("queue_wait_ns", "ptc_tenant_queue_wait_seconds", 1e-9,
         "submit -> admitted wait"),
        ("latency_ns", "ptc_tenant_request_seconds", 1e-9,
         "submit -> done latency"),
        ("tokens_per_s", "ptc_tenant_tokens_per_second", 1.0,
         "per-request decode rate"),
        ("spec_accept_pct", "ptc_tenant_spec_accept_percent", 1.0,
         "speculative-decode draft acceptance per verify wave"),
    )
    _TENANT_COUNTERS = ("submitted", "completed", "failed", "rejected",
                        "slo_violations", "prefix_hits", "prefix_misses",
                        "spec_proposed", "spec_accepted")
    # derived per-tenant rate gauges off the counters above
    # (ptc-share dashboards): (family, numerator, denominator keys)
    _TENANT_RATES = (
        ("ptc_tenant_prefix_hit_rate", "prefix_hits",
         ("prefix_hits", "prefix_misses"), "prefix-cache page hit rate"),
        ("ptc_tenant_spec_accept_rate", "spec_accepted",
         ("spec_proposed",), "speculative draft acceptance rate"),
    )

    def _tenant_lines(self) -> List[str]:
        """Tenant-dimensioned exposition from the ScopeRegistry (empty
        when no serve stack is attached)."""
        reg = getattr(self.ctx, "_scope_registry", None)
        if reg is None:
            return []
        lines: List[str] = []
        try:
            with reg._lock:
                tenants = {name: ({k: t.hists[k] for k, _, _, _ in
                                   self._TENANT_FAMILIES},
                                  dict(t.counters))
                           for name, t in reg.tenants.items()}
            slo = reg.slo_status()
        except Exception:
            return []
        for key, fam, scale, help_ in self._TENANT_FAMILIES:
            rows = [(n, h[key]) for n, (h, _) in sorted(tenants.items())
                    if h[key].count > 0]
            if not rows:
                continue
            lines.append(f"# HELP {fam} {help_} (per tenant)")
            lines.append(f"# TYPE {fam} summary")
            for name, h in rows:
                lbl = f'tenant="{name}"'
                for q in (0.5, 0.9, 0.99):
                    lines.append(f'{fam}{{{lbl},quantile="{q}"}} '
                                 f"{h.quantile(q) * scale:.9g}")
                lines.append(f"{fam}_sum{{{lbl}}} {h.sum * scale:.9g}")
                lines.append(f"{fam}_count{{{lbl}}} {h.count}")
        for cname in self._TENANT_COUNTERS:
            fam = f"ptc_tenant_{cname}_total"
            rows = [(n, c.get(cname, 0))
                    for n, (_, c) in sorted(tenants.items())]
            if not any(v for _, v in rows):
                continue
            lines.append(f"# TYPE {fam} counter")
            for name, v in rows:
                lines.append(f'{fam}{{tenant="{name}"}} {v}')
        for fam, num, dens, help_ in self._TENANT_RATES:
            rows = []
            for name, (_, c) in sorted(tenants.items()):
                total = sum(c.get(k, 0) for k in dens)
                if total:
                    rows.append((name, c.get(num, 0) / total))
            if not rows:
                continue
            lines.append(f"# HELP {fam} {help_} (per tenant)")
            lines.append(f"# TYPE {fam} gauge")
            for name, v in rows:
                lines.append(f'{fam}{{tenant="{name}"}} {v:.9g}')
        for name, st in sorted(slo.items()):
            lines.append("# TYPE ptc_tenant_slo_burn_rate gauge")
            lines.append(f'ptc_tenant_slo_burn_rate{{tenant="{name}"}} '
                         f"{st['burn_rate']:.9g}")
        return lines


class MetricsExporter:
    """Scrape endpoint on `port` (PTC_MCA_runtime_metrics_port):
      GET /metrics     Prometheus text (the registry's summary render)
      GET /stats.json  raw Context.stats() + histogram summaries (JSON)
      GET /healthz     watchdog status (200 ok / 503 after detections)
    Runs a daemon ThreadingHTTPServer; stop() closes the socket.
    """

    def __init__(self, ctx, port: int, merged: bool = False):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.ctx = ctx
        self.registry = MetricsRegistry(ctx)
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # scrapes must not spam stderr
                pass

            def _send(self, code, ctype, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if self.path.startswith("/metrics"):
                        txt = exporter.registry.prometheus_text(
                            merged=exporter.merged)
                        self._send(200, "text/plain; version=0.0.4",
                                   txt.encode())
                    elif self.path.startswith("/stats.json"):
                        body = json.dumps(
                            exporter.registry.snapshot(
                                merged=exporter.merged),
                            default=str).encode()
                        self._send(200, "application/json", body)
                    elif self.path.startswith("/fleet.json"):
                        fv = getattr(exporter.ctx, "_fleetview", None)
                        if fv is None:
                            self._send(404, "text/plain",
                                       b"no fleet view attached\n")
                        else:
                            self._send(200, "application/json",
                                       json.dumps(fv.snapshot(),
                                                  default=str).encode())
                    elif self.path.startswith("/healthz"):
                        wd = getattr(exporter.ctx, "_watchdog", None)
                        st = wd.status() if wd is not None else {
                            "watchdog": "off"}
                        # tenant SLO burn (ptc-scope) degrades health
                        # exactly like a watchdog detection: a scraper
                        # needs ONE endpoint for "is this serving rank
                        # meeting its promises"
                        reg = getattr(exporter.ctx, "_scope_registry",
                                      None)
                        breached = False
                        if reg is not None:
                            try:
                                slo = reg.slo_status()
                                st = dict(st, slo=slo)
                                breached = any(v.get("breached")
                                               for v in slo.values())
                            except Exception:
                                pass
                        code = 503 if (st.get("detections") or breached) \
                            else 200
                        self._send(code, "application/json",
                                   json.dumps(st, default=str).encode())
                    else:
                        self._send(404, "text/plain", b"not found\n")
                except Exception as e:  # scrape must never kill the server
                    try:
                        self._send(500, "text/plain", repr(e).encode())
                    except Exception:
                        pass

        self.merged = merged
        self._srv = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
        self.port = self._srv.server_address[1]  # resolved (port=0 ok)
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True,
                                        name="ptc-metrics-exporter")
        self._thread.start()

    def stop(self):
        try:
            self._srv.shutdown()
            self._srv.server_close()
        except Exception:
            pass


class Watchdog:
    """Health monitor thread.  Detections (each a structured event):

      stuck_task     an EXEC body open longer than the per-class
                     adaptive deadline max(k * p99(class), floor_s)
      starved_worker a worker whose selected-task count did not move
                     across `starve_ticks` ticks while the rest of the
                     context retired >= `starve_min_progress` tasks/tick
                     (advisory: no flight dump)
      stalled_pull   rendezvous pulls outstanding with no chunk/byte
                     progress across two ticks (a parked GET / stream
                     session not advancing its watermark looks exactly
                     like this from the consumer side)
      slow_rank      rank 0 only: a peer's fence-time clock-sync RTT
                     > outlier_factor * the median peer RTT (and above
                     1 ms — loopback noise must not page anyone)

    Every non-advisory detection triggers a flight-recorder dump
    (tracing must be on for the dump to contain anything), so an
    incident always leaves a post-mortem artifact next to the event.
    Dump names carry a per-process run id + a generation seq
    (`<prefix>.watchdog.<run_id>.<rank>.<seq>.ptt`) so repeat
    detections never overwrite an earlier incident's artifact;
    `max_dumps` bounds the generations per run and the emitted event
    (and its journal record) references the exact path it wrote.
    """

    def __init__(self, ctx, interval: float, k: float = 8.0,
                 floor_s: float = 30.0, min_count: int = 20,
                 starve_ticks: int = 3, starve_min_progress: int = 100,
                 outlier_factor: float = 4.0, max_dumps: int = 4):
        self.ctx = ctx
        self.interval = float(interval)
        self.k = float(k)
        self.floor_ns = int(float(floor_s) * 1e9)
        self.min_count = int(min_count)
        self.starve_ticks = int(starve_ticks)
        self.starve_min_progress = int(starve_min_progress)
        self.outlier_factor = float(outlier_factor)
        self.max_dumps = int(max_dumps)
        self.events: List[dict] = []
        self.ticks = 0
        self._dumps = 0
        # per-process run id: repeat runs against the same dump prefix
        # (or repeat detections within one) can never collide on names
        self._run_id = f"{os.getpid():x}-{int(time.time()) & 0xffffff:x}"
        self._reported = set()  # dedup key per incident
        self._prev_exec: Optional[list] = None
        self._starve_count: Dict[int, int] = {}
        self._prev_pull = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ptc-watchdog")
        self._thread.start()

    # ------------------------------------------------------------ events
    def _emit(self, ev: dict, dump: bool = True):
        ev = dict(ev, t=round(time.time(), 3), rank=self.ctx.myrank,
                  source="watchdog")
        key = (ev["type"], ev.get("key"))
        if key in self._reported:
            return
        self._reported.add(key)
        self.events.append(ev)
        sys.stderr.write("ptc-watchdog: " + json.dumps(ev) + "\n")
        for mon in list(getattr(self.ctx, "_monitors", [])):
            emit = getattr(mon, "emit", None)
            if emit is not None:
                try:
                    emit(dict(ev, event=ev["type"]))
                except Exception:
                    pass
        # ptc-pilot interrupt path: a stuck task or slow rank is acted
        # on IMMEDIATELY — the controller closes its observation window
        # and re-evaluates now rather than waiting out control.window
        # more pools
        if ev["type"] in ("stuck_task", "slow_rank"):
            ctrl = getattr(self.ctx, "_controller", None)
            if ctrl is not None:
                try:
                    ctrl.interrupt(ev["type"], key=str(ev.get("key")))
                except Exception:
                    pass
        if dump and self._dumps < self.max_dumps:
            try:
                if self.ctx.profile_level() > 0:
                    from ..utils import params as _mca
                    prefix = (_mca.get("runtime.trace_dump")
                              or "/tmp/ptc_flight")
                    path = (f"{prefix}.watchdog.{self._run_id}."
                            f"{self.ctx.myrank}.{self._dumps}.ptt")
                    self.ctx.flight_dump(path)
                    self._dumps += 1
                    ev["flight_dump"] = path
                    sys.stderr.write(
                        f"ptc-watchdog: flight-recorder dump -> {path}\n")
            except Exception as e:
                sys.stderr.write(f"ptc-watchdog: flight dump failed "
                                 f"({e!r})\n")
        # ptc-blackbox: every detection is a durable journal record that
        # references the dump it corresponds to (after the dump attempt,
        # so flight_dump rides along when one was written)
        jr = getattr(self.ctx, "_journal", None)
        if jr is not None:
            try:
                # the detection's own "type" rides as `kind` so it
                # cannot clobber the journal envelope's record type
                jr.record("watchdog",
                          **{("kind" if k == "type" else k): v
                             for k, v in ev.items()})
            except Exception:
                pass

    # -------------------------------------------------------- detections
    def _exec_p99(self) -> Dict[int, float]:
        out: Dict[int, float] = {}
        for h in snapshot_histograms(self.ctx):
            if h.kind == N.MET_EXEC and h.mid >= 0 and \
                    h.count >= self.min_count:
                out[h.mid] = h.quantile(0.99)
        return out

    def _class_name(self, mid: int) -> str:
        buf = C.create_string_buffer(256)
        k = N.lib.ptc_metrics_class_name(self.ctx._ptr, mid, buf, 256)
        return buf.value.decode(errors="replace") if k > 0 else f"#{mid}"

    def _scope_owner(self, scope: int) -> dict:
        """Name the victim request of a scoped detection: tenant + rid
        from the ScopeRegistry's legend (empty for unscoped work)."""
        if not scope:
            return {}
        out = {"scope_id": int(scope)}
        reg = getattr(self.ctx, "_scope_registry", None)
        if reg is not None:
            try:
                with reg._lock:
                    r = reg.requests.get(int(scope))
                if r is not None:
                    out["tenant"] = r.tenant
                    if r.rid is not None:
                        out["rid"] = r.rid
            except Exception:
                pass
        return out

    def _live_requests(self, cap: int = 8) -> list:
        """The in-flight requests at detection time (for detections —
        stalled pull, starved worker — with no single owning task):
        the flight dump then still names candidate victims."""
        reg = getattr(self.ctx, "_scope_registry", None)
        if reg is None:
            return []
        out = []
        try:
            with reg._lock:
                for sid, r in reg.requests.items():
                    if r.kind == "request" and r.state in ("submitted",
                                                           "running"):
                        out.append({"scope_id": sid, "tenant": r.tenant,
                                    "rid": r.rid})
                        if len(out) >= cap:
                            break
        except Exception:
            pass
        return out

    def _check_stuck(self, now_ns: int):
        cap = 4 * (self.ctx.nb_workers + 2)
        buf = (C.c_int64 * cap)()
        n = N.lib.ptc_metrics_inflight(self.ctx._ptr, buf, cap)
        if n <= 0:
            return
        p99 = self._exec_p99()
        for i in range(0, int(n), 4):
            worker, mid, begin, scope = (buf[i], buf[i + 1], buf[i + 2],
                                         buf[i + 3])
            open_ns = now_ns - begin
            deadline = max(self.k * p99.get(mid, 0.0), self.floor_ns)
            if open_ns > deadline:
                self._emit(dict({
                    "type": "stuck_task",
                    "key": (worker, begin),
                    "task_class": self._class_name(mid),
                    "worker": int(worker),
                    "open_ms": round(open_ns / 1e6, 1),
                    "deadline_ms": round(deadline / 1e6, 1),
                    "class_p99_ms": round(p99.get(mid, 0.0) / 1e6, 3),
                }, **self._scope_owner(scope)))

    def _check_starved(self):
        ex = self.ctx.worker_stats()
        prev = self._prev_exec
        self._prev_exec = ex
        if prev is None or len(prev) != len(ex) or len(ex) < 2:
            return
        deltas = [b - a for a, b in zip(prev, ex)]
        total = sum(deltas)
        if total < self.starve_min_progress:
            self._starve_count.clear()
            return
        for w, d in enumerate(deltas):
            if d == 0:
                self._starve_count[w] = self._starve_count.get(w, 0) + 1
                if self._starve_count[w] >= self.starve_ticks:
                    self._emit({
                        "type": "starved_worker",
                        "key": w,
                        "worker": w,
                        "ticks": self._starve_count[w],
                        "others_progress": total,
                        "live_requests": self._live_requests(),
                    }, dump=False)
            else:
                self._starve_count[w] = 0

    def _check_stalled_pull(self):
        if not self.ctx.comm_enabled:
            return
        rdv = self.ctx.comm_rdv_stats()
        tuning = self.ctx.comm_tuning()
        cur = (rdv["pending_pulls"], tuning["chunks_recv"],
               self.ctx.comm_stats()["bytes_recv"])
        prev = self._prev_pull
        self._prev_pull = cur
        if prev is None:
            return
        if cur[0] > 0 and prev[0] > 0 and cur[1] == prev[1] and \
                cur[2] == prev[2]:
            self._emit({
                "type": "stalled_pull",
                "key": cur[1],
                "pending_pulls": int(cur[0]),
                "stalled_for_s": round(self.interval, 3),
                "live_requests": self._live_requests(),
            })

    def _check_slow_ranks(self):
        ctx = self.ctx
        if not ctx.comm_enabled or ctx.myrank != 0 or ctx.nodes < 3:
            return
        rtts = ctx.metrics_peer_rtts()
        peers = [(r, v) for r, v in enumerate(rtts) if r != 0 and v > 0]
        if len(peers) < 2:
            return
        vals = sorted(v for _, v in peers)
        median = vals[len(vals) // 2]
        for r, v in peers:
            if v > max(self.outlier_factor * median, 1_000_000):
                self._emit({
                    "type": "slow_rank",
                    "key": r,
                    "peer_rank": r,
                    "rtt_ms": round(v / 1e6, 3),
                    "median_rtt_ms": round(median / 1e6, 3),
                }, dump=False)

    def _check_slo_burn(self):
        """Tenant SLO burn (ptc-scope): a tenant whose sliding-window
        violation rate reached its burn threshold gets a structured
        event (advisory: the flight dump stays armed for harder
        incidents, /healthz already turns 503)."""
        reg = getattr(self.ctx, "_scope_registry", None)
        if reg is None:
            return
        try:
            status = reg.slo_status()
        except Exception:
            return
        for tenant, st in status.items():
            if st.get("breached"):
                self._emit({
                    "type": "slo_burn",
                    "key": (tenant, st["violations"]),
                    "tenant": tenant,
                    "slo_ms": st["slo_ms"],
                    "burn_rate": st["burn_rate"],
                    "window_n": st["window_n"],
                }, dump=False)

    # --------------------------------------------------------------- run
    def _tick(self):
        self.ticks += 1
        self._check_stuck(_native_now())
        self._check_starved()
        self._check_stalled_pull()
        self._check_slow_ranks()
        self._check_slo_burn()

    def _loop(self):
        warned = False
        while not self._stop.wait(self.interval):
            if getattr(self.ctx, "_destroyed", False):
                return
            try:
                self._tick()
            except Exception as e:
                if not warned:
                    warned = True
                    sys.stderr.write(f"ptc-watchdog: tick failed ({e!r}); "
                                     "will keep trying\n")

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)

    def status(self) -> dict:
        return {
            "watchdog": "on",
            "interval_s": self.interval,
            "ticks": self.ticks,
            "detections": len(self.events),
            "events": self.events[-16:],
        }


def _native_now() -> int:
    """Clock base for comparing against the native inflight begin_ns
    stamps: the runtime's OWN ptc_now_ns (exported as ptc_clock_ns) —
    its TSC fast path drifts from CLOCK_MONOTONIC over long processes,
    so time.monotonic_ns would skew open-duration estimates by
    milliseconds after minutes of uptime."""
    return int(N.lib.ptc_clock_ns())


def enable_from_param(ctx, secs) -> Optional[Watchdog]:
    """`PTC_MCA_runtime_watchdog=<seconds>` hook (Context.__init__)."""
    try:
        iv = float(secs)
    except (TypeError, ValueError):
        sys.stderr.write(f"ptc-watchdog: runtime.watchdog={secs!r} is not "
                         "a number of seconds; watchdog disabled\n")
        return None
    if iv <= 0:
        return None
    from ..utils import params as _mca
    return Watchdog(
        ctx, iv,
        k=_mca.get("runtime.watchdog_k"),
        floor_s=_mca.get("runtime.watchdog_floor_s"),
    )
