"""Observability: paired-event binary traces, trace tables, DOT grapher.

The reference's L7 stack (SURVEY.md §5): per-thread event buffers flushed
to a per-rank binary profile ("dbp", parsec/parsec_binary_profile.h:45)
whose events are paired begin/end keys from a global dictionary
(parsec/profiling.c:580,791), converted offline to pandas trace tables by
the Cython pbt2ptt (tools/profiling/python/pbt2ptt.pyx), plus a DOT DAG
grapher (parsec/parsec_prof_grapher.c:86-135).  This package is the
TPU-native equivalent over the native core's 8-word event stream
(native/runtime_internal.h PROF_WORDS):

  Dictionary     event-key registry with names/colors
  Trace          take/save/load/merge + to_pandas() trace tables +
                 to_perfetto() standard-tool sink (the OTF2-writer analog)
                 — merge applies cross-rank CLOCK SYNC, detects
                 dictionary conflicts and matches send/recv FLOW ids
                 (tracing v2); flows()/wire_latency() expose the
                 per-message pairs
  critpath       critical_path() / lost_time() over the executed DAG
  to_dot         executed-DAG capture from EDGE event pairs
  pins           pluggable instrumentation-module chain at the event
                 points (parsec/mca/pins/pins.h analog), MCA-selected
  Journal        crash-durable per-rank JSONL flight journal + native
                 fatal-signal dump arming (ptc-blackbox)
  FleetView      cross-replica /stats.json federation -> /fleet.json
"""
from .trace import (KEY_EXEC, KEY_RELEASE, KEY_EDGE,
                    KEY_COMM_SEND, KEY_COMM_RECV, KEY_DEVICE, KEY_H2D,
                    KEY_STREAM, KEY_COLL, KEY_SCOPE, KEY_INFLIGHT,
                    Dictionary, Trace, take_trace, to_dot)
from .critpath import critical_path, lost_time
from .pins import (PinsModule, PinsChain, TaskCounter, TaskProfiler,
                   CommVolume, DeviceActivity, StragglerLog, REGISTRY,
                   enable_pins)
from .metrics import (Hist, MetricsRegistry, MetricsExporter, Watchdog,
                      snapshot_histograms)
from .scope import ScopeRegistry, request_timeline
from .blackbox import Journal, FleetView

__all__ = ["KEY_EXEC", "KEY_RELEASE", "KEY_EDGE",
           "KEY_COMM_SEND", "KEY_COMM_RECV", "KEY_DEVICE", "KEY_H2D",
           "KEY_STREAM", "KEY_COLL", "KEY_SCOPE", "KEY_INFLIGHT",
           "Dictionary", "Trace",
           "take_trace", "to_dot",
           "critical_path", "lost_time",
           "PinsModule", "PinsChain", "TaskCounter", "TaskProfiler",
           "CommVolume", "DeviceActivity", "StragglerLog", "REGISTRY",
           "enable_pins",
           "Hist", "MetricsRegistry", "MetricsExporter", "Watchdog",
           "snapshot_histograms",
           "ScopeRegistry", "request_timeline",
           "Journal", "FleetView"]
