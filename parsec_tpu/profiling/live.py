"""Live metrics monitor — the minimal aggregator_visu analog.

Reference role: tools/aggregator_visu streams per-rank runtime counters
out of a running job for live display.  TPU-native translation: a
sampler thread snapshots the context's counters (worker selected-task
counts, device queue depth / cache occupancy, comm volumes, rusage,
and the always-on latency histograms' per-class p50/p99) at a fixed
interval and appends one JSON line per sample to a sink — a file any
dashboard, `tail -f`, or pandas can consume live.  Enable per process
with `PTC_MCA_runtime_live=<interval_s>` or programmatically:

    mon = LiveMonitor(ctx, path="/tmp/ptc_live_{rank}.jsonl", interval=1.0)
    ... run taskpools ...
    mon.latest()  # newest sample dict (None before the first)
    mon.stop()    # or it stops with the context

The sink is SIZE-CAPPED (runtime.live_max_bytes, default 64 MiB): when
it grows past the cap it rotates to `<path>.1` (one generation kept),
so a long serving run cannot grow /tmp unboundedly.  Watchdog
detections are written into the same stream via `emit()` — one file
carries both the periodic samples and the structured incident events.

The sink path is formatted with the context's rank at FIRST SAMPLE (not
construction), so the env-installed monitor picks up set_rank() done by
comm bring-up.  On shared hosts point `path` somewhere private — the
default lives in /tmp for tail-ability, like the repo's other scratch
sinks.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional


class LiveMonitor:
    def __init__(self, ctx, path: str = "/tmp/ptc_live_{rank}.jsonl",
                 interval: float = 1.0,
                 max_bytes: Optional[int] = None):
        from ..utils import params as _mca
        self.ctx = ctx
        self._path_tmpl = path
        self.path: Optional[str] = None  # resolved at first sample
        self.interval = float(interval)
        self.max_bytes = (_mca.get("runtime.live_max_bytes")
                          if max_bytes is None else int(max_bytes))
        self._stop = threading.Event()
        self._t0 = time.time()
        self._fh = None
        self._written = 0  # bytes in the current sink generation
        self._last: Optional[dict] = None
        self._write_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ptc-live-monitor")
        self._thread.start()
        # registered for teardown in its OWN list — _devices is the
        # device-protocol fan-out (stage-in, coherence callbacks) and a
        # monitor must never be visible there
        ctx._monitors.append(self)

    def stop(self):
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # a wedged sample owns the file handle: do not race it
            sys.stderr.write("ptc-live: sampler did not stop in 5s; "
                             "leaving its file handle open\n")
            return
        try:
            self._sample()  # final snapshot so short runs record something
        except Exception:
            pass
        finally:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def latest(self) -> Optional[dict]:
        """The newest sample record (None before the first sample) —
        the programmatic accessor dashboards-in-process use instead of
        re-parsing their own JSONL sink."""
        return self._last

    def emit(self, rec: dict):
        """Append an arbitrary record to the sink (thread-safe).  The
        watchdog routes its structured detection events here so one
        stream carries samples AND incidents."""
        with self._write_lock:
            self._write_locked(rec)

    def _ensure_sink_locked(self):
        if self._fh is None:
            self.path = self._path_tmpl.format(rank=self.ctx.myrank)
            self._fh = open(self.path, "a", buffering=1)
            try:
                self._written = os.fstat(self._fh.fileno()).st_size
            except OSError:
                self._written = 0

    def _write_locked(self, rec: dict):
        self._ensure_sink_locked()
        line = json.dumps(rec) + "\n"
        # size-capped rotation: never let one generation exceed the cap
        # (checked BEFORE the write, so a line lands whole in exactly
        # one generation — the rotation-boundary contract the test pins)
        if self.max_bytes > 0 and self._fh is not None and \
                self._written + len(line) > self.max_bytes and \
                self._written > 0:
            self._fh.close()
            self._fh = None
            try:
                os.replace(self.path, self.path + ".1")
            except OSError as e:
                sys.stderr.write(f"ptc-live: rotation failed ({e!r}); "
                                 "continuing in place\n")
            self._ensure_sink_locked()
        self._fh.write(line)
        self._written += len(line)

    def _sample(self):
        ctx = self.ctx
        rec = {
            "t": round(time.time() - self._t0, 3),
            "rank": ctx.myrank,
            "workers": ctx.worker_stats(),
            "steals": ctx.worker_steals(),
        }
        for i, dev in enumerate(ctx._devices):
            if not hasattr(dev, "stats"):
                continue
            s = dev.stats
            rec[f"dev{i}_tasks"] = s.get("tasks", 0)
            rec[f"dev{i}_cache_bytes"] = s.get("cache_bytes", 0)
            qid = getattr(dev, "qid", None)
            if qid is not None:
                rec[f"dev{i}_qdepth"] = ctx.device_queue_depth(qid)
        if ctx._devices:
            # device-pipeline counters (PR3): prefetch effectiveness +
            # stall/overlap evolution is what a live dashboard watches
            ds = ctx.device_stats()
            rec["device"] = {k: ds[k] for k in
                             ("prefetch_hits", "prefetch_misses",
                              "prefetch_staged", "h2d_stall_ns",
                              "prefetch_h2d_ns", "overlap_ratio",
                              "spills", "reserve_fails")}
        if ctx.comm_enabled:
            rec["comm"] = ctx.comm_stats()
            # streaming-pipeline counters (PR4): session count + the
            # d2h/wire overlap fraction, live
            ss = ctx.comm_stream_stats()
            rec["stream"] = {k: ss[k] for k in
                             ("sessions", "parked_gets",
                              "overlap_fraction")}
            # per-link-class wire split (ptc-topo): compact rows — the
            # ici/dcn byte balance is the live signal that hierarchical
            # collectives / rank remaps are actually keeping bulk
            # traffic off the inter-island links
            try:
                ts = ctx.comm_topo_stats()
                rec["topo"] = {
                    "n_islands": ts["n_islands"],
                    "classes": {c: [row["bytes_sent"],
                                    row["msgs_sent"]]
                                for c, row in ts["classes"].items()
                                if row["msgs_sent"]
                                or row["bytes_sent"]}}
            except Exception:
                pass  # topo rows are best-effort in a live sample
        # always-on latency quantiles (PR7): per-class exec p50/p99 +
        # the per-kind p99s — the continuous-serving signal the offline
        # trace can't give.  Compact form: [count, p50_ns, p99_ns].
        if ctx.metrics_enabled:
            try:
                from . import metrics as _m
                lat = {}
                kinds = {}
                for h in _m.snapshot_histograms(ctx):
                    row = [h.count, round(h.quantile(0.5)),
                           round(h.quantile(0.99))]
                    if h.kind == 0 and h.name:  # MET_EXEC
                        lat[h.name] = row
                    elif h.kind != 0:
                        kinds[h.kind_name] = row
                if lat:
                    rec["latency"] = lat
                if kinds:
                    rec["latency_kinds"] = kinds
            except Exception:
                pass  # histograms are best-effort in a live sample
            rec["trace_dropped"] = ctx.profile_dropped()
        # serving rows (ptc-serve + ptc-scope): per-tenant occupancy,
        # TTFT/latency p99, tokens/s, SLO burn and the conformance
        # makespan ratio — the live tenant table tools/ptc_top.py draws
        servers = getattr(ctx, "_servers", None)
        if servers:
            try:
                sv = servers[-1].stats()
                rec["serve"] = {
                    name: {"active": row["active_pools"],
                           "queued": row["queue_depth"],
                           "rejected": row["rejected"]}
                    for name, row in sv["tenants"].items()}
            except Exception:
                pass
        # fleet rows (ptc-route): the Router registers on every replica
        # context it fronts; one stats() snapshot per sample feeds the
        # per-replica table (occupancy, pfx_hit, migrated bytes) that
        # tools/ptc_top.py draws
        routers = getattr(ctx, "_routers", None)
        if routers:
            try:
                rt = routers[-1].stats()
                rec["fleet"] = {"router": rt["router"],
                                "replicas": rt["replicas"]}
            except Exception:
                pass
        # ptc-pilot: the self-driving controller's decision snapshot
        # (drift, retunes, hot-swaps, budget shares, per-tenant spec_k)
        ctrl = getattr(ctx, "_controller", None)
        if ctrl is not None:
            try:
                rec["control"] = ctrl.stats()
            except Exception:
                pass
        reg = getattr(ctx, "_scope_registry", None)
        if reg is not None:
            try:
                sc = reg.stats()
                def _rate(row, num, *dens):
                    total = sum(row.get(k, 0) for k in dens)
                    return round(row.get(num, 0) / total, 3) \
                        if total else None

                rec["tenants"] = {
                    name: {"completed": row["completed"],
                           "ttft_p99_ms": round(
                               row["ttft_ns_p99"] / 1e6, 3),
                           "latency_p99_ms": round(
                               row["latency_ns_p99"] / 1e6, 3),
                           "tok_s_p50": row["tokens_per_s_p50"],
                           "slo_burn": (sc["slo"].get(name) or {}).get(
                               "burn_rate"),
                           # ptc-share: prefix-cache hit rate +
                           # speculative draft acceptance per tenant
                           "prefix_hit": _rate(row, "prefix_hits",
                                               "prefix_hits",
                                               "prefix_misses"),
                           "spec_acc": _rate(row, "spec_accepted",
                                             "spec_proposed"),
                           # ptc-shard: p99 stall waiting on the
                           # embedded tensor-parallel collective
                           "coll_wait_p99_ms": round(
                               row.get("coll_wait_ns_p99", 0) / 1e6, 3)}
                    for name, row in sc["tenants"].items()}
                conf = sc["conformance"]
                rec["conformance"] = {
                    "coverage": conf["coverage"],
                    "makespan_ratio_p50": conf["makespan"]["ratio_p50"],
                    "comm_sound": conf["comm_bytes"]["sound"],
                }
            except Exception:
                pass
        ru = ctx.rusage()
        rec["maxrss_kb"] = ru["maxrss_kb"]
        rec["utime_s"] = ru["utime_s"]
        self._last = rec
        with self._write_lock:
            self._write_locked(rec)

    def _loop(self):
        warned = False
        while not self._stop.wait(self.interval):
            if getattr(self.ctx, "_destroyed", False):
                return
            try:
                self._sample()
            except Exception as e:
                # transient errors (device mid-teardown, full disk) must
                # not silently end a multi-hour monitoring run
                if not warned:
                    warned = True
                    sys.stderr.write(f"ptc-live: sample failed ({e!r}); "
                                     "will keep trying\n")


def enable_from_param(ctx, value) -> Optional[LiveMonitor]:
    """`PTC_MCA_runtime_live=<seconds>` hook (Context.__init__)."""
    try:
        iv = float(value)
    except (TypeError, ValueError):
        sys.stderr.write(f"ptc-live: runtime.live={value!r} is not a "
                         "number of seconds; monitoring disabled\n")
        return None
    if iv <= 0:
        sys.stderr.write(f"ptc-live: runtime.live={value!r} must be a "
                         "positive interval; monitoring disabled\n")
        return None
    return LiveMonitor(ctx, interval=iv)
