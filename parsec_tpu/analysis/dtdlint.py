"""DTD insertion linter: the dynamic-path counterpart of ptc-verify.

PTG graphs are verified before execution (analysis.verify); a DTD graph
only exists as it is inserted, so the linter rides insertion.  Opt-in
via `DtdTaskpool(ctx, lint=True)` (or lint="warn" to report instead of
raise).  Rules carry stable IDs like the V-rules:

  D101  undeclared access-mode conflict: the same tile passed twice to
        one task with modes that overlap in a write (e.g. INPUT +
        OUTPUT as separate arguments).  The native accessor chain
        orders the two flows arbitrarily — declare one INOUT argument
        instead.
  D102  use-after-finalize: a task inserted against a tile whose
        owning taskpool already ran wait()/destroy() — the accessor
        chain is gone and the insert dangles.
  D103  dead store (reported at wait()): a tile whose LAST access is
        OUTPUT with no later reader in the pool — the write is never
        observed through the dataflow (warning; the backing memory
        still holds it).
  D104  tile/arena stride mismatch: an inserted tile whose backing
        data's byte size disagrees with its collection's declared
        stride (mb x nb x itemsize — what device staging and the
        arena-backed wire path assume).  Caught statically at insert,
        before the runtime truncates or over-reads the payload.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

DTD_RULES: Dict[str, str] = {
    "D101": "undeclared access-mode conflict in one task",
    "D102": "tile use after taskpool finalize",
    "D103": "dead store: OUTPUT tile never read afterwards",
    "D104": "tile byte size disagrees with its collection's stride",
}


class DtdLintError(RuntimeError):
    """Error-severity DTD lint finding (rule id in .rule)."""

    def __init__(self, rule: str, message: str):
        self.rule = rule
        super().__init__(f"{rule}: {message}")


class DtdLinter:
    """Per-taskpool insertion observer.  The DtdTaskpool calls
    `on_insert` before handing the task to the native engine,
    `on_wait` when the window closes, and `on_destroy` when tiles are
    freed; `findings` accumulates (rule, message) warnings."""

    def __init__(self, mode: str = "error"):
        # mode "error": raise DtdLintError on error-severity findings;
        # mode "warn": record everything in .findings only
        self.mode = mode
        self.findings: List[Tuple[str, str]] = []
        self._finalized = False
        self._task_no = 0
        # tile id -> (last mode, task_no of last access, reads seen
        #             since last write)
        self._tiles: Dict[int, list] = {}
        self._names: Dict[int, str] = {}

    # ---------------------------------------------------------- events
    def _emit(self, rule: str, severity: str, message: str):
        self.findings.append((rule, message))
        if severity == "error" and self.mode != "warn":
            raise DtdLintError(rule, message)

    def _tname(self, tile) -> str:
        nm = self._names.get(id(tile))
        if nm is None:
            nm = f"tile#{len(self._names)}"
            self._names[id(tile)] = nm
        return nm

    def on_insert(self, args):
        """args: sequence of (tile, mode_int) the task was declared
        with (modes already normalized to INPUT=1/OUTPUT=2/INOUT=3)."""
        self._task_no += 1
        if self._finalized:
            self._emit(
                "D102", "error",
                f"task #{self._task_no} inserted after the taskpool "
                "was finalized (wait() already closed the window): "
                "the dependency chains it would attach to are gone")
            return
        seen: Dict[int, int] = {}
        for tile, mode in args:
            key = id(tile)
            st = self._tiles.get(key)
            stride = getattr(tile, "coll_stride", None)
            nbytes = getattr(tile, "nbytes", None)
            if st is None and stride is not None and nbytes is not None \
                    and nbytes != stride:
                # first sight of the tile: its data size must match the
                # collection's declared stride, or the runtime's staging
                # and wire paths truncate or over-read the payload
                self._emit(
                    "D104", "error",
                    f"task #{self._task_no}: {self._tname(tile)} backs "
                    f"{nbytes} B but its collection declares a "
                    f"{stride} B tile stride — device staging and the "
                    "arena-backed wire path move stride-sized "
                    "payloads, so this tile would be truncated or "
                    "over-read; fix the collection's tile allocation "
                    "(or its declared mb/nb/dtype)")
            if getattr(tile, "_lint_finalized", False):
                self._emit(
                    "D102", "error",
                    f"task #{self._task_no} uses {self._tname(tile)} "
                    "from a destroyed taskpool: its accessor chain was "
                    "freed (use-after-finalize)")
            if key in seen:
                if (seen[key] | mode) & 2 and seen[key] != mode:
                    self._emit(
                        "D101", "error",
                        f"task #{self._task_no} passes "
                        f"{self._tname(tile)} twice with conflicting "
                        f"modes ({seen[key]} and {mode}): the two "
                        "flows order arbitrarily in the accessor "
                        "chain — declare one INOUT argument instead")
                seen[key] |= mode
            else:
                seen[key] = mode
            if st is None:
                st = self._tiles[key] = [0, 0, 0, tile]
            st[0] = mode
            st[1] = self._task_no
            if mode & 1:
                st[2] += 1  # read since last write ...
            if mode & 2 and not (mode & 1):
                st[2] = 0  # ... pure write resets the reader count

    def on_wait(self):
        if self._finalized:
            return
        self._finalized = True
        for key, (mode, _task, nreads, tile) in self._tiles.items():
            if mode == 2 and nreads == 0:
                self._emit(
                    "D103", "warning",
                    f"{self._tname(tile)}: last access is OUTPUT with "
                    "no later reader in this pool — dead store through "
                    "the dataflow (drop the task or read the result)")

    def on_destroy(self):
        self._finalized = True
        for st in self._tiles.values():
            tile = st[3]
            try:
                tile._lint_finalized = True
            except AttributeError:
                pass
