"""Symbolic flow-graph extraction from compiled PTG task-class tables.

One extractor, two consumers: the `verify` rule engine and the
tools/jdf2dot.py visualizer both read the graph produced here, so what
the verifier checks is exactly what the grapher draws.

The extraction mirrors the native dependency engine's semantics
(native/core.cpp) rather than re-inventing them:

  - expression evaluation replicates the stack-VM opcode semantics
    (C truncating division/modulo, shift clamps, div-by-zero -> 0);
  - execution-space membership replicates `task_params_in_domain`
    (per-axis bounds with the candidate params bound, comprehension
    value-set walks);
  - input selection replicates `select_input_dep` — first guard-true
    dep with an existing producer; in COUNTING (conservative) mode a
    dynamic guard (one containing a Python escape) on a task source is
    treated as a potential delivery, exactly like the native counter;
  - producer emission replicates the `release_deps` walk: per-dep
    bracketed iterators, range (broadcast) expansion, and silent
    dropping of out-of-domain successors.

Two analysis levels:

  symbolic   — the classes/flows/deps structure with guard classification
               and interval (affine) bounds reasoning; always available.
  concrete   — bounded enumeration of the execution space producing the
               exact instance DAG (expected input counts vs actual
               deliveries, memory reads/writes).  `FlowGraph.concretize`
               refuses past `max_instances` and records a note instead
               of silently truncating.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import _native as N
from ..core import expr as E
from ..core.taskclass import Mem, Ref


# ------------------------------------------------------------------ C-ops
def _tdiv(a: int, b: int) -> int:
    """C truncating integer division; div-by-zero -> 0 (native VM)."""
    if b == 0:
        return 0
    q = abs(a) // abs(b)
    return q if (a < 0) == (b < 0) else -q


def _tmod(a: int, b: int) -> int:
    if b == 0:
        return 0
    return a - _tdiv(a, b) * b


def _shclamp(b: int) -> int:
    return 0 if b < 0 else (62 if b > 62 else b)


def _jdf_nodes():
    from ..dsl import jdf
    return jdf._Name, jdf._PyEscape


def expr_nodes(e):
    """Iterate an expression tree (Expr objects, ints excluded)."""
    stack = [e]
    while stack:
        x = stack.pop()
        if not isinstance(x, E.Expr):
            continue
        yield x
        if isinstance(x, E.BinOp):
            stack += [x.a, x.b]
        elif isinstance(x, E.UnOp):
            stack.append(x.a)
        elif isinstance(x, E.Select):
            stack += [x.c, x.a, x.b]


def expr_is_dynamic(e) -> bool:
    """Does the expression call into Python (a `%{ %}` escape or
    pt.call)?  Mirrors the native guard_dyn classification
    (core.cpp expr_has_call): such an expression may read state task
    bodies write later, so the ENGINE counts it conservatively —
    whether or not it is declared pure."""
    if e is None or isinstance(e, int):
        return False
    _Name, _PyEscape = _jdf_nodes()
    return any(isinstance(x, (E.Call, _PyEscape)) for x in expr_nodes(e))


def expr_is_impure(e) -> bool:
    """Is analysis-time evaluation NOT binding?  True for `%{ %}`
    escapes and undeclared pt.call callbacks; False for pt.call(...,
    pure=True) frozen tables, whose value the verifier may trust."""
    if e is None or isinstance(e, int):
        return False
    _Name, _PyEscape = _jdf_nodes()
    for x in expr_nodes(e):
        if isinstance(x, _PyEscape):
            return True
        if isinstance(x, E.Call) and not getattr(x, "pure", False):
            return True
    return False


# ------------------------------------------------------------- intervals
def interval_of(e, ivals: Dict[int, Tuple[int, int]], names: Dict[str, int],
                gdict: Dict[str, int]):
    """Affine/interval bound of an expression: (lo, hi) or None when the
    expression leaves the affine fragment (escapes, div/mod).  `ivals`
    maps local slots to their (lo, hi) bounds."""
    if e is None:
        return None
    if isinstance(e, int):
        return (e, e)
    _Name, _PyEscape = _jdf_nodes()
    if isinstance(e, E.Const):
        return (e.v, e.v)

    def name_iv(nm):
        if nm in names and names[nm] in ivals:
            return ivals[names[nm]]
        if nm in gdict:
            return (gdict[nm], gdict[nm])
        return None

    if isinstance(e, E.L):
        return name_iv(e.name)
    if isinstance(e, _Name):
        return name_iv(e.name)
    if isinstance(e, E.G):
        return (gdict[e.name], gdict[e.name]) if e.name in gdict else None
    if isinstance(e, E.UnOp):
        a = interval_of(e.a, ivals, names, gdict)
        if e.op == N.OP_NEG:
            return (-a[1], -a[0]) if a else None
        if e.op == N.OP_NOT:
            return (0, 1)
        return None
    if isinstance(e, E.Select):
        a = interval_of(e.a, ivals, names, gdict)
        b = interval_of(e.b, ivals, names, gdict)
        if a and b:
            return (min(a[0], b[0]), max(a[1], b[1]))
        return None
    if isinstance(e, E.BinOp):
        if e.op in (N.OP_EQ, N.OP_NE, N.OP_LT, N.OP_LE, N.OP_GT, N.OP_GE,
                    N.OP_AND, N.OP_OR):
            return (0, 1)
        a = interval_of(e.a, ivals, names, gdict)
        b = interval_of(e.b, ivals, names, gdict)
        if not a or not b:
            return None
        if e.op == N.OP_ADD:
            return (a[0] + b[0], a[1] + b[1])
        if e.op == N.OP_SUB:
            return (a[0] - b[1], a[1] - b[0])
        if e.op == N.OP_MUL:
            ps = (a[0] * b[0], a[0] * b[1], a[1] * b[0], a[1] * b[1])
            return (min(ps), max(ps))
        if e.op == N.OP_MIN:
            return (min(a[0], b[0]), min(a[1], b[1]))
        if e.op == N.OP_MAX:
            return (max(a[0], b[0]), max(a[1], b[1]))
        return None
    return None


# -------------------------------------------------------- expr -> lambda
class ExprCompiler:
    """Compile Expr trees to Python lambdas over a locals list `l`.

    Globals are constant per taskpool and folded in; `pt.call`
    callbacks receive (locals_list, globals_dict) like the native
    OP_CALL bridge; JDF `%{ %}` escapes are evaluated over the program
    scope with task locals bound by name — both exactly as at runtime.
    """

    def __init__(self, gdict: Dict[str, int], scope: Optional[dict]):
        self.gdict = gdict
        self.scope = scope
        self._cache: Dict[tuple, Callable] = {}
        self._esc_code: Dict[int, object] = {}

    def compile(self, e, names: Dict[str, int],
                default: int = 0) -> Callable[[list], int]:
        if e is None:
            return lambda l, _d=default: _d
        key = (id(e), id(names))
        fn = self._cache.get(key)
        if fn is None:
            closures: List = []
            src = self._gen(e if isinstance(e, E.Expr) else E.Const(int(e)),
                            names, closures)
            env = {"_tdiv": _tdiv, "_tmod": _tmod, "_sc": _shclamp,
                   "_g": self.gdict, "min": min, "max": max, "int": int}
            for i, c in enumerate(closures):
                env[f"_f{i}"] = c
            fn = eval(f"lambda l: ({src})", env)
            fn._expr = e  # keep-alive: id(e) keys the cache
            self._cache[key] = fn
        return fn

    def _gen(self, e, names, closures) -> str:
        _Name, _PyEscape = _jdf_nodes()
        if isinstance(e, E.Const):
            return repr(int(e.v))
        if isinstance(e, (E.L, _Name)):
            nm = e.name
            if nm in names:
                return f"l[{names[nm]}]"
            if isinstance(e, _Name) and nm in self.gdict:
                return repr(int(self.gdict[nm]))
            if nm in self.gdict:  # L() cannot name a global natively,
                raise KeyError(f"unknown local {nm!r}")  # mirror that
            raise KeyError(f"unknown symbol {nm!r}")
        if isinstance(e, E.G):
            if e.name not in self.gdict:
                raise KeyError(f"unknown global {e.name!r}")
            return repr(int(self.gdict[e.name]))
        if isinstance(e, _PyEscape):
            code = self._esc_code.get(id(e))
            if code is None:
                code = compile(e.code, "<jdf-escape>", "eval")
                self._esc_code[id(e)] = code
            pairs = tuple(names.items())
            scope = self.scope if self.scope is not None else {}
            gd = self.gdict

            def esc(l, _c=code, _p=pairs, _s=scope, _g=gd):
                env = dict(_g)
                for n, s in _p:
                    env[n] = l[s]
                return int(eval(_c, _s, env))

            closures.append(esc)
            return f"_f{len(closures) - 1}(l)"
        if isinstance(e, E.Call):
            fn = e.fn
            gd = self.gdict
            closures.append(lambda l, _fn=fn, _g=gd: int(_fn(l, _g)))
            return f"_f{len(closures) - 1}(l)"
        if isinstance(e, E.UnOp):
            a = self._gen(e.a, names, closures)
            if e.op == N.OP_NEG:
                return f"(-{a})"
            if e.op == N.OP_NOT:
                return f"(0 if {a} else 1)"
            raise ValueError(f"unknown unop {e.op}")
        if isinstance(e, E.Select):
            c = self._gen(e.c, names, closures)
            a = self._gen(e.a, names, closures)
            b = self._gen(e.b, names, closures)
            return f"({a} if {c} else {b})"
        if isinstance(e, E.BinOp):
            a = self._gen(e.a, names, closures)
            b = self._gen(e.b, names, closures)
            op = e.op
            simple = {N.OP_ADD: "+", N.OP_SUB: "-", N.OP_MUL: "*",
                      N.OP_EQ: "==", N.OP_NE: "!=", N.OP_LT: "<",
                      N.OP_LE: "<=", N.OP_GT: ">", N.OP_GE: ">="}
            if op in simple:
                return f"({a}{simple[op]}{b})"
            if op == N.OP_DIV:
                return f"_tdiv({a},{b})"
            if op == N.OP_MOD:
                return f"_tmod({a},{b})"
            if op == N.OP_AND:
                return f"(1 if ({a}!=0 and {b}!=0) else 0)"
            if op == N.OP_OR:
                return f"(1 if ({a}!=0 or {b}!=0) else 0)"
            if op == N.OP_MIN:
                return f"min({a},{b})"
            if op == N.OP_MAX:
                return f"max({a},{b})"
            if op == N.OP_SHL:
                return f"({a}<<_sc({b}))"
            if op == N.OP_SHR:
                return f"({a}>>_sc({b}))"
            raise ValueError(f"unknown binop {op}")
        raise TypeError(f"cannot compile {e!r} as an expression")


def _in_range(v: int, lo: int, hi: int, st: int) -> bool:
    """Stride-range membership (native in_range)."""
    if st > 0:
        return lo <= v <= hi and (v - lo) % st == 0
    return hi <= v <= lo and (lo - v) % (-st) == 0


def _steps(lo: int, hi: int, st: int):
    if st == 0:
        st = 1
    v = lo
    while (v <= hi) if st > 0 else (v >= hi):
        yield v
        v += st


class SpaceTooLarge(Exception):
    """Concrete enumeration refused: past the instance budget."""


# ------------------------------------------------------------ class model
class ClassModel:
    """One task class: precompiled bounds/guards/targets + the native
    domain-membership and input-selection rules."""

    def __init__(self, fg: "FlowGraph", tc):
        self.fg = fg
        self.tc = tc
        self.name = tc.name
        self.id = tc.id
        self.is_coll = tc.name.startswith("ptc_coll_")
        self.locals: List[Tuple[str, str, object]] = []
        for (nm, is_range, payload) in tc.locals:
            if isinstance(payload, E.Compr):
                kind = "compr"
            elif is_range:
                kind = "range"
            else:
                kind = "derived"
            self.locals.append((nm, kind, payload))
        self.nb_locals = len(self.locals)
        self.slot_of = {nm: i for i, (nm, _, _) in enumerate(self.locals)}
        self.names = dict(self.slot_of)
        self.range_slots = [i for i, (_, k, _) in enumerate(self.locals)
                            if k != "derived"]
        self.param_names = [self.locals[s][0] for s in self.range_slots]
        self.flows = list(tc.flows)
        cc = fg.cc
        # locals machinery
        self._local_fns = []
        for (nm, kind, payload) in self.locals:
            if kind == "derived":
                self._local_fns.append(("derived",
                                        cc.compile(payload, self.names)))
            elif kind == "range":
                self._local_fns.append(
                    ("range", (cc.compile(payload.lo, self.names),
                               cc.compile(payload.hi, self.names),
                               cc.compile(payload.step, self.names, 1))))
            else:  # compr: value reads its own slot as the iterator
                vnames = self.names
                if payload.iter_name:
                    vnames = dict(self.names)
                    vnames[payload.iter_name] = self.slot_of[nm]
                self._local_fns.append(
                    ("compr", (cc.compile(payload.lo, self.names),
                               cc.compile(payload.hi, self.names),
                               cc.compile(payload.step, self.names, 1),
                               cc.compile(payload.value, vnames))))
        # per-dep machinery: guard fn, guard_dyn, iters, params
        self._dep_info: Dict[Tuple[int, int], dict] = {}
        for fi, fl in enumerate(self.flows):
            for di, d in enumerate(fl.deps):
                self._dep_info[(fi, di)] = self._prep_dep(d)
        self._domain_cache = None  # None = undecided; False = dynamic
        # placement affinity (": desc(m, n)"): the instance executes on
        # rank_of(*idx) of the affinity collection — the rank mapping
        # that V009 and the ptc-plan residency/comm analyses evaluate
        aff = getattr(tc, "_affinity", None)
        self._aff_coll = aff.collection if aff is not None else None
        self._aff_fns = ([cc.compile(e, self.names) for e in aff.idx]
                         if aff is not None else [])

    def rank_of_instance(self, l: list) -> Optional[int]:
        """Rank this instance executes on (affinity collection's
        rank_of over the evaluated placement indices), or None when the
        mapping is unknowable statically (no affinity declared, no
        Python collection object registered, or rank_of raising on an
        out-of-range probe)."""
        if self._aff_coll is None:
            return None
        coll = self.fg.collection_objs.get(self._aff_coll)
        if coll is None:
            return None
        try:
            return int(coll.rank_of(*[fn(l) for fn in self._aff_fns]))
        except Exception:
            return None

    def mem_owner_rank(self, fi: int, di: int, l: list) -> Optional[int]:
        """Owner rank of the collection datum a Mem dep addresses, or
        None when unknowable (same caveats as rank_of_instance)."""
        info = self._dep_info[(fi, di)]
        if info.get("kind") != "mem":
            return None
        coll = self.fg.collection_objs.get(info["coll"])
        if coll is None:
            return None
        try:
            return int(coll.rank_of(*[fn(l) for fn in info["idx"]]))
        except Exception:
            return None

    # ------------------------------------------------------------ prep
    def _prep_dep(self, d) -> dict:
        cc = self.fg.cc
        names = dict(self.names)
        iters = []
        for k, (inm, lo, hi, st) in enumerate(d.iters):
            # iterator k's own bounds see only earlier iterators
            bnames = dict(names)
            iters.append((cc.compile(lo, bnames), cc.compile(hi, bnames),
                          cc.compile(st, bnames, 1)))
            names[inm] = self.nb_locals + k
        info = {
            "guard": cc.compile(d.guard, names, 1),
            "guard_dyn": expr_is_dynamic(d.guard),
            "guard_imp": expr_is_impure(d.guard),
            "iters": iters,
            "names": names,
            "kind": ("task" if isinstance(d.target, Ref)
                     else "mem" if isinstance(d.target, Mem) else "none"),
        }
        if info["kind"] == "task":
            params = []
            for p in d.target.params:
                if isinstance(p, E.Range):
                    params.append(("range", (cc.compile(p.lo, names),
                                             cc.compile(p.hi, names),
                                             cc.compile(p.step, names, 1))))
                else:
                    params.append(("scalar", cc.compile(p, names)))
            info["params"] = params
            info["peer"] = d.target.task
            info["peer_flow"] = d.target.flow
        elif info["kind"] == "mem":
            info["coll"] = d.target.collection
            info["idx"] = [cc.compile(x, names) for x in d.target.idx]
        return info

    # ------------------------------------------------- space enumeration
    def instances(self, budget: List[int]) -> List[tuple]:
        """Enumerate the execution space (list of range-param tuples).
        `budget` is a single-element mutable countdown shared across
        classes; exhausting it raises SpaceTooLarge."""
        out: List[tuple] = []
        nb = self.nb_locals
        vals = [0] * nb

        def rec(i: int):
            if i == nb:
                budget[0] -= 1
                if budget[0] < 0:
                    raise SpaceTooLarge(self.name)
                out.append(tuple(vals[s] for s in self.range_slots))
                return
            kind, fns = self._local_fns[i]
            if kind == "derived":
                vals[i] = fns(vals)
                rec(i + 1)
            elif kind == "range":
                lo, hi, st = fns[0](vals), fns[1](vals), fns[2](vals)
                for v in _steps(lo, hi, st):
                    vals[i] = v
                    rec(i + 1)
                vals[i] = 0
            else:  # compr: dedupe repeated values at this level
                lo, hi, st = fns[0](vals), fns[1](vals), fns[2](vals)
                seen = {}
                for it in _steps(lo, hi, st):
                    vals[i] = it
                    seen.setdefault(fns[3](vals), None)
                for v in seen:
                    vals[i] = v
                    rec(i + 1)
                vals[i] = 0

        rec(0)
        return out

    def space_intervals(self) -> Dict[int, Tuple[int, int]]:
        """Per-slot interval bounds of the execution space (affine
        reasoning; slots whose bounds leave the affine fragment are
        omitted)."""
        ivals: Dict[int, Tuple[int, int]] = {}
        gd = self.fg.gdict
        for i, (nm, kind, payload) in enumerate(self.locals):
            if kind == "derived":
                iv = interval_of(payload, ivals, self.names, gd)
            elif kind == "range":
                lo = interval_of(payload.lo, ivals, self.names, gd)
                hi = interval_of(payload.hi, ivals, self.names, gd)
                iv = (lo[0], hi[1]) if lo and hi else None
            else:
                iv = interval_of(payload.value, ivals, self.names, gd)
            if iv is not None:
                ivals[i] = iv
        return ivals

    # ------------------------------------------------------ domain check
    def fill_locals(self, params: tuple) -> list:
        l = [0] * self.nb_locals
        for i, s in enumerate(self.range_slots):
            l[s] = params[i]
        for i, (kind, fns) in enumerate(self._local_fns):
            if kind == "derived":
                l[i] = fns(l)
        return l

    def _decide_domain_cache(self):
        """Mirror the native pool-const fast path: when every range
        bound reads nothing but globals/consts (and a comprehension
        value nothing but its own slot), membership is per-axis
        constant ranges / value sets."""
        _Name, _PyEscape = _jdf_nodes()

        def const_expr(e, allowed_slot=None):
            if e is None or isinstance(e, int):
                return True
            for x in expr_nodes(e):
                if isinstance(x, (E.Call, _PyEscape)):
                    return False
                if isinstance(x, (E.L, _Name)):
                    nm = x.name
                    if nm in self.names and self.names[nm] != allowed_slot:
                        return False
                    if nm not in self.names and nm not in self.fg.gdict:
                        return False
            return True

        axes = []
        zeros = [0] * self.nb_locals
        for s in self.range_slots:
            nm, kind, payload = self.locals[s]
            _, fns = self._local_fns[s]
            if not (const_expr(payload.lo) and const_expr(payload.hi)
                    and const_expr(payload.step)):
                self._domain_cache = False
                return
            if kind == "compr":
                if not const_expr(payload.value, allowed_slot=s):
                    self._domain_cache = False
                    return
                lo, hi = fns[0](zeros), fns[1](zeros)
                st = fns[2](zeros) or 1
                n = (hi - lo) // st + 1 if st > 0 else (lo - hi) // (-st) + 1
                if n > 65536:
                    self._domain_cache = False
                    return
                vals = set()
                for it in _steps(lo, hi, st):
                    zeros[s] = it
                    vals.add(fns[3](zeros))
                zeros[s] = 0
                axes.append(("set", vals))
            else:
                st = fns[2](zeros) or 1
                axes.append(("range", (fns[0](zeros), fns[1](zeros), st)))
        self._domain_cache = axes

    def in_domain(self, params) -> bool:
        """task_params_in_domain mirror."""
        if len(params) != len(self.range_slots):
            return False
        if self._domain_cache is None:
            self._decide_domain_cache()
        if self._domain_cache:
            for (kind, ax), v in zip(self._domain_cache, params):
                if kind == "set":
                    if v not in ax:
                        return False
                elif not _in_range(v, *ax):
                    return False
            return True
        # dynamic bounds: evaluate in declaration order with the
        # candidate params bound
        l = self.fill_locals(tuple(params))
        for i, s in enumerate(self.range_slots):
            nm, kind, payload = self.locals[s]
            _, fns = self._local_fns[s]
            lo, hi = fns[0](l), fns[1](l)
            st = fns[2](l) or 1
            if kind == "compr":
                found = False
                for it in _steps(lo, hi, st):
                    l[s] = it
                    if fns[3](l) == params[i]:
                        found = True
                        break
                l[s] = params[i]  # restore for later range bounds
                if not found:
                    return False
                continue
            if not _in_range(params[i], lo, hi, st):
                return False
        return True

    # --------------------------------------------------- input selection
    def producer_in_domain(self, fi: int, di: int, l: list) -> bool:
        """dep_producer_in_domain mirror (range params -> True, the
        caller expands and checks per instance)."""
        info = self._dep_info[(fi, di)]
        peer = self.fg.by_name.get(info["peer"])
        if peer is None:
            return False
        vals = []
        for kind, fn in info["params"]:
            if kind == "range":
                return True
            vals.append(fn(l))
        return peer.in_domain(tuple(vals))

    def select_input_dep(self, fi: int, l: list,
                         conservative: bool = False) -> Optional[int]:
        """select_input_dep mirror: dep index into flows[fi].deps or
        None."""
        fl = self.flows[fi]
        for di, d in enumerate(fl.deps):
            if d.direction != 0:
                continue
            info = self._dep_info[(fi, di)]
            if conservative and info["guard_dyn"]:
                if info["kind"] != "task":
                    continue  # dynamic memory source: cannot deliver
                if not self.producer_in_domain(fi, di, l):
                    continue
                return di
            if not info["guard"](l):
                continue
            if info["kind"] == "task" \
                    and not self.producer_in_domain(fi, di, l):
                continue
            return di
        return None

    def _iters_walk(self, info: dict, l: list, fn: Callable[[list], None]):
        """walk_dep_iters mirror: bind scratch slots nb_locals+k."""
        iters = info["iters"]
        if not iters:
            fn(l)
            return
        ext = l + [0] * len(iters)

        def rec(k: int):
            if k == len(iters):
                fn(ext)
                return
            lo, hi, st = (iters[k][0](ext), iters[k][1](ext),
                          iters[k][2](ext) or 1)
            for v in _steps(lo, hi, st):
                ext[self.nb_locals + k] = v
                rec(k + 1)

        rec(0)

    def count_ctl_inputs(self, fi: int, l: list) -> int:
        """count_task_inputs mirror for one CTL flow."""
        fl = self.flows[fi]
        count = 0
        for di, d in enumerate(fl.deps):
            if d.direction != 0:
                continue
            info = self._dep_info[(fi, di)]
            if info["kind"] != "task":
                continue
            peer = self.fg.by_name.get(info["peer"])
            if peer is None:
                continue

            def per_combo(lx):
                nonlocal count
                if not info["guard"](lx):
                    return
                for vals in self._expand_params(info, lx):
                    if peer.in_domain(vals):
                        count += 1

            self._iters_walk(info, l, per_combo)
        return count

    def _expand_params(self, info: dict, l: list):
        """Expand a dep's params (odometer over Range params) ->
        concrete target tuples."""
        params = info["params"]
        vals = [0] * len(params)
        ranges = []
        for i, (kind, fn) in enumerate(params):
            if kind == "scalar":
                vals[i] = fn(l)
            else:
                ranges.append(i)
        if not ranges:
            yield tuple(vals)
            return

        def rec(j: int):
            if j == len(ranges):
                yield tuple(vals)
                return
            i = ranges[j]
            fns = params[i][1]
            for v in _steps(fns[0](l), fns[1](l), fns[2](l) or 1):
                vals[i] = v
                yield from rec(j + 1)

        yield from rec(0)

    def out_emissions(self, fi: int, di: int, l: list):
        """release_deps emission mirror for one OUT dep of one instance:
        yields ("task", vals, certain) / ("oob", vals, certain) /
        ("mem", (coll, idx), certain).  `certain` is False when the
        guard is dynamic (evaluated for real only at completion time)."""
        d = self.flows[fi].deps[di]
        info = self._dep_info[(fi, di)]
        out: List[tuple] = []

        def per_combo(lx):
            if info["guard_imp"]:
                # impure guard: its analysis-time value is not binding
                # (evaluated for real only at completion time) — every
                # combination is a maybe-edge
                certain = False
            else:
                # pure (possibly table-driven) guard: the value is
                # frozen for the pool's life, so evaluation is exact
                if not info["guard"](lx):
                    return
                certain = True
            if info["kind"] == "task":
                peer = self.fg.by_name.get(info["peer"])
                for vals in self._expand_params(info, lx):
                    if peer is not None and peer.in_domain(vals):
                        out.append(("task", vals, certain))
                    else:
                        out.append(("oob", vals, certain))
            elif info["kind"] == "mem":
                idx = tuple(fn(lx) for fn in info["idx"])
                out.append(("mem", (info["coll"], idx), certain))

        self._iters_walk(info, l, per_combo)
        return out

    def dep(self, fi: int, di: int):
        return self.flows[fi].deps[di]

    def dep_loc(self, fi: int, di: int) -> Optional[str]:
        d = self.flows[fi].deps[di]
        return getattr(d, "srcloc", None) \
            or getattr(self.flows[fi], "srcloc", None) \
            or getattr(self.tc, "srcloc", None)

    def is_ctl(self, fi: int) -> bool:
        return self.flows[fi].access == N.FLOW_CTL

    def peer_flow_index(self, fi: int, di: int):
        """Resolve the peer flow index of a task dep (taskclass.compile
        rule: explicit flow name, else position-matched)."""
        info = self._dep_info[(fi, di)]
        peer = self.fg.by_name.get(info["peer"])
        if peer is None:
            return None
        if info["peer_flow"] is not None:
            for i, f in enumerate(peer.flows):
                if f.name == info["peer_flow"]:
                    return i
            return None
        if peer.flows:
            # positional fallback mirrors TaskClass.compile
            return min(len(peer.flows) - 1, fi)
        return None


# -------------------------------------------------------------- flow graph
class FlowGraph:
    """Symbolic flow graph of one (uncommitted or committed) Taskpool."""

    def __init__(self, tp):
        self.tp = tp
        self.globals_map = dict(tp.globals_map)
        self.gdict = {nm: int(N.lib.ptc_tp_global(tp._ptr, idx))
                      for nm, idx in tp.globals_map.items()}
        self.scope = getattr(tp, "jdf_scope", None)
        self.cc = ExprCompiler(self.gdict, self.scope)
        ctx = tp.ctx
        self.arena_sizes = dict(getattr(ctx, "arena_sizes", {}))
        self.datatype_bytes = dict(getattr(ctx, "datatype_bytes", {}))
        self.collections = dict(getattr(ctx, "collections", {}))
        # name -> the Python collection object (rank_of + geometry);
        # native-only (linear) collections register a shim with the same
        # duck type, so rank mapping and tile-byte sizing stay uniform
        self.collection_objs = dict(getattr(ctx, "collection_objs", {}))
        self.classes: List[ClassModel] = [ClassModel(self, tc)
                                          for tc in tp.classes]
        self.by_name = {cm.name: cm for cm in self.classes}

    def concretize(self, max_instances: int = 200_000) -> "ConcreteGraph":
        return ConcreteGraph(self, max_instances)


class ConcreteGraph:
    """Exact instance-level dataflow: expected input counts (the native
    counting rule) vs actual deliveries (the native release walk)."""

    def __init__(self, fg: FlowGraph, max_instances: int):
        self.fg = fg
        self.bounded = False
        self.notes: List[str] = []
        self.instances: Dict[int, List[tuple]] = {}
        budget = [max_instances]
        for cm in fg.classes:
            try:
                self.instances[cm.id] = cm.instances(budget)
            except SpaceTooLarge:
                self.bounded = True
                self.notes.append(
                    f"execution space past {max_instances} instances at "
                    f"class {cm.name}; concrete rules skipped")
                self.instances = {}
                break
        # node = (class_id, params)
        self.expected: Dict[tuple, int] = {}     # (node, fi) -> count
        self.selected: Dict[tuple, int] = {}     # (node, fi) -> dep idx
        self.ncert: Dict[tuple, int] = {}        # (node, fi) -> deliveries
        self.nmaybe: Dict[tuple, int] = {}
        self.src_sample: Dict[tuple, List] = {}  # (node, fi) -> [(src,
        #                                          (cid, fi, di), certain)]
        self.succ: Dict[tuple, List] = {}        # node -> [(node, certain)]
        self.mem_writes: Dict[tuple, List] = {}  # (coll, idx) -> [(node,
        #                                          (cid, fi, di), certain)]
        self.emit_stats: Dict[tuple, List[int]] = {}  # (cid, fi, di) ->
        #                                          [attempts, landed, oob]
        self.nb_edges = 0
        if not self.bounded:
            self._build()

    def _build(self):
        fg = self.fg
        for cm in fg.classes:
            for params in self.instances[cm.id]:
                node = (cm.id, params)
                l = cm.fill_locals(params)
                # consumer side: expected deliveries per flow
                for fi in range(len(cm.flows)):
                    if cm.is_ctl(fi):
                        n = cm.count_ctl_inputs(fi, l)
                        if n:
                            self.expected[(node, fi)] = n
                    else:
                        di = cm.select_input_dep(fi, l, conservative=True)
                        if di is not None:
                            self.selected[(node, fi)] = di
                            info = cm._dep_info[(fi, di)]
                            if info["kind"] == "task":
                                self.expected[(node, fi)] = 1
                # producer side: the release walk
                for fi, fl in enumerate(cm.flows):
                    for di, d in enumerate(fl.deps):
                        if d.direction != 1:
                            continue
                        info = cm._dep_info[(fi, di)]
                        stats = self.emit_stats.setdefault(
                            (cm.id, fi, di), [0, 0, 0])
                        if info["kind"] == "none":
                            continue
                        for kind, payload, certain in \
                                cm.out_emissions(fi, di, l):
                            stats[0] += 1
                            if kind == "mem":
                                self.mem_writes.setdefault(
                                    payload, []).append(
                                        (node, (cm.id, fi, di), certain))
                                stats[1] += 1
                                continue
                            if kind == "oob":
                                stats[2] += 1
                                continue
                            stats[1] += 1
                            peer = fg.by_name[info["peer"]]
                            pfi = cm.peer_flow_index(fi, di)
                            if pfi is None:
                                continue
                            dst = (peer.id, payload)
                            key = (dst, pfi)
                            if certain:
                                self.ncert[key] = \
                                    self.ncert.get(key, 0) + 1
                            else:
                                self.nmaybe[key] = \
                                    self.nmaybe.get(key, 0) + 1
                            s = self.src_sample.setdefault(key, [])
                            if len(s) < 8:
                                s.append((node, (cm.id, fi, di), certain))
                            self.succ.setdefault(node, []).append(
                                (dst, certain))
                            self.nb_edges += 1

    # ------------------------------------------------------------- helpers
    def node_name(self, node) -> str:
        cm = self.fg.classes[node[0]]
        return f"{cm.name}({', '.join(str(v) for v in node[1])})"

    def nb_instances(self) -> int:
        return sum(len(v) for v in self.instances.values())


def collection_tile_bytes(coll) -> Optional[int]:
    """Per-datum payload bytes of a collection, from its declared
    geometry (the full mb x nb allocation the device stages and the
    arena-backed wire path assumes; boundary tiles are padded to it).
    None when the collection exposes no recognizable geometry."""
    if coll is None:
        return None
    try:
        if hasattr(coll, "mb") and hasattr(coll, "nb") \
                and hasattr(coll, "dtype"):
            return int(coll.mb) * int(coll.nb) * \
                int(np.dtype(coll.dtype).itemsize)
        if hasattr(coll, "nb") and hasattr(coll, "dtype"):
            return int(coll.nb) * int(np.dtype(coll.dtype).itemsize)
        if hasattr(coll, "elem_size"):
            return int(coll.elem_size)
    except Exception:
        return None
    return None


class LinearCollectionShim:
    """Stand-in for natively-registered linear collections
    (Context.register_linear_collection): rank_of(k) = k % nodes and a
    fixed elem_size — enough for rank mapping + byte sizing."""

    def __init__(self, nodes: int, elem_size: int):
        self.nodes = nodes
        self.elem_size = elem_size

    def rank_of(self, k: int) -> int:
        return int(k) % max(1, self.nodes)


def extract_flowgraph(tp) -> FlowGraph:
    """Extract the symbolic flow graph of a Taskpool (committed or not).
    Works on the Python task-class tables; nothing is executed."""
    return FlowGraph(tp)


def flowgraph_to_dot(cg: ConcreteGraph, findings=None,
                     name: str = "ptg") -> str:
    """Instance-level DOT of a concretized flow graph.  `findings`
    (from analysis.verify) overlay in red: edges emitted by an
    implicated dep, and implicated instances' nodes."""
    bad_deps = set()
    bad_nodes = set()
    for f in (findings or []):
        cm = cg.fg.by_name.get(f.cls)
        if cm is None:
            continue
        if f.flow is not None and f.dep is not None:
            fi = next((i for i, fl in enumerate(cm.flows)
                       if fl.name == f.flow), None)
            if fi is not None:
                bad_deps.add((cm.id, fi, f.dep))
        for params in f.instances:
            bad_nodes.add((cm.id, tuple(params)))
    lines = [f'digraph "{name}" {{', "  rankdir=LR;",
             "  node [shape=box, fontsize=10];"]
    ids = {}
    for cid, plist in cg.instances.items():
        for params in plist:
            node = (cid, params)
            ids[node] = f"n{len(ids)}"
            style = ", color=red, penwidth=2" if node in bad_nodes else ""
            lines.append(
                f'  {ids[node]} [label="{cg.node_name(node)}"{style}];')
    for src, outs in cg.succ.items():
        for dst, certain in outs:
            if src not in ids or dst not in ids:
                continue
            attrs = []
            if not certain:
                attrs.append("style=dashed")
            lines.append(f"  {ids[src]} -> {ids[dst]}"
                         + (f" [{', '.join(attrs)}]" if attrs else "")
                         + ";")
    # red overlay: re-emit implicated dep edges in red
    for (cid, fi, di) in bad_deps:
        cm = cg.fg.classes[cid]
        for params in cg.instances.get(cid, []):
            node = (cid, params)
            l = cm.fill_locals(params)
            info = cm._dep_info[(fi, di)]
            if cm.flows[fi].deps[di].direction != 1 \
                    or info["kind"] != "task":
                continue
            pfi = cm.peer_flow_index(fi, di)
            peer = cg.fg.by_name.get(info["peer"])
            for kind, payload, certain in cm.out_emissions(fi, di, l):
                if kind != "task" or peer is None or pfi is None:
                    continue
                dst = (peer.id, payload)
                if node in ids and dst in ids:
                    lines.append(f"  {ids[node]} -> {ids[dst]} "
                                 "[color=red, penwidth=2];")
    lines.append("}")
    return "\n".join(lines)
