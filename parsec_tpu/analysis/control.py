"""ptc-pilot — the online feedback controller that closes the
conformance loop (ROADMAP item 5).

PR 12's ScheduleSimulator picks knob vectors per (graph, host) OFFLINE;
PR 11's conformance records measure — live, per pool — exactly how
wrong the cost model is.  This module is the consumer that was missing:
a deterministic `Controller` that runs at pool/step boundaries (no new
threads anywhere near the hot path) and

  (a) detects MODEL DRIFT — the median measured/lower-bound makespan
      ratio over the last `control.window` planned pools exceeding
      `control.drift_ratio` — then folds the live per-class calibration
      ratios into the CostModel (CostModel.recalibrated), re-runs
      ScheduleSimulator.propose() on the recalibrated model, and
      hot-swaps the winning knob vector at the NEXT pool boundary
      through tune.py's snapshot/restore apply path (hold_knobs).
      Winners persist through the PR 12 TuneStore so recovery survives
      a restart;
  (b) drives the per-tenant cached-page budgets (PagePool cached-free
      LRU shares re-weighted by prefix hit rate) and feeds tenant SLO
      burn back into admission pricing (Server.set_admission_pressure)
      — a burning tenant sheds load BEFORE /healthz flips — via
      `poll()`, which the serving engine calls once per decode step;
  (c) takes watchdog `stuck_task` / `slow_rank` detections as its
      interrupt path: the observation window closes immediately and an
      evaluation runs without waiting for a full window.

Every decision is a structured scope event (`control_*` kinds in the
ScopeRegistry ring) AND an entry in the controller's own bounded
decision log.  The whole loop is deterministic: observations arrive
only through `observe_pool` / `interrupt` / `poll`, and with a
`SimClock` even the timestamps are reproducible — replaying the same
observation sequence yields an identical decision log (the replay
tests pin this).

Wiring (the serve stack does all of this automatically):

    ctrl = ctx.controller()            # lazy, one per context
    ctrl.attach_target(tp)             # the retune target graph
    ctrl.bind_engine(eng)              # budgets + spec_k visibility
    ... pools run; scope.record_pool_done feeds observe_pool ...
    ctx.stats()["control"]             # the unified namespace
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional


class SimClock:
    """Deterministic clock for replay: every call advances a virtual
    nanosecond counter by a fixed step.  Two controllers fed the same
    observation sequence under equal SimClocks produce byte-identical
    decision logs, timestamps included."""

    def __init__(self, start_ns: int = 0, step_ns: int = 1_000_000):
        self._t = int(start_ns)
        self.step_ns = int(step_ns)

    def __call__(self) -> int:
        t = self._t
        self._t += self.step_ns
        return t


class Controller:
    """Deterministic pool-boundary feedback controller (module
    docstring).  Thread-safe: observations arrive from whatever thread
    retires a pool (engine driver, server pump, watchdog), stats
    scrapes from anywhere."""

    def __init__(self, ctx, clock: Optional[Callable[[], int]] = None,
                 drift_ratio: Optional[float] = None,
                 window: Optional[int] = None,
                 cooldown: Optional[int] = None,
                 store=None, max_decisions: int = 256):
        from ..utils import params as _mca
        self.ctx = ctx
        self.scope = ctx.scope_registry()
        self.drift_ratio = float(_mca.get("control.drift_ratio")
                                 if drift_ratio is None else drift_ratio)
        self.window = max(1, int(_mca.get("control.window")
                                 if window is None else window))
        self.cooldown = max(0, int(_mca.get("control.cooldown")
                                   if cooldown is None else cooldown))
        self._clock = clock or time.monotonic_ns
        self._store = store  # TuneStore (or stub); None = default
        self._lock = threading.Lock()
        self._ratios: deque = deque(maxlen=self.window)
        self._pools = 0          # boundaries observed
        self._cool_until = 0     # drift ignored until this boundary
        self._pending: Optional[dict] = None   # evaluated, not yet live
        self._applied: Optional[dict] = None   # live swap record
        self._restore: Optional[Callable] = None
        self._plan = None
        self._signature: Optional[str] = None
        self._workers: Optional[int] = None
        self._econ = None
        self._base_cost = None   # CostModel the target plan assumed
        self._engine = None
        self._retunes = 0
        self._swaps = 0
        self._interrupts = 0
        self._persisted = 0
        self._budget_shares: Dict[str, float] = {}
        self._pressure: Dict[str, float] = {}
        self.decisions: List[dict] = []
        self._max_decisions = int(max_decisions)
        self._stopped = False
        ctx._controller = self

    # ------------------------------------------------------------ wiring
    def attach_target(self, tp=None, plan=None, cost=None,
                      workers: Optional[int] = None, econ=None,
                      signature: Optional[str] = None):
        """Declare the retune target: a representative taskpool (or its
        concrete Plan).  Drift evaluation re-simulates THIS graph under
        the recalibrated cost model; without a target, drift is still
        detected and logged but no knob swap can be proposed."""
        from .plan import CostModel, plan_graph
        from .flowgraph import extract_flowgraph
        from .tune import graph_signature
        if plan is None:
            if tp is None:
                raise ValueError("attach_target needs a taskpool or plan")
            fg = extract_flowgraph(tp)
            plan = plan_graph(fg, cost=cost, econ=econ, workers=workers)
        if plan.bounded or plan.cg is None:
            raise ValueError("control target must plan concretely "
                             "(symbolic bounds cannot be simulated)")
        if signature is None and tp is not None:
            signature = graph_signature(tp)
        per_cls = (plan.makespan or {}).get("per_class_cost") or {}
        with self._lock:
            self._plan = plan
            self._signature = signature
            self._workers = workers
            self._econ = econ
            self._base_cost = cost or CostModel(
                dict(per_cls), source=(plan.makespan or {}).get(
                    "cost_source", "plan"))
        return plan

    def bind_engine(self, engine):
        """Give the controller its resource levers: the engine's
        PagePool (cached-share budgets), Server (admission pressure)
        and the adaptive-speculation snapshot for stats()."""
        with self._lock:
            self._engine = engine

    # ------------------------------------------------------ decision log
    def _record_locked(self, kind: str, **fields) -> dict:
        entry = {"n": len(self.decisions) + 1, "pool": self._pools,
                 "t_ns": int(self._clock()), "kind": kind}
        entry.update(fields)
        self.decisions.append(entry)
        if len(self.decisions) > self._max_decisions:
            del self.decisions[0]
        try:
            self.scope.record_event(
                kind, **{k: v for k, v in entry.items() if k != "kind"})
        except Exception:
            pass
        return entry

    def decision_log(self) -> List[dict]:
        with self._lock:
            return [dict(d) for d in self.decisions]

    # ------------------------------------------------------ observations
    def observe_pool(self, ratio: Optional[float] = None):
        """ONE retired pool (the boundary clock): apply any pending
        knob swap — the hot-swap contract is 'next pool boundary', and
        this IS it — then fold the pool's measured/lower-bound makespan
        ratio (None for an unplanned pool) and check for sustained
        drift."""
        with self._lock:
            if self._stopped:
                return
            self._pools += 1
            if self._pending is not None:
                self._apply_locked()
            if ratio is not None and ratio > 0:
                self._ratios.append(float(ratio))
            if len(self._ratios) < self.window or \
                    self._pools < self._cool_until:
                return
            med = sorted(self._ratios)[len(self._ratios) // 2]
            if med > self.drift_ratio:
                self._evaluate_locked("drift", med)

    def interrupt(self, kind: str, **fields):
        """Watchdog interrupt path (`stuck_task` / `slow_rank`): close
        the observation window NOW and evaluate without waiting for it
        to fill — a wedged task or a straggler rank is exactly the
        regime where the tuned knobs stopped describing reality.  The
        swap itself still waits for the next pool boundary."""
        with self._lock:
            if self._stopped:
                return
            self._interrupts += 1
            self._record_locked("control_interrupt", trigger=str(kind),
                                **fields)
            if self._pools < self._cool_until:
                return
            med = None
            if self._ratios:
                s = sorted(self._ratios)
                med = s[len(s) // 2]
            self._evaluate_locked(f"interrupt:{kind}", med)

    # ------------------------------------------------------- evaluation
    def _evaluate_locked(self, trigger: str, med: Optional[float]):
        """Window close: recalibrate, re-simulate, decide.  Runs under
        the controller lock; the scope registry is only ever taken
        AFTER it (record_pool_done delivers observations outside the
        registry lock), so the order is acyclic."""
        self._ratios.clear()
        self._cool_until = self._pools + self.cooldown
        if self._plan is None:
            self._record_locked(
                "control_drift", trigger=trigger,
                makespan_ratio=round(med, 4) if med else None,
                target=False)
            return
        from .tune import ScheduleSimulator, default_knobs
        ratios: Dict[str, float] = {}
        try:
            for cls, row in (self.scope.conformance()["per_class"]
                             or {}).items():
                if row.get("ratio"):
                    ratios[cls] = float(row["ratio"])
        except Exception:
            pass
        fallback = med if (med and med > 0) else 1.0
        cm = self._base_cost.recalibrated(ratios, fallback=fallback)
        sim = ScheduleSimulator(self._plan, cost=cm, econ=self._econ,
                                workers=self._workers)
        current = default_knobs()
        before_ns = sim.simulate(current)["makespan_ns"]
        ranked = sim.propose(topk=3, rounds=2)
        winner = ranked[0]
        changed = {k: v for k, v in winner["knobs"].items()
                   if v != current.get(k)}
        if not changed:
            self._record_locked(
                "control_drift", trigger=trigger,
                makespan_ratio=round(med, 4) if med else None,
                target=True, before_ns=round(before_ns),
                after_ns=round(winner["predicted_ns"]),
                held=True)
            return
        self._retunes += 1
        self._pending = {"knobs": dict(winner["knobs"]),
                         "changed": changed,
                         "before_ns": round(before_ns),
                         "after_ns": round(winner["predicted_ns"]),
                         "trigger": trigger}
        self._record_locked(
            "control_retune", trigger=trigger,
            makespan_ratio=round(med, 4) if med else None,
            before_ns=round(before_ns),
            after_ns=round(winner["predicted_ns"]),
            knobs=dict(changed))
        self._persist_locked(winner)

    def _persist_locked(self, winner: dict):
        """Tuned-cache persistence (PR 12 TuneStore): the recovered
        vector keyed by (graph signature, host fingerprint), so a
        restarted process starts from the controller's winner instead
        of re-drifting through the same incident."""
        if self._signature is None:
            return
        try:
            from .tune import TuneStore, host_fingerprint
            store = self._store or TuneStore()
            store.put(self._signature, host_fingerprint(), {
                "knobs": dict(winner["knobs"]),
                "predicted_ns": winner["predicted_ns"],
                "measured_s": None, "critpath_ratio": None,
                "source": "control",
            })
            self._persisted += 1
        except Exception:
            pass

    def _apply_locked(self):
        """The pool-boundary hot swap: restore any previous hold, then
        apply the pending vector through tune.hold_knobs (MCA registry
        + PTC_MCA_* env, snapshot kept for teardown)."""
        from .tune import hold_knobs
        pending, self._pending = self._pending, None
        if self._restore is not None:
            self._restore()
            self._restore = None
        try:
            _, self._restore = hold_knobs(pending["knobs"])
        except Exception as e:
            self._record_locked("control_apply", ok=False,
                                error=repr(e))
            return
        self._swaps += 1
        self._applied = pending
        self._record_locked("control_apply", ok=True,
                            knobs=dict(pending["changed"]),
                            before_ns=pending["before_ns"],
                            after_ns=pending["after_ns"])

    # --------------------------------------------------------- budgets
    def poll(self):
        """Step-boundary resource pass (the engine calls this once per
        decode step; anyone else may too — it is idempotent and cheap):
        re-weight the PagePool's cached-free LRU shares by per-tenant
        prefix hit rate, and feed tenant SLO burn into admission
        pricing so a burning tenant sheds load before /healthz flips.
        Changes (beyond a 0.05 dead-band) are logged decisions."""
        with self._lock:
            if self._stopped or self._engine is None:
                return
            engine = self._engine
        from ..utils import params as _mca
        min_share = float(_mca.get("control.budget_min_share"))
        rates: Dict[str, float] = {}
        burns: Dict[str, float] = {}
        try:
            with self.scope._lock:
                for name, t in self.scope.tenants.items():
                    h = t.counters.get("prefix_hits", 0)
                    m = t.counters.get("prefix_misses", 0)
                    if h + m:
                        rates[name] = h / (h + m)
            for name, st in self.scope.slo_status().items():
                burns[name] = float(st.get("burn_rate") or 0.0)
        except Exception:
            return
        shares: Dict[str, float] = {}
        if len(rates) > 1:
            total = sum(max(r, min_share) for r in rates.values())
            shares = {n: max(r, min_share) / total
                      for n, r in rates.items()}
        with self._lock:
            if shares and any(
                    abs(shares.get(n, 0.0)
                        - self._budget_shares.get(n, 0.0)) >= 0.05
                    for n in set(shares) | set(self._budget_shares)):
                self._budget_shares = dict(shares)
                try:
                    engine.pool.set_cached_shares(shares)
                except Exception:
                    pass
                self._record_locked(
                    "control_budget",
                    shares={n: round(s, 3)
                            for n, s in sorted(shares.items())})
            for name, burn in sorted(burns.items()):
                if abs(burn - self._pressure.get(name, 0.0)) < 0.05:
                    continue
                self._pressure[name] = burn
                try:
                    engine.server.set_admission_pressure(name, burn)
                except Exception:
                    pass
                self._record_locked("control_pressure", tenant=name,
                                    burn_rate=round(burn, 4))

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        with self._lock:
            s = sorted(self._ratios)
            spec = {}
            eng = self._engine
            applied = self._applied
            out = {
                "enabled": True,
                "pools": self._pools,
                "window": self.window,
                "window_n": len(s),
                "drift_ratio": self.drift_ratio,
                "drift_now": round(s[len(s) // 2], 4) if s else None,
                "retunes": self._retunes,
                "swaps": self._swaps,
                "interrupts": self._interrupts,
                "persisted": self._persisted,
                "pending": self._pending is not None,
                "target": self._plan is not None,
                "decisions": len(self.decisions),
                "last_swap": ({
                    "trigger": applied["trigger"],
                    "before_ns": applied["before_ns"],
                    "after_ns": applied["after_ns"],
                    "knobs": dict(applied["changed"]),
                } if applied else None),
                "budget_shares": {n: round(v, 4) for n, v in
                                  sorted(self._budget_shares.items())},
                "pressure": {n: round(v, 4) for n, v in
                             sorted(self._pressure.items())},
            }
        if eng is not None:
            try:
                spec = eng.spec_k_snapshot()
            except Exception:
                spec = {}
        out["spec_k"] = spec
        return out

    # --------------------------------------------------------- teardown
    def stop(self):
        """Restore any held knob vector and detach (idempotent; wired
        into Context.destroy)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            self._pending = None
            if self._restore is not None:
                try:
                    self._restore()
                except Exception:
                    pass
                self._restore = None
        if getattr(self.ctx, "_controller", None) is self:
            self.ctx._controller = None
