"""Static analysis over PTG/DTD graphs (the parsec_ptgpp sanity-check
role, SURVEY §L1: the reference compiler rejects dangling flows and
malformed dep targets before any task runs).

`flowgraph` extracts a symbolic flow graph from compiled task-class
tables — one extractor shared by the verifier, the resource planner
and tools/jdf2dot.py — `verify` runs the V001–V009 rule engine over
it, using affine/interval reasoning where index expressions allow and
bounded concrete enumeration of the execution space as the exact
fallback, and `plan` (ptc-plan) computes the quantitative bounds:
per-rank peak tile residency, wave decomposition, comm volume and
makespan lower bounds.  `dtdlint` is the insertion-time linter for the
dynamic (DTD) path.
"""
from .flowgraph import (ConcreteGraph, FlowGraph, collection_tile_bytes,
                        extract_flowgraph, flowgraph_to_dot)
from .verify import (RULES, Finding, Report, VerifyError, verify_graph,
                     verify_taskpool)
from .plan import (CostModel, Plan, PlanCheckError, certify_waves,
                   chain_certificates, compare_critpath, plan_graph,
                   plan_taskpool)
from .tune import (ScheduleSimulator, TuneStore, apply_knobs, autotune,
                   graph_signature, hold_knobs, host_fingerprint)
from .control import Controller, SimClock
from .dtdlint import DtdLintError, DtdLinter

__all__ = [
    "FlowGraph", "ConcreteGraph", "extract_flowgraph", "flowgraph_to_dot",
    "collection_tile_bytes",
    "Finding", "Report", "RULES", "VerifyError", "verify_graph",
    "verify_taskpool",
    "CostModel", "Plan", "PlanCheckError", "plan_graph", "plan_taskpool",
    "compare_critpath", "certify_waves", "chain_certificates",
    "ScheduleSimulator", "TuneStore", "apply_knobs", "hold_knobs",
    "autotune", "graph_signature", "host_fingerprint",
    "Controller", "SimClock",
    "DtdLinter", "DtdLintError",
]
