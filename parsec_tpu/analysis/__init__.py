"""Static analysis over PTG/DTD graphs (the parsec_ptgpp sanity-check
role, SURVEY §L1: the reference compiler rejects dangling flows and
malformed dep targets before any task runs).

`flowgraph` extracts a symbolic flow graph from compiled task-class
tables — one extractor shared by the verifier and tools/jdf2dot.py —
and `verify` runs the V001–V008 rule engine over it, using
affine/interval reasoning where index expressions allow and bounded
concrete enumeration of the execution space as the exact fallback.
`dtdlint` is the insertion-time linter for the dynamic (DTD) path.
"""
from .flowgraph import (ConcreteGraph, FlowGraph, extract_flowgraph,
                        flowgraph_to_dot)
from .verify import (RULES, Finding, Report, VerifyError, verify_graph,
                     verify_taskpool)
from .dtdlint import DtdLintError, DtdLinter

__all__ = [
    "FlowGraph", "ConcreteGraph", "extract_flowgraph", "flowgraph_to_dot",
    "Finding", "Report", "RULES", "VerifyError", "verify_graph",
    "verify_taskpool", "DtdLinter", "DtdLintError",
]
