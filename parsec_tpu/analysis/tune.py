"""ptc-tune: static schedule simulation and plan-driven autotuning of
the runtime knob space.

The runtime exposes a hand-tuned knob surface (chunk size, rails, eager
threshold, collective topology, staging slots, cache budget, magazine
batch) while ptc-plan's engine-exact concretized instance DAG, the
PR 7 histogram-seeded CostModel and the fitted transfer economics
(comm/economics.py, arXiv:2112.09017-style alpha/beta legs) already
contain everything needed to price a knob vector WITHOUT running the
job — ROADMAP item 5's closed loop.  Three layers:

  simulator   `ScheduleSimulator`: a deterministic discrete-event list
              scheduling simulation over the concretized DAG — workers
              x waves x wire.  Task cost from the CostModel plus a
              modeled per-task dispatch overhead (amortized by the
              magazine batch), cross-rank edges priced by the fitted
              alpha/beta legs with eager/rendezvous split, chunk
              pipelining and rail striping, device h2d stalls gated by
              the staging slots, and cache-budget spills priced through
              `Plan.predict_spills`.  No wall clock anywhere: same
              inputs -> same numbers, bit for bit.

  search      `propose()`: deterministic coordinate descent over the
              graph-relevant knob axes (axes that cannot matter — comm
              knobs on a single-rank DAG, device knobs without device
              chores — are pruned), ranked by simulated makespan.
              `autotune()` validates the top-k with REAL runs through a
              caller-supplied `measure(knobs)` callback and records the
              `compare_critpath` predicted-vs-measured ratio per
              validation run — the regression signal that keeps the
              model honest.

  persistence `TuneStore`: winners keyed by (graph signature, host
              provenance fingerprint) in a JSON cache
              (PTC_MCA_tune_cache_path, default ~/.ptc/tuned.json) that
              `Taskpool.run(tuned=True)` auto-applies — with MCA
              snapshot/restore around the run so one pool's knobs can
              never leak into the next pool in the same Context.

The knob vector is applied through `apply_knobs()`: both the Python MCA
registry (programmatic set) and the PTC_MCA_* environment (the native
comm/context layers read env at init), snapshotting and restoring both.
Knobs bound at Context/comm/device creation take effect for runs that
create their runtime under `apply_knobs` (the tuner's validation runs
and the bench harnesses do); `Taskpool.run(tuned=)` covers the
pool-scoped reads (commit, plan_check, the context's lazy start).
"""
from __future__ import annotations

import contextlib
import hashlib
import heapq
import json
import os
import sys
from typing import Callable, Dict, List, Optional, Tuple

from .. import _native as N
from ..core import expr as E
from ..core.taskclass import Mem, Ref
from .flowgraph import FlowGraph, extract_flowgraph
from .plan import CostModel, Plan, compare_critpath, plan_graph

# ------------------------------------------------------ knob registry
# The tunable surface.  Each knob's value is applied through BOTH the
# MCA registry and the PTC_MCA_* env spelling (native init paths read
# env); see apply_knobs().
TUNE_KNOBS: Tuple[str, ...] = (
    "comm.chunk_size",      # rendezvous chunk quantum (wire pipelining)
    "comm.rails",           # striped TCP connections per peer
    "comm.eager_limit",     # eager/rendezvous payload split
    "coll.topo",            # collective topology (ring|binomial|star|auto)
    "coll.max_slices",      # slices per collective segment
    "device.staging_slots", # prefetch double-buffering depth
    "device.cache_bytes",   # device byte budget (0 = constructor default)
    "device.wave_fuse",     # wave mega-kernelization (ptc-fuse)
    "runtime.mag_batch",    # task/arena freelist magazine batch
    # ptc-topo: per-link-class overrides ("" = inherit the base knob).
    # The simulator prices each cross-rank edge at ITS class, so these
    # axes only matter (and are only searched) on multi-island meshes.
    "comm.chunk_size.ici",
    "comm.chunk_size.dcn",
    "comm.rails.ici",
    "comm.rails.dcn",
    "comm.eager_limit.ici",
    "comm.eager_limit.dcn",
    "coll.topo.ici",
    "coll.topo.dcn",
)

# Modeled dispatch-path constants (nanoseconds), calibrated against the
# committed BENCH_dispatch level-0 numbers: the per-task dispatch floor
# at the default magazine batch (64) sits near the measured ~0.25 us
# single-chain p50, and the magazine term prices the amortized
# free-lock crossing a refill/spill costs (one mutex pair per batch).
DISPATCH_BASE_NS = 220.0
DISPATCH_MAG_NS = 1600.0   # per-batch lock crossing, amortized /batch
# Per-chunk envelope floor on the streamed rendezvous path (frame
# header + ranged-GET bookkeeping): the real per-chunk cost is modeled
# as the path's fitted ALPHA leg (every chunk is its own ranged round
# on the serve lane), floored here when a fit clamps to zero.  Rail
# striping gets DIMINISHING returns (1 + (rails-1) * RAIL_EFF as the
# effective per-byte divisor): rails divide wire serialization, not
# the host memcpy/d2h legs the fits also contain.  The h2d per-byte
# cost prices dispatch stalls when staging cannot double-buffer.
# Deliberately coarse: the simulator prices RELATIVE knob changes,
# the validation runs price reality.
CHUNK_ENVELOPE_NS = 4000.0
RAIL_EFF = 0.25
H2D_BYTE_NS = 0.05
SPILL_ALPHA_NS = 20000.0


def _stripe_div(rails: int, nchunks: int) -> float:
    """Effective per-byte divisor of `rails` striped connections."""
    stripe = max(1, min(int(rails), int(nchunks)))
    return 1.0 + (stripe - 1) * RAIL_EFF


def host_fingerprint() -> str:
    """Stable host provenance fingerprint: cpu count, architecture,
    platform, page size and the CPU feature flags — the tuner's
    persistence key (a knob vector tuned on one box must not silently
    apply on a different one).  Shared with bench.host_provenance()."""
    cpus = os.cpu_count() or 1
    try:
        page = os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        page = 4096
    flags = ""
    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if not model and line.startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                if not flags and line.startswith("flags"):
                    flags = " ".join(sorted(
                        line.split(":", 1)[1].split()))
                if model and flags:
                    break
    except OSError:
        pass
    import platform
    blob = "|".join([str(cpus), platform.machine(), sys.platform,
                     str(page), model, flags])
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# --------------------------------------------------- graph signature
def _sig_expr(e) -> str:
    """Canonical, process-stable serialization of an expression tree
    (the signature analog of ExprCompiler._gen: opcode ints + symbol
    names + escape source; pt.call callbacks key by name + purity)."""
    if e is None:
        return "_"
    if isinstance(e, bool):
        return f"c{int(e)}"
    if isinstance(e, int):
        return f"c{e}"
    if isinstance(e, E.Const):
        return f"c{int(e.v)}"
    if isinstance(e, E.L):
        return f"l:{e.name}"
    if isinstance(e, E.G):
        return f"g:{e.name}"
    if isinstance(e, E.BinOp):
        return f"b{e.op}({_sig_expr(e.a)},{_sig_expr(e.b)})"
    if isinstance(e, E.UnOp):
        return f"u{e.op}({_sig_expr(e.a)})"
    if isinstance(e, E.Select):
        return (f"s({_sig_expr(e.c)},{_sig_expr(e.a)},"
                f"{_sig_expr(e.b)})")
    if isinstance(e, E.Call):
        nm = getattr(e.fn, "__name__", "fn")
        return f"call:{nm}:{int(getattr(e, 'pure', False))}"
    if isinstance(e, E.Range):
        return (f"r({_sig_expr(e.lo)},{_sig_expr(e.hi)},"
                f"{_sig_expr(e.step)})")
    if isinstance(e, E.Compr):
        return (f"cp({_sig_expr(e.lo)},{_sig_expr(e.hi)},"
                f"{_sig_expr(e.step)},{_sig_expr(e.value)},"
                f"{getattr(e, 'iter_name', None)})")
    # JDF nodes (duck-typed to avoid the import cycle)
    code = getattr(e, "code", None)
    if code is not None:
        return f"esc:{code}"
    name = getattr(e, "name", None)
    if name is not None:
        return f"n:{name}"
    return f"?{type(e).__name__}"


def _sig_target(t) -> str:
    if t is None:
        return "none"
    if isinstance(t, Ref):
        ps = ",".join(_sig_expr(p) for p in t.params)
        return f"ref:{t.task}({ps})@{t.flow}"
    if isinstance(t, Mem):
        ix = ",".join(_sig_expr(x) for x in t.idx)
        return f"mem:{t.collection}[{ix}]"
    return f"?{type(t).__name__}"


def graph_signature(tp) -> str:
    """Content hash of a taskpool's compiled shape: classes (locals,
    flows, deps, guards, targets, bodies, affinity), global values, and
    the registered collections' geometry.  Two pools built the same way
    over the same problem size share a signature — the tuning-cache
    key's graph half."""
    parts: List[str] = []
    gdict = {nm: int(N.lib.ptc_tp_global(tp._ptr, idx))
             for nm, idx in tp.globals_map.items()}
    parts.append("G:" + ",".join(f"{k}={v}"
                                 for k, v in sorted(gdict.items())))
    colls = getattr(tp.ctx, "collection_objs", {})
    for name in sorted(colls):
        c = colls[name]
        geo = [name]
        for attr in ("mt", "nt", "mb", "nb", "nodes", "elem_size"):
            if hasattr(c, attr):
                geo.append(f"{attr}={getattr(c, attr)}")
        if hasattr(c, "dtype"):
            geo.append(f"dtype={c.dtype}")
        parts.append("C:" + ";".join(str(g) for g in geo))
    for tc in tp.classes:
        cparts = [f"T:{tc.name}"]
        for (nm, is_range, payload) in tc.locals:
            cparts.append(f"p:{nm}:{int(is_range)}:{_sig_expr(payload)}")
        aff = getattr(tc, "_affinity", None)
        if aff is not None:
            cparts.append("a:" + _sig_target(aff))
        for fl in tc.flows:
            fparts = [f"f:{fl.name}:{fl.access}:{fl.arena}"]
            for d in fl.deps:
                its = ";".join(
                    f"{inm}:{_sig_expr(lo)}:{_sig_expr(hi)}:{_sig_expr(st)}"
                    for (inm, lo, hi, st) in d.iters)
                fparts.append(
                    f"d{d.direction}:{_sig_target(d.target)}"
                    f":{_sig_expr(d.guard)}:{d.dtype}:{d.ltype}:{its}")
            cparts.append("|".join(fparts))
        for ch in tc.chores:
            cparts.append(f"ch:{ch.device_type}:{ch.body_kind}:"
                          f"{int(getattr(ch, 'pure', False))}")
        parts.append("||".join(cparts))
    blob = "\n".join(parts)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ------------------------------------------------------ knob handling
def default_knobs() -> Dict[str, object]:
    """The knob vector currently in force (MCA resolution order)."""
    from ..utils import params as _mca
    return {k: _mca.get(k) for k in TUNE_KNOBS}


@contextlib.contextmanager
def apply_knobs(knobs: Optional[Dict[str, object]]):
    """Apply a knob vector for the duration of the with-block, through
    BOTH the MCA registry (Python-side reads) and the PTC_MCA_* env
    spelling (native init paths + spawned SPMD ranks inherit it), then
    RESTORE both — the snapshot/restore that keeps one pool's tuned
    knobs from leaking into the next pool in the same Context/process.
    Unknown knob names raise (a persisted cache from a newer version
    must not be silently half-applied)."""
    if not knobs:
        yield {}
        return
    from ..utils import params as reg
    saved_param: Dict[str, Tuple[object, str]] = {}
    saved_env: Dict[str, Optional[str]] = {}
    applied: Dict[str, object] = {}
    try:
        for name, value in knobs.items():
            p = reg._reg.get(name)
            if p is None:
                raise KeyError(f"unknown tuning knob {name!r}")
            saved_param[name] = (p.value, p.source)
            reg.set(name, value)
            env = reg._env_name(name)
            saved_env[env] = os.environ.get(env)
            os.environ[env] = str(value)
            applied[name] = reg.get(name)
        yield applied
    finally:
        for name, (value, source) in saved_param.items():
            p = reg._reg[name]
            p.value, p.source = value, source
        for env, old in saved_env.items():
            if old is None:
                os.environ.pop(env, None)
            else:
                os.environ[env] = old


def hold_knobs(knobs: Dict[str, object]):
    """apply_knobs, held open: apply the vector NOW (same MCA + env
    double-write, same unknown-name check) and return a zero-argument
    `restore()` that puts the snapshot back — the ptc-pilot
    controller's hot-swap primitive, where the swap must outlive any
    single with-block (it stays in force across pools until the next
    retune or teardown).  Restore is idempotent."""
    cm = apply_knobs(dict(knobs) if knobs else None)
    applied = cm.__enter__()
    done = []

    def restore():
        if done:
            return
        done.append(True)
        cm.__exit__(None, None, None)

    return applied, restore


def knob_env(knobs: Dict[str, object]) -> Dict[str, str]:
    """The PTC_MCA_* env spelling of a knob vector — what a spawned
    SPMD rank needs in its environment to run under the vector."""
    from ..utils import params as reg
    return {reg._env_name(name): str(v) for name, v in knobs.items()}


def resolve_tuned(tp, tuned) -> Optional[Dict[str, object]]:
    """Resolve Taskpool.run's `tuned=` argument to a knob vector:
    a dict passes through, True looks up the persisted store by
    (graph signature, host fingerprint) — None when no winner is
    recorded for this graph on this box."""
    if not tuned:
        return None
    if isinstance(tuned, dict):
        return dict(tuned)
    rec = TuneStore().get(graph_signature(tp), host_fingerprint())
    return dict(rec["knobs"]) if rec else None


# ------------------------------------------------------- persistence
class TuneStore:
    """Persisted tuning winners: {"version": 1, "entries":
    {graph_signature: {host_fingerprint: record}}} where record =
    {"knobs", "predicted_ns", "measured_s", "critpath_ratio",
    "source"}.  Written atomically (tmp + rename); a missing or
    garbled file reads as empty — the tuner must work on fresh
    hosts.  Path: PTC_MCA_tune_cache_path, default ~/.ptc/tuned.json
    (see MIGRATION.md for the format contract)."""

    VERSION = 1

    def __init__(self, path: Optional[str] = None):
        if path is None:
            from ..utils import params as _mca
            path = _mca.get("tune.cache_path") or os.path.expanduser(
                "~/.ptc/tuned.json")
        self.path = path
        self._doc: Optional[dict] = None

    def load(self) -> dict:
        if self._doc is None:
            try:
                with open(self.path) as f:
                    doc = json.load(f)
                if not isinstance(doc, dict) \
                        or doc.get("version") != self.VERSION:
                    doc = {"version": self.VERSION, "entries": {}}
            except (OSError, ValueError):
                doc = {"version": self.VERSION, "entries": {}}
            self._doc = doc
        return self._doc

    def get(self, signature: str, host: str) -> Optional[dict]:
        return self.load()["entries"].get(signature, {}).get(host)

    def put(self, signature: str, host: str, record: dict):
        doc = self.load()
        doc["entries"].setdefault(signature, {})[host] = record
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        os.replace(tmp, self.path)


# -------------------------------------------------------- simulator
class ScheduleSimulator:
    """Deterministic discrete-event schedule simulation of one
    concretized taskpool under a knob vector.

    List scheduling over the engine-exact instance DAG: per-rank
    `workers` worker resources, task durations from the CostModel plus
    the modeled dispatch overhead, cross-rank delivery edges delayed by
    the fitted wire model (eager/rdv split at the knob threshold,
    chunk-pipelined + rail-striped above the chunk quantum, topology
    factor on collective-class edges), device-chore h2d stalls when
    staging cannot double-buffer, and a cache-budget spill penalty from
    the plan's residency simulation.  Pure arithmetic end to end:
    NO wall-clock reads, NO randomness — same inputs, same makespan."""

    def __init__(self, plan: Plan, cost: Optional[CostModel] = None,
                 econ=None, workers: Optional[int] = None,
                 tmodel=None):
        if plan.bounded or plan.cg is None:
            raise ValueError(
                "ScheduleSimulator needs a concrete plan (enumeration "
                "was refused; raise plan.max_instances)")
        self.plan = plan
        self.fg: FlowGraph = plan.fg
        self.cg = plan.cg
        if cost is None:
            src = plan.makespan.get("per_class_cost") or {}
            cost = CostModel(dict(src),
                             source=plan.makespan.get("cost_source",
                                                      "uniform"))
        self.cost = cost
        if econ is None:
            from ..comm.economics import default_economics
            econ = default_economics()
        self.econ = econ
        if workers is None:
            workers = int(plan.makespan.get("workers_per_rank", 1) or 1)
        self.workers = max(1, workers)
        self._prepare()
        if tmodel is None:
            from ..comm.topology import default_topology
            tmodel = default_topology(max(self.ranks, default=0) + 1)
        self.tmodel = tmodel
        self._cls_cache: Dict[Tuple[int, int], str] = {}

    # ------------------------------------------------------- prepare
    def _prepare(self):
        fg, cg = self.fg, self.cg
        from .plan import _Analyzer, _has_device_chore
        an = _Analyzer(fg, cg, Plan(fg))
        an.compute_waves()
        self._an = an
        nodes = sorted(an.inst_set)
        self.order = {n: i for i, n in enumerate(nodes)}
        self.nodes = nodes
        self.rank = {n: an._rank(n) for n in nodes}
        self.ranks = sorted(set(self.rank.values()))
        dev_cls = {cm.id for cm in fg.classes
                   if _has_device_chore(cm.tc)}
        coll_cls = {cm.id for cm in fg.classes if cm.is_coll}
        self.has_device = bool(dev_cls)
        self.has_coll = bool(coll_cls)
        self.has_wire = False
        self.exec_ns = {}
        self.in_bytes: Dict[tuple, int] = {}
        self.is_dev = {}
        for n in nodes:
            cm = fg.classes[n[0]]
            self.exec_ns[n] = float(self.cost.ns(cm.name))
            self.is_dev[n] = n[0] in dev_cls
        # ptc-fuse pricing input: nodes sitting in a CERTIFIED fusable
        # wave (plan.certify) share ONE dispatch-overhead charge when
        # the wave_fuse knob is on — the simulator's model of the wave
        # compiler collapsing a wave into one launch.  Only device
        # nodes qualify (fusion lives in the device layer).
        self.fused_width: Dict[tuple, int] = {}
        cert_w = {(c["rank"], c["wave"]): c["width"]
                  for c in self.plan.fusability
                  if c.get("fusable") and c.get("width", 0) > 1}
        for n in nodes:
            if not self.is_dev[n]:
                continue
            w = cert_w.get((self.rank[n], an.wave[n]))
            if w:
                self.fused_width[n] = w
        # per-edge payloads: mirror the release walk once, keep the max
        # payload per (src, dst) node pair + the collective flag
        self.edge_payload: Dict[Tuple[tuple, tuple], int] = {}
        self.edge_coll: Dict[Tuple[tuple, tuple], bool] = {}
        for n in nodes:
            cm = fg.classes[n[0]]
            l = an.locals_of(n)
            for fi, fl in enumerate(cm.flows):
                is_ctl = fl.access == N.FLOW_CTL
                for di, d in enumerate(fl.deps):
                    if d.direction != 1:
                        continue
                    info = cm._dep_info[(fi, di)]
                    if info["kind"] != "task":
                        continue
                    payload = 0
                    if not is_ctl:
                        if d.dtype is not None:
                            payload = fg.datatype_bytes.get(d.dtype) or 0
                        if payload == 0:
                            datum = an.datum_of(n, fi)
                            payload = an.datum_bytes(datum, n, fi)
                    peer = fg.by_name.get(info["peer"])
                    if peer is None:
                        continue
                    for kind, vals, _cert in cm.out_emissions(fi, di, l):
                        if kind != "task":
                            continue
                        dst = (peer.id, vals)
                        if dst not in self.order:
                            continue
                        key = (n, dst)
                        if payload > self.edge_payload.get(key, -1):
                            self.edge_payload[key] = payload
                        if n[0] in coll_cls or dst[0] in coll_cls:
                            self.edge_coll[key] = True
                        # h2d staging volume per destination device task
                        if dst[0] in dev_cls and not is_ctl:
                            self.in_bytes[dst] = \
                                self.in_bytes.get(dst, 0) + payload
                        if self.rank[n] != self.rank[dst]:
                            self.has_wire = True
        # predecessors (all delivery edges; a dynamically-guarded edge
        # that fires at runtime delays its consumer like any other, so
        # the simulator includes maybe-edges — the conservative read)
        self.preds: Dict[tuple, List[tuple]] = {}
        self.indeg0: Dict[tuple, int] = {n: 0 for n in nodes}
        self.succ: Dict[tuple, List[tuple]] = {}
        for src, outs in cg.succ.items():
            for dst, _certain in outs:
                if dst in self.indeg0:
                    self.indeg0[dst] += 1
                    self.succ.setdefault(src, []).append(dst)

    # ------------------------------------------------------- pricing
    def _edge_cls(self, src_rank: int, dst_rank: int) -> Optional[str]:
        """Link class of a cross-rank edge (memoized; None = unclassed
        flat pricing when no topology model is present)."""
        key = (src_rank, dst_rank)
        c = self._cls_cache.get(key)
        if c is None:
            tm = self.tmodel
            c = tm.class_of(src_rank, dst_rank) if tm is not None \
                else "ici"
            self._cls_cache[key] = c
        return c

    def _mesh_cls(self) -> Optional[str]:
        """The class collectives resolve against: 'dcn' when the mesh
        spans islands, 'ici' otherwise (matches coll._mesh_class)."""
        tm = self.tmodel
        if tm is None or len(self.ranks) <= 1:
            return None
        return "dcn" if tm.n_islands > 1 else "ici"

    @staticmethod
    def _knob_cls(kv: Dict[str, object], name: str,
                  cls: Optional[str]) -> object:
        """Per-class override of a base knob inside a knob VECTOR: the
        `{name}.{cls}` spelling when present and non-empty, else the
        base value — the vector-local mirror of
        topology.resolve_class_knob (which reads the MCA registry)."""
        if cls in ("ici", "dcn"):
            v = kv.get(f"{name}.{cls}")
            if v not in (None, ""):
                return v
        return kv[name]

    def _wire_ns(self, payload: int, kv: Dict[str, object],
                 cls: Optional[str] = None) -> float:
        econ = self.econ
        eager = int(self._knob_cls(kv, "comm.eager_limit", cls))
        if payload <= eager:
            return econ.cost(payload, "eager", cls=cls) * 1e9
        chunk = int(self._knob_cls(kv, "comm.chunk_size", cls))
        rails = max(1, int(self._knob_cls(kv, "comm.rails", cls)))
        a = econ.alpha("rdv", cls=cls) * 1e9
        b = econ.beta("rdv", cls=cls) * 1e9
        env = max(a, CHUNK_ENVELOPE_NS)
        if chunk > 0 and payload > chunk:
            nch = (payload + chunk - 1) // chunk
            return (a + (nch - 1) * env
                    + payload * b / _stripe_div(rails, nch))
        return a + payload * b

    def _coll_factor(self, payload: int, kv: Dict[str, object]) -> float:
        cls = self._mesh_cls()
        topo = self._knob_cls(kv, "coll.topo", cls) or "auto"
        nranks = max(2, len(self.ranks))
        costs = self.econ.topology_costs("reduce", max(1, payload),
                                         nranks, cls=cls,
                                         tmodel=self.tmodel)
        best = min(costs.values())
        if best <= 0:
            return 1.0
        if topo in costs:
            return costs[topo] / best
        return 1.0  # auto = the selector picks the best

    def _slice_overhead_ns(self, kv: Dict[str, object],
                           payload: int) -> float:
        """Per-collective-edge slicing cost: more slices pipeline the
        wire but each slice is its own task chain (dispatch + frame)."""
        ms = max(1, int(kv["coll.max_slices"]))
        return (ms - 1) * CHUNK_ENVELOPE_NS / 2.0

    def simulate(self, knobs: Optional[Dict[str, object]] = None) -> dict:
        """Price one knob vector: returns {"makespan_ns", "wire_ns",
        "stall_ns", "spill_ns", "spills", "dispatch_ns_per_task",
        "tasks"} — all derived deterministically."""
        kv = default_knobs()
        if knobs:
            kv.update(knobs)
        mag = max(1, int(kv["runtime.mag_batch"]))
        slots = max(1, int(kv["device.staging_slots"]))
        cache = int(kv["device.cache_bytes"] or 0)
        wave_fuse = bool(kv.get("device.wave_fuse", True))
        dispatch = DISPATCH_BASE_NS + DISPATCH_MAG_NS / mag

        indeg = dict(self.indeg0)
        ready_at: Dict[tuple, float] = {}
        heap: List[Tuple[float, int, tuple]] = []
        for n in self.nodes:
            if indeg[n] == 0:
                heapq.heappush(heap, (0.0, self.order[n], n))
        worker_free: Dict[int, List[float]] = {
            r: [0.0] * self.workers for r in self.ranks}
        for wf in worker_free.values():
            heapq.heapify(wf)
        makespan = 0.0
        wire_total = 0.0
        stall_total = 0.0
        done = 0
        while heap:
            t_ready, _ord, n = heapq.heappop(heap)
            r = self.rank[n]
            wf = worker_free.setdefault(r, [0.0] * self.workers)
            t_w = heapq.heappop(wf)
            start = max(t_ready, t_w)
            stall = 0.0
            if self.is_dev[n] and slots < 2:
                # single staging slot: the wave's h2d cannot overlap
                # the previous wave's compute — the dispatch stalls for
                # the task's staged input volume
                stall = self.in_bytes.get(n, 0) * H2D_BYTE_NS
            disp_n = dispatch
            if wave_fuse:
                # certified fusable wave -> ONE launch for the whole
                # wave: the per-task share of the dispatch overhead is
                # 1/width (ptc-fuse; the certificate is the gate, so
                # uncertified waves keep the full per-task charge)
                fw = self.fused_width.get(n)
                if fw:
                    disp_n = dispatch / fw
            dur = self.exec_ns[n] + disp_n + stall
            finish = start + dur
            stall_total += stall
            heapq.heappush(wf, finish)
            makespan = max(makespan, finish)
            done += 1
            for dst in self.succ.get(n, ()):
                delay = 0.0
                if self.rank[n] != self.rank[dst]:
                    payload = self.edge_payload.get((n, dst), 0)
                    delay = self._wire_ns(
                        payload, kv,
                        self._edge_cls(self.rank[n], self.rank[dst]))
                    if self.edge_coll.get((n, dst)):
                        delay *= self._coll_factor(payload, kv)
                        delay += self._slice_overhead_ns(kv, payload)
                    wire_total += delay
                arr = finish + delay
                if arr > ready_at.get(dst, -1.0):
                    ready_at[dst] = arr
                indeg[dst] -= 1
                if indeg[dst] == 0:
                    heapq.heappush(heap, (ready_at[dst],
                                          self.order[dst], dst))
        if done != len(self.nodes):
            # cycle-parked tail (V003): count the unreachable tasks as
            # serial work so the number stays finite and comparable
            makespan += sum(self.exec_ns[n] + dispatch
                            for n in self.nodes if indeg.get(n, 0) > 0)
        spills = 0
        spill_ns = 0.0
        if self.has_device and cache > 0:
            spills = self.plan.predict_spills(cache)
            if spills:
                tile = max(self.plan._datum_bytes.values(), default=0)
                d2h = self.econ.beta("device") * 1e9
                spill_ns = spills * (SPILL_ALPHA_NS + tile * d2h)
        return {
            "makespan_ns": makespan + spill_ns,
            "wire_ns": wire_total,
            "stall_ns": stall_total,
            "spill_ns": spill_ns,
            "spills": spills,
            "dispatch_ns_per_task": dispatch,
            "tasks": len(self.nodes),
        }

    # --------------------------------------------------------- axes
    def knob_axes(self) -> Dict[str, List[object]]:
        """Graph-relevant candidate values per knob.  Axes that cannot
        change this DAG's simulated cost (comm knobs without a
        cross-rank edge, device knobs without device chores) collapse
        to the current default so the search space stays small and the
        proposals deterministic."""
        kv = default_knobs()
        multi = (self.tmodel is not None
                 and self.tmodel.n_islands > 1
                 and len(self.ranks) > 1)
        axes: Dict[str, List[object]] = {}
        axes["runtime.mag_batch"] = [16, 64, 128, 256]
        if self.has_wire:
            axes["comm.chunk_size"] = [256 << 10, 1 << 20, 4 << 20]
            axes["comm.rails"] = [1, 2, 4]
            axes["comm.eager_limit"] = [16 << 10, 64 << 10, 256 << 10]
        else:
            for k in ("comm.chunk_size", "comm.rails",
                      "comm.eager_limit"):
                axes[k] = [kv[k]]
        if self.has_coll and self.has_wire:
            axes["coll.topo"] = ["auto", "ring", "binomial", "star"]
            if multi:
                axes["coll.topo"].append("hier")
            axes["coll.max_slices"] = [1, 4, 16]
        else:
            axes["coll.topo"] = [kv["coll.topo"]]
            axes["coll.max_slices"] = [kv["coll.max_slices"]]
        # ptc-topo per-class overrides: only a multi-island mesh has a
        # 'dcn' class for them to act on, so the dcn axes open there
        # ("" = inherit base always a candidate) and collapse to the
        # current value everywhere else.  The ici spellings stay
        # collapsed — on a single-island mesh they ARE the base knob.
        if self.has_wire and multi:
            axes["comm.chunk_size.dcn"] = ["", 1 << 20, 4 << 20,
                                           16 << 20]
            axes["comm.rails.dcn"] = ["", 2, 4, 8]
            axes["comm.eager_limit.dcn"] = ["", 8 << 10, 64 << 10]
        else:
            for k in ("comm.chunk_size.dcn", "comm.rails.dcn",
                      "comm.eager_limit.dcn"):
                axes[k] = [kv[k]]
        if self.has_coll and self.has_wire and multi:
            axes["coll.topo.dcn"] = ["", "hier", "star", "binomial"]
        else:
            axes["coll.topo.dcn"] = [kv["coll.topo.dcn"]]
        for k in ("comm.chunk_size.ici", "comm.rails.ici",
                  "comm.eager_limit.ici", "coll.topo.ici"):
            axes[k] = [kv[k]]
        if self.has_device:
            axes["device.staging_slots"] = [1, 2, 4]
            peak = int(self.plan.peak_bytes(device_only=True) or 0)
            cands = [0]
            if peak > 0:
                cands += [peak, 2 * peak]
            axes["device.cache_bytes"] = cands
        else:
            axes["device.staging_slots"] = [kv["device.staging_slots"]]
            axes["device.cache_bytes"] = [kv["device.cache_bytes"]]
        if self.has_device and self.fused_width:
            # fusion width vs staging: only worth searching when a
            # certified fusable wave exists for the compiler to fuse
            axes["device.wave_fuse"] = [True, False]
        else:
            axes["device.wave_fuse"] = [kv["device.wave_fuse"]]
        return axes

    # ------------------------------------------------------- search
    def propose(self, topk: int = 3, rounds: int = 2) -> List[dict]:
        """Deterministic coordinate descent over knob_axes(): sweep
        each axis in declared order holding the others, keep the best,
        repeat up to `rounds` or to a fixed point.  Returns the top-k
        DISTINCT vectors ranked by simulated makespan, the incumbent
        default vector always included (rank whatever it earns) so a
        validation pass always has the baseline to beat."""
        axes = self.knob_axes()
        seen: Dict[tuple, dict] = {}

        def key(kv):
            return tuple(kv[k] for k in TUNE_KNOBS)

        def price(kv):
            k = key(kv)
            if k not in seen:
                seen[k] = {"knobs": dict(kv),
                           "sim": self.simulate(kv),
                           }
                seen[k]["predicted_ns"] = seen[k]["sim"]["makespan_ns"]
            return seen[k]["predicted_ns"]

        best = default_knobs()
        best_ns = price(best)
        for _round in range(max(1, rounds)):
            changed = False
            for name in TUNE_KNOBS:
                for v in axes.get(name, [best[name]]):
                    cand = dict(best)
                    cand[name] = v
                    ns = price(cand)
                    if ns < best_ns * (1 - 1e-9):
                        best, best_ns = cand, ns
                        changed = True
            if not changed:
                break
        ranked = sorted(seen.values(),
                        key=lambda r: (r["predicted_ns"],
                                       key(r["knobs"])))
        out, have = [], set()
        for r in ranked:
            k = key(r["knobs"])
            if k in have:
                continue
            have.add(k)
            out.append(r)
            if len(out) >= max(1, topk):
                break
        # the incumbent defaults always ride along for the validator
        dk = key(default_knobs())
        if dk not in have:
            out.append(seen[dk])
        return out


# ---------------------------------------------------------- driver
def autotune(tp, measure: Optional[Callable] = None, topk: int = 3,
             cost: Optional[CostModel] = None, econ=None,
             workers: Optional[int] = None,
             max_instances: Optional[int] = None,
             store: Optional[TuneStore] = None,
             persist: bool = True) -> dict:
    """Tune one taskpool: plan it, propose knob vectors from the
    schedule simulator, optionally validate the top-k with real runs,
    and persist the winner keyed by (graph signature, host
    fingerprint) for Taskpool.run(tuned=True) to auto-apply.

    `measure(knobs) -> seconds | (seconds, trace)`: the caller-supplied
    real-run validator, called once per top-k candidate (and for the
    default vector).  When it returns a level-2 Trace alongside the
    wall time, the `compare_critpath` predicted-vs-measured ratio is
    recorded per validation run — the regression signal that keeps the
    model honest.  Without `measure`, the best PREDICTED vector wins
    and nothing persists (model-only proposals are hints, not
    winners).

    Returns {"signature", "host", "candidates", "validated", "winner",
    "persisted", "notes"}."""
    fg = extract_flowgraph(tp)
    plan = plan_graph(fg, max_instances=max_instances, cost=cost,
                      econ=econ, workers=workers)
    sig = graph_signature(tp)
    host = host_fingerprint()
    result = {"signature": sig, "host": host, "candidates": [],
              "validated": [], "winner": None, "persisted": False,
              "notes": list(plan.notes)}
    if plan.bounded:
        result["notes"].append(
            "autotune refused: enumeration past plan.max_instances — "
            "no simulation possible")
        return result
    sim = ScheduleSimulator(plan, cost=cost, econ=econ, workers=workers)
    ranked = sim.propose(topk=topk)
    result["candidates"] = [
        {"knobs": r["knobs"], "predicted_ns": r["predicted_ns"]}
        for r in ranked]
    if measure is None:
        result["winner"] = {
            "knobs": ranked[0]["knobs"],
            "predicted_ns": ranked[0]["predicted_ns"],
            "measured_s": None, "critpath_ratio": None,
            "source": "model-only",
        }
        return result
    validated = []
    for r in ranked:
        out = measure(dict(r["knobs"]))
        trace = None
        if isinstance(out, tuple):
            secs, trace = out
        else:
            secs = out
        row = {"knobs": r["knobs"],
               "predicted_ns": r["predicted_ns"],
               "measured_s": float(secs),
               # simulated-vs-wall, always recorded (the model-honesty
               # signal even when the executed critpath degenerates)
               "predicted_vs_wall": (round(r["predicted_ns"]
                                           / (secs * 1e9), 4)
                                     if secs > 0 else None)}
        if trace is not None:
            try:
                row["critpath"] = compare_critpath(plan, trace)
                row["critpath_ratio"] = row["critpath"]["ratio"]
            except Exception as exc:  # a truncated trace must not
                row["critpath_error"] = str(exc)  # kill the tuner
        validated.append(row)
    result["validated"] = validated
    winner = min(validated, key=lambda r: (r["measured_s"],
                                           r["predicted_ns"]))
    result["winner"] = {
        "knobs": winner["knobs"],
        "predicted_ns": winner["predicted_ns"],
        "measured_s": winner["measured_s"],
        "predicted_vs_wall": winner.get("predicted_vs_wall"),
        "critpath_ratio": winner.get("critpath_ratio"),
        "source": "validated",
    }
    if persist:
        st = store or TuneStore()
        st.put(sig, host, result["winner"])
        result["persisted"] = True
        result["store_path"] = st.path
    return result


# ------------------------------------------- collective knob pricing
def price_collective(knobs: Dict[str, object], size_bytes: int,
                     nranks: int, econ=None,
                     task_overhead_ns: float = DISPATCH_BASE_NS) -> float:
    """Model-side price (ns) of one runtime-native collective of
    `size_bytes` across `nranks` under a knob vector — the proposal
    model the collective bench's tuned section searches with (the
    graph itself is built rank-side inside gemm_panel_reduce, so the
    bench proposes from this closed-form model and validates with real
    2-rank runs, exactly the simulator->validate loop in miniature).

    Prices the fitted topology cost of the reduction — on the EAGER
    legs when the per-rank segment fits under the knob's eager
    threshold (the fitted eager path is markedly cheaper per byte than
    rendezvous on loopback: the single biggest lever this model
    surfaces), rendezvous otherwise — plus the slicing trade-off: more
    slices overlap wire and compute (T3-style) but each slice is its
    own task chain and frame."""
    if econ is None:
        from ..comm.economics import default_economics
        econ = default_economics()
    topo = knobs.get("coll.topo", "auto")
    slices = max(1, int(knobs.get("coll.max_slices", 16)))
    limit = knobs.get("comm.eager_limit")
    if limit is None:
        from ..utils import params as _mca
        limit = _mca.get("comm.eager_limit")
    seg = max(1, size_bytes) / max(2, nranks)
    path = "eager" if seg <= int(limit) else "rdv"
    costs = econ.topology_costs("reduce", max(1, size_bytes),
                                max(2, nranks), path=path)
    base = (min(costs.values()) if topo in (None, "", "auto")
            else costs.get(topo, min(costs.values())))
    base_ns = base * 1e9
    # slicing: up to PIPE_DEPTH slices genuinely overlap (wire vs the
    # downstream partial reduction), every slice beyond that is pure
    # per-slice chain overhead (step tasks + frames on every rank)
    PIPE_DEPTH = 4
    per_slice = 3 * task_overhead_ns + CHUNK_ENVELOPE_NS
    alpha_ns = econ.alpha(path) * 1e9
    wire_ns = max(0.0, base_ns - alpha_ns)
    return (alpha_ns + wire_ns / min(slices, PIPE_DEPTH)
            + slices * per_slice)


def price_stream(knobs: Dict[str, object], size_bytes: int,
                 hops: int = 1, econ=None) -> float:
    """Model-side price (ns) of a `hops`-deep cross-rank DEVICE tile
    chain under a knob vector (the BENCH_stream workload): per hop the
    fitted device-path alpha leg, one more alpha-sized envelope per
    extra chunk (every chunk is its own d2h-slice + ranged wire
    round), and the per-byte leg divided by the diminishing-returns
    rail stripe.  Like price_collective, this is the proposal half of
    the miniature simulate->validate loop the stream bench runs; the
    validation half is real 2-process pairs."""
    if econ is None:
        from ..comm.economics import default_economics
        econ = default_economics()
    chunk = int(knobs.get("comm.chunk_size", 1 << 20))
    rails = max(1, int(knobs.get("comm.rails", 2)))
    a = econ.alpha("device") * 1e9
    b = econ.beta("device") * 1e9
    if chunk > 0 and size_bytes > chunk:
        nch = (size_bytes + chunk - 1) // chunk
        hop = (a + (nch - 1) * max(a, CHUNK_ENVELOPE_NS)
               + size_bytes * b / _stripe_div(rails, nch))
    else:
        hop = a + size_bytes * b
    return hops * hop


def propose_stream(size_bytes: int, hops: int = 1, econ=None,
                   topk: int = 3) -> List[dict]:
    """Ranked streaming knob proposals (chunk quantum x rails) from
    price_stream, defaults included."""
    from ..utils import params as _mca
    default = {"comm.chunk_size": _mca.get("comm.chunk_size"),
               "comm.rails": _mca.get("comm.rails")}
    cands = []
    seen_behavior = set()
    for chunk in (256 << 10, 1 << 20, 4 << 20, 2 * size_bytes):
        for rails in (1, 2, 4):
            kv = {"comm.chunk_size": chunk, "comm.rails": rails}
            # behavioral dedupe: a single-chunk payload never stripes,
            # so the rails axis collapses (validating three identical
            # configs would waste the top-k slots)
            nch = ((size_bytes + chunk - 1) // chunk
                   if chunk > 0 and size_bytes > chunk else 1)
            key = (chunk, rails if nch > 1 else 0)
            if key in seen_behavior and kv != default:
                continue
            seen_behavior.add(key)
            cands.append({
                "knobs": kv,
                "predicted_ns": price_stream(kv, size_bytes, hops,
                                             econ)})
    cands.sort(key=lambda r: (r["predicted_ns"],
                              str(sorted(r["knobs"].items()))))
    out = cands[:max(1, topk)]
    if not any(r["knobs"] == default for r in out):
        out.append({"knobs": default,
                    "predicted_ns": price_stream(default, size_bytes,
                                                 hops, econ)})
    return out


def propose_collective(size_bytes: int, nranks: int, econ=None,
                       topk: int = 3) -> List[dict]:
    """Ranked collective knob proposals from the closed-form model:
    the cross product of topology x slicing x eager threshold, priced
    by price_collective, defaults included."""
    from ..utils import params as _mca
    default = {"coll.topo": _mca.get("coll.topo"),
               "coll.max_slices": _mca.get("coll.max_slices"),
               "comm.eager_limit": _mca.get("comm.eager_limit")}
    cands = []
    seen_behavior = set()
    seg = max(1, size_bytes) / max(2, nranks)
    for topo in ("auto", "ring", "binomial", "star"):
        for slices in (1, 4, 16):
            for eager in sorted({default["comm.eager_limit"],
                                 1 << 20, 4 << 20}):
                kv = {"coll.topo": topo, "coll.max_slices": slices,
                      "comm.eager_limit": eager}
                # behavioral dedupe: two thresholds on the same side of
                # the segment size run identically
                key = (topo, slices, seg <= eager)
                if key in seen_behavior and kv != default:
                    continue
                seen_behavior.add(key)
                cands.append({
                    "knobs": kv,
                    "predicted_ns": price_collective(kv, size_bytes,
                                                     nranks, econ)})
    cands.sort(key=lambda r: (r["predicted_ns"],
                              str(sorted(r["knobs"].items()))))
    out = cands[:max(1, topk)]
    if not any(r["knobs"] == default for r in out):
        pred = price_collective(default, size_bytes, nranks, econ)
        out.append({"knobs": default, "predicted_ns": pred})
    return out
