"""ptc-verify: static dataflow verification of PTG task graphs.

The reference's parsec_ptgpp compiler statically sanity-checks a JDF
before any task runs (dangling flows, malformed dep targets — SURVEY
§L1).  This is the equivalent pass for our table-compiled task classes,
run over the shared `analysis.flowgraph` extraction.  Each rule has a
stable ID and reports class/flow/dep source locations:

  V001  dangling IN: an input counted as a task delivery that no
        producer OUT dep ever emits (guaranteed hang)
  V002  `%{ %}` escape guard on a data input in a flow with a memory
        fallback (the documented wait-forever case — see dsl/jdf.py
        dynamic-guard semantics; promoted from comment to error)
  V003  dependency cycle in the concretized DAG
  V004  dep target index outside the target class's execution space
        for EVERY emission (statically dead edge; per-instance
        boundary drops are JDF semantics and stay silent)
  V005  two unordered OUT deps writing the same tile version
        (write-write race on a collection datum)
  V006  never-read OUT: a delivery no consumer input expects
        (dead dataflow -> wasted comm, and a spurious dependency-count
        decrement on the receiver)
  V007  dtype/shape mismatch across an edge (wire datatype names
        disagree, or arena payload sizes differ with no declared
        reshape)
  V008  ptc_coll_* usage-contract violation (PR 6 constraints: data IN
        deps of collective step classes must carry no guards — a
        guarded IN would be counted as a maybe-input and wait forever)
  V009  rank-mapping soundness: a data input read straight from a
        collection datum whose owner rank differs from the consuming
        instance's placement rank — memory reads are affine with
        placement in this runtime (there is no wire path for a Mem
        IN), so the consumer reads an uninitialized local mirror
  V010  wave-fusability soundness: a wave the plan layer marks
        homogeneous fails its fusability certificate STRUCTURALLY —
        an intra-wave dependency (cycle tail) or a datum written by
        one member while another member touches it with no ordering
        between them.  The engine schedules wave members in arbitrary
        order, so such a pair is a latent race today and a corruption
        once the wave fuses into one executable (MPK prep; see
        analysis/plan.py certify()).  Body opacity and tile-signature
        mismatches refuse the certificate WITHOUT tripping V010 —
        they are legal graphs, just not fusable

Affine/interval reasoning handles what it can prove symbolically
(V004's never-in-domain proof); bounded concrete enumeration of the
execution space is the exact fallback for the instance-level rules
(V001/V003/V005/V006).  Enumeration past `max_instances` degrades to
symbolic-only with an explicit note — never a silent truncation.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

from .. import _native as N
from ..core.expr import Range
from ..core.taskclass import Mem, Ref
from .flowgraph import (ConcreteGraph, FlowGraph, expr_is_dynamic,
                        extract_flowgraph, interval_of)

RULES: Dict[str, str] = {
    "V001": "dangling IN dependency (no producing OUT -> hang)",
    "V002": "escape guard on a data input with a memory fallback",
    "V003": "dependency cycle in the concretized DAG",
    "V004": "dep target never inside the target execution space",
    "V005": "unordered writes to the same collection datum",
    "V006": "never-read OUT dependency (dead dataflow)",
    "V007": "dtype/shape mismatch across an edge",
    "V008": "ptc_coll_* usage-contract violation",
    "V009": "memory read of a remote-owned collection datum",
    "V010": "homogeneous wave fails its fusability certificate",
}

_MAX_SAMPLES = 4


class Finding:
    """One verifier finding: rule + class/flow/dep + source location."""

    __slots__ = ("rule", "severity", "cls", "flow", "dep", "loc",
                 "message", "count", "instances")

    def __init__(self, rule: str, severity: str, cls: str,
                 flow: Optional[str], dep: Optional[int],
                 loc: Optional[str], message: str, count: int = 1,
                 instances: Optional[Sequence[tuple]] = None):
        self.rule = rule
        self.severity = severity
        self.cls = cls
        self.flow = flow
        self.dep = dep
        self.loc = loc
        self.message = message
        self.count = count
        self.instances = [tuple(i) for i in (instances or [])]

    def where(self) -> str:
        w = self.cls
        if self.flow is not None:
            w += f".{self.flow}"
        if self.dep is not None:
            w += f"[dep {self.dep}]"
        return w

    def __repr__(self):
        return (f"{self.rule} {self.severity} {self.where()}"
                + (f" ({self.loc})" if self.loc else "")
                + f": {self.message}")

    def to_json(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "class": self.cls, "flow": self.flow, "dep": self.dep,
                "loc": self.loc, "message": self.message,
                "count": self.count,
                "instances": [list(i) for i in self.instances]}


class Report:
    def __init__(self, findings: List[Finding], notes: List[str],
                 stats: dict):
        order = {"error": 0, "warning": 1, "note": 2}
        self.findings = sorted(
            findings, key=lambda f: (order.get(f.severity, 3), f.rule,
                                     f.cls, f.flow or "", f.dep or 0))
        self.notes = notes
        self.stats = stats

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warning"]

    def ok(self) -> bool:
        return not self.findings

    def text(self) -> str:
        lines = []
        for f in self.findings:
            loc = f" ({f.loc})" if f.loc else ""
            lines.append(f"{f.rule} {f.severity:7s} {f.where()}{loc}: "
                         f"{f.message}")
            if f.instances:
                inst = ", ".join(
                    "(" + ", ".join(str(v) for v in i) + ")"
                    for i in f.instances[:_MAX_SAMPLES])
                more = (f" ... x{f.count}" if f.count > len(f.instances)
                        else "")
                lines.append(f"       instances: {inst}{more}")
        for n in self.notes:
            lines.append(f"note: {n}")
        s = self.stats
        lines.append(
            f"{len(self.errors)} error(s), {len(self.warnings)} "
            f"warning(s) over {s.get('classes', 0)} class(es), "
            f"{s.get('instances', 0)} instance(s), "
            f"{s.get('edges', 0)} edge(s) "
            f"[{s.get('elapsed_ms', 0):.0f} ms]")
        return "\n".join(lines)

    def to_json(self) -> dict:
        return {"findings": [f.to_json() for f in self.findings],
                "notes": list(self.notes), "stats": dict(self.stats)}


class VerifyError(RuntimeError):
    """Raised by verify= enforcement when error-severity findings
    exist."""

    def __init__(self, report: Report):
        self.report = report
        errs = report.errors
        head = "; ".join(repr(f) for f in errs[:3])
        more = f" (+{len(errs) - 3} more)" if len(errs) > 3 else ""
        super().__init__(f"ptc-verify: {len(errs)} error(s): {head}{more}")


# ===================================================================== rules

def _is_data(cm, fi) -> bool:
    return cm.flows[fi].access != N.FLOW_CTL


def _v002_escape_guard_with_mem_fallback(fg: FlowGraph) -> List[Finding]:
    out = []
    for cm in fg.classes:
        for fi, fl in enumerate(cm.flows):
            if not _is_data(cm, fi):
                continue
            has_mem_in = any(d.direction == 0 and isinstance(d.target, Mem)
                             for d in fl.deps)
            if not has_mem_in:
                continue
            for di, d in enumerate(fl.deps):
                if d.direction == 0 and isinstance(d.target, Ref) \
                        and expr_is_dynamic(d.guard):
                    out.append(Finding(
                        "V002", "error", cm.name, fl.name, di,
                        cm.dep_loc(fi, di),
                        "dynamic (escape) guard on a data input whose "
                        "flow has a memory fallback: the instance is "
                        "counted as WAITING for the task delivery and "
                        "the fallback can never fire — if no producer "
                        "chooses it, the taskpool hangs; write the "
                        "guard as a plain expression instead"))
    return out


def _v008_coll_contract(fg: FlowGraph) -> List[Finding]:
    out = []
    for cm in fg.classes:
        if not cm.is_coll:
            continue
        for fi, fl in enumerate(cm.flows):
            if not _is_data(cm, fi):
                continue
            for di, d in enumerate(fl.deps):
                if d.direction == 0 and d.guard is not None:
                    out.append(Finding(
                        "V008", "error", cm.name, fl.name, di,
                        cm.dep_loc(fi, di),
                        "guarded data IN dep on a collective step "
                        "class: ptc_coll_* input selection must ride "
                        "the producer-domain check (a guard holding an "
                        "escape is counted as a maybe-input and the "
                        "step waits forever; see comm/coll.py)"))
    return out


def _v007_dtype_shape(fg: FlowGraph) -> List[Finding]:
    out = []
    for cm in fg.classes:
        for fi, fl in enumerate(cm.flows):
            for di, d in enumerate(fl.deps):
                if d.direction != 1 or not isinstance(d.target, Ref):
                    continue
                peer = fg.by_name.get(d.target.task)
                pfi = cm.peer_flow_index(fi, di)
                if peer is None or pfi is None:
                    continue
                pfl = peer.flows[pfi]
                in_dtypes = {x.dtype for x in pfl.deps
                             if x.direction == 0 and x.dtype is not None}
                if d.dtype is not None and in_dtypes \
                        and d.dtype not in in_dtypes:
                    # Context.datatype_bytes tells a true layout
                    # mismatch (different payload sizes -> corruption)
                    # from a rename of the same layout (warning)
                    db = fg.datatype_bytes
                    sz = db.get(d.dtype)
                    peer_sz = {db.get(x) for x in in_dtypes}
                    rename_only = (sz is not None and peer_sz == {sz})
                    out.append(Finding(
                        "V007", "warning" if rename_only else "error",
                        cm.name, fl.name, di, cm.dep_loc(fi, di),
                        f"wire datatype {d.dtype!r}"
                        + (f" ({sz} B)" if sz is not None else "")
                        + f" sent to {peer.name}.{pfl.name}, whose "
                        f"input deps declare {sorted(in_dtypes)!r}"
                        + (" of the same payload size (rename?)"
                           if rename_only else
                           " with a different payload layout")))
                    continue
                # shape: arena payload sizes must agree unless a
                # reshape is declared on either endpoint
                asz = fg.arena_sizes
                src_a = fl.arena
                dst_a = pfl.arena
                retyped = (d.dtype is not None or d.ltype is not None
                           or any(x.ltype is not None or
                                  x.dtype is not None
                                  for x in pfl.deps if x.direction == 0))
                if (not retyped and src_a and dst_a
                        and src_a in asz and dst_a in asz
                        and asz[src_a] != asz[dst_a]):
                    out.append(Finding(
                        "V007", "warning", cm.name, fl.name, di,
                        cm.dep_loc(fi, di),
                        f"arena payload size mismatch across the edge "
                        f"to {peer.name}.{pfl.name}: {src_a!r} is "
                        f"{asz[src_a]} B, {dst_a!r} is {asz[dst_a]} B "
                        "and no reshape datatype is declared"))
    return out


def _v004_symbolic(fg: FlowGraph) -> List[Finding]:
    """Interval proof that an OUT dep's target can never be inside the
    peer's execution space — works even when enumeration is refused."""
    out = []
    for cm in fg.classes:
        ivals = cm.space_intervals()
        for fi, fl in enumerate(cm.flows):
            for di, d in enumerate(fl.deps):
                if d.direction != 1 or not isinstance(d.target, Ref):
                    continue
                if expr_is_dynamic(d.guard):
                    continue
                peer = fg.by_name.get(d.target.task)
                if peer is None or len(d.target.params) \
                        != len(peer.range_slots):
                    continue
                peer_iv = peer.space_intervals()
                dead_axis = None
                for ax, p in enumerate(d.target.params):
                    if p is None or isinstance(p, Range):
                        continue
                    tiv = interval_of(p, ivals, cm.names, fg.gdict)
                    ps = peer.range_slots[ax]
                    piv = peer_iv.get(ps)
                    if tiv is None or piv is None:
                        continue
                    if tiv[1] < piv[0] or tiv[0] > piv[1]:
                        dead_axis = (ax, tiv, piv)
                        break
                if dead_axis is not None:
                    ax, tiv, piv = dead_axis
                    out.append(Finding(
                        "V004", "error", cm.name, fl.name, di,
                        cm.dep_loc(fi, di),
                        f"target {peer.name} param {ax} evaluates in "
                        f"[{tiv[0]}, {tiv[1]}] but the execution space "
                        f"bounds it to [{piv[0]}, {piv[1]}]: the edge "
                        "can never land (every emission is dropped)"))
    return out


def _v004_concrete(cg: ConcreteGraph) -> List[Finding]:
    out = []
    fg = cg.fg
    for (cid, fi, di), (attempts, landed, oob) in cg.emit_stats.items():
        cm = fg.classes[cid]
        d = cm.flows[fi].deps[di]
        if not isinstance(d.target, Ref):
            continue
        if attempts > 0 and landed == 0 and oob > 0:
            out.append(Finding(
                "V004", "error", cm.name, cm.flows[fi].name, di,
                cm.dep_loc(fi, di),
                f"all {attempts} emission(s) target "
                f"{d.target.task} instances outside its execution "
                "space: the edge never lands (statically dead)",
                count=attempts))
    return out


def _v001_dangling_in(cg: ConcreteGraph) -> List[Finding]:
    out: Dict[tuple, Finding] = {}
    fg = cg.fg
    for (node, fi), expected in cg.expected.items():
        have = cg.ncert.get((node, fi), 0) + cg.nmaybe.get((node, fi), 0)
        if have >= expected:
            continue
        cid, params = node
        cm = fg.classes[cid]
        di = cg.selected.get((node, fi))
        key = (cid, fi, di)
        f = out.get(key)
        if f is None:
            what = ("control gather" if cm.is_ctl(fi)
                    else "task-delivery input")
            f = out[key] = Finding(
                "V001", "error", cm.name, cm.flows[fi].name, di,
                cm.dep_loc(fi, di) if di is not None else
                getattr(cm.flows[fi], "srcloc", None),
                f"{what} counted as expected but no producer OUT dep "
                "ever delivers to it: the instance waits forever "
                "(and no memory fallback applies)", count=0)
        f.count += 1
        if len(f.instances) < _MAX_SAMPLES:
            f.instances.append(params)
    return list(out.values())


def _v006_never_read_out(cg: ConcreteGraph) -> List[Finding]:
    out: Dict[tuple, Finding] = {}
    fg = cg.fg
    for (node, fi), ncert in cg.ncert.items():
        expected = cg.expected.get((node, fi), 0)
        extra = ncert - expected
        if extra <= 0:
            continue
        # attribute to the producing deps we sampled
        srcs = [s for s in cg.src_sample.get((node, fi), []) if s[2]]
        dep_keys = {s[1] for s in srcs} or {None}
        cid, params = node
        cm = fg.classes[cid]
        for dk in dep_keys:
            f = out.get(dk if dk else (node, fi))
            if f is None:
                if dk is not None:
                    scm = fg.classes[dk[0]]
                    f = Finding(
                        "V006", "warning", scm.name,
                        scm.flows[dk[1]].name, dk[2],
                        scm.dep_loc(dk[1], dk[2]),
                        f"delivers to {cm.name}.{cm.flows[fi].name} "
                        "instances whose input selection never expects "
                        "it: dead dataflow (wasted comm, and each "
                        "delivery decrements the receiver's dependency "
                        "count it never budgeted)", count=0)
                else:
                    f = Finding(
                        "V006", "warning", cm.name, cm.flows[fi].name,
                        None, getattr(cm.flows[fi], "srcloc", None),
                        "receives deliveries its input selection never "
                        "expects", count=0)
                out[dk if dk else (node, fi)] = f
            f.count += extra
            if len(f.instances) < _MAX_SAMPLES:
                f.instances.append(params)
    return list(out.values())


def _v003_cycles(cg: ConcreteGraph) -> List[Finding]:
    """Tarjan SCC (iterative) over the concrete delivery edges."""
    succ = cg.succ
    index: Dict[tuple, int] = {}
    low: Dict[tuple, int] = {}
    onstack = set()
    stack: List[tuple] = []
    sccs: List[List[tuple]] = []
    counter = [0]

    for root in list(succ):
        if root in index:
            continue
        work = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                onstack.add(node)
            recurse = False
            outs = succ.get(node, ())
            for i in range(pi, len(outs)):
                w = outs[i][0]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if w in onstack:
                    low[node] = min(low[node], index[w])
            if recurse:
                continue
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or any(
                        d == node for d, _ in succ.get(node, ())):
                    sccs.append(comp)
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    out = []
    for comp in sccs:
        members = set(comp)
        certain_only = all(
            c for n in comp for (d, c) in cg.succ.get(n, ())
            if d in members)
        sample = ", ".join(cg.node_name(n) for n in comp[:4])
        more = f" ... ({len(comp)} tasks)" if len(comp) > 4 else ""
        cm = cg.fg.classes[comp[0][0]]
        out.append(Finding(
            "V003", "error" if certain_only else "warning",
            cm.name, None, None, getattr(cm.tc, "srcloc", None),
            f"dependency cycle in the concretized DAG: {sample}{more}"
            + ("" if certain_only else
               " (through dynamically-guarded edges; may or may not "
               "materialize at runtime)"),
            count=len(comp),
            instances=[n[1] for n in comp[:_MAX_SAMPLES]]))
    return out


def _v005_write_races(cg: ConcreteGraph) -> List[Finding]:
    out = []
    fg = cg.fg
    adj: Dict[tuple, List[tuple]] = {}
    for src, outs in cg.succ.items():
        adj[src] = [d for d, _ in outs]
    reach_cache: Dict[tuple, set] = {}

    def reaches(a: tuple, b: tuple) -> bool:
        seen = reach_cache.get(a)
        if seen is None:
            seen = set()
            frontier = [a]
            while frontier:
                n = frontier.pop()
                for d in adj.get(n, ()):
                    if d not in seen:
                        seen.add(d)
                        frontier.append(d)
            reach_cache[a] = seen
        return b in seen

    for datum, writers in cg.mem_writes.items():
        certain = [(n, dk) for (n, dk, c) in writers if c]
        if len(certain) < 2:
            continue
        nodes = {}
        for n, dk in certain:
            nodes.setdefault(n, []).append(dk)
        race = None
        nlist = list(nodes)
        for n, dks in nodes.items():
            if len(dks) > 1:  # same instance writes the datum twice
                race = (n, n, dks[0], dks[1])
                break
        if race is None:
            for i in range(len(nlist)):
                for j in range(i + 1, len(nlist)):
                    a, b = nlist[i], nlist[j]
                    if not reaches(a, b) and not reaches(b, a):
                        race = (a, b, nodes[a][0], nodes[b][0])
                        break
                if race:
                    break
        if race is None:
            continue
        a, b, dka, dkb = race
        cm = fg.classes[dka[0]]
        coll, idx = datum
        out.append(Finding(
            "V005", "error", cm.name, cm.flows[dka[1]].name, dka[2],
            cm.dep_loc(dka[1], dka[2]),
            f"unordered write-write to {coll}"
            f"[{', '.join(str(v) for v in idx)}]: "
            f"{cg.node_name(a)} and {cg.node_name(b)} both write it "
            "with no dependency path between them (final value is a "
            "race)", count=len(certain),
            instances=[a[1], b[1]]))
    return out


def _v010_wave_fusability(fg: FlowGraph, cg: ConcreteGraph) -> List[Finding]:
    """V010: homogeneous waves whose fusability certificate refuses for
    a STRUCTURAL reason (intra-wave dependency / intra-wave datum
    conflict).  One finding per affected class, counting its refused
    waves."""
    from .plan import certify_waves
    out: Dict[str, Finding] = {}
    for cert in certify_waves(fg, cg):
        if not cert.get("homogeneous") or not cert.get("structural"):
            continue
        cm = fg.by_name.get(cert["cls"])
        f = out.get(cert["cls"])
        if f is None:
            f = out[cert["cls"]] = Finding(
                "V010", "error", cert["cls"], None, None,
                getattr(cm.tc, "srcloc", None) if cm else None,
                "homogeneous wave fails its fusability certificate "
                f"structurally: {'; '.join(cert['reasons'])} — wave "
                "members execute in arbitrary order, so this is a "
                "latent race per-task and a certain corruption under "
                "wave fusion", count=0)
        f.count += 1
        if len(f.instances) < _MAX_SAMPLES:
            f.instances.append((cert["rank"], cert["wave"]))
    return list(out.values())


def _v009_rank_mapping(cg: ConcreteGraph) -> List[Finding]:
    """V009: a concretized instance whose SELECTED data input is a Mem
    read of a collection datum owned by a different rank than the one
    the instance executes on (placement affinity).  Unlike task
    deliveries — which ride the wire — a Mem IN has no transport: the
    consuming rank reads its local mirror buffer, which was never
    materialized (gemm_dist's docstring: memory reads must be affine
    with placement; the fix is a reader task placed AT the datum that
    forwards it as a task dependency)."""
    out: Dict[tuple, Finding] = {}
    fg = cg.fg
    for cm in fg.classes:
        if cm._aff_coll is None:
            continue  # placement unknowable: nothing provable
        mem_fis = [
            (fi, ) for fi in range(len(cm.flows))
            if not cm.is_ctl(fi)
            and any(d.direction == 0 and isinstance(d.target, Mem)
                    for d in cm.flows[fi].deps)]
        if not mem_fis:
            continue
        for params in cg.instances.get(cm.id, []):
            node = (cm.id, params)
            l = cm.fill_locals(params)
            trank = cm.rank_of_instance(l)
            if trank is None:
                continue
            for (fi, ) in mem_fis:
                di = cg.selected.get((node, fi))
                if di is None:
                    continue
                info = cm._dep_info[(fi, di)]
                if info["kind"] != "mem":
                    continue
                orank = cm.mem_owner_rank(fi, di, l)
                if orank is None or orank == trank:
                    continue
                key = (cm.id, fi, di)
                f = out.get(key)
                if f is None:
                    f = out[key] = Finding(
                        "V009", "error", cm.name, cm.flows[fi].name, di,
                        cm.dep_loc(fi, di),
                        f"memory read of {info['coll']!r} data owned "
                        "by another rank: the instance executes where "
                        "its affinity datum lives but this IN has no "
                        "wire path — the rank reads an uninitialized "
                        "local mirror.  Read it through a task placed "
                        "at the datum instead (gemm_dist ReadA/ReadB "
                        "pattern)", count=0)
                f.count += 1
                if len(f.instances) < _MAX_SAMPLES:
                    f.instances.append(params)
    return list(out.values())


# ================================================================ driver

def verify_graph(fg: FlowGraph, max_instances: int = 200_000,
                 ignore: Sequence[str] = ()) -> Report:
    """Run the V001-V010 rule engine over an extracted flow graph."""
    t0 = time.perf_counter()
    findings: List[Finding] = []
    notes: List[str] = []
    # symbolic rules (always available)
    findings += _v002_escape_guard_with_mem_fallback(fg)
    findings += _v008_coll_contract(fg)
    findings += _v007_dtype_shape(fg)
    sym_v004 = _v004_symbolic(fg)
    # concrete rules (bounded enumeration)
    cg = fg.concretize(max_instances=max_instances)
    notes += cg.notes
    if not cg.bounded:
        conc_v004 = _v004_concrete(cg)
        seen = {(f.cls, f.flow, f.dep) for f in conc_v004}
        findings += conc_v004
        findings += [f for f in sym_v004
                     if (f.cls, f.flow, f.dep) not in seen]
        findings += _v001_dangling_in(cg)
        findings += _v003_cycles(cg)
        findings += _v005_write_races(cg)
        findings += _v006_never_read_out(cg)
        findings += _v009_rank_mapping(cg)
        findings += _v010_wave_fusability(fg, cg)
    else:
        findings += sym_v004
        notes.append("instance-level rules (V001/V003/V005/V006/V009/"
                     "V010) skipped: raise max_instances to enable")
    if ignore:
        ign = set(ignore)
        findings = [f for f in findings if f.rule not in ign]
    stats = {
        "classes": len(fg.classes),
        "instances": cg.nb_instances(),
        "edges": cg.nb_edges,
        "bounded": cg.bounded,
        "elapsed_ms": (time.perf_counter() - t0) * 1e3,
    }
    return Report(findings, notes, stats), cg


def verify_taskpool(tp, max_instances: int = 200_000,
                    ignore: Sequence[str] = ()) -> Report:
    """Extract + verify a Taskpool's task-class tables (nothing is
    executed).  Returns the Report."""
    fg = extract_flowgraph(tp)
    report, _cg = verify_graph(fg, max_instances=max_instances,
                               ignore=ignore)
    return report
