"""ptc-plan: static resource & schedule analysis over PTG flow graphs.

PTG's problem-size-independent symbolic task graph makes ahead-of-time
quantitative analysis possible — the feasibility question the TPU
distributed-LA work poses ("does the working set fit?", arXiv:2112.09017)
can be answered before anything runs.  This module computes, from the
PR 8 `flowgraph` extraction (engine-exact concretized instance DAG):

  liveness    per-rank peak tile residency in bytes, two numbers per
              rank over a topological wave schedule:
                peak_bytes       the no-eviction working set (what the
                                 device's LRU actually holds when under
                                 budget — the ground-truth-matching
                                 "predicted peak" of the plan-vs-measured
                                 tests)
                live_peak_bytes  the interval-liveness lower bound (the
                                 residency NO schedule can avoid: when
                                 even this exceeds the budget, spilling
                                 is certain, not just likely)
              plus the wave decomposition itself — ready fronts grouped
              by task class per rank, the fusable-wave artifact ROADMAP
              item 2's mega-kernelization (MPK, arXiv:2512.22219) needs
  comm        per-rank and per-(src, dst) delivery-edge bytes from the
              rank mapping (affinity rank_of) of every concretized edge,
              deduplicated per (producer instance, flow, destination
              rank) exactly like the wire's per-rank activation fanout,
              split eager vs rendezvous at the fitted transfer-economics
              threshold (comm/economics.py)
  makespan    critical-path and work/p lower bounds under a per-class
              cost model seeded from the PR 7 always-on latency
              histograms (or a recorded JSON profile), reported next to
              the PR 5 *executed* critical path by tools/ptc_plan.py so
              predicted-vs-measured is a first-class regression signal

Two modes, like the verifier: exact bounded enumeration (default), and
a symbolic interval fallback for execution spaces past `max_instances`
— the residency bound degrades to per-class interval counting with an
explicit note, never a silent truncation.

Consumers: `Taskpool.plan()`, the serving front door's admission bytes
(serve/server.py: an unknown `est_bytes` falls back to the static
bound), the device pre-run `plan_check` (device.plan_check knob), and
the `tools/ptc_plan.py` CLI / `make plan-graphs` baseline.
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Tuple

from .. import _native as N
from .flowgraph import (ConcreteGraph, FlowGraph, collection_tile_bytes,
                        extract_flowgraph)

# modeled wire envelope per cross-rank message (frame header + dep
# payload descriptors + rendezvous GET/ACK round), and a static
# control-plane allowance per rank (hello/fence/clock-sync/metrics
# frames): the comm-volume *bound* must stay >= the measured per-rank
# bytes_sent, which counts those frames too
WIRE_ENVELOPE_BYTES = 512
WIRE_STATIC_BYTES = 256 * 1024

DEFAULT_TASK_NS = 1_000


class PlanCheckError(RuntimeError):
    """Raised by the device pre-run plan_check (device.plan_check=error)
    when the predicted device working set exceeds the byte budget and
    out-of-core execution is disabled — the run would pin HBM past
    budget until it OOMs."""


# ------------------------------------------------------------ cost model
class CostModel:
    """Per-class execution-cost model (nanoseconds per instance).

    Sources, best first: a live context's always-on per-class EXEC
    histograms (p50 — `from_context`), a recorded JSON profile
    (`from_json`: {"classes": {name: ns}} or a flat {name: ns}), or the
    uniform default.  `source` names where the numbers came from so a
    plan's makespan bound is auditable."""

    def __init__(self, costs: Optional[Dict[str, float]] = None,
                 default_ns: float = DEFAULT_TASK_NS,
                 source: str = "uniform"):
        self.costs = dict(costs or {})
        self.default_ns = float(default_ns)
        self.source = source

    def ns(self, cls_name: str) -> float:
        return self.costs.get(cls_name, self.default_ns)

    @classmethod
    def from_context(cls, ctx, merged: bool = False) -> Optional["CostModel"]:
        """Seed from the PR 7 metrics histograms: per-class EXEC p50.
        None when no class has samples yet (cold context)."""
        try:
            hists = ctx.metrics_histograms(merged=merged)
        except Exception:
            return None
        costs = {h.name: h.quantile(0.50) for h in hists
                 if h.kind == N.MET_EXEC and h.name and h.count > 0}
        costs = {k: v for k, v in costs.items() if v > 0}
        if not costs:
            return None
        med = sorted(costs.values())[len(costs) // 2]
        return cls(costs, default_ns=med, source="metrics")

    @classmethod
    def from_json(cls, path: str) -> "CostModel":
        """Load a recorded profile: {"classes": {name: ns}, ...} (the
        ptc_plan --profile schema) or a flat {name: ns} mapping."""
        with open(path) as f:
            doc = json.load(f)
        costs = doc.get("classes", doc) if isinstance(doc, dict) else {}
        costs = {str(k): float(v) for k, v in costs.items()
                 if isinstance(v, (int, float)) and v > 0}
        default = float(doc.get("default_ns", DEFAULT_TASK_NS)) \
            if isinstance(doc, dict) else DEFAULT_TASK_NS
        return cls(costs, default_ns=default, source=path)

    def recalibrated(self, ratios: Dict[str, float],
                     fallback: float = 1.0) -> "CostModel":
        """ptc-pilot: fold live measured/planned calibration ratios
        (scope conformance `per_class[cls]["ratio"]`) into a NEW model
        — each named class's cost scales by its ratio, classes without
        a live ratio (and the default) scale by `fallback` (typically
        the window's median makespan ratio).  The original is never
        mutated: the planner that produced it may still be in use."""
        fb = max(0.0, float(fallback)) or 1.0
        costs = {cls: ns * max(0.0, float(ratios.get(cls, fb)) or fb)
                 for cls, ns in self.costs.items()}
        return CostModel(costs, default_ns=self.default_ns * fb,
                         source=f"{self.source}+recalibrated")

    def to_json(self) -> dict:
        return {"source": self.source, "default_ns": self.default_ns,
                "classes": dict(self.costs)}


def _eager_threshold(ctx, econ=None) -> int:
    """The eager/rendezvous split the comm volume analysis models:
    the live engine's effective threshold when comm is up, else the
    fitted transfer-economics crossover (falling back to the static
    comm.eager_limit param)."""
    try:
        if getattr(ctx, "comm_enabled", False):
            lim = int(ctx.comm_tuning()["eager_limit"])
            if lim > 0:
                return lim
    except Exception:
        pass
    from ..utils import params as _mca
    try:
        fallback = int(_mca.get("comm.eager_limit"))
    except (TypeError, ValueError):
        fallback = 64 * 1024
    if econ is None:
        from ..comm.economics import default_economics
        econ = default_economics()
    return econ.eager_threshold(fallback)


# ------------------------------------------------------------------ plan
class Plan:
    """One pool's static resource & schedule analysis result."""

    def __init__(self, fg: FlowGraph):
        self.fg = fg
        self.cg: Optional[ConcreteGraph] = None
        self.bounded = False          # True = symbolic fallback
        self.notes: List[str] = []
        self.stats: Dict[str, object] = {}
        # per-rank rows: tasks, work_ns, peak_bytes, live_peak_bytes,
        # device_{peak,live_peak}_bytes, comm_{out,in}_bytes,
        # comm_out_msgs, eager_bytes, rdv_bytes, wire_out_bound
        self.per_rank: Dict[int, Dict[str, int]] = {}
        self.edges_bytes: Dict[Tuple[int, int], int] = {}
        self.edges_msgs: Dict[Tuple[int, int], int] = {}
        # collective sub-matrix: (src, dst) -> {"bytes", "msgs"} for
        # edges whose producer is a ptc_coll_* chain class (ptc-shard:
        # the embedded tensor-parallel reduction legs, costed per link
        # class by coll_legs())
        self.coll_edges: Dict[Tuple[int, int], Dict[str, int]] = {}
        # per-rank wave tables: rank -> [{"wave", "tasks", "classes"}]
        self.waves: Dict[int, List[dict]] = {}
        # wave-fusability certificates: one record per (rank, wave) —
        # the MPK-prep artifact (ROADMAP item 1): an explicit
        # certify/refuse verdict for every wave, machine-readable
        self.fusability: List[dict] = []
        # wave-chain certificates: one record per adjacent pair of
        # certified waves — `linked` proves the producer wave feeds the
        # consumer wave rank-locally with matching tile signatures, so
        # the device wave compiler (device/fuse.py) may compile both
        # into ONE multi-wave executable; refusals carry reasons
        self.chains: List[dict] = []
        # rank -> (producer cls, params) -> [consumer link dicts]; the
        # runtime consumption side of the chain certificates (see
        # chain_index())
        self._chain_links: Dict[int, Dict[tuple, list]] = {}
        self._chain_classes: Dict[str, dict] = {}
        self.makespan: Dict[str, object] = {}
        self.eager_limit = 0
        self.has_device_classes = False
        # internal: spill-simulation inputs (concrete mode only)
        self._touch: Dict[Tuple[int, str], Dict[object, List[int]]] = {}
        self._dirty_from: Dict[Tuple[int, str], Dict[object, int]] = {}
        self._persistent: Dict[object, bool] = {}
        self._datum_bytes: Dict[object, int] = {}
        self._symbolic_peak: Optional[int] = None

    # ----------------------------------------------------------- queries
    def ranks(self) -> List[int]:
        return sorted(self.per_rank)

    def peak_bytes(self, rank: Optional[int] = None,
                   device_only: bool = False) -> Optional[int]:
        """Predicted peak residency in bytes: the no-eviction working
        set (max over ranks when `rank` is None).  device_only=True
        restricts to data touched by device-chore classes (what the
        device cache actually stages)."""
        key = "device_peak_bytes" if device_only else "peak_bytes"
        if self.bounded:
            return self._symbolic_peak
        rows = ([self.per_rank[rank]] if rank is not None
                else list(self.per_rank.values()))
        if not rows:
            return 0
        return max(r[key] for r in rows)

    def live_peak_bytes(self, rank: Optional[int] = None,
                        device_only: bool = False) -> Optional[int]:
        """Interval-liveness lower bound on residency (the bytes no
        schedule can avoid holding simultaneously)."""
        if self.bounded:
            return None
        key = ("device_live_peak_bytes" if device_only
               else "live_peak_bytes")
        rows = ([self.per_rank[rank]] if rank is not None
                else list(self.per_rank.values()))
        if not rows:
            return 0
        return max(r[key] for r in rows)

    def est_bytes(self, discount_bytes: int = 0,
                  rank: Optional[int] = None) -> Optional[int]:
        """Admission-control byte estimate: the pool's global working
        set (sum of per-rank peaks — every rank holds its own mirrors).
        None only when the symbolic fallback could not bound it.

        `discount_bytes` subtracts working-set bytes the caller knows
        are ALREADY resident and shared (ptc-share: prompt pages
        predicted to map onto frozen prefix-cache pages cost admission
        nothing); the estimate never discounts below 1 byte, so a
        known bound stays distinguishable from the <=0 UNKNOWN
        sentinel serve admission uses.

        `rank` restricts the estimate to ONE rank's peak (ptc-shard:
        a tensor-parallel pool holds 1/R of the weights and KV pages
        per rank, so per-rank admission must not be charged the global
        sum — each rank's server admits against its own residency)."""
        if self.bounded:
            total = self._symbolic_peak
        elif rank is not None:
            row = self.per_rank.get(rank)
            total = row["peak_bytes"] if row is not None else 0
        else:
            total = sum(r["peak_bytes"] for r in self.per_rank.values())
        if total is None:
            return None
        disc = max(0, int(discount_bytes or 0))
        if disc and total > 0:
            total = max(1, total - disc)
        return total

    def comm_bytes(self) -> int:
        return sum(self.edges_bytes.values())

    def fusable_waves(self, rank: Optional[int] = None) -> int:
        """Number of waves certified fusable (one cached executable per
        wave, à la MPK): homogeneous, fusion-eligible bodies, no
        intra-wave dependency or datum conflict, matching tile
        signatures."""
        return sum(1 for c in self.fusability
                   if c["fusable"] and (rank is None or c["rank"] == rank))

    def chained_waves(self, rank: Optional[int] = None) -> int:
        """Number of certified chain LINKS (adjacent wave pairs the
        device wave compiler may fuse into one multi-wave executable)."""
        return sum(1 for c in self.chains
                   if c["linked"] and (rank is None or c["rank"] == rank))

    def chain_index(self, rank: int = 0) -> dict:
        """Certificate-consumption view for the device wave compiler:

          classes  {cls_name: {"id", "param_slots"}} — param_slots are
                   the native local-variable indices whose values form
                   the instance key (the same tuple order the
                   concretized graph uses), so the device can key a
                   LIVE task to its certificate lane with a handful of
                   ptc_task_local reads
          links    {(producer cls, params): [consumer dicts]} for this
                   rank; each consumer dict carries its class, params
                   and per-read-flow input spec:
                     ("wave", producer_params, producer_flow) — comes
                        from the producer wave's output (in-program)
                     ("mem", collection, idx) — an external collection
                        tile, fetchable at speculation time

        Every spec is STATIC; the runtime re-validates all of it
        against live copy versions at consumption, so a stale index can
        only cost a wasted speculation, never a wrong answer."""
        return {"classes": dict(self._chain_classes),
                "links": dict(self._chain_links.get(rank, {}))}

    def wire_out_bound(self, rank: int,
                       cls: Optional[str] = None) -> int:
        """Upper bound on the rank's wire bytes_sent: payload out plus
        the modeled per-message envelope and static control-plane
        allowance.  With `cls` (ptc-topo link class: "host"/"ici"/
        "dcn") only the edges of that class count — the per-class bound
        the topo soak checks against the measured per-class split."""
        if cls is not None:
            tmodel = self._tmodel()
            payload = msgs = 0
            for (s, d), b in self.edges_bytes.items():
                if s == rank and tmodel.class_of(s, d) == cls:
                    payload += b
                    msgs += self.edges_msgs.get((s, d), 0)
            return payload + msgs * WIRE_ENVELOPE_BYTES \
                + WIRE_STATIC_BYTES
        row = self.per_rank.get(rank)
        if row is None:
            return WIRE_STATIC_BYTES
        return (row["comm_out_bytes"]
                + row["comm_out_msgs"] * WIRE_ENVELOPE_BYTES
                + WIRE_STATIC_BYTES)

    # --------------------------------------------- topology (ptc-topo)
    def _nranks_hint(self) -> int:
        n = 0
        for r in self.per_rank:
            n = max(n, int(r) + 1)
        for (s, d) in self.edges_bytes:
            n = max(n, int(s) + 1, int(d) + 1)
        return n

    def _tmodel(self, tmodel=None):
        if tmodel is not None:
            return tmodel
        from ..comm.topology import default_topology
        return default_topology(self._nranks_hint())

    def class_bytes(self, tmodel=None,
                    perm: Optional[List[int]] = None) -> Dict[str, int]:
        """The comm volume split by link class over the exact
        per-(src, dst) traffic matrix.  `perm` (a rank_of remap,
        perm[logical] = physical) reclasses every edge as if the pool
        ran under that mapping — the objective remap_ranks minimizes."""
        from ..comm.topology import LINK_CLASSES
        tm = self._tmodel(tmodel)
        out = {c: 0 for c in LINK_CLASSES}
        for (s, d), b in self.edges_bytes.items():
            ps = perm[s] if perm and s < len(perm) else s
            pd = perm[d] if perm and d < len(perm) else d
            out[tm.class_of(ps, pd)] += b
        return out

    def dcn_bytes(self, tmodel=None,
                  perm: Optional[List[int]] = None) -> int:
        """Predicted inter-island payload bytes (the slow-network spend
        the topo tier exists to shrink)."""
        return self.class_bytes(tmodel, perm)["dcn"]

    def coll_bytes(self) -> int:
        """Total payload bytes carried by ptc_coll_* chain edges (the
        embedded collective's share of comm_bytes())."""
        return sum(r["bytes"] for r in self.coll_edges.values())

    def coll_legs(self, tmodel=None, econ=None) -> List[dict]:
        """Classed collective legs (ptc-shard): one record per
        (src, dst) wire edge produced by a ptc_coll_* chain class,
        carrying its ptc-topo link class and the modeled wire cost
        under the PR 17 transfer economics —

          {"src", "dst", "cls", "bytes", "msgs", "cost_us"}

        cost_us = (msgs * alpha("rdv", cls) + bytes * beta("rdv", cls))
        in microseconds (rdv mode — coll chunks stream large segments).
        Sorted most-expensive-first, so the top row is the leg a
        topology remap or chunk-size retune should attack.  Empty when
        the pool embeds no collective."""
        if not self.coll_edges:
            return []
        tm = self._tmodel(tmodel)
        if econ is None:
            from ..comm.economics import default_economics
            econ = default_economics()
        legs = []
        for (s, d), r in sorted(self.coll_edges.items()):
            cls = tm.class_of(s, d)
            cost = (r["msgs"] * econ.alpha("rdv", cls)
                    + r["bytes"] * econ.beta("rdv", cls)) * 1e6
            legs.append({"src": s, "dst": d, "cls": cls,
                         "bytes": r["bytes"], "msgs": r["msgs"],
                         "cost_us": float(cost)})
        legs.sort(key=lambda g: -g["cost_us"])
        return legs

    def _perm_cost(self, perm: List[int], tmodel, econ) -> float:
        """Modeled wire seconds of the traffic matrix under `perm`:
        per-edge classed alpha (per message) + beta (per byte)."""
        tot = 0.0
        for (s, d), b in self.edges_bytes.items():
            if s >= len(perm) or d >= len(perm):
                continue
            cls = tmodel.class_of(perm[s], perm[d])
            if cls == "loopback":
                continue
            m = self.edges_msgs.get((s, d), 1)
            tot += (m * econ.alpha("rdv", cls) * 1e-6
                    + b * econ.beta("rdv", cls) * 1e-9)
        return tot

    def remap_ranks(self, tmodel=None, econ=None) -> List[int]:
        """Search rank_of permutations (perm[logical] = physical) that
        minimize the modeled classed wire cost of the EXACT traffic
        matrix — in practice: keep chatty logical ranks inside one ICI
        island so the DCN carries as little as possible.

        Greedy constructive seed (assign logical ranks, heaviest
        talkers first, to the island holding their traffic) followed by
        island-aware pairwise-swap refinement; the identity mapping is
        always a candidate, so the result never predicts worse than
        not remapping.  Returns the identity permutation when the
        topology is flat or no permutation helps — callers can compare
        against list(range(n)) to decide whether to install it
        (Taskpool.run(remap=...), ctx.set_rank_map)."""
        tm = self._tmodel(tmodel)
        n = max(self._nranks_hint(), tm.nranks)
        ident = list(range(n))
        if tm.n_islands <= 1 or n <= 1 or not self.edges_bytes \
                or n > tm.nranks:
            return ident
        if econ is None:
            from ..comm.economics import default_economics
            econ = default_economics()
        sym: Dict[Tuple[int, int], float] = {}
        deg = [0.0] * n
        for (s, d), b in self.edges_bytes.items():
            if s == d or s >= n or d >= n:
                continue
            k = (min(s, d), max(s, d))
            sym[k] = sym.get(k, 0.0) + b
            deg[s] += b
            deg[d] += b
        # greedy: heaviest talkers first, each into the island where
        # its already-placed traffic lives (ties: most free slots)
        slots = [list(tm.island_ranks(i)) for i in range(tm.n_islands)]
        free = [len(sl) for sl in slots]
        isl_of_logical: Dict[int, int] = {}
        assign: Dict[int, int] = {}
        for l in sorted(range(n), key=lambda x: -deg[x]):
            best_i, best_aff = -1, -1.0
            for i in range(tm.n_islands):
                if free[i] <= 0:
                    continue
                aff = sum(sym.get((min(l, o), max(l, o)), 0.0)
                          for o, oi in isl_of_logical.items() if oi == i)
                if aff > best_aff or (aff == best_aff and best_i >= 0
                                      and free[i] > free[best_i]):
                    best_i, best_aff = i, aff
            isl_of_logical[l] = best_i
            assign[l] = slots[best_i][len(slots[best_i]) - free[best_i]]
            free[best_i] -= 1
        greedy = [assign[l] for l in range(n)]

        def refine(perm: List[int]) -> Tuple[List[int], float]:
            perm = list(perm)
            cost = self._perm_cost(perm, tm, econ)
            for _ in range(2 * n):
                improved = False
                for i in range(n):
                    for j in range(i + 1, n):
                        if tm.island_of(perm[i]) == tm.island_of(perm[j]):
                            continue  # island-aware: only DCN-moving swaps
                        perm[i], perm[j] = perm[j], perm[i]
                        c = self._perm_cost(perm, tm, econ)
                        if c < cost - 1e-15:
                            cost, improved = c, True
                        else:
                            perm[i], perm[j] = perm[j], perm[i]
                if not improved:
                    break
            return perm, cost

        cand = [refine(ident), refine(greedy)]
        ident_cost = self._perm_cost(ident, tm, econ)
        best, best_cost = min(cand, key=lambda pc: pc[1])
        if best_cost >= ident_cost - 1e-15:
            return ident
        return best

    # ------------------------------------------------- spill prediction
    def predict_spills(self, cache_bytes: int, rank: int = 0,
                       device_only: bool = True) -> int:
        """Predicted spill count for running this pool on `rank` under
        a device byte budget: a greedy wave-order residency simulation
        (furthest-next-use eviction, the planner's clean-first order).
        A spill is an eviction of a datum written earlier on this rank
        and backed by a collection (dirty persistent mirror -> d2h
        write-back), exactly what device_stats counts as `spills`.
        0 when the working set fits."""
        if self.bounded:
            return 0
        key = (rank, "device" if device_only else "all")
        touch = self._touch.get(key)
        if not touch:
            return 0
        dirty_from = self._dirty_from.get(key, {})
        budget = max(0, int(cache_bytes))
        by_wave: Dict[int, List[object]] = {}
        for d, ws in touch.items():
            for w in ws:
                by_wave.setdefault(w, []).append(d)
        resident: Dict[object, int] = {}   # datum -> next-use wave (-1 end)
        used = 0
        spills = 0

        def is_dirty(d, w) -> bool:
            wrote = dirty_from.get(d)
            return (wrote is not None and wrote <= w
                    and self._persistent.get(d, False))

        for w in sorted(by_wave):
            needed = by_wave[w]
            for d in needed:
                if d not in resident:
                    used += self._datum_bytes.get(d, 0)
                ws = touch[d]
                later = [x for x in ws if x > w]
                resident[d] = later[0] if later else -1
            if used <= budget:
                continue
            # over budget: evict idle datums first (furthest next use,
            # never-again first); a dirty persistent eviction is a
            # spill (d2h write-back), a clean one is free
            needed_set = set(needed)
            order = sorted(
                (d for d in resident if d not in needed_set),
                key=lambda d: (resident[d] != -1, -resident[d]))
            for d in order:
                if used <= budget:
                    break
                used -= self._datum_bytes.get(d, 0)
                if is_dirty(d, w):
                    spills += 1
                del resident[d]
            if used <= budget:
                continue
            # the wave's own footprint exceeds the budget: execution
            # degrades to panel-cyclic within the wave — tiles cycle
            # through the cache, and every dirty one past the horizon
            # must write back at least once.  Clean-first order mirrors
            # the device's eviction preference.
            order = sorted(needed_set & set(resident),
                           key=lambda d: is_dirty(d, w))
            for d in order:
                if used <= budget:
                    break
                used -= self._datum_bytes.get(d, 0)
                if is_dirty(d, w):
                    spills += 1
                del resident[d]
        return spills

    # ------------------------------------------------------------ output
    def to_json(self) -> dict:
        return {
            "bounded": self.bounded,
            "notes": list(self.notes),
            "stats": dict(self.stats),
            "per_rank": {str(r): dict(row)
                         for r, row in self.per_rank.items()},
            "edges_bytes": {f"{s}->{d}": b
                            for (s, d), b in self.edges_bytes.items()},
            "waves": {str(r): [dict(w) for w in ws]
                      for r, ws in self.waves.items()},
            "fusability": [dict(c) for c in self.fusability],
            "fusable_waves": self.fusable_waves(),
            "chains": [dict(c) for c in self.chains],
            "chained_waves": self.chained_waves(),
            "makespan": dict(self.makespan),
            "comm": {
                "total_bytes": self.comm_bytes(),
                "eager_limit": self.eager_limit,
                "coll_bytes": self.coll_bytes(),
                "coll_edges": {f"{s}->{d}": dict(r)
                               for (s, d), r in self.coll_edges.items()},
            },
            "est_bytes": self.est_bytes(),
        }

    def wave_table(self, rank: int = 0, max_rows: int = 32) -> str:
        """Per-wave text table: tasks, classes, live bytes, and the
        fusability verdict (see `fusability` for refusal reasons)."""
        ws = self.waves.get(rank, [])
        fus = {(c["rank"], c["wave"]): c for c in self.fusability}
        lines = [f"{'wave':>5} {'tasks':>6} {'live_bytes':>12} "
                 f"{'fusable':>8}  classes"]
        for row in ws[:max_rows]:
            classes = ", ".join(f"{c}x{n}" for c, n in
                                sorted(row["classes"].items()))
            c = fus.get((rank, row["wave"]))
            verdict = ("-" if c is None
                       else "yes" if c["fusable"] else "no")
            lines.append(f"{row['wave']:>5} {row['tasks']:>6} "
                         f"{row['live_bytes']:>12} {verdict:>8}  "
                         f"{classes}")
        if len(ws) > max_rows:
            lines.append(f"  ... {len(ws) - max_rows} more wave(s)")
        return "\n".join(lines)

    def text(self, waves: bool = False) -> str:
        s = self.stats
        lines = [
            f"ptc-plan: {s.get('classes', 0)} class(es), "
            f"{s.get('instances', 0)} instance(s), "
            f"{s.get('edges', 0)} edge(s), "
            f"{s.get('waves', 0)} wave(s) "
            f"[{s.get('elapsed_ms', 0):.0f} ms]"
            + (" [SYMBOLIC: enumeration refused]" if self.bounded
               else "")]
        if self.bounded:
            peak = (self._symbolic_peak if self._symbolic_peak is not None
                    else "unbounded")
            lines.append(f"  peak residency bound (interval): {peak} B")
        for r in self.ranks():
            row = self.per_rank[r]
            lines.append(
                f"  rank {r}: {row['tasks']} task(s), "
                f"peak {row['peak_bytes']} B "
                f"(liveness floor {row['live_peak_bytes']} B"
                + (f", device {row['device_peak_bytes']} B"
                   if self.has_device_classes else "")
                + f"), comm out {row['comm_out_bytes']} B"
                f"/{row['comm_out_msgs']} msg(s) "
                f"(eager {row['eager_bytes']} B, rdv {row['rdv_bytes']} B)"
                f", work {row['work_ns'] / 1e6:.3f} ms")
        if self.fusability:
            nfus = self.fusable_waves()
            lines.append(
                f"  fusable waves: {nfus}/{len(self.fusability)} "
                "certified (homogeneous, independent, table-driven "
                "bodies, one tile signature)")
        if self.chains:
            lines.append(
                f"  chained waves: {self.chained_waves()}/"
                f"{len(self.chains)} adjacent certified pairs linked "
                "(producer wave feeds consumer wave rank-locally, "
                "matching tile signatures — multi-wave fusable)")
        m = self.makespan
        if m:
            lines.append(
                f"  makespan lower bound: {m['lower_bound_ns'] / 1e6:.3f} ms "
                f"(critical path {m['critical_path_ns'] / 1e6:.3f} ms over "
                f"{m['path_len']} task(s), work/p {m['work_ns'] / 1e6:.3f} ms; "
                f"cost model: {m['cost_source']})")
        for (sr, dr), b in sorted(self.edges_bytes.items()):
            lines.append(f"  edge {sr} -> {dr}: {b} B")
        for n in self.notes:
            lines.append(f"  note: {n}")
        if waves and not self.bounded:
            for r in self.ranks():
                lines.append(f"-- waves, rank {r}:")
                lines.append(self.wave_table(r))
        return "\n".join(lines)


# ------------------------------------------------------------- analysis
def _has_device_chore(tc) -> bool:
    return any(getattr(ch, "body_kind", None) == N.BODY_DEVICE
               for ch in getattr(tc, "chores", []))


def _chore_kinds(tc) -> List[str]:
    """Body kinds of a class, certificate-facing: "noop" / "device" /
    "pure-cb" (a Python body the author declared pure) / "opaque-cb"."""
    out = []
    for ch in getattr(tc, "chores", []):
        bk = getattr(ch, "body_kind", None)
        if bk == N.BODY_NOOP:
            out.append("noop")
        elif bk == N.BODY_DEVICE:
            out.append("device")
        elif getattr(ch, "pure", False):
            out.append("pure-cb")
        else:
            out.append("opaque-cb")
    return out


def _is_write(access: int) -> bool:
    return access in (N.FLOW_WRITE, N.FLOW_RW)


class _Analyzer:
    """One-shot concrete analysis over a concretized flow graph."""

    def __init__(self, fg: FlowGraph, cg: ConcreteGraph, plan: Plan):
        self.fg = fg
        self.cg = cg
        self.plan = plan
        self.rank_of: Dict[tuple, int] = {}
        self.wave: Dict[tuple, int] = {}
        self.datum: Dict[tuple, object] = {}   # (node, fi) -> datum key
        self.inst_set = {(cid, params)
                         for cid, plist in cg.instances.items()
                         for params in plist}
        self._locals: Dict[tuple, list] = {}
        self._unknown_rank_note = False

    def locals_of(self, node) -> list:
        l = self._locals.get(node)
        if l is None:
            cm = self.fg.classes[node[0]]
            l = self._locals[node] = cm.fill_locals(node[1])
        return l

    # --------------------------------------------------------- rank map
    def _rank(self, node) -> int:
        r = self.rank_of.get(node)
        if r is None:
            cm = self.fg.classes[node[0]]
            r = cm.rank_of_instance(self.locals_of(node))
            if r is None:
                r = 0
                if not self._unknown_rank_note:
                    self._unknown_rank_note = True
                    self.plan.notes.append(
                        f"class {cm.name}: no statically-evaluable "
                        "placement affinity; instances assumed rank 0")
            self.rank_of[node] = r
        return r

    # ------------------------------------------------------------ waves
    def compute_waves(self) -> int:
        preds: Dict[tuple, List[tuple]] = {}
        indeg: Dict[tuple, int] = {n: 0 for n in self.inst_set}
        for src, outs in self.cg.succ.items():
            for dst, _certain in outs:
                if dst in indeg:
                    indeg[dst] += 1
                    preds.setdefault(dst, []).append(src)
        ready = [n for n in self.inst_set if indeg[n] == 0]
        for n in ready:
            self.wave[n] = 0
        seen = 0
        while ready:
            n = ready.pop()
            seen += 1
            w = self.wave[n]
            for dst, _certain in self.cg.succ.get(n, ()):
                if dst not in indeg:
                    continue
                if w + 1 > self.wave.get(dst, -1):
                    self.wave[dst] = w + 1
                indeg[dst] -= 1
                if indeg[dst] == 0:
                    ready.append(dst)
        if seen != len(self.inst_set):
            # cyclic graph (a V003 finding): park the unreached tail one
            # wave past the end so the analysis still terminates
            tail = 1 + max(self.wave.values(), default=0)
            for n in self.inst_set:
                self.wave.setdefault(n, tail)
            self.plan.notes.append(
                f"{len(self.inst_set) - seen} instance(s) sit on a "
                "dependency cycle (see ptc-verify V003); scheduled "
                "past the final wave for analysis purposes")
        return 1 + max(self.wave.values(), default=-1)

    # ------------------------------------------------------ datum chains
    def datum_of(self, node, fi) -> object:
        """Root datum of (instance, flow): the collection datum the
        version chain bottoms out in, or a per-(instance, flow)
        temporary (arena copy).  Mirrors the engine's copy flow: an In
        from Mem reads the collection datum, an In from a task reads
        the producer's output copy (recursively), a pure-output flow
        births a fresh arena copy."""
        key = (node, fi)
        memo = self.datum
        stack = [key]
        on_stack = set(stack)
        while stack:
            cur = stack[-1]
            if cur in memo:
                on_stack.discard(cur)
                stack.pop()
                continue
            cnode, cfi = cur
            cm = self.fg.classes[cnode[0]]
            di = self.cg.selected.get(cur)
            if di is None:
                memo[cur] = ("tmp", cnode, cfi)
                on_stack.discard(cur)
                stack.pop()
                continue
            info = cm._dep_info[(cfi, di)]
            if info["kind"] == "mem":
                l = self.locals_of(cnode)
                idx = tuple(fn(l) for fn in info["idx"])
                memo[cur] = ("mem", info["coll"], idx)
                on_stack.discard(cur)
                stack.pop()
                continue
            if info["kind"] != "task":  # In(None): fresh arena copy
                memo[cur] = ("tmp", cnode, cfi)
                on_stack.discard(cur)
                stack.pop()
                continue
            # task source: resolve the producer instance
            peer = self.fg.by_name.get(info["peer"])
            pfi = cm.peer_flow_index(cfi, di)
            pnode = None
            if peer is not None and pfi is not None:
                l = self.locals_of(cnode)
                try:
                    vals = tuple(fn(l) for kind, fn in info["params"]
                                 if kind == "scalar")
                    if len(vals) == len(info["params"]):
                        cand = (peer.id, vals)
                        if cand in self.inst_set:
                            pnode = cand
                except Exception:
                    pnode = None
            if pnode is None:
                memo[cur] = ("tmp", cnode, cfi)
                on_stack.discard(cur)
                stack.pop()
                continue
            parent = (pnode, pfi)
            if parent in memo:
                memo[cur] = memo[parent]
                on_stack.discard(cur)
                stack.pop()
                continue
            if parent in on_stack:  # chain cycle: break with a temp
                memo[cur] = ("tmp", cnode, cfi)
                on_stack.discard(cur)
                stack.pop()
                continue
            stack.append(parent)
            on_stack.add(parent)
        return memo[key]

    def datum_bytes(self, datum, node, fi) -> int:
        plan = self.plan
        b = plan._datum_bytes.get(datum)
        if b is not None:
            return b
        fg = self.fg
        if datum[0] == "mem":
            coll = fg.collection_objs.get(datum[1])
            b = collection_tile_bytes(coll)
            plan._persistent[datum] = True
        else:
            cm = fg.classes[datum[1][0]]
            arena = cm.flows[datum[2]].arena
            b = fg.arena_sizes.get(arena) if arena else None
            plan._persistent[datum] = False
        if b is None:
            # last resort: the consuming flow's arena, else 0 + note
            cm = fg.classes[node[0]]
            arena = cm.flows[fi].arena
            b = fg.arena_sizes.get(arena, 0) if arena else 0
            if b == 0:
                nm = (datum[1] if datum[0] == "mem"
                      else fg.classes[datum[1][0]].name)
                note = (f"payload bytes unknown for data rooted at "
                        f"{nm!r}; counted as 0")
                if note not in plan.notes:
                    plan.notes.append(note)
        plan._datum_bytes[datum] = int(b)
        return int(b)

    # -------------------------------------------------------- residency
    def run(self, cost: CostModel, eager_limit: int, workers: int):
        fg, cg, plan = self.fg, self.cg, self.plan
        n_waves = self.compute_waves()
        plan.eager_limit = eager_limit
        plan.has_device_classes = any(_has_device_chore(cm.tc)
                                      for cm in fg.classes)
        dev_cls = {cm.id for cm in fg.classes if _has_device_chore(cm.tc)}

        # (rank, scope) -> datum -> [touch waves];  scope "all"|"device"
        touch: Dict[Tuple[int, str], Dict[object, List[int]]] = {}
        dirty_from: Dict[Tuple[int, str], Dict[object, int]] = {}
        # per-rank per-wave class counts
        wave_rows: Dict[int, Dict[int, Dict[str, int]]] = {}
        work_ns: Dict[int, float] = {}
        tasks: Dict[int, int] = {}

        for node in self.inst_set:
            cid = node[0]
            cm = fg.classes[cid]
            r = self._rank(node)
            w = self.wave[node]
            tasks[r] = tasks.get(r, 0) + 1
            work_ns[r] = work_ns.get(r, 0.0) + cost.ns(cm.name)
            wr = wave_rows.setdefault(r, {}).setdefault(w, {})
            wr[cm.name] = wr.get(cm.name, 0) + 1
            scopes = [("all", True), ("device", cid in dev_cls)]
            for fi, fl in enumerate(cm.flows):
                if fl.access == N.FLOW_CTL:
                    continue
                datum = self.datum_of(node, fi)
                self.datum_bytes(datum, node, fi)
                for scope, active in scopes:
                    if not active:
                        continue
                    key = (r, scope)
                    touch.setdefault(key, {}).setdefault(
                        datum, []).append(w)
                    if _is_write(fl.access):
                        df = dirty_from.setdefault(key, {})
                        if w < df.get(datum, 1 << 60):
                            df[datum] = w

        for key, tmap in touch.items():
            for d in tmap:
                tmap[d] = sorted(set(tmap[d]))
        plan._touch = touch
        plan._dirty_from = dirty_from

        # liveness sweep per (rank, scope): interval [wmin, wmax]
        def live_curve(key) -> List[int]:
            ev = [0] * (n_waves + 1)
            for d, ws in touch.get(key, {}).items():
                b = plan._datum_bytes.get(d, 0)
                ev[ws[0]] += b
                ev[ws[-1] + 1] -= b
            out, cur = [], 0
            for w in range(n_waves):
                cur += ev[w]
                out.append(cur)
            return out

        ranks = sorted(set(tasks) | {0})
        for r in ranks:
            all_curve = live_curve((r, "all"))
            dev_curve = live_curve((r, "device"))
            total = sum(plan._datum_bytes.get(d, 0)
                        for d in touch.get((r, "all"), {}))
            dev_total = sum(plan._datum_bytes.get(d, 0)
                            for d in touch.get((r, "device"), {}))
            plan.per_rank[r] = {
                "tasks": tasks.get(r, 0),
                "work_ns": int(work_ns.get(r, 0)),
                "peak_bytes": total,
                "live_peak_bytes": max(all_curve, default=0),
                "device_peak_bytes": dev_total,
                "device_live_peak_bytes": max(dev_curve, default=0),
                "comm_out_bytes": 0, "comm_in_bytes": 0,
                "comm_out_msgs": 0, "eager_bytes": 0, "rdv_bytes": 0,
            }
            rows = []
            for w in sorted(wave_rows.get(r, {})):
                classes = wave_rows[r][w]
                rows.append({
                    "wave": w,
                    "tasks": sum(classes.values()),
                    "classes": dict(classes),
                    "homogeneous": len(classes) == 1,
                    "live_bytes": all_curve[w] if w < len(all_curve)
                    else 0,
                })
            plan.waves[r] = rows

        plan.fusability = self.certify()
        plan.chains = self.certify_chains(plan.fusability)
        fus = {(c["rank"], c["wave"]): c for c in plan.fusability}
        for r, rows in plan.waves.items():
            for row in rows:
                c = fus.get((r, row["wave"]))
                if c is not None:
                    row["fusable"] = c["fusable"]

        self._comm_volume(eager_limit)
        self._makespan(cost, workers)
        plan.stats.update({
            "classes": len(fg.classes),
            "instances": cg.nb_instances(),
            "edges": cg.nb_edges,
            "waves": n_waves,
        })

    # ---------------------------------------------------- fusability
    def certify(self) -> List[dict]:
        """Wave-fusability certificates: one explicit certify/refuse
        record per (rank, wave) — never a silent skip.

        A wave certifies (fusable=True) when it could compile into ONE
        cached executable (MPK, arXiv:2512.22219) and run its members
        in any order inside it:

          homogeneous   one task class across the wave (the executable
                        is keyed by class)
          bodies        every chore is table-driven or declared pure
                        ("noop" / "device" / "pure-cb"): an opaque
                        Python callback may read or write state the
                        fused executable cannot see
          independence  no delivery edge between two members (possible
                        only on a cycle-parked tail wave — V003), and
                        no datum written by one member while another
                        member touches it (the engine's wave order is
                        arbitrary within a wave, so such a pair is a
                        race the per-task path hides behind copies and
                        fusion would surface — V010 flags it)
          tile shapes   every member's per-flow payload signature
                        matches (one executable = one set of buffer
                        shapes)

        Structural refusals of a homogeneous wave (intra-wave
        dependency or datum conflict) also surface as verify rule
        V010; body opacity and signature mismatches are plain
        refusals — legal graphs, just not fusable."""
        fg, cg = self.fg, self.cg
        members: Dict[Tuple[int, int], List[tuple]] = {}
        for node in self.inst_set:
            members.setdefault(
                (self._rank(node), self.wave[node]), []).append(node)
        self.members = members  # reused by the chain pass
        certs: List[dict] = []
        for (r, w) in sorted(members):
            nodes = sorted(members[(r, w)])
            classes = sorted({n[0] for n in nodes})
            reasons: List[str] = []
            structural: List[str] = []
            if len(classes) > 1:
                names = sorted(fg.classes[c].name for c in classes)
                cert = {"rank": r, "wave": w, "cls": None,
                        "width": len(nodes), "homogeneous": False,
                        "claimed": False, "fusable": False,
                        "body_kinds": [], "chain_next": False,
                        "reasons": [f"heterogeneous wave "
                                    f"({', '.join(names)})"]}
                certs.append(cert)
                continue
            cm = fg.classes[classes[0]]
            kinds = _chore_kinds(cm.tc)
            claimed = bool(kinds) and all(k != "opaque-cb" for k in kinds)
            if not kinds:
                reasons.append("no body chore")
            elif not claimed:
                reasons.append(
                    "opaque body (Python callback not declared pure; "
                    "see TaskClass.body(pure=))")
            member_set = set(nodes)
            # independence: delivery edges between members (cycle tail)
            dep_pairs = 0
            for n in nodes:
                for dst, _cert in cg.succ.get(n, ()):
                    if dst in member_set:
                        dep_pairs += 1
            if dep_pairs:
                structural.append(
                    f"{dep_pairs} intra-wave dependency edge(s) "
                    "(cycle-parked tail; see V003)")
            # independence: datum conflicts + tile signatures
            touched: Dict[object, set] = {}
            written: Dict[object, set] = {}
            sigs = set()
            for n in nodes:
                sig = []
                for fi, fl in enumerate(cm.flows):
                    if fl.access == N.FLOW_CTL:
                        continue
                    datum = self.datum_of(n, fi)
                    sig.append(self.datum_bytes(datum, n, fi))
                    touched.setdefault(datum, set()).add(n)
                    if _is_write(fl.access):
                        written.setdefault(datum, set()).add(n)
                sigs.add(tuple(sig))
            conflicts = 0
            sample = None
            for datum, writers in written.items():
                others = touched.get(datum, set()) | writers
                if len(others) > 1:
                    conflicts += 1
                    if sample is None:
                        sample = datum
            if conflicts:
                nm = (f"{sample[1]}[{', '.join(str(v) for v in sample[2])}]"
                      if sample and sample[0] == "mem" else "a temporary")
                structural.append(
                    f"{conflicts} intra-wave datum conflict(s) (e.g. "
                    f"{nm} written by one member and touched by "
                    "another with no ordering between them)")
            if len(sigs) > 1:
                reasons.append(
                    f"{len(sigs)} distinct tile signatures across "
                    "members (one executable needs one buffer shape "
                    "set)")
            reasons += structural
            certs.append({
                "rank": r, "wave": w, "cls": cm.name,
                "width": len(nodes), "homogeneous": True,
                "claimed": claimed,
                "fusable": claimed and not reasons,
                "body_kinds": kinds,
                "tile_sig": sorted(sigs)[0] if len(sigs) == 1 else None,
                "chain_next": False,
                "reasons": reasons,
                "structural": bool(structural),
            })
        return certs

    # ------------------------------------------------------ wave chains
    def certify_chains(self, certs: List[dict]) -> List[dict]:
        """Chain certificates: one record per ADJACENT pair of
        individually-certified waves on one rank, proving (or refusing,
        with reasons — never silently) that the pair may compile into a
        single multi-wave executable (the MPK one-level-up step,
        arXiv:2512.22219):

          tile shapes   both waves share one tile signature (one
                        executable = one buffer shape set)
          locality      no certain producer->consumer edge of the pair
                        crosses ranks (a cross-rank edge means the
                        consumer wave cannot complete from locally
                        parked results)
          resolvable    every consumer read flow is either fed by a
                        single certain producer inside the producer
                        wave (in-program dataflow) or is a statically
                        evaluable collection read (fetchable at
                        speculation time); anything else — maybe-edges,
                        multi-source selection, arena-fresh inputs,
                        nonadjacent task sources — refuses

        A `linked` pair feeds Plan.chain_index(): the runtime
        re-validates every input against live copy versions at
        consumption, so these records can only cost a wasted
        speculation when stale, never a wrong answer."""
        fg, cg = self.fg, self.cg
        plan = self.plan
        by_rw = {(c["rank"], c["wave"]): c for c in certs}
        chains: List[dict] = []
        classes_used: Dict[str, dict] = {}

        def _use_class(cm):
            classes_used[cm.name] = {"id": cm.id,
                                     "param_slots": list(cm.range_slots)}

        for (r, w) in sorted(by_rw):
            cert = by_rw[(r, w)]
            nxt = by_rw.get((r, w + 1))
            if not cert.get("fusable") or nxt is None \
                    or not nxt.get("fusable"):
                continue  # only certified pairs get a chain verdict
            rec = {"rank": r, "wave": w, "next_wave": w + 1,
                   "cls": cert["cls"], "next_cls": nxt["cls"],
                   "width": cert["width"], "next_width": nxt["width"],
                   "linked": False, "reasons": []}
            chains.append(rec)
            if cert.get("tile_sig") != nxt.get("tile_sig"):
                rec["reasons"].append(
                    "tile-signature mismatch across the pair (one "
                    "executable needs one buffer shape set)")
                continue
            prod_nodes = set(self.members.get((r, w), ()))
            cons_nodes = self.members.get((r, w + 1), [])
            # locality: certain edges into the consumer wave must stay
            # on this rank (both directions of the pair)
            cross = 0
            for n1 in prod_nodes:
                for dst, certain in cg.succ.get(n1, ()):
                    if certain and self.wave.get(dst) == w + 1 \
                            and self._rank(dst) != r:
                        cross += 1
            if cross:
                rec["reasons"].append(
                    f"{cross} cross-rank producer->consumer edge(s)")
                continue
            lane_links: Dict[tuple, list] = {}
            fed = 0
            for n2 in cons_nodes:
                cm2 = fg.classes[n2[0]]
                l2 = self.locals_of(n2)
                ins: List[tuple] = []
                srcs: List[tuple] = []
                why = None
                for fi, fl in enumerate(cm2.flows):
                    if fl.access not in (N.FLOW_READ, N.FLOW_RW):
                        continue
                    di = cg.selected.get((n2, fi))
                    if di is None:
                        why = (f"{cm2.name} flow {fl.name}: no "
                               "statically resolvable input source")
                        break
                    info = cm2._dep_info[(fi, di)]
                    if info["kind"] == "mem":
                        try:
                            idx = tuple(fn(l2) for fn in info["idx"])
                        except Exception:
                            why = (f"{cm2.name} flow {fl.name}: "
                                   "collection index not evaluable")
                            break
                        ins.append((fl.name,
                                    ("mem", info["coll"], idx)))
                        continue
                    if info["kind"] != "task":
                        why = (f"{cm2.name} flow {fl.name}: "
                               "arena-fresh input (no producer)")
                        break
                    key = (n2, fi)
                    if cg.nmaybe.get(key, 0) \
                            or cg.ncert.get(key, 0) != 1 \
                            or not cg.src_sample.get(key):
                        why = (f"{cm2.name} flow {fl.name}: input "
                               "source not a single certain edge")
                        break
                    src, (pcid, pfi, _pdi), _c = cg.src_sample[key][0]
                    if src not in prod_nodes:
                        why = (f"{cm2.name} flow {fl.name}: producer "
                               f"{cg.node_name(src)} is not in the "
                               "adjacent wave")
                        break
                    pname = fg.classes[pcid].flows[pfi].name
                    ins.append((fl.name, ("wave", src[1], pname)))
                    srcs.append(src)
                if why is not None:
                    rec["reasons"].append(why)
                    lane_links = {}
                    break
                if not srcs:
                    rec["reasons"].append(
                        f"{cg.node_name(n2)} reads nothing from the "
                        "producer wave")
                    lane_links = {}
                    break
                fed += 1
                entry = {"cls": cm2.name, "params": n2[1], "ins": ins}
                _use_class(cm2)
                for src in sorted(set(srcs)):
                    _use_class(fg.classes[src[0]])
                    lane_links.setdefault(
                        (fg.classes[src[0]].name, src[1]),
                        []).append(entry)
            if not lane_links or fed != len(cons_nodes):
                if not rec["reasons"]:
                    rec["reasons"].append("no consumer resolved")
                continue
            rec["linked"] = True
            cert["chain_next"] = True
            rlinks = plan._chain_links.setdefault(r, {})
            for key, entries in lane_links.items():
                # a producer key never spans two wave pairs (waves
                # partition instances), so plain insert is safe
                rlinks.setdefault(key, []).extend(entries)
        plan._chain_classes.update(classes_used)
        return chains

    # ---------------------------------------------------------- comm
    def _comm_volume(self, eager_limit: int):
        fg, cg, plan = self.fg, self.cg, self.plan
        # one payload transfer per (producer instance, flow, dst rank)
        # — the wire's per-rank activation/bcast dedup — plus remote
        # collection write-backs (MSG_PUT) per (instance, dep, owner)
        for node in self.inst_set:
            cm = fg.classes[node[0]]
            src_rank = self._rank(node)
            l = self.locals_of(node)
            sent: set = set()
            for fi, fl in enumerate(cm.flows):
                is_ctl = fl.access == N.FLOW_CTL
                for di, d in enumerate(fl.deps):
                    if d.direction != 1:
                        continue
                    info = cm._dep_info[(fi, di)]
                    if info["kind"] == "none":
                        continue
                    payload = 0
                    if not is_ctl:
                        if d.dtype is not None:
                            payload = fg.datatype_bytes.get(d.dtype) or 0
                        if payload == 0:
                            datum = self.datum_of(node, fi)
                            payload = self.datum_bytes(datum, node, fi)
                    for kind, payload_t, _cert in \
                            cm.out_emissions(fi, di, l):
                        if kind == "task":
                            peer = fg.by_name.get(info["peer"])
                            dst = (peer.id, payload_t)
                            if dst not in self.inst_set:
                                continue
                            dst_rank = self._rank(dst)
                        elif kind == "mem":
                            # payload_t is the evaluated (collection,
                            # idx) — iterator-extended deps included
                            coll = fg.collection_objs.get(payload_t[0])
                            if coll is None:
                                continue
                            try:
                                dst_rank = int(coll.rank_of(*payload_t[1]))
                            except Exception:
                                continue
                        else:
                            continue
                        if dst_rank == src_rank:
                            continue
                        dedup = (fi, dst_rank) if kind == "task" \
                            else (fi, di, dst_rank, payload_t[1])
                        if dedup in sent:
                            continue
                        sent.add(dedup)
                        self._account_edge(src_rank, dst_rank, payload,
                                           eager_limit, cls=cm.name)

    def _account_edge(self, src: int, dst: int, payload: int,
                      eager_limit: int, cls: Optional[str] = None):
        plan = self.plan
        for r in (src, dst):
            if r not in plan.per_rank:
                plan.per_rank[r] = {
                    "tasks": 0, "work_ns": 0, "peak_bytes": 0,
                    "live_peak_bytes": 0, "device_peak_bytes": 0,
                    "device_live_peak_bytes": 0, "comm_out_bytes": 0,
                    "comm_in_bytes": 0, "comm_out_msgs": 0,
                    "eager_bytes": 0, "rdv_bytes": 0}
        srow, drow = plan.per_rank[src], plan.per_rank[dst]
        srow["comm_out_bytes"] += payload
        srow["comm_out_msgs"] += 1
        drow["comm_in_bytes"] += payload
        if payload <= eager_limit:
            srow["eager_bytes"] += payload
        else:
            srow["rdv_bytes"] += payload
        key = (src, dst)
        plan.edges_bytes[key] = plan.edges_bytes.get(key, 0) + payload
        plan.edges_msgs[key] = plan.edges_msgs.get(key, 0) + 1
        if cls is not None and cls.startswith("ptc_coll_"):
            row = plan.coll_edges.setdefault(key, {"bytes": 0, "msgs": 0})
            row["bytes"] += payload
            row["msgs"] += 1

    # ------------------------------------------------------- makespan
    def _makespan(self, cost: CostModel, workers: int):
        fg, cg, plan = self.fg, self.cg, self.plan
        # critical path over CERTAIN edges only: a maybe-edge may not
        # materialize at runtime, so only the certain subgraph yields a
        # sound lower bound
        dist: Dict[tuple, float] = {}
        best_pred: Dict[tuple, Optional[tuple]] = {}
        indeg: Dict[tuple, int] = {n: 0 for n in self.inst_set}
        for src, outs in cg.succ.items():
            for dst, certain in outs:
                if certain and dst in indeg:
                    indeg[dst] += 1
        ready = [n for n in self.inst_set if indeg[n] == 0]
        for n in ready:
            dist[n] = cost.ns(fg.classes[n[0]].name)
            best_pred[n] = None
        while ready:
            n = ready.pop()
            for dst, certain in cg.succ.get(n, ()):
                if not certain or dst not in indeg:
                    continue
                cand = dist[n] + cost.ns(fg.classes[dst[0]].name)
                if cand > dist.get(dst, -1.0):
                    dist[dst] = cand
                    best_pred[dst] = n
                indeg[dst] -= 1
                if indeg[dst] == 0:
                    ready.append(dst)
        cp_ns = 0.0
        path_classes: Dict[str, float] = {}
        path_len = 0
        if dist:
            sink = max(dist, key=lambda n: dist[n])
            cp_ns = dist[sink]
            n = sink
            while n is not None:
                cname = fg.classes[n[0]].name
                path_classes[cname] = (path_classes.get(cname, 0.0)
                                       + cost.ns(cname))
                path_len += 1
                n = best_pred.get(n)
        workers = max(1, workers)
        work_bound = max(
            (row["work_ns"] / workers for row in plan.per_rank.values()),
            default=0.0)
        plan.makespan = {
            "critical_path_ns": int(cp_ns),
            "path_len": path_len,
            "path_classes_ns": {k: int(v)
                                for k, v in path_classes.items()},
            "work_ns": int(work_bound),
            "workers_per_rank": workers,
            "lower_bound_ns": int(max(cp_ns, work_bound)),
            "cost_source": cost.source,
            # the per-class ns assumptions this bound used — the
            # calibration baseline scope conformance compares the live
            # metrics p50s against (ptc-scope / ROADMAP item 5)
            "per_class_cost": {fg.classes[cid].name:
                               cost.ns(fg.classes[cid].name)
                               for cid in sorted({n[0]
                                                  for n in self.inst_set})},
        }


# ----------------------------------------------------- symbolic fallback
def _symbolic_plan(fg: FlowGraph, plan: Plan):
    """Interval-mode residency bound for execution spaces too large to
    enumerate: per-class instance-count bounds from the space intervals,
    touched-tile counts capped at each collection's extent.  An upper
    bound on the working set — sound for admission (never under-admits),
    explicit about what it could not bound."""
    plan.bounded = True
    total = 0
    unbounded = False
    coll_caps: Dict[str, int] = {}
    coll_touch: Dict[str, int] = {}
    tmp_bytes = 0
    for cm in fg.classes:
        ivals = cm.space_intervals()
        inst_bound = 1
        for s in cm.range_slots:
            iv = ivals.get(s)
            if iv is None:
                inst_bound = None
                break
            inst_bound *= max(0, iv[1] - iv[0] + 1)
        if inst_bound is None:
            unbounded = True
            plan.notes.append(
                f"class {cm.name}: execution-space bounds leave the "
                "affine fragment; residency bound is incomplete")
            continue
        for fi, fl in enumerate(cm.flows):
            if fl.access == N.FLOW_CTL:
                continue
            mem_colls = {d.target.collection for d in fl.deps
                         if getattr(d.target, "collection", None)}
            if mem_colls:
                for cname in mem_colls:
                    coll = fg.collection_objs.get(cname)
                    tb = collection_tile_bytes(coll) or 0
                    cap = None
                    if coll is not None and hasattr(coll, "mt") \
                            and hasattr(coll, "nt"):
                        cap = int(coll.mt) * int(coll.nt) * tb
                    elif coll is not None and hasattr(coll, "nt"):
                        cap = int(coll.nt) * tb
                    coll_touch[cname] = (coll_touch.get(cname, 0)
                                         + inst_bound * tb)
                    if cap is not None:
                        coll_caps[cname] = cap
            elif fl.arena and not any(d.direction == 0
                                      and d.target is not None
                                      for d in fl.deps):
                # pure-output arena flow: one fresh copy per instance
                # (task-rooted flows are counted at their producer)
                tmp_bytes += inst_bound * fg.arena_sizes.get(fl.arena, 0)
    for cname, b in coll_touch.items():
        cap = coll_caps.get(cname)
        total += min(b, cap) if cap is not None else b
    total += tmp_bytes
    plan._symbolic_peak = None if unbounded else int(total)
    plan.notes.append(
        "concrete enumeration refused: residency bound from interval "
        "counting; comm volume, waves and makespan unavailable (raise "
        "max_instances for exact analysis)")
    plan.stats.update({"classes": len(fg.classes), "instances": 0,
                       "edges": 0, "waves": 0})


def certify_waves(fg: FlowGraph, cg: ConcreteGraph) -> List[dict]:
    """Standalone wave-fusability certification over an already-
    concretized graph (no cost model or economics needed): the records
    `Plan.fusability` carries, computed for consumers that only need
    the certificates (the V010 verify rule).  Empty when enumeration
    was refused."""
    if cg.bounded:
        return []
    plan = Plan(fg)
    an = _Analyzer(fg, cg, plan)
    an.compute_waves()
    return an.certify()


def chain_certificates(tp, max_instances: Optional[int] = None
                       ) -> Optional[Plan]:
    """Wave + chain certification only — the device wave compiler's
    certificate-consumption entry point (no cost model, economics or
    comm analysis: a fraction of a full plan).  Returns a Plan whose
    `fusability`, `chains` and `chain_index()` are populated, or None
    when concrete enumeration was refused (the compiler then refuses
    fusion with an explicit reason, never a silent guess)."""
    from .flowgraph import extract_flowgraph
    if max_instances is None:
        from ..utils import params as _mca
        max_instances = int(_mca.get("plan.max_instances"))
    fg = extract_flowgraph(tp)
    cg = fg.concretize(max_instances=max_instances)
    if cg.bounded:
        return None
    plan = Plan(fg)
    plan.cg = cg
    an = _Analyzer(fg, cg, plan)
    an.compute_waves()
    plan.fusability = an.certify()
    plan.chains = an.certify_chains(plan.fusability)
    return plan


# ---------------------------------------------------------------- driver
def plan_graph(fg: FlowGraph, max_instances: Optional[int] = None,
               cost: Optional[CostModel] = None,
               econ=None, workers: Optional[int] = None) -> Plan:
    """Run the static resource & schedule analysis over an extracted
    flow graph.  `cost` defaults to the context's live metrics
    histograms when they carry samples, else the uniform model."""
    t0 = time.perf_counter()
    if max_instances is None:
        from ..utils import params as _mca
        max_instances = int(_mca.get("plan.max_instances"))
    plan = Plan(fg)
    ctx = fg.tp.ctx
    if cost is None:
        cost = CostModel.from_context(ctx) or CostModel()
    if workers is None:
        try:
            workers = int(ctx.nb_workers)
        except Exception:
            workers = 1
    cg = fg.concretize(max_instances=max_instances)
    plan.notes += cg.notes
    # the concretized instance DAG is kept for downstream consumers
    # (the ptc-tune schedule simulator walks its edges)
    plan.cg = cg
    if cg.bounded:
        _symbolic_plan(fg, plan)
    else:
        eager = _eager_threshold(ctx, econ)
        _Analyzer(fg, cg, plan).run(cost, eager, workers)
    plan.stats["elapsed_ms"] = (time.perf_counter() - t0) * 1e3
    return plan


def plan_taskpool(tp, max_instances: Optional[int] = None,
                  cost: Optional[CostModel] = None,
                  econ=None, workers: Optional[int] = None) -> Plan:
    """Extract + plan a Taskpool (committed or not; nothing executes)."""
    return plan_graph(extract_flowgraph(tp), max_instances=max_instances,
                      cost=cost, econ=econ, workers=workers)


def compare_critpath(plan: Plan, trace) -> dict:
    """Predicted vs *executed* critical path (PR 5 critpath over a
    level-2 trace): the first-class regression signal ptc_plan --trace
    prints.  ratio < 1 means the prediction under-ran the measured path
    (expected: the bound is a lower bound)."""
    from ..profiling.critpath import critical_path
    executed = critical_path(trace)
    pred = int(plan.makespan.get("critical_path_ns", 0))
    exe = int(executed.get("total_ns", 0))
    return {
        "predicted_ns": pred,
        "executed_ns": exe,
        "ratio": round(pred / exe, 4) if exe else None,
        "predicted_path_len": plan.makespan.get("path_len", 0),
        "executed_path_len": len(executed.get("path", [])),
        "cost_source": plan.makespan.get("cost_source"),
    }


# -------------------------------------------------- fleet placement cost
def placement_cost(est_bytes: int, shared_bytes: int, queued_bytes: int,
                   active_pools: int, burn_rate: float,
                   migrate_bytes: int = 0, econ=None,
                   mem_gbps: float = 16.0,
                   migrate_cls: Optional[str] = None) -> float:
    """Modeled seconds-until-done for placing ONE request on ONE replica
    — the scalar the fleet router minimizes (serve/router.py).  Three
    legs, all in seconds so they compose with the fitted transfer
    economics:

      cold work     the bytes the replica must actually produce —
                    est_bytes minus the prefix bytes its frozen-page
                    index already holds (never below 1: the ptc-plan
                    UNKNOWN sentinel convention) — through a nominal
                    host-memory bandwidth.  Prefix locality enters the
                    score HERE, as saved bytes, commensurable with the
                    wire leg rather than an ad-hoc bonus term.
      queue         the replica's admitted-but-unfinished bytes plus a
                    per-active-pool slot cost of a QUARTER request
                    equivalent (continuous batching overlaps active
                    sequences, so an occupied slot delays a newcomer by
                    a fraction of a request, not a full one — and
                    keeping it in byte-time units means locality vs
                    occupancy trades off identically at toy and
                    production page sizes), scaled by (1 + burn_rate):
                    a replica burning its SLO budget serves its backlog
                    slower than its steady-state bandwidth suggests, so
                    pressure is super-linear.
      wire          econ.cost() of any frozen pages the router would
                    migrate to create the locality it is pricing in
                    (disaggregated prefill->decode handoff) — one
                    rendezvous transfer per bundle on today's chunked
                    pull path.  `migrate_cls` (ptc-topo link class of
                    the donor->target leg, e.g. "dcn") prices it with
                    the classed fit: a migration that wins inside an
                    island can honestly lose across islands.

    Pure arithmetic under a static model (deliberately so: deterministic
    placement tests pin tie-breaks), sharing TransferEconomics with the
    collective selector so a refit of BENCH_comm.json moves BOTH."""
    if econ is None:
        from ..comm.economics import default_economics
        econ = default_economics()
    per_byte = 1.0 / (max(float(mem_gbps), 1e-3) * (1 << 30))
    cold = max(1, int(est_bytes) - int(shared_bytes)) * per_byte
    queue = (max(0, int(queued_bytes))
             + 0.25 * max(0, int(active_pools)) * max(1, int(est_bytes))
             ) * per_byte
    queue *= 1.0 + max(0.0, float(burn_rate))
    wire = econ.cost(int(migrate_bytes), "rdv", cls=migrate_cls) \
        if migrate_bytes else 0.0
    return cold + queue + wire
