"""ptc-serve: multi-tenant serving runtime over the task runtime.

The runtime so far ran one taskpool to completion; a serving system
runs thousands of small concurrent DAGs under priority and admission
control (ROADMAP item 3, "millions of users").  This package is that
layer:

  Server            admission-controlled front door: per-tenant
                    concurrent-pool and queued-bytes budgets, queue or
                    reject beyond them, per-pool QoS (priority/weight)
                    stamped on every admitted taskpool; counters export
                    through Context.stats()["serve"] and the PR 7
                    MetricsRegistry (Prometheus + /stats.json)
  InferenceEngine   continuous-batching LLM inference scenario: paged
                    KV-cache attention DAGs (ops/paged_attention) for
                    prefill and per-step decode, sequences admitted and
                    retired continuously as mixed-priority tenants
  PagedLM           deterministic toy attention LM (f32, fixed op
                    order) whose batched and sequential runs are
                    bit-identical — the serve bench's correctness oracle
  Router            fleet tier (ptc-route): prefix-locality scored
                    placement over N replicas, prefill/decode role
                    disaggregation, content-hash KV page migration and
                    queued-only re-placement off unhealthy replicas
"""
from .server import (AdmissionError, Server, TenantConfig, Ticket)
from .engine import InferenceEngine, PagedLM, PagedLMConfig, RequestHandle
from .router import (FleetHandle, KeyDigest, Replica, RoutePolicy,
                     Router)

__all__ = [
    "Server", "TenantConfig", "Ticket", "AdmissionError",
    "InferenceEngine", "PagedLM", "PagedLMConfig", "RequestHandle",
    "Router", "Replica", "RoutePolicy", "KeyDigest", "FleetHandle",
]
