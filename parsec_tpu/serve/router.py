"""Fleet front door (ptc-route): prefix-locality routing across Server
replicas, disaggregated prefill/decode roles, and content-hash KV page
migration.

One Router places requests across N replicas (each an InferenceEngine +
Server on its OWN Context / rank group) by a scored policy over each
replica's cheap `Server.advertise()` snapshot:

  locality   the prompt's frozen-page key chain (the SAME
             ops.paged_attention.prefix_page_keys content hashes the
             engine freezes under) probed against the replica's
             advertised key digest — predicted warm bytes, computed
             WITHOUT touching the replica, and exact by construction:
             a predicted hit is precisely what acquire_prefix will map
  load       advertised occupancy (active pools, queued bytes) scaled
             by the tenant SLO burn rate — pressure is super-linear on
             a replica burning its error budget
  migration  when another replica holds pages this one lacks, the
             router prices moving them (transfer-economics wire legs)
             against prefilling them cold, and migrates when cheaper

All three legs fold into ONE scalar via analysis.plan.placement_cost
(seconds-until-done under the static model), so the policy is
deterministic and unit-pinnable: min cost wins, ties break to the
lowest replica index.

Role disaggregation: replicas marked role="prefill" never serve decode
traffic — prefill_then_decode() runs the compute-bound prefill there
(max_new=0: freeze pages, emit nothing), migrates the frozen pages to
the chosen decode replica (in-process or over the chunked streaming
wire — comm/migrate.py), and submits the real request fully warm.
Because frozen page bytes are a pure function of their content key,
the disaggregated output is BIT-IDENTICAL to a single-replica run.

Re-placement: a request still QUEUED (never past admission, so never
decoding) on a replica whose health flips (closed, or SLO burn breach —
the /healthz 503 condition) is cancelled and re-placed on a healthy
replica; the cancelled->rerouted counter pair proves nothing is
silently dropped.  A decoding sequence is NEVER re-placed.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..analysis.plan import placement_cost
from ..comm.economics import default_economics
from ..comm.migrate import migrate_keys, wanted_keys
from ..ops.paged_attention import prefix_page_keys

__all__ = ["KeyDigest", "RoutePolicy", "Replica", "FleetHandle",
           "Router"]


# ------------------------------------------------------------- digest
class KeyDigest:
    """Compact mergeable summary of a replica's frozen content keys.

    mode="set"    the exact key set — deterministic (zero false
                  positives: predicted warm length == acquire_prefix's
                  result, which the placement tests pin)
    mode="bloom"  an m-bit Bloom filter (k hashes of the hex key) —
                  constant-size for fleets whose key population
                  outgrows the advertisement; predictions become upper
                  bounds (false positives only — never false negatives,
                  so a warm page is never missed)

    Mergeable: `merge` unions two digests (set union / bitwise OR), so
    a tier of routers can fold replica digests upward."""

    def __init__(self, mode: str = "set", keys: Sequence = (),
                 m: int = 4096, k: int = 3, bits: int = 0):
        if mode not in ("set", "bloom"):
            raise ValueError(f"unknown digest mode {mode!r}")
        self.mode = mode
        self.m = int(m)
        self.k = int(k)
        self._keys = set(str(x) for x in keys) if mode == "set" else set()
        self._bits = int(bits)
        if mode == "bloom":
            for key in keys:
                self.add(key)

    def _hashes(self, key) -> List[int]:
        h = hashlib.sha1(str(key).encode()).digest()
        return [int.from_bytes(h[4 * i:4 * i + 4], "little") % self.m
                for i in range(self.k)]

    def add(self, key):
        if self.mode == "set":
            self._keys.add(str(key))
        else:
            for b in self._hashes(key):
                self._bits |= 1 << b

    def __contains__(self, key) -> bool:
        if self.mode == "set":
            return str(key) in self._keys
        return all(self._bits >> b & 1 for b in self._hashes(key))

    def __len__(self) -> int:
        return len(self._keys) if self.mode == "set" else \
            bin(self._bits).count("1")

    def predict_warm(self, keys: Sequence) -> int:
        """Longest leading run of `keys` present — the router-side twin
        of PagePool.probe (exact for mode="set")."""
        n = 0
        for key in keys:
            if key not in self:
                break
            n += 1
        return n

    def merge(self, other: "KeyDigest") -> "KeyDigest":
        if self.mode != other.mode:
            raise ValueError("cannot merge digests of different modes")
        if self.mode == "set":
            out = KeyDigest("set", self._keys | other._keys)
        else:
            if (self.m, self.k) != (other.m, other.k):
                raise ValueError("bloom digests differ in (m, k)")
            out = KeyDigest("bloom", m=self.m, k=self.k,
                            bits=self._bits | other._bits)
        return out

    def to_advert(self) -> dict:
        if self.mode == "set":
            return {"mode": "set", "n": len(self._keys),
                    "keys": sorted(self._keys)}
        return {"mode": "bloom", "m": self.m, "k": self.k,
                "bits": format(self._bits, "x")}

    @classmethod
    def from_advert(cls, advert: Optional[dict]) -> "KeyDigest":
        """Parse the Server.advertise()["prefix"] payload (schema in
        MIGRATION.md).  Missing/garbled adverts decode to an empty set
        digest — an unreachable replica just looks cold."""
        if not isinstance(advert, dict):
            return cls("set")
        if advert.get("mode") == "bloom":
            try:
                bits = int(str(advert.get("bits", "0")), 16)
            except ValueError:
                bits = 0
            return cls("bloom", m=advert.get("m", 4096),
                       k=advert.get("k", 3), bits=bits)
        return cls("set", advert.get("keys") or ())


# ------------------------------------------------------------- policy
class RoutePolicy:
    """Placement knobs (README "Fleet tier").

      mem_gbps      nominal replica memory bandwidth for the cold-work
                    and queue legs of placement_cost
      migrate       price page migration into placement and perform it
                    when it wins (False: locality only counts pages
                    already local)
      digest_mode   advisory — replicas advertise "set" by default;
                    a bloom advert is parsed transparently
      replace_unhealthy
                    re-place still-queued requests off replicas whose
                    healthy() flips false
      econ          TransferEconomics for the wire legs (defaults to
                    the fitted BENCH_comm.json model)
      topo          TopologyModel over REPLICA INDICES (replica i is
                    "rank" i of the fleet mesh) — ptc-topo.  Migration
                    legs are priced at the (donor, target) link class,
                    so a cross-island donor pays the DCN rate and an
                    intra-island donor the ICI rate; the donor choice
                    itself minimizes the classed cost.  Defaults to
                    the PTC_MCA_comm_topology spec over the fleet size
                    (flat when unset — the pre-topo behavior)."""

    def __init__(self, mem_gbps: float = 16.0, migrate: bool = True,
                 digest_mode: str = "set",
                 replace_unhealthy: bool = True, econ=None,
                 topo=None):
        self.mem_gbps = float(mem_gbps)
        self.migrate = bool(migrate)
        self.digest_mode = digest_mode
        self.replace_unhealthy = bool(replace_unhealthy)
        self.econ = econ or default_economics()
        self.topo = topo


# ------------------------------------------------------------ replica
class Replica:
    """One fleet member: an InferenceEngine (+ its Server) on its own
    Context / rank group.  role: "mixed" (default — prefill + decode),
    "decode" (placement target), "prefill" (feeder: only prefill_warm
    jobs land here; its frozen pages migrate out)."""

    def __init__(self, engine, role: str = "mixed",
                 name: Optional[str] = None):
        if role not in ("mixed", "decode", "prefill"):
            raise ValueError(f"unknown replica role {role!r}")
        self.engine = engine
        self.role = role
        self.name = name or engine.server.name

    @property
    def server(self):
        return self.engine.server

    @property
    def pool(self):
        return self.engine.pool

    def advertise(self) -> dict:
        return self.server.advertise()


# ------------------------------------------------------------- handle
class FleetHandle:
    """One routed request across its (possibly re-placed) lifetime.
    `handle` is the CURRENT engine RequestHandle; `reroutes` counts
    re-placements (each paired with a server-side `cancelled`)."""

    __slots__ = ("prompt", "max_new", "tenant", "handle", "replica",
                 "reroutes")

    def __init__(self, prompt, max_new, tenant, handle, replica):
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.tenant = tenant
        self.handle = handle
        self.replica = replica
        self.reroutes = 0

    @property
    def state(self) -> str:
        return self.handle.state

    @property
    def tokens(self):
        return self.handle.tokens

    @property
    def generated(self):
        return self.handle.generated

    @property
    def outputs(self):
        return self.handle.outputs


# -------------------------------------------------------------- router
class Router:
    """The fleet front door.  submit() scores decode-capable replicas
    and places; prefill_then_decode() runs the disaggregated handoff;
    run() drives every replica's engine loop plus the re-placement
    pump in one thread (the stress job threads it externally)."""

    def __init__(self, replicas: Sequence, policy: Optional[RoutePolicy]
                 = None):
        self.replicas: List[Replica] = [
            r if isinstance(r, Replica) else Replica(r) for r in replicas]
        if not any(r.role != "prefill" for r in self.replicas):
            raise ValueError("fleet needs at least one decode-capable "
                             "replica")
        self.policy = policy or RoutePolicy()
        self._lock = threading.Lock()
        self._handles: List[FleetHandle] = []
        self.counters = {"placed": 0, "rerouted": 0, "reroute_failed": 0,
                         "prefill_jobs": 0, "migrated_pages": 0,
                         "migrated_bytes": 0, "migration_dups": 0}
        # register on each replica's context (deduped — replicas may
        # share one) so LiveMonitor samples carry the fleet table and
        # tools/ptc_top.py can draw it from any replica's sink
        seen = set()
        for r in self.replicas:
            ctx = r.engine.ctx
            if id(ctx) in seen:
                continue
            seen.add(id(ctx))
            routers = getattr(ctx, "_routers", None)
            if routers is None:
                routers = ctx._routers = []
            routers.append(self)

    # ----------------------------------------------------------- scoring
    def _decode_replicas(self) -> List[int]:
        return [i for i, r in enumerate(self.replicas)
                if r.role != "prefill"]

    def page_keys(self, prompt: Sequence[int]) -> List[str]:
        model = self.replicas[0].engine.model
        return prefix_page_keys(model.model_id, prompt, model.cfg.page)

    def score(self, prompt: Sequence[int],
              adverts: Optional[Dict[int, dict]] = None) -> List[dict]:
        """One row per decode-capable replica: the placement_cost legs,
        the predicted warm length, and the migration plan considered.
        `adverts` injects snapshots (deterministic tests); by default
        each replica is polled live.  Rows for unhealthy replicas carry
        cost=inf (never chosen while an alternative exists)."""
        keys = self.page_keys(prompt)
        model = self.replicas[0].engine.model
        P = model.cfg.page
        n_pages = (len(prompt) + P - 1) // P
        idxs = self._decode_replicas()
        snap = {}
        for i in idxs:
            snap[i] = (adverts or {}).get(i) or \
                self.replicas[i].advertise()
        digests = {i: KeyDigest.from_advert(snap[i].get("prefix"))
                   for i in idxs}
        warms = {i: digests[i].predict_warm(keys) for i in idxs}
        best_warm = max(warms.values()) if warms else 0
        topo = self.policy.topo
        if topo is None:
            from ..comm.topology import default_topology
            topo = default_topology(len(self.replicas))
        rows = []
        for i in idxs:
            ad = snap[i]
            pb = (ad.get("prefix") or {}).get("page_bytes") or \
                self.replicas[i].pool.bytes_per_page
            est = n_pages * pb
            warm = warms[i]
            extra = max(0, best_warm - warm) if self.policy.migrate \
                else 0
            row = {"replica": i, "warm": warm,
                   "healthy": bool(ad.get("healthy", True)),
                   "burn": float(ad.get("slo_burn_rate") or 0.0),
                   "migrate_pages": 0, "migrate_from": None,
                   "migrate_cls": None}
            # ptc-pilot: a replica whose controller raised admission
            # pricing is already shedding load — fold the advertised
            # pressure into the burn leg so the fleet steers new
            # placements away BEFORE the replica's /healthz flips
            press = float(ad.get("admission_pressure") or 0.0)
            base = dict(est_bytes=est,
                        queued_bytes=int(ad.get("queued_bytes") or 0),
                        active_pools=int(ad.get("active_pools") or 0),
                        burn_rate=row["burn"] + press,
                        econ=self.policy.econ,
                        mem_gbps=self.policy.mem_gbps)
            cost = placement_cost(shared_bytes=warm * pb,
                                  migrate_bytes=0, **base)
            if extra:
                # donor candidates: any OTHER replica advertising the
                # full best_warm chain.  Each donor's leg is priced at
                # ITS link class (ptc-topo: an intra-island donor at
                # ici, a cross-island one at dcn), and the cheapest
                # classed donor wins (ties -> lowest index).
                best_mig = None
                for j in sorted(warms):
                    if j == i or warms[j] < warm + extra:
                        continue
                    cls = topo.class_of(j, i)
                    cmig = placement_cost(
                        shared_bytes=(warm + extra) * pb,
                        migrate_bytes=extra * pb,
                        migrate_cls=cls, **base)
                    if best_mig is None or cmig < best_mig[0]:
                        best_mig = (cmig, j, cls)
                if best_mig is not None and best_mig[0] < cost:
                    cost = best_mig[0]
                    row["migrate_pages"] = extra
                    row["migrate_from"] = best_mig[1]
                    row["migrate_cls"] = best_mig[2]
            if not row["healthy"]:
                cost = float("inf")
            row["cost"] = cost
            rows.append(row)
        return rows

    # --------------------------------------------------------- placement
    def _choose(self, rows: List[dict]) -> dict:
        return min(rows, key=lambda r: (r["cost"], r["replica"]))

    def submit(self, prompt: Sequence[int], max_new: int,
               tenant: str = "default",
               adverts: Optional[Dict[int, dict]] = None) -> FleetHandle:
        """Scored placement: pick the min-cost decode-capable replica,
        perform the priced-in page migration (if it won), submit.  The
        decision lands in the chosen replica's scope registry as a
        structured "route_place" event (per-replica scores included)."""
        rows = self.score(prompt, adverts=adverts)
        best = self._choose(rows)
        rep = self.replicas[best["replica"]]
        if best["migrate_pages"] and best["migrate_from"] is not None:
            keys = self.page_keys(prompt)
            self.migrate(keys, dst=rep,
                         src=self.replicas[best["migrate_from"]])
        handle = rep.engine.submit(prompt, max_new, tenant=tenant)
        fh = FleetHandle(prompt, max_new, tenant, handle, rep)
        with self._lock:
            self._handles.append(fh)
            self.counters["placed"] += 1
        rep.engine.scope.record_event(
            "route_place", replica=best["replica"], rid=handle.rid,
            tenant=tenant, warm=best["warm"], cost=best["cost"],
            migrate_pages=best["migrate_pages"],
            scores=[{"replica": r["replica"],
                     "cost": r["cost"], "warm": r["warm"]}
                    for r in rows])
        return fh

    # --------------------------------------------------------- migration
    def migrate(self, keys: Sequence, dst: Replica,
                src: Optional[Replica] = None) -> dict:
        """Move the frozen pages `keys` the destination lacks from
        `src` (or the first other replica holding them).  Receiver-
        driven dedup: already-held keys move ZERO bytes.  In-process
        transport here; rank-group fleets run the same contract over
        the chunked wire (comm.migrate.build_page_migration)."""
        wanted = wanted_keys(dst.pool, keys)
        held = len(list(keys)) - len(wanted)
        agg = {"requested": len(list(keys)), "transferred": 0,
               "skipped_held": held, "skipped_missing": 0, "bytes": 0}
        srcs = [src] if src is not None else \
            [r for r in self.replicas if r is not dst]
        for s in srcs:
            if not wanted:
                break
            res = migrate_keys(s.pool, dst.pool, wanted)
            agg["transferred"] += res["transferred"]
            agg["skipped_held"] += res["skipped_held"]
            agg["bytes"] += res["bytes"]
            wanted = wanted_keys(dst.pool, wanted)
        agg["skipped_missing"] = len(wanted)
        with self._lock:
            self.counters["migrated_pages"] += agg["transferred"]
            self.counters["migrated_bytes"] += agg["bytes"]
            self.counters["migration_dups"] += agg["skipped_held"]
        dst.engine.scope.record_event(
            "page_migration", to=dst.name,
            transferred=agg["transferred"], bytes=agg["bytes"],
            skipped_held=agg["skipped_held"],
            skipped_missing=agg["skipped_missing"])
        return agg

    # ----------------------------------------------- disaggregated roles
    def prefill_then_decode(self, prompt: Sequence[int], max_new: int,
                            tenant: str = "default") -> FleetHandle:
        """The production fleet split: run the compute-bound prefill on
        a prefill-role replica (max_new=0 — pages freeze, nothing is
        emitted), migrate the frozen pages to the best decode replica,
        then submit the real request there — its prefill maps every
        full page warm (acquire_prefix) and only the partial tail page
        stages cold.  Frozen bytes are pure functions of their keys, so
        the output is bit-identical to an undisaggregated run.  With no
        prefill-role replica configured this degrades to submit()."""
        pres = [r for r in self.replicas if r.role == "prefill"]
        if not pres:
            return self.submit(prompt, max_new, tenant=tenant)
        pre = min(pres, key=lambda r: (r.advertise()["active_pools"]
                                       + r.advertise()["queue_depth"]))
        pre.engine.prefill_warm(prompt, tenant=tenant)
        with self._lock:
            self.counters["prefill_jobs"] += 1
        pre.engine.run(timeout_s=120.0)  # drive the warm job to freeze
        rows = [r for r in self.score(prompt)
                if r["cost"] != float("inf")]
        best = self._choose(rows or self.score(prompt))
        rep = self.replicas[best["replica"]]
        self.migrate(self.page_keys(prompt), dst=rep, src=pre)
        handle = rep.engine.submit(prompt, max_new, tenant=tenant)
        fh = FleetHandle(prompt, max_new, tenant, handle, rep)
        with self._lock:
            self._handles.append(fh)
            self.counters["placed"] += 1
        rep.engine.scope.record_event(
            "route_place", replica=best["replica"], rid=handle.rid,
            tenant=tenant, warm=best["warm"], cost=best["cost"],
            disaggregated=True, prefill_replica=pre.name)
        return fh

    # ------------------------------------------------------ re-placement
    def _pump(self) -> int:
        """Re-place still-QUEUED requests off unhealthy replicas.  A
        ticket past admission (running — i.e. prefilling or decoding)
        is NEVER touched; Server.cancel enforces that atomically, so a
        racing admission simply wins.  Every successful cancel pairs
        with a rerouted++ (or reroute_failed++ when no healthy replica
        exists — still visible, never silent)."""
        if not self.policy.replace_unhealthy:
            return 0
        moved = 0
        with self._lock:
            handles = list(self._handles)
        for fh in handles:
            ticket = fh.handle.ticket
            if ticket is None or ticket.state != "queued":
                continue
            if fh.replica.server.healthy():
                continue
            if not fh.replica.server.cancel(ticket):
                continue  # raced into running: leave it be
            fh.handle.state = "cancelled"
            fh.handle.done_t = time.monotonic()
            old = fh.replica
            rows = [r for r in self.score(fh.prompt)
                    if r["cost"] != float("inf") and
                    self.replicas[r["replica"]] is not old]
            if not rows:
                with self._lock:
                    self.counters["reroute_failed"] += 1
                old.engine.scope.record_event(
                    "route_replace_failed", rid=fh.handle.rid,
                    from_replica=old.name)
                continue
            best = self._choose(rows)
            rep = self.replicas[best["replica"]]
            fh.handle = rep.engine.submit(fh.prompt, fh.max_new,
                                          tenant=fh.tenant)
            fh.replica = rep
            fh.reroutes += 1
            moved += 1
            with self._lock:
                self.counters["rerouted"] += 1
            rep.engine.scope.record_event(
                "route_replace", rid=fh.handle.rid,
                from_replica=old.name, to_replica=rep.name,
                cost=best["cost"])
        return moved

    # ------------------------------------------------------------ driver
    def _busy(self) -> bool:
        return any(r.engine.pending() or r.engine._inflight
                   for r in self.replicas)

    def run(self, timeout_s: float = 120.0):
        """Drive every replica's continuous-batching loop round-robin
        (launch + reap, exactly engine.run's internals) plus the
        re-placement pump, until the whole fleet is quiescent."""
        deadline = time.monotonic() + timeout_s
        while self._busy():
            if time.monotonic() > deadline:
                raise TimeoutError("fleet loop exceeded its deadline")
            progressed = self._pump()
            for r in self.replicas:
                progressed += r.engine._launch()
                progressed += r.engine._reap()
            if not progressed:
                time.sleep(0.0005)
        for r in self.replicas:
            r.engine.run(timeout_s=max(1.0,
                                       deadline - time.monotonic()))

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        """Router counters + per-replica occupancy/prefix rows (the
        ptc_top fleet table's feed)."""
        with self._lock:
            out = {"router": dict(self.counters), "replicas": {}}
        for i, r in enumerate(self.replicas):
            ad = r.advertise()
            ps = r.pool.stats()
            out["replicas"][r.name] = {
                "index": i, "role": r.role,
                "healthy": ad["healthy"],
                "active_pools": ad["active_pools"],
                "queue_depth": ad["queue_depth"],
                "slo_burn_rate": ad["slo_burn_rate"],
                "pfx_hit": ps["hit_rate"],
                "frozen_live": ps["frozen_live"],
                "imported": ps["imported"],
                "exported": ps["exported"],
                "migrated_in_bytes": ps["migrated_in_bytes"],
            }
        return out

    def close(self):
        for r in self.replicas:
            routers = getattr(r.engine.ctx, "_routers", None)
            if routers is not None and self in routers:
                routers.remove(self)
            r.engine.close()
