"""Continuous-batching LLM inference engine over paged KV-cache DAGs.

The serving scenario proving the runtime end-to-end (ROADMAP item 3):
many concurrent sequences, each owned by a tenant, generate tokens
step-by-step.  Every PREFILL is one admission-controlled taskpool
(Server front door: per-tenant budgets, QoS priority/weight); every
DECODE step builds one taskpool PER TENANT batching that tenant's
active sequences (continuous batching: sequences join after prefill and
retire mid-stream, pools churn every step).  KV pages are first-class
runtime tiles (ops/paged_attention.PagePool) budgeted by the admission
layer and managed by the device residency planner when a TpuDevice is
attached.

The model (PagedLM) is a deterministic single-layer attention LM in
f32 with a FIXED operation order — the engine's batched run and a
sequential per-request run produce bit-identical outputs, which is the
serve bench's correctness acceptance.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..data.collections import ReplicatedLocal, TwoDimBlockCyclic
from ..ops.paged_attention import (PagePool, SeqSpec, attend_heads,
                                   attend_page, finalize_attention,
                                   finalize_heads, build_paged_decode,
                                   build_paged_prefill, build_paged_verify,
                                   make_slot_collections, prefix_page_keys,
                                   reset_acc)
from .server import ResourceBusy, Server, TenantConfig

__all__ = ["PagedLMConfig", "PagedLM", "InferenceEngine", "RequestHandle"]


# ---------------------------------------------------------------- model
class PagedLMConfig:
    def __init__(self, vocab: int = 64, d: int = 16, page: int = 8,
                 seed: int = 0, heads: int = 1, qlog: bool = False):
        self.vocab, self.d, self.page, self.seed = vocab, d, page, seed
        # ptc-shard: `heads` independent attention heads (d must divide
        # evenly) — the tensor-parallel sharding unit; `qlog` quantizes
        # the output projection to a dyadic grid so the pre-logit
        # partial sums are EXACT in f32 (order-independent — the
        # cross-rank all-reduce is bit-identical to a single-rank run)
        assert d % max(1, heads) == 0, "d must divide by heads"
        self.heads = max(1, int(heads))
        self.qlog = bool(qlog)


class PagedLM:
    """Deterministic toy attention LM: fixed random embed/projections
    (f32).  qkv() and logits() are plain numpy with one op order, so
    every execution schedule reproduces the same bytes.

    Tensor-parallel vocabulary (ptc-shard): think of the weights laid
    on a 1-D mesh with a `tp` axis — qkv projections partitioned
    PartitionSpec(None, "tp") (column/head parallel), the output
    projection wo PartitionSpec("tp", None) (row parallel), embed
    replicated — the SNIPPETS [2]/[3] layout-rule shape ("heads" ->
    "mp").  `shard_slice`/`wo_shard` hand each rank its contiguous
    head-block; partial projections sum across ranks (all-reduce).

    `qlog` mode snaps attention outputs to the 1/256 grid and wo to the
    1/8 grid: every pre-logit partial product is a small dyadic
    rational, so f32 sums are exact in ANY association — the integer-
    valued-f32 trick the coll tests use, applied to the model head, and
    the reason a tp=2/tp=4 run is BIT-identical to tp=1."""

    def __init__(self, cfg: PagedLMConfig):
        self.cfg = cfg
        # prefix-cache identity: a page's KV bytes are a pure function
        # of (model_id, token-id prefix), so the content-hash index is
        # keyed by both — two engines sharing one PagePool but serving
        # different weights can never cross-hit.  Non-default heads /
        # qlog change the bytes, so they suffix the id (defaults keep
        # the historical id: existing frozen-key baselines stand).
        self.model_id = (f"paged-lm:v{cfg.vocab}:d{cfg.d}:"
                         f"p{cfg.page}:s{cfg.seed}")
        if cfg.heads != 1:
            self.model_id += f":h{cfg.heads}"
        if cfg.qlog:
            self.model_id += ":q"
        rng = np.random.RandomState(cfg.seed)
        d, v = cfg.d, cfg.vocab
        self.embed = rng.randn(v, d).astype(np.float32) * np.float32(0.5)
        self.wq = rng.randn(d, d).astype(np.float32) * np.float32(d ** -0.5)
        self.wk = rng.randn(d, d).astype(np.float32) * np.float32(d ** -0.5)
        self.wv = rng.randn(d, d).astype(np.float32) * np.float32(d ** -0.5)
        self.wo = rng.randn(d, d).astype(np.float32) * np.float32(d ** -0.5)
        if cfg.qlog:
            self.wo = (np.round(self.wo * np.float32(8.0)) /
                       np.float32(8.0)).astype(np.float32)
        self.dh = d // cfg.heads
        self.scale = float(self.dh) ** -0.5  # == d**-0.5 for heads=1

    def qkv(self, token: int):
        e = self.embed[int(token)]
        return e @ self.wq, e @ self.wk, e @ self.wv

    @staticmethod
    def quant_o(o: np.ndarray) -> np.ndarray:
        """Snap an attention output to the 1/256 dyadic grid (qlog)."""
        return (np.round(o * np.float32(256.0)) /
                np.float32(256.0)).astype(np.float32)

    def pre_logits(self, o: np.ndarray) -> np.ndarray:
        """Output projection (pre-embedding-tie logits) — the quantity
        the tp ranks produce partially and all-reduce."""
        if self.cfg.qlog:
            return self.quant_o(o) @ self.wo
        return o @ self.wo

    def logits_from_pre(self, pre: np.ndarray) -> np.ndarray:
        return pre @ self.embed.T.astype(np.float32)

    def logits(self, o: np.ndarray) -> np.ndarray:
        return self.logits_from_pre(self.pre_logits(o))

    def next_token(self, o: np.ndarray) -> int:
        return int(np.argmax(self.logits(o)))

    def next_token_pre(self, pre: np.ndarray) -> int:
        return int(np.argmax(self.logits_from_pre(pre)))

    # --------------------------------------------- tensor-parallel view
    def shard_slice(self, rank: int, tp: int) -> slice:
        """This rank's contiguous head-block of the model dim: heads
        [rank*hl, (rank+1)*hl) with hl = heads/tp — dims
        [rank*dl, (rank+1)*dl), dl = hl*dh."""
        assert self.cfg.heads % tp == 0, "heads must divide by tp"
        dl = (self.cfg.heads // tp) * self.dh
        return slice(rank * dl, (rank + 1) * dl)

    def wo_shard(self, rank: int, tp: int) -> np.ndarray:
        """Row-parallel wo shard: the rows matched to this rank's head
        block (partial products sum exactly under qlog)."""
        return np.ascontiguousarray(self.wo[self.shard_slice(rank, tp), :])

    # ------------------------------------------------- numpy reference
    def reference_generate(self, prompt: Sequence[int], max_new: int,
                           page: Optional[int] = None):
        """Pure-numpy oracle using the SAME page blocking and fold order
        as the DAG (attend_heads per page) — bit-identical to the
        engine at ANY tp degree.  Returns (tokens, outputs[n_steps, d])."""
        P = self.cfg.page if page is None else page
        d, H = self.cfg.d, self.cfg.heads
        ks: List[np.ndarray] = []
        vs: List[np.ndarray] = []
        toks = [int(t) for t in prompt]
        for t in toks:
            _, k, v = self.qkv(t)
            ks.append(k)
            vs.append(v)
        outs = []
        at = np.zeros((1, d + 2 * H), np.float32)
        for _ in range(max_new):
            q = self.qkv(toks[-1])[0]
            reset_acc(at, H)
            for off in range(0, len(ks), P):
                K = np.stack(ks[off:off + P])
                V = np.stack(vs[off:off + P])
                attend_heads(q, K, V, at, self.scale, H)
            o = finalize_heads(at, H)
            outs.append(o)
            nxt = self.next_token(o)
            toks.append(nxt)
            _, k, v = self.qkv(nxt)
            ks.append(k)
            vs.append(v)
        return toks, np.stack(outs) if outs else np.zeros((0, d), np.float32)


# ------------------------------------------------------------- requests
class RequestHandle:
    """One inference request's lifecycle: prefill ticket (admission) +
    generated tokens/outputs filled in by the decode loop."""

    __slots__ = ("rid", "tenant", "prompt", "max_new", "ticket", "tokens",
                 "outputs", "state", "submitted_t", "done_t", "_seq",
                 "scope_id")

    def __init__(self, rid: int, tenant: str, prompt: Sequence[int],
                 max_new: int):
        self.rid = rid
        self.tenant = tenant
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.ticket = None
        self.scope_id: Optional[int] = None  # ptc-scope request id
        self.tokens: List[int] = list(self.prompt)
        self.outputs: List[np.ndarray] = []
        self.state = "submitted"  # -> active -> done | rejected | failed
        self.submitted_t = time.monotonic()
        self.done_t: Optional[float] = None
        self._seq = None

    @property
    def latency_s(self) -> Optional[float]:
        if self.done_t is None:
            return None
        return self.done_t - self.submitted_t

    @property
    def generated(self) -> List[int]:
        return self.tokens[len(self.prompt):]


class _Seq:
    """Engine-internal active-sequence state."""

    __slots__ = ("req", "slot", "pages", "length", "remaining")

    def __init__(self, req: RequestHandle, slot: int, pages: List[int],
                 length: int):
        self.req = req
        self.slot = slot
        self.pages = pages
        self.length = length          # tokens materialized in pages
        self.remaining = req.max_new  # decode steps left


# --------------------------------------------------------------- engine
class InferenceEngine:
    """Continuous-batching driver.

    submit() routes each request's PREFILL pool through the Server
    (admission + tenant QoS); step() builds one DECODE pool per tenant
    over that tenant's active sequences, runs them concurrently (the
    scheduler's QoS lanes arbitrate), applies the model head, appends
    tokens, and retires finished sequences (pages + slots freed, pools
    destroyed).  run() loops until every request is terminal.

    `body_wrap` wraps every decode PATTL body — the fault-injection seam
    the watchdog tail-latency e2e uses.

    Tensor-parallel mode (`tp` > 1, ptc-shard): construct the SAME
    engine on every rank of a tp-rank comm group (SPMD) and drive the
    SAME submit sequence on each.  Per step the ranks' pools are
    coupled by the embedded all-reduce, so step() is naturally
    barriered by the collective itself.  Driving contract: let every
    submitted prefill complete (handle.state == "active") on a rank
    before that rank enters its decode step loop — mid-stream joins
    would need a cross-rank agreement layer the engine does not
    provide."""

    def __init__(self, ctx, model: PagedLM, n_pages: int = 64,
                 max_seqs: int = 16, server: Optional[Server] = None,
                 tenants: Optional[List[TenantConfig]] = None,
                 name: str = "eng", body_wrap: Optional[Callable] = None,
                 dev=None, conformance: bool = True,
                 prefix_cache: bool = True, spec_k=0,
                 spec_draft="self", tp: int = 1):
        cfg = model.cfg
        self.ctx = ctx
        self.model = model
        # ptc-shard: tensor-parallel serving across a rank group.  The
        # engine is constructed SPMD on every rank of the group (one
        # process-local ctx per rank, comm-initialized): each rank owns
        # the KV pages and slot scratch for ITS contiguous head block
        # (d_local = d/tp — the model is bigger than one rank's pages),
        # and every decode/verify/prefill pool embeds a RefReduce
        # all-reduce chain summing the per-rank partial pre-logit
        # projections.  qlog quantization makes those sums exact, so
        # tp>1 output bytes equal the single-rank reference's.
        self.tp = max(1, int(tp))
        if self.tp > 1:
            assert ctx.nodes == self.tp, \
                f"tp={self.tp} needs a {self.tp}-rank ctx (nodes={ctx.nodes})"
            assert cfg.heads % self.tp == 0, "heads must divide by tp"
            assert cfg.qlog, \
                "tp>1 requires qlog=True (exact cross-rank partial sums)"
        self.rank = ctx.myrank if self.tp > 1 else 0
        self._nh = cfg.heads // self.tp            # heads held locally
        self._dl = self._nh * model.dh             # local model-dim slice
        self._shard_sl = slice(self.rank * self._dl,
                               (self.rank + 1) * self._dl)
        self._wo_s = model.wo_shard(self.rank, self.tp) \
            if self.tp > 1 else None
        nodes = ctx.nodes if self.tp > 1 else 1
        # ptc-share serving fast path: `prefix_cache` turns the shared
        # copy-on-write prompt-prefix index on (default); `spec_k` > 0
        # turns on speculative decoding — a draft model proposes k
        # tokens per sequence per step and ONE batched verify wave of
        # the target model checks them all (greedy accept / longest-
        # prefix reject, page-table rollback on rejection).
        # `spec_draft` is the proposer: "self" (the target's own
        # argmax chain — the oracle upper bound) or any PagedLM.
        # ptc-pilot: spec_k="auto" turns on per-tenant ADAPTIVE
        # speculation — scratch sizes for control.spec_k_max, and each
        # tenant's live k tracks its own acceptance window (shrink on
        # low acceptance, pause under page pressure, grow back on
        # sustained high acceptance).  Acceptance is a pure function of
        # draft-vs-target token agreement, so every k emits the same
        # bit-exact stream — the policy only moves the work/latency
        # trade-off, never the tokens.
        from ..utils import params as _mca
        self._spec_auto = (spec_k == "auto")
        if self._spec_auto:
            try:
                self.spec_k = max(1, int(_mca.get("control.spec_k_max")))
            except Exception:
                self.spec_k = 4
        else:
            self.spec_k = max(0, int(spec_k))
        try:
            self._spec_window = max(1, int(_mca.get("control.spec_window")))
            self._spec_low = float(_mca.get("control.spec_accept_low"))
            self._spec_high = float(_mca.get("control.spec_accept_high"))
            self._spec_floor = float(_mca.get("control.spec_page_floor"))
        except Exception:
            self._spec_window, self._spec_low = 4, 0.45
            self._spec_high, self._spec_floor = 0.80, 0.25
        self._spec_state: Dict[str, dict] = {}  # tenant -> bandit state
        self.prefix_cache = bool(prefix_cache)
        self.spec_draft = (model if spec_draft in (None, "self")
                           else spec_draft)
        # ptc-scope: per-request scopes (TTFT/tokens-per-s SLO feed) +
        # per-decode-step shared scopes; conformance=True statically
        # plans each decode pool so plan-vs-measured stays covered
        self.scope = ctx.scope_registry()
        self.conformance = bool(conformance)
        # KV pages shard BY HEAD: one PagePool per rank holding the
        # d_local columns of every page — refcount/COW/freeze semantics
        # are untouched (frozen keys digest token ids, so the per-shard
        # content chains are deterministic and rank-consistent)
        self.pool = PagePool(ctx, n_pages, cfg.page, self._dl,
                             name=f"{name}_KV", nodes=nodes,
                             myrank=self.rank)
        (self.Qc, self.ACCc, self.Oc, self.KNc,
         self.slot_names) = make_slot_collections(ctx, max_seqs, self._dl,
                                                  name=f"{name}_PA",
                                                  nh=self._nh, nodes=nodes,
                                                  myrank=self.rank)
        self.max_seqs = max_seqs
        self._free_slots = list(range(max_seqs - 1, -1, -1))
        # speculative verify scratch: one (Q, ACC, O) row per (sequence
        # slot, query position) — slot s's query i lives at row
        # s * (spec_k + 1) + i, so no allocator is needed
        if self.spec_k:
            (self.SQc, self.SACCc, self.SOc, _,
             self.spec_names) = make_slot_collections(
                ctx, max_seqs * (self.spec_k + 1), self._dl,
                name=f"{name}_SV", nh=self._nh, nodes=nodes,
                myrank=self.rank)
        self.server = server or Server(
            ctx, tenants or [TenantConfig("default")], name=name)
        # stats()["serve"] grows the pool's prefix-cache counters and
        # the engine's speculative-decode counters
        self.server.register_resource_stats("prefix", self.pool.stats)
        self.server.register_resource_stats("spec", self._spec_stats)
        self.server.register_resource_stats("tp", self._tp_stats)
        # ptc-route: the frozen-page key digest a fleet router scores
        # placements against (Server.advertise()["prefix"])
        self.server.register_advertiser("prefix", self._prefix_advert)
        self.body_wrap = body_wrap
        self.dev = dev
        self._lock = threading.Lock()
        self._active: List[_Seq] = []
        self._inflight: Dict[str, tuple] = {}  # tenant -> (tp, seqs, ev)
        self._next_rid = 0
        self._next_prompt_tile = 0
        self._prompt_coll_name = f"{name}_PR"
        # staged prompt k|v pages; grows with the largest in-flight
        # prompt set (tiles recycle per prefill pool)
        self._prompt_tiles = 256
        if self.tp > 1:
            self.PRc = ReplicatedLocal(self._prompt_tiles * cfg.page,
                                       2 * self._dl, cfg.page,
                                       2 * self._dl, nodes=nodes,
                                       myrank=self.rank,
                                       dtype=np.float32)
        else:
            self.PRc = TwoDimBlockCyclic(self._prompt_tiles * cfg.page,
                                         2 * self._dl, cfg.page,
                                         2 * self._dl, dtype=np.float32)
        self.PRc.register(ctx, self._prompt_coll_name)
        self.requests: List[RequestHandle] = []
        self.stats = {"decode_pools": 0, "decode_steps": 0,
                      "prefills": 0, "retired": 0, "page_stalls": 0,
                      "prefix_hits": 0, "prefix_misses": 0,
                      "cow_copies": 0, "spec_steps": 0,
                      "spec_proposed": 0, "spec_accepted": 0,
                      "spec_fallbacks": 0, "tp_coll_pools": 0,
                      "tp_coll_wait_ns": 0}
        # ptc-pilot: a Controller created before the engine gets its
        # resource levers (cached-free shares, admission pressure,
        # per-tenant spec_k) bound automatically
        ctrl = getattr(ctx, "_controller", None)
        if ctrl is not None:
            try:
                ctrl.bind_engine(self)
            except Exception:
                pass

    def _prefix_advert(self) -> dict:
        """Advertisement payload (Server.advertise()["prefix"], schema
        in MIGRATION.md): the exact frozen content-key set plus the
        scalars a router needs to convert predicted hits into bytes."""
        keys = self.pool.frozen_keys()
        return {"mode": "set", "n": len(keys),
                "keys": [str(k) for k in keys],
                "model_id": self.model.model_id,
                "page_bytes": self.pool.bytes_per_page,
                "free_pages": self.pool.free_pages}

    def _spec_stats(self) -> dict:
        with self._lock:
            prop = self.stats["spec_proposed"]
            acc = self.stats["spec_accepted"]
            return {
                "enabled": self.spec_k > 0, "k": self.spec_k,
                "auto": self._spec_auto,
                "k_by_tenant": {t: (0 if st["paused"] else st["k"])
                                for t, st in
                                sorted(self._spec_state.items())},
                "steps": self.stats["spec_steps"],
                "proposed": prop, "accepted": acc,
                "fallbacks": self.stats["spec_fallbacks"],
                "accept_rate": (acc / prop) if prop else 0.0,
            }

    def _tp_stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.tp > 1, "tp": self.tp, "rank": self.rank,
                "heads_local": self._nh, "d_local": self._dl,
                "coll_pools": self.stats["tp_coll_pools"],
                "coll_wait_ns": self.stats["tp_coll_wait_ns"],
            }

    # ------------------------------------------------- tp shard plumbing
    def _project(self, o: np.ndarray) -> np.ndarray:
        """This rank's partial output projection: the local head-block's
        attention output against the matching wo rows.  Under qlog every
        partial product is dyadic-exact, so the cross-rank sum equals
        the full-width projection BITWISE."""
        return self.model.quant_o(o) @ self._wo_s

    def _mk_shard(self, nseg: int):
        """Per-pool shard record: the dict build_paged_* hands to
        _wire_shard (rank identity + projection + delivery sink) and the
        reap-side record carrying the reduced pre-logit buffer plus the
        coll-wait instants (local shard done -> reduced vector back)."""
        d = self.model.cfg.d
        buf = np.zeros((nseg, d), np.float32)
        t_loc = np.zeros(nseg, np.int64)
        t_del = np.zeros(nseg, np.int64)

        def mark(seg, t=t_loc):
            t[seg] = time.monotonic_ns()

        def sink(seg, slc, x, buf=buf, t=t_del):
            # RefReduce fanout uses ns=1 (the pre-logit vector is one
            # slice); x is the whole reduced segment
            buf[seg, :x.size] = x
            t[seg] = time.monotonic_ns()

        shard = {"rank": self.rank, "nranks": self.tp, "dm": d,
                 "project": self._project, "sink": sink, "local": mark}
        return shard, {"buf": buf, "t_local": t_loc, "t_deliver": t_del}

    def _coll_wait(self, srec, tenant: str) -> int:
        """Fold one reaped pool's coll-wait instants into the stats +
        the tenant's live scope feed; returns the pool's max wait (the
        step's critical-path exposure to the wire)."""
        waits = np.maximum(srec["t_deliver"] - srec["t_local"], 0)
        total = int(waits.sum())
        with self._lock:
            self.stats["tp_coll_pools"] += 1
            self.stats["tp_coll_wait_ns"] += total
        self.scope.record_coll_wait(tenant, int(waits.max()) if
                                    waits.size else 0, n=int(waits.size))
        return int(waits.max()) if waits.size else 0

    def _host_wrote(self, coll, m: int, n: int = 0):
        """The engine rewrote a slot tile's HOST bytes directly (numpy,
        outside the runtime) — with a device attached, any mirror of it
        is stale and must drop (the copy version cannot tell: no
        runtime write happened)."""
        if self.dev is None:
            return
        self.ctx.host_wrote(coll, m, n)

    # ------------------------------------------------------ prefix keys
    def _page_keys(self, prompt: Sequence[int]) -> List[str]:
        """Content-hash keys for a prompt's FULL pages — the shared
        ops.paged_attention.prefix_page_keys chain (ptc-route: the fleet
        router and the migration wire compute the SAME keys without an
        engine in hand, so a router-predicted warm hit is exactly what
        acquire_prefix will find)."""
        return prefix_page_keys(self.model.model_id, prompt,
                                self.model.cfg.page)

    # ------------------------------------------------------------ submit
    def submit(self, prompt: Sequence[int], max_new: int,
               tenant: str = "default") -> RequestHandle:
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        req = RequestHandle(rid, tenant, prompt, max_new)
        req.scope_id = self.scope.new_scope(tenant, rid=rid,
                                            meta={"prompt": len(req.prompt),
                                                  "max_new": max_new})
        self.requests.append(req)
        P = self.model.cfg.page
        n_pages = (len(req.prompt) + P - 1) // P
        est = n_pages * self.pool.bytes_per_page
        # admission-time prefix discount: pages predicted to map onto
        # existing frozen pages cost the pool nothing — the byte budget
        # sees only the cold tail (plan-side twin: Plan.est_bytes'
        # discount_bytes parameter)
        discount = 0
        if self.prefix_cache:
            discount = self.pool.probe(self._page_keys(req.prompt)) * \
                self.pool.bytes_per_page
        req.ticket = self.server.submit(
            tenant, lambda priority, weight, req=req: self._build_prefill(
                req, priority, weight),
            est_bytes=est, est_discount_bytes=discount,
            meta={"rid": rid}, scope=req.scope_id)
        if req.ticket.state == "rejected":
            req.state = "rejected"
            req.done_t = time.monotonic()
        return req

    def prefill_warm(self, prompt: Sequence[int],
                     tenant: str = "default") -> RequestHandle:
        """Disaggregated-prefill entry point (ptc-route): prefill the
        prompt, FREEZE its full pages into the prefix cache, emit
        nothing.  A prefill-role replica runs these so a decode-role
        replica can import the frozen pages (page migration) and serve
        the real request fully warm."""
        return self.submit(prompt, max_new=0, tenant=tenant)

    def _build_prefill(self, req: RequestHandle, priority, weight):
        """Server-side builder: admit the page table ATOMICALLY —
        `acquire_prefix` maps the longest warm prefix onto existing
        frozen pages (refcount++) and reserves only the cold tail in
        one pool-lock transaction (ResourceBusy when it doesn't fit —
        backpressure, with no half-taken pages) — then stage the COLD
        prompt k|v and build the pool: a warm page never re-prefills."""
        cfg = self.model.cfg
        P, d = cfg.page, cfg.d
        T = len(req.prompt)
        n_pages = (T + P - 1) // P
        keys = self._page_keys(req.prompt) if self.prefix_cache else []
        with self._lock:
            if not self._free_slots:
                self.stats["page_stalls"] += 1
                raise ResourceBusy("slots=0")
            got = self.pool.acquire_prefix(keys, n_pages)
            if got is None:
                self.stats["page_stalls"] += 1
                raise ResourceBusy(
                    f"pages={self.pool.free_pages}<{n_pages}")
            pages, warm = got
            slot = self._free_slots.pop()
            self.stats["prefix_hits"] += warm
            self.stats["prefix_misses"] += n_pages - warm
            ptile0 = self._next_prompt_tile
            self._next_prompt_tile = (ptile0 + n_pages) % \
                self._prompt_tiles
        self.scope.record_prefix(req.tenant, hits=warm,
                                 misses=n_pages - warm)
        # stage COLD prompt k|v into the PR collection + the last
        # token's q; warm pages already hold their rows (frozen).  In tp
        # mode the FULL qkv rows are computed and this rank's head block
        # sliced out — projection numerics never depend on the shard.
        dl, sl = self._dl, self._shard_sl
        kv = np.zeros((n_pages * P, 2 * dl), np.float32)
        for i, tok in enumerate(req.prompt):
            if i < warm * P:
                continue
            _, k, v = self.model.qkv(tok)
            kv[i, :dl] = k[sl]
            kv[i, dl:] = v[sl]
        ptiles = [(ptile0 + i) % self._prompt_tiles
                  for i in range(n_pages)]
        for i, pt_i in enumerate(ptiles):
            if i < warm:
                continue
            self.PRc.tile(pt_i, 0)[...] = kv[i * P:(i + 1) * P]
            self._host_wrote(self.PRc, pt_i)
        q = self.model.qkv(req.prompt[-1])[0]
        self.Qc.tile(slot, 0)[0] = q[sl]
        reset_acc(self.ACCc.tile(slot, 0), self._nh)
        self._host_wrote(self.Qc, slot)
        self._host_wrote(self.ACCc, slot)
        fill = T - (n_pages - 1) * P
        spec = SeqSpec(slot, pages, fill)
        shard = srec = None
        if self.tp > 1:
            shard, srec = self._mk_shard(1)
        tp = build_paged_prefill(
            self.ctx, self.pool, [spec],
            {"Q": self.slot_names["Q"], "ACC": self.slot_names["ACC"],
             "O": self.slot_names["O"]},
            self._prompt_coll_name, [ptiles],
            scale=self.model.scale,
            priority=priority, weight=weight, warm=[warm],
            nh=self._nh, shard=shard)
        tp.on_complete(lambda: self._prefill_done(req, spec, warm, keys,
                                                  srec))
        self.stats["prefills"] += 1
        return tp

    def _prefill_done(self, req: RequestHandle, spec: SeqSpec,
                      warm: int = 0, keys: Optional[List[str]] = None,
                      srec: Optional[dict] = None):
        """Worker-thread callback: activate the sequence + consume the
        first decode output (the prefill chain already attended the
        last prompt position)."""
        # freeze the cold FULL pages under their content keys — the
        # next request sharing this prefix maps onto them (first
        # writer wins; the mutable last page never freezes)
        if keys:
            for j in range(warm, len(keys)):
                self.pool.freeze(spec.pages[j], keys[j],
                                 owner=req.tenant)
        if req.max_new <= 0:
            # prefill-warm (ptc-route disaggregated prefill role): the
            # request exists only to POPULATE the prefix cache — no
            # token is emitted, no TTFT recorded.  Retiring releases
            # the pages; the frozen full ones park on the cached LRU,
            # warm for export_frozen / the next acquire_prefix.
            seq = _Seq(req, spec.slot, spec.pages, len(req.prompt))
            req._seq = seq
            with self._lock:
                self._retire_locked(seq)
            return
        if srec is not None:
            # tp: token selection from the all-reduced pre-logits (the
            # same bytes on every rank); outputs carry the reduced
            # pre-logit vector in tp mode
            pre = srec["buf"][0].copy()
            req.outputs.append(pre)
            nxt = self.model.next_token_pre(pre)
            self._coll_wait(srec, req.tenant)
        else:
            o = self.Oc.tile(spec.slot, 0)[0].copy()
            req.outputs.append(o)
            nxt = self.model.next_token(o)
        req.tokens.append(nxt)
        # the prefill chain attended the last prompt position: this IS
        # the first generated token — the tenant TTFT histogram's feed
        self.scope.record_first_token(req.scope_id)
        seq = _Seq(req, spec.slot, spec.pages, len(req.prompt))
        seq.remaining = req.max_new - 1
        req._seq = seq
        req.state = "active"
        with self._lock:
            if seq.remaining <= 0:
                self._retire_locked(seq)
            else:
                self._active.append(seq)

    # -------------------------------------------------------------- step
    def _launch(self) -> int:
        """Build + run one decode pool per tenant that has active
        sequences and no decode pool in flight.  Tenants advance
        INDEPENDENTLY — a high-priority tenant's pools complete faster
        under the QoS lanes, so its tokens/sec (and latency) pull ahead
        instead of lock-stepping with every other tenant's wave."""
        cfg = self.model.cfg
        P, d = cfg.page, cfg.d
        with self._lock:
            ready: Dict[str, List[_Seq]] = {}
            for seq in self._active:
                tenant = seq.req.tenant
                if tenant in self._inflight:
                    continue
                # grow the page list when the last page is full
                if seq.length % P == 0 and len(seq.pages) * P <= \
                        seq.length:
                    p = self.pool.alloc()
                    if p is None:
                        self.stats["page_stalls"] += 1
                        continue
                    seq.pages.append(p)
                ready.setdefault(tenant, []).append(seq)
        launched = 0
        items = list(ready.items())
        if self.tp > 1:
            # SPMD discipline: every rank must build the SAME pool
            # sequence (the embedded RefReduce uids and class tables
            # must line up across ranks), so tenant build order and
            # per-tenant sequence order are made canonical
            items.sort(key=lambda kv: kv[0])
            for _, seqs in items:
                seqs.sort(key=lambda s: s.req.rid)
        for tenant, seqs in items:
            ts = self.server._tenants.get(tenant)
            prio, wt = (ts.cfg.priority, ts.cfg.weight) if ts else (0, 1)
            rec = None
            k = self._spec_k_for(tenant)
            if k:
                rec = self._stage_spec(seqs, prio, wt, k)
                if rec is None:  # page reservation failed: plain decode
                    with self._lock:
                        self.stats["spec_fallbacks"] += 1
                    self._spec_reserve_failed(tenant)
            if rec is None:
                rec = self._stage_decode(seqs, prio, wt)
            tp, staged, spec_info, srec = rec
            if not staged:
                tp.destroy()  # nothing stageable this wave (COW dry)
                continue
            # ptc-scope: one shared scope per decode step, with the
            # member rid order matching the spec order so EXEC spans'
            # sequence lane (locals[0]) maps back to each request; plan
            # the pool for the conformance record when enabled
            dsid = self.scope.new_scope(
                tenant,
                kind="spec_verify_step" if spec_info else "decode_step",
                members=[s.req.rid for s in staged])
            self.scope.stamp(tp, dsid)
            plan = None
            if self.conformance:
                try:
                    plan = self.scope.plan_summary(tp.plan())
                except Exception:
                    plan = None
            done = threading.Event()
            tp.on_complete(done.set)
            self._inflight[tenant] = (tp, staged, done, dsid, plan,
                                      time.monotonic_ns(), spec_info,
                                      srec)
            tp.run()
            self.stats["decode_pools"] += 1
            launched += 1
        return launched

    def _stage_decode(self, seqs, prio, wt):
        """Stage + build one NORMAL decode step over `seqs`.  A shared
        (prefix-cache) or frozen last page goes copy-on-write first:
        PUPD appends in place, and a sharer's view must never move.
        Returns (taskpool, staged sequences, None, shard record)."""
        cfg = self.model.cfg
        P = cfg.page
        dl, sl = self._dl, self._shard_sl
        specs, staged = [], []
        for seq in seqs:
            last = seq.pages[-1]
            if self.pool.refcount(last) > 1 or self.pool.is_frozen(last):
                priv = self.pool.make_private(last)
                if priv is None:  # clone pool dry: retry next wave
                    with self._lock:
                        self.stats["page_stalls"] += 1
                    continue
                if priv != last:
                    with self._lock:
                        self.stats["cow_copies"] += 1
                    seq.pages[-1] = priv
            tok = seq.req.tokens[-1]
            q, k, v = self.model.qkv(tok)
            self.Qc.tile(seq.slot, 0)[0] = q[sl]
            knrow = self.KNc.tile(seq.slot, 0)
            knrow[0, :dl] = k[sl]
            knrow[0, dl:] = v[sl]
            reset_acc(self.ACCc.tile(seq.slot, 0), self._nh)
            for coll in (self.Qc, self.KNc, self.ACCc):
                self._host_wrote(coll, seq.slot)
            specs.append(SeqSpec(seq.slot, seq.pages, seq.length % P))
            staged.append(seq)
        shard = srec = None
        if self.tp > 1 and specs:
            shard, srec = self._mk_shard(len(specs))
        tp = build_paged_decode(
            self.ctx, self.pool, specs, self.slot_names,
            scale=self.model.scale,
            priority=prio, weight=wt, body_wrap=self.body_wrap,
            dev=self.dev, nh=self._nh, shard=shard)
        return tp, staged, None, srec

    # -------------------------------------------- adaptive speculation
    def _spec_tenant_locked(self, tenant: str) -> dict:
        st = self._spec_state.get(tenant)
        if st is None:
            # optimistic start at k_max: the first windows measure the
            # tenant's real acceptance and shrink from there
            st = {"k": self.spec_k, "paused": False,
                  "accepts": deque(maxlen=self._spec_window)}
            self._spec_state[tenant] = st
        return st

    def _spec_event(self, tenant: str, ev: Optional[dict]):
        if ev is not None:
            self.scope.record_event("control_spec", tenant=tenant, **ev)

    def _spec_k_for(self, tenant: str) -> int:
        """The k this tenant speculates with THIS wave.  Fixed spec_k
        passes through; auto mode reads the tenant's bandit state and
        the pool's free fraction — under page pressure speculation
        pauses (k=0: private verify clones are the first load to shed),
        resuming at the remembered k once pressure clears."""
        if not self.spec_k:
            return 0
        if not self._spec_auto:
            return self.spec_k
        frac = self.pool.free_pages / max(1, self.pool.n_pages)
        ev, k = None, 0
        with self._lock:
            st = self._spec_tenant_locked(tenant)
            if frac < self._spec_floor:
                if not st["paused"]:
                    st["paused"] = True
                    ev = {"k_from": st["k"], "k_to": 0,
                          "reason": "page_pressure",
                          "free_frac": round(frac, 4)}
            else:
                if st["paused"]:
                    st["paused"] = False
                    st["accepts"].clear()
                    ev = {"k_from": 0, "k_to": st["k"],
                          "reason": "pressure_cleared",
                          "free_frac": round(frac, 4)}
                k = st["k"]
        self._spec_event(tenant, ev)  # outside the engine lock
        return k

    def _spec_reserve_failed(self, tenant: str):
        """All-or-nothing page reservation failed mid-stage: treat it
        as pressure (the free-fraction gate raced a concurrent
        allocation) and pause this tenant's speculation."""
        if not self._spec_auto:
            return
        ev = None
        with self._lock:
            st = self._spec_tenant_locked(tenant)
            if not st["paused"]:
                st["paused"] = True
                ev = {"k_from": st["k"], "k_to": 0,
                      "reason": "reserve_failed"}
        self._spec_event(tenant, ev)

    def _spec_observe(self, tenant: str, proposed: int, accepted: int):
        """Fold one reaped verify wave's acceptance into the tenant's
        window; on a FULL window, halve k below control.spec_accept_low
        and grow k+1 at/above control.spec_accept_high (window clears on
        every move — full-window hysteresis, deterministic for a given
        token stream)."""
        if not self._spec_auto or proposed <= 0:
            return
        ev = None
        with self._lock:
            st = self._spec_tenant_locked(tenant)
            st["accepts"].append(accepted / proposed)
            if len(st["accepts"]) == self._spec_window:
                mean = sum(st["accepts"]) / self._spec_window
                k = st["k"]
                if mean < self._spec_low and k > 1:
                    st["k"] = max(1, k // 2)
                    st["accepts"].clear()
                    ev = {"k_from": k, "k_to": st["k"],
                          "reason": "accept_low",
                          "accept": round(mean, 4)}
                elif mean >= self._spec_high and k < self.spec_k:
                    st["k"] = k + 1
                    st["accepts"].clear()
                    ev = {"k_from": k, "k_to": st["k"],
                          "reason": "accept_high",
                          "accept": round(mean, 4)}
        self._spec_event(tenant, ev)

    def spec_k_snapshot(self) -> dict:
        """Controller/monitor view of live per-tenant speculation
        (stats()["control"]["spec_k"])."""
        with self._lock:
            return {"auto": self._spec_auto, "max": self.spec_k,
                    "tenants": {t: (0 if st["paused"] else st["k"])
                                for t, st in
                                sorted(self._spec_state.items())}}

    def _stage_spec(self, seqs, prio, wt, k: Optional[int] = None):
        """Stage + build one SPECULATIVE decode step over `seqs`: the
        draft proposes up to k tokens per sequence, and the k+1 query
        positions (current token + each draft token) verify in ONE
        batched target-model wave (build_paged_verify — the VATF wave
        is homogeneous, so PR 13 fuses it to a single launch).

        Per (sequence, query i): the query window's pages — every page
        touched by rows L..L+i — are PRIVATE clones (existing rows
        copied, speculative k|v rows host-staged), while pages wholly
        below row L stay shared read-only; the fold then reproduces the
        sequential decode step for position L+i bit-exactly.  Page
        reservation is all-or-nothing against the refcounted pool:
        shortfall returns None and the caller falls back to plain
        decode (never half-speculates).  Returns
        (taskpool, sequences, per-seq speculation records, shard
        record)."""
        cfg = self.model.cfg
        P = cfg.page
        dl, hsl = self._dl, self._shard_sl
        dm = self.spec_draft
        # per-wave k (adaptive speculation): scratch rows and vslot
        # stride stay sized for spec_k (the max), only nq shrinks
        k = self.spec_k if k is None else min(int(k), self.spec_k)
        nq_tot = 0
        layout = []
        for seq in seqs:
            L = seq.length
            nq = min(k + 1, seq.remaining)
            pbase = L // P
            cnt = sum(((L + i) // P + 1) - pbase for i in range(nq))
            layout.append((seq, L, nq, pbase, cnt))
            nq_tot += nq
        total_pages = sum(c for _, _, _, _, c in layout)
        pages = self.pool.reserve(total_pages)
        if pages is None:
            return None
        take = iter(pages)
        vspecs, recs = [], []
        for seq, L, nq, pbase, _cnt in layout:
            # draft proposals: the draft model's own greedy chain over
            # the sequence's tokens (for spec_draft="self" this is the
            # target's argmax chain — the oracle acceptance bound)
            toks = list(seq.req.tokens)
            g = dm.reference_generate(toks, nq - 1)[0][len(toks):] \
                if nq > 1 else []
            u = [toks[-1]] + [int(t) for t in g]
            kvs = [self.model.qkv(t) for t in u]  # (q, k, v) per query
            base_rows = L - pbase * P  # existing rows in the base page
            privs = []
            for i in range(nq):
                npg = (L + i) // P + 1
                priv = [next(take) for _ in range(npg - pbase)]
                # copy the base page's existing rows, then host-stage
                # the speculative rows u[0..i] at absolute rows L..L+i
                if base_rows:
                    src = seq.pages[pbase]
                    self.pool.k_tile(priv[0])[:base_rows] = \
                        self.pool.k_tile(src)[:base_rows]
                    self.pool.v_tile(priv[0])[:base_rows] = \
                        self.pool.v_tile(src)[:base_rows]
                for r in range(L, L + i + 1):
                    pg = priv[r // P - pbase]
                    _, k, v = kvs[r - L]
                    self.pool.k_tile(pg)[r % P] = k[hsl]
                    self.pool.v_tile(pg)[r % P] = v[hsl]
                for pg in priv:
                    self.pool.host_wrote(pg)
                vslot = seq.slot * (self.spec_k + 1) + i
                self.SQc.tile(vslot, 0)[0] = kvs[i][0][hsl]
                reset_acc(self.SACCc.tile(vslot, 0), self._nh)
                self._host_wrote(self.SQc, vslot)
                self._host_wrote(self.SACCc, vslot)
                R = L + 1 + i
                vspecs.append(SeqSpec(
                    vslot, seq.pages[:pbase] + priv,
                    R - ((L + i) // P) * P))
                privs.append(priv)
            recs.append({"seq": seq, "nq": nq, "g": [int(t) for t in g],
                         "pbase": pbase, "privs": privs})
        shard = srec = None
        if self.tp > 1 and vspecs:
            shard, srec = self._mk_shard(len(vspecs))
        tp = build_paged_verify(
            self.ctx, self.pool, vspecs, self.spec_names,
            scale=self.model.scale,
            priority=prio, weight=wt, body_wrap=self.body_wrap,
            dev=self.dev, nh=self._nh, shard=shard)
        return tp, seqs, recs, srec

    def _reap(self) -> int:
        """Consume completed decode pools: apply the model head, append
        tokens, retire finished sequences, destroy the pools.  Returns
        sequences advanced."""
        done = [(t, rec) for t, rec in self._inflight.items()
                if rec[2].is_set()]
        advanced = 0
        for tenant, (tp, seqs, _, dsid, plan, t0_ns, spec,
                     srec) in done:
            del self._inflight[tenant]
            coll_ns = None
            if spec is not None:
                advanced += self._reap_spec(tenant, spec, srec)
                if srec is not None:
                    coll_ns = self._coll_wait(srec, tenant)
            else:
                for k, seq in enumerate(seqs):
                    if srec is not None:
                        # tp: the all-reduced pre-logits (identical
                        # bytes on every rank) select the token; the
                        # staged order IS the segment order
                        o = srec["buf"][k].copy()
                        nxt = self.model.next_token_pre(o)
                    else:
                        o = self.Oc.tile(seq.slot, 0)[0].copy()
                        nxt = self.model.next_token(o)
                    seq.req.outputs.append(o)
                    seq.req.tokens.append(nxt)
                    seq.length += 1
                    seq.remaining -= 1
                    advanced += 1
                if srec is not None:
                    coll_ns = self._coll_wait(srec, tenant)
            # conformance: decode-step pool retired — compare the plan
            # snapshot against the measured step wall + lane counters
            qos = None
            try:
                qos = tp.qos_stats()
            except Exception:
                pass
            measured = {"wall_ns": time.monotonic_ns() - t0_ns}
            if coll_ns is not None:
                measured["coll_wait_ns"] = coll_ns
            self.scope.record_pool_done(dsid, qos=qos, plan=plan,
                                        measured=measured)
            tp.destroy()
            self.stats["decode_steps"] += 1
        with self._lock:
            for seq in [s for s in self._active if s.remaining <= 0]:
                self._retire_locked(seq)
        if done:
            # pool boundary: let an attached controller rebalance its
            # resource budgets (cached-free shares, admission pressure)
            ctrl = getattr(self.ctx, "_controller", None)
            if ctrl is not None:
                try:
                    ctrl.poll()
                except Exception:
                    pass
        return advanced

    def _reap_spec(self, tenant: str, recs, srec=None) -> int:
        """Consume one speculative verify wave: greedy accept — query i
        is valid while every earlier draft matched the target's own
        argmax — so the emitted (token, output) stream is BIT-IDENTICAL
        to sequential decode regardless of draft quality.  Rejected
        tokens roll back by truncating the page table: the losing
        queries' private pages release (refcounts make this free)."""
        advanced = 0
        vi = 0  # flat verify-spec index == srec segment index (tp)
        wave_prop = wave_acc = 0
        for rec in recs:
            seq, nq, g = rec["seq"], rec["nq"], rec["g"]
            pbase, privs = rec["pbase"], rec["privs"]
            outs, nxts = [], []
            for i in range(nq):
                if srec is not None:
                    o = srec["buf"][vi].copy()
                    vi += 1
                    outs.append(o)
                    nxts.append(self.model.next_token_pre(o))
                    continue
                vslot = seq.slot * (self.spec_k + 1) + i
                o = self.SOc.tile(vslot, 0)[0].copy()
                outs.append(o)
                nxts.append(self.model.next_token(o))
            j = 0  # query 0 is the plain decode position: always valid
            while j < nq - 1 and g[j] == nxts[j]:
                j += 1
            for i in range(j + 1):
                seq.req.outputs.append(outs[i])
                seq.req.tokens.append(nxts[i])
            # the deepest accepted query's window becomes the canonical
            # page-table tail; everything else rolls back to the pool
            old_tail = seq.pages[pbase:]
            seq.pages = seq.pages[:pbase] + privs[j]
            self.pool.release(old_tail + [
                p for i, priv in enumerate(privs) if i != j for p in priv])
            seq.length += j + 1
            seq.remaining -= j + 1
            advanced += j + 1
            with self._lock:
                self.stats["spec_steps"] += 1
                self.stats["spec_proposed"] += nq - 1
                self.stats["spec_accepted"] += j
            self.scope.record_spec(tenant, proposed=nq - 1, accepted=j)
            wave_prop += nq - 1
            wave_acc += j
        # adaptive speculation: one acceptance sample per verify wave
        self._spec_observe(tenant, wave_prop, wave_acc)
        return advanced

    def step(self) -> int:
        """Synchronous decode wave: launch every launchable tenant pool,
        wait for ALL in-flight pools, reap.  Returns sequences
        advanced (0 = nothing active)."""
        self._launch()
        for rec in list(self._inflight.values()):
            rec[2].wait()
        return self._reap()

    def _retire_locked(self, seq: _Seq):
        if seq in self._active:
            self._active.remove(seq)
        self.pool.free(seq.pages)
        self._free_slots.append(seq.slot)
        seq.req.state = "done"
        seq.req.done_t = time.monotonic()
        self.stats["retired"] += 1
        # request terminal: tenant latency/tokens-per-s SLO feed
        self.scope.record_done(seq.req.scope_id, state="done",
                               tokens=len(seq.req.generated))
        # pages/slots freed outside pool completion: unblock
        # ResourceBusy-paused tenants (lock order: engine -> server is
        # safe — server never calls into the engine under its lock)
        self.server.notify_resources()

    # --------------------------------------------------------------- run
    def pending(self) -> bool:
        with self._lock:
            active = bool(self._active)
        if active:
            return True
        for req in self.requests:
            if req.state in ("submitted", "active") and \
                    req.ticket is not None and \
                    req.ticket.state not in ("rejected", "failed",
                                             "cancelled"):
                return True
        return False

    def run(self, timeout_s: float = 120.0):
        """Drive the continuous-batching loop until every request is
        terminal: tenants launch and reap decode pools independently
        (QoS latency separation), the admission queue drains through
        the server's pump as capacity frees."""
        deadline = time.monotonic() + timeout_s
        while self.pending() or self._inflight:
            if time.monotonic() > deadline:
                raise TimeoutError("serving loop exceeded its deadline")
            launched = self._launch()
            reaped = self._reap()
            if not launched and not reaped:
                time.sleep(0.0005)  # waiting on pools / prefills
        # requests that never passed admission keep their terminal state
        for req in self.requests:
            if req.state == "submitted" and req.ticket is not None and \
                    req.ticket.state in ("rejected", "failed",
                                         "cancelled"):
                req.state = req.ticket.state
                req.done_t = req.done_t or time.monotonic()

    def close(self):
        self.server.close()
        if self.tp > 1:
            # RefReduce(bcast=True) leaves the fanout topology set on
            # the comm layer (per-pool restore would race concurrent
            # tenant pools; every step chooses the same topology).
            # Put the configured default back on teardown.
            from ..comm.coll import restore_topology
            restore_topology(self.ctx)
