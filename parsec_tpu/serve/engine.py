"""Continuous-batching LLM inference engine over paged KV-cache DAGs.

The serving scenario proving the runtime end-to-end (ROADMAP item 3):
many concurrent sequences, each owned by a tenant, generate tokens
step-by-step.  Every PREFILL is one admission-controlled taskpool
(Server front door: per-tenant budgets, QoS priority/weight); every
DECODE step builds one taskpool PER TENANT batching that tenant's
active sequences (continuous batching: sequences join after prefill and
retire mid-stream, pools churn every step).  KV pages are first-class
runtime tiles (ops/paged_attention.PagePool) budgeted by the admission
layer and managed by the device residency planner when a TpuDevice is
attached.

The model (PagedLM) is a deterministic single-layer attention LM in
f32 with a FIXED operation order — the engine's batched run and a
sequential per-request run produce bit-identical outputs, which is the
serve bench's correctness acceptance.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..data.collections import TwoDimBlockCyclic
from ..ops.paged_attention import (PagePool, SeqSpec, attend_page,
                                   finalize_attention, build_paged_decode,
                                   build_paged_prefill, build_paged_verify,
                                   make_slot_collections, prefix_page_keys,
                                   reset_acc)
from .server import ResourceBusy, Server, TenantConfig

__all__ = ["PagedLMConfig", "PagedLM", "InferenceEngine", "RequestHandle"]


# ---------------------------------------------------------------- model
class PagedLMConfig:
    def __init__(self, vocab: int = 64, d: int = 16, page: int = 8,
                 seed: int = 0):
        self.vocab, self.d, self.page, self.seed = vocab, d, page, seed


class PagedLM:
    """Deterministic toy attention LM: fixed random embed/projections
    (f32).  qkv() and logits() are plain numpy with one op order, so
    every execution schedule reproduces the same bytes."""

    def __init__(self, cfg: PagedLMConfig):
        self.cfg = cfg
        # prefix-cache identity: a page's KV bytes are a pure function
        # of (model_id, token-id prefix), so the content-hash index is
        # keyed by both — two engines sharing one PagePool but serving
        # different weights can never cross-hit
        self.model_id = (f"paged-lm:v{cfg.vocab}:d{cfg.d}:"
                         f"p{cfg.page}:s{cfg.seed}")
        rng = np.random.RandomState(cfg.seed)
        d, v = cfg.d, cfg.vocab
        self.embed = rng.randn(v, d).astype(np.float32) * np.float32(0.5)
        self.wq = rng.randn(d, d).astype(np.float32) * np.float32(d ** -0.5)
        self.wk = rng.randn(d, d).astype(np.float32) * np.float32(d ** -0.5)
        self.wv = rng.randn(d, d).astype(np.float32) * np.float32(d ** -0.5)
        self.wo = rng.randn(d, d).astype(np.float32) * np.float32(d ** -0.5)

    def qkv(self, token: int):
        e = self.embed[int(token)]
        return e @ self.wq, e @ self.wk, e @ self.wv

    def logits(self, o: np.ndarray) -> np.ndarray:
        return (o @ self.wo) @ self.embed.T.astype(np.float32)

    def next_token(self, o: np.ndarray) -> int:
        return int(np.argmax(self.logits(o)))

    # ------------------------------------------------- numpy reference
    def reference_generate(self, prompt: Sequence[int], max_new: int,
                           page: Optional[int] = None):
        """Pure-numpy oracle using the SAME page blocking and fold order
        as the DAG (attend_page per page) — bit-identical to the engine.
        Returns (tokens, outputs[n_steps, d])."""
        P = self.cfg.page if page is None else page
        d = self.cfg.d
        ks: List[np.ndarray] = []
        vs: List[np.ndarray] = []
        toks = [int(t) for t in prompt]
        for t in toks:
            _, k, v = self.qkv(t)
            ks.append(k)
            vs.append(v)
        outs = []
        for _ in range(max_new):
            q = self.qkv(toks[-1])[0]
            acc = np.zeros(d, np.float32)
            m, l = np.float32(-1.0e30), np.float32(0.0)
            for off in range(0, len(ks), P):
                K = np.stack(ks[off:off + P])
                V = np.stack(vs[off:off + P])
                acc, m, l = attend_page(q, K, V, acc, m, l, d ** -0.5)
            o = finalize_attention(acc, l)
            outs.append(o)
            nxt = self.next_token(o)
            toks.append(nxt)
            _, k, v = self.qkv(nxt)
            ks.append(k)
            vs.append(v)
        return toks, np.stack(outs) if outs else np.zeros((0, d), np.float32)


# ------------------------------------------------------------- requests
class RequestHandle:
    """One inference request's lifecycle: prefill ticket (admission) +
    generated tokens/outputs filled in by the decode loop."""

    __slots__ = ("rid", "tenant", "prompt", "max_new", "ticket", "tokens",
                 "outputs", "state", "submitted_t", "done_t", "_seq",
                 "scope_id")

    def __init__(self, rid: int, tenant: str, prompt: Sequence[int],
                 max_new: int):
        self.rid = rid
        self.tenant = tenant
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.ticket = None
        self.scope_id: Optional[int] = None  # ptc-scope request id
        self.tokens: List[int] = list(self.prompt)
        self.outputs: List[np.ndarray] = []
        self.state = "submitted"  # -> active -> done | rejected | failed
        self.submitted_t = time.monotonic()
        self.done_t: Optional[float] = None
        self._seq = None

    @property
    def latency_s(self) -> Optional[float]:
        if self.done_t is None:
            return None
        return self.done_t - self.submitted_t

    @property
    def generated(self) -> List[int]:
        return self.tokens[len(self.prompt):]


class _Seq:
    """Engine-internal active-sequence state."""

    __slots__ = ("req", "slot", "pages", "length", "remaining")

    def __init__(self, req: RequestHandle, slot: int, pages: List[int],
                 length: int):
        self.req = req
        self.slot = slot
        self.pages = pages
        self.length = length          # tokens materialized in pages
        self.remaining = req.max_new  # decode steps left


# --------------------------------------------------------------- engine
class InferenceEngine:
    """Continuous-batching driver.

    submit() routes each request's PREFILL pool through the Server
    (admission + tenant QoS); step() builds one DECODE pool per tenant
    over that tenant's active sequences, runs them concurrently (the
    scheduler's QoS lanes arbitrate), applies the model head, appends
    tokens, and retires finished sequences (pages + slots freed, pools
    destroyed).  run() loops until every request is terminal.

    `body_wrap` wraps every decode PATTL body — the fault-injection seam
    the watchdog tail-latency e2e uses."""

    def __init__(self, ctx, model: PagedLM, n_pages: int = 64,
                 max_seqs: int = 16, server: Optional[Server] = None,
                 tenants: Optional[List[TenantConfig]] = None,
                 name: str = "eng", body_wrap: Optional[Callable] = None,
                 dev=None, conformance: bool = True,
                 prefix_cache: bool = True, spec_k: int = 0,
                 spec_draft="self"):
        cfg = model.cfg
        self.ctx = ctx
        self.model = model
        # ptc-share serving fast path: `prefix_cache` turns the shared
        # copy-on-write prompt-prefix index on (default); `spec_k` > 0
        # turns on speculative decoding — a draft model proposes k
        # tokens per sequence per step and ONE batched verify wave of
        # the target model checks them all (greedy accept / longest-
        # prefix reject, page-table rollback on rejection).
        # `spec_draft` is the proposer: "self" (the target's own
        # argmax chain — the oracle upper bound) or any PagedLM.
        self.prefix_cache = bool(prefix_cache)
        self.spec_k = max(0, int(spec_k))
        self.spec_draft = (model if spec_draft in (None, "self")
                           else spec_draft)
        # ptc-scope: per-request scopes (TTFT/tokens-per-s SLO feed) +
        # per-decode-step shared scopes; conformance=True statically
        # plans each decode pool so plan-vs-measured stays covered
        self.scope = ctx.scope_registry()
        self.conformance = bool(conformance)
        self.pool = PagePool(ctx, n_pages, cfg.page, cfg.d,
                             name=f"{name}_KV")
        (self.Qc, self.ACCc, self.Oc, self.KNc,
         self.slot_names) = make_slot_collections(ctx, max_seqs, cfg.d,
                                                  name=f"{name}_PA")
        self.max_seqs = max_seqs
        self._free_slots = list(range(max_seqs - 1, -1, -1))
        # speculative verify scratch: one (Q, ACC, O) row per (sequence
        # slot, query position) — slot s's query i lives at row
        # s * (spec_k + 1) + i, so no allocator is needed
        if self.spec_k:
            (self.SQc, self.SACCc, self.SOc, _,
             self.spec_names) = make_slot_collections(
                ctx, max_seqs * (self.spec_k + 1), cfg.d,
                name=f"{name}_SV")
        self.server = server or Server(
            ctx, tenants or [TenantConfig("default")], name=name)
        # stats()["serve"] grows the pool's prefix-cache counters and
        # the engine's speculative-decode counters
        self.server.register_resource_stats("prefix", self.pool.stats)
        self.server.register_resource_stats("spec", self._spec_stats)
        # ptc-route: the frozen-page key digest a fleet router scores
        # placements against (Server.advertise()["prefix"])
        self.server.register_advertiser("prefix", self._prefix_advert)
        self.body_wrap = body_wrap
        self.dev = dev
        self._lock = threading.Lock()
        self._active: List[_Seq] = []
        self._inflight: Dict[str, tuple] = {}  # tenant -> (tp, seqs, ev)
        self._next_rid = 0
        self._next_prompt_tile = 0
        self._prompt_coll_name = f"{name}_PR"
        # staged prompt k|v pages; grows with the largest in-flight
        # prompt set (tiles recycle per prefill pool)
        self._prompt_tiles = 256
        self.PRc = TwoDimBlockCyclic(self._prompt_tiles * cfg.page,
                                     2 * cfg.d, cfg.page, 2 * cfg.d,
                                     dtype=np.float32)
        self.PRc.register(ctx, self._prompt_coll_name)
        self.requests: List[RequestHandle] = []
        self.stats = {"decode_pools": 0, "decode_steps": 0,
                      "prefills": 0, "retired": 0, "page_stalls": 0,
                      "prefix_hits": 0, "prefix_misses": 0,
                      "cow_copies": 0, "spec_steps": 0,
                      "spec_proposed": 0, "spec_accepted": 0,
                      "spec_fallbacks": 0}

    def _prefix_advert(self) -> dict:
        """Advertisement payload (Server.advertise()["prefix"], schema
        in MIGRATION.md): the exact frozen content-key set plus the
        scalars a router needs to convert predicted hits into bytes."""
        keys = self.pool.frozen_keys()
        return {"mode": "set", "n": len(keys),
                "keys": [str(k) for k in keys],
                "model_id": self.model.model_id,
                "page_bytes": self.pool.bytes_per_page,
                "free_pages": self.pool.free_pages}

    def _spec_stats(self) -> dict:
        with self._lock:
            prop = self.stats["spec_proposed"]
            acc = self.stats["spec_accepted"]
            return {
                "enabled": self.spec_k > 0, "k": self.spec_k,
                "steps": self.stats["spec_steps"],
                "proposed": prop, "accepted": acc,
                "fallbacks": self.stats["spec_fallbacks"],
                "accept_rate": (acc / prop) if prop else 0.0,
            }

    def _host_wrote(self, coll, m: int, n: int = 0):
        """The engine rewrote a slot tile's HOST bytes directly (numpy,
        outside the runtime) — with a device attached, any mirror of it
        is stale and must drop (the copy version cannot tell: no
        runtime write happened)."""
        if self.dev is None:
            return
        self.ctx.host_wrote(coll, m, n)

    # ------------------------------------------------------ prefix keys
    def _page_keys(self, prompt: Sequence[int]) -> List[str]:
        """Content-hash keys for a prompt's FULL pages — the shared
        ops.paged_attention.prefix_page_keys chain (ptc-route: the fleet
        router and the migration wire compute the SAME keys without an
        engine in hand, so a router-predicted warm hit is exactly what
        acquire_prefix will find)."""
        return prefix_page_keys(self.model.model_id, prompt,
                                self.model.cfg.page)

    # ------------------------------------------------------------ submit
    def submit(self, prompt: Sequence[int], max_new: int,
               tenant: str = "default") -> RequestHandle:
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        req = RequestHandle(rid, tenant, prompt, max_new)
        req.scope_id = self.scope.new_scope(tenant, rid=rid,
                                            meta={"prompt": len(req.prompt),
                                                  "max_new": max_new})
        self.requests.append(req)
        P = self.model.cfg.page
        n_pages = (len(req.prompt) + P - 1) // P
        est = n_pages * self.pool.bytes_per_page
        # admission-time prefix discount: pages predicted to map onto
        # existing frozen pages cost the pool nothing — the byte budget
        # sees only the cold tail (plan-side twin: Plan.est_bytes'
        # discount_bytes parameter)
        discount = 0
        if self.prefix_cache:
            discount = self.pool.probe(self._page_keys(req.prompt)) * \
                self.pool.bytes_per_page
        req.ticket = self.server.submit(
            tenant, lambda priority, weight, req=req: self._build_prefill(
                req, priority, weight),
            est_bytes=est, est_discount_bytes=discount,
            meta={"rid": rid}, scope=req.scope_id)
        if req.ticket.state == "rejected":
            req.state = "rejected"
            req.done_t = time.monotonic()
        return req

    def prefill_warm(self, prompt: Sequence[int],
                     tenant: str = "default") -> RequestHandle:
        """Disaggregated-prefill entry point (ptc-route): prefill the
        prompt, FREEZE its full pages into the prefix cache, emit
        nothing.  A prefill-role replica runs these so a decode-role
        replica can import the frozen pages (page migration) and serve
        the real request fully warm."""
        return self.submit(prompt, max_new=0, tenant=tenant)

    def _build_prefill(self, req: RequestHandle, priority, weight):
        """Server-side builder: admit the page table ATOMICALLY —
        `acquire_prefix` maps the longest warm prefix onto existing
        frozen pages (refcount++) and reserves only the cold tail in
        one pool-lock transaction (ResourceBusy when it doesn't fit —
        backpressure, with no half-taken pages) — then stage the COLD
        prompt k|v and build the pool: a warm page never re-prefills."""
        cfg = self.model.cfg
        P, d = cfg.page, cfg.d
        T = len(req.prompt)
        n_pages = (T + P - 1) // P
        keys = self._page_keys(req.prompt) if self.prefix_cache else []
        with self._lock:
            if not self._free_slots:
                self.stats["page_stalls"] += 1
                raise ResourceBusy("slots=0")
            got = self.pool.acquire_prefix(keys, n_pages)
            if got is None:
                self.stats["page_stalls"] += 1
                raise ResourceBusy(
                    f"pages={self.pool.free_pages}<{n_pages}")
            pages, warm = got
            slot = self._free_slots.pop()
            self.stats["prefix_hits"] += warm
            self.stats["prefix_misses"] += n_pages - warm
            ptile0 = self._next_prompt_tile
            self._next_prompt_tile = (ptile0 + n_pages) % \
                self._prompt_tiles
        self.scope.record_prefix(req.tenant, hits=warm,
                                 misses=n_pages - warm)
        # stage COLD prompt k|v into the PR collection + the last
        # token's q; warm pages already hold their rows (frozen)
        kv = np.zeros((n_pages * P, 2 * d), np.float32)
        for i, tok in enumerate(req.prompt):
            if i < warm * P:
                continue
            _, k, v = self.model.qkv(tok)
            kv[i, :d] = k
            kv[i, d:] = v
        ptiles = [(ptile0 + i) % self._prompt_tiles
                  for i in range(n_pages)]
        for i, pt_i in enumerate(ptiles):
            if i < warm:
                continue
            self.PRc.tile(pt_i, 0)[...] = kv[i * P:(i + 1) * P]
            self._host_wrote(self.PRc, pt_i)
        q = self.model.qkv(req.prompt[-1])[0]
        self.Qc.tile(slot, 0)[0] = q
        reset_acc(self.ACCc.tile(slot, 0))
        self._host_wrote(self.Qc, slot)
        self._host_wrote(self.ACCc, slot)
        fill = T - (n_pages - 1) * P
        spec = SeqSpec(slot, pages, fill)
        tp = build_paged_prefill(
            self.ctx, self.pool, [spec],
            {"Q": self.slot_names["Q"], "ACC": self.slot_names["ACC"],
             "O": self.slot_names["O"]},
            self._prompt_coll_name, [ptiles],
            priority=priority, weight=weight, warm=[warm])
        tp.on_complete(lambda: self._prefill_done(req, spec, warm, keys))
        self.stats["prefills"] += 1
        return tp

    def _prefill_done(self, req: RequestHandle, spec: SeqSpec,
                      warm: int = 0, keys: Optional[List[str]] = None):
        """Worker-thread callback: activate the sequence + consume the
        first decode output (the prefill chain already attended the
        last prompt position)."""
        # freeze the cold FULL pages under their content keys — the
        # next request sharing this prefix maps onto them (first
        # writer wins; the mutable last page never freezes)
        if keys:
            for j in range(warm, len(keys)):
                self.pool.freeze(spec.pages[j], keys[j])
        if req.max_new <= 0:
            # prefill-warm (ptc-route disaggregated prefill role): the
            # request exists only to POPULATE the prefix cache — no
            # token is emitted, no TTFT recorded.  Retiring releases
            # the pages; the frozen full ones park on the cached LRU,
            # warm for export_frozen / the next acquire_prefix.
            seq = _Seq(req, spec.slot, spec.pages, len(req.prompt))
            req._seq = seq
            with self._lock:
                self._retire_locked(seq)
            return
        o = self.Oc.tile(spec.slot, 0)[0].copy()
        req.outputs.append(o)
        nxt = self.model.next_token(o)
        req.tokens.append(nxt)
        # the prefill chain attended the last prompt position: this IS
        # the first generated token — the tenant TTFT histogram's feed
        self.scope.record_first_token(req.scope_id)
        seq = _Seq(req, spec.slot, spec.pages, len(req.prompt))
        seq.remaining = req.max_new - 1
        req._seq = seq
        req.state = "active"
        with self._lock:
            if seq.remaining <= 0:
                self._retire_locked(seq)
            else:
                self._active.append(seq)

    # -------------------------------------------------------------- step
    def _launch(self) -> int:
        """Build + run one decode pool per tenant that has active
        sequences and no decode pool in flight.  Tenants advance
        INDEPENDENTLY — a high-priority tenant's pools complete faster
        under the QoS lanes, so its tokens/sec (and latency) pull ahead
        instead of lock-stepping with every other tenant's wave."""
        cfg = self.model.cfg
        P, d = cfg.page, cfg.d
        with self._lock:
            ready: Dict[str, List[_Seq]] = {}
            for seq in self._active:
                tenant = seq.req.tenant
                if tenant in self._inflight:
                    continue
                # grow the page list when the last page is full
                if seq.length % P == 0 and len(seq.pages) * P <= \
                        seq.length:
                    p = self.pool.alloc()
                    if p is None:
                        self.stats["page_stalls"] += 1
                        continue
                    seq.pages.append(p)
                ready.setdefault(tenant, []).append(seq)
        launched = 0
        for tenant, seqs in ready.items():
            ts = self.server._tenants.get(tenant)
            prio, wt = (ts.cfg.priority, ts.cfg.weight) if ts else (0, 1)
            rec = None
            if self.spec_k:
                rec = self._stage_spec(seqs, prio, wt)
                if rec is None:  # page reservation failed: plain decode
                    with self._lock:
                        self.stats["spec_fallbacks"] += 1
            if rec is None:
                rec = self._stage_decode(seqs, prio, wt)
            tp, staged, spec_info = rec
            if not staged:
                tp.destroy()  # nothing stageable this wave (COW dry)
                continue
            # ptc-scope: one shared scope per decode step, with the
            # member rid order matching the spec order so EXEC spans'
            # sequence lane (locals[0]) maps back to each request; plan
            # the pool for the conformance record when enabled
            dsid = self.scope.new_scope(
                tenant,
                kind="spec_verify_step" if spec_info else "decode_step",
                members=[s.req.rid for s in staged])
            self.scope.stamp(tp, dsid)
            plan = None
            if self.conformance:
                try:
                    plan = self.scope.plan_summary(tp.plan())
                except Exception:
                    plan = None
            done = threading.Event()
            tp.on_complete(done.set)
            self._inflight[tenant] = (tp, staged, done, dsid, plan,
                                      time.monotonic_ns(), spec_info)
            tp.run()
            self.stats["decode_pools"] += 1
            launched += 1
        return launched

    def _stage_decode(self, seqs, prio, wt):
        """Stage + build one NORMAL decode step over `seqs`.  A shared
        (prefix-cache) or frozen last page goes copy-on-write first:
        PUPD appends in place, and a sharer's view must never move.
        Returns (taskpool, staged sequences, None)."""
        cfg = self.model.cfg
        P, d = cfg.page, cfg.d
        specs, staged = [], []
        for seq in seqs:
            last = seq.pages[-1]
            if self.pool.refcount(last) > 1 or self.pool.is_frozen(last):
                priv = self.pool.make_private(last)
                if priv is None:  # clone pool dry: retry next wave
                    with self._lock:
                        self.stats["page_stalls"] += 1
                    continue
                if priv != last:
                    with self._lock:
                        self.stats["cow_copies"] += 1
                    seq.pages[-1] = priv
            tok = seq.req.tokens[-1]
            q, k, v = self.model.qkv(tok)
            self.Qc.tile(seq.slot, 0)[0] = q
            knrow = self.KNc.tile(seq.slot, 0)
            knrow[0, :d] = k
            knrow[0, d:] = v
            reset_acc(self.ACCc.tile(seq.slot, 0))
            for coll in (self.Qc, self.KNc, self.ACCc):
                self._host_wrote(coll, seq.slot)
            specs.append(SeqSpec(seq.slot, seq.pages, seq.length % P))
            staged.append(seq)
        tp = build_paged_decode(
            self.ctx, self.pool, specs, self.slot_names,
            priority=prio, weight=wt, body_wrap=self.body_wrap,
            dev=self.dev)
        return tp, staged, None

    def _stage_spec(self, seqs, prio, wt):
        """Stage + build one SPECULATIVE decode step over `seqs`: the
        draft proposes up to k tokens per sequence, and the k+1 query
        positions (current token + each draft token) verify in ONE
        batched target-model wave (build_paged_verify — the VATF wave
        is homogeneous, so PR 13 fuses it to a single launch).

        Per (sequence, query i): the query window's pages — every page
        touched by rows L..L+i — are PRIVATE clones (existing rows
        copied, speculative k|v rows host-staged), while pages wholly
        below row L stay shared read-only; the fold then reproduces the
        sequential decode step for position L+i bit-exactly.  Page
        reservation is all-or-nothing against the refcounted pool:
        shortfall returns None and the caller falls back to plain
        decode (never half-speculates).  Returns
        (taskpool, sequences, per-seq speculation records)."""
        cfg = self.model.cfg
        P, d = cfg.page, cfg.d
        dm = self.spec_draft
        nq_tot = 0
        layout = []
        for seq in seqs:
            L = seq.length
            nq = min(self.spec_k + 1, seq.remaining)
            pbase = L // P
            cnt = sum(((L + i) // P + 1) - pbase for i in range(nq))
            layout.append((seq, L, nq, pbase, cnt))
            nq_tot += nq
        total_pages = sum(c for _, _, _, _, c in layout)
        pages = self.pool.reserve(total_pages)
        if pages is None:
            return None
        take = iter(pages)
        vspecs, recs = [], []
        for seq, L, nq, pbase, _cnt in layout:
            # draft proposals: the draft model's own greedy chain over
            # the sequence's tokens (for spec_draft="self" this is the
            # target's argmax chain — the oracle acceptance bound)
            toks = list(seq.req.tokens)
            g = dm.reference_generate(toks, nq - 1)[0][len(toks):] \
                if nq > 1 else []
            u = [toks[-1]] + [int(t) for t in g]
            kvs = [self.model.qkv(t) for t in u]  # (q, k, v) per query
            base_rows = L - pbase * P  # existing rows in the base page
            privs = []
            for i in range(nq):
                npg = (L + i) // P + 1
                priv = [next(take) for _ in range(npg - pbase)]
                # copy the base page's existing rows, then host-stage
                # the speculative rows u[0..i] at absolute rows L..L+i
                if base_rows:
                    src = seq.pages[pbase]
                    self.pool.k_tile(priv[0])[:base_rows] = \
                        self.pool.k_tile(src)[:base_rows]
                    self.pool.v_tile(priv[0])[:base_rows] = \
                        self.pool.v_tile(src)[:base_rows]
                for r in range(L, L + i + 1):
                    pg = priv[r // P - pbase]
                    _, k, v = kvs[r - L]
                    self.pool.k_tile(pg)[r % P] = k
                    self.pool.v_tile(pg)[r % P] = v
                for pg in priv:
                    self.pool.host_wrote(pg)
                vslot = seq.slot * (self.spec_k + 1) + i
                self.SQc.tile(vslot, 0)[0] = kvs[i][0]
                reset_acc(self.SACCc.tile(vslot, 0))
                self._host_wrote(self.SQc, vslot)
                self._host_wrote(self.SACCc, vslot)
                R = L + 1 + i
                vspecs.append(SeqSpec(
                    vslot, seq.pages[:pbase] + priv,
                    R - ((L + i) // P) * P))
                privs.append(priv)
            recs.append({"seq": seq, "nq": nq, "g": [int(t) for t in g],
                         "pbase": pbase, "privs": privs})
        tp = build_paged_verify(
            self.ctx, self.pool, vspecs, self.spec_names,
            priority=prio, weight=wt, body_wrap=self.body_wrap,
            dev=self.dev)
        return tp, seqs, recs

    def _reap(self) -> int:
        """Consume completed decode pools: apply the model head, append
        tokens, retire finished sequences, destroy the pools.  Returns
        sequences advanced."""
        done = [(t, rec) for t, rec in self._inflight.items()
                if rec[2].is_set()]
        advanced = 0
        for tenant, (tp, seqs, _, dsid, plan, t0_ns, spec) in done:
            del self._inflight[tenant]
            if spec is not None:
                advanced += self._reap_spec(tenant, spec)
            else:
                for seq in seqs:
                    o = self.Oc.tile(seq.slot, 0)[0].copy()
                    seq.req.outputs.append(o)
                    nxt = self.model.next_token(o)
                    seq.req.tokens.append(nxt)
                    seq.length += 1
                    seq.remaining -= 1
                    advanced += 1
            # conformance: decode-step pool retired — compare the plan
            # snapshot against the measured step wall + lane counters
            qos = None
            try:
                qos = tp.qos_stats()
            except Exception:
                pass
            self.scope.record_pool_done(
                dsid, qos=qos, plan=plan,
                measured={"wall_ns": time.monotonic_ns() - t0_ns})
            tp.destroy()
            self.stats["decode_steps"] += 1
        with self._lock:
            for seq in [s for s in self._active if s.remaining <= 0]:
                self._retire_locked(seq)
        return advanced

    def _reap_spec(self, tenant: str, recs) -> int:
        """Consume one speculative verify wave: greedy accept — query i
        is valid while every earlier draft matched the target's own
        argmax — so the emitted (token, output) stream is BIT-IDENTICAL
        to sequential decode regardless of draft quality.  Rejected
        tokens roll back by truncating the page table: the losing
        queries' private pages release (refcounts make this free)."""
        advanced = 0
        for rec in recs:
            seq, nq, g = rec["seq"], rec["nq"], rec["g"]
            pbase, privs = rec["pbase"], rec["privs"]
            outs, nxts = [], []
            for i in range(nq):
                vslot = seq.slot * (self.spec_k + 1) + i
                o = self.SOc.tile(vslot, 0)[0].copy()
                outs.append(o)
                nxts.append(self.model.next_token(o))
            j = 0  # query 0 is the plain decode position: always valid
            while j < nq - 1 and g[j] == nxts[j]:
                j += 1
            for i in range(j + 1):
                seq.req.outputs.append(outs[i])
                seq.req.tokens.append(nxts[i])
            # the deepest accepted query's window becomes the canonical
            # page-table tail; everything else rolls back to the pool
            old_tail = seq.pages[pbase:]
            seq.pages = seq.pages[:pbase] + privs[j]
            self.pool.release(old_tail + [
                p for i, priv in enumerate(privs) if i != j for p in priv])
            seq.length += j + 1
            seq.remaining -= j + 1
            advanced += j + 1
            with self._lock:
                self.stats["spec_steps"] += 1
                self.stats["spec_proposed"] += nq - 1
                self.stats["spec_accepted"] += j
            self.scope.record_spec(tenant, proposed=nq - 1, accepted=j)
        return advanced

    def step(self) -> int:
        """Synchronous decode wave: launch every launchable tenant pool,
        wait for ALL in-flight pools, reap.  Returns sequences
        advanced (0 = nothing active)."""
        self._launch()
        for rec in list(self._inflight.values()):
            rec[2].wait()
        return self._reap()

    def _retire_locked(self, seq: _Seq):
        if seq in self._active:
            self._active.remove(seq)
        self.pool.free(seq.pages)
        self._free_slots.append(seq.slot)
        seq.req.state = "done"
        seq.req.done_t = time.monotonic()
        self.stats["retired"] += 1
        # request terminal: tenant latency/tokens-per-s SLO feed
        self.scope.record_done(seq.req.scope_id, state="done",
                               tokens=len(seq.req.generated))
        # pages/slots freed outside pool completion: unblock
        # ResourceBusy-paused tenants (lock order: engine -> server is
        # safe — server never calls into the engine under its lock)
        self.server.notify_resources()

    # --------------------------------------------------------------- run
    def pending(self) -> bool:
        with self._lock:
            active = bool(self._active)
        if active:
            return True
        for req in self.requests:
            if req.state in ("submitted", "active") and \
                    req.ticket is not None and \
                    req.ticket.state not in ("rejected", "failed",
                                             "cancelled"):
                return True
        return False

    def run(self, timeout_s: float = 120.0):
        """Drive the continuous-batching loop until every request is
        terminal: tenants launch and reap decode pools independently
        (QoS latency separation), the admission queue drains through
        the server's pump as capacity frees."""
        deadline = time.monotonic() + timeout_s
        while self.pending() or self._inflight:
            if time.monotonic() > deadline:
                raise TimeoutError("serving loop exceeded its deadline")
            launched = self._launch()
            reaped = self._reap()
            if not launched and not reaped:
                time.sleep(0.0005)  # waiting on pools / prefills
        # requests that never passed admission keep their terminal state
        for req in self.requests:
            if req.state == "submitted" and req.ticket is not None and \
                    req.ticket.state in ("rejected", "failed",
                                         "cancelled"):
                req.state = req.ticket.state
                req.done_t = req.done_t or time.monotonic()

    def close(self):
        self.server.close()
