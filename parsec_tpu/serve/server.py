"""Serving front door: admission control + backpressure over taskpools.

Reference role: PaRSEC has no serving story — this is the new subsystem
the ROADMAP's "millions of users" north star names.  A Server accepts
request DAGs (each a taskpool builder), enforces per-tenant budgets, and
stamps every admitted pool with the tenant's QoS (priority/weight → the
native SchedLWS lanes, see native/sched.cpp):

  admission   a tenant may hold at most `max_pools` concurrently-running
              pools; excess submissions QUEUE up to `max_queue` entries
              and `max_queued_bytes` estimated bytes, and are REJECTED
              beyond that (backpressure the caller can see)
  retirement  completed pools are destroyed on the pump thread (native
              memory stays flat under pool churn) and the tenant's queue
              is pumped
  resources   a builder may raise ResourceBusy (engine out of KV pages /
              sequence slots): the ticket goes back to the queue head
              and the tenant pauses until the next retirement

Counters (per tenant + totals) export through Context.stats()["serve"],
which the PR 7 MetricsRegistry flattens into Prometheus samples
(ptc_serve_*) and /stats.json.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

__all__ = ["TenantConfig", "Ticket", "Server", "AdmissionError",
           "ResourceBusy"]


class AdmissionError(RuntimeError):
    """Raised by submit(wait=True) when the request was rejected."""


class ResourceBusy(RuntimeError):
    """Raised by a pool builder when a shared resource (KV pages,
    sequence slots) is exhausted: the ticket re-queues instead of
    failing, and the tenant pauses until a retirement."""


class TenantConfig:
    """One tenant's QoS + admission budgets.

    priority/weight feed Context.taskpool (native QoS lanes: priority
    orders tenants strictly at every scheduler wave boundary, weight
    stride-shares one priority tier).  max_pools bounds concurrently
    running pools; max_queue / max_queued_bytes bound the backlog."""

    def __init__(self, name: str, priority: int = 0, weight: int = 1,
                 max_pools: int = 4, max_queue: int = 64,
                 max_queued_bytes: Optional[int] = None,
                 default_est_bytes: Optional[int] = None,
                 slo_ms: Optional[float] = None,
                 slo_burn: float = 0.5):
        self.name = name
        self.priority = int(priority)
        self.weight = max(1, int(weight))
        self.max_pools = max(1, int(max_pools))
        self.max_queue = max(0, int(max_queue))
        self.max_queued_bytes = max_queued_bytes
        # byte estimate for submissions that declare none (est_bytes=0
        # means UNKNOWN): None = derive the static ptc-plan bound from
        # the submitted pool instead (see Server.submit)
        self.default_est_bytes = default_est_bytes
        # SLO target on submit->done latency (ms).  The ScopeRegistry
        # tracks a sliding violation window; a burn rate at or above
        # `slo_burn` marks the tenant breached — /healthz turns 503 and
        # the watchdog emits a structured slo_burn event.
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        self.slo_burn = float(slo_burn)


class Ticket:
    """One submission's lifecycle handle.  States:
    queued -> running -> done | failed, or rejected / cancelled
    (terminal).  `cancelled` only ever happens to a still-QUEUED ticket
    (Server.cancel — the fleet router re-placing work off an unhealthy
    replica); a running pool is never torn out from under its waves."""

    __slots__ = ("tenant", "est_bytes", "meta", "state", "submitted_t",
                 "admitted_t", "done_t", "error", "_event", "_make_pool",
                 "_pool", "scope_id", "_owns_scope", "_plan",
                 "_est_discount")

    def __init__(self, tenant: str, make_pool: Callable, est_bytes,
                 meta):
        self.tenant = tenant
        # None = unknown AND statically unboundable (rejected whenever a
        # byte budget is in force — the budget can never be evaded)
        self.est_bytes = None if est_bytes is None else int(est_bytes)
        self.meta = meta
        self.state = "queued"
        self.submitted_t = time.monotonic()
        self.admitted_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.error: Optional[BaseException] = None
        self._event = threading.Event()
        self._make_pool = make_pool
        self._pool = None
        # request scope (ptc-scope): stamped into the pool at admission;
        # _owns_scope = the server allocated it, so pool completion IS
        # the request's terminal state (an engine-owned scope outlives
        # the prefill pool — the engine retires it)
        self.scope_id: Optional[int] = None
        self._owns_scope = False
        self._plan: Optional[dict] = None  # ptc-plan prediction summary
        self._est_discount = 0  # predicted-shared bytes (prefix cache)

    @property
    def terminal(self) -> bool:
        return self.state in ("done", "failed", "rejected", "cancelled")

    def wait(self, timeout: Optional[float] = None) -> str:
        """Block until terminal; returns the final state."""
        self._event.wait(timeout)
        return self.state

    @property
    def queue_wait_s(self) -> float:
        if self.admitted_t is None:
            return 0.0
        return self.admitted_t - self.submitted_t

    @property
    def latency_s(self) -> Optional[float]:
        """submit -> done wall seconds (None before completion)."""
        if self.done_t is None:
            return None
        return self.done_t - self.submitted_t


class _TenantState:
    def __init__(self, cfg: TenantConfig):
        self.cfg = cfg
        self.active = 0
        self.queue: deque = deque()
        self.queued_bytes = 0
        self.blocked = False  # ResourceBusy: pause until a retirement
        self.counters = {
            "submitted": 0, "admitted": 0, "rejected": 0,
            "completed": 0, "failed": 0, "resource_waits": 0,
            "queue_wait_ns": 0, "discounted_bytes": 0, "cancelled": 0,
            "pressure_inflated_bytes": 0,
        }


class Server:
    """Admission-controlled multi-tenant front door over one Context.

    submit(tenant, make_pool, est_bytes) hands the server a taskpool
    BUILDER: `make_pool(priority=, weight=)` must create (and may
    commit) a Taskpool on the server's context and return it without
    running it — the server runs it at admission time with the tenant's
    QoS stamped, tracks completion, destroys it at retirement, and
    pumps the tenant's queue.  Builders raising ResourceBusy re-queue.
    """

    def __init__(self, ctx, tenants: List[TenantConfig],
                 name: str = "serve", conformance: bool = True):
        self.ctx = ctx
        self.name = name
        # request-scope observability (ptc-scope): every ticket gets a
        # scope_id stamped into its pool beside the QoS stamp; the
        # registry folds tenant SLO metrics + plan-vs-measured
        # conformance.  conformance=False skips the per-pool ptc-plan
        # pass (prediction-free pools count against coverage).
        self.scope = ctx.scope_registry()
        self.conformance = bool(conformance)
        for t in tenants:
            self.scope.tenant(t.name, slo_ms=t.slo_ms,
                              burn_threshold=t.slo_burn)
        self._tenants: Dict[str, _TenantState] = {
            t.name: _TenantState(t) for t in tenants}
        # shared-resource counter providers (the engine registers its
        # PagePool prefix-cache + speculative-decode counters here so
        # they export through Context.stats()["serve"])
        self._resource_stats: Dict[str, Callable[[], dict]] = {}
        # advertisement providers (ptc-route): cheap snapshots folded
        # into advertise() — the engine registers its frozen-page key
        # digest here so a fleet router can predict warm-prefix hits
        # without scraping full stats()
        self._advertisers: Dict[str, Callable[[], object]] = {}
        # ptc-pilot admission pricing: per-tenant SLO-burn pressure set
        # by the controller — a burning tenant's byte estimates inflate
        # by (1 + pressure), so its queue budget bites EARLIER and load
        # sheds before /healthz flips for the whole replica
        self._admission_pressure: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._retired: List[Ticket] = []
        self._closed = False
        self._preempts_retired = 0
        self._pump_thread = threading.Thread(
            target=self._pump_loop, daemon=True, name=f"ptc-{name}-pump")
        self._pump_thread.start()
        servers = getattr(ctx, "_servers", None)
        if servers is None:
            servers = ctx._servers = []
        servers.append(self)

    # ------------------------------------------------------------ submit
    def add_tenant(self, cfg: TenantConfig):
        self.scope.tenant(cfg.name, slo_ms=cfg.slo_ms,
                          burn_threshold=cfg.slo_burn)
        with self._lock:
            self._tenants[cfg.name] = _TenantState(cfg)

    def register_resource_stats(self, name: str, fn: Callable[[], dict]):
        """Export a shared-resource counter snapshot (e.g. the KV
        PagePool's prefix-cache counters) under stats()[name]."""
        self._resource_stats[name] = fn

    def register_advertiser(self, name: str, fn: Callable[[], object]):
        """Fold `fn()` into advertise() under `name` — the engine
        registers its frozen-page key digest here (ptc-route)."""
        self._advertisers[name] = fn

    def set_admission_pressure(self, tenant: str, pressure: float):
        """Install SLO-burn admission pricing for `tenant` (ptc-pilot):
        subsequent submits see their byte estimates inflated by
        (1 + pressure), clamped to [0, 4].  Pressure ~0 removes the
        entry (free admission).  Unknown tenants are ignored."""
        p = min(4.0, max(0.0, float(pressure)))
        with self._lock:
            if tenant not in self._tenants:
                return
            if p < 1e-3:
                self._admission_pressure.pop(tenant, None)
            else:
                self._admission_pressure[tenant] = p

    def admission_pressure(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._admission_pressure)

    # ---------------------------------------------------------- fleet
    def _journal_rec(self, op: str, ticket):
        """ptc-blackbox: every admission decision is a durable journal
        record (type "serve") so a postmortem can replay the front door
        without the process's memory."""
        jr = getattr(self.ctx, "_journal", None)
        if jr is None:
            return
        try:
            jr.record("serve", op=op, server=self.name,
                      tenant=ticket.tenant, scope_id=ticket.scope_id)
        except Exception:
            pass

    def healthy(self) -> bool:
        """The /healthz verdict a router polls: False once closed or
        when any tenant's SLO burn rate breached its threshold (the
        same condition that flips the metrics exporter to 503)."""
        if self._closed:
            return False
        try:
            slo = self.scope.slo_status()
        except Exception:
            return True
        return not any(st.get("breached") for st in slo.values())

    def advertise(self) -> dict:
        """Cheap placement snapshot for a fleet router — schema in
        MIGRATION.md (PR 16).  Deliberately NOT full stats(): occupancy
        scalars + the max tenant SLO burn rate + whatever digests the
        engine registered (register_advertiser), typically
        {"prefix": {"mode": "set"|"bloom", ...}} over the PagePool's
        frozen content keys."""
        with self._lock:
            active = sum(t.active for t in self._tenants.values())
            queued = sum(len(t.queue) for t in self._tenants.values())
            queued_bytes = sum(t.queued_bytes
                               for t in self._tenants.values())
            pressure = max(self._admission_pressure.values(), default=0.0)
        burn = 0.0
        try:
            for st in self.scope.slo_status().values():
                burn = max(burn, float(st.get("burn_rate") or 0.0))
        except Exception:
            pass
        out = {
            "name": self.name,
            "healthy": self.healthy(),
            "active_pools": active,
            "queue_depth": queued,
            "queued_bytes": queued_bytes,
            "slo_burn_rate": round(burn, 4),
            "admission_pressure": round(pressure, 4),
        }
        for name, fn in self._advertisers.items():
            try:
                out[name] = fn()
            except Exception:
                pass
        return out

    def cancel(self, ticket: Ticket) -> bool:
        """Withdraw a still-QUEUED ticket (fleet re-placement off an
        unhealthy replica).  True = removed from its tenant's queue and
        marked terminal `cancelled` (counted, never silently dropped);
        False = already running or terminal — a decoding sequence is
        NEVER re-placed, per the fleet contract."""
        t = self._tenants.get(ticket.tenant)
        if t is None:
            return False
        with self._lock:
            if ticket.state != "queued":
                return False
            try:
                t.queue.remove(ticket)
            except ValueError:
                return False  # racing _pump_loop already popped it
            t.queued_bytes -= ticket.est_bytes or 0
            t.counters["cancelled"] += 1
            ticket.state = "cancelled"
            ticket.done_t = time.monotonic()
            ticket._event.set()
        if ticket._owns_scope and ticket.scope_id is not None:
            # scope-side terminal: counts as a rejection (the router's
            # re-route counter pairs with it so nothing is lost)
            self.scope.record_rejected(ticket.scope_id)
        self._journal_rec("cancel", ticket)
        if ticket._pool is not None:
            self._destroy_pool(ticket)  # planning pool never admitted
        return True

    def submit(self, tenant: str, make_pool: Callable, est_bytes: int = 0,
               meta=None, wait: bool = False,
               scope: Optional[int] = None,
               est_discount_bytes: int = 0) -> Ticket:
        """Submit one request DAG.  Returns its Ticket immediately
        (state "queued", "running" — admitted synchronously — or
        "rejected").  wait=True blocks for the terminal state and
        raises AdmissionError on rejection.

        `est_bytes` <= 0 means UNKNOWN (it used to silently bypass the
        max_queued_bytes backpressure — see MIGRATION.md).  When the
        tenant has a byte budget in force, an unknown estimate resolves
        to the tenant's `default_est_bytes`, or — when none is
        configured — the server builds the pool NOW and takes the
        static ptc-plan working-set bound (`Taskpool.plan().est_bytes`);
        the built pool is reused at admission, never built twice.  A
        submission whose bytes cannot be bounded at all is REJECTED
        whenever the byte budget applies: the budget can no longer be
        evaded.

        `scope` attaches a caller-owned request scope (the inference
        engine allocates one per request): the server stamps it into
        the pool but does not retire it at pool completion.  Left None,
        the server allocates its own — pool completion is then the
        request's terminal state."""
        if self._closed:
            raise RuntimeError("server closed")
        t = self._tenants[tenant]
        ticket = Ticket(tenant, make_pool, est_bytes, meta)
        # prefix-cache admission discount (ptc-share): pages predicted
        # to map onto existing frozen pages are free to the pool, so
        # the byte budget charges only the cold tail.  Clamped to stay
        # a KNOWN estimate (<= 0 means unknown — see MIGRATION.md).
        disc = max(0, int(est_discount_bytes or 0))
        ticket._est_discount = disc
        if disc and ticket.est_bytes is not None and ticket.est_bytes > 0:
            applied = min(disc, ticket.est_bytes - 1)
            ticket.est_bytes -= applied
            with self._lock:
                t.counters["discounted_bytes"] += applied
        # SLO-burn admission pricing (ptc-pilot): a burning tenant's
        # KNOWN estimates inflate by (1 + pressure), so max_queued_bytes
        # sheds its load first — applied after the prefix discount (the
        # discount models real pool bytes; pressure is pure pricing)
        with self._lock:
            pressure = self._admission_pressure.get(tenant, 0.0)
        if pressure > 0 and ticket.est_bytes is not None \
                and ticket.est_bytes > 0:
            infl = int(ticket.est_bytes * pressure)
            if infl:
                ticket.est_bytes += infl
                with self._lock:
                    t.counters["pressure_inflated_bytes"] += infl
        if scope is None:
            ticket.scope_id = self.scope.new_scope(tenant, meta=meta)
            ticket._owns_scope = True
        else:
            ticket.scope_id = int(scope)
        if (ticket.est_bytes is None or ticket.est_bytes <= 0) \
                and t.cfg.max_queued_bytes is not None:
            early = self._resolve_est(t, ticket)
            if early is not None:  # ResourceBusy / failure at build
                if wait and not ticket.terminal:
                    ticket.wait()
                return ticket
        admit_now = False
        with self._lock:
            t.counters["submitted"] += 1
            if t.active < t.cfg.max_pools and not t.queue and \
                    not t.blocked:
                admit_now = True
                t.active += 1  # reserve before dropping the lock
            elif self._can_queue(t, ticket):
                t.queue.append(ticket)
                t.queued_bytes += ticket.est_bytes or 0
            else:
                t.counters["rejected"] += 1
                ticket.state = "rejected"
                ticket.done_t = time.monotonic()
                ticket._event.set()
        if ticket.state == "rejected":
            self.scope.record_rejected(ticket.scope_id)
            self._journal_rec("reject", ticket)
        if ticket.state == "rejected" and ticket._pool is not None:
            self._destroy_pool(ticket)  # planning pool never admitted
        if admit_now:
            self._admit(t, ticket)
        if wait and not ticket.terminal:
            ticket.wait()
        if wait and ticket.state == "rejected":
            raise AdmissionError(
                f"tenant {tenant!r}: queue budget exceeded "
                f"(max_queue={t.cfg.max_queue}, "
                f"max_queued_bytes={t.cfg.max_queued_bytes}, "
                f"est_bytes={ticket.est_bytes})")
        return ticket

    def _resolve_est(self, t: _TenantState, ticket: Ticket):
        """Resolve an UNKNOWN byte estimate while the tenant's byte
        budget is in force: per-tenant default first, else build the
        pool and take the static plan bound.  Returns None on success
        (ticket.est_bytes resolved, possibly to the None=unboundable
        sentinel) or the ticket when the build itself parked (
        ResourceBusy) or failed — submit returns it as-is then."""
        if t.cfg.default_est_bytes is not None:
            ticket.est_bytes = int(t.cfg.default_est_bytes)
            return None
        try:
            tp = ticket._make_pool(priority=t.cfg.priority,
                                   weight=t.cfg.weight)
        except ResourceBusy:
            with self._lock:
                t.counters["submitted"] += 1
                t.counters["resource_waits"] += 1
                t.queue.appendleft(ticket)
                t.blocked = True
            return ticket
        except BaseException as e:
            with self._lock:
                t.counters["submitted"] += 1
                t.counters["failed"] += 1
            ticket.state = "failed"
            ticket.error = e
            ticket.done_t = time.monotonic()
            ticket._event.set()
            self.scope.record_done(ticket.scope_id, state="failed")
            return ticket
        ticket._pool = tp  # reused by _admit; destroyed on rejection
        try:
            plan = tp.plan()
            # None = unbounded; predicted-shared pages discount here too
            ticket.est_bytes = plan.est_bytes(
                discount_bytes=ticket._est_discount)
            if self.conformance:
                ticket._plan = self.scope.plan_summary(plan)
        except Exception:
            ticket.est_bytes = None
        return None

    def _can_queue(self, t: _TenantState, ticket: Ticket) -> bool:
        if len(t.queue) >= t.cfg.max_queue:
            return False
        if t.cfg.max_queued_bytes is not None:
            if ticket.est_bytes is None:  # unboundable: never evades
                return False
            if t.queued_bytes + ticket.est_bytes > t.cfg.max_queued_bytes:
                return False
        return True

    # --------------------------------------------------------- admission
    def _admit(self, t: _TenantState, ticket: Ticket):
        """Build + run one pool (caller already reserved t.active).
        Runs on the submitter or the pump thread, never on a worker."""
        try:
            tp = ticket._pool  # prebuilt at submit for the plan bound
            if tp is None:
                tp = ticket._make_pool(priority=t.cfg.priority,
                                       weight=t.cfg.weight)
        except ResourceBusy:
            with self._lock:
                t.active -= 1
                t.counters["resource_waits"] += 1
                t.queue.appendleft(ticket)
                t.queued_bytes += ticket.est_bytes or 0
                t.blocked = True
            return
        except BaseException as e:
            with self._lock:
                t.active -= 1
                t.counters["failed"] += 1
            ticket.state = "failed"
            ticket.error = e
            ticket.done_t = time.monotonic()
            ticket._event.set()
            self.scope.record_done(ticket.scope_id, state="failed")
            return
        ticket._pool = tp
        ticket.admitted_t = time.monotonic()
        ticket.state = "running"
        # ptc-scope: stamp the request scope beside the QoS stamp
        # (EXEC spans, wire frames and the watchdog inflight slot all
        # carry it from here on), mark admission, and snapshot the
        # static plan predictions the conformance record compares
        # against at retirement
        if ticket.scope_id is not None:
            self.scope.stamp(tp, ticket.scope_id)
            # no explicit timestamp: the registry reads the native
            # trace clock, which its windows must align with
            self.scope.record_admitted(ticket.scope_id)
            if self.conformance and ticket._plan is None:
                try:
                    ticket._plan = self.scope.plan_summary(tp.plan())
                except Exception:
                    ticket._plan = None
        with self._lock:
            t.counters["admitted"] += 1
            t.counters["queue_wait_ns"] += int(ticket.queue_wait_s * 1e9)
        self._journal_rec("admit", ticket)
        tp.on_complete(lambda: self._on_pool_complete(t, ticket))
        try:
            tp.run()
        except BaseException as e:
            with self._lock:
                t.active -= 1
                t.counters["failed"] += 1
            ticket.state = "failed"
            ticket.error = e
            ticket.done_t = time.monotonic()
            ticket._event.set()
            self.scope.record_done(ticket.scope_id, state="failed")
            self._journal_rec("failed", ticket)

    def _on_pool_complete(self, t: _TenantState, ticket: Ticket):
        """Fires on the completing worker thread: only mark + wake the
        pump (pool destroy and queue pumping never run on workers)."""
        ticket.done_t = time.monotonic()
        failed = ticket._pool is not None and ticket._pool.nb_errors > 0
        with self._lock:
            t.active -= 1
            t.blocked = False
            if failed:
                t.counters["failed"] += 1
                ticket.state = "failed"
            else:
                t.counters["completed"] += 1
                ticket.state = "done"
            self._retired.append(ticket)
            self._wake.notify_all()
        self._journal_rec("failed" if failed else "done", ticket)
        # ptc-scope: fold the pool's conformance record (plan
        # predictions vs measured wall + the pool's QoS lane counters)
        # while the native pool is still alive; the request itself
        # retires here only when the server owns the scope (an
        # engine-owned scope keeps decoding past its prefill pool)
        if ticket.scope_id is not None:
            qos = None
            try:
                qos = ticket._pool.qos_stats() \
                    if ticket._pool is not None else None
            except Exception:
                pass
            measured = None
            if ticket.admitted_t is not None:
                measured = {"wall_ns": int(
                    (ticket.done_t - ticket.admitted_t) * 1e9)}
            self.scope.record_pool_done(ticket.scope_id, qos=qos,
                                        plan=ticket._plan,
                                        measured=measured)
            if ticket._owns_scope:
                self.scope.record_done(ticket.scope_id,
                                       state=ticket.state)
        ticket._event.set()

    def notify_resources(self):
        """A shared resource (KV pages, sequence slots) was freed
        OUTSIDE pool completion (engine sequence retirement): unblock
        every ResourceBusy-paused tenant and wake the pump."""
        with self._lock:
            for t in self._tenants.values():
                t.blocked = False
            self._wake.notify_all()

    # -------------------------------------------------------------- pump
    def _pump_loop(self):
        while True:
            with self._lock:
                while not self._closed and not self._retired and \
                        not self._admittable_locked():
                    self._wake.wait(0.2)
                if self._closed:
                    return
                retired = self._retired
                self._retired = []
                batch = []
                for t in self._tenants.values():
                    while t.queue and not t.blocked and \
                            t.active < t.cfg.max_pools:
                        ticket = t.queue.popleft()
                        t.queued_bytes -= ticket.est_bytes or 0
                        t.active += 1
                        batch.append((t, ticket))
            for ticket in retired:
                self._destroy_pool(ticket)
            for t, ticket in batch:
                self._admit(t, ticket)

    def _admittable_locked(self) -> bool:
        return any(t.queue and not t.blocked and
                   t.active < t.cfg.max_pools
                   for t in self._tenants.values())

    def _destroy_pool(self, ticket: Ticket):
        tp = ticket._pool
        ticket._pool = None
        if tp is None:
            return
        try:
            # fold the pool's scheduler preempt evidence into the
            # server's lifetime counter before the rows disappear
            st = tp.qos_stats()
            if st:
                self._preempts_retired += st["preempts"]
            tp.destroy()
        except Exception:
            pass

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        """Per-tenant + total admission counters (the serve namespace of
        Context.stats(); flattened into ptc_serve_* Prometheus
        samples by the MetricsRegistry)."""
        with self._lock:
            tenants = {}
            totals = {"submitted": 0, "admitted": 0, "rejected": 0,
                      "completed": 0, "failed": 0, "resource_waits": 0,
                      "queue_depth": 0, "queued_bytes": 0,
                      "active_pools": 0, "cancelled": 0}
            for name, t in self._tenants.items():
                row = dict(t.counters)
                row["queue_depth"] = len(t.queue)
                row["queued_bytes"] = t.queued_bytes
                row["active_pools"] = t.active
                row["priority"] = t.cfg.priority
                row["weight"] = t.cfg.weight
                tenants[name] = row
                for k in totals:
                    totals[k] += row.get(k, 0)
            totals["preempts"] = self._preempts_retired + sum(
                p["preempts"] for p in self.ctx._qos_pool_rows())
        out = {"tenants": tenants, "totals": totals}
        # shared-resource counters (prefix cache, speculative decode)
        for name, fn in self._resource_stats.items():
            try:
                out[name] = fn()
            except Exception:
                pass
        return out

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted request is terminal."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                busy = any(t.active or t.queue
                           for t in self._tenants.values()) or \
                    bool(self._retired)
            if not busy:
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.002)

    def close(self):
        """Stop the pump thread and destroy retired pools."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            retired = self._retired
            self._retired = []
            self._wake.notify_all()
        self._pump_thread.join(timeout=10)
        for ticket in retired:
            self._destroy_pool(ticket)
        servers = getattr(self.ctx, "_servers", [])
        if self in servers:
            servers.remove(self)
