"""Tiled GEMM as a PTG taskpool over a 2D block-cyclic distribution.

C(m,n) += sum_k A(m,k) @ B(k,n): each Gemm(m,n,k) task carries the C tile
through a k-chain (owner-computes on C's placement), reading A/B tiles from
their collections.  This is the DPLASMA-style summa-ish shape used by the
BASELINE measurement ladder rung 2/5; the kernel runs as a cached XLA
executable on the TPU device (or numpy on the CPU fallback chore).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import parsec_tpu as pt
from ..data.collections import TwoDimBlockCyclic
from ..device.tpu import TpuDevice


def k_gemm_nn(a, b, c):
    # bf16 inputs to the MXU with f32 accumulate is the TPU-native contract
    import jax
    return c + jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   preferred_element_type=c.dtype)


def _gemm_class(tp, A, B, C, dev, cn, a_in, b_in):
    """The shared Gemm(m,n,k) class: owner-computes C k-chain; only the
    A/B input deps differ between the single-rank (collection reads) and
    distributed (reader-broadcast Refs) builders."""
    m, n, k = pt.L("m"), pt.L("n"), pt.L("k")
    g = tp.task_class("Gemm")
    g.param("m", 0, pt.G("MT"))
    g.param("n", 0, pt.G("NT"))
    g.param("k", 0, pt.G("KT"))
    g.affinity(cn, m, n)
    # deeper k first so the chain head is prioritized
    g.priority(pt.G("KT") - k)
    g.flow("A", "READ", a_in)
    g.flow("B", "READ", b_in)
    g.flow("C", "RW",
           pt.In(pt.Mem(cn, m, n), guard=(k == 0)),
           pt.In(pt.Ref("Gemm", m, n, k - 1, flow="C")),
           pt.Out(pt.Ref("Gemm", m, n, k + 1, flow="C"),
                  guard=(k < pt.G("KT"))),
           pt.Out(pt.Mem(cn, m, n), guard=(k == pt.G("KT"))))

    shp = {"A": (A.mb, A.nb), "B": (B.mb, B.nb), "C": (C.mb, C.nb)}
    if dev is not None:
        dev.attach(g, tp, kernel=k_gemm_nn, reads=["A", "B", "C"],
                   writes=["C"], shapes=shp, dtype=C.dtype)

    def cpu_body(t):
        a = t.data("A", C.dtype, shp["A"])
        b = t.data("B", C.dtype, shp["B"])
        c = t.data("C", C.dtype, shp["C"])
        c += a @ b

    g.body(cpu_body)
    return g


def build_gemm(ctx: pt.Context, A: TwoDimBlockCyclic, B: TwoDimBlockCyclic,
               C: TwoDimBlockCyclic, dev: Optional[TpuDevice] = None,
               names=("A", "B", "C")) -> pt.Taskpool:
    """Build (but don't run) the GEMM taskpool.  Collections must already be
    registered with ctx under `names`."""
    mt, nt, kt = C.mt, C.nt, A.nt
    assert A.mt == mt and B.nt == nt and B.mt == kt
    tp = pt.Taskpool(ctx, globals={"MT": mt - 1, "NT": nt - 1, "KT": kt - 1})
    m, n, k = pt.L("m"), pt.L("n"), pt.L("k")
    an, bn, cn = names

    _gemm_class(tp, A, B, C, dev, cn,
                pt.In(pt.Mem(an, m, k)), pt.In(pt.Mem(bn, k, n)))
    return tp


def build_gemm_dist(ctx: pt.Context, A: TwoDimBlockCyclic,
                    B: TwoDimBlockCyclic, C: TwoDimBlockCyclic,
                    dev: Optional[TpuDevice] = None,
                    names=("A", "B", "C")) -> pt.Taskpool:
    """Distributed GEMM: owner-computes on C with A/B tiles moved by
    reader-task broadcasts placed AT their data.

    The single-rank builder reads A(m,k)/B(k,n) straight from the
    collections, which this runtime (deliberately) rejects cross-rank —
    memory reads must be affine with placement.  DPLASMA's answer is the
    one used here: ReadA(m,k) runs on A(m,k)'s owner and BROADCASTS the
    tile to the whole Gemm row m (all n at step k), ReadB(k,n) to the
    whole column — the reference's collective-propagation machinery
    carries the panels (remote_dep.c:39-47 bcast trees; dplasma gemm's
    read_A/read_B task classes).  Chain/binomial topologies apply via
    ctx.comm_set_topology."""
    mt, nt, kt = C.mt, C.nt, A.nt
    assert A.mt == mt and B.nt == nt and B.mt == kt
    tp = pt.Taskpool(ctx, globals={"MT": mt - 1, "NT": nt - 1, "KT": kt - 1})
    m, n, k = pt.L("m"), pt.L("n"), pt.L("k")
    an, bn, cn = names

    ra = tp.task_class("ReadA")
    ra.param("m", 0, pt.G("MT"))
    ra.param("k", 0, pt.G("KT"))
    ra.affinity(an, m, k)
    ra.flow("A", "READ",
            pt.In(pt.Mem(an, m, k)),
            pt.Out(pt.Ref("Gemm", m, pt.Range(0, pt.G("NT")), k,
                          flow="A")))
    ra.body_noop()

    rb = tp.task_class("ReadB")
    rb.param("k", 0, pt.G("KT"))
    rb.param("n", 0, pt.G("NT"))
    rb.affinity(bn, k, n)
    rb.flow("B", "READ",
            pt.In(pt.Mem(bn, k, n)),
            pt.Out(pt.Ref("Gemm", pt.Range(0, pt.G("MT")), n, k,
                          flow="B")))
    rb.body_noop()

    _gemm_class(tp, A, B, C, dev, cn,
                pt.In(pt.Ref("ReadA", m, k, flow="A")),
                pt.In(pt.Ref("ReadB", k, n, flow="B")))
    return tp


def run_gemm(ctx, A, B, C, dev=None) -> None:
    tp = build_gemm(ctx, A, B, C, dev)
    tp.run()
    tp.wait()
    if dev is not None:
        dev.flush()
