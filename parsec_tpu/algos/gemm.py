"""Tiled GEMM as a PTG taskpool over a 2D block-cyclic distribution.

C(m,n) += sum_k A(m,k) @ B(k,n): each Gemm(m,n,k) task carries the C tile
through a k-chain (owner-computes on C's placement), reading A/B tiles from
their collections.  This is the DPLASMA-style summa-ish shape used by the
BASELINE measurement ladder rung 2/5; the kernel runs as a cached XLA
executable on the TPU device (or numpy on the CPU fallback chore).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import parsec_tpu as pt
from ..data.collections import TwoDimBlockCyclic
from ..device.tpu import TpuDevice


def k_gemm_nn(a, b, c):
    # bf16 inputs to the MXU with f32 accumulate is the TPU-native contract
    import jax
    return c + jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   preferred_element_type=c.dtype)


def _gemm_class(tp, A, B, C, dev, cn, a_in, b_in):
    """The shared Gemm(m,n,k) class: owner-computes C k-chain; only the
    A/B input deps differ between the single-rank (collection reads) and
    distributed (reader-broadcast Refs) builders."""
    m, n, k = pt.L("m"), pt.L("n"), pt.L("k")
    g = tp.task_class("Gemm")
    g.param("m", 0, pt.G("MT"))
    g.param("n", 0, pt.G("NT"))
    g.param("k", 0, pt.G("KT"))
    g.affinity(cn, m, n)
    # deeper k first so the chain head is prioritized
    g.priority(pt.G("KT") - k)
    g.flow("A", "READ", a_in)
    g.flow("B", "READ", b_in)
    g.flow("C", "RW",
           pt.In(pt.Mem(cn, m, n), guard=(k == 0)),
           pt.In(pt.Ref("Gemm", m, n, k - 1, flow="C")),
           pt.Out(pt.Ref("Gemm", m, n, k + 1, flow="C"),
                  guard=(k < pt.G("KT"))),
           pt.Out(pt.Mem(cn, m, n), guard=(k == pt.G("KT"))))

    shp = {"A": (A.mb, A.nb), "B": (B.mb, B.nb), "C": (C.mb, C.nb)}
    if dev is not None:
        dev.attach(g, tp, kernel=k_gemm_nn, reads=["A", "B", "C"],
                   writes=["C"], shapes=shp, dtype=C.dtype)

    def cpu_body(t):
        a = t.data("A", C.dtype, shp["A"])
        b = t.data("B", C.dtype, shp["B"])
        c = t.data("C", C.dtype, shp["C"])
        c += a @ b

    g.body(cpu_body, pure=True)  # pure tile chore: fusion-eligible
    return g


def build_gemm(ctx: pt.Context, A: TwoDimBlockCyclic, B: TwoDimBlockCyclic,
               C: TwoDimBlockCyclic, dev: Optional[TpuDevice] = None,
               names=("A", "B", "C")) -> pt.Taskpool:
    """Build (but don't run) the GEMM taskpool.  Collections must already be
    registered with ctx under `names`."""
    mt, nt, kt = C.mt, C.nt, A.nt
    assert A.mt == mt and B.nt == nt and B.mt == kt
    tp = pt.Taskpool(ctx, globals={"MT": mt - 1, "NT": nt - 1, "KT": kt - 1})
    m, n, k = pt.L("m"), pt.L("n"), pt.L("k")
    an, bn, cn = names

    _gemm_class(tp, A, B, C, dev, cn,
                pt.In(pt.Mem(an, m, k)), pt.In(pt.Mem(bn, k, n)))
    return tp


def build_gemm_dist(ctx: pt.Context, A: TwoDimBlockCyclic,
                    B: TwoDimBlockCyclic, C: TwoDimBlockCyclic,
                    dev: Optional[TpuDevice] = None,
                    names=("A", "B", "C")) -> pt.Taskpool:
    """Distributed GEMM: owner-computes on C with A/B tiles moved by
    reader-task broadcasts placed AT their data.

    The single-rank builder reads A(m,k)/B(k,n) straight from the
    collections, which this runtime (deliberately) rejects cross-rank —
    memory reads must be affine with placement.  DPLASMA's answer is the
    one used here: ReadA(m,k) runs on A(m,k)'s owner and BROADCASTS the
    tile to the whole Gemm row m (all n at step k), ReadB(k,n) to the
    whole column — the reference's collective-propagation machinery
    carries the panels (remote_dep.c:39-47 bcast trees; dplasma gemm's
    read_A/read_B task classes).  Chain/binomial topologies apply via
    ctx.comm_set_topology."""
    mt, nt, kt = C.mt, C.nt, A.nt
    assert A.mt == mt and B.nt == nt and B.mt == kt
    tp = pt.Taskpool(ctx, globals={"MT": mt - 1, "NT": nt - 1, "KT": kt - 1})
    m, n, k = pt.L("m"), pt.L("n"), pt.L("k")
    an, bn, cn = names

    ra = tp.task_class("ReadA")
    ra.param("m", 0, pt.G("MT"))
    ra.param("k", 0, pt.G("KT"))
    ra.affinity(an, m, k)
    ra.flow("A", "READ",
            pt.In(pt.Mem(an, m, k)),
            pt.Out(pt.Ref("Gemm", m, pt.Range(0, pt.G("NT")), k,
                          flow="A")))
    ra.body_noop()

    rb = tp.task_class("ReadB")
    rb.param("k", 0, pt.G("KT"))
    rb.param("n", 0, pt.G("NT"))
    rb.affinity(bn, k, n)
    rb.flow("B", "READ",
            pt.In(pt.Mem(bn, k, n)),
            pt.Out(pt.Ref("Gemm", pt.Range(0, pt.G("MT")), n, k,
                          flow="B")))
    rb.body_noop()

    _gemm_class(tp, A, B, C, dev, cn,
                pt.In(pt.Ref("ReadA", m, k, flow="A")),
                pt.In(pt.Ref("ReadB", k, n, flow="B")))
    return tp


def run_gemm(ctx, A, B, C, dev=None) -> None:
    tp = build_gemm(ctx, A, B, C, dev)
    tp.run()
    tp.wait()
    if dev is not None:
        dev.flush()


def gemm_panel_reduce(ctx: pt.Context, a_slab: np.ndarray,
                      b_slab: np.ndarray, reduce: str = "coll",
                      topo=None, panel_rows: int = 0) -> np.ndarray:
    """k-split GEMM with a cross-rank panel reduction:
    C = sum_r a_slab_r @ b_slab_r, rank r holding k-slab r.  Returns the
    full C on every rank (all-reduce shape).

    reduce="coll" (ISSUE 6 tentpole): C is split into row panels and
    each Partial(r, p) feeds the runtime-native ptc_coll reduction the
    moment it completes — panel p's reduction (and its wire traffic)
    overlaps panel p+1's compute, so the collective starts after the
    FIRST panel, not the last (T3, arXiv:2401.16677).  Topology per the
    transfer-economics selector (PTC_MCA_coll_topo override).

    reduce="chain": the DAG-dependency baseline — each rank computes its
    WHOLE partial, a serial rank chain sums them, the result fans out —
    exactly how reductions were expressed before runtime-native
    collectives existed.  Bit-identical to "coll" on integer-valued
    inputs (both sum in rank order along their chains)."""
    from ..comm.coll import RefReduce, rank_affinity_collection

    M, _ = a_slab.shape
    Nc = b_slab.shape[1]
    R = max(1, ctx.nodes)
    if R == 1 or not ctx.comm_enabled:
        return (a_slab @ b_slab).astype(np.float32)
    if panel_rows <= 0:
        from ..utils import params as _mca
        q = _mca.get("coll.slice") or _mca.get("comm.chunk_size")
        panel_rows = max(1, min(M, int(q) // max(1, Nc * 4)))
    c_out = np.zeros((M, Nc), dtype=np.float32)
    rankc = rank_affinity_collection(ctx)
    r_, p_, t_, q_ = pt.L("r"), pt.L("p"), pt.L("t"), pt.L("q")

    if reduce == "coll":
        P = (M + panel_rows - 1) // panel_rows
        panel_bytes = panel_rows * Nc * 4
        tp = pt.Taskpool(ctx)
        part = tp.task_class("GemmPartial")
        part.param("r", 0, R - 1)
        part.param("p", 0, P - 1)
        part.affinity(rankc, r_)
        rr = RefReduce(
            ctx, tp, nseg=P,
            contributors_of=lambda p: [(r, (p, r)) for r in range(R)],
            root_of=lambda p: p % R,
            prod_class="GemmPartial", prod_flow="P", prod_nparams=2,
            prod_params_of=lambda cid: (cid[1], cid[0]),
            arena_bytes=panel_bytes, dtype=np.float32, topo=topo,
            bcast=True,
            fanout_sink=lambda seg, sl, arr: _store_panel(
                c_out, seg, panel_rows, arr))
        part.flow("P", "W",
                  *rr.producer_out_deps(lambda l, g: (l[1], l[0])),
                  arena=f"__ptc_coll_{rr.uid}")

        def part_body(view):
            p = view["p"]
            rows = slice(p * panel_rows, min(M, (p + 1) * panel_rows))
            out = (a_slab[rows] @ b_slab).astype(np.float32).ravel()
            view.data("P", dtype=np.float32)[:out.size] = out

        part.body(part_body)
        tp.run()
        tp.wait()
        return c_out

    if reduce != "chain":
        raise ValueError(f"gemm_panel_reduce: unknown reduce={reduce!r}")
    # DAG-dependency baseline: whole-matrix partials, serial rank chain
    from ..comm.coll import _next_uid
    full_bytes = M * Nc * 4
    arena = f"__gemm_chain_{_next_uid(ctx)}"
    ctx.register_arena(arena, full_bytes)
    tp = pt.Taskpool(ctx)
    whole = tp.task_class("GemmWhole")
    whole.param("r", 0, R - 1)
    whole.affinity(rankc, r_)
    whole.flow("W", "W", pt.Out(pt.Ref("GemmChain", r_, flow="B")),
               arena=arena)

    def whole_body(view):
        out = (a_slab @ b_slab).astype(np.float32).ravel()
        view.data("W", dtype=np.float32)[:out.size] = out

    whole.body(whole_body)
    chain = tp.task_class("GemmChain")
    chain.param("t", 0, R - 1)
    chain.affinity(rankc, t_)
    chain.flow("B", "READ", pt.In(pt.Ref("GemmWhole", t_, flow="W")),
               arena=arena)
    chain.flow("A", "READ", pt.In(pt.Ref("GemmChain", t_ - 1, flow="R")),
               arena=arena)
    chain.flow("R", "W",
               pt.Out(pt.Ref("GemmChain", t_ + 1, flow="A"),
                      guard=(t_ < R - 1)),
               pt.Out(pt.Ref("GemmFan", pt.Range(0, R - 1), flow="X"),
                      guard=(t_ == R - 1)),
               arena=arena)

    def chain_body(view):
        b = view.data("B", dtype=np.float32)
        r = view.data("R", dtype=np.float32)
        if view.data_ptr("A"):
            r[:] = view.data("A", dtype=np.float32) + b
        else:
            r[:b.size] = b

    chain.body(chain_body)
    fan = tp.task_class("GemmFan")
    fan.param("q", 0, R - 1)
    fan.affinity(rankc, q_)
    fan.flow("X", "READ", pt.In(pt.Ref("GemmChain", R - 1, flow="R")),
             arena=arena)

    def fan_body(view):
        x = view.data("X", dtype=np.float32)
        c_out[...] = x[:M * Nc].reshape(M, Nc)

    fan.body(fan_body)
    tp.run()
    tp.wait()
    return c_out


def _store_panel(c_out, seg, panel_rows, arr):
    M, Nc = c_out.shape
    rows = slice(seg * panel_rows, min(M, (seg + 1) * panel_rows))
    n = (rows.stop - rows.start) * Nc
    c_out[rows] = arr[:n].reshape(-1, Nc)
