"""Reshape: dtype/layout conversion between collections as a taskpool.

Reference analog (SURVEY.md §2.3 "reshape"): parsec/parsec_reshape.c
converts datacopies between datatypes/layouts through datacopy futures on
flow boundaries, locally or pre-send.  The TPU-native translation: layout
is XLA's concern on-device, so reshape is a *library algorithm* over
collections — per-tile dtype casts / element transforms ride the
map_operator taskpool (same geometry), and geometry changes (tile size,
distribution) ride redistribute.  Both compose with user DAGs like any
other taskpool, which is exactly how the reference packages its reshape
paths as PTG algorithms.
"""
from typing import Callable, Optional

import numpy as np

import parsec_tpu as pt
from .matrix_ops import build_map_operator
from .redistribute import redistribute


def build_reshape_dtype(ctx: pt.Context, src, dst,
                        cast: Optional[Callable] = None,
                        src_name: str = "RSsrc", dst_name: str = "RSdst"):
    """Tile-by-tile dtype conversion src -> dst (same tile geometry).

    `cast(tile) -> np.ndarray` defaults to a plain astype onto the dst
    collection's dtype.  Returns the taskpool (run()/wait() to execute).
    """
    if (src.mt, src.nt) != (dst.mt, dst.nt):
        raise ValueError(
            f"reshape_dtype needs matching tile grids; "
            f"src {(src.mt, src.nt)} vs dst {(dst.mt, dst.nt)} "
            f"(use reshape_geometry for regridding)")
    to = np.dtype(dst.dtype)

    def op(src_tile, dst_tile, m, n):
        out = cast(src_tile) if cast is not None else src_tile
        return np.asarray(out, dtype=to)

    return build_map_operator(ctx, src, dst, op,
                              src_name=src_name, dst_name=dst_name)


def reshape_geometry(ctx: pt.Context, src, dst,
                     size_row: Optional[int] = None,
                     size_col: Optional[int] = None):
    """Regrid src's elements into dst (different mb/nb and/or distribution)
    — the redistribute path of the reference's reshape machinery."""
    return redistribute(ctx, src, dst,
                        size_row if size_row is not None else min(src.M,
                                                                  dst.M),
                        size_col if size_col is not None else min(src.N,
                                                                  dst.N))
