"""Tiled QR factorization (DPLASMA dgeqrf dataflow) as a PTG taskpool:

  GEQRT(k)      : QR of the diagonal tile       A[k,k] -> Q_k, R
  UNMQR(k, n)   : apply Q_k^T to the row        A[k,n] = Q_k^T A[k,n]
  TSQRT(k, m)   : stacked QR of [R; A[m,k]]     eliminates tile A[m,k]
  TSMQR(k, m, n): apply the stacked reflector   [top; A[m,n]] update

Same four-class shape and dependency structure as the reference
(dplasma dgeqrf.jdf: geqrt/unmqr/tsqrt/tsmqr), with one deliberately
TPU-native representation change: instead of the compact-WY (V, T)
reflector storage - whose construction is a sequential Householder loop
- each factor task materializes its ORTHOGONAL Q explicitly (nb x nb
for the diagonal, 2nb x 2nb for the stacked elimination) and the apply
tasks are plain MXU matmuls.  Q blocks travel as arena-allocated WRITE
flows feeding row broadcasts; A is overwritten by R (upper triangular,
eliminated tiles zeroed), matching the in-place contract.

All collection reads are affine with task placement, so the taskpool
runs distributed over a PxQ grid unchanged.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import parsec_tpu as pt
from ..data.collections import TwoDimBlockCyclic
from ..device.tpu import TpuDevice

from ._util import as_device_list


# ---------------------------------------------------------------- kernels
def k_geqrt(a):
    """Full QR of the diagonal tile: returns (q, r) — r replaces the
    tile, q rides the Q flow."""
    import jax.numpy as jnp
    q, r = jnp.linalg.qr(a, mode="complete")
    return q, r


def k_unmqr(q, c):
    import jax
    return jax.lax.dot_general(q, c, (((0,), (0,)), ((), ())),
                               preferred_element_type=c.dtype)  # q^T c


def k_tsqrt(r, v):
    """Stacked QR of [r; v] (2nb x nb): new r, zeroed v, full q2."""
    import jax.numpy as jnp
    nb = r.shape[0]
    s = jnp.concatenate([r, v], axis=0)
    q2, rf = jnp.linalg.qr(s, mode="complete")
    return rf[:nb], jnp.zeros_like(v), q2


def k_tsmqr(q2, top, bot):
    import jax
    import jax.numpy as jnp
    nb = top.shape[0]
    s = jnp.concatenate([top, bot], axis=0)
    out = jax.lax.dot_general(q2, s, (((0,), (0,)), ((), ())),
                              preferred_element_type=s.dtype)  # q2^T s
    return out[:nb], out[nb:]


def build_geqrf(ctx: pt.Context, A: TwoDimBlockCyclic,
                dev: Optional[TpuDevice] = None,
                name: str = "A") -> pt.Taskpool:
    """Build the QR taskpool for square tiled `A` (registered under
    `name`).  On completion A holds R (upper triangular; tiles below the
    diagonal zeroed)."""
    nt = A.mt
    assert A.mt == A.nt and A.mb == A.nb
    nb = A.mb
    esize = int(np.dtype(A.dtype).itemsize)
    ctx.register_arena(f"{name}_qrq", nb * nb * esize)
    ctx.register_arena(f"{name}_qrq2", 4 * nb * nb * esize)
    tp = pt.Taskpool(ctx, globals={"NT": nt - 1})
    k, m, n = pt.L("k"), pt.L("m"), pt.L("n")
    NT = pt.G("NT")
    shp = (nb, nb)
    shp2 = (2 * nb, 2 * nb)
    dt = A.dtype

    # ------------------------------------------------------------ GEQRT(k)
    gq = tp.task_class("GEQRT")
    gq.param("k", 0, NT)
    gq.affinity(name, k, k)
    gq.priority((NT - k) * 1000)
    gq.flow("T", "RW",
            pt.In(pt.Mem(name, k, k), guard=(k == 0)),
            pt.In(pt.Ref("TSMQR", k - 1, k, k, flow="B")),
            pt.Out(pt.Ref("TSQRT", k, k + 1, flow="R"), guard=(k < NT)),
            pt.Out(pt.Mem(name, k, k), guard=(k == NT)))
    gq.flow("Q", "WRITE",
            pt.Out(pt.Ref("UNMQR", k, pt.Range(k + 1, NT), flow="Q"),
                   guard=(k < NT)),
            arena=f"{name}_qrq")

    # --------------------------------------------------------- UNMQR(k, n)
    un = tp.task_class("UNMQR")
    un.param("k", 0, NT)
    un.param("n", k + 1, NT)
    un.affinity(name, k, n)
    un.priority((NT - k) * 1000 - n)
    un.flow("Q", "READ", pt.In(pt.Ref("GEQRT", k, flow="Q")))
    un.flow("C", "RW",
            pt.In(pt.Mem(name, k, n), guard=(k == 0)),
            pt.In(pt.Ref("TSMQR", k - 1, k, n, flow="B")),
            pt.Out(pt.Ref("TSMQR", k, k + 1, n, flow="T")))

    # --------------------------------------------------------- TSQRT(k, m)
    ts = tp.task_class("TSQRT")
    ts.param("k", 0, NT)
    ts.param("m", k + 1, NT)
    ts.affinity(name, m, k)
    ts.priority((NT - k) * 1000 - m)
    ts.flow("R", "RW",
            pt.In(pt.Ref("GEQRT", k, flow="T"), guard=(m == k + 1)),
            pt.In(pt.Ref("TSQRT", k, m - 1, flow="R")),
            pt.Out(pt.Ref("TSQRT", k, m + 1, flow="R"), guard=(m < NT)),
            pt.Out(pt.Mem(name, k, k), guard=(m == NT)))
    ts.flow("V", "RW",
            pt.In(pt.Mem(name, m, k), guard=(k == 0)),
            pt.In(pt.Ref("TSMQR", k - 1, m, k, flow="B")),
            pt.Out(pt.Mem(name, m, k)))
    ts.flow("Q2", "WRITE",
            pt.Out(pt.Ref("TSMQR", k, m, pt.Range(k + 1, NT), flow="Q"),
                   guard=(k < NT)),
            arena=f"{name}_qrq2")

    # ------------------------------------------------------ TSMQR(k, m, n)
    tm = tp.task_class("TSMQR")
    tm.param("k", 0, NT)
    tm.param("m", k + 1, NT)
    tm.param("n", k + 1, NT)
    tm.affinity(name, m, n)
    tm.priority((NT - k) * 1000 - m - n)
    tm.flow("Q", "READ", pt.In(pt.Ref("TSQRT", k, m, flow="Q2")))
    tm.flow("T", "RW",
            pt.In(pt.Ref("UNMQR", k, n, flow="C"), guard=(m == k + 1)),
            pt.In(pt.Ref("TSMQR", k, m - 1, n, flow="T")),
            pt.Out(pt.Ref("TSMQR", k, m + 1, n, flow="T"),
                   guard=(m < NT)),
            pt.Out(pt.Mem(name, k, n), guard=(m == NT)))
    tm.flow("B", "RW",
            pt.In(pt.Mem(name, m, n), guard=(k == 0)),
            pt.In(pt.Ref("TSMQR", k - 1, m, n, flow="B")),
            pt.Out(pt.Ref("GEQRT", k + 1, flow="T"),
                   guard=(m == k + 1) & (n == k + 1)),
            pt.Out(pt.Ref("UNMQR", k + 1, n, flow="C"),
                   guard=(m == k + 1) & (n > k + 1)),
            pt.Out(pt.Ref("TSQRT", k + 1, m, flow="V"),
                   guard=(m > k + 1) & (n == k + 1)),
            pt.Out(pt.Ref("TSMQR", k + 1, m, n, flow="B"),
                   guard=(m > k + 1) & (n > k + 1)))

    # --------------------------------------------------------------- chores
    for d in as_device_list(dev):
        d.attach(gq, tp, kernel=k_geqrt, reads=["T"], writes=["Q", "T"],
                 shapes={"T": shp, "Q": shp}, dtype=dt)
        d.attach(un, tp, kernel=k_unmqr, reads=["Q", "C"], writes=["C"],
                 shapes={"Q": shp, "C": shp}, dtype=dt)
        d.attach(ts, tp, kernel=k_tsqrt, reads=["R", "V"],
                 writes=["R", "V", "Q2"],
                 shapes={"R": shp, "V": shp, "Q2": shp2}, dtype=dt)
        d.attach(tm, tp, kernel=k_tsmqr, reads=["Q", "T", "B"],
                 writes=["T", "B"],
                 shapes={"Q": shp2, "T": shp, "B": shp}, dtype=dt)

    def b_geqrt(t):
        a = t.data("T", dt, shp)
        q = t.data("Q", dt, shp)
        qq, rr = np.linalg.qr(a, mode="complete")
        q[...] = qq
        a[...] = rr

    def b_unmqr(t):
        q = t.data("Q", dt, shp)
        c = t.data("C", dt, shp)
        c[...] = q.T @ c

    def b_tsqrt(t):
        r = t.data("R", dt, shp)
        v = t.data("V", dt, shp)
        q2 = t.data("Q2", dt, shp2)
        s = np.concatenate([r, v], axis=0)
        qq, rr = np.linalg.qr(s, mode="complete")
        q2[...] = qq
        r[...] = rr[:nb]
        v[...] = 0

    def b_tsmqr(t):
        q2 = t.data("Q", dt, shp2)
        top = t.data("T", dt, shp)
        bot = t.data("B", dt, shp)
        s = q2.T @ np.concatenate([top, bot], axis=0)
        top[...] = s[:nb]
        bot[...] = s[nb:]

    gq.body(b_geqrt)
    un.body(b_unmqr)
    ts.body(b_tsqrt)
    tm.body(b_tsmqr)
    return tp


def geqrf_flops(N: int) -> float:
    return 4.0 * N ** 3 / 3.0
