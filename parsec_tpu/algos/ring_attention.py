"""Ring attention as a PTG taskpool over a sequence-sharded collection.

The flagship ML algorithm run THROUGH the task runtime (not a sibling
GSPMD library — that exact-math jax implementation lives in
parallel/ring_attention.py and is the validation oracle): the sequence
axis is tiled into S shards; ATT(i, t) attends query shard i against the
K/V block that reaches it at ring step t, carrying streaming-softmax
state (o, m, l) task-to-task; the K/V blocks hop to the ring-left
neighbor every step — that hop IS a runtime dependency, so on multiple
ranks the block rides the comm engine (PK_DEVICE data plane / rendezvous
for big tiles) exactly like any other tile.  Reference pattern:
algorithms packaged as dataflow taskpools (apply/reduce/redistribute,
parsec/data_dist/matrix/redistribute/redistribute.jdf); the ring walk is
the chain-topology neighbor pattern of remote_dep.c:43.

DAG (S shards, S steps, one softmax pass):

  ATT(i, t):   Q    <- Q(i)            (t == 0)  | ATT(i, t-1).Q
               KV   <- KV(i)           (t == 0)  | ATT((i+1)%S, t-1).KV
               ACC  <- ACC(i)          (t == 0)  | ATT(i, t-1).ACC
               KV   -> ATT((i-1+S)%S, t+1).KV    (t < S-1)
               ACC  -> ATT(i, t+1).ACC (t < S-1) | FIN(i).ACC
  FIN(i):      O(i) = ACC.o / ACC.l

ACC packs (o, m, l) as one (T, d+2) tile; KV packs K and V stacked as
one (2T, d) tile — one flow each keeps the wire/arena story simple and
the kernels fused.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

import parsec_tpu as pt
from ..data.collections import TwoDimBlockCyclic


def _as_dev_list(dev):
    if dev is None:
        return []
    return list(dev) if isinstance(dev, (list, tuple)) else [dev]


# ---------------------------------------------------------------- kernels
def k_att(q, kv, acc):
    import jax.numpy as jnp
    T, d = q.shape
    k, v = kv[:T], kv[T:]
    o, m, l = acc[:, :d], acc[:, d:d + 1], acc[:, d + 1:d + 2]
    s = (q @ k.T) / jnp.sqrt(jnp.float32(d))
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + p.sum(axis=-1, keepdims=True)
    o_new = alpha * o + p @ v
    return q, kv, jnp.concatenate([o_new, m_new, l_new], axis=1)


def k_fin(acc):
    import jax.numpy as jnp
    d = acc.shape[1] - 2
    return acc[:, :d] / acc[:, d + 1:d + 2]


def make_collections(S: int, T: int, d: int, nodes: int = 1, myrank: int = 0,
                     q=None, k=None, v=None):
    """Sequence-sharded collections for S shards of T tokens, head dim d.
    q/k/v: optional (S*T, d) dense arrays to initialize from (rank 0
    layout; each rank stores only its own shards)."""
    def init_from(dense):
        if dense is None:
            return None
        return lambda c, m, n: np.ascontiguousarray(
            dense[m * c.mb:(m + 1) * c.mb], dtype=np.float32)

    Qc = TwoDimBlockCyclic(S * T, d, T, d, P=nodes, Q=1, nodes=nodes,
                           myrank=myrank, dtype=np.float32,
                           init=init_from(q))
    kvd = None
    if k is not None:
        kv = np.concatenate(
            [np.stack([k[i * T:(i + 1) * T], v[i * T:(i + 1) * T]])
             .reshape(2 * T, d) for i in range(S)])
        kvd = init_from(kv)
    KVc = TwoDimBlockCyclic(S * 2 * T, d, 2 * T, d, P=nodes, Q=1,
                            nodes=nodes, myrank=myrank, dtype=np.float32,
                            init=kvd)

    def acc_init(c, m, n):
        t = np.zeros((T, d + 2), dtype=np.float32)
        t[:, d] = -np.inf  # running max
        return t

    ACCc = TwoDimBlockCyclic(S * T, d + 2, T, d + 2, P=nodes, Q=1,
                             nodes=nodes, myrank=myrank, dtype=np.float32,
                             init=acc_init)
    Oc = TwoDimBlockCyclic(S * T, d, T, d, P=nodes, Q=1, nodes=nodes,
                           myrank=myrank, dtype=np.float32)
    return Qc, KVc, ACCc, Oc


def build_ring_attention(ctx: pt.Context, Qc, KVc, ACCc, Oc,
                         dev=None) -> pt.Taskpool:
    """S = Qc.mt shards; requires the four collections registered names
    Q/KV/ACC/O (done here)."""
    S = Qc.mt
    T, d = Qc.mb, Qc.nb
    Qc.register(ctx, "Q")
    KVc.register(ctx, "KV")
    ACCc.register(ctx, "ACC")
    Oc.register(ctx, "O")
    ctx.register_arena("ra_kv", 2 * T * d * 4)
    ctx.register_arena("ra_acc", T * (d + 2) * 4)
    ctx.register_arena("ra_o", T * d * 4)
    tp = pt.Taskpool(ctx, globals={"S": S - 1})
    i, t = pt.L("i"), pt.L("t")
    Sg = pt.G("S")
    att = tp.task_class("ATT")
    att.param("i", 0, Sg)
    att.param("t", 0, Sg)
    att.affinity("Q", i, 0)
    att.priority(Sg - t)
    att.flow("Q", "RW",
             pt.In(pt.Mem("Q", i, 0), guard=(t == 0)),
             pt.In(pt.Ref("ATT", i, t - 1, flow="Q")),
             pt.Out(pt.Ref("ATT", i, t + 1, flow="Q"), guard=(t < Sg)))
    att.flow("KV", "RW",
             pt.In(pt.Mem("KV", i, 0), guard=(t == 0)),
             pt.In(pt.Ref("ATT", (i + 1) % (Sg + 1), t - 1, flow="KV")),
             pt.Out(pt.Ref("ATT", (i - 1 + (Sg + 1)) % (Sg + 1), t + 1,
                           flow="KV"),
                    guard=(t < Sg)),
             arena="ra_kv")
    att.flow("ACC", "RW",
             pt.In(pt.Mem("ACC", i, 0), guard=(t == 0)),
             pt.In(pt.Ref("ATT", i, t - 1, flow="ACC")),
             pt.Out(pt.Ref("ATT", i, t + 1, flow="ACC"), guard=(t < Sg)),
             pt.Out(pt.Ref("FIN", i, flow="ACC"), guard=(t == Sg)))
    fin = tp.task_class("FIN")
    fin.param("i", 0, Sg)
    fin.affinity("O", i, 0)
    fin.flow("ACC", "READ", pt.In(pt.Ref("ATT", i, Sg, flow="ACC")),
             arena="ra_acc")
    fin.flow("O", "W", pt.Out(pt.Mem("O", i, 0)), arena="ra_o")

    for dv in _as_dev_list(dev):
        dv.attach(att, tp, kernel=k_att, reads=["Q", "KV", "ACC"],
                  writes=["Q", "KV", "ACC"],
                  shapes={"Q": (T, d), "KV": (2 * T, d),
                          "ACC": (T, d + 2)}, dtype=np.float32)
        # O is written into a DIFFERENT collection tile at release:
        # the host copy must be coherent when the memcpy runs
        dv.attach(fin, tp, kernel=k_fin, reads=["ACC"], writes=["O"],
                  shapes={"ACC": (T, d + 2), "O": (T, d)},
                  dtype=np.float32, sync_mem_out=True)

    def b_att(view):
        qv = view.data("Q", np.float32, (T, d))
        kv = view.data("KV", np.float32, (2 * T, d))
        ac = view.data("ACC", np.float32, (T, d + 2))
        kk, vv = kv[:T], kv[T:]
        o, m, l = ac[:, :d], ac[:, d:d + 1], ac[:, d + 1:d + 2]
        s = (qv @ kk.T) / math.sqrt(d)
        m_new = np.maximum(m, s.max(axis=-1, keepdims=True))
        p = np.exp(s - m_new)
        alpha = np.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        o_new = alpha * o + p @ vv
        ac[:, :d] = o_new
        ac[:, d:d + 1] = m_new
        ac[:, d + 1:d + 2] = l_new

    def b_fin(view):
        ac = view.data("ACC", np.float32, (T, d + 2))
        ov = view.data("O", np.float32, (T, d))
        ov[...] = ac[:, :d] / ac[:, d + 1:d + 2]

    att.body(b_att)
    fin.body(b_fin)
    return tp


def run_ring_attention(ctx, S, T, d, q, k, v, dev=None, nodes=1, myrank=0):
    """Convenience: build collections from dense (S*T, d) q/k/v, run, and
    return the dense output (valid on the owning ranks' shards)."""
    Qc, KVc, ACCc, Oc = make_collections(S, T, d, nodes, myrank, q, k, v)
    tp = build_ring_attention(ctx, Qc, KVc, ACCc, Oc, dev=dev)
    tp.run()
    tp.wait()
    for dv in _as_dev_list(dev):
        dv.flush()
    return Oc


def dense_reference(q, k, v):
    """Oracle: plain softmax attention in float64."""
    q64, k64, v64 = (x.astype(np.float64) for x in (q, k, v))
    s = q64 @ k64.T / math.sqrt(q.shape[1])
    p = np.exp(s - s.max(axis=-1, keepdims=True))
    p /= p.sum(axis=-1, keepdims=True)
    return (p @ v64).astype(np.float32)
