"""Generic per-tile matrix operations as PTG taskpools: apply, map_operator
and row/column tree reductions.

Reference analogs (SURVEY.md §2.3 "matrix ops"):
  - apply.jdf / apply_wrapper.c:52-188  — unary operator on every tile of a
    triangle/full region, one task per tile, owner-computes affinity
  - map_operator.c                      — src→dst per-tile map (two
    collections, reads src, writes dst)
  - reduce_col.jdf / reduce_row.jdf / reduce_wrapper.c — binary-tree
    reduction of the tiles of each column/row into a destination tile

These are themselves taskpools (the reference builds them as JDFs); they
compose with user DAGs via Taskpool.run()/wait() or compose() chaining.
The tree reductions generalize the reference's power-of-two index tree with
existence guards so any mt/nt works.
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import parsec_tpu as pt
from ..core.expr import shl


def _ceil_div_pow2(e, lvl):
    """ceil(e / 2**lvl) as a VM expression."""
    return (e + shl(1, lvl) - 1) // shl(1, lvl)


def build_apply(ctx: pt.Context, A, op: Callable, uplo: str = "full",
                name: str = "A") -> pt.Taskpool:
    """Apply `op(coll, m, n, tile)` to every stored tile of the region.

    uplo: "full" | "lower" (n <= m) | "upper" (m <= n).  The operator
    mutates the tile in place (RW flow, collection in/out — the reference's
    APPLY_L/APPLY_U/APPLY_DIAG pattern collapsed into guarded classes).
    """
    if uplo not in ("full", "lower", "upper"):
        raise ValueError(f"uplo must be full/lower/upper, got {uplo!r}")
    tp = pt.Taskpool(ctx, globals={"MT": A.mt - 1, "NT": A.nt - 1})
    m, n = pt.L("m"), pt.L("n")
    MT, NT = pt.G("MT"), pt.G("NT")
    dt = A.dtype
    shp = (A.mb, getattr(A, "nb", 1))

    def make_class(cname, m_lo, m_hi, n_lo, n_hi):
        tc = tp.task_class(cname)
        tc.param("m", m_lo, m_hi)
        tc.param("n", n_lo, n_hi)
        tc.affinity(name, m, n)
        tc.flow("T", "RW", pt.In(pt.Mem(name, m, n)),
                pt.Out(pt.Mem(name, m, n)))

        def body(t):
            tile = t.data("T", dt, shp)
            op(A, t.local("m"), t.local("n"), tile)

        tc.body(body)
        return tc

    # diagonal is its own class so the triangular regions exclude it
    # (reference: APPLY_DIAG, apply.jdf)
    if uplo in ("full", "lower", "upper"):
        make_class("APPLY_DIAG", 0, pt.minimum(MT, NT), m, m)
    if uplo in ("full", "lower"):
        make_class("APPLY_L", 1, MT, 0, pt.minimum(m - 1, NT))
    if uplo in ("full", "upper"):
        make_class("APPLY_U", 0, pt.minimum(MT, NT), m + 1, NT)
    return tp


def build_map_operator(ctx: pt.Context, src, dst, op: Callable,
                       src_name: str = "S", dst_name: str = "D"
                       ) -> pt.Taskpool:
    """Per-tile map: dst(m,n) = op(src_tile, dst_tile, m, n) over the
    common tile grid (reference: map_operator.c — sequential-ish chain per
    column there; fully parallel here, the stronger dataflow).

    `op(src_tile, dst_tile, m, n)` returns the new dst tile contents (or
    mutates dst_tile in place and returns None).
    """
    mt = min(src.mt, dst.mt)
    nt = min(getattr(src, "nt", 1), getattr(dst, "nt", 1))
    tp = pt.Taskpool(ctx, globals={"MT": mt - 1, "NT": nt - 1})
    m, n = pt.L("m"), pt.L("n")
    sdt, ddt = src.dtype, dst.dtype
    sshp = (src.mb, getattr(src, "nb", 1))
    dshp = (dst.mb, getattr(dst, "nb", 1))

    tc = tp.task_class("MAP")
    tc.param("m", 0, pt.G("MT"))
    tc.param("n", 0, pt.G("NT"))
    tc.affinity(dst_name, m, n)
    tc.flow("S", "READ", pt.In(pt.Mem(src_name, m, n)))
    tc.flow("D", "RW", pt.In(pt.Mem(dst_name, m, n)),
            pt.Out(pt.Mem(dst_name, m, n)))

    def body(t):
        s = t.data("S", sdt, sshp)
        d = t.data("D", ddt, dshp)
        r = op(s, d, t.local("m"), t.local("n"))
        if r is not None:
            d[...] = r

    tc.body(body)
    return tp


def _build_reduce(ctx: pt.Context, A, op: Callable, axis: int,
                  name: str, dest_name: Optional[str]) -> pt.Taskpool:
    """Binary-tree reduction of tiles along `axis` (0: reduce rows of each
    column — reduce_col.jdf; 1: reduce columns of each row — reduce_row.jdf).

    op(acc_tile, in_tile) -> new acc contents.  The reduced tile for
    column/row j lands in dest(0, j) / dest(j, 0) when a dest collection is
    given, else in A's tile (0, j) / (j, 0).

    DESTRUCTIVE on A either way: the accumulator rides the left spine of
    the tree in place (RW flow), so after completion the source tiles on
    each lane's left spine hold partial sums — exactly the reference's
    reduce_col.jdf RW Rtop semantics.  Copy A first if you need it intact.

    The reference's tree (reduce_col.jdf) assumes a power-of-two tile count;
    here nodes at (level, index) carry existence guards derived from
    ceil(extent / 2**level) so any extent works: a node whose right child
    is beyond the extent passes its left value through unchanged.
    """
    extent = A.mt if axis == 0 else A.nt
    lanes = A.nt if axis == 0 else A.mt
    depth = max(1, int(np.ceil(np.log2(max(2, extent)))))
    tp = pt.Taskpool(ctx, globals={"DEPTH": depth, "EXT": extent,
                                   "LANES": lanes - 1})
    lvl, idx, j = pt.L("level"), pt.L("index"), pt.L("j")
    DEPTH, EXT = pt.G("DEPTH"), pt.G("EXT")
    dt = A.dtype
    shp = (A.mb, getattr(A, "nb", 1))

    def mem(i, jj, coll=name):
        return pt.Mem(coll, i, jj) if axis == 0 else pt.Mem(coll, jj, i)

    # nodes at level L: ceil(EXT / 2**L); node (L, i) combines (L-1, 2i)
    # and (L-1, 2i+1); level-0 "nodes" are the tiles themselves.
    def nodes_at(level_e):
        return _ceil_div_pow2(EXT, level_e)

    tc = tp.task_class("REDUCE")
    tc.param("level", 1, DEPTH)
    tc.param("index", 0, nodes_at(lvl) - 1)
    tc.param("j", 0, pt.G("LANES"))
    # run where the left descendant tile lives (reference: : src(2*index, 0))
    if axis == 0:
        tc.affinity(name, shl(idx, lvl), j)
    else:
        tc.affinity(name, j, shl(idx, lvl))
    tc.priority((DEPTH - lvl) * 10)

    right_exists = (2 * idx + 1) <= (nodes_at(lvl - 1) - 1)
    # Rtop: the accumulator rides up the left spine
    top_in = [
        pt.In(mem(shl(idx, lvl), j), guard=(lvl == 1)),
        pt.In(pt.Ref("REDUCE", lvl - 1, 2 * idx, j, flow="T"),
              guard=(lvl > 1)),
    ]
    top_out = [
        pt.Out(pt.Ref("REDUCE", lvl + 1, idx // 2, j, flow="T"),
               guard=(lvl < DEPTH) & ((idx % 2) == 0)),
        pt.Out(pt.Ref("REDUCE", lvl + 1, idx // 2, j, flow="B"),
               guard=(lvl < DEPTH) & ((idx % 2) == 1)),
        pt.Out(mem(0, j, dest_name or name), guard=(lvl == DEPTH)),
    ]
    tc.flow("T", "RW", *(top_in + top_out))
    # Rbottom: right child (may not exist near the boundary)
    tc.flow("B", "READ",
            pt.In(mem(2 * idx + 1, j), guard=(lvl == 1) & right_exists),
            pt.In(pt.Ref("REDUCE", lvl - 1, 2 * idx + 1, j, flow="T"),
                  guard=(lvl > 1) & right_exists))

    def body(t):
        level, index = t.local("level"), t.local("index")
        n_prev = (extent + (1 << (level - 1)) - 1) >> (level - 1)
        acc = t.data("T", dt, shp)
        if 2 * index + 1 <= n_prev - 1:  # right child exists
            b = t.data("B", dt, shp)
            r = op(acc, b)
            if r is not None:
                acc[...] = r

    tc.body(body)
    return tp


def build_reduce_col(ctx, A, op, name="A", dest_name=None):
    """Tree-reduce the tiles of each column; result in (0, col)."""
    return _build_reduce(ctx, A, op, 0, name, dest_name)


def build_reduce_row(ctx, A, op, name="A", dest_name=None):
    """Tree-reduce the tiles of each row; result in (row, 0)."""
    return _build_reduce(ctx, A, op, 1, name, dest_name)
