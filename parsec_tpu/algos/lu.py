"""Tiled LU factorization without pivoting (DPLASMA dgetrf_nopiv) as a
PTG taskpool over a 2D block-cyclic matrix:

  GETRF(k)     : diagonal tile LU          A[k,k] = L[k,k] U[k,k]
  TRSM_L(k, n) : row-panel solve           A[k,n] = L[k,k]^-1 A[k,n]
  TRSM_U(m, k) : column-panel solve        A[m,k] = A[m,k] U[k,k]^-1
  GEMM(k,m,n)  : trailing update           A[m,n] -= A[m,k] A[k,n]

Doolittle convention: L is unit-lower (diagonal implied), U upper — both
packed into the tile in place, exactly the reference's storage
(dplasma dgetrf_nopiv.jdf dataflow shape).  All initial collection reads
are affine with task placement, so the same taskpool runs distributed:
cross-rank panel flows ride the remote-dep protocol like potrf's.

No pivoting means the input must be (block) diagonally dominant or
otherwise LU-stable — same contract as the reference algorithm.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import parsec_tpu as pt
from ..data.collections import TwoDimBlockCyclic
from ..device.tpu import TpuDevice

from ._util import as_device_list


# ---------------------------------------------------------------- kernels
def k_getrf_nopiv(a):
    """In-place Doolittle elimination: O(nb) sequential rank-1 updates —
    the diagonal tile is the serial pivot of the DAG, like potrf's
    cholesky call (only 1/nt of the tiles run this)."""
    import jax
    import jax.numpy as jnp
    n = a.shape[0]
    idx = jnp.arange(n)

    def step(i, a):
        below = idx > i
        col = jnp.where(below, a[:, i] / a[i, i], 0.0).astype(a.dtype)
        row = jnp.where(idx > i, a[i, :], 0.0).astype(a.dtype)
        a = a - jnp.outer(col, row)
        return a.at[:, i].set(jnp.where(below, col, a[:, i]))

    return jax.lax.fori_loop(0, n - 1, step, a)


def k_trsm_l(t, c):
    """Row panel: L[k,k]^-1 C with unit-diagonal L."""
    import jax
    return jax.scipy.linalg.solve_triangular(t, c, lower=True,
                                             unit_diagonal=True)


def k_trsm_u(t, c):
    """Column panel: C U[k,k]^-1 (non-unit upper)."""
    import jax
    return jax.scipy.linalg.solve_triangular(t.T, c.T, lower=True).T


def k_gemm_lu(a, b, c):
    import jax
    return c - jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   preferred_element_type=c.dtype)


def _getrf_np(a):
    """Numpy Doolittle, in place (float32/float64 tile)."""
    n = a.shape[0]
    for i in range(n - 1):
        a[i + 1:, i] /= a[i, i]
        a[i + 1:, i + 1:] -= np.outer(a[i + 1:, i], a[i, i + 1:])
    return a


def build_getrf_nopiv(ctx: pt.Context, A: TwoDimBlockCyclic,
                      dev: Optional[TpuDevice] = None,
                      name: str = "A") -> pt.Taskpool:
    """Build the LU-nopiv taskpool for square tiled `A` (registered with
    ctx under `name`)."""
    nt = A.mt
    assert A.mt == A.nt and A.mb == A.nb
    nb = A.mb
    tp = pt.Taskpool(ctx, globals={"NT": nt - 1})
    k, m, n = pt.L("k"), pt.L("m"), pt.L("n")
    NT = pt.G("NT")
    shp = (nb, nb)
    dt = A.dtype

    # ------------------------------------------------------------ GETRF(k)
    gf = tp.task_class("GETRF")
    gf.param("k", 0, NT)
    gf.affinity(name, k, k)
    gf.priority((NT - k) * 1000)
    gf.flow("T", "RW",
            pt.In(pt.Mem(name, k, k), guard=(k == 0)),
            pt.In(pt.Ref("GEMM", k - 1, k, k, flow="C")),
            pt.Out(pt.Ref("TRSM_L", k, pt.Range(k + 1, NT), flow="T"),
                   guard=(k < NT)),
            # NB: TRSM_U's declared param order is (k, m)
            pt.Out(pt.Ref("TRSM_U", k, pt.Range(k + 1, NT), flow="T"),
                   guard=(k < NT)),
            pt.Out(pt.Mem(name, k, k)))

    # --------------------------------------------------------- TRSM_L(k, n)
    tl = tp.task_class("TRSM_L")
    tl.param("k", 0, NT)
    tl.param("n", k + 1, NT)
    tl.affinity(name, k, n)
    tl.priority((NT - k) * 1000 - n)
    tl.flow("T", "READ", pt.In(pt.Ref("GETRF", k, flow="T")))
    tl.flow("C", "RW",
            pt.In(pt.Mem(name, k, n), guard=(k == 0)),
            pt.In(pt.Ref("GEMM", k - 1, k, n, flow="C")),
            pt.Out(pt.Ref("GEMM", k, pt.Range(k + 1, NT), n, flow="B")),
            pt.Out(pt.Mem(name, k, n)))

    # --------------------------------------------------------- TRSM_U(m, k)
    tu = tp.task_class("TRSM_U")
    tu.param("k", 0, NT)
    tu.param("m", k + 1, NT)
    tu.affinity(name, m, k)
    tu.priority((NT - k) * 1000 - m)
    tu.flow("T", "READ", pt.In(pt.Ref("GETRF", k, flow="T")))
    tu.flow("C", "RW",
            pt.In(pt.Mem(name, m, k), guard=(k == 0)),
            pt.In(pt.Ref("GEMM", k - 1, m, k, flow="C")),
            pt.Out(pt.Ref("GEMM", k, m, pt.Range(k + 1, NT), flow="A")),
            pt.Out(pt.Mem(name, m, k)))

    # -------------------------------------------------------- GEMM(k, m, n)
    ge = tp.task_class("GEMM")
    ge.param("k", 0, NT)
    ge.param("m", k + 1, NT)
    ge.param("n", k + 1, NT)
    ge.affinity(name, m, n)
    ge.priority((NT - k) * 1000 - m - n)
    ge.flow("A", "READ", pt.In(pt.Ref("TRSM_U", k, m, flow="C")))
    ge.flow("B", "READ", pt.In(pt.Ref("TRSM_L", k, n, flow="C")))
    ge.flow("C", "RW",
            pt.In(pt.Mem(name, m, n), guard=(k == 0)),
            pt.In(pt.Ref("GEMM", k - 1, m, n, flow="C")),
            pt.Out(pt.Ref("GETRF", k + 1, flow="T"),
                   guard=(m == k + 1) & (n == k + 1)),
            pt.Out(pt.Ref("TRSM_L", k + 1, n, flow="C"),
                   guard=(m == k + 1) & (n > k + 1)),
            pt.Out(pt.Ref("TRSM_U", k + 1, m, flow="C"),
                   guard=(m > k + 1) & (n == k + 1)),
            pt.Out(pt.Ref("GEMM", k + 1, m, n, flow="C"),
                   guard=(m > k + 1) & (n > k + 1)))

    # --------------------------------------------------------------- chores
    for d in as_device_list(dev):
        d.attach(gf, tp, kernel=k_getrf_nopiv, reads=["T"], writes=["T"],
                 shapes={"T": shp}, dtype=dt)
        d.attach(tl, tp, kernel=k_trsm_l, reads=["T", "C"], writes=["C"],
                 shapes={"T": shp, "C": shp}, dtype=dt)
        d.attach(tu, tp, kernel=k_trsm_u, reads=["T", "C"], writes=["C"],
                 shapes={"T": shp, "C": shp}, dtype=dt)
        d.attach(ge, tp, kernel=k_gemm_lu, reads=["A", "B", "C"],
                 writes=["C"], shapes={"A": shp, "B": shp, "C": shp},
                 dtype=dt)

    def b_getrf(t):
        _getrf_np(t.data("T", dt, shp))

    def b_trsm_l(t):
        l = np.tril(t.data("T", dt, shp), -1) + np.eye(nb, dtype=dt)
        c = t.data("C", dt, shp)
        c[...] = np.linalg.solve(l, c)

    def b_trsm_u(t):
        u = np.triu(t.data("T", dt, shp))
        c = t.data("C", dt, shp)
        c[...] = np.linalg.solve(u.T, c.T).T

    def b_gemm(t):
        a = t.data("A", dt, shp)
        b = t.data("B", dt, shp)
        c = t.data("C", dt, shp)
        c -= a @ b

    gf.body(b_getrf)
    tl.body(b_trsm_l)
    tu.body(b_trsm_u)
    ge.body(b_gemm)
    return tp


def getrf_nopiv_reference(full: np.ndarray) -> np.ndarray:
    """Float64 no-pivot LU of the dense matrix, packed L\\U (oracle)."""
    a = full.astype(np.float64).copy()
    return _getrf_np(a)


def getrf_flops(N: int) -> float:
    return 2.0 * N ** 3 / 3.0


# ------------------------------------------------------ panel variant
# Same coarse right-looking shape as build_potrf_panels (one tall MXU
# contraction per trailing-panel update, shared DAG in
# potrf._build_panel_factorization), LU math:
#   F(k)   : diag block -> packed L\U (Doolittle); rows below become
#            L_below = P_below U_kk^-1; rows ABOVE stay (they hold the
#            finalized U rows of earlier panels)
#   U(k,j) : u_kj = unit_lower_solve(L_kk, P_j[kblock]);
#            P_j[kblock] = u_kj; P_j[below] -= L_below @ u_kj


def k_panel_getrf(p, ks):
    """Returns (factored panel, ki): ki forwards the panel index to the
    U wave as data (U solves at row block k, and pidx[k] is not
    co-located with U(k, j) on rank j)."""
    import jax
    import jax.numpy as jnp
    nb = p.shape[1]
    off = ks[0] * nb
    d = jax.lax.dynamic_slice(p, (off, 0), (nb, nb))
    packed = k_getrf_nopiv(d)
    ukk = jnp.triu(packed)
    rows = jnp.arange(p.shape[0], dtype=ks.dtype)[:, None]
    below = jnp.where(rows >= off + nb, p, jnp.zeros((), p.dtype))
    # X U_kk = below  ->  X = (U_kk^T \ below^T)^T
    lb = jax.scipy.linalg.solve_triangular(ukk.T, below.T, lower=True).T
    out = jnp.where(rows >= off + nb, lb, p)
    return jax.lax.dynamic_update_slice(out, packed, (off, 0)), ks


def k_panel_getrf_update(pk, ki, pj):
    import jax
    import jax.numpy as jnp
    nb = pk.shape[1]
    off = ki[0] * nb
    lkk = jax.lax.dynamic_slice(pk, (off, 0), (nb, nb))
    bk = jax.lax.dynamic_slice(pj, (off, 0), (nb, nb))
    ukj = jax.scipy.linalg.solve_triangular(lkk, bk, lower=True,
                                            unit_diagonal=True)
    rows = jnp.arange(pk.shape[0], dtype=ki.dtype)[:, None]
    lmask = jnp.where(rows >= off + nb, pk, jnp.zeros((), pk.dtype))
    upd = pj - jax.lax.dot_general(lmask, ukj, (((1,), (0,)), ((), ())),
                                   preferred_element_type=pj.dtype)
    return jax.lax.dynamic_update_slice(upd, ukj, (off, 0))


def _getrf_b_factor(nt, nb, pshp, dt):
    def b_factor(t):
        p = t.data("P", dt, pshp)
        kk = int(t.data("KS", np.int32, (1,))[0])
        t.data("KI", np.int32, (1,))[0] = kk
        off = kk * nb
        packed = _getrf_np(p[off:off + nb].copy())
        ukk = np.triu(packed)
        p[off + nb:] = np.linalg.solve(ukk.T, p[off + nb:].T).T
        p[off:off + nb] = packed
    return b_factor


def _getrf_b_update(nt, nb, pshp, dt):
    def b_update(t):
        pk_ = t.data("PK", dt, pshp)
        kk = int(t.data("KI", np.int32, (1,))[0])
        pj_ = t.data("PJ", dt, pshp)
        off = kk * nb
        lkk = np.tril(pk_[off:off + nb], -1) + np.eye(nb, dtype=dt)
        ukj = np.linalg.solve(lkk, pj_[off:off + nb])
        pj_[off + nb:] -= pk_[off + nb:] @ ukj
        pj_[off:off + nb] = ukj
    return b_update


def build_getrf_panels(ctx, A, dev=None, name: str = "A"):
    """Panel-granular no-pivot LU: the getrf analog of
    build_potrf_panels (same shared DAG; LU kernels/bodies).  Result
    layout per panel j: rows above j*nb = finalized U rows, the block =
    packed L\\U, rows below = L columns — assembling tril(,-1)+I and
    triu reproduces getrf_nopiv_reference's packed dense."""
    from .potrf import _build_panel_factorization
    return _build_panel_factorization(
        ctx, A, dev, name, k_panel_getrf, k_panel_getrf_update,
        _getrf_b_factor, _getrf_b_update, update_uses="k")
