"""Generic collection→collection redistribution.

Reference: parsec/data_dist/matrix/redistribute/ (redistribute.jdf +
redistribute_dtd.c + redistribute_wrapper.c — SURVEY.md §2.3): copy an
arbitrary M×N submatrix from a source tiled collection (any tile size,
any displacement) into a target collection (any tile size, any
displacement).  The reference JDF splits every target tile into nine
regions (NW/N/NE/W/I/E/SW/S/SE) against the source grid; here the DAG is
discovered dynamically (the reference ships a DTD variant too,
redistribute_dtd.c): one copy task per (source tile × target tile)
intersection, INPUT on the source tile and INOUT on the target tile —
the per-tile accessor chains serialize writers of the same target tile
while distinct tiles proceed fully in parallel, and in distributed mode
tasks run on the target tile's owner with source payloads shipped by the
comm engine.

This is also the framework's all-to-all resharding primitive (the dense-LA
analog of sequence/context resharding — SURVEY.md §5 long-context note).
"""
from __future__ import annotations

import numpy as np

from ..core.context import Context
from ..dsl.dtd import DtdTaskpool


def _tile_range(lo: int, hi: int, tb: int):
    """Tiles [t_lo, t_hi] covering element rows [lo, hi)."""
    return lo // tb, (hi - 1) // tb


def redistribute(ctx: Context, src, dst, size_row: int, size_col: int,
                 disi_src: int = 0, disj_src: int = 0,
                 disi_dst: int = 0, disj_dst: int = 0,
                 window: int = 8000) -> None:
    """Copy src[disi_src:disi_src+size_row, disj_src:disj_src+size_col]
    into dst[disi_dst:..., disj_dst:...].  Blocks until done."""
    if size_row <= 0 or size_col <= 0:
        return
    if disi_src + size_row > src.M or disj_src + size_col > src.N:
        raise ValueError("source region out of bounds")
    if disi_dst + size_row > dst.M or disj_dst + size_col > dst.N:
        raise ValueError("target region out of bounds")
    for coll in (src, dst):  # data_of needs a context to create Data handles
        if getattr(coll, "_ctx", None) is None:
            coll._ctx = ctx
    sdt, ddt = src.dtype, dst.dtype
    smb, snb, dmb, dnb = src.mb, src.nb, dst.mb, dst.nb

    tp = DtdTaskpool(ctx, window=window)
    try:
        # accumulate (body, args) specs; ONE native crossing per
        # dtd.insert_batch tasks (tp.insert_tasks) instead of a
        # begin/arg/submit triple per copy task
        batch = []
        tm_lo, tm_hi = _tile_range(disi_dst, disi_dst + size_row, dmb)
        tn_lo, tn_hi = _tile_range(disj_dst, disj_dst + size_col, dnb)
        for tm in range(tm_lo, tm_hi + 1):
            # target tile row-extent ∩ copied region, in "offset" space
            # (r = row index within the copied submatrix)
            r0 = max(tm * dmb, disi_dst) - disi_dst
            r1 = min((tm + 1) * dmb, disi_dst + size_row) - disi_dst
            for tn in range(tn_lo, tn_hi + 1):
                c0 = max(tn * dnb, disj_dst) - disj_dst
                c1 = min((tn + 1) * dnb, disj_dst + size_col) - disj_dst
                dst_tile = tp.tile_of(dst, tm, tn)
                # source tiles overlapped by this offset rectangle
                sm_lo, sm_hi = _tile_range(disi_src + r0, disi_src + r1, smb)
                sn_lo, sn_hi = _tile_range(disj_src + c0, disj_src + c1, snb)
                for sm in range(sm_lo, sm_hi + 1):
                    rr0 = max(r0, sm * smb - disi_src)
                    rr1 = min(r1, (sm + 1) * smb - disi_src)
                    for sn in range(sn_lo, sn_hi + 1):
                        cc0 = max(c0, sn * snb - disj_src)
                        cc1 = min(c1, (sn + 1) * snb - disj_src)
                        if rr1 <= rr0 or cc1 <= cc0:
                            continue
                        src_tile = tp.tile_of(src, sm, sn)
                        # local offsets of the intersection in each tile
                        si = disi_src + rr0 - sm * smb
                        sj = disj_src + cc0 - sn * snb
                        di = disi_dst + rr0 - tm * dmb
                        dj = disj_dst + cc0 - tn * dnb
                        h, w = rr1 - rr0, cc1 - cc0

                        def body(view, si=si, sj=sj, di=di, dj=dj, h=h, w=w):
                            s = view.data(0, sdt, (smb, snb))
                            d = view.data(1, ddt, (dmb, dnb))
                            d[di:di + h, dj:dj + w] = \
                                s[si:si + h, sj:sj + w].astype(ddt)

                        batch.append((body, ((src_tile, "INPUT"),
                                             (dst_tile, "INOUT"))))
        tp.insert_tasks(batch)
        tp.wait()
    finally:
        tp.destroy()
