"""Tiled triangular solve L X = B (DPLASMA dtrsm Left/Lower/NoTrans) as
a PTG taskpool — forward substitution over tile columns:

  ReadDiag(k)   : broadcast L[k,k] to the solve row
  ReadL(k, m)   : broadcast L[m,k] (m > k) to the update row
  SOLVE(k, n)   : X[k,n] = L[k,k]^-1 B'[k,n]
  GEMM(k, m, n) : B'[m,n] -= L[m,k] X[k,n]        (m > k)

B is overwritten by X in place (the reference's dtrsm contract).  The L
tiles move by reader-task broadcasts placed AT their data (this runtime
rejects cross-rank collection reads; see build_gemm_dist), so L and B
may have completely different distributions.  Composed after
build_potrf this is the dpotrs/dposv pipeline.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import parsec_tpu as pt
from ..data.collections import TwoDimBlockCyclic
from ..device.tpu import TpuDevice

from ._util import as_device_list


def k_solve(t, c):
    import jax
    return jax.scipy.linalg.solve_triangular(t, c, lower=True)


def k_update(l, x, c):
    import jax
    return c - jax.lax.dot_general(l, x, (((1,), (0,)), ((), ())),
                                   preferred_element_type=c.dtype)


def build_trsm(ctx: pt.Context, L: TwoDimBlockCyclic, B: TwoDimBlockCyclic,
               dev: Optional[TpuDevice] = None,
               names=("L", "B")) -> pt.Taskpool:
    """Build the solve taskpool: L lower-triangular (mt == nt), B the
    right-hand sides (B.mt == L.mt), both registered with ctx."""
    assert L.mt == L.nt and B.mt == L.mt
    nt, nrhs = L.mt, B.nt
    tp = pt.Taskpool(ctx, globals={"NT": nt - 1, "NR": nrhs - 1})
    k, m, n = pt.L("k"), pt.L("m"), pt.L("n")
    NT, NR = pt.G("NT"), pt.G("NR")
    ln, bn = names
    dt = B.dtype
    shp_l = (L.mb, L.nb)
    shp_b = (B.mb, B.nb)

    rd = tp.task_class("ReadDiag")
    rd.param("k", 0, NT)
    rd.affinity(ln, k, k)
    rd.flow("T", "READ",
            pt.In(pt.Mem(ln, k, k)),
            pt.Out(pt.Ref("SOLVE", k, pt.Range(0, NR), flow="T")))
    rd.body_noop()

    rl = tp.task_class("ReadL")
    rl.param("k", 0, NT)
    rl.param("m", k + 1, NT)
    rl.affinity(ln, m, k)
    rl.flow("L", "READ",
            pt.In(pt.Mem(ln, m, k)),
            pt.Out(pt.Ref("GEMM", k, m, pt.Range(0, NR), flow="L")))
    rl.body_noop()

    so = tp.task_class("SOLVE")
    so.param("k", 0, NT)
    so.param("n", 0, NR)
    so.affinity(bn, k, n)
    so.priority((NT - k) * 1000 - n)
    so.flow("T", "READ", pt.In(pt.Ref("ReadDiag", k, flow="T")))
    so.flow("X", "RW",
            pt.In(pt.Mem(bn, k, n), guard=(k == 0)),
            pt.In(pt.Ref("GEMM", k - 1, k, n, flow="C")),
            pt.Out(pt.Ref("GEMM", k, pt.Range(k + 1, NT), n, flow="X"),
                   guard=(k < NT)),
            pt.Out(pt.Mem(bn, k, n)))

    ge = tp.task_class("GEMM")
    ge.param("k", 0, NT)
    ge.param("m", k + 1, NT)
    ge.param("n", 0, NR)
    ge.affinity(bn, m, n)
    ge.priority((NT - k) * 1000 - m - n)
    ge.flow("L", "READ", pt.In(pt.Ref("ReadL", k, m, flow="L")))
    ge.flow("X", "READ", pt.In(pt.Ref("SOLVE", k, n, flow="X")))
    ge.flow("C", "RW",
            pt.In(pt.Mem(bn, m, n), guard=(k == 0)),
            pt.In(pt.Ref("GEMM", k - 1, m, n, flow="C")),
            pt.Out(pt.Ref("SOLVE", m, n, flow="X"), guard=(m == k + 1)),
            pt.Out(pt.Ref("GEMM", k + 1, m, n, flow="C"),
                   guard=(m > k + 1)))

    for d in as_device_list(dev):
        d.attach(so, tp, kernel=k_solve, reads=["T", "X"], writes=["X"],
                 shapes={"T": shp_l, "X": shp_b}, dtype=dt)
        d.attach(ge, tp, kernel=k_update, reads=["L", "X", "C"],
                 writes=["C"], shapes={"L": shp_l, "X": shp_b, "C": shp_b},
                 dtype=dt)

    def b_solve(t):
        l = np.tril(t.data("T", dt, shp_l))
        c = t.data("X", dt, shp_b)
        c[...] = np.linalg.solve(l, c)

    def b_update(t):
        l = t.data("L", dt, shp_l)
        x = t.data("X", dt, shp_b)
        c = t.data("C", dt, shp_b)
        c -= l @ x

    so.body(b_solve)
    ge.body(b_update)
    return tp
