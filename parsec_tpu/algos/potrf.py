"""Tiled Cholesky factorization (lower) as a PTG taskpool — the DPLASMA
dpotrf_L dataflow (the BASELINE north-star workload), built from four task
classes over a 2D block-cyclic matrix:

  POTRF(k)    : diagonal tile factor        A[k,k] = chol(A[k,k])
  TRSM(m,k)   : panel solve                 A[m,k] = A[m,k] inv(L[k,k])^T
  SYRK(k,m)   : diagonal trailing update    A[m,m] -= A[m,k] A[m,k]^T
  GEMM(m,n,k) : off-diag trailing update    A[m,n] -= A[m,k] A[n,k]^T

Kernels run as cached XLA executables on the TPU device, with numpy CPU
fallback chores.  Priorities favor the critical path (deeper k first),
matching the reference's priority-expression practice in dense LA JDFs.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import parsec_tpu as pt
from ..data.collections import TwoDimBlockCyclic
from ..device.tpu import TpuDevice

from ._util import as_device_list


# ---------------------------------------------------------------- kernels
# module-level so their identity is stable: jax.jit keeps ONE compiled
# executable per (kernel, tile shape, dtype) across taskpools/processes
def k_potrf(t):
    import jax.numpy as jnp
    return jnp.linalg.cholesky(t)


def k_trsm(l, c):
    import jax
    return jax.scipy.linalg.solve_triangular(l, c.T, lower=True).T


def k_potrf_inv(t):
    """POTRF that also emits inv(L): ONE small triangular solve per panel
    turns every TRSM in the panel's wave into a plain batched GEMM — the
    MXU runs matmuls an order of magnitude faster than XLA's blocked
    triangular solve runs on a whole wave of tiles (tools/
    probe_la_kernels.py quantifies the gap per chip).  Standard
    inversion-based TRSM practice from GPU dense LA, TPU-shaped."""
    import jax
    import jax.numpy as jnp
    l = jnp.linalg.cholesky(t)
    linv = jax.scipy.linalg.solve_triangular(
        l, jnp.eye(t.shape[0], dtype=t.dtype), lower=True)
    return l, linv


def k_trsm_mm(linv, c):
    """TRSM as GEMM: X L^T = C  ->  X = C inv(L)^T."""
    import jax
    return jax.lax.dot_general(c, linv, (((1,), (1,)), ((), ())),
                               preferred_element_type=c.dtype)


def k_syrk(a, t):
    import jax
    return t - jax.lax.dot_general(a, a, (((1,), (1,)), ((), ())),
                                   preferred_element_type=t.dtype)


def k_gemm(a, b, c):
    import jax
    return c - jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                                   preferred_element_type=c.dtype)


def build_potrf(ctx: pt.Context, A: TwoDimBlockCyclic,
                dev: Optional[TpuDevice] = None,
                name: str = "A",
                trsm_via_inverse: bool = True) -> pt.Taskpool:
    """Build the Cholesky taskpool for the square tiled SPD matrix `A`
    (registered with ctx under `name`).  A.mt == A.nt required.

    trsm_via_inverse (default): POTRF(k) additionally emits inv(L[k,k])
    through a W temp flow and TRSM becomes a batched GEMM against it —
    one extra NB-size triangular solve per PANEL instead of one per
    TILE, and the whole TRSM wave rides the MXU.  Set False for the
    textbook solve_triangular dataflow."""
    nt = A.mt
    assert A.mt == A.nt and A.mb == A.nb
    nb = A.mb
    tp = pt.Taskpool(ctx, globals={"NT": nt - 1})
    k, m, n = pt.L("k"), pt.L("m"), pt.L("n")
    NT = pt.G("NT")
    shp = (nb, nb)
    dt = A.dtype
    if trsm_via_inverse:
        li_arena = f"potrf_li_{nb}_{np.dtype(dt).str}"
        if li_arena not in ctx.arenas:  # re-builds must not leak an id
            ctx.register_arena(li_arena, nb * nb * np.dtype(dt).itemsize)

    # ------------------------------------------------------------- POTRF(k)
    po = tp.task_class("POTRF")
    po.param("k", 0, NT)
    po.affinity(name, k, k)
    po.priority((NT - k) * 1000)
    if trsm_via_inverse:
        po.flow("T", "RW",
                pt.In(pt.Mem(name, k, k), guard=(k == 0)),
                pt.In(pt.Ref("SYRK", k - 1, k, flow="T")),
                pt.Out(pt.Mem(name, k, k)))
        # the panel inverse: consumed by every TRSM in this panel's wave
        po.flow("I", "W",
                pt.Out(pt.Ref("TRSM", k, pt.Range(k + 1, NT), flow="L"),
                       guard=(k < NT)),
                arena=li_arena)
    else:
        po.flow("T", "RW",
                pt.In(pt.Mem(name, k, k), guard=(k == 0)),
                pt.In(pt.Ref("SYRK", k - 1, k, flow="T")),
                pt.Out(pt.Ref("TRSM", k, pt.Range(k + 1, NT), flow="L"),
                       guard=(k < NT)),
                pt.Out(pt.Mem(name, k, k)))

    # ----------------------------------------------------------- TRSM(m, k)
    tr = tp.task_class("TRSM")
    tr.param("k", 0, NT)
    tr.param("m", k + 1, NT)
    tr.affinity(name, m, k)
    tr.priority((NT - k) * 1000 - m)
    tr.flow("L", "READ",
            pt.In(pt.Ref("POTRF", k, flow="I" if trsm_via_inverse
                         else "T")))
    # NB: GEMM's declared param order is (k, m, n) — Refs must match it
    tr.flow("C", "RW",
            pt.In(pt.Mem(name, m, k), guard=(k == 0)),
            pt.In(pt.Ref("GEMM", k - 1, m, k, flow="C")),
            # SYRK(k, m) updates diagonal (m, m) with this panel
            pt.Out(pt.Ref("SYRK", k, m, flow="A")),
            # GEMM row m: A[m, n] for k < n < m uses this as the A operand
            pt.Out(pt.Ref("GEMM", k, m, pt.Range(k + 1, m - 1), flow="A"),
                   guard=(m > k + 1)),
            # GEMM column m: A[mm, m] for m < mm <= NT uses it as B operand
            pt.Out(pt.Ref("GEMM", k, pt.Range(m + 1, NT), m, flow="B"),
                   guard=(m < NT)),
            pt.Out(pt.Mem(name, m, k)))

    # ----------------------------------------------------------- SYRK(k, m)
    sy = tp.task_class("SYRK")
    sy.param("k", 0, NT)
    sy.param("m", k + 1, NT)
    sy.affinity(name, m, m)
    sy.priority((NT - k) * 1000 - m)
    sy.flow("A", "READ", pt.In(pt.Ref("TRSM", k, m, flow="C")))
    sy.flow("T", "RW",
            pt.In(pt.Mem(name, m, m), guard=(k == 0)),
            pt.In(pt.Ref("SYRK", k - 1, m, flow="T")),
            pt.Out(pt.Ref("POTRF", m, flow="T"), guard=(m == k + 1)),
            pt.Out(pt.Ref("SYRK", k + 1, m, flow="T"), guard=(m > k + 1)))

    # -------------------------------------------------------- GEMM(m, n, k)
    ge = tp.task_class("GEMM")
    ge.param("k", 0, NT)
    ge.param("m", k + 2, NT)
    ge.param("n", k + 1, m - 1)
    ge.affinity(name, m, n)
    ge.priority((NT - k) * 1000 - m - n)
    ge.flow("A", "READ", pt.In(pt.Ref("TRSM", k, m, flow="C")))
    ge.flow("B", "READ", pt.In(pt.Ref("TRSM", k, n, flow="C")))
    ge.flow("C", "RW",
            pt.In(pt.Mem(name, m, n), guard=(k == 0)),
            pt.In(pt.Ref("GEMM", k - 1, m, n, flow="C")),
            pt.Out(pt.Ref("TRSM", n, m, flow="C"), guard=(n == k + 1)),
            pt.Out(pt.Ref("GEMM", k + 1, m, n, flow="C"), guard=(n > k + 1)))

    # --------------------------------------------------------------- chores
    # one or several devices: each attach adds a device chore; the native
    # best-device routing load-balances task instances across the queues
    # (reference: parsec_get_best_device, device.c:79-160), and sibling
    # mirrors stage D2D over the fabric
    for d in as_device_list(dev):
        if trsm_via_inverse:
            d.attach(po, tp, kernel=k_potrf_inv, reads=["T"],
                     writes=["T", "I"], shapes={"T": shp, "I": shp},
                     dtype=dt)
            d.attach(tr, tp, kernel=k_trsm_mm, reads=["L", "C"],
                     writes=["C"], shapes={"L": shp, "C": shp}, dtype=dt)
        else:
            d.attach(po, tp, kernel=k_potrf, reads=["T"], writes=["T"],
                     shapes={"T": shp}, dtype=dt)
            d.attach(tr, tp, kernel=k_trsm, reads=["L", "C"],
                     writes=["C"], shapes={"L": shp, "C": shp}, dtype=dt)
        d.attach(sy, tp, kernel=k_syrk, reads=["A", "T"], writes=["T"],
                 shapes={"A": shp, "T": shp}, dtype=dt)
        d.attach(ge, tp, kernel=k_gemm, reads=["A", "B", "C"], writes=["C"],
                 shapes={"A": shp, "B": shp, "C": shp}, dtype=dt)

    def b_potrf(t):
        a = t.data("T", dt, shp)
        a[...] = np.linalg.cholesky(a)
        if trsm_via_inverse:
            li = t.data("I", dt, shp)
            li[...] = np.linalg.solve(a, np.eye(nb, dtype=dt))

    def b_trsm(t):
        l = t.data("L", dt, shp)  # inv(L) when trsm_via_inverse
        c = t.data("C", dt, shp)
        if trsm_via_inverse:
            c[...] = c @ l.T
        else:
            # X L^T = C -> X = (L^-1 C^T)^T ; use lapack-free solve
            c[...] = np.linalg.solve(l, c.T).T

    def b_syrk(t):
        a = t.data("A", dt, shp)
        x = t.data("T", dt, shp)
        x -= a @ a.T

    def b_gemm(t):
        a = t.data("A", dt, shp)
        b = t.data("B", dt, shp)
        c = t.data("C", dt, shp)
        c -= a @ b.T

    # pure tile chores (read/write only their declared flows): the
    # declaration makes homogeneous waves fusion-eligible for the
    # wave-fusability certificate (analysis/plan.py certify())
    po.body(b_potrf, pure=True)
    tr.body(b_trsm, pure=True)
    sy.body(b_syrk, pure=True)
    ge.body(b_gemm, pure=True)
    return tp


# ------------------------------------------------------ panel variant
# Right-looking blocked Cholesky at PANEL granularity: tasks operate on
# full-height N x nb column panels instead of nb x nb tiles.  Same math
# as the tiled dataflow (DPLASMA dpotrf_L), coarser tasks: each trailing
# update U(k, j) is ONE (N x nb) @ (nb x nb) MXU matmul, and a wave of
# them is one vmapped call — the TPU-shaped answer to the tile DAG's
# launch-overhead wall on a single fat chip.  (The panel-granular,
# few-big-matmuls shape follows the published TPU dense-LA recipe —
# "Large Scale Distributed Linear Algebra With Tensor Processing
# Units", arXiv:2112.09017 — recast as runtime task dataflow.)  The
# tiled build_potrf remains the distributed (PxQ block-cyclic) form.
#
#   F(k)   : factor panel k   diag = chol(P[kb:kb+nb]); P = P inv(L)^T
#            (rows above kb zeroed, diag block set to L exactly)
#   U(k,j) : panel j trailing update   P_j -= P_k P_k[jb:jb+nb]^T
#
# Panel row offsets ride a tiny int32 index collection (kernels receive
# only flow arrays; the offset is data, not a compile-time constant, so
# ONE executable serves every k).


def k_panel_factor(p, ks):
    import jax
    import jax.numpy as jnp
    nb = p.shape[1]
    off = ks[0] * nb
    diag = jax.lax.dynamic_slice(p, (off, 0), (nb, nb))
    l = jnp.linalg.cholesky(diag)
    linv = jax.scipy.linalg.solve_triangular(
        l, jnp.eye(nb, dtype=p.dtype), lower=True)
    x = jax.lax.dot_general(p, linv, (((1,), (1,)), ((), ())),
                            preferred_element_type=p.dtype)
    rows = jnp.arange(p.shape[0], dtype=ks.dtype)[:, None]
    x = jnp.where(rows >= off, x, jnp.zeros((), p.dtype))
    return jax.lax.dynamic_update_slice(x, l, (off, 0))


def k_panel_update(pk, js, pj):
    import jax
    nb = pk.shape[1]
    off = js[0] * nb
    bj = jax.lax.dynamic_slice(pk, (off, 0), (nb, nb))
    return pj - jax.lax.dot_general(pk, bj, (((1,), (1,)), ((), ())),
                                    preferred_element_type=pj.dtype)


def _register_pidx(ctx: pt.Context, A: TwoDimBlockCyclic, name: str):
    """Register (once) the int32 panel-index collection `name + "_pidx"`
    following A's panel-cyclic map, so every Mem(pidx, j) read is
    co-located with the task that issues it."""
    from ..data.collections import VectorCyclic
    pidx_name = name + "_pidx"
    # guard on OUR registry, not ctx.collections: a user collection that
    # happens to be named <name>_pidx must not satisfy the early return
    # (it has no _pidx_colls record and the wrong contents)
    if pidx_name in getattr(ctx, "_pidx_colls", {}):
        return pidx_name, ctx._pidx_colls[pidx_name]
    if pidx_name in ctx.collections:
        raise ValueError(
            f"collection name {pidx_name!r} is reserved for the panel "
            f"index of {name!r} but is already registered")
    pidx = VectorCyclic(A.nt, 1, nodes=A.nodes, myrank=A.myrank,
                        dtype=np.int32)
    for j in range(A.nt):
        pidx.seg(j)[0] = j
    pidx.register(ctx, pidx_name)
    if not hasattr(ctx, "_pidx_colls"):
        ctx._pidx_colls = {}
    ctx._pidx_colls[pidx_name] = pidx
    return pidx_name, pidx


def _build_panel_factorization(ctx: pt.Context, A: TwoDimBlockCyclic,
                               dev, name: str,
                               k_factor, k_update,
                               b_factor, b_update,
                               update_uses: str = "j") -> pt.Taskpool:
    """Shared panel-factorization DAG (right-looking, full-height
    panels): F(k) factors panel k, U(k, j) applies its rank-nb update to
    panel j, a U wave batches into one vmapped MXU call.  The algorithm
    lives in the kernel/body pair: Cholesky (build_potrf_panels) and
    no-pivot LU (build_getrf_panels) share this graph.

      F(k)   : P RW (chain from U(k-1,k)), KS index READ
      U(k,j) : PK READ (broadcast from F(k)), an index flow, PJ RW chain

    update_uses selects which panel index U's kernel needs:
      "j" — the TARGET panel's index, read co-located from the pidx
            collection (Cholesky slices the source panel at row block j)
      "k" — the SOURCE panel's index; pidx[k] is NOT co-located with
            U(k, j) on rank j, so F(k) emits it as a tiny KI arena flow
            that broadcasts WITH the panel (distributed-correct; LU
            solves at row block k).  k_factor then returns (panel, ki).

    Host bodies are built by b_factor/b_update factories given
    (nt, nb, pshp, dt)."""
    assert A.mt == 1 and A.M == A.N and A.M == A.mb, \
        "panel collection: mb == M (one block row of panels)"
    assert A.P == 1, "panels distribute 1-D: P must be 1 (Q = nodes)"
    nt = A.nt
    nb = A.nb
    NN = A.M
    dt = A.dtype
    pidx_name, pidx = _register_pidx(ctx, A, name)
    tp = pt.Taskpool(ctx, globals={"NT": nt - 1})
    k, j = pt.L("k"), pt.L("j")
    NT = pt.G("NT")

    # ------------------------------------------------------------- F(k)
    fa = tp.task_class("PF")
    fa.param("k", 0, NT)
    fa.affinity(name, 0, k)
    fa.priority((NT - k) * 1000 + 500)
    fa.flow("P", "RW",
            pt.In(pt.Mem(name, 0, k), guard=(k == 0)),
            pt.In(pt.Ref("PU", k - 1, k, flow="PJ")),
            pt.Out(pt.Ref("PU", k, pt.Range(k + 1, NT), flow="PK"),
                   guard=(k < NT)),
            pt.Out(pt.Mem(name, 0, k)))
    fa.flow("KS", "READ", pt.In(pt.Mem(pidx_name, k)))
    if update_uses == "k":
        ki_arena = f"panel_ki_{name}"
        if ki_arena not in ctx.arenas:  # re-builds must not leak an id
            ctx.register_arena(ki_arena, 4)
        fa.flow("KI", "W",
                pt.Out(pt.Ref("PU", k, pt.Range(k + 1, NT), flow="KI"),
                       guard=(k < NT)),
                arena=ki_arena)

    # ----------------------------------------------------------- U(k, j)
    up = tp.task_class("PU")
    up.param("k", 0, NT)
    up.param("j", k + 1, NT)
    up.affinity(name, 0, j)
    up.priority((NT - k) * 1000 - j)
    up.flow("PK", "READ", pt.In(pt.Ref("PF", k, flow="P")))
    if update_uses == "k":
        up.flow("KI", "READ", pt.In(pt.Ref("PF", k, flow="KI")))
    else:
        up.flow("JS", "READ", pt.In(pt.Mem(pidx_name, j)))
    up.flow("PJ", "RW",
            pt.In(pt.Mem(name, 0, j), guard=(k == 0)),
            pt.In(pt.Ref("PU", k - 1, j, flow="PJ")),
            pt.Out(pt.Ref("PF", j, flow="P"), guard=(j == k + 1)),
            pt.Out(pt.Ref("PU", k + 1, j, flow="PJ"), guard=(j > k + 1)))

    # --------------------------------------------------------------- chores
    pshp = (NN, nb)
    devs = as_device_list(dev)
    # pre-stage this rank's index segments as ONE stacked device array
    # per device: every wave's KS/JS gather then rides the fused
    # (stack, idx) path instead of an eager per-wave stack of h2d'd
    # scalars
    local = [k2 for k2 in range(nt) if pidx.rank_of(k2) == pidx.myrank]
    seg_host = np.asarray(local, dtype=np.int32).reshape(-1, 1)
    for d in devs:
        if local:
            from ..device.bench_utils import install_device_segments
            install_device_segments(
                d, pidx, d._jax.device_put(seg_host, d.device))
        idxf = "KI" if update_uses == "k" else "JS"
        d.attach(fa, tp, kernel=k_factor, reads=["P", "KS"],
                 writes=["P", "KI"] if update_uses == "k" else ["P"],
                 shapes={"P": pshp, "KS": (1,), "KI": (1,)},
                 dtypes={"P": np.dtype(dt), "KS": np.dtype(np.int32),
                         "KI": np.dtype(np.int32)})
        d.attach(up, tp, kernel=k_update, reads=["PK", idxf, "PJ"],
                 writes=["PJ"],
                 shapes={"PK": pshp, idxf: (1,), "PJ": pshp},
                 dtypes={"PK": np.dtype(dt), idxf: np.dtype(np.int32),
                         "PJ": np.dtype(dt)})
        # speculative epilogue (dispatch-economics lever): the U(k, k+1)
        # lane's output IS F(k+1)'s input — factor it inside the same
        # wave program, so the factor chain costs ONE device call per k
        # step instead of two.  F(k+1) then completes from the parked
        # result, version-checked.  Works for both variants: potrf's
        # factor returns the panel; getrf's returns (panel, KI), which
        # matches its two write flows (arity is validated at the hit).
        d.attach_epilogue(
            up, fa, tp, src_flow="PJ", dst_in_flow="P",
            pick=lambda v: ((v.local("j"),)
                            if v.local("j") == v.local("k") + 1
                            else None),
            dst_params=lambda v: (v.local("k"),),
            kernel=k_factor,
            ops=lambda key: [np.asarray([key[0]], dtype=np.int32)],
            # KS is the pivot-index flow: constant per k and folded into
            # ops (single-varying-input contract, see attach_epilogue)
            const_flows=("KS",))

    fa.body(b_factor(nt, nb, pshp, dt))
    up.body(b_update(nt, nb, pshp, dt))
    return tp


def _potrf_b_factor(nt, nb, pshp, dt):
    def b_factor(t):
        p = t.data("P", dt, pshp)
        kk = int(t.data("KS", np.int32, (1,))[0])
        off = kk * nb
        diag = p[off:off + nb]
        l = np.linalg.cholesky(diag)
        linv = np.linalg.solve(l, np.eye(nb, dtype=dt))
        x = p @ linv.T
        x[:off] = 0
        x[off:off + nb] = l
        p[...] = x
    return b_factor


def _potrf_b_update(nt, nb, pshp, dt):
    def b_update(t):
        pk_ = t.data("PK", dt, pshp)
        jj = int(t.data("JS", np.int32, (1,))[0])
        pj_ = t.data("PJ", dt, pshp)
        off = jj * nb
        pj_ -= pk_ @ pk_[off:off + nb].T
    return b_update


def build_potrf_panels(ctx: pt.Context, A: TwoDimBlockCyclic,
                       dev: Optional[TpuDevice] = None,
                       name: str = "A") -> pt.Taskpool:
    """Panel-granular Cholesky taskpool.  `A` must be a single block row
    of N x nb panels: TwoDimBlockCyclic(N, N, N, nb) registered under
    `name`.  Also registers an int32 index collection under
    `name + "_pidx"`."""
    return _build_panel_factorization(
        ctx, A, dev, name, k_panel_factor, k_panel_update,
        _potrf_b_factor, _potrf_b_update)


def k_panel_fwd(p, ks, b):
    """Forward-substitution step on the whole RHS block: solve the
    diagonal rows against L_kk, then eliminate below."""
    import jax
    import jax.numpy as jnp
    nb = p.shape[1]
    off = ks[0] * nb
    lkk = jax.lax.dynamic_slice(p, (off, 0), (nb, nb))
    bk = jax.lax.dynamic_slice(b, (off, 0), (nb, b.shape[1]))
    yk = jax.scipy.linalg.solve_triangular(lkk, bk, lower=True)
    upd = b - jax.lax.dot_general(p, yk, (((1,), (0,)), ((), ())),
                                  preferred_element_type=b.dtype)
    rows = jnp.arange(b.shape[0], dtype=ks.dtype)[:, None]
    # rows above the block keep their solved values; the block row takes
    # y_k; rows below take the eliminated update
    out = jnp.where(rows >= off + nb, upd, b)
    return jax.lax.dynamic_update_slice(out, yk, (off, 0))


def k_panel_bwd(p, ks, b):
    """Backward-substitution step: x_k = L_kk^-T (y_k - L_below^T x_below)."""
    import jax
    import jax.numpy as jnp
    nb = p.shape[1]
    off = ks[0] * nb
    lkk = jax.lax.dynamic_slice(p, (off, 0), (nb, nb))
    # contribution of already-solved rows BELOW the block: P rows below
    # hold L[below, k-block]; mask rows <= off+nb so only solved x rows
    # contribute
    rows = jnp.arange(b.shape[0], dtype=ks.dtype)[:, None]
    xmask = jnp.where(rows >= off + nb, b, jnp.zeros((), b.dtype))
    contrib = jax.lax.dot_general(p, xmask, (((0,), (0,)), ((), ())),
                                  preferred_element_type=b.dtype)
    yk = jax.lax.dynamic_slice(b, (off, 0), (nb, b.shape[1]))
    xk = jax.scipy.linalg.solve_triangular(lkk, yk - contrib, lower=True,
                                           trans="T")
    return jax.lax.dynamic_update_slice(b, xk, (off, 0))


def build_potrs_panels(ctx: pt.Context, A: TwoDimBlockCyclic, B,
                       dev: Optional[TpuDevice] = None,
                       name: str = "A", bname: str = "B") -> pt.Taskpool:
    """Panel-granular triangular solve after build_potrf_panels (the
    dpotrs role; potrf_panels + potrs_panels = posv).  `A` holds the
    factored panels (same collection the factorization ran on); `B` is a
    single-tile (N, nrhs) collection registered under `bname`.  Forward
    substitution walks panels 0..NT-1, backward NT-1..0 — 2*NT tasks,
    each one tall MXU contraction over the whole RHS block.
    Single-rank form (the distributed solve rides the tiled
    algos/trsm.py)."""
    assert A.mt == 1 and A.M == A.mb
    assert A.nodes == 1, \
        "potrs_panels is the single-rank solve (distributed: algos/trsm.py)"
    nt = A.nt
    nb = A.nb
    NN = A.M
    dt = A.dtype
    nrhs = B.nb
    assert B.mt == 1 and B.nt == 1 and B.mb == NN
    assert B.dtype == A.dtype, "A and B dtypes must match"
    pidx_name, _ = _register_pidx(ctx, A, name)
    tp = pt.Taskpool(ctx, globals={"NT": nt - 1})
    k = pt.L("k")
    NT = pt.G("NT")

    fw = tp.task_class("FWD")
    fw.param("k", 0, NT)
    fw.affinity(bname, 0, 0)
    fw.flow("P", "READ", pt.In(pt.Mem(name, 0, k)))
    fw.flow("KS", "READ", pt.In(pt.Mem(pidx_name, k)))
    fw.flow("B", "RW",
            pt.In(pt.Mem(bname, 0, 0), guard=(k == 0)),
            pt.In(pt.Ref("FWD", k - 1, flow="B")),
            pt.Out(pt.Ref("FWD", k + 1, flow="B"), guard=(k < NT)),
            pt.Out(pt.Ref("BWD", NT, flow="B"), guard=(k == NT)))

    bw = tp.task_class("BWD")
    bw.param("k", 0, NT)
    bw.affinity(bname, 0, 0)
    bw.flow("P", "READ", pt.In(pt.Mem(name, 0, k)))
    bw.flow("KS", "READ", pt.In(pt.Mem(pidx_name, k)))
    bw.flow("B", "RW",
            pt.In(pt.Ref("FWD", NT, flow="B"), guard=(k == NT)),
            pt.In(pt.Ref("BWD", k + 1, flow="B"), guard=(k < NT)),
            pt.Out(pt.Ref("BWD", k - 1, flow="B"), guard=(k > 0)),
            pt.Out(pt.Mem(bname, 0, 0), guard=(k == 0)))

    pshp, bshp = (NN, nb), (NN, nrhs)
    for d in as_device_list(dev):
        d.attach(fw, tp, kernel=k_panel_fwd, reads=["P", "KS", "B"],
                 writes=["B"], shapes={"P": pshp, "KS": (1,), "B": bshp},
                 dtypes={"P": np.dtype(dt), "KS": np.dtype(np.int32),
                         "B": np.dtype(dt)}, sync_mem_out=True)
        d.attach(bw, tp, kernel=k_panel_bwd, reads=["P", "KS", "B"],
                 writes=["B"], shapes={"P": pshp, "KS": (1,), "B": bshp},
                 dtypes={"P": np.dtype(dt), "KS": np.dtype(np.int32),
                         "B": np.dtype(dt)}, sync_mem_out=True)

    def b_fwd(t):
        p = t.data("P", dt, pshp)
        kk = int(t.data("KS", np.int32, (1,))[0])
        b = t.data("B", dt, bshp)
        off = kk * nb
        yk = np.linalg.solve(p[off:off + nb], b[off:off + nb])
        b[off:off + nb] = yk
        b[off + nb:] -= p[off + nb:] @ yk

    def b_bwd(t):
        p = t.data("P", dt, pshp)
        kk = int(t.data("KS", np.int32, (1,))[0])
        b = t.data("B", dt, bshp)
        off = kk * nb
        lkk = p[off:off + nb]
        contrib = p[off + nb:].T @ b[off + nb:]
        b[off:off + nb] = np.linalg.solve(lkk.T, b[off:off + nb] - contrib)

    fw.body(b_fwd)
    bw.body(b_bwd)
    return tp


def run_potrf(ctx, A, dev=None):
    tp = build_potrf(ctx, A, dev)
    tp.run()
    tp.wait()
    devs = as_device_list(dev)
    for d in devs:
        d.flush()


def potrf_flops(N: int) -> float:
    return N ** 3 / 3.0 + N ** 2 / 2.0 + N / 6.0
