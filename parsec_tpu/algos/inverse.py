"""Tiled triangular inversion and SPD inverse — the DPLASMA inversion
chain (dtrtri + dlauum = dpotri, composed after dpotrf) as PTG taskpools.

  build_trtri : W = inv(L), L lower triangular (dtrtri_L role)
  build_lauum : C = W^T W, W lower triangular (dlauum role: the upper-
                times-lower product that finishes the SPD inverse)
  run_potri   : A^{-1} for SPD A = potrf -> trtri -> lauum (dpotri role)

Design notes (TPU-first, diverging from the reference on purpose):
  - The reference factors IN PLACE (plasma-style anti-dependency
    ordering).  Here each stage writes a separate collection: the
    anti-deps disappear and every tile column of trtri is independent
    (wide waves for the batched device dispatch).  lauum's accumulator
    seed is the zero tile of its output collection (one RW chain per
    tile — safe); trtri's accumulators live in arena copies because its
    result tile has a second writer (MUL).
  - TRSM-free: the diagonal inverse is computed once per diagonal tile
    (DIAG), then every off-diagonal tile is pure batched GEMM on the
    MXU — same inversion-based practice as build_potrf's TRSM.
  - L tiles move by reader-task broadcasts placed AT their data (this
    runtime rejects cross-rank collection reads), so L, W, C may have
    completely different distributions.

Math (forward substitution by block column, W lower triangular):
  W[j][j] = inv(L[j][j])
  W[i][j] = -inv(L[i][i]) @ sum_{k=j..i-1} L[i][k] @ W[k][j]   (i > j)
LAUUM (lower result, i >= j):
  C[i][j] = sum_{k=max(i,j)..NT} W[k][i]^T @ W[k][j]

Reference: dplasma-style ztrtri_L/zlauum_L dataflows; tiled inversion
chain per parsec/data_dist/matrix + DPLASMA zpotri composition.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import parsec_tpu as pt
from ..data.collections import TwoDimBlockCyclic
from ..device.tpu import TpuDevice

from ._util import as_device_list


# ---------------------------------------------------------------- kernels
def k_tri_inv(t):
    import jax
    import jax.numpy as jnp
    return jax.scipy.linalg.solve_triangular(
        jnp.tril(t), jnp.eye(t.shape[0], dtype=t.dtype), lower=True)


def k_acc_ab(a, b, c):
    """c + a @ b."""
    import jax
    return c + jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   preferred_element_type=c.dtype)


def k_mul_ab(a, b):
    """a @ b (chain head: no accumulator)."""
    import jax
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=a.dtype)


def k_neg_mul(d, s):
    """-(d @ s)."""
    import jax
    return -jax.lax.dot_general(d, s, (((1,), (0,)), ((), ())),
                                preferred_element_type=s.dtype)


def k_acc_atb(a, b, c):
    """c + a^T @ b."""
    import jax
    return c + jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                                   preferred_element_type=c.dtype)


def trtri_flops(n: int) -> float:
    return n ** 3 / 3.0


def lauum_flops(n: int) -> float:
    return n ** 3 / 3.0


def build_trtri(ctx: pt.Context, L: TwoDimBlockCyclic,
                W: TwoDimBlockCyclic, dev: Optional[TpuDevice] = None,
                names=("L", "W")) -> pt.Taskpool:
    """W = inv(L) for lower-triangular L (square tiles, L.mt == L.nt).
    W is a same-geometry output collection; only its lower triangle is
    written (accumulators live in arena copies, not in W's tiles)."""
    assert L.mt == L.nt and L.mb == L.nb
    assert W.mt == L.mt and W.mb == L.mb
    nt, nb = L.mt, L.mb
    tp = pt.Taskpool(ctx, globals={"NT": nt - 1})
    i, j, k = pt.L("i"), pt.L("j"), pt.L("k")
    NT = pt.G("NT")
    ln, wn = names
    shp = (nb, nb)
    dt = L.dtype
    w_arena = f"trtri_w_{nb}_{np.dtype(dt).str}"
    ctx.register_arena(w_arena, nb * nb * np.dtype(dt).itemsize)

    # RdD(j): read L[j][j] AT L's distribution (cross-rank collection
    # reads are rejected; L and W may be distributed differently)
    rd = tp.task_class("RdD")
    rd.param("j", 0, NT)
    rd.affinity(ln, j, j)
    rd.flow("T", "READ",
            pt.In(pt.Mem(ln, j, j)),
            pt.Out(pt.Ref("DIAG", j, flow="T")))
    rd.body_noop()

    # DIAG(j): W[j][j] = inv(L[j][j]); feeds row-j MULs (as the inverse)
    # and column-j chains (as W[j][j])
    dg = tp.task_class("DIAG")
    dg.param("j", 0, NT)
    dg.affinity(wn, j, j)
    dg.priority((NT - j) * 100)
    dg.flow("T", "READ", pt.In(pt.Ref("RdD", j, flow="T")))
    dg.flow("W", "W",
            pt.Out(pt.Ref("GEMM0", pt.Range(j + 1, NT), j, flow="B"),
                   guard=(j < NT)),
            pt.Out(pt.Ref("MUL", j, pt.Range(0, j - 1), flow="D"),
                   guard=(j > 0)),
            pt.Out(pt.Mem(wn, j, j)),
            arena=w_arena)

    # RdL(i, k): broadcast L[i][k] (i > k) to every product that uses it
    rl = tp.task_class("RdL")
    rl.param("k", 0, NT)
    rl.param("i", k + 1, NT)
    rl.affinity(ln, i, k)
    rl.flow("A", "READ",
            pt.In(pt.Mem(ln, i, k)),
            pt.Out(pt.Ref("GEMM0", i, k, flow="A")),
            pt.Out(pt.Ref("GEMM", i, pt.Range(0, k - 1), k, flow="A"),
                   guard=(k > 0)))
    rl.body_noop()

    # GEMM0(i, j): S = L[i][j] @ W[j][j] — the chain head.  The
    # accumulator lives in arena copies, NEVER in the W(i,j) tile
    # itself: MUL also writes that tile, and two writers racing their
    # write-backs through the device mirrors corrupts it (the in-place
    # seed trick is only safe within a single RW chain, cf. potrf's C
    # flow / lauum's UPD)
    g0 = tp.task_class("GEMM0")
    g0.param("i", 1, NT)
    g0.param("j", 0, i - 1)
    g0.affinity(wn, i, j)
    g0.priority((NT - j) * 100 - i)
    g0.flow("A", "READ", pt.In(pt.Ref("RdL", j, i, flow="A")))
    g0.flow("B", "READ", pt.In(pt.Ref("DIAG", j, flow="W")))
    g0.flow("C", "W",
            pt.Out(pt.Ref("GEMM", i, j, j + 1, flow="C"),
                   guard=(i > j + 1)),
            pt.Out(pt.Ref("MUL", i, j, flow="S"), guard=(i == j + 1)),
            arena=w_arena)

    # GEMM(i, j, k): S[i][j] += L[i][k] @ W[k][j]   (j < k < i)
    ge = tp.task_class("GEMM")
    ge.param("i", 2, NT)
    ge.param("j", 0, i - 2)
    ge.param("k", j + 1, i - 1)
    ge.affinity(wn, i, j)
    ge.priority((NT - j) * 100 - i)
    ge.flow("A", "READ", pt.In(pt.Ref("RdL", k, i, flow="A")))
    ge.flow("B", "READ", pt.In(pt.Ref("MUL", k, j, flow="W")))
    ge.flow("C", "RW",
            pt.In(pt.Ref("GEMM0", i, j, flow="C"), guard=(k == j + 1)),
            pt.In(pt.Ref("GEMM", i, j, k - 1, flow="C")),
            pt.Out(pt.Ref("MUL", i, j, flow="S"), guard=(k == i - 1)),
            pt.Out(pt.Ref("GEMM", i, j, k + 1, flow="C"),
                   guard=(k < i - 1)))

    # MUL(i, j): W[i][j] = -inv(L[i][i]) @ S[i][j]   (i > j)
    mu = tp.task_class("MUL")
    mu.param("i", 1, NT)
    mu.param("j", 0, i - 1)
    mu.affinity(wn, i, j)
    mu.priority((NT - j) * 100 - i)
    mu.flow("D", "READ", pt.In(pt.Ref("DIAG", i, flow="W")))
    mu.flow("S", "READ",
            pt.In(pt.Ref("GEMM0", i, j, flow="C"), guard=(i == j + 1)),
            pt.In(pt.Ref("GEMM", i, j, i - 1, flow="C"),
                  guard=(i > j + 1)))
    mu.flow("W", "W",
            pt.Out(pt.Ref("GEMM", pt.Range(i + 1, NT), j, i, flow="B"),
                   guard=(i < NT)),
            pt.Out(pt.Mem(wn, i, j)),
            arena=w_arena)

    for d in as_device_list(dev):
        d.attach(dg, tp, kernel=k_tri_inv, reads=["T"], writes=["W"],
                 shapes={"T": shp, "W": shp}, dtype=dt)
        d.attach(g0, tp, kernel=k_mul_ab, reads=["A", "B"], writes=["C"],
                 shapes={"A": shp, "B": shp, "C": shp}, dtype=dt)
        d.attach(ge, tp, kernel=k_acc_ab, reads=["A", "B", "C"],
                 writes=["C"], shapes={"A": shp, "B": shp, "C": shp},
                 dtype=dt)
        d.attach(mu, tp, kernel=k_neg_mul, reads=["D", "S"], writes=["W"],
                 shapes={"D": shp, "S": shp, "W": shp}, dtype=dt)

    def b_diag(t):
        a = np.tril(t.data("T", dt, shp))
        w = t.data("W", dt, shp)
        w[...] = np.linalg.solve(a, np.eye(nb, dtype=dt))

    def b_gemm0(t):
        a = t.data("A", dt, shp)
        b = t.data("B", dt, shp)
        c = t.data("C", dt, shp)
        c[...] = a @ b

    def b_gemm(t):
        a = t.data("A", dt, shp)
        b = t.data("B", dt, shp)
        c = t.data("C", dt, shp)
        c += a @ b

    def b_mul(t):
        d = t.data("D", dt, shp)
        s = t.data("S", dt, shp)
        w = t.data("W", dt, shp)
        w[...] = -(d @ s)

    dg.body(b_diag)
    g0.body(b_gemm0)
    ge.body(b_gemm)
    mu.body(b_mul)
    return tp


def build_lauum(ctx: pt.Context, W: TwoDimBlockCyclic,
                C: TwoDimBlockCyclic, dev: Optional[TpuDevice] = None,
                names=("W", "C")) -> pt.Taskpool:
    """C = W^T @ W (lower triangle) for lower-triangular W — the dlauum
    role finishing the SPD inverse.  C must be ZERO-initialized; only
    its lower triangle is written."""
    assert W.mt == W.nt and W.mb == W.nb
    assert C.mt == W.mt and C.mb == W.mb
    nt, nb = W.mt, W.mb
    tp = pt.Taskpool(ctx, globals={"NT": nt - 1})
    i, j, k = pt.L("i"), pt.L("j"), pt.L("k")
    NT = pt.G("NT")
    wn, cn = names
    shp = (nb, nb)
    dt = W.dtype

    # RdW(k, i): broadcast W[k][i] (k >= i) to its products: the LEFT
    # operand of row i (any j <= i) and the RIGHT operand of column i
    # (any row i' with i <= i' <= k)
    rw = tp.task_class("RdW")
    rw.param("i", 0, NT)
    rw.param("k", i, NT)
    rw.affinity(wn, k, i)
    rw.flow("W", "READ",
            pt.In(pt.Mem(wn, k, i)),
            pt.Out(pt.Ref("UPD", i, pt.Range(0, i), k, flow="A")),
            pt.Out(pt.Ref("UPD", pt.Range(i, k), i, k, flow="B")))
    rw.body_noop()

    # UPD(i, j, k): C[i][j] += W[k][i]^T @ W[k][j]   (j <= i <= k)
    up = tp.task_class("UPD")
    up.param("i", 0, NT)
    up.param("j", 0, i)
    up.param("k", i, NT)
    up.affinity(cn, i, j)
    up.priority((NT - j) * 100 - i)
    up.flow("A", "READ", pt.In(pt.Ref("RdW", i, k, flow="W")))
    up.flow("B", "READ", pt.In(pt.Ref("RdW", j, k, flow="W")))
    up.flow("C", "RW",
            pt.In(pt.Mem(cn, i, j), guard=(k == i)),  # zero seed
            pt.In(pt.Ref("UPD", i, j, k - 1, flow="C")),
            pt.Out(pt.Ref("UPD", i, j, k + 1, flow="C"), guard=(k < NT)),
            pt.Out(pt.Mem(cn, i, j), guard=(k == NT)))

    for d in as_device_list(dev):
        d.attach(up, tp, kernel=k_acc_atb, reads=["A", "B", "C"],
                 writes=["C"], shapes={"A": shp, "B": shp, "C": shp},
                 dtype=dt)

    def b_upd(t):
        a = t.data("A", dt, shp)
        b = t.data("B", dt, shp)
        c = t.data("C", dt, shp)
        c += a.T @ b

    up.body(b_upd)
    return tp


def run_potri(ctx: pt.Context, A: TwoDimBlockCyclic,
              W: TwoDimBlockCyclic, C: TwoDimBlockCyclic,
              dev: Optional[TpuDevice] = None,
              names=("A", "W", "C")) -> None:
    """SPD inverse (dpotri role): A -> potrf in place -> W = inv(L) ->
    C = lower(A^{-1}) = W^T W.  W and C must be zero-initialized
    collections registered under names[1], names[2]."""
    from .potrf import build_potrf
    an, wn, cn = names
    tp = build_potrf(ctx, A, dev=dev, name=an)
    tp.run()
    tp.wait()
    tp = build_trtri(ctx, A, W, dev=dev, names=(an, wn))
    tp.run()
    tp.wait()
    tp = build_lauum(ctx, W, C, dev=dev, names=(wn, cn))
    tp.run()
    tp.wait()
    for d in as_device_list(dev):
        d.flush()
