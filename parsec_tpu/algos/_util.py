"""Shared helpers for the algorithm taskpools."""


def as_device_list(dev):
    """Normalize the dev argument (None | device | list/tuple) to a list."""
    if dev is None:
        return []
    if isinstance(dev, (list, tuple)):
        return list(dev)
    return [dev]
