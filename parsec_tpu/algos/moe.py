"""Mixture-of-Experts dispatch/combine THROUGH the task runtime.

The GSPMD library implementation lives in parallel/expert.py (GShard
one-hot dispatch/combine over an `ep` mesh axis); this is the same
computation expressed as a dataflow taskpool, so the two all-to-all legs
are ordinary runtime dependencies: dispatch tiles move shard-rank →
expert-rank and result tiles move back, riding the comm engine
(eager/GET rendezvous/device plane) like any other tile.  Reference
pattern: algorithms packaged as dataflow taskpools
(parsec/data_dist/matrix/redistribute/redistribute.jdf); validation
oracle: parallel/expert.py moe_ffn_reference.

DAG (S token shards of T tokens, E experts, capacity C):

  GATE(s):    X(s), WG          -> R(s)  (T, 2k) top-k ids + renorm probs
  DISP(s, e): R(s), X(s)        -> D     (C, d+2) = [x | token idx | prob]
  EXP(e, s):  D, WU(e), WD(e)   -> D     (result written over the x cols;
              affinity = expert e's rank: D moving here IS the dispatch
              all-to-all leg, the result moving out IS the combine leg)
  ACC(s, e):  chain over e scatter-adding prob-weighted rows into Y(s)

Tokens beyond an expert's capacity are dropped in token order — the
same rule as parallel/expert.py's cumsum positioning."""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import parsec_tpu as pt
from ..data.collections import TwoDimBlockCyclic


def _relu(x):
    return np.maximum(x, 0.0)


def _softmax(x):
    m = x.max(axis=-1, keepdims=True)
    p = np.exp(x - m)
    return p / p.sum(axis=-1, keepdims=True)


def _topk_gate(x, w_gate, k):
    """Shared routing rule for the runtime gate and the oracle: softmax
    over experts, stable top-k, renormalized top-k probabilities."""
    probs = _softmax(x @ w_gate)
    idx = np.argsort(-probs, axis=-1, kind="stable")[:, :k]
    vals = np.take_along_axis(probs, idx, axis=-1)
    return idx, vals / vals.sum(axis=-1, keepdims=True)


def _k_exp_relu(dtile, wu, wd):
    """Stock device kernel for EXP (relu FFN over the packed dispatch
    tile); module-level so the jit cache holds exactly one entry per
    (shape, dtype) across every build_moe call in the process."""
    import jax.numpy as jnp
    d = wu.shape[0]
    y = jnp.maximum(dtile[:, :d] @ wu, 0.0) @ wd
    return jnp.concatenate([y, dtile[:, d:]], axis=1)


def make_moe_collections(S, T, d, f, E, nodes=1, myrank=0, x=None,
                         w_gate=None, w_up=None, w_down=None):
    """Token shards X/Y (shard s on rank s%nodes), per-expert weights
    WU/WD (expert e on rank e%nodes), gate weights WG replicated via
    rank-0 ownership... gate runs on every shard rank, so WG is stored
    per shard-rank (broadcast-free: it is small and passed at init)."""
    def init_from(arr, rows):
        if arr is None:
            return None
        return lambda c, m, n: np.ascontiguousarray(
            arr[m * rows:(m + 1) * rows], dtype=np.float32)

    Xc = TwoDimBlockCyclic(S * T, d, T, d, P=nodes, Q=1, nodes=nodes,
                          myrank=myrank, dtype=np.float32,
                          init=init_from(x, T))
    Yc = TwoDimBlockCyclic(S * T, d, T, d, P=nodes, Q=1, nodes=nodes,
                          myrank=myrank, dtype=np.float32,
                          init=lambda c, m, n: np.zeros((T, d),
                                                        np.float32))
    # every shard rank gates locally: replicate WG as a per-rank tile
    WGc = TwoDimBlockCyclic(nodes * d, E, d, E, P=nodes, Q=1, nodes=nodes,
                            myrank=myrank, dtype=np.float32,
                            init=(lambda c, m, n: np.ascontiguousarray(
                                w_gate, dtype=np.float32))
                            if w_gate is not None else None)
    WUc = TwoDimBlockCyclic(E * d, f, d, f, P=nodes, Q=1, nodes=nodes,
                            myrank=myrank, dtype=np.float32,
                            init=init_from(
                                w_up.reshape(E * d, f) if w_up is not None
                                else None, d))
    WDc = TwoDimBlockCyclic(E * f, d, f, d, P=nodes, Q=1, nodes=nodes,
                            myrank=myrank, dtype=np.float32,
                            init=init_from(
                                w_down.reshape(E * f, d)
                                if w_down is not None else None, f))
    return Xc, Yc, WGc, WUc, WDc


def build_moe(ctx: pt.Context, Xc, Yc, WGc, WUc, WDc, E: int, k: int = 2,
              capacity: Optional[int] = None,
              activation: Callable = _relu,
              activation_jax: Optional[Callable] = None,
              dev=None, combine: str = "chain",
              coll_topo: Optional[str] = None) -> pt.Taskpool:
    """`activation` runs in the CPU bodies (numpy); when `dev` is given
    the EXP FFN offloads to the device and needs a jax-traceable
    `activation_jax` (defaulted for the stock relu).

    combine="chain" (default): the expert-combine leg is the original
    sequential ACC chain over e on the shard-owner rank — every expert's
    full dispatch tile crosses to the owner and the adds serialize.
    combine="coll" (ISSUE 6): each expert rank first folds ITS experts'
    contributions into one Y-shaped partial locally (CMB, zero wire
    traffic), then the per-rank partials ride a runtime-native ptc_coll
    reduction (topology per the transfer-economics selector) to the
    shard owner, which adds the result into Y — E tiles on the wire
    become min(E, nodes) partials, and the reduction starts as soon as
    the FIRST expert finishes instead of waiting for the chain head."""
    S, T, d = Xc.mt, Xc.mb, Xc.nb
    f = WUc.nb
    C = capacity if capacity is not None else T
    Xc.register(ctx, "X")
    Yc.register(ctx, "Y")
    WGc.register(ctx, "WG")
    WUc.register(ctx, "WU")
    WDc.register(ctx, "WD")
    ctx.register_arena("moe_r", T * 2 * k * 4)
    ctx.register_arena("moe_d", C * (d + 2) * 4)
    ctx.register_arena("moe_y", T * d * 4)
    nodes = max(1, Xc.nodes)
    tp = pt.Taskpool(ctx, globals={"S": S - 1, "E": E - 1, "P": nodes})
    s, e = pt.L("s"), pt.L("e")
    Sg, Eg, Pg = pt.G("S"), pt.G("E"), pt.G("P")

    gate = tp.task_class("GATE")
    gate.param("s", 0, Sg)
    gate.affinity("X", s, 0)
    gate.flow("X", "READ", pt.In(pt.Mem("X", s, 0)))
    # WG is replicated one tile per rank; the gate reads its own rank's
    gate.flow("WG", "READ", pt.In(pt.Mem("WG", s % Pg, 0)))
    gate.flow("R", "W",
              pt.Out(pt.Ref("DISP", s, pt.Range(0, Eg), flow="R")),
              arena="moe_r")

    disp = tp.task_class("DISP")
    disp.param("s", 0, Sg)
    disp.param("e", 0, Eg)
    disp.affinity("X", s, 0)
    disp.flow("R", "READ", pt.In(pt.Ref("GATE", s, flow="R")))
    disp.flow("X", "READ", pt.In(pt.Mem("X", s, 0)))
    disp.flow("D", "W", pt.Out(pt.Ref("EXP", e, s, flow="D")),
              arena="moe_d")

    exp = tp.task_class("EXP")
    exp.param("e", 0, Eg)
    exp.param("s", 0, Sg)
    exp.affinity("WU", e, 0)  # expert-owner computes: the all-to-all
    cmb_cls = "ACC" if combine == "chain" else "CMB"
    exp.flow("D", "RW", pt.In(pt.Ref("DISP", s, e, flow="D")),
             pt.Out(pt.Ref(cmb_cls, s, e, flow="C")), arena="moe_d")
    exp.flow("WU", "READ", pt.In(pt.Mem("WU", e, 0)))
    exp.flow("WD", "READ", pt.In(pt.Mem("WD", e, 0)))

    if combine == "chain":
        acc = tp.task_class("ACC")
        acc.param("s", 0, Sg)
        acc.param("e", 0, Eg)
        acc.affinity("X", s, 0)
        acc.flow("A", "RW",
                 pt.In(pt.Mem("Y", s, 0), guard=(e == 0)),
                 pt.In(pt.Ref("ACC", s, e - 1, flow="A")),
                 pt.Out(pt.Ref("ACC", s, e + 1, flow="A"), guard=(e < Eg)),
                 pt.Out(pt.Mem("Y", s, 0), guard=(e == Eg)), arena="moe_y")
        acc.flow("C", "READ", pt.In(pt.Ref("EXP", e, s, flow="D")),
                 arena="moe_d")
    elif combine == "coll":
        from ..comm.coll import RefReduce

        # CMB(s, e): on the EXPERT rank, fold expert e's dispatch tile
        # into a Y-shaped partial (the elementwise-reducible form)
        cmb = tp.task_class("CMB")
        cmb.param("s", 0, Sg)
        cmb.param("e", 0, Eg)
        cmb.affinity("WU", e, 0)
        cmb.flow("C", "READ", pt.In(pt.Ref("EXP", e, s, flow="D")),
                 arena="moe_d")
        rr = RefReduce(
            ctx, tp, nseg=S,
            contributors_of=lambda ss: [(WUc.rank_of(ee, 0), (ss, ee))
                                        for ee in range(E)],
            root_of=lambda ss: Xc.rank_of(ss, 0),
            prod_class="CMB", prod_flow="P", prod_nparams=2,
            prod_params_of=lambda cid: cid,
            arena_bytes=T * d * 4, dtype=np.float32, topo=coll_topo)
        cmb.flow("P", "W",
                 *rr.producer_out_deps(lambda l, g: (l[0], l[1])),
                 arena="moe_y")

        def b_cmb(view):
            c = view.data("C", np.float32, (C, d + 2))
            p = view.data("P", np.float32)[:T * d].reshape(T, d)
            p[...] = 0.0
            for row in range(C):
                pr = c[row, d + 1]
                if pr != 0.0:
                    p[int(c[row, d])] += pr * c[row, :d]

        cmb.body(b_cmb)
        # STORE(s): on the shard owner, add the reduced combine into Y
        store = tp.task_class("STORE")
        store.param("s", 0, Sg)
        store.affinity("X", s, 0)
        store.flow("C", "READ", rr.final_in_dep(0), arena="moe_y")
        store.flow("A", "RW", pt.In(pt.Mem("Y", s, 0)),
                   pt.Out(pt.Mem("Y", s, 0)), arena="moe_y")
        rr.wire_final_consumer(tp, "STORE", "C", lambda seg: (seg,))

        def b_store(view):
            a = view.data("A", np.float32, (T, d))
            a += view.data("C", np.float32)[:T * d].reshape(T, d)

        store.body(b_store)
    else:
        raise ValueError(f"build_moe: unknown combine={combine!r}")

    def b_gate(view):
        x = view.data("X", np.float32, (T, d))
        wg = view.data("WG", np.float32, (d, E))
        r = view.data("R", np.float32, (T, 2 * k))
        idx, vals = _topk_gate(x, wg, k)
        r[:, :k] = idx
        r[:, k:] = vals

    def b_disp(view):
        my_e = view.local("e")
        r = view.data("R", np.float32, (T, 2 * k))
        x = view.data("X", np.float32, (T, d))
        dtile = view.data("D", np.float32, (C, d + 2))
        dtile[...] = 0.0
        cnt = 0
        for t in range(T):
            for j in range(k):
                if int(r[t, j]) == my_e and cnt < C:
                    dtile[cnt, :d] = x[t]
                    dtile[cnt, d] = t
                    dtile[cnt, d + 1] = r[t, k + j]
                    cnt += 1
        # rows past cnt stay zero: prob 0 contributes nothing at combine

    def b_exp(view):
        dtile = view.data("D", np.float32, (C, d + 2))
        wu = view.data("WU", np.float32, (d, f))
        wd = view.data("WD", np.float32, (f, d))
        dtile[:, :d] = activation(dtile[:, :d] @ wu) @ wd

    def b_acc(view):
        a = view.data("A", np.float32, (T, d))
        c = view.data("C", np.float32, (C, d + 2))
        for row in range(C):
            p = c[row, d + 1]
            if p != 0.0:
                a[int(c[row, d])] += p * c[row, :d]

    if dev is not None:
        # device chore attached BEFORE the CPU bodies: chores are tried
        # in declaration order, so the device runs and CPU is the
        # fallback.  The FLOPs live in EXP — offload its fused FFN.
        if activation_jax is not None:
            def k_exp(dtile, wu, wd, _act=activation_jax):
                import jax.numpy as jnp
                dd = wu.shape[0]
                y = _act(dtile[:, :dd] @ wu) @ wd
                return jnp.concatenate([y, dtile[:, dd:]], axis=1)
        elif activation is _relu:
            k_exp = _k_exp_relu  # module-level: one jitted entry/process
        else:
            raise ValueError(
                "build_moe: a custom activation needs a jax-traceable "
                "activation_jax= for the device kernel (the numpy "
                "activation cannot trace)")
        dev.attach(exp, tp, kernel=k_exp, reads=["D", "WU", "WD"],
                   writes=["D"],
                   shapes={"D": (C, d + 2), "WU": (d, f), "WD": (f, d)},
                   dtype=np.float32)

    gate.body(b_gate)
    disp.body(b_disp)
    exp.body(b_exp)
    if combine == "chain":
        acc.body(b_acc)
    return tp


def moe_oracle(x, w_gate, w_up, w_down, k=2, activation=_relu):
    """Dense numpy oracle, same math as parallel/expert.py
    moe_ffn_reference (no capacity limit)."""
    T, d = x.shape
    idx, vals = _topk_gate(x, w_gate, k)
    y = np.zeros_like(x)
    for t in range(T):
        for j in range(k):
            e = idx[t, j]
            h = activation(x[t] @ w_up[e])
            y[t] += vals[t, j] * (h @ w_down[e])
    return y
