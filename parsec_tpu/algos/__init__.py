from .gemm import build_gemm, run_gemm
from .potrf import build_potrf, potrf_flops, run_potrf

__all__ = ["build_gemm", "run_gemm", "build_potrf", "run_potrf",
           "potrf_flops"]
