from .gemm import build_gemm, build_gemm_dist, run_gemm
from .inverse import (build_lauum, build_trtri, lauum_flops, run_potri,
                      trtri_flops)
from .lu import (build_getrf_nopiv, build_getrf_panels,
                 getrf_flops, getrf_nopiv_reference)
from .matrix_ops import (build_apply, build_map_operator, build_reduce_col,
                         build_reduce_row)
from .potrf import (build_potrf, build_potrf_panels,
                    build_potrs_panels, potrf_flops, run_potrf)
from .redistribute import redistribute
from .qr import build_geqrf, geqrf_flops
from .trsm import build_trsm
from .reshape import build_reshape_dtype, reshape_geometry

__all__ = ["build_gemm", "build_gemm_dist", "run_gemm",
           "build_getrf_nopiv", "build_getrf_panels", "getrf_flops",
           "getrf_nopiv_reference",
           "build_potrf", "build_potrf_panels", "build_potrs_panels",
           "run_potrf",
           "potrf_flops", "build_apply", "build_map_operator",
           "build_reduce_col", "build_reduce_row", "redistribute",
           "build_reshape_dtype", "reshape_geometry", "build_trsm",
           "build_geqrf", "geqrf_flops",
           "build_trtri", "build_lauum", "run_potri", "trtri_flops",
           "lauum_flops"]
