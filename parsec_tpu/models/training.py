"""Training harness for the model families: optax optimization, gradient
clipping, LR schedules, periodic checkpointing — the loop a reference
user would otherwise hand-roll around train_step.

Composes the framework's own pieces: models.transformer for the sharded
loss, checkpoint/ for resume (closing the reference's declared
checkpoint gap, SURVEY.md §5), parallel/ meshes for placement.
"""
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Iterable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .transformer import (TransformerConfig, init_params, loss_fn,
                          param_shardings)
from ..checkpoint import save_train_state, load_train_state


@dataclass
class TrainConfig:
    lr: float = 1e-3
    warmup_steps: int = 100
    total_steps: int = 1000
    clip_norm: float = 1.0
    weight_decay: float = 0.0
    ckpt_path: Optional[str] = None
    ckpt_every: int = 0          # 0 = never


def make_optimizer(tc: TrainConfig):
    import optax
    sched = optax.warmup_cosine_decay_schedule(
        0.0, tc.lr, tc.warmup_steps, max(tc.total_steps, tc.warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(tc.clip_norm),
        optax.adamw(sched, weight_decay=tc.weight_decay),
    )


def init_train_state(cfg: TransformerConfig, tc: TrainConfig, key):
    params = init_params(cfg, key)
    opt = make_optimizer(tc)
    return {"params": params, "opt": opt.init(params),
            "step": jnp.zeros((), jnp.int32)}


def make_train_step(cfg: TransformerConfig, tc: TrainConfig,
                    mesh: Optional[Mesh] = None):
    """jitted (state, batch) -> (state, loss) with sharding bound when a
    mesh is given (tp from param_shardings; dp/sp on the batch)."""
    opt = make_optimizer(tc)

    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(
            state["params"], batch, cfg, mesh)
        updates, new_opt = opt.update(grads, state["opt"], state["params"])
        import optax
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "opt": new_opt,
                "step": state["step"] + 1}, loss

    if mesh is None:
        return jax.jit(step)
    # tp shardings pinned on params; the optimizer state mirrors the param
    # tree so GSPMD propagates matching shardings (None = unconstrained)
    pshard = param_shardings(cfg, mesh)
    bshard = (NamedSharding(mesh, P(cfg.dp_axis, cfg.sp_axis)),) * 2
    return jax.jit(step, in_shardings=(
        {"params": pshard, "opt": None, "step": NamedSharding(mesh, P())},
        bshard))


def train(cfg: TransformerConfig, tc: TrainConfig, batches: Iterable,
          mesh: Optional[Mesh] = None, key=None, state=None,
          on_step: Optional[Callable[[int, float], None]] = None):
    """Run the loop over `batches`; returns the final state and losses.

    Resume: pass `state` (e.g. from resume_train_state).  Checkpoints are
    written every tc.ckpt_every steps to tc.ckpt_path."""
    if state is None:
        state = init_train_state(cfg, tc, key if key is not None
                                 else jax.random.PRNGKey(0))
    step_fn = make_train_step(cfg, tc, mesh)
    losses = []
    # track the step in Python: blocking on state["step"] (or float(loss))
    # every iteration would serialize jax's async dispatch and stall the
    # device between steps
    n = int(state["step"])
    for batch in batches:
        state, loss = step_fn(state, batch)
        losses.append(loss)
        n += 1
        if on_step:
            on_step(n, float(loss))
        if tc.ckpt_path and tc.ckpt_every and n % tc.ckpt_every == 0:
            save_train_state(tc.ckpt_path, state)
    return state, [float(l) for l in losses]


def resume_train_state(cfg: TransformerConfig, tc: TrainConfig, path: str,
                       key=None):
    """Rebuild the state STRUCTURE (abstract, no weights materialized —
    eval_shape) and load a checkpoint into it."""
    k = key if key is not None else jax.random.PRNGKey(0)
    like = jax.eval_shape(lambda: init_train_state(cfg, tc, k))
    return load_train_state(path, like)
