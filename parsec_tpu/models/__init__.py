"""Model families built on the framework's parallelism libraries.

The reference ships dense-LA algorithm families on top of its runtime
(DPLASMA-style potrf/gemm — our parsec_tpu.algos); this package adds the
ML model families the TPU framework is expected to serve, composed from
the same mesh axes: a transformer LM with dp/tp/sp(/ep) sharding and an
optional GPipe pipeline over the block stack.
"""
from .transformer import (TransformerConfig, init_params, forward, loss_fn,
                          train_step, make_sharded_train_step,
                          pipelined_forward)
from .training import (TrainConfig, init_train_state, make_train_step,
                       train, resume_train_state)

__all__ = [
    "TransformerConfig", "init_params", "forward", "loss_fn", "train_step",
    "make_sharded_train_step", "pipelined_forward",
    "TrainConfig", "init_train_state", "make_train_step", "train",
    "resume_train_state",
]
