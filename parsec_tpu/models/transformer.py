"""Decoder-only transformer LM, mesh-native.

Parallelism is composed the way SURVEY.md §2.10 prescribes for the new
framework: named strategies as libraries over a `jax.sharding.Mesh` —
  dp  batch sharding (owner-computes over the batch, the analog of the
      reference's rank_of affinity, parsec/include/parsec/data_distribution.h:40)
  tp  head/ffn sharding with XLA-inserted psum (the PxQ grid analog,
      parsec/data_dist/matrix/grid_2Dcyclic.c)
  sp  sequence sharding via ring attention (parallel/ring_attention.py)
  ep  expert sharding via all-to-all MoE (parallel/expert.py), riding the
      dp axis (tokens are already batch-local there)
  pp  GPipe over the block stack (parallel/pipeline.py, pipelined_forward)

Everything under jit; GSPMD propagates tp shardings from the parameter
PartitionSpecs, only the sp ring and the ep all-to-all are explicit
shard_map regions.  bf16 matmuls with f32 accumulation for the MXU.
"""
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.ring_attention import (ring_attention,
                                       blockwise_attention_reference)
from ..parallel.expert import moe_ffn
from ..parallel.pipeline import gpipe


@dataclass
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    head_dim: int = 16
    n_layers: int = 4
    d_ff: int = 512
    n_experts: int = 0          # 0 = dense FFN; >0 = MoE every layer
    moe_k: int = 2
    dtype: object = jnp.float32
    use_flash: bool = False     # Pallas flash kernel for local attention
    use_pallas_norm: bool = False  # Pallas fused RMSNorm (ops/rms_norm)
    remat: bool = False         # jax.checkpoint each block: recompute
    #                             activations in backward — HBM for FLOPs
    #                             (the standard long-context/deep-stack
    #                             memory lever on TPU)
    # mesh axis names (None = strategy unused)
    dp_axis: Optional[str] = "dp"
    tp_axis: Optional[str] = "tp"
    sp_axis: Optional[str] = "sp"
    ep_axis: Optional[str] = "ep"   # commonly == dp_axis


def _rms_norm(x, scale, use_pallas: bool = False):
    if use_pallas:
        from ..ops import rms_norm
        return rms_norm(x, scale)
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def _rotary(q, k):
    """Rotary position embedding over the full (global) sequence."""
    b, s, h, d = q.shape
    half = d // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = jnp.arange(s, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]

    def rot(x):
        x1, x2 = x[..., :half], x[..., half:]
        xr = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
        return xr.astype(x.dtype)

    return rot(q), rot(k)


def init_params(cfg: TransformerConfig, key):
    """Block params stacked on a leading n_layers dim (scan/pp friendly)."""
    ks = jax.random.split(key, 8)
    L, D, H, Dh, F = (cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.head_dim,
                      cfg.d_ff)
    dt = cfg.dtype
    p = {
        "embed": (jax.random.normal(ks[0], (cfg.vocab, D)) * 0.02).astype(dt),
        "ln_f": jnp.ones((D,), dt),
        "blocks": {
            "ln1": jnp.ones((L, D), dt),
            "ln2": jnp.ones((L, D), dt),
            "wqkv": (jax.random.normal(ks[1], (L, D, 3, H, Dh))
                     * D ** -0.5).astype(dt),
            "wo": (jax.random.normal(ks[2], (L, H, Dh, D))
                   * (H * Dh) ** -0.5).astype(dt),
        },
    }
    if cfg.n_experts:
        E = cfg.n_experts
        p["blocks"]["wg"] = (jax.random.normal(ks[3], (L, D, E))
                             * 0.02).astype(dt)
        p["blocks"]["wu"] = (jax.random.normal(ks[4], (L, E, D, F))
                             * D ** -0.5).astype(dt)
        p["blocks"]["wd"] = (jax.random.normal(ks[5], (L, E, F, D))
                             * F ** -0.5).astype(dt)
    else:
        p["blocks"]["w1"] = (jax.random.normal(ks[3], (L, D, F))
                             * D ** -0.5).astype(dt)
        p["blocks"]["w2"] = (jax.random.normal(ks[4], (L, F, D))
                             * F ** -0.5).astype(dt)
    return p


def param_shardings(cfg: TransformerConfig, mesh: Mesh):
    """NamedShardings mirroring init_params' tree: tp on heads/ffn, ep on
    experts, everything else replicated (GSPMD derives the rest)."""
    tp, ep = cfg.tp_axis, cfg.ep_axis

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    blocks = {
        "ln1": ns(None, None), "ln2": ns(None, None),
        "wqkv": ns(None, None, None, tp, None),
        "wo": ns(None, tp, None, None),
    }
    if cfg.n_experts:
        blocks["wg"] = ns(None, None, None)
        blocks["wu"] = ns(None, ep, None, None)
        blocks["wd"] = ns(None, ep, None, None)
    else:
        blocks["w1"] = ns(None, None, tp)
        blocks["w2"] = ns(None, tp, None)
    return {"embed": ns(None, None), "ln_f": ns(None), "blocks": blocks}


def _attention(q, k, v, cfg: TransformerConfig, mesh: Optional[Mesh]):
    if mesh is not None and cfg.sp_axis and mesh.shape.get(cfg.sp_axis, 1) > 1:
        spec = P(cfg.dp_axis, cfg.sp_axis, cfg.tp_axis, None)
        return ring_attention(q, k, v, mesh, cfg.sp_axis, causal=True,
                              spec=spec)
    if cfg.use_flash:
        from ..ops import flash_attention
        return flash_attention(q, k, v, causal=True)
    return blockwise_attention_reference(q, k, v, causal=True)


def _block(x, bp, cfg: TransformerConfig, mesh: Optional[Mesh]):
    h = _rms_norm(x, bp["ln1"], cfg.use_pallas_norm)
    qkv = jnp.einsum("bsd,dchn->bschn", h, bp["wqkv"],
                     preferred_element_type=jnp.float32).astype(x.dtype)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q, k = _rotary(q, k)
    o = _attention(q, k, v, cfg, mesh)
    x = x + jnp.einsum("bshn,hnd->bsd", o, bp["wo"],
                       preferred_element_type=jnp.float32).astype(x.dtype)
    h = _rms_norm(x, bp["ln2"], cfg.use_pallas_norm)
    if cfg.n_experts:
        if mesh is not None and cfg.ep_axis and \
                mesh.shape.get(cfg.ep_axis, 1) > 1:
            if cfg.ep_axis != cfg.dp_axis:
                raise ValueError(
                    "expert parallelism rides the dp axis (tokens are "
                    f"batch-local there); got ep_axis={cfg.ep_axis!r} != "
                    f"dp_axis={cfg.dp_axis!r}")
            xsp = P(cfg.ep_axis, cfg.sp_axis, None)
            f = moe_ffn(h, bp["wg"], bp["wu"], bp["wd"], mesh, cfg.ep_axis,
                        k=cfg.moe_k, x_spec=xsp)
        else:
            from ..parallel.expert import moe_ffn_reference
            f = moe_ffn_reference(h, bp["wg"], bp["wu"], bp["wd"],
                                  k=cfg.moe_k).astype(x.dtype)
    else:
        u = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h, bp["w1"],
                        preferred_element_type=jnp.float32).astype(x.dtype))
        f = jnp.einsum("bsf,fd->bsd", u, bp["w2"],
                       preferred_element_type=jnp.float32).astype(x.dtype)
    return x + f


def forward(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None):
    """tokens [B, S] int32 -> logits [B, S, vocab] (f32)."""
    x = params["embed"][tokens]
    if mesh is not None:
        x = lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(cfg.dp_axis, cfg.sp_axis, None)))

    def body(xc, bp):
        return _block(xc, bp, cfg, mesh), None

    if cfg.remat:
        # rematerialize each block in the backward pass: activation
        # memory drops from O(L) to O(1) blocks at ~1/3 extra FLOPs
        body = jax.checkpoint(body)

    # scan over the stacked layer dim; shard_map regions nest fine inside
    x, _ = lax.scan(body, x, params["blocks"])
    x = _rms_norm(x, params["ln_f"], cfg.use_pallas_norm)
    return jnp.einsum("bsd,vd->bsv", x.astype(jnp.float32),
                      params["embed"].astype(jnp.float32))


def pipelined_forward(params, tokens, cfg: TransformerConfig, mesh: Mesh,
                      pp_axis: str = "pp", n_microbatch: int = 4):
    """forward() with the block stack run as a GPipe pipeline over
    `pp_axis`.  n_layers must divide by the pp axis size; the embedding
    and final norm run replicated outside the pipeline."""
    n_stages = mesh.shape[pp_axis]
    L = cfg.n_layers
    assert L % n_stages == 0, (L, n_stages)
    per = L // n_stages
    x = params["embed"][tokens]
    b = x.shape[0]
    assert b % n_microbatch == 0, (b, n_microbatch)
    x_mb = x.reshape(n_microbatch, b // n_microbatch, *x.shape[1:])
    # restack blocks: [L, ...] -> [n_stages, per, ...]
    stages = jax.tree.map(
        lambda a: a.reshape(n_stages, per, *a.shape[1:]), params["blocks"])

    def stage_fn(bp_stage, xc):
        def body(c, bp):
            return _block(c, bp, cfg, mesh=None), None
        out, _ = lax.scan(body, xc, bp_stage)
        return out

    y = gpipe(stage_fn, stages, x_mb, mesh, pp_axis)
    y = y.reshape(b, *y.shape[2:])
    y = _rms_norm(y, params["ln_f"], cfg.use_pallas_norm)
    return jnp.einsum("bsd,vd->bsv", y.astype(jnp.float32),
                      params["embed"].astype(jnp.float32))


def loss_fn(params, batch, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None):
    """Next-token cross-entropy; batch = (tokens, targets) [B, S]."""
    tokens, targets = batch
    logits = forward(params, tokens, cfg, mesh)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], -1)[..., 0]
    return jnp.mean(logz - gold)


def train_step(params, batch, cfg: TransformerConfig,
               mesh: Optional[Mesh] = None, lr: float = 1e-2):
    """One SGD step (the driver's dryrun vehicle; real training loops wrap
    this in optax, see tests/models)."""
    loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg, mesh)
    new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                              params, grads)
    return new_params, loss


def make_sharded_train_step(cfg: TransformerConfig, mesh: Mesh,
                            lr: float = 1e-2):
    """jit train_step with parameter/batch shardings bound (GSPMD does the
    tp collectives; sp/ep run their explicit shard_map regions)."""
    pshard = param_shardings(cfg, mesh)
    bshard = (NamedSharding(mesh, P(cfg.dp_axis, cfg.sp_axis)),) * 2

    @partial(jax.jit, in_shardings=(pshard, bshard),
             out_shardings=(pshard, NamedSharding(mesh, P())))
    def step(params, batch):
        return train_step(params, batch, cfg, mesh, lr)

    return step
