"""Quiescent-point collection checkpoints + jax train-state save/restore.

Format: one .npz per (path, collection name) holding every *local* tile
keyed "m_n", plus a JSON-ish meta array (geometry, rank) used to validate
the resume target — mismatched geometry is an error, not silent
corruption.  Multi-rank runs write per-rank files (path.rank<k>.npz), the
same per-rank-file scheme as the reference's dbp profiles
(parsec/parsec_binary_profile.h) and standard for pod checkpoints.
"""
import json
from typing import Dict, List, Tuple

import numpy as np


def _coll_meta(coll) -> dict:
    return {
        "M": coll.M, "N": coll.N, "mb": coll.mb, "nb": coll.nb,
        "P": getattr(coll, "P", 1), "Q": getattr(coll, "Q", 1),
        "nodes": coll.nodes, "myrank": coll.myrank,
        "dtype": np.dtype(coll.dtype).str,
    }


def _path_for(path: str, name: str, rank: int, nodes: int) -> str:
    base = f"{path}.{name}"
    return f"{base}.rank{rank}.npz" if nodes > 1 else f"{base}.npz"


def save_collections(path: str, named_colls: Dict[str, object]):
    """Checkpoint local tiles of each collection.  Call at a quiescent
    point (after tp.wait() / ctx.wait()) — tile buffers are then the
    complete algorithm state."""
    for name, coll in named_colls.items():
        arrays = {}
        # Enumerate through the public API (the same walk Collection.fill
        # uses) so band/sym collections — whose tiles live in nested
        # descriptors, not a flat _tiles dict — checkpoint correctly, and
        # lazily-allocated tiles materialize instead of being dropped.
        for m in range(coll.mt):
            for n in range(coll.nt):
                if not coll.stored(m, n):
                    continue
                if coll.rank_of(m, n) != coll.myrank:
                    continue
                arrays[f"{m}_{n}"] = coll.tile(m, n)
        arrays["__meta__"] = np.frombuffer(
            json.dumps(_coll_meta(coll)).encode(), dtype=np.uint8)
        np.savez(_path_for(path, name, coll.myrank, coll.nodes), **arrays)


def load_collections(path: str, named_colls: Dict[str, object]):
    """Restore local tiles into freshly-constructed collections with the
    same geometry.  Raises ValueError on geometry mismatch."""
    for name, coll in named_colls.items():
        f = np.load(_path_for(path, name, coll.myrank, coll.nodes))
        meta = json.loads(bytes(f["__meta__"]).decode())
        want = _coll_meta(coll)
        for k in ("M", "N", "mb", "nb", "P", "Q", "nodes", "dtype"):
            if meta[k] != want[k]:
                raise ValueError(
                    f"checkpoint {name}: geometry mismatch on {k}: "
                    f"saved {meta[k]!r} vs target {want[k]!r}")
        for key in f.files:
            if key == "__meta__":
                continue
            m, n = (int(x) for x in key.split("_"))
            coll.tile(m, n)[...] = f[key]


# ------------------------------------------------------------------ model


def _flatten_with_paths(tree) -> List[Tuple[str, object]]:
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(kp), leaf) for kp, leaf in flat]


def save_train_state(path: str, state):
    """Save a jax pytree (params / optimizer state / step) to one .npz.
    Device/sharded arrays are gathered to host first."""
    import jax
    arrays = {}
    for keystr, leaf in _flatten_with_paths(state):
        arrays[keystr] = np.asarray(jax.device_get(leaf))
    np.savez(path if path.endswith(".npz") else path + ".npz", **arrays)


def load_train_state(path: str, like, shardings=None):
    """Restore into the structure of `like` (a pytree with the target
    treedef).  `shardings`: optional matching pytree of NamedShardings to
    device_put each leaf back onto the mesh."""
    import jax
    f = np.load(path if path.endswith(".npz") else path + ".npz")
    leaves_like, treedef = jax.tree_util.tree_flatten(like)
    del leaves_like
    paths = [jax.tree_util.keystr(kp) for kp, _ in
             jax.tree_util.tree_flatten_with_path(like)[0]]
    leaves = []
    for p in paths:
        if p not in f.files:
            raise ValueError(f"checkpoint missing leaf {p}")
        leaves.append(f[p])
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    if shardings is not None:
        state = jax.tree.map(jax.device_put, state, shardings)
    return state
