"""Checkpoint / resume.

The reference has NO checkpointing (SURVEY.md §5 "Checkpoint / resume:
Not present") — flagged there as a gap that is mandatory on TPU pods
(preemptions, ICI link flaps).  This package closes it at the two natural
boundaries of the framework:

  collections  quiescent-point checkpoint of distributed data collections
               (tile payloads + versions) — the task-DAG state lives in
               the data between taskpool runs, so save-after-wait /
               load-before-rebuild gives exact resume of any algorithm
               expressed as a sequence of taskpools.
  train state  jax pytree save/restore (params + opt state + step) with
               sharding re-application on load — the model-side analog,
               safe under jit because it round-trips through host numpy.
"""
from .checkpoint import (save_collections, load_collections,
                         save_train_state, load_train_state)

__all__ = ["save_collections", "load_collections",
           "save_train_state", "load_train_state"]
