"""PTG front-end: the JDF language, compiled to table-driven task classes.

Reference: the parsec_ptgpp compiler (parsec/interfaces/ptg/ptg-compiler:
parsec.l flex lexer, parsec.y bison grammar, jdf2c.c generator — SURVEY.md
§2.7/§3.6).  This implementation keeps the JDF *surface syntax* — globals,
parameter ranges, derived locals, `: coll(...)` affinity, guarded/ternary
dataflow deps with ranges, CTL flows, NEW/NULL, multiple BODY incarnations
— but compiles to the native expression-VM spec via the TaskClass builder
instead of generating C, and bodies are Python (CPU chore) or jax-traceable
code (`BODY [type=TPU]`) instead of inline C.

Supported grammar (subset, expanding):

    extern "C" %{ <python prologue> %}      # exec'd into the program scope
    %option name = value                     # taskpool options (parsec.y
                                             #   jdf_set_default_properties)
    NAME [type="int"] [hidden=on] [default=<expr>]
    Task(k, m) [ make_key_fn = fn startup_fn = fn ... ]   # class properties
    k = lo .. hi [.. step]                   # range parameter
    loc = <expr>                             # derived local
    : coll(<expr>, ...)                      # affinity
    priority = <expr>                        # optional
    RW|READ|WRITE|CTL F <- <dep>  -> <dep> ...
    BODY [type=TPU weight=<e>] { <python/jax code> } END / BODY END

    <dep> := [(guard) | %{..%} ?] <target> [: <target>] [ [props] ]
    <target> := F Task(e, lo..hi, ...) | coll(e, ...) | NEW | NULL

Expressions: C-style with ? :, && || !, comparisons, + - * / %, and
`%{ <python expr> %}` escapes evaluated over locals, int globals, and the
program scope (prologue definitions + objects bound via builder.scope).

Dynamic-guard semantics (matches the reference): a data-input dep whose
guard contains a `%{ %}` escape cannot be pruned statically — the escape
may read state task bodies write later (the choice pattern) — so the
instance is counted as WAITING for that delivery rather than evaluated
now.  If no producer ever chooses it, retire it via
`taskpool.addto_nb_tasks(-1)` (what choice-style DAGs do); a pure
always-false escape guard on a data input with a memory fallback would
therefore wait forever — write such guards as plain expressions instead.

User-defined functions (reference: tests/dsl/ptg/user-defined-functions):
  %option nb_local_tasks_fn = fn   — fn(taskpool) -> int overrides the
      enumerated local-task count used for termination detection.
  startup_fn = fn (class property) — fn(taskpool, class_name) hook invoked
      at run() before tasks execute.
  make_key_fn / hash_struct — parsed and validated against the program
      scope, then intentionally unused: the native dependency engine keys
      on the exact parameter vector (collision-safe full-key record,
      native/core.cpp DepEntry), so user key packing has nothing to fix.
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core import expr as E
from ..core.taskclass import In, Mem, Out, Ref, TaskClass
from ..core.taskpool import Taskpool

# ------------------------------------------------------------------ lexer

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|//[^\n]*|/\*.*?\*/)
  | (?P<escape>%\{.*?%\})
  | (?P<num>\d+)
  | (?P<id>[A-Za-z_]\w*)
  | (?P<str>"[^"]*")
  | (?P<arrow_in><-)
  | (?P<arrow_out>->)
  | (?P<dotdot>\.\.)
  | (?P<op>==|!=|<=|>=|&&|\|\||[-+*/%()\[\],:?=<>!;{}])
""", re.VERBOSE | re.DOTALL)


class Tok:
    def __init__(self, kind: str, val: str, pos: int):
        self.kind = kind
        self.val = val
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.val!r}"


def _lex(src: str) -> List[Tok]:
    toks = []
    i = 0
    while i < len(src):
        m = _TOKEN_RE.match(src, i)
        if not m:
            raise SyntaxError(f"jdf: cannot tokenize at {src[i:i+40]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        toks.append(Tok(kind, m.group(), m.start()))
    toks.append(Tok("eof", "", len(src)))
    return toks


# ------------------------------------------------------------------ AST

class JdfGlobal:
    def __init__(self, name, typ="int", hidden=False, default=None):
        self.name, self.typ, self.hidden, self.default = \
            name, typ, hidden, default


class JdfDepTarget:
    def __init__(self, kind, name=None, flow=None, args=None, iters=None):
        self.kind = kind  # "task" | "mem" | "new" | "null"
        self.name = name  # task or collection name
        self.flow = flow  # flow name on the peer (task kind)
        self.args = args or []
        self.iters = iters or []  # target-level bracketed iterators


class JdfDep:
    def __init__(self, direction, guard, target, alt=None, props=None,
                 iters=None, pos=-1):
        self.direction = direction  # 0 in, 1 out
        self.guard = guard          # Expr | None
        self.target = target        # JdfDepTarget
        self.alt = alt              # else-branch target
        self.props = props or {}    # [type=.. layout=.. count=.. displ=..]
        self.iters = iters or []    # dep-level bracketed iterators
        self.pos = pos              # source offset (for verifier locs)


class JdfCompr:
    """Comprehension local: name = [ it = lo .. hi .. st ] value."""

    def __init__(self, iter_name, lo, hi, st, value):
        self.iter_name = iter_name
        self.lo, self.hi, self.st = lo, hi, st
        self.value = value


class JdfFlow:
    def __init__(self, access, name):
        self.access = access
        self.name = name
        self.deps: List[JdfDep] = []


class JdfBody:
    def __init__(self, props, code):
        self.props = props  # dict
        self.code = code


class JdfTask:
    def __init__(self, name, params, props=None, pos=-1):
        self.name = name
        self.params = params  # [str]
        self.pos = pos        # source offset (for verifier locs)
        self.props = props or {}  # class properties [make_key_fn = ...]
        self.locals: List[Tuple[str, object]] = []  # (name, Range|Expr)
        self.affinity: Optional[Tuple[str, list]] = None
        self.priority = None
        self.flows: List[JdfFlow] = []
        self.bodies: List[JdfBody] = []


class JdfProgram:
    def __init__(self):
        self.prologue = ""
        self.options: Dict[str, str] = {}  # %option lines
        self.globals: List[JdfGlobal] = []
        self.tasks: List[JdfTask] = []
        self.src = ""  # body-stripped source (token pos -> line)


# ------------------------------------------------------------------ parser

_ACCESS = {"RW": "RW", "READ": "READ", "WRITE": "WRITE", "CTL": "CTL"}

# %option names accepted at program level (reference: parsec.y
# jdf_set_default_properties; no_taskpool_instance et al.)
_KNOWN_OPTIONS = {"no_taskpool_instance", "nb_local_tasks_fn"}


# braces are optional: `BODY\nEND` is an empty body (reference:
# tests/dsl/ptg/complex_deps.jdf FCT1..FCT5)
_BODY_RE = re.compile(
    r"BODY(?P<props>\s*\[[^\]]*\])?\s*(?:\{(?P<code>.*?)\}\s*)?END",
    re.DOTALL)


def _extract_bodies(src: str):
    """Replace BODY [...] { python } END blocks with `BODY <idx>` markers so
    the JDF lexer never sees Python code."""
    bodies = []

    def repl(m):
        bodies.append((m.group("props") or "", m.group("code") or "pass"))
        # newline-preserving so token positions keep mapping to the
        # original source lines (findings/locations stay accurate)
        return f"BODY {len(bodies) - 1}" + "\n" * max(
            1, m.group(0).count("\n"))

    return _BODY_RE.sub(repl, src), bodies


class _Parser:
    def __init__(self, toks: List[Tok], src: str, bodies):
        self.toks = toks
        self.i = 0
        self.src = src
        self.bodies = bodies

    def peek(self, k=0) -> Tok:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, val) -> Tok:
        t = self.next()
        if t.val != val:
            raise SyntaxError(f"jdf: expected {val!r}, got {t.val!r} "
                              f"near {self.src[t.pos:t.pos+40]!r}")
        return t

    def accept(self, val) -> bool:
        if self.peek().val == val:
            self.i += 1
            return True
        return False

    # ------------------------------------------------------- program level
    def parse(self) -> JdfProgram:
        prog = JdfProgram()
        while self.peek().kind != "eof":
            t = self.peek()
            if t.kind == "id" and t.val == "extern":
                self.next()
                self.expect('"C"') if self.peek().val == '"C"' else None
                esc = self.next()
                if esc.kind != "escape":
                    raise SyntaxError("jdf: expected %{ ... %} after extern")
                prog.prologue += esc.val[2:-2] + "\n"
            elif t.kind == "escape":
                self.next()
                prog.prologue += t.val[2:-2] + "\n"
            elif t.val == "%" and self.peek(1).val == "option":
                # %option name = value (value: one id/num/string token)
                self.next()
                self.next()
                name = self.next().val
                if name not in _KNOWN_OPTIONS:
                    # a typo'd option (e.g. nb_local_task_fn) silently
                    # ignored can hang a DAG relying on it — fail loudly
                    raise SyntaxError(
                        f"jdf: unknown %option {name!r}; known: "
                        f"{sorted(_KNOWN_OPTIONS)}")
                self.expect("=")
                prog.options[name] = self.next().val.strip('"')
            elif t.kind == "id" and self.peek(1).val == "[":
                prog.globals.append(self._parse_global())
            elif t.kind == "id" and self.peek(1).val == "(":
                prog.tasks.append(self._parse_task())
            elif t.kind == "id":
                # global without properties: NAME
                prog.globals.append(JdfGlobal(self.next().val))
            else:
                raise SyntaxError(f"jdf: unexpected {t.val!r}")
        return prog

    def _parse_props(self) -> Dict[str, str]:
        props: Dict[str, str] = {}
        self.expect("[")
        while not self.accept("]"):
            key = self.next().val
            self.expect("=")
            vals = []
            while self.peek().val not in ("]",) and not (
                    self.peek().kind == "id" and self.peek(1).val == "="):
                vals.append(self.next().val)
            props[key] = " ".join(vals)
        return props

    def _parse_global(self) -> JdfGlobal:
        name = self.next().val
        props = self._parse_props()
        typ = props.get("type", '"int"').strip('"')
        hidden = props.get("hidden", "off") in ("on", "ON", "true")
        default = props.get("default")
        return JdfGlobal(name, typ, hidden, default)

    # ------------------------------------------------------- task level
    def _parse_task(self) -> JdfTask:
        name_tok = self.next()
        name = name_tok.val
        self.expect("(")
        params = []
        while not self.accept(")"):
            params.append(self.next().val)
            self.accept(",")
        props = self._parse_props() if self.peek().val == "[" else {}
        task = JdfTask(name, params, props, pos=name_tok.pos)
        # locals until ':' (affinity) — every line `id = ...`
        while True:
            t = self.peek()
            if t.val == ":":
                break
            if t.kind == "id" and self.peek(1).val == "=":
                nm = self.next().val
                self.expect("=")
                if self._at_iter_bracket():
                    # comprehension local (local indices):
                    #   nm = [ it = lo .. hi [.. st] ] value
                    its = self._parse_iters()
                    if len(its) != 1:
                        raise SyntaxError(
                            "jdf: comprehension locals take exactly one "
                            "iterator")
                    it_name, lo, hi, st = its[0]
                    val = self._parse_expr()
                    task.locals.append(
                        (nm, JdfCompr(it_name, lo, hi, st, val)))
                    continue
                first = self._parse_expr()
                if self.accept(".."):
                    hi = self._parse_expr()
                    step = self._parse_expr() if self.accept("..") else 1
                    if nm == "priority":
                        raise SyntaxError("jdf: priority cannot be a range")
                    task.locals.append((nm, E.Range(first, hi, step)))
                elif nm == "priority":
                    task.priority = first
                else:
                    task.locals.append((nm, first))
            else:
                break
        if self.accept(":"):
            coll = self.next().val
            self.expect("(")
            args = []
            while not self.accept(")"):
                args.append(self._parse_expr())
                self.accept(",")
            task.affinity = (coll, args)
        # priority may also follow affinity
        while self.peek().kind == "id" and self.peek().val == "priority" \
                and self.peek(1).val == "=":
            self.next()
            self.expect("=")
            task.priority = self._parse_expr()
        # flows
        while self.peek().kind == "id" and self.peek().val in _ACCESS:
            task.flows.append(self._parse_flow())
        # reference priority clause between dataflow and BODY: `; expr`
        # (tests/dsl/ptg/startup.jdf `; prio`)
        if self.accept(";"):
            task.priority = self._parse_expr()
        # bodies
        while self.peek().kind == "id" and self.peek().val == "BODY":
            task.bodies.append(self._parse_body())
        if not task.bodies:
            raise SyntaxError(f"jdf: task {name} has no BODY")
        return task

    def _parse_flow(self) -> JdfFlow:
        access = self.next().val
        name = self.next().val
        fl = JdfFlow(_ACCESS[access], name)
        while self.peek().val in ("<-", "->"):
            direction = 0 if self.next().val == "<-" else 1
            fl.deps.append(self._parse_dep(direction))
        return fl

    def _at_iter_bracket(self) -> bool:
        """A '[' opening an iterator list: `[ id = ... ]` (dep properties
        also look like `[ id = ... ]` but only appear AFTER a target)."""
        return (self.peek().val == "[" and self.peek(1).kind == "id"
                and self.peek(2).val == "=")

    def _parse_iters(self):
        """[ i = lo .. hi [.. st] (, j = ...)* ]"""
        its = []
        self.expect("[")
        while True:
            name = self.next().val
            self.expect("=")
            lo = self._parse_expr()
            self.expect("..")
            hi = self._parse_expr()
            st = self._parse_expr() if self.accept("..") else 1
            its.append((name, lo, hi, st))
            if not self.accept(","):
                break
        self.expect("]")
        return its

    def _parse_dep(self, direction: int) -> JdfDep:
        guard = None
        alt = None
        dep_pos = self.peek().pos
        # dep-level bracketed iterators (local indices):
        #   [ i = 0 .. odd ] guard ? target : target
        iters = self._parse_iters() if self._at_iter_bracket() else []
        if self.peek().val == "(" or self.peek().kind == "escape":
            # or-level, not ternary: the dep's own `?` must stay unconsumed.
            # A %{ ... %} escape can itself be the whole guard (reference:
            # tests/dsl/ptg/choice/choice.jdf).
            guard = self._or()
            self.expect("?")
            target = self._parse_target()
            if self.accept(":"):
                alt = self._parse_target()
        else:
            # unparenthesized guards (`odd < 4 ? A t(..) : ...`,
            # tests/dsl/ptg/local-indices) are indistinguishable from a
            # target without lookahead: try guard-form, backtrack to
            # target-form (a bare flow name never survives expect('?')).
            # When BOTH forms fail, report whichever parse got further —
            # the shorter one's error points at the wrong token.
            save = self.i
            try:
                guard = self._or()
                self.expect("?")
                target = self._parse_target()
                if self.accept(":"):
                    alt = self._parse_target()
            except SyntaxError as guard_err:
                guard_pos = self.i
                self.i = save
                guard = None
                try:
                    target = self._parse_target()
                except SyntaxError:
                    if guard_pos > self.i:
                        self.i = guard_pos
                        raise guard_err from None
                    raise
        # trailing dep properties: [type = X displ_remote = e ...]
        props = self._parse_props() if self.peek().val == "[" else {}
        return JdfDep(direction, guard, target, alt, props, iters,
                      pos=dep_pos)

    def _parse_target(self) -> JdfDepTarget:
        # target-level iterators: `? [ j = 0 .. e .. 2 ] A tA(...)`
        iters = self._parse_iters() if self._at_iter_bracket() else []
        t = self.next()
        if t.kind != "id":
            raise SyntaxError(f"jdf: bad dep target {t.val!r}")
        if t.val == "NEW":
            return JdfDepTarget("new")
        if t.val == "NULL":
            return JdfDepTarget("null")
        if self.peek().val == "(":
            # collection reference: coll(args)
            self.expect("(")
            args = []
            while not self.accept(")"):
                args.append(self._parse_range_or_expr())
                self.accept(",")
            return JdfDepTarget("mem", name=t.val, args=args, iters=iters)
        # flow Task(args)
        flow = t.val
        tname = self.next().val
        self.expect("(")
        args = []
        while not self.accept(")"):
            args.append(self._parse_range_or_expr())
            self.accept(",")
        return JdfDepTarget("task", name=tname, flow=flow, args=args,
                            iters=iters)

    def _parse_body(self) -> JdfBody:
        """Bodies are pre-extracted (their code is Python, not lexable as
        JDF): the preprocessor replaced each with `BODY <idx>`."""
        self.next()  # BODY
        idx = int(self.next().val)
        props_str, code = self.bodies[idx]
        props = dict(re.findall(r'(\w+)\s*=\s*("[^"]*"|[^\s\]]+)', props_str))
        props = {k: v.strip('"') for k, v in props.items()}
        return JdfBody(props, code)

    # ------------------------------------------------------- expressions
    def _parse_range_or_expr(self):
        e = self._parse_expr()
        if self.accept(".."):
            hi = self._parse_expr()
            step = self._parse_expr() if self.accept("..") else 1
            return E.Range(e, hi, step)
        return e

    def _parse_expr(self):
        return self._ternary()

    def _ternary(self):
        c = self._or()
        if self.accept("?"):
            a = self._ternary()
            self.expect(":")
            b = self._ternary()
            return E.select(c, a, b)
        return c

    def _or(self):
        a = self._and()
        while self.peek().val == "||":
            self.next()
            a = E.BinOp(E.N.OP_OR, a, self._and())
        return a

    def _and(self):
        a = self._cmp()
        while self.peek().val == "&&":
            self.next()
            a = E.BinOp(E.N.OP_AND, a, self._cmp())
        return a

    _CMPOPS = {"==": E.N.OP_EQ, "!=": E.N.OP_NE, "<": E.N.OP_LT,
               "<=": E.N.OP_LE, ">": E.N.OP_GT, ">=": E.N.OP_GE}

    def _cmp(self):
        a = self._add()
        while self.peek().val in self._CMPOPS:
            op = self.next().val
            a = E.BinOp(self._CMPOPS[op], a, self._add())
        return a

    def _add(self):
        a = self._mul()
        while self.peek().val in ("+", "-"):
            op = self.next().val
            b = self._mul()
            a = E.BinOp(E.N.OP_ADD if op == "+" else E.N.OP_SUB, a, b)
        return a

    def _mul(self):
        a = self._unary()
        while self.peek().val in ("*", "/", "%"):
            op = self.next().val
            b = self._unary()
            a = E.BinOp({"*": E.N.OP_MUL, "/": E.N.OP_DIV,
                         "%": E.N.OP_MOD}[op], a, b)
        return a

    def _unary(self):
        if self.accept("-"):
            return E.UnOp(E.N.OP_NEG, self._unary())
        if self.accept("!"):
            return E.UnOp(E.N.OP_NOT, self._unary())
        return self._primary()

    def _primary(self):
        t = self.next()
        if t.kind == "num":
            return E.Const(int(t.val))
        if t.kind == "escape":
            code = t.val[2:-2].strip()
            if code.startswith("return"):
                code = code[len("return"):].strip().rstrip(";")
            return _PyEscape(code)
        if t.kind == "id":
            return _Name(t.val)
        if t.val == "(":
            e = self._parse_expr()
            self.expect(")")
            return e
        raise SyntaxError(f"jdf: bad expression token {t.val!r}")


class _Name(E.Expr):
    """Deferred local-or-global reference, resolved at build time."""

    def __init__(self, name):
        self.name = name

    def _emit(self, out, ctx):
        if self.name in ctx.locals:
            out += [E.N.OP_LOCAL, ctx.locals[self.name]]
        elif self.name in ctx.globals:
            out += [E.N.OP_GLOBAL, ctx.globals[self.name]]
        else:
            raise KeyError(f"jdf: unknown symbol {self.name!r}")


class _PyEscape(E.Expr):
    """%{ python expr %}: evaluated via a registered callback; the
    expression sees task locals by name, int globals by name, and the
    program scope (prologue definitions + objects the caller bound via
    builder.scope — reference: JDF inline C sees taskpool globals of any
    type, e.g. the `decision` array of tests/dsl/ptg/choice)."""

    def __init__(self, code):
        self.code = code
        self._names: List[str] = []

    def _emit(self, out, ctx):
        # one slot may carry several names (a comprehension parameter and
        # its iterator alias both bind to the parameter's slot)
        names: Dict[int, List[str]] = {}
        for name, idx in ctx.locals.items():
            names.setdefault(idx, []).append(name)
        code = compile(self.code, "<jdf-escape>", "eval")
        scope = ctx.scope  # live dict: later caller bindings stay visible

        def fn(locs, globs):
            # live scope as eval-globals: no per-call copy of the program
            # scope (it can be large), and later caller bindings stay
            # visible; int globals and task locals shadow it via env
            env = dict(globs)
            for i, v in enumerate(locs):
                for n in names.get(i, ()):
                    env[n] = v
            return int(eval(code, scope if scope is not None else {}, env))

        cb_id = ctx.register_call(fn)
        out += [E.N.OP_CALL, cb_id]


# ------------------------------------------------------------------ build

def parse_jdf(src: str) -> JdfProgram:
    stripped, bodies = _extract_bodies(src)
    prog = _Parser(_lex(stripped), stripped, bodies).parse()
    prog.src = stripped
    return prog


def _target_to_builder(t: JdfDepTarget, flow_name: str):
    if t.kind == "new":
        return None  # pure allocation (arena on the flow)
    if t.kind == "null":
        return None
    if t.kind == "mem":
        return Mem(t.name, *t.args)
    return Ref(t.name, *t.args, flow=t.flow)


class JdfTaskpoolBuilder:
    """Instantiate a parsed JDF program as a ready-to-run Taskpool."""

    def __init__(self, prog: JdfProgram, ctx, globals: Dict[str, int],
                 dtype=np.uint8, shapes: Optional[Dict] = None,
                 arenas: Optional[Dict[str, str]] = None, dev=None,
                 late_bound: Optional[List[str]] = None,
                 filename: Optional[str] = None):
        self.prog = prog
        self.filename = filename or "<jdf>"
        self.ctx = ctx
        self.late_bound = set(late_bound or [])
        self.dtype = np.dtype(dtype)
        self.shapes = shapes or {}
        self.arenas = arenas or {}
        self.dev = dev
        # program scope: prologue definitions + globals
        self.scope: Dict[str, object] = {"np": np}
        if prog.prologue:
            exec(prog.prologue, self.scope)
        gvals: Dict[str, int] = {}
        for g in prog.globals:
            if g.typ.rstrip().endswith("*"):
                # pointer-typed global (reference: collections / user arrays
                # like `decision [type = "int *"]`, tests/dsl/ptg/choice):
                # lives in the program scope, not the int-global table.
                # Must be satisfiable: a registered collection, a caller
                # value, a prologue definition, or a late builder.scope
                # binding (promised via late_bound=[names]).
                if g.name in globals:
                    self.scope[g.name] = globals[g.name]
                elif g.name not in ctx.collections and \
                        g.name not in self.scope and \
                        g.name not in self.late_bound:
                    raise ValueError(
                        f"jdf: pointer global {g.name!r} has no value: "
                        "register a collection under that name, pass it in "
                        "globals=, define it in the prologue, or list it "
                        "in late_bound= and set builder.scope[name]")
                continue
            if g.name in globals:
                gvals[g.name] = int(globals[g.name])
            elif g.default is not None:
                gvals[g.name] = int(eval(str(g.default).strip("()"),
                                         dict(self.scope), dict(gvals)))
            else:
                raise ValueError(f"jdf: global {g.name} has no value")
        self.gvals = gvals
        self.tp = Taskpool(ctx, globals=gvals)
        # escapes compiled at commit() read this live dict (CompileCtx.scope)
        self.tp.jdf_scope = self.scope
        self._startup_hooks: List[Tuple[str, str]] = []  # (class, fn name)
        for jt in prog.tasks:
            self._build_task(jt)

    # nb_local_tasks_fn is deliberately NOT here: it is a %option (pool
    # scope), and accepting it per class would validate-then-ignore it
    _CLASS_PROPS = ("make_key_fn", "startup_fn", "hash_struct",
                    "high_priority")

    def _loc(self, pos: int) -> Optional[str]:
        """file:line of a source offset (body-stripped source is
        newline-preserving, so lines match the original)."""
        if pos < 0:
            return None
        return f"{self.filename}:{self.prog.src[:pos].count(chr(10)) + 1}"

    def _build_task(self, jt: JdfTask):
        tc = self.tp.task_class(jt.name)
        tc.srcloc = self._loc(jt.pos) or tc.srcloc
        tc.jdf_props = dict(jt.props)
        for k in jt.props:
            if k not in self._CLASS_PROPS:
                raise ValueError(f"jdf: {jt.name}: unknown class property "
                                 f"{k!r}")
        if "startup_fn" in jt.props:
            self._startup_hooks.append((jt.name, jt.props["startup_fn"]))
        for (nm, payload) in jt.locals:
            if isinstance(payload, JdfCompr):
                tc.param_compr(nm, payload.lo, payload.hi, payload.value,
                               payload.st, iter_name=payload.iter_name)
            elif isinstance(payload, E.Range):
                tc.locals.append((nm, True, payload))
            else:
                tc.locals.append((nm, False, payload))
        if jt.affinity:
            tc.affinity(jt.affinity[0], *jt.affinity[1])
        if jt.priority is not None:
            tc.priority(jt.priority)
        for fl in jt.flows:
            deps = []
            for d in fl.deps:
                mk = In if d.direction == 0 else Out
                # ptgpp compiler checks (reference messages verbatim:
                # tests/dsl/ptg/ptgpp/output_{NEW,NULL}*.jdf expect them)
                if d.direction == 1:
                    tkinds = [d.target.kind] + (
                        [d.alt.kind] if d.alt is not None else [])
                    if "new" in tkinds:
                        raise ValueError(
                            f"jdf: {jt.name}.{fl.name}: Automatic data "
                            "allocation with NEW only supported in IN "
                            "dependencies.")
                    if "null" in tkinds:
                        raise ValueError(
                            f"jdf: {jt.name}.{fl.name}: NULL data only "
                            "supported in IN dependencies.")
                # reference dep-type semantics (parsec_reshape.c,
                # tests/collections/reshape/): [type = X] reshapes
                # locally through a datacopy future AND types the wire;
                # [type_remote = X] types the wire only; [type_data = X]
                # types the collection read / selective write-back.
                t_full = d.props.get("type")
                t_rem = d.props.get("type_remote")
                t_data = d.props.get("type_data")
                dt = t_rem if t_rem is not None else t_full
                lt = t_full if t_full is not None else t_data
                for nm_, role in ((t_full, "type"),
                                  (t_rem, "type_remote"),
                                  (t_data, "type_data")):
                    if nm_ is not None and nm_ not in self.ctx.datatypes:
                        raise ValueError(
                            f"jdf: {jt.name}.{fl.name}: dep [{role} = "
                            f"{nm_}] names no registered datatype "
                            "(Context.register_datatype*)")
                tgt = _target_to_builder(d.target, fl.name)
                its = d.iters + d.target.iters  # dep-level outer
                loc = self._loc(d.pos)
                if d.alt is not None:
                    alt = _target_to_builder(d.alt, fl.name)
                    built = [mk(tgt, guard=d.guard, dtype=dt, iters=its,
                                ltype=lt),
                             mk(alt, guard=E.UnOp(E.N.OP_NOT, d.guard),
                                dtype=dt, iters=d.iters + d.alt.iters,
                                ltype=lt)]
                else:
                    built = [mk(tgt, guard=d.guard, dtype=dt, iters=its,
                                ltype=lt)]
                for b in built:
                    b.srcloc = loc or b.srcloc
                deps += built
            tc.flow(fl.name, fl.access, *deps,
                    arena=self.arenas.get(fl.name))
        self._attach_bodies(jt, tc)

    def _attach_bodies(self, jt: JdfTask, tc: TaskClass):
        param_names = [nm for (nm, is_r, _) in tc.locals]
        data_flows = [f.name for f in jt.flows if f.access != "CTL"]
        for body in jt.bodies:
            btype = body.props.get("type", "CPU").upper()
            if btype == "TPU" and self.dev is not None:
                reads = [s.strip() for s in
                         body.props.get("reads", ",".join(data_flows))
                         .split(",") if s.strip()]
                writes = [s.strip() for s in
                          body.props.get("writes", "").split(",")
                          if s.strip()]
                if not writes:
                    writes = [f.name for f in jt.flows
                              if f.access in ("RW", "WRITE")]
                code = compile(body.code, f"<jdf-{jt.name}-tpu>", "exec")

                def kernel(*arrs, _code=code, _reads=tuple(reads),
                           _writes=tuple(writes), _scope=self.scope):
                    env = dict(_scope)
                    import jax.numpy as jnp
                    env["jnp"] = jnp
                    env.update(dict(zip(_reads, arrs)))
                    exec(_code, env)
                    outs = tuple(env[w] for w in _writes)
                    return outs if len(outs) > 1 else outs[0]

                self.dev.attach(tc, self.tp, kernel=kernel, reads=reads,
                                writes=writes, shapes=self.shapes,
                                dtype=self.dtype,
                                batch=body.props.get("batch", "1") != "0")
            elif btype == "TPU":
                continue  # no device available: skip this incarnation
            else:
                code = compile(body.code, f"<jdf-{jt.name}>", "exec")

                def pybody(view, _code=code, _params=tuple(param_names),
                           _flows=tuple(data_flows), _scope=self.scope):
                    env = dict(_scope)
                    env["this"] = view
                    env["taskpool"] = self.tp  # bodies may addto_nb_tasks
                    env.update({p: view.local(p) for p in _params})
                    env.update(self.gvals)
                    for f in _flows:
                        try:
                            env[f] = view.data(f, self.dtype,
                                               self.shapes.get(f))
                        except RuntimeError:
                            env[f] = None
                    exec(_code, env)

                tc.body(pybody)

    def _scope_fn(self, name: str, what: str):
        fn = self.scope.get(name)
        if not callable(fn):
            raise ValueError(f"jdf: {what} = {name!r} is not a callable in "
                             "the program scope")
        return fn

    def run(self):
        # class startup hooks (reference: startup_fn property,
        # tests/dsl/ptg/user-defined-functions/udf.jdf — there it replaces
        # the generated startup enumerator; here enumeration is interpreted
        # natively, so the hook runs for its side effects before tasks do)
        for name in self.late_bound:
            if name not in self.scope:
                raise ValueError(
                    f"jdf: late_bound global {name!r} was never bound: set "
                    "builder.scope[name] before run() (an unbound name "
                    "would make every escape referencing it evaluate to 0)")
        for cls_name, fn_name in self._startup_hooks:
            self._scope_fn(fn_name, "startup_fn")(self.tp, cls_name)
        # make_key_fn / hash_struct: validated, then intentionally unused —
        # the native engine keys on the exact parameter vector (see module
        # docstring)
        for jt in self.prog.tasks:
            if "make_key_fn" in jt.props:
                self._scope_fn(jt.props["make_key_fn"], "make_key_fn")
            if "hash_struct" in jt.props and \
                    jt.props["hash_struct"] not in self.scope:
                raise ValueError(f"jdf: hash_struct = "
                                 f"{jt.props['hash_struct']!r} not in scope")
        nbfn_name = self.prog.options.get("nb_local_tasks_fn")
        if nbfn_name is not None:
            # %option nb_local_tasks_fn: the user count overrides the
            # enumerated one for termination detection.  Hold the pool open
            # so it cannot complete before the adjustment is applied.
            nbfn = self._scope_fn(nbfn_name, "nb_local_tasks_fn")
            self.tp.set_open(True)
            try:
                self.tp.run()
                delta = int(nbfn(self.tp)) - self.tp.nb_total_tasks
                if delta:
                    self.tp.addto_nb_tasks(delta)
            finally:
                # a raising count fn must not leave the pool open forever
                self.tp.set_open(False)
        else:
            self.tp.run()
        return self.tp


def compile_jdf(src: str, ctx, globals: Dict[str, int], **kw):
    """Parse + instantiate: returns a JdfTaskpoolBuilder (call .run())."""
    return JdfTaskpoolBuilder(parse_jdf(src), ctx, globals, **kw)
