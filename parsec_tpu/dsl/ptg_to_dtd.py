"""Runtime PTG -> DTD conversion — a correctness cross-check tool.

Reference: parsec/mca/pins/ptg_to_dtd, which re-executes a PTG taskpool
through the DTD engine so the two dataflow front-ends validate each
other (the PTG compiler's dependency iterators against DTD's
access-order discovery).  Here the conversion is a library function: it
interprets the PYTHON-side task-class spec (the same declarations the
native spec blob is compiled from) with a small expression evaluator,
enumerates every instance, resolves each flow to its ROOT datum by
walking In-dep chains back to a Mem reference (or to a fresh transient
datum for `In(None)` chain heads), topologically orders the instances,
and re-submits them as DTD tasks whose tile access order reproduces the
PTG dataflow.  Running both and comparing collection contents
cross-validates the dense/hash dependency engines, guard evaluation,
and release_deps against DTD's data-driven discovery.

Scope (the tool's contract, mirroring the reference tool's limits):
CPU-body pools with expression guards; bracketed dep iterators and CTL
flows are rejected loudly.  DTD serializes tile access, so a converted
pool may run MORE ordered than the PTG original — results, not
schedules, are what is compared.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import _native as N
from ..core.context import Context, Data
from ..core.expr import BinOp, Compr, Const, G, L, Select, UnOp
from ..core.taskclass import Mem, Ref, TaskClass
from ..core.taskpool import Taskpool
from .dtd import DtdTaskpool

def _tdiv(a: int, b: int) -> int:
    """C++ TRUNCATING int division — the native VM's semantics
    (core.cpp OP_DIV); Python's floor division differs for mixed
    signs, which would make guards diverge from the engine under
    cross-check."""
    if b == 0:
        return 0
    q = abs(a) // abs(b)
    return q if (a < 0) == (b < 0) else -q


def _tmod(a: int, b: int) -> int:
    """C++ truncating remainder: sign follows the dividend."""
    if b == 0:
        return 0
    return a - _tdiv(a, b) * b


_BINOPS = {
    N.OP_ADD: lambda a, b: a + b,
    N.OP_SUB: lambda a, b: a - b,
    N.OP_MUL: lambda a, b: a * b,
    N.OP_DIV: _tdiv,
    N.OP_MOD: _tmod,
    N.OP_EQ: lambda a, b: int(a == b),
    N.OP_NE: lambda a, b: int(a != b),
    N.OP_LT: lambda a, b: int(a < b),
    N.OP_LE: lambda a, b: int(a <= b),
    N.OP_GT: lambda a, b: int(a > b),
    N.OP_GE: lambda a, b: int(a >= b),
    N.OP_AND: lambda a, b: int(bool(a) and bool(b)),
    N.OP_OR: lambda a, b: int(bool(a) or bool(b)),
    N.OP_MIN: min,
    N.OP_MAX: max,
    N.OP_SHL: lambda a, b: a << b,
    N.OP_SHR: lambda a, b: a >> b,
}
_UNOPS = {
    N.OP_NEG: lambda a: -a,
    N.OP_NOT: lambda a: int(not a),
}


def eval_expr(e, loc: Dict[str, int], glb: Dict[str, int]) -> int:
    """Evaluate a Python-side Expr tree (the same trees compile_expr
    serializes for the native VM) against named locals/globals."""
    if isinstance(e, bool):
        return int(e)
    if isinstance(e, (int, np.integer)):
        return int(e)
    if isinstance(e, Const):
        return int(e.v)
    if isinstance(e, L):
        return int(loc[e.name])
    if isinstance(e, G):
        return int(glb[e.name])
    if isinstance(e, BinOp):
        return _BINOPS[e.op](eval_expr(e.a, loc, glb),
                             eval_expr(e.b, loc, glb))
    if isinstance(e, UnOp):
        return _UNOPS[e.op](eval_expr(e.a, loc, glb))
    if isinstance(e, Select):
        return eval_expr(e.a if eval_expr(e.c, loc, glb) else e.b,
                         loc, glb)
    if isinstance(e, str):
        return int(glb[e])
    raise NotImplementedError(
        f"ptg_to_dtd: unsupported expression node {type(e).__name__} "
        "(UDF calls need the native VM)")


def _walk(lo: int, hi: int, st: int):
    """The native enumeration walk: ascending for st>0, DESCENDING for
    st<0 (lo down to hi), empty for st==0 — matching enumerate_class."""
    if st == 0:
        return range(0)
    if st > 0:
        return range(lo, hi + 1, st)
    return range(lo, hi - 1, st)


def _instances(tc: TaskClass, glb: Dict[str, int]):
    """Enumerate the class domain as {name: value} dicts, honoring
    range, derived, and comprehension locals in declaration order."""
    out: List[Dict[str, int]] = [{}]
    for (name, is_range, payload) in tc.locals:
        nxt = []
        for loc in out:
            if isinstance(payload, Compr):
                it = payload.iter_name or name
                lo = eval_expr(payload.lo, loc, glb)
                hi = eval_expr(payload.hi, loc, glb)
                st = eval_expr(payload.step, loc, glb)
                for i in _walk(lo, hi, st):
                    l2 = dict(loc)
                    l2[it] = i
                    l2[name] = eval_expr(payload.value, l2, glb)
                    if it != name:
                        del l2[it]
                    nxt.append(l2)
            elif is_range:
                lo = eval_expr(payload.lo, loc, glb)
                hi = eval_expr(payload.hi, loc, glb)
                st = eval_expr(payload.step, loc, glb)
                for v in _walk(lo, hi, st):
                    l2 = dict(loc)
                    l2[name] = v
                    nxt.append(l2)
            else:  # derived local
                l2 = dict(loc)
                l2[name] = eval_expr(payload, loc, glb)
                nxt.append(l2)
        out = nxt
    return out


class _NativeColl:
    """data_of/rank_of adapter over a NATIVELY-registered collection
    (e.g. register_linear_collection) so DtdTaskpool.tile_of can key
    tiles on it when no Python collection object exists."""

    class _D:
        __slots__ = ("_ptr",)

        def __init__(self, ptr):
            self._ptr = ptr

    def __init__(self, ctx: Context, dc_id: int):
        import ctypes as C
        self._C = C
        self.ctx = ctx
        self.dc_id = dc_id

    def data_of(self, *idx):
        arr = (self._C.c_int64 * max(1, len(idx)))(*idx)
        p = N.lib.ptc_dc_data_of(self.ctx._ptr, self.dc_id, arr, len(idx))
        return self._D(p) if p else None

    def rank_of(self, *idx):
        arr = (self._C.c_int64 * max(1, len(idx)))(*idx)
        return N.lib.ptc_dc_rank_of(self.ctx._ptr, self.dc_id, arr,
                                    len(idx))


class _ConvView:
    """TaskView-compatible adapter handed to the original PTG bodies:
    locals come from the enumeration, flow data from the DTD view."""

    def __init__(self, dtd_view, loc, glb, flow_slot):
        self._v = dtd_view
        self._loc = loc
        self._glb = glb
        self._slot = flow_slot

    def local(self, name: str) -> int:
        return self._loc[name]

    def __getitem__(self, name: str) -> int:
        return self.local(name)

    def global_(self, name: str) -> int:
        return self._glb[name]

    def data(self, flow: str, dtype=np.uint8, shape=None,
             sync: bool = True) -> np.ndarray:
        return self._v.data(self._slot[flow], dtype=dtype, shape=shape)


def run_ptg_as_dtd(ctx: Context, tp: Taskpool,
                   collections: Dict[str, object],
                   window: Optional[int] = None) -> Dict[str, int]:
    """Re-execute a (not-yet-run) PTG taskpool spec through DTD.

    `collections` maps the Mem names used in the spec to their Python
    collection objects (rank_of/data_of), or to None for collections
    registered natively (register_linear_collection) — those are
    reached through the ptc_dc_data_of tool ABI.  Runs to completion;
    returns {"tasks": N, "classes": C}.  The caller compares collection
    contents against a PTG run of the same spec."""
    collections = {
        name: (c if c is not None
               else _NativeColl(ctx, ctx.collections[name]))
        for name, c in collections.items()}
    glb = {name: N.lib.ptc_tp_global(tp._ptr, i)
           for name, i in tp.globals_map.items()}
    classes = {tc.name: tc for tc in tp.classes}

    # ---- per-instance flow roots (memoized), via active-In resolution
    roots: Dict[tuple, tuple] = {}
    transients: Dict[tuple, Data] = {}
    tkey = [1000]

    def peer_locals(ref: Ref, loc) -> Dict[str, int]:
        """Full locals of the peer instance a Ref names: Ref params bind
        the range/comprehension slots in declaration order; derived
        locals re-derive from them (the native dep-param translation)."""
        pview = tuple(eval_expr(p, loc, glb) for p in ref.params)
        ptc = classes[ref.task]
        ploc: Dict[str, int] = {}
        ri = 0
        for (n, is_range, payload) in ptc.locals:
            if isinstance(payload, Compr) or is_range:
                ploc[n] = pview[ri]
                ri += 1
            else:
                ploc[n] = eval_expr(payload, ploc, glb)
        return ploc

    def active_in(tc: TaskClass, fl, loc):
        for d in fl.deps:
            if d.direction != 0:
                continue
            if d.iters:
                raise NotImplementedError(
                    "ptg_to_dtd: bracketed dep iterators")
            if d.guard is None or eval_expr(d.guard, loc, glb):
                return d
        return None

    _IN_PROGRESS = ("...",)  # cycle-guard sentinel (never a real root)

    def root_of(cname: str, params: tuple, fname: str):
        key = (cname, params, fname)
        if key in roots:
            r = roots[key]
            if r is _IN_PROGRESS:
                # re-entered while resolving this very instance: the In
                # chain loops.  Raise here — letting the sentinel escape
                # surfaces later as an opaque tuple-unpack ValueError at
                # the caller, far from the cycle.
                raise ValueError(
                    f"ptg_to_dtd: cyclic In chain at {cname}/{fname} "
                    f"(params {params})")
            return r
        roots[key] = _IN_PROGRESS
        tc = classes[cname]
        loc = dict(zip([n for n, _, _ in tc.locals], params))
        # re-derive non-param locals (params covers ALL locals here
        # because instances carry every local)
        fl = next(f for f in tc.flows if f.name == fname)
        d = active_in(tc, fl, loc)
        if d is None or d.target is None:
            # chain head: a fresh transient datum (the arena copy)
            size = ctx.arena_sizes.get(fl.arena, 64) if fl.arena else 64
            tkey[0] += 1
            td = Data(tkey[0], np.zeros(size, np.uint8))
            transients[key] = td
            r = ("data", td)
        elif isinstance(d.target, Mem):
            idx = tuple(eval_expr(i, loc, glb) for i in d.target.idx)
            r = ("mem", d.target.collection, idx)
        elif isinstance(d.target, Ref):
            pflow = d.target.flow or fname
            ploc = peer_locals(d.target, loc)
            r = root_of(d.target.task,
                        tuple(ploc[n] for n, _, _ in
                              classes[d.target.task].locals), pflow)
        else:
            raise NotImplementedError(
                f"ptg_to_dtd: unsupported In target {d.target!r}")
        roots[key] = r
        return r

    # ---- enumerate + topologically order (Kahn over producer edges)
    insts = []  # (cname, params(dict))
    for tc in tp.classes:
        for loc in _instances(tc, glb):
            insts.append((tc.name, loc))
    idx_of = {(c, tuple(l.values())): i for i, (c, l) in enumerate(insts)}
    succs: List[List[int]] = [[] for _ in insts]
    preds = [0] * len(insts)
    for i, (cname, loc) in enumerate(insts):
        tc = classes[cname]
        for fl in tc.flows:
            if fl.access == N.FLOW_CTL:
                raise NotImplementedError("ptg_to_dtd: CTL flows")
            d = active_in(tc, fl, loc)
            if d is not None and isinstance(d.target, Ref):
                ploc = peer_locals(d.target, loc)
                j = idx_of.get((d.target.task, tuple(ploc.values())))
                if j is not None:
                    succs[j].append(i)
                    preds[i] += 1
    order: List[int] = [i for i in range(len(insts)) if preds[i] == 0]
    qi = 0
    while qi < len(order):
        for s in succs[order[qi]]:
            preds[s] -= 1
            if preds[s] == 0:
                order.append(s)
        qi += 1
    if len(order) != len(insts):
        raise ValueError("ptg_to_dtd: dependency cycle in the PTG spec")

    # ---- insert in topo order; DTD rediscovers the DAG from access
    # order.  Specs accumulate into a batch stream: ONE native crossing
    # per dtd.insert_batch tasks (ptc_dtask_insert_batch) instead of the
    # per-task begin/arg/submit triple — access order is the batch
    # stream's order, so the discovered DAG is identical.
    dtp = DtdTaskpool(ctx, window=window)
    n_inserted = 0
    batch_stream = []

    def _copy_body(v):
        src = v.data(0)
        dst = v.data(1)
        k = min(len(src), len(dst))
        dst[:k] = src[:k]

    for i in order:
        cname, loc = insts[i]
        tc = classes[cname]
        body = next((ch.body for ch in tc.chores
                     if ch.body_kind == N.BODY_CB and ch.body), None)
        params = tuple(loc.values())
        args = []
        slot = {}
        writebacks = []  # (root tile, dst tile): PTG's release-time
        #                  cross-tile Mem memcpy, as explicit copy tasks
        for fl in tc.flows:
            r = root_of(cname, params, fl.name)
            if r[0] == "data":
                tile = dtp.tile_of(r[1])
            else:
                _, collname, idx = r
                tile = dtp.tile_of(collections[collname], *idx)
            mode = {N.FLOW_READ: "INPUT", N.FLOW_WRITE: "OUTPUT",
                    N.FLOW_RW: "INOUT"}[fl.access]
            slot[fl.name] = len(args)
            args.append((tile, mode))
            for d in fl.deps:
                if d.direction != 1 or not isinstance(d.target, Mem):
                    continue
                if d.guard is not None and not eval_expr(d.guard, loc,
                                                         glb):
                    continue
                if d.ltype is not None:
                    raise NotImplementedError(
                        "ptg_to_dtd: reshaped Mem writeback ([type=..])")
                idx = tuple(eval_expr(x, loc, glb) for x in d.target.idx)
                dst = ("mem", d.target.collection, idx)
                if dst != r:
                    writebacks.append(
                        (tile, dtp.tile_of(
                            collections[d.target.collection], *idx)))

        def mk(body, loc, slot):
            if body is None:
                return lambda v: None
            return lambda v: body(_ConvView(v, loc, glb, slot))

        batch_stream.append((mk(body, dict(loc), dict(slot)), tuple(args)))
        n_inserted += 1
        for src_tile, dst_tile in writebacks:
            batch_stream.append((_copy_body, ((src_tile, "INPUT"),
                                              (dst_tile, "INOUT"))))
    dtp.insert_tasks(batch_stream)
    dtp.wait()
    dtp.destroy()  # tiles go before their transient Data backings
    for d in transients.values():
        d.destroy()
    return {"tasks": n_inserted, "classes": len(tp.classes)}
