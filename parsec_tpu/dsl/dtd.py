"""DTD — Dynamic Task Discovery interface.

Build the DAG as you go: every inserted task names its data arguments with
access modes (INPUT/OUTPUT/INOUT); dependencies derive from per-tile
last-writer/readers accessor chains maintained natively.  A sliding window
throttles discovery so the DAG never outruns execution.

Reference: parsec/interfaces/dtd/insert_function.{c,h} (SURVEY.md §2.7,
call stack §3.5): parsec_dtd_taskpool_new / parsec_dtd_tile_of /
parsec_dtd_insert_task / parsec_dtd_taskpool_wait, window throttling at
insert_function.c:69,472-509.
"""
from __future__ import annotations

import ctypes as C
import traceback
from typing import Callable, Dict, Iterable, Optional, Tuple

import numpy as np

from .. import _native as N
from ..core.context import Context, Data
from ..core.taskpool import Taskpool

INPUT = N_INPUT = 1
OUTPUT = N_OUTPUT = 2
INOUT = N_INOUT = 3

_MODES = {"INPUT": 1, "OUTPUT": 2, "INOUT": 3, "R": 1, "W": 2, "RW": 3}


class DtdView:
    """Body-side view of a DTD task: flows addressed by argument index."""

    __slots__ = ("_ptr", "nb_flows")

    def __init__(self, ptr, nb_flows: int = -1):
        self._ptr = ptr
        # per-task arity from the native side (a cached body callback is
        # shared between insertions of the same fn with different arities)
        self.nb_flows = (nb_flows if nb_flows >= 0
                         else N.lib.ptc_dtask_nb_flows(ptr))

    def data_ptr(self, i: int) -> int:
        return N.lib.ptc_task_data_ptr(self._ptr, i)

    def data(self, i: int, dtype=np.uint8, shape=None,
             sync: bool = True) -> np.ndarray:
        import ctypes as C
        ptr = N.lib.ptc_task_data_ptr(self._ptr, i)
        if not ptr:
            raise RuntimeError(f"dtd task: argument {i} has no data")
        cptr = N.lib.ptc_task_copy(self._ptr, i)
        if sync:
            from ..device.tpu import maybe_sync_copy
            maybe_sync_copy(cptr)
        size = N.lib.ptc_copy_size(cptr)
        dt = np.dtype(dtype)
        buf = (C.c_char * size).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dt, count=size // dt.itemsize)
        return arr.reshape(shape) if shape is not None else arr


class DtdTile:
    """Handle to a tracked datum (reference: parsec_dtd_tile_of).  `owner`
    is the rank that executes tasks writing this tile (distributed DTD
    placement; other ranks keep shadow tasks + mirror copies).

    `nbytes`/`coll_stride` carry the tile's actual payload size vs its
    collection's declared stride for the insertion linter's D104
    size-mismatch rule (None when the source declares no geometry)."""

    __slots__ = ("_ptr", "data", "owner", "_lint_finalized", "nbytes",
                 "coll_stride")

    def __init__(self, ctx: Context, data: Data, owner: int = 0,
                 coll_stride: Optional[int] = None):
        self.data = data
        self.owner = owner
        self._lint_finalized = False  # set by the DTD linter on destroy
        self.nbytes = int(data.array.nbytes) \
            if getattr(data, "array", None) is not None else None
        self.coll_stride = coll_stride
        self._ptr = N.lib.ptc_dtile_new(ctx._ptr, data._ptr)
        if owner:
            N.lib.ptc_dtile_set_owner(self._ptr, owner)


class DtdTaskpool:
    def __init__(self, ctx: Context, window: Optional[int] = None,
                 lint=False):
        """`lint=True` (or "error") turns on the insertion linter
        (analysis.dtdlint): undeclared access-mode conflicts and
        use-after-finalize raise DtdLintError at insert time;
        lint="warn" records findings in `self.linter.findings`
        without raising."""
        if window is None:
            from ..utils import params as _mca
            window = _mca.get("dtd.window_size")
        self.ctx = ctx
        self.window = window
        self.linter = None
        if lint:
            from ..analysis.dtdlint import DtdLinter
            self.linter = DtdLinter(
                mode="warn" if lint == "warn" else "error")
        self.tp = Taskpool(ctx)
        self.tp.set_open(True)
        self.tp.run()  # zero classes; registers with the context
        self._tiles: Dict[Tuple[int, object], DtdTile] = {}
        self._body_ids: Dict[Callable, int] = {}
        self._closed = False

    # ------------------------------------------------------------- tiles
    def tile_of(self, source, *key, owner: Optional[int] = None) -> DtdTile:
        """Tile for a Data object or a (collection, key...) pair.  The
        owning rank defaults to the collection's rank_of (Data objects
        default to rank 0 unless `owner=` is given)."""
        if isinstance(source, Data):
            k = (id(source), None)
            if k not in self._tiles:
                self._tiles[k] = DtdTile(self.ctx, source, owner or 0)
            return self._tiles[k]
        k = (id(source), key)
        if k not in self._tiles:
            d = source.data_of(*key)
            own = owner if owner is not None else source.rank_of(*key)
            from ..analysis.flowgraph import collection_tile_bytes
            self._tiles[k] = DtdTile(self.ctx, d, own,
                                     coll_stride=collection_tile_bytes(
                                         source))
        return self._tiles[k]

    # ------------------------------------------------------------- insert
    def _body_id(self, fn: Callable) -> int:
        bid = self._body_ids.get(fn)
        if bid is None:
            def _cb(user, task_ptr):
                try:
                    r = fn(DtdView(task_ptr))
                    if isinstance(r, int) and not isinstance(r, bool):
                        return r
                    return N.HOOK_DONE
                except Exception:
                    traceback.print_exc()
                    return N.HOOK_ERROR

            bid = self.ctx.register_body_cb(_cb)
            self._body_ids[fn] = bid
        return bid

    def insert_task(self, fn: Callable, *args, priority: int = 0,
                    rank: Optional[int] = None):
        """insert_task(body, (tile, "INPUT"), (tile2, "INOUT"), ...).

        body(view) runs on a worker; view.data(i) is the i-th argument.
        In distributed mode every rank inserts the same stream; the task
        executes on `rank` (default: first OUTPUT tile's owner) and other
        ranks keep a shadow released by the owner's completion broadcast."""
        if self._closed:
            raise RuntimeError("taskpool already closed")
        if self.linter is not None:
            self.linter.on_insert(
                [(tile, _MODES[mode.upper()] if isinstance(mode, str)
                  else int(mode)) for tile, mode in args])
        bid = self._body_id(fn)
        t = N.lib.ptc_dtask_begin(self.tp._ptr, N.BODY_CB, bid, priority)
        for tile, mode in args:
            m = _MODES[mode.upper()] if isinstance(mode, str) else int(mode)
            if N.lib.ptc_dtask_arg(t, tile._ptr, m) < 0:
                raise ValueError(
                    "insert_task: too many arguments (max 20)")
        if rank is not None:
            N.lib.ptc_dtask_set_rank(t, rank)
        if N.lib.ptc_dtask_submit(self.ctx._ptr, t, self.window) != 0:
            raise RuntimeError("taskpool aborted: insertion refused")
        return t

    def insert_tasks(self, tasks: Iterable, batch: Optional[int] = None
                     ) -> int:
        """Batched insert_task: ONE native crossing (and one GIL bounce)
        per `batch` tasks instead of 2+nargs crossings per task — the
        amortized path for DAG builders that insert thousands of tasks
        in a loop (ptg_to_dtd, redistribute).

        `tasks` yields (fn, args) or (fn, args, priority) or
        (fn, args, priority, rank) tuples, where args is the usual
        ((tile, mode), ...) sequence.  `batch` defaults to the
        dtd.insert_batch MCA param; the window throttle still applies
        per task inside the native batch.  Returns tasks inserted."""
        if self._closed:
            raise RuntimeError("taskpool already closed")
        if batch is None:
            from ..utils import params as _mca
            batch = _mca.get("dtd.insert_batch")
        batch = max(1, int(batch))
        spec: list = []
        pending = 0
        inserted = 0

        def flush():
            nonlocal spec, pending, inserted
            if not pending:
                return
            arr = (C.c_int64 * len(spec))(*spec)
            rc = N.lib.ptc_dtask_insert_batch(
                self.ctx._ptr, self.tp._ptr, arr, len(spec), self.window)
            if rc < 0:
                inserted += ~rc
                raise RuntimeError(
                    f"taskpool aborted: insertion refused after "
                    f"{inserted} tasks")
            inserted += rc
            spec = []
            pending = 0

        for item in tasks:
            fn, args = item[0], item[1]
            prio = int(item[2]) if len(item) > 2 else 0
            rank = int(item[3]) if len(item) > 3 and item[3] is not None \
                else -1
            if len(args) > N.MAX_FLOWS:
                raise ValueError(
                    f"insert_tasks: too many arguments (max {N.MAX_FLOWS})")
            spec += [N.BODY_CB, self._body_id(fn), prio, rank, len(args)]
            normed = []
            for tile, mode in args:
                m = _MODES[mode.upper()] if isinstance(mode, str) \
                    else int(mode)
                normed.append((tile, m))
                spec += [tile._ptr, m]
            if self.linter is not None:
                self.linter.on_insert(normed)
            pending += 1
            if pending >= batch:
                flush()
        flush()
        return inserted

    def insert_tpu_task(self, dev, kernel: Callable, *args,
                        shapes=None, dtype=np.float32, priority: int = 0):
        """Insert a device task: kernel(*inputs) -> outputs, dispatched by
        the TPU device manager (reads = all args; writes = OUTPUT/INOUT
        args, in order)."""
        if self._closed:
            raise RuntimeError("taskpool already closed")
        if self.linter is not None:
            self.linter.on_insert(
                [(tile, _MODES[mode.upper()] if isinstance(mode, str)
                  else int(mode)) for tile, mode in args])
        # same hazard attach() guards: float64 without jax x64 silently
        # downcasts on device and corrupts the writeback.  DTD device
        # tasks have no host fallback chore, so fail loudly at insert.
        if np.dtype(dtype) == np.float64 \
                and not dev._jax.config.jax_enable_x64:
            raise ValueError(
                "insert_tpu_task: float64 needs JAX_ENABLE_X64=1 "
                "(the device would silently downcast to float32); "
                "use insert_task with a host body instead")
        t = N.lib.ptc_dtask_begin(self.tp._ptr, N.BODY_DEVICE, dev.qid,
                                  priority)
        reads, writes = [], []
        for i, (tile, mode) in enumerate(args):
            m = _MODES[mode.upper()] if isinstance(mode, str) else int(mode)
            if N.lib.ptc_dtask_arg(t, tile._ptr, m) < 0:
                raise ValueError("insert_tpu_task: too many arguments")
            if m & 1:
                reads.append(i)
            if m & 2:
                writes.append(i)
        dev.register_dtd_task(t, kernel, reads, writes,
                              shapes or {}, dtype, len(args))
        if N.lib.ptc_dtask_submit(self.ctx._ptr, t, self.window) != 0:
            raise RuntimeError("taskpool aborted: insertion refused")
        return t

    # ------------------------------------------------------------- finish
    def wait(self):
        """Close the window and wait for every discovered task."""
        self._closed = True
        if self.linter is not None:
            self.linter.on_wait()
        self.tp.set_open(False)
        self.tp.wait()

    def destroy(self):
        if self.linter is not None:
            self.linter.on_destroy()
        for tile in self._tiles.values():
            N.lib.ptc_dtile_destroy(self.ctx._ptr, tile._ptr)
        self._tiles.clear()
        self.tp.destroy()
