from .dtd import INOUT, INPUT, OUTPUT, DtdTaskpool, DtdTile, DtdView

__all__ = ["DtdTaskpool", "DtdTile", "DtdView", "INPUT", "OUTPUT", "INOUT"]
