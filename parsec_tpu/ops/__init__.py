"""Pallas TPU kernels for the framework's hot ops.

The reference's analog is its CUDA chore bodies (dyld'd cublas kernels,
SURVEY.md §2.6); here the hot paths are hand-written Pallas kernels that
the higher layers (parallel/, models/, device/) pick up when running on
TPU, with jnp reference fallbacks everywhere else.
"""
from .flash_attention import flash_attention
from .rms_norm import rms_norm

__all__ = ["flash_attention", "rms_norm"]
