"""Paged KV-cache attention as PTG taskpools (the Ragged Paged
Attention decode shape, arXiv:2604.15464, PAPERS.md).

A sequence's KV cache lives in fixed-size PAGES — (page, d) tiles of two
ordinary tiled collections — so the cache of thousands of concurrent
sequences shares one pool of first-class runtime tiles: the PR 3
residency planner stages, prefetches and evicts KV pages exactly like
GEMM tiles, and the serving engine's admission control budgets them in
bytes.  Decode is blockwise over pages with the online-softmax
recurrence of ops/flash_attention.py carried task-to-task instead of
kv-block-to-kv-block inside one kernel:

  PUPD(s)      appends the step's new k/v row into the sequence's last
               page (in place + runtime dataflow to the attention task)
  PATTF(s, j)  folds FROZEN (full) page j into the (acc, m, l)
               accumulator — a per-sequence chain, pages ragged per
               sequence (pure-call lookup tables, verifier-exact)
  PATTL(s)     folds the last (partial) page — received from PUPD
               through the DAG, never stale — normalizes, writes O

The prefill variant (build_paged_prefill) writes whole prompt pages
(PFILL) and runs the same fold chain for the last prompt position.

Bit-exactness contract: every fold uses `attend_page` below in f32 with
a fixed operation order, so a batched decode step and a sequential
per-request run produce IDENTICAL bytes — the serve bench's acceptance
check rides on this.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.collections import ReplicatedLocal, TwoDimBlockCyclic

__all__ = ["PagePool", "SeqSpec", "attend_page", "attend_heads",
           "finalize_attention", "finalize_heads",
           "build_paged_decode", "build_paged_prefill",
           "build_paged_verify", "make_slot_collections",
           "prefix_page_keys"]


def prefix_page_keys(model_id: str, tokens: Sequence[int],
                     page: int) -> List[str]:
    """Content-hash keys for a prompt's FULL pages.  Key j digests
    (model id, tokens[0 : (j+1)*page]) — prefix-CUMULATIVE, so a page's
    KV bytes are a pure function of its key: a hit can only map onto a
    page holding exactly the bytes a cold prefill would write, and two
    PROCESSES (or Server replicas) computing the chain independently
    agree bit-for-bit.  This is the single definition the engine, the
    fleet router and the page-migration wire all share — the router
    predicts a replica's warm-prefix hit length from these keys without
    touching it, and a migrated page is addressed by them on the wire."""
    h = hashlib.sha1(str(model_id).encode())
    keys: List[str] = []
    for j in range(len(tokens) // page):
        h.update(np.asarray(tokens[j * page:(j + 1) * page],
                            np.int64).tobytes())
        keys.append(h.hexdigest())
    return keys


# ------------------------------------------------------------ page pool
class PagePool:
    """Refcounted copy-on-write KV page pool: two tiled collections
    (K pages, V pages) of (page, d) tiles plus a free-list allocator.
    Pages are ordinary collection tiles — the device residency planner
    manages them like any other tile, and `bytes_per_page` feeds
    admission budgets.

    ptc-share adds prefix sharing à la Ragged Paged Attention
    (arXiv:2604.15464 — pages are the unit of sharing):

      refcounts     every live page carries a reference count; a page
                    is handed out again only at refcount 0 (a shared
                    frozen page can NEVER be evicted under a sharer)
      frozen index  FULL immutable pages register a content-hash key
                    (token-id prefix chunk + model id) — `freeze()`;
                    `acquire_prefix()` maps the longest page-aligned
                    warm prefix of a new prompt onto existing frozen
                    pages (refcount++) so only the cold tail prefills
      cached free   a frozen page released to refcount 0 keeps its
                    content and index entry on an LRU list; allocation
                    prefers never-written free pages and only then
                    evicts cached pages (clean-first — the page is
                    host-authoritative, dropping it loses no data),
                    counting `evictions`
      copy-on-write `make_private()` gives a writer an exclusive page:
                    the same page with its index entry dropped when
                    nobody shares it, else a fresh page with the bytes
                    copied (`cow_copies`) — a sharer's view is never
                    mutated

    Every operation is ATOMIC under the pool lock: admission's
    check-and-reserve (`reserve`/`acquire_prefix`) cannot be interleaved
    with concurrent sequence retirement on the pump thread, so two
    tenants can no longer both pass a `free_pages` check and
    oversubscribe the pool."""

    def __init__(self, ctx, n_pages: int, page: int, d: int,
                 dtype=np.float32, name: str = "KV", nodes: int = 1,
                 myrank: int = 0):
        self.n_pages, self.page, self.d = n_pages, page, d
        self.dtype = np.dtype(dtype)
        self.name = name
        self._ctx = ctx
        # tensor-parallel serving (ptc-shard): KV pages shard BY HEAD —
        # each rank's pool holds its head-slice (d = d_model / tp) of
        # every page, rank-replicated placement (rank_of == myrank) so
        # page folds stay purely local Mem edges on every rank.  The
        # refcount/COW/content-hash machinery below is rank-local and
        # unchanged: frozen keys digest token ids (not KV bytes), so the
        # per-shard chains stay deterministic and prefix sharing,
        # admission discounts and fleet page migration work per rank.
        if nodes > 1:
            self.Kc = ReplicatedLocal(n_pages * page, d, page, d,
                                      nodes=nodes, myrank=myrank,
                                      dtype=dtype)
            self.Vc = ReplicatedLocal(n_pages * page, d, page, d,
                                      nodes=nodes, myrank=myrank,
                                      dtype=dtype)
        else:
            self.Kc = TwoDimBlockCyclic(n_pages * page, d, page, d,
                                        dtype=dtype)
            self.Vc = TwoDimBlockCyclic(n_pages * page, d, page, d,
                                        dtype=dtype)
        self.k_name, self.v_name = f"{name}_K", f"{name}_V"
        self.Kc.register(ctx, self.k_name)
        self.Vc.register(ctx, self.v_name)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(n_pages - 1, -1, -1))
        self._refs: List[int] = [0] * n_pages
        self._index: Dict[object, int] = {}      # content key -> page
        self._key_of: Dict[int, object] = {}     # page -> content key
        self._cached: "OrderedDict[int, bool]" = OrderedDict()  # LRU
        # ptc-pilot: frozen pages carry the tenant that wrote them so the
        # controller can steer cached-free capacity between tenants —
        # `set_cached_shares` installs target fractions and eviction
        # prefers the most over-budget owner (LRU within that owner)
        # instead of the global LRU head.  Empty shares = plain LRU.
        self._owner_of: Dict[int, str] = {}      # page -> tenant tag
        self._shares: Dict[str, float] = {}      # tenant -> target share
        self._counters = {
            "prefix_hits": 0, "prefix_misses": 0, "shared_bytes": 0,
            "cow_copies": 0, "evictions": 0, "reserve_fails": 0,
            "frozen": 0, "share_evictions": 0,
            # fleet page migration (ptc-route)
            "exported": 0, "imported": 0, "import_dups": 0,
            "migrated_in_bytes": 0,
        }

    @property
    def free_pages(self) -> int:
        """Pages allocatable right now (never-written free list + the
        refcount-0 cached frozen pages an allocation may evict)."""
        with self._lock:
            return len(self._free) + len(self._cached)

    @property
    def bytes_per_page(self) -> int:
        return 2 * self.page * self.d * self.dtype.itemsize

    # ------------------------------------------------------- allocation
    def _take_free_locked(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        if self._cached:  # evict a cached frozen page (refcount 0)
            p = self._pick_evict_locked()
            del self._cached[p]
            key = self._key_of.pop(p)
            del self._index[key]
            self._owner_of.pop(p, None)
            self._counters["evictions"] += 1
            return p
        return None

    def _pick_evict_locked(self) -> int:
        """Which cached-free page to sacrifice: with no shares installed,
        the global LRU head; with shares, the LRU page of the tenant most
        over its target fraction of the cached set (O(cached) scan — the
        cached set is bounded by n_pages and eviction is already the slow
        path)."""
        lru = next(iter(self._cached))
        if not self._shares:
            return lru
        total = len(self._cached)
        by_owner: Dict[str, int] = {}
        for q in self._cached:
            o = self._owner_of.get(q, "")
            by_owner[o] = by_owner.get(o, 0) + 1
        worst, worst_over = None, 0.0
        for owner, cnt in sorted(by_owner.items()):
            over = cnt / total - self._shares.get(owner, 0.0)
            if over > worst_over + 1e-9:
                worst, worst_over = owner, over
        if worst is None:
            return lru
        for q in self._cached:  # LRU-first within the over-budget owner
            if self._owner_of.get(q, "") == worst:
                if q != lru:
                    self._counters["share_evictions"] += 1
                return q
        return lru

    def set_cached_shares(self, shares: Dict[str, float]):
        """Install per-tenant target fractions of the cached-free set
        (ptc-pilot dynamic budgets).  Values are clamped to [0, 1]; an
        empty dict restores plain global LRU eviction."""
        clean = {str(k): min(1.0, max(0.0, float(v)))
                 for k, v in (shares or {}).items()}
        with self._lock:
            self._shares = clean

    def cached_shares(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._shares)

    def alloc(self) -> Optional[int]:
        """One page at refcount 1, or None (backpressure signal)."""
        got = self.reserve(1)
        return got[0] if got else None

    def reserve(self, n: int) -> Optional[List[int]]:
        """ATOMIC check-and-reserve of `n` pages (each refcount 1) —
        all or nothing: on shortfall every page taken so far goes back
        and None returns (the admission backpressure signal)."""
        with self._lock:
            got: List[int] = []
            for _ in range(int(n)):
                p = self._take_free_locked()
                if p is None:
                    for q in got:
                        self._refs[q] = 0
                        self._free.append(q)
                    self._counters["reserve_fails"] += 1
                    return None
                self._refs[p] = 1
                got.append(p)
            return got

    def free(self, pages: Sequence[int]):
        """Release one reference per page (see `release`)."""
        self.release(pages)

    def release(self, pages: Sequence[int]):
        """Drop one reference per page.  At refcount 0 a frozen
        (content-indexed) page parks on the cached-free LRU — content
        preserved for future prefix hits — and an unindexed page goes
        straight back to the free list."""
        with self._lock:
            for p in pages:
                p = int(p)
                assert self._refs[p] > 0, f"page {p} over-released"
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    if p in self._key_of:
                        self._cached[p] = True  # LRU tail (most recent)
                    else:
                        self._free.append(p)

    def retain(self, pages: Sequence[int]):
        """One extra reference per (already-live) page."""
        with self._lock:
            for p in pages:
                assert self._refs[int(p)] > 0
                self._refs[int(p)] += 1

    def refcount(self, p: int) -> int:
        with self._lock:
            return self._refs[int(p)]

    # ---------------------------------------------------- prefix sharing
    def freeze(self, p: int, key, owner: Optional[str] = None) -> bool:
        """Register a FULL immutable page under its content key.  First
        writer wins: a concurrent identical prefill keeps its private
        copy unindexed (False).  `owner` tags the page with the tenant
        that wrote it for share-aware eviction (`set_cached_shares`)."""
        with self._lock:
            if key in self._index or int(p) in self._key_of:
                return False
            self._index[key] = int(p)
            self._key_of[int(p)] = key
            if owner is not None:
                self._owner_of[int(p)] = str(owner)
            self._counters["frozen"] += 1
            return True

    def is_frozen(self, p: int) -> bool:
        with self._lock:
            return int(p) in self._key_of

    def probe(self, keys: Sequence) -> int:
        """Longest warm prefix (leading keys present in the index) —
        NO side effects; admission's predicted-shared-page discount."""
        with self._lock:
            n = 0
            for k in keys:
                if k not in self._index:
                    break
                n += 1
            return n

    def acquire_prefix(self, keys: Sequence,
                       n_pages: int) -> Optional[Tuple[List[int], int]]:
        """ATOMIC admission of an `n_pages` sequence whose leading full
        pages carry content `keys`: map the longest warm prefix onto
        existing frozen pages (refcount++) and reserve fresh pages for
        the cold tail.  Returns (pages, warm_count), or None with every
        side effect rolled back when the cold tail doesn't fit."""
        with self._lock:
            warm: List[int] = []
            for k in keys:
                p = self._index.get(k)
                if p is None:
                    break
                warm.append(p)
            for p in warm:
                if self._refs[p] == 0:
                    self._cached.pop(p, None)
                self._refs[p] += 1
            cold: List[int] = []
            for _ in range(n_pages - len(warm)):
                p = self._take_free_locked()
                if p is None:
                    for q in cold:
                        self._refs[q] = 0
                        self._free.append(q)
                    for q in warm:
                        self._refs[q] -= 1
                        if self._refs[q] == 0:
                            self._cached[q] = True
                    self._counters["reserve_fails"] += 1
                    return None
                self._refs[p] = 1
                cold.append(p)
            self._counters["prefix_hits"] += len(warm)
            self._counters["prefix_misses"] += len(cold)
            self._counters["shared_bytes"] += \
                len(warm) * self.bytes_per_page
            return warm + cold, len(warm)

    def make_private(self, p: int) -> Optional[int]:
        """Exclusive writable view of page `p` for its (sole calling)
        owner: when nobody else holds it, the page itself with its
        index entry dropped; otherwise a COPY-ON-WRITE clone — fresh
        page, bytes copied, caller's reference moved (old refcount--).
        Returns None when the pool can't supply the clone."""
        with self._lock:
            p = int(p)
            assert self._refs[p] > 0
            if self._refs[p] == 1:
                key = self._key_of.pop(p, None)
                if key is not None:
                    del self._index[key]
                    self._owner_of.pop(p, None)
                return p
            q = self._take_free_locked()
            if q is None:
                self._counters["reserve_fails"] += 1
                return None
            self._refs[q] = 1
            self._refs[p] -= 1  # >0: sharers remain, p stays frozen
            self._counters["cow_copies"] += 1
        # bytes copied OUTSIDE the lock: q is exclusively ours, p is
        # immutable (frozen) while its sharers hold it
        np.copyto(self.k_tile(q), self.k_tile(p))
        np.copyto(self.v_tile(q), self.v_tile(p))
        self.host_wrote(q)
        return q

    def host_wrote(self, p: int):
        """The caller rewrote page `p`'s HOST bytes directly (numpy,
        outside the runtime): any device mirror is stale and must drop
        (COW clones, speculative row staging)."""
        ctx = self._ctx
        if hasattr(ctx, "host_wrote"):
            ctx.host_wrote(self.Kc, int(p))
            ctx.host_wrote(self.Vc, int(p))

    # ------------------------------------------------- page migration
    def frozen_keys(self) -> List:
        """Snapshot of every content key currently indexed (live frozen
        pages AND cached-free ones) — the raw material for a replica's
        advertised key digest."""
        with self._lock:
            return list(self._index.keys())

    def export_frozen(self, key) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Copy a frozen page's (K, V) tiles out by content key, or None
        when the key is not indexed (e.g. just evicted — the caller
        treats it as a miss and moves on).  The page is PINNED
        (refcount++) for the out-of-lock copy, so a concurrent
        `_take_free_locked` eviction can never recycle it mid-read; the
        pin is dropped afterwards, re-parking a refcount-0 page on the
        cached LRU.  Because frozen bytes are a pure function of the
        key, the returned copy is valid forever — export is idempotent
        and migration needs no coherence protocol."""
        with self._lock:
            p = self._index.get(key)
            if p is None:
                return None
            if self._refs[p] == 0:
                self._cached.pop(p, None)
            self._refs[p] += 1
            self._counters["exported"] += 1
        k = np.array(self.k_tile(p), copy=True)
        v = np.array(self.v_tile(p), copy=True)
        self.release([p])
        return k, v

    def import_frozen(self, key, k: np.ndarray, v: np.ndarray) -> bool:
        """Install a migrated frozen page under its content key.  True =
        page written and indexed (parked refcount-0 on the cached LRU,
        warm for the next `acquire_prefix`, evictable under pressure);
        False = the key was already held (or won a concurrent race to
        the index) — ZERO page bytes written, `import_dups` counted.
        Idempotent by construction: the key determines the bytes, so a
        duplicate import has nothing to add."""
        with self._lock:
            if key in self._index:
                self._counters["import_dups"] += 1
                return False
            p = self._take_free_locked()
            if p is None:
                self._counters["reserve_fails"] += 1
                return False
            self._refs[p] = 1  # private until frozen: invisible to probes
        np.copyto(self.k_tile(p), np.asarray(k, dtype=self.dtype))
        np.copyto(self.v_tile(p), np.asarray(v, dtype=self.dtype))
        self.host_wrote(p)
        if not self.freeze(p, key):
            # lost a first-writer race since the check above: the winner
            # holds identical bytes, ours goes straight back (unindexed)
            self.release([p])
            with self._lock:
                self._counters["import_dups"] += 1
            return False
        with self._lock:
            self._counters["imported"] += 1
            self._counters["migrated_in_bytes"] += self.bytes_per_page
        self.release([p])  # refcount 0 + indexed -> cached-free LRU
        return True

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        """Prefix-cache counter snapshot (stats()["serve"]["prefix"])."""
        with self._lock:
            out = dict(self._counters)
            out["n_pages"] = self.n_pages
            out["free"] = len(self._free)
            out["cached_free"] = len(self._cached)
            out["frozen_live"] = len(self._key_of)
            out["shared_refs"] = sum(
                r - 1 for p, r in enumerate(self._refs)
                if r > 1 and p in self._key_of)
            by_owner: Dict[str, int] = {}
            for q in self._cached:
                o = self._owner_of.get(q, "")
                by_owner[o] = by_owner.get(o, 0) + 1
            out["cached_by_owner"] = by_owner
            out["shares"] = dict(self._shares)
            hits, misses = out["prefix_hits"], out["prefix_misses"]
            out["hit_rate"] = (hits / (hits + misses)
                               if hits + misses else 0.0)
            return out

    def k_tile(self, p: int) -> np.ndarray:
        return self.Kc.tile(p, 0)

    def v_tile(self, p: int) -> np.ndarray:
        return self.Vc.tile(p, 0)


def make_slot_collections(ctx, max_seqs: int, d: int, name: str = "PA",
                          nh: int = 1, nodes: int = 1, myrank: int = 0):
    """Per-slot scratch collections for `max_seqs` concurrent sequences:
    Qc (1, d) query rows, ACCc (1, d+2*nh) online-softmax accumulators
    ([acc | m_0..m_{nh-1} | l_0..l_{nh-1}]), Oc (1, d) attention
    outputs, KNc (1, 2d) the new token's k|v rows.  Registered as
    {name}_{Q,ACC,O,KN}.  `nh` is the number of attention heads held
    locally (each with its own softmax state); with nodes > 1 the
    collections are rank-replicated (tensor-parallel shard scratch)."""
    aw = d + 2 * nh

    def mk(rows, cols):
        if nodes > 1:
            return ReplicatedLocal(rows, cols, 1, cols, nodes=nodes,
                                   myrank=myrank, dtype=np.float32)
        return TwoDimBlockCyclic(rows, cols, 1, cols, dtype=np.float32)

    Qc = mk(max_seqs, d)
    ACCc = mk(max_seqs, aw)
    Oc = mk(max_seqs, d)
    KNc = mk(max_seqs, 2 * d)
    names = {}
    for suffix, coll in (("Q", Qc), ("ACC", ACCc), ("O", Oc), ("KN", KNc)):
        n = f"{name}_{suffix}"
        coll.register(ctx, n)
        names[suffix] = n
    return Qc, ACCc, Oc, KNc, names


# ------------------------------------------------------ shared fold math
def attend_page(q: np.ndarray, K: np.ndarray, V: np.ndarray,
                acc: np.ndarray, m: float, l: float, scale: float):
    """One online-softmax fold of `rows` K/V rows into (acc, m, l).
    Pure f32 with a FIXED op order — the single definition both the DAG
    bodies and the numpy reference call, so batched and sequential runs
    are bit-identical."""
    q = q.astype(np.float32, copy=False)
    s = (K.astype(np.float32, copy=False) @ q) * np.float32(scale)
    m_new = np.float32(max(np.float32(m), np.float32(s.max())))
    p = np.exp((s - m_new).astype(np.float32))
    corr = np.float32(np.exp(np.float32(m) - m_new))
    l_new = np.float32(l) * corr + np.float32(p.sum(dtype=np.float32))
    acc_new = acc.astype(np.float32, copy=False) * corr + \
        p @ V.astype(np.float32, copy=False)
    return acc_new.astype(np.float32), m_new, np.float32(l_new)


def finalize_attention(acc: np.ndarray, l: float) -> np.ndarray:
    return (acc / np.float32(max(float(l), 1e-30))).astype(np.float32)


_NEG_BIG = np.float32(-1.0e30)


def _acc_unpack(tile: np.ndarray):
    d = tile.shape[1] - 2
    return tile[0, :d], np.float32(tile[0, d]), np.float32(tile[0, d + 1])


def _acc_pack(tile: np.ndarray, acc: np.ndarray, m, l):
    d = tile.shape[1] - 2
    tile[0, :d] = acc
    tile[0, d] = m
    tile[0, d + 1] = l


def reset_acc(tile: np.ndarray, nh: int = 1):
    """Accumulator tile initial value: acc=0, m=-big, l=0 (per head)."""
    dl = tile.shape[1] - 2 * nh
    tile[...] = 0.0
    tile[0, dl:dl + nh] = _NEG_BIG


def attend_heads(q: np.ndarray, K: np.ndarray, V: np.ndarray,
                 at: np.ndarray, scale: float, nh: int,
                 rows: Optional[int] = None):
    """Fold K/V `rows` into the packed `nh`-head accumulator tile IN
    PLACE (layout [acc | m_0.. | l_0..], width dl + 2*nh).  Each head's
    fold is one `attend_page` on CONTIGUOUS per-head operands — slices
    are materialized before BLAS sees them, so the fold's f32 op
    sequence is a function of (head values, rows, dh) only, never of
    how many ranks the heads happen to be split over: per-head outputs
    are bit-identical across tp degrees.  nh=1 degenerates to exactly
    the single-softmax fold the non-sharded builders always ran."""
    dl = q.shape[0]
    dh = dl // nh
    if rows is not None:
        K = K[:rows]
        V = V[:rows]
    for h in range(nh):
        sl = slice(h * dh, (h + 1) * dh)
        acc, m, l = attend_page(
            np.ascontiguousarray(q[sl]),
            np.ascontiguousarray(K[:, sl]),
            np.ascontiguousarray(V[:, sl]),
            np.ascontiguousarray(at[0, sl]),
            np.float32(at[0, dl + h]), np.float32(at[0, dl + nh + h]),
            scale)
        at[0, sl] = acc
        at[0, dl + h] = m
        at[0, dl + nh + h] = l


def finalize_heads(at: np.ndarray, nh: int) -> np.ndarray:
    """Per-head finalize of a packed accumulator tile -> (dl,) output."""
    dl = at.shape[1] - 2 * nh
    dh = dl // nh
    out = np.empty(dl, np.float32)
    for h in range(nh):
        out[h * dh:(h + 1) * dh] = finalize_attention(
            np.ascontiguousarray(at[0, h * dh:(h + 1) * dh]),
            np.float32(at[0, dl + nh + h]))
    return out


# ----------------------------------------------------------- seq specs
class SeqSpec:
    """One sequence's view of a decode step (or prefill):
      slot    scratch-collection row (Q/ACC/O/KN index)
      pages   page ids, oldest first; the LAST page receives the new row
      fill    decode: row index the new token lands in (valid rows after
              the step = fill + 1); prefill: rows already written is 0
              and fill = rows used in the last page AFTER the prompt
    """

    __slots__ = ("slot", "pages", "fill")

    def __init__(self, slot: int, pages: Sequence[int], fill: int):
        self.slot = int(slot)
        self.pages = [int(p) for p in pages]
        self.fill = int(fill)
        assert self.pages, "a sequence owns at least one page"
        assert 0 <= self.fill


def _tables(seqs: Sequence[SeqSpec]):
    slot = [s.slot for s in seqs]
    pages = [list(s.pages) for s in seqs]
    nfro = [len(s.pages) - 1 for s in seqs]
    last = [s.pages[-1] for s in seqs]
    fill = [s.fill for s in seqs]
    return slot, pages, nfro, last, fill


def _wire_shard(ctx, tp, classes, prod_class: str, nseg: int, shard: dict):
    """Tensor-parallel shard wiring (ptc-shard).  The pool is built SPMD
    on every rank of the tp group: `classes` are anchored on THIS rank
    (rank-replicated shard compute — each rank folds its own head slice
    of every sequence), and a RefReduce all-reduce chain is embedded in
    the SAME taskpool to sum the per-rank partial pre-logit projections.
    Contributions enter the ptc_coll_* steps slice-granularly as each
    sequence's last fold completes, so the wire starts after the FIRST
    sequence's shard is done and overlaps the remaining per-head
    compute.  `shard` keys:

      rank     this rank (affinity anchor + contributor-id base)
      nranks   tp degree R (every rank contributes one partial per seq)
      dm       full model dim — the (dm,) reduction payload
      sink     fanout_sink(seg, slc, x): reduced pre-logits, delivered
               ON EVERY RANK (bcast=True) for SPMD next-token selection
      topo     optional reduce/fanout topology override

    Returns (rr, cid_of): the caller declares the producer "PL" flow
    with `*rr.producer_out_deps(cid_of)` on rr.arena."""
    import parsec_tpu as pt
    from ..comm.coll import RefReduce, rank_affinity_collection

    R = max(1, int(shard.get("nranks", 1)))
    rk = int(shard.get("rank", 0))
    dm = int(shard["dm"])
    rankc = rank_affinity_collection(ctx)
    my = pt.call(lambda l, g, r=rk: r, pure=True)
    for cls in classes:
        cls.affinity(rankc, my)
    rr = RefReduce(
        ctx, tp, nseg,
        contributors_of=lambda seg, R=R, n=nseg:
            [(r, r * n + seg) for r in range(R)],
        root_of=lambda seg, R=R: seg % R,
        prod_class=prod_class, prod_flow="PL", prod_nparams=1,
        prod_params_of=lambda cid, n=nseg: (cid % n,),
        arena_bytes=dm * 4, dtype=np.float32, op="sum",
        topo=shard.get("topo"), bcast=True,
        fanout_sink=shard.get("sink"))

    def cid_of(l, g, rk=rk, n=nseg):
        return rk * n + l[0]

    return rr, cid_of


# ------------------------------------------------------------- builders
def build_paged_decode(ctx, pool: PagePool, seqs: Sequence[SeqSpec],
                       coll_names: Dict[str, str], *, scale: float = None,
                       priority: Optional[int] = None,
                       weight: Optional[int] = None,
                       body_wrap: Optional[Callable] = None,
                       dev=None, nh: int = 1,
                       shard: Optional[dict] = None):
    """One continuous-batching DECODE step over `seqs` as a taskpool
    (created with the given per-pool QoS priority/weight — the tenant
    knobs).  Per sequence: PUPD appends the KN row into the last page,
    PATTF folds each frozen page, PATTL folds the updated last page and
    writes O.  `body_wrap` wraps the PATTL body (fault-injection seam
    for the watchdog e2e).  With `dev`, the page-fold classes attach
    device chores (per-task shapes are uniform: whole pages).

    `nh` heads live locally (packed accumulator, per-head softmax);
    with `shard` (see _wire_shard) the classes anchor on this rank and
    PATTL additionally projects its head-slice output through the
    rank's wo rows, feeding the embedded ptc_coll_* all-reduce."""
    import parsec_tpu as pt

    d, P = pool.d, pool.page
    aw = d + 2 * nh
    sc = (d ** -0.5) if scale is None else float(scale)
    slot_t, pages_t, nfro_t, last_t, fill_t = _tables(seqs)
    qn, an, on, kn = (coll_names["Q"], coll_names["ACC"], coll_names["O"],
                      coll_names["KN"])

    tp = ctx.taskpool(globals={"NS": len(seqs) - 1}, priority=priority,
                      weight=weight)
    s = pt.L("s")
    j = pt.L("j")
    c_slot = pt.call(lambda locs, g: slot_t[locs[0]], pure=True)
    c_nfro = pt.call(lambda locs, g: nfro_t[locs[0]], pure=True)
    c_last = pt.call(lambda locs, g: last_t[locs[0]], pure=True)
    c_page = pt.call(lambda locs, g: pages_t[locs[0]][locs[1]], pure=True)

    upd = tp.task_class("PUPD")
    upd.param("s", 0, pt.G("NS"))
    upd.flow("KN", "READ", pt.In(pt.Mem(kn, c_slot, 0)))
    upd.flow("KP", "RW", pt.In(pt.Mem(pool.k_name, c_last, 0)),
             pt.Out(pt.Mem(pool.k_name, c_last, 0)),
             pt.Out(pt.Ref("PATTL", s, flow="KP")))
    upd.flow("VP", "RW", pt.In(pt.Mem(pool.v_name, c_last, 0)),
             pt.Out(pt.Mem(pool.v_name, c_last, 0)),
             pt.Out(pt.Ref("PATTL", s, flow="VP")))

    def upd_body(v):
        si = v["s"]
        knrow = v.data("KN", np.float32, (1, 2 * d))
        kp = v.data("KP", np.float32, (P, d))
        vp = v.data("VP", np.float32, (P, d))
        row = fill_t[si]
        kp[row] = knrow[0, :d]
        vp[row] = knrow[0, d:]

    upd.body(upd_body, pure=True)

    fro = tp.task_class("PATTF")
    fro.param("s", 0, pt.G("NS"))
    fro.param("j", 0, c_nfro - 1)  # empty range when the seq has 1 page
    fro.flow("Q", "READ", pt.In(pt.Mem(qn, c_slot, 0)))
    fro.flow("KP", "READ", pt.In(pt.Mem(pool.k_name, c_page, 0)))
    fro.flow("VP", "READ", pt.In(pt.Mem(pool.v_name, c_page, 0)))
    fro.flow("ACC", "RW",
             pt.In(pt.Mem(an, c_slot, 0), guard=(j == 0)),
             pt.In(pt.Ref("PATTF", s, j - 1, flow="ACC")),
             pt.Out(pt.Ref("PATTF", s, j + 1, flow="ACC"),
                    guard=(j < c_nfro - 1)),
             pt.Out(pt.Ref("PATTL", s, flow="ACC"),
                    guard=(j == c_nfro - 1)))

    if dev is not None:
        # device chore FIRST (the runtime takes the first enabled
        # chore): frozen-page folds are shape-uniform (whole pages) —
        # KV pages stage through the residency planner like any other
        # tile.  PUPD/PATTL stay host (per-task ragged row counts).
        def k_fold(qb, kb, vb, ab):
            if nh == 1:
                return _fold_kernel(qb, kb, vb, ab, sc)
            return _fold_kernel_heads(qb, kb, vb, ab, sc, nh)

        dev.attach(fro, tp, kernel=k_fold, reads=["Q", "KP", "VP", "ACC"],
                   writes=["ACC"],
                   shapes={"Q": (1, d), "KP": (P, d), "VP": (P, d),
                           "ACC": (1, aw)},
                   dtype=np.float32, batch=False)

    def fro_body(v):
        q = v.data("Q", np.float32, (1, d))[0]
        K = v.data("KP", np.float32, (P, d))
        V = v.data("VP", np.float32, (P, d))
        at = v.data("ACC", np.float32, (1, aw))
        attend_heads(q, K, V, at, sc, nh)

    fro.body(fro_body, pure=True)

    lst = tp.task_class("PATTL")
    lst.param("s", 0, pt.G("NS"))
    lst.flow("Q", "READ", pt.In(pt.Mem(qn, c_slot, 0)))
    lst.flow("KP", "READ", pt.In(pt.Ref("PUPD", s, flow="KP")))
    lst.flow("VP", "READ", pt.In(pt.Ref("PUPD", s, flow="VP")))
    # chain tail when frozen pages exist; ACC memory slot otherwise —
    # selection rides the producer domain (PATTF(s, -1) does not exist),
    # not a dynamic guard: the counting path stays exact
    lst.flow("ACC", "RW",
             pt.In(pt.Ref("PATTF", s, c_nfro - 1, flow="ACC")),
             pt.In(pt.Mem(an, c_slot, 0)))
    lst.flow("O", "RW", pt.In(pt.Mem(on, c_slot, 0)),
             pt.Out(pt.Mem(on, c_slot, 0)))

    rr = None
    if shard is not None:
        rr, cid_of = _wire_shard(ctx, tp, (upd, fro, lst), "PATTL",
                                 len(seqs), shard)
        lst.flow("PL", "W", *rr.producer_out_deps(cid_of), arena=rr.arena)
        dm = int(shard["dm"])
        project = shard["project"]
        mark = shard.get("local")

    def lst_body(v):
        si = v["s"]
        rows = fill_t[si] + 1  # old rows + the row PUPD just wrote
        q = v.data("Q", np.float32, (1, d))[0]
        K = v.data("KP", np.float32, (P, d))
        V = v.data("VP", np.float32, (P, d))
        at = v.data("ACC", np.float32, (1, aw))
        attend_heads(q, K, V, at, sc, nh, rows=rows)
        o = finalize_heads(at, nh)
        v.data("O", np.float32, (1, d))[0] = o
        if shard is not None:
            v.data("PL", np.float32)[:dm] = project(o)
            if mark is not None:
                mark(si)

    if body_wrap:
        lst.body(body_wrap(lst_body))
    elif shard is not None:
        lst.body(lst_body)
    else:
        lst.body(lst_body, pure=True)
    return tp


def build_paged_verify(ctx, pool: PagePool, seqs: Sequence[SeqSpec],
                       coll_names: Dict[str, str], *, scale: float = None,
                       priority: Optional[int] = None,
                       weight: Optional[int] = None,
                       body_wrap: Optional[Callable] = None,
                       dev=None, nh: int = 1,
                       shard: Optional[dict] = None):
    """Speculative-decoding VERIFY WAVE: every page of every sequence
    is already materialized in the KV collections (the shared frozen
    prefix plus host-staged private query-window pages), so the pool is
    pure fold chains — VATF(s, j) folds frozen page j, VATL(s) folds
    the last page to `fill` rows and writes O.  One virtual sequence
    per (real sequence, query position): the engine flattens a k-token
    draft window into k+1 of these, and the resulting VATF wave is
    HOMOGENEOUS — with a device attached it carries the same
    shape-uniform chore as decode's PATTF, so the PR 13 wave compiler
    certifies it and the whole batched verification dispatches as one
    fused launch.  Fold math and page blocking are `attend_page` with
    the decode builder's exact operand split: a verified position's
    output is BIT-IDENTICAL to the sequential decode step's.

    `nh`/`shard` as in build_paged_decode: per-head fold state, and the
    tensor-parallel rank anchoring + embedded partial-projection
    all-reduce (producer VATL)."""
    import parsec_tpu as pt

    d, P = pool.d, pool.page
    aw = d + 2 * nh
    sc = (d ** -0.5) if scale is None else float(scale)
    slot_t, pages_t, nfro_t, last_t, fill_t = _tables(seqs)
    qn, an, on = coll_names["Q"], coll_names["ACC"], coll_names["O"]

    tp = ctx.taskpool(globals={"NS": len(seqs) - 1}, priority=priority,
                      weight=weight)
    s = pt.L("s")
    j = pt.L("j")
    c_slot = pt.call(lambda locs, g: slot_t[locs[0]], pure=True)
    c_nfro = pt.call(lambda locs, g: nfro_t[locs[0]], pure=True)
    c_last = pt.call(lambda locs, g: last_t[locs[0]], pure=True)
    c_page = pt.call(lambda locs, g: pages_t[locs[0]][locs[1]], pure=True)

    fro = tp.task_class("VATF")
    fro.param("s", 0, pt.G("NS"))
    fro.param("j", 0, c_nfro - 1)
    fro.flow("Q", "READ", pt.In(pt.Mem(qn, c_slot, 0)))
    fro.flow("KP", "READ", pt.In(pt.Mem(pool.k_name, c_page, 0)))
    fro.flow("VP", "READ", pt.In(pt.Mem(pool.v_name, c_page, 0)))
    fro.flow("ACC", "RW",
             pt.In(pt.Mem(an, c_slot, 0), guard=(j == 0)),
             pt.In(pt.Ref("VATF", s, j - 1, flow="ACC")),
             pt.Out(pt.Ref("VATF", s, j + 1, flow="ACC"),
                    guard=(j < c_nfro - 1)),
             pt.Out(pt.Ref("VATL", s, flow="ACC"),
                    guard=(j == c_nfro - 1)))

    if dev is not None:
        # same shape-uniform fold as decode's PATTF, but declared
        # BATCHABLE (the kernel is elementwise over whole-page tiles):
        # a homogeneous VATF wave certifies under the PR 13 wave
        # compiler and the entire batched verification dispatches as
        # ONE fused launch — in tp mode each rank certifies and fuses
        # ITS OWN shard of the wave (the per-rank fused_waves count)
        def k_fold(qb, kb, vb, ab):
            if nh == 1:
                return _fold_kernel(qb, kb, vb, ab, sc)
            return _fold_kernel_heads(qb, kb, vb, ab, sc, nh)

        dev.attach(fro, tp, kernel=k_fold, reads=["Q", "KP", "VP", "ACC"],
                   writes=["ACC"],
                   shapes={"Q": (1, d), "KP": (P, d), "VP": (P, d),
                           "ACC": (1, aw)},
                   dtype=np.float32, batch=True)

    def fro_body(v):
        q = v.data("Q", np.float32, (1, d))[0]
        K = v.data("KP", np.float32, (P, d))
        V = v.data("VP", np.float32, (P, d))
        at = v.data("ACC", np.float32, (1, aw))
        attend_heads(q, K, V, at, sc, nh)

    fro.body(fro_body, pure=True)

    lst = tp.task_class("VATL")
    lst.param("s", 0, pt.G("NS"))
    lst.flow("Q", "READ", pt.In(pt.Mem(qn, c_slot, 0)))
    lst.flow("KP", "READ", pt.In(pt.Mem(pool.k_name, c_last, 0)))
    lst.flow("VP", "READ", pt.In(pt.Mem(pool.v_name, c_last, 0)))
    lst.flow("ACC", "RW",
             pt.In(pt.Ref("VATF", s, c_nfro - 1, flow="ACC")),
             pt.In(pt.Mem(an, c_slot, 0)))
    lst.flow("O", "RW", pt.In(pt.Mem(on, c_slot, 0)),
             pt.Out(pt.Mem(on, c_slot, 0)))

    rr = None
    if shard is not None:
        rr, cid_of = _wire_shard(ctx, tp, (fro, lst), "VATL",
                                 len(seqs), shard)
        lst.flow("PL", "W", *rr.producer_out_deps(cid_of), arena=rr.arena)
        dm = int(shard["dm"])
        project = shard["project"]
        mark = shard.get("local")

    def lst_body(v):
        si = v["s"]
        rows = fill_t[si]
        q = v.data("Q", np.float32, (1, d))[0]
        K = v.data("KP", np.float32, (P, d))
        V = v.data("VP", np.float32, (P, d))
        at = v.data("ACC", np.float32, (1, aw))
        attend_heads(q, K, V, at, sc, nh, rows=rows)
        o = finalize_heads(at, nh)
        v.data("O", np.float32, (1, d))[0] = o
        if shard is not None:
            v.data("PL", np.float32)[:dm] = project(o)
            if mark is not None:
                mark(si)

    if body_wrap:
        lst.body(body_wrap(lst_body))
    elif shard is not None:
        lst.body(lst_body)
    else:
        lst.body(lst_body, pure=True)
    return tp


def _fold_kernel(qb, kb, vb, ab, sc):
    """jnp form of attend_page for the device chore (frozen pages)."""
    import jax.numpy as jnp
    d = qb.shape[1]
    acc, m, l = ab[0, :d], ab[0, d], ab[0, d + 1]
    s = (kb @ qb[0]) * sc
    m_new = jnp.maximum(m, s.max())
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum()
    acc_new = acc * corr + p @ vb
    return jnp.concatenate([acc_new, m_new[None], l_new[None]])[None, :]


def _fold_kernel_heads(qb, kb, vb, ab, sc, nh):
    """jnp form of attend_heads: `nh` statically-unrolled per-head folds
    over the packed (1, dl + 2*nh) accumulator — the _fold_kernel op
    sequence applied to each head's contiguous slice."""
    import jax.numpy as jnp
    dl = qb.shape[1]
    dh = dl // nh
    outs, ms, ls = [], [], []
    for h in range(nh):
        sl = slice(h * dh, (h + 1) * dh)
        acc, m, l = ab[0, sl], ab[0, dl + h], ab[0, dl + nh + h]
        s = (kb[:, sl] @ qb[0, sl]) * sc
        m_new = jnp.maximum(m, s.max())
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        ls.append((l * corr + p.sum())[None])
        outs.append(acc * corr + p @ vb[:, sl])
        ms.append(m_new[None])
    return jnp.concatenate(outs + ms + ls)[None, :]


def build_paged_prefill(ctx, pool: PagePool, seqs: Sequence[SeqSpec],
                        coll_names: Dict[str, str], prompt_name: str,
                        prompt_tiles: Sequence[Sequence[int]], *,
                        scale: float = None,
                        priority: Optional[int] = None,
                        weight: Optional[int] = None,
                        body_wrap: Optional[Callable] = None,
                        warm: Optional[Sequence[int]] = None,
                        nh: int = 1, shard: Optional[dict] = None):
    """PREFILL as a taskpool: PFILL(s, j) writes page j of sequence s
    from the staged prompt collection (`prompt_name`, one (page, 2d)
    k|v tile per written page, indices in `prompt_tiles[s][j]`), then
    the PATTF/PATTL fold chain computes attention for the LAST prompt
    position over all written rows.  `seqs[i].fill` is the row count
    used in the last page (1..page).

    `warm[i]` (prefix cache, ptc-share) marks the first `warm[i]` pages
    of sequence i as ALREADY MATERIALIZED shared frozen pages: PFILL's
    domain starts at the cold tail, and the fold chain reads warm pages
    straight from the KV collections — selection rides the producer
    domain (PFILL(s, j<warm) does not exist), not dynamic guards, so
    input counting stays verifier-exact.  A fully-warm sequence
    prefills ZERO pages and still folds its whole cache.

    `nh`/`shard` as in build_paged_decode: in tp mode every rank
    prefills its own head-slice pages and the first generated token's
    partial projection rides the embedded all-reduce (producer
    PATTL)."""
    import parsec_tpu as pt

    d, P = pool.d, pool.page
    aw = d + 2 * nh
    sc = (d ** -0.5) if scale is None else float(scale)
    slot_t, pages_t, nfro_t, last_t, fill_t = _tables(seqs)
    ptiles = [list(row) for row in prompt_tiles]
    warm_t = [0] * len(seqs) if warm is None else [int(w) for w in warm]
    assert len(warm_t) == len(seqs)
    qn, an, on = coll_names["Q"], coll_names["ACC"], coll_names["O"]

    tp = ctx.taskpool(globals={"NS": len(seqs) - 1}, priority=priority,
                      weight=weight)
    s = pt.L("s")
    j = pt.L("j")
    c_slot = pt.call(lambda locs, g: slot_t[locs[0]], pure=True)
    c_nfro = pt.call(lambda locs, g: nfro_t[locs[0]], pure=True)
    c_npag = pt.call(lambda locs, g: nfro_t[locs[0]], pure=True)
    c_warm = pt.call(lambda locs, g: warm_t[locs[0]], pure=True)
    c_last = pt.call(lambda locs, g: last_t[locs[0]], pure=True)
    c_page = pt.call(lambda locs, g: pages_t[locs[0]][locs[1]], pure=True)
    c_ptile = pt.call(lambda locs, g: ptiles[locs[0]][locs[1]], pure=True)

    fil = tp.task_class("PFILL")
    fil.param("s", 0, pt.G("NS"))
    fil.param("j", c_warm, c_npag)  # cold tail: warm..npages-1
    fil.flow("SRC", "READ", pt.In(pt.Mem(prompt_name, c_ptile, 0)))
    fil.flow("KP", "RW", pt.In(pt.Mem(pool.k_name, c_page, 0)),
             pt.Out(pt.Mem(pool.k_name, c_page, 0)),
             pt.Out(pt.Ref("PATTF", s, j, flow="KP"),
                    guard=(j < c_nfro)),
             pt.Out(pt.Ref("PATTL", s, flow="KP"),
                    guard=(j == c_nfro)))
    fil.flow("VP", "RW", pt.In(pt.Mem(pool.v_name, c_page, 0)),
             pt.Out(pt.Mem(pool.v_name, c_page, 0)),
             pt.Out(pt.Ref("PATTF", s, j, flow="VP"),
                    guard=(j < c_nfro)),
             pt.Out(pt.Ref("PATTL", s, flow="VP"),
                    guard=(j == c_nfro)))

    def fil_body(v):
        si = v["s"]
        rows = P if v["j"] < nfro_t[si] else fill_t[si]
        src = v.data("SRC", np.float32, (P, 2 * d))
        kp = v.data("KP", np.float32, (P, d))
        vp = v.data("VP", np.float32, (P, d))
        kp[:rows] = src[:rows, :d]
        vp[:rows] = src[:rows, d:]

    fil.body(fil_body, pure=True)

    fro = tp.task_class("PATTF")
    fro.param("s", 0, pt.G("NS"))
    fro.param("j", 0, c_nfro - 1)
    fro.flow("Q", "READ", pt.In(pt.Mem(qn, c_slot, 0)))
    # cold pages arrive from PFILL through the DAG; warm (shared frozen)
    # pages fall back to the KV collection datum — PFILL(s, j < warm)
    # is out of the producer domain, so selection stays exact
    fro.flow("KP", "READ", pt.In(pt.Ref("PFILL", s, j, flow="KP")),
             pt.In(pt.Mem(pool.k_name, c_page, 0)))
    fro.flow("VP", "READ", pt.In(pt.Ref("PFILL", s, j, flow="VP")),
             pt.In(pt.Mem(pool.v_name, c_page, 0)))
    fro.flow("ACC", "RW",
             pt.In(pt.Mem(an, c_slot, 0), guard=(j == 0)),
             pt.In(pt.Ref("PATTF", s, j - 1, flow="ACC")),
             pt.Out(pt.Ref("PATTF", s, j + 1, flow="ACC"),
                    guard=(j < c_nfro - 1)),
             pt.Out(pt.Ref("PATTL", s, flow="ACC"),
                    guard=(j == c_nfro - 1)))

    def fro_body(v):
        q = v.data("Q", np.float32, (1, d))[0]
        K = v.data("KP", np.float32, (P, d))
        V = v.data("VP", np.float32, (P, d))
        at = v.data("ACC", np.float32, (1, aw))
        attend_heads(q, K, V, at, sc, nh)

    fro.body(fro_body, pure=True)

    lst = tp.task_class("PATTL")
    lst.param("s", 0, pt.G("NS"))
    lst.flow("Q", "READ", pt.In(pt.Mem(qn, c_slot, 0)))
    # a fully-warm sequence's LAST page is shared too: Mem fallback
    lst.flow("KP", "READ", pt.In(pt.Ref("PFILL", s, c_nfro, flow="KP")),
             pt.In(pt.Mem(pool.k_name, c_last, 0)))
    lst.flow("VP", "READ", pt.In(pt.Ref("PFILL", s, c_nfro, flow="VP")),
             pt.In(pt.Mem(pool.v_name, c_last, 0)))
    lst.flow("ACC", "RW",
             pt.In(pt.Ref("PATTF", s, c_nfro - 1, flow="ACC")),
             pt.In(pt.Mem(an, c_slot, 0)))
    lst.flow("O", "RW", pt.In(pt.Mem(on, c_slot, 0)),
             pt.Out(pt.Mem(on, c_slot, 0)))

    rr = None
    if shard is not None:
        rr, cid_of = _wire_shard(ctx, tp, (fil, fro, lst), "PATTL",
                                 len(seqs), shard)
        lst.flow("PL", "W", *rr.producer_out_deps(cid_of), arena=rr.arena)
        dm = int(shard["dm"])
        project = shard["project"]
        mark = shard.get("local")

    def lst_body(v):
        si = v["s"]
        rows = fill_t[si]
        q = v.data("Q", np.float32, (1, d))[0]
        K = v.data("KP", np.float32, (P, d))
        V = v.data("VP", np.float32, (P, d))
        at = v.data("ACC", np.float32, (1, aw))
        attend_heads(q, K, V, at, sc, nh, rows=rows)
        o = finalize_heads(at, nh)
        v.data("O", np.float32, (1, d))[0] = o
        if shard is not None:
            v.data("PL", np.float32)[:dm] = project(o)
            if mark is not None:
                mark(si)

    if body_wrap:
        lst.body(body_wrap(lst_body))
    elif shard is not None:
        lst.body(lst_body)
    else:
        lst.body(lst_body, pure=True)
    return tp
