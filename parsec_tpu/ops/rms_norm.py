"""Pallas fused RMSNorm: one VMEM pass per row block computes the
mean-square, normalizes, and applies the scale — the elementwise+
reduction chain XLA would otherwise split across HBM round trips on the
boundary of fusion clusters.  Second hand-written device kernel next to
ops/flash_attention.py (reference contrast: hand-written cuBLAS/cuDNN
kernels dyld'd per chore, device_cuda_module.c:175).

Forward is the fused Pallas kernel; backward is plain jnp through a
custom VJP (the backward chain is matmul-shaped and XLA already fuses it
well — fusing the forward is where the win is)."""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...]
    ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                  keepdims=True)
    r = jax.lax.rsqrt(ms + eps)
    o_ref[...] = (x.astype(jnp.float32) * r).astype(x.dtype) * w_ref[...]


def _rms_fwd_pallas(x2d, w, eps, block_rows, interpret):
    n, d = x2d.shape
    grid = (n // block_rows,)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
                  pl.BlockSpec((d,), lambda i: (0,))],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2d.dtype),
        interpret=interpret,
    )(x2d, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rms(x2d, w, eps, block_rows, interpret):
    return _rms_fwd_pallas(x2d, w, eps, block_rows, interpret)


def _rms_vjp_fwd(x2d, w, eps, block_rows, interpret):
    return _rms_fwd_pallas(x2d, w, eps, block_rows, interpret), (x2d, w)


def _rms_vjp_bwd(eps, block_rows, interpret, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    r = jax.lax.rsqrt(ms + eps)
    xhat = xf * r
    gw = gf * wf
    d = x.shape[-1]
    # dx = r*gw - x * (sum(gw*x)/d) * r^3   (d/dx of x*rsqrt(mean x^2))
    dx = r * gw - xf * (jnp.sum(gw * xf, axis=-1, keepdims=True) / d) \
        * (r ** 3)
    dw = jnp.sum(gf * xhat, axis=0)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_rms.defvjp(_rms_vjp_fwd, _rms_vjp_bwd)


def rms_norm(x, w, eps: float = 1e-6, block_rows: int = 128,
             interpret: Optional[bool] = None):
    """y = x / sqrt(mean(x^2, -1) + eps) * w over the last dim.

    Any leading shape; `interpret=None` auto-selects (Mosaic on TPU,
    interpreter elsewhere).  Falls back to plain jnp when the row count
    doesn't fill one block, or when the last dim violates the TPU lane
    tiling (d % 128) — Mosaic would reject the kernel on hardware even
    though interpret mode happily runs it."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    d = x.shape[-1]
    lead = x.shape[:-1]
    n = 1
    for s in lead:
        n *= s
    if n % block_rows or d % 128:
        ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                      keepdims=True)
        return (x.astype(jnp.float32)
                * jax.lax.rsqrt(ms + eps)).astype(x.dtype) * w
    out = _rms(x.reshape(n, d), w, eps, block_rows, interpret)
    return out.reshape(*lead, d)


# ------------------------------------------------------------ PTG builder
def build_rms_norm(ctx, Xc, Wc, Oc, eps: float = 1e-6, dev=None,
                   names=("RNX", "RNW", "RNO")):
    """Tile-granular RMSNorm as a PTG taskpool: NORM(r) normalizes row
    tile r of `Xc` against the shared scale tile `Wc` into `Oc` —
    the runtime-task form of this op (one task per row block, fully
    parallel), so norm layers compose with other tile DAGs instead of
    leaving the runtime for a whole-array XLA call.

    Xc/Oc: (R*T, d) collections tiled (T, d); Wc: one (1, d) tile.
    Registers the collections under `names`.  With `dev`, the chore is
    the fused Pallas kernel (rms_norm); the CPU body is the numpy
    reference."""
    import numpy as np

    import parsec_tpu as pt

    assert Xc.mt == Oc.mt and Xc.mb == Oc.mb and Xc.nb == Oc.nb
    xn, wn, on = names
    Xc.register(ctx, xn)
    Wc.register(ctx, wn)
    Oc.register(ctx, on)
    tp = pt.Taskpool(ctx, globals={"R": Xc.mt - 1})
    r = pt.L("r")
    shp = (Xc.mb, Xc.nb)
    wshp = (Wc.mb, Wc.nb)
    dt = Xc.dtype

    tc = tp.task_class("NORM")
    tc.param("r", 0, pt.G("R"))
    tc.affinity(xn, r, 0)
    tc.flow("X", "READ", pt.In(pt.Mem(xn, r, 0)))
    tc.flow("W", "READ", pt.In(pt.Mem(wn, 0, 0)))
    tc.flow("O", "RW", pt.In(pt.Mem(on, r, 0)),
            pt.Out(pt.Mem(on, r, 0)))

    if dev is not None:
        def k_norm(x, w):
            return rms_norm(x, w[0], eps)

        dev.attach(tc, tp, kernel=k_norm, reads=["X", "W"],
                   writes=["O"],
                   shapes={"X": shp, "W": wshp, "O": shp}, dtype=dt)

    def body(t):
        x = t.data("X", dt, shp).astype(np.float32)
        w = t.data("W", dt, wshp)[0].astype(np.float32)
        o = t.data("O", dt, shp)
        ms = np.mean(np.square(x), axis=-1, keepdims=True)
        o[...] = (x / np.sqrt(ms + eps) * w).astype(dt)

    tc.body(body, pure=True)  # pure tile chore: fusion-eligible
    return tp
