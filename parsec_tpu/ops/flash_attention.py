"""Flash attention as a Pallas TPU kernel.

The hot op of the transformer family (models/transformer.py) and the
per-block compute of ring attention (parallel/ring_attention.py).  One
fused kernel computes softmax(QK^T * scale [+ causal mask]) V blockwise
with the online-softmax recurrence held in VMEM scratch — no [L, L]
score matrix ever materializes in HBM.

Kernel shape: grid (batch*heads, q_blocks, kv_blocks); the kv axis is
"arbitrary" (sequential) so the running max/sum/accumulator scratch
carries across kv steps; q/batch axes are parallel.  Blocks default to
128 (MXU-aligned); f32 accumulation (guide: preferred_element_type).

`flash_attention` is differentiable: forward runs the kernel, backward
falls back to the jnp reference VJP (recompute strategy) — exact same
math, so gradients match the oracle.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1.0e30


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
               scale: float, causal: bool, block_q: int, block_k: int,
               nk: int):
    i_q = pl.program_id(1)
    i_k = pl.program_id(2)

    @pl.when(i_k == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: the whole kv block is masked iff its first key index exceeds
    # the last query index of this q block — skip the matmuls entirely
    run = (i_k * block_k <= (i_q + 1) * block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0].astype(jnp.float32)            # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            qpos = i_q * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = i_k * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos > qpos, _NEG_BIG, s)
        m_prev = m_ref[:, :1]                        # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            # rows fully masked in this block contribute nothing even when
            # m_new == _NEG_BIG (exp(0) == 1 would poison them otherwise)
            p = jnp.where(s <= _NEG_BIG / 2, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = l_ref[:, :1] * corr + jnp.sum(p, -1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, :1] = m_new

    @pl.when(i_k == nk - 1)
    def _final():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal: bool, scale: float, block_q: int,
               block_k: int, interpret: bool):
    bh, lq, d = q.shape
    lk = k.shape[1]
    nq, nk = _cdiv(lq, block_q), _cdiv(lk, block_k)
    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk)
    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, iq, ik: (b, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, iq, ik: (b, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)


def _reference(q, k, v, causal, scale):
    s = jnp.einsum("bld,bsd->bls", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(lk)[None, :] > jnp.arange(lq)[:, None]
        s = jnp.where(mask, -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bls,bsd->bld", p, v.astype(jnp.float32)).astype(
        q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    # recompute-backward through the mathematically identical reference
    _, vjp = jax.vjp(lambda q, k, v: _reference(q, k, v, causal, scale),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Fused blockwise attention.  q,k,v: [B, L, H, D] -> [B, L, H, D].

    `interpret=None` auto-selects: real Mosaic lowering on TPU, the
    Pallas interpreter elsewhere (tests on the virtual CPU mesh).  Falls
    back to the jnp reference when L is smaller than one block (the
    kernel would be all padding)."""
    b, l, h, d = q.shape
    scale = (d ** -0.5) if scale is None else scale
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if l < block_q or l < block_k:
        return _reference(
            jnp.reshape(jnp.transpose(q, (0, 2, 1, 3)), (b * h, l, d)),
            jnp.reshape(jnp.transpose(k, (0, 2, 1, 3)), (b * h, l, d)),
            jnp.reshape(jnp.transpose(v, (0, 2, 1, 3)), (b * h, l, d)),
            causal, scale).reshape(b, h, l, d).transpose(0, 2, 1, 3)
    if l % block_q or l % block_k:
        raise ValueError(f"seq len {l} must divide by blocks "
                         f"({block_q}, {block_k})")

    def fold(x):
        return jnp.reshape(jnp.transpose(x, (0, 2, 1, 3)), (b * h, l, d))

    out = _flash(fold(q), fold(k), fold(v), causal, scale,
                 block_q, block_k, interpret)
    return jnp.transpose(out.reshape(b, h, l, d), (0, 2, 1, 3))


# ------------------------------------------------------------ PTG builder
def build_flash_attention(ctx, Qc, Kc, Vc, Oc, causal: bool = False,
                          scale: Optional[float] = None, dev=None,
                          names=("FAQ", "FAK", "FAV", "FAO")):
    """Blockwise attention as a PTG taskpool: FATT(q) attends row block
    q of `Qc` over the full `Kc`/`Vc` into `Oc` — the runtime-task form
    of this op (one task per query block, fully parallel; block-level
    causality masks by absolute row), so attention composes with other
    tile DAGs instead of leaving the runtime for a whole-array XLA
    call.  The sequence-sharded, KV-rotating variant is
    algos/ring_attention.py.

    Qc/Oc: (B*L, d) collections tiled (T, d); Kc/Vc: one (L, d) tile
    each.  Registers the collections under `names`.  With `dev`, the
    chore runs the fused Pallas kernel (flash_attention); the CPU body
    is the numpy reference."""
    import numpy as np

    import parsec_tpu as pt

    assert Qc.mt == Oc.mt and Qc.mb == Oc.mb and Qc.nb == Oc.nb
    qn, kn, vn, on = names
    Qc.register(ctx, qn)
    Kc.register(ctx, kn)
    Vc.register(ctx, vn)
    Oc.register(ctx, on)
    tp = pt.Taskpool(ctx, globals={"NQ": Qc.mt - 1})
    q = pt.L("q")
    T, d = Qc.mb, Qc.nb
    L = Kc.mb
    sc = (d ** -0.5) if scale is None else scale
    qshp, kshp = (T, d), (L, d)
    dt = Qc.dtype

    tc = tp.task_class("FATT")
    tc.param("q", 0, pt.G("NQ"))
    tc.affinity(qn, q, 0)
    tc.flow("Q", "READ", pt.In(pt.Mem(qn, q, 0)))
    tc.flow("K", "READ", pt.In(pt.Mem(kn, 0, 0)))
    tc.flow("V", "READ", pt.In(pt.Mem(vn, 0, 0)))
    tc.flow("O", "RW", pt.In(pt.Mem(on, q, 0)),
            pt.Out(pt.Mem(on, q, 0)))

    if dev is not None:
        def k_fatt(qb, kb, vb, _q=None):
            if qb.shape[0] == kb.shape[0]:
                # [T, d] block through the fused kernel as [1, T, 1, d]
                o = flash_attention(qb[None, :, None, :],
                                    kb[None, :, None, :],
                                    vb[None, :, None, :],
                                    causal=False, scale=sc)
                return o[0, :, 0, :]
            # T != L (a multi-block Q attending the full K/V tile): the
            # fused kernel's internal reshape assumes square self-
            # attention, so the blockwise softmax runs directly — the
            # same op order as the CPU reference body
            import jax.numpy as jnp
            s = (qb @ kb.T) * sc
            s = s - s.max(axis=-1, keepdims=True)
            p = jnp.exp(s)
            p = p / p.sum(axis=-1, keepdims=True)
            return (p @ vb).astype(qb.dtype)

        if causal:
            raise ValueError(
                "build_flash_attention: causal device chores need the "
                "per-block row offset; use the CPU bodies (dev=None) "
                "or algos/ring_attention for causal DAG attention")
        dev.attach(tc, tp, kernel=k_fatt, reads=["Q", "K", "V"],
                   writes=["O"],
                   shapes={"Q": qshp, "K": kshp, "V": kshp, "O": qshp},
                   dtype=dt)

    def body(t):
        qb = t.data("Q", dt, qshp).astype(np.float32)
        kb = t.data("K", dt, kshp).astype(np.float32)
        vb = t.data("V", dt, kshp).astype(np.float32)
        o = t.data("O", dt, qshp)
        s = (qb @ kb.T) * sc
        if causal:
            off = t.local("q") * T
            rows = off + np.arange(T)[:, None]
            s = np.where(rows >= np.arange(L)[None, :], s, -np.inf)
        s = s - s.max(axis=-1, keepdims=True)
        p = np.exp(s)
        p /= p.sum(axis=-1, keepdims=True)
        o[...] = (p @ vb).astype(dt)

    tc.body(body, pure=True)  # pure tile chore: fusion-eligible
    return tp
