"""tpu-parsec: a TPU-native task-based runtime with the capabilities of
PaRSEC (reference: /root/reference — see SURVEY.md).

Applications are DAGs of tile-granularity tasks with labeled data-flow edges.
The native C++ core schedules tasks across worker threads and resolves
dependencies; the TPU device layer dispatches cached XLA/Pallas executables;
the comm engine moves activations and tile payloads between ranks.
"""
from ._native import (DEV_CPU, DEV_RECURSIVE, DEV_TPU, HOOK_AGAIN, HOOK_ASYNC,
                      HOOK_DISABLE, HOOK_DONE, HOOK_ERROR, HOOK_NEXT)
from .core import (Compound, Context, CountableFuture, Data, Future, G, In,
                   L, Mem, Out, Range, Ref, TaskClass, Taskpool, TaskView,
                   TriggeredFuture, call, compose, maximum, minimum,
                   recursive_call, select, shl, shr)

__version__ = "0.1.0"

__all__ = [
    "Context", "Data", "Taskpool", "TaskClass", "TaskView",
    "In", "Out", "Mem", "Ref",
    "L", "G", "Range", "select", "call", "minimum", "maximum", "shl", "shr",
    "Compound", "compose", "recursive_call",
    "Future", "CountableFuture", "TriggeredFuture",
    "HOOK_DONE", "HOOK_AGAIN", "HOOK_ASYNC", "HOOK_NEXT", "HOOK_DISABLE",
    "HOOK_ERROR", "DEV_CPU", "DEV_TPU", "DEV_RECURSIVE",
    "__version__",
]
