CXX ?= g++
CXXFLAGS ?= -O2 -g -std=c++17 -fPIC -Wall -Wextra -pthread
BUILD := build
LIB := $(BUILD)/libparsec_core.so

all: $(LIB)

$(LIB): native/core.cpp native/parsec_core.h
	@mkdir -p $(BUILD)
	$(CXX) $(CXXFLAGS) -shared -o $@ native/core.cpp

clean:
	rm -rf $(BUILD)

.PHONY: all clean
