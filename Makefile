CXX ?= g++
CXXFLAGS ?= -O2 -g -std=c++17 -fPIC -Wall -Wextra -pthread
BUILD := build
LIB := $(BUILD)/libparsec_core.so

all: $(LIB)

SRCS := native/core.cpp native/sched.cpp native/comm.cpp
HDRS := native/parsec_core.h native/runtime_internal.h native/lockfree.h

$(LIB): $(SRCS) $(HDRS)
	@mkdir -p $(BUILD)
	$(CXX) $(CXXFLAGS) -shared -o $@ $(SRCS)

clean:
	rm -rf $(BUILD)

# ThreadSanitizer build of the core (the lock-free scheduler path's
# correctness harness; see tools/stress_tsan.py).  Loaded via
# PTC_NATIVE_LIB with the tsan runtime LD_PRELOADed.
TSAN_LIB := $(BUILD)/libparsec_core_tsan.so

tsan: $(TSAN_LIB)

$(TSAN_LIB): $(SRCS) $(HDRS)
	@mkdir -p $(BUILD)
	$(CXX) -O1 -g -std=c++17 -fPIC -Wall -pthread -fsanitize=thread \
		-shared -o $@ $(SRCS)

# UndefinedBehaviorSanitizer build + the stress_tsan job set (the same
# concurrency workloads, here hunting signed overflow / bad shifts /
# misaligned access in the spec decoder and dep engine).  halt_on_error
# + no-recover: the first report fails the run.
UBSAN_LIB := $(BUILD)/libparsec_core_ubsan.so

$(UBSAN_LIB): $(SRCS) $(HDRS)
	@mkdir -p $(BUILD)
	$(CXX) -O1 -g -std=c++17 -fPIC -Wall -pthread \
		-fsanitize=undefined -fno-sanitize-recover=all \
		-shared -o $@ $(SRCS)

ubsan: $(UBSAN_LIB)
	PTC_NATIVE_LIB=$(UBSAN_LIB) \
	LD_PRELOAD=$$($(CXX) -print-file-name=libubsan.so) \
	UBSAN_OPTIONS="halt_on_error=1 print_stacktrace=1 exitcode=67" \
	timeout 900 python tools/stress_tsan.py

# Curated clang-tidy pass over the native core (.clang-tidy: bugprone-*
# + concurrency-* + performance-*).  Gated: containers without
# clang-tidy skip with a notice instead of failing the check recipe.
tidy:
	@if command -v clang-tidy >/dev/null 2>&1; then \
		clang-tidy --quiet $(SRCS) -- -std=c++17 -pthread; \
	else \
		echo "tidy: clang-tidy not installed; skipped" \
		     "(config committed in .clang-tidy)"; \
	fi

# Static dataflow verification of every in-tree graph generator
# (tools/verify_graphs.py -> parsec_tpu/analysis rules V001-V009).
# Exit 1 = a graph regressed the clean baseline.
verify-graphs: $(LIB)
	python tools/verify_graphs.py

# Static resource & schedule analysis of every in-tree graph generator
# (tools/plan_graphs.py -> parsec_tpu/analysis/plan.py): every graph
# must plan CLEAN (no enumeration refusal, finite residency/makespan
# bounds) and the potrf bench tiling must plan inside its latency
# budget.  Emits PLAN_graphs.json (bench_check guards potrf_nt16_ms).
plan-graphs: $(LIB)
	python tools/plan_graphs.py --json PLAN_graphs.json

# Transfer-economics sweep (tools/testbandwidth.py): eager / rendezvous
# / PK_DEVICE paths on loopback, fitted fixed-overhead + per-byte cost,
# BENCH-style JSON.  Runs entirely without a TPU tunnel.
bench-comm: $(LIB)
	python tools/testbandwidth.py --json BENCH_comm.json

# Dispatch-latency suite (bench.py --dispatch --json): single-chain +
# contended successor-begin percentiles with sched_stats evidence
# (bypass hits, freelist hit rate, inject traffic) and host provenance
# (cpu_count vs workers — oversubscribed runs are flagged, not silently
# reported).  Rung-1 of the measurement ladder.
bench-dispatch: $(LIB)
	python bench.py --dispatch --json BENCH_dispatch.json

# Device-pipeline suite (bench.py --device --json): staged-vs-prefetched
# wave dispatch (per-wave h2d stall off the DEVICE span aux, overlap
# fraction from paired DEVICE/H2D spans) + the 2x-budget out-of-core
# GEMM, with host provenance and an oversubscription flag.  Runs on the
# CPU jax backend — no TPU needed.
bench-device: $(LIB)
	python bench.py --device --json BENCH_device.json

# Cross-rank streaming sweep (bench.py --stream --json): steady-state
# >=4 MiB device-to-device tile latency with the wire-v4 streaming
# pipeline (progressive serve + 2 rails) vs the serialized baseline
# (stream off, 1 rail), rails=1 vs rails=2 throughput, and per-hop
# d2h/wire overlap evidence.  Loopback, CPU jax backend — no TPU needed.
bench-stream: $(LIB)
	python bench.py --stream --json BENCH_stream.json

# Runtime-native collective suite (bench.py --collective --json):
# DAG-dependency chain reduction vs runtime-native streamed collective
# across message sizes on a 2-rank pair, the whole-array XLA shard_map
# psum baseline, and the level-2 trace evidence (comm_wait+coll_wait
# lost time, compute/wire overlap fraction) for the largest size.
# Loopback, CPU jax backend — no TPU needed.
bench-collective: $(LIB)
	python bench.py --collective --json BENCH_collective.json

# Serving-runtime suite (bench.py --serve --json): mixed-tenant
# latency p50/p99 (hi-priority tenant vs a no-QoS control over the SAME
# request mix), admission rejects under tight budgets, and the
# continuous-batching decode's bit-exactness vs the sequential
# per-request baseline.  CPU-only — no TPU needed.
bench-serve: $(LIB)
	python bench.py --serve --json BENCH_serve.json

# Self-driving-runtime suite (bench.py --control --json, ptc-pilot):
# the drift soak — a stale device-cache knob vector lands mid-run with
# PTC_COMM_FAULT_DELAY_US armed, the controller detects the sustained
# makespan drift, re-simulates on the recalibrated cost model and
# hot-swaps the winner at the next pool boundary (recovered-throughput
# ratio gated >= 0.5, no restart) — plus the adaptive-vs-fixed spec_k
# sweep over a mixed oracle/adversarial draft workload (deterministic
# score; bit-identity never relaxed).  CPU-only — no TPU needed.
bench-control: $(LIB)
	python bench.py --control --json BENCH_control.json

# Topology-tier soak (bench.py --topo --json, ptc-topo): the 4-rank
# two-island mesh under the island emulator's per-peer recv delays —
# ring vs hierarchical all_reduce (bit-exact, per-class wire split),
# and the rank-remap chain: measured DCN bytes identity vs
# run(remap=True) (>= 30% reduction enforced), plan-predicted per-class
# bytes sound vs the classed wire_out_bound.  CPU-only, loopback.
bench-topo: $(LIB)
	python bench.py --topo --json BENCH_topo.json

# Tracing-overhead ladder (bench.py --trace --json): per-task cost at
# trace levels 0/1/2 and the flight-recorder ring vs unbounded buffers
# at level 1 (the PR2 one-transaction-per-task contract), plus the
# always-on metrics on/off cost at level 0, with host provenance.
# No TPU needed.
bench-trace: $(LIB)
	python bench.py --trace --json BENCH_trace.json

# Bench-trajectory regression guard (the CI gate): compares the working
# tree's BENCH_*.json against the committed copies with per-metric
# tolerances (dispatch p50, stream overlap_fraction, trace ring ratio
# and level-0 cost, coll ratios, device stall reduction), honoring each
# file's recorded `oversubscribed` flag.  Run the bench suite first,
# then this; exit 1 = a guarded metric regressed.
bench-check:
	python tools/bench_check.py

# ptc-tune gate (tools/ptc_tune.py --check): every in-tree graph must
# plan concretely (no enumeration refusal), carry an explicit wave-
# fusability certify/refuse verdict per wave (no silent skips), and
# simulate to a finite, bit-reproducible makespan under the default
# knob vector.  Exit 1 = a graph regressed the gate.
tune-check: $(LIB)
	python tools/ptc_tune.py --check

# ptc-blackbox smoke: the postmortem assembler over a committed
# fixture (two survivor journals for a 3-rank incident) must produce a
# byte-stable incident report — dead rank, first cause, holdings.
# Deterministic, no runtime needed; exit 1 = report drift.
postmortem-smoke:
	python tools/ptc_postmortem.py tests/data/blackbox_fixture \
		--expect tests/data/blackbox_fixture/expected.json > /dev/null

# Default check recipe: bench-trajectory guard + graph hygiene (verify
# + plan + tune baselines) + postmortem smoke + native lint —
# regressions in any fail fast.
check: bench-check verify-graphs plan-graphs tune-check postmortem-smoke tidy

.PHONY: all clean tsan ubsan tidy verify-graphs plan-graphs tune-check \
	check bench-comm bench-dispatch bench-device bench-stream \
	bench-collective bench-trace bench-serve bench-topo \
	bench-control bench-check postmortem-smoke
