CXX ?= g++
CXXFLAGS ?= -O2 -g -std=c++17 -fPIC -Wall -Wextra -pthread
BUILD := build
LIB := $(BUILD)/libparsec_core.so

all: $(LIB)

SRCS := native/core.cpp native/sched.cpp native/comm.cpp
HDRS := native/parsec_core.h native/runtime_internal.h

$(LIB): $(SRCS) $(HDRS)
	@mkdir -p $(BUILD)
	$(CXX) $(CXXFLAGS) -shared -o $@ $(SRCS)

clean:
	rm -rf $(BUILD)

.PHONY: all clean
