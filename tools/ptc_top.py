#!/usr/bin/env python
"""ptc_top — live text dashboard over a running parsec_tpu process.

Replaces ad-hoc `tail -f` squinting at the LiveMonitor JSONL sink: one
refreshing screen with workers, per-class latency quantiles, the tenant
table (occupancy, TTFT p99, tokens/s, SLO burn) and the plan-vs-measured
conformance rollup.

Sources (either or both):
  --live PATH[,PATH...]   LiveMonitor sinks (default: every
                          /tmp/ptc_live_*.jsonl present), newest sample
                          per rank
  --url  http://HOST:PORT the PR 7 metrics exporter — polls /stats.json
                          and /healthz (PTC_MCA_runtime_metrics_port)

Usage:
  python tools/ptc_top.py                     # tail the default sinks
  python tools/ptc_top.py --url http://127.0.0.1:9400
  python tools/ptc_top.py --once              # one frame, no clear
"""
import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _last_json_line(path):
    """Newest whole JSON record of a JSONL sink (tail without loading
    the file: read the last 64 KiB and take the last parseable line)."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 65536))
            tail = f.read().decode(errors="replace")
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            return rec
    return None


def _fetch(url, path):
    import urllib.request
    try:
        with urllib.request.urlopen(url.rstrip("/") + path,
                                    timeout=2) as r:
            return r.status, json.loads(r.read().decode())
    except Exception as e:
        return None, {"error": repr(e)}


def _fmt(v, nd=2):
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "ok" if v else "VIOLATED"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render_live(samples):
    """One frame from per-rank LiveMonitor samples."""
    lines = []
    tenants = {}
    conf = None
    fleet = None
    control = None
    for rank in sorted(samples):
        rec = samples[rank]
        fleet = rec.get("fleet") or fleet
        control = rec.get("control") or control
        w = rec.get("workers") or []
        lines.append(
            f"rank {rank}: t={rec.get('t', '?')}s "
            f"tasks={sum(w)} workers={len(w)} "
            f"rss={rec.get('maxrss_kb', 0) // 1024}MB")
        for name, row in (rec.get("latency") or {}).items():
            lines.append(f"  {name:<14} n={row[0]:<8} "
                         f"p50={row[1] / 1e3:.1f}us p99={row[2] / 1e3:.1f}us")
        topo = rec.get("topo")
        if topo and topo.get("classes"):
            # per-link-class wire split (ptc-topo): bytes/msgs sent per
            # class — dcn staying small is the hier/remap win, live
            parts = " ".join(
                f"{cls}={row[0] // 1024}kb/{row[1]}m"
                for cls, row in sorted(topo["classes"].items()))
            lines.append(f"  topo: islands={topo.get('n_islands', 1)} "
                         f"{parts}")
        for name, row in (rec.get("serve") or {}).items():
            t = tenants.setdefault(name, {})
            t["active"] = t.get("active", 0) + row.get("active", 0)
            t["queued"] = t.get("queued", 0) + row.get("queued", 0)
            t["rejected"] = t.get("rejected", 0) + row.get("rejected", 0)
        for name, row in (rec.get("tenants") or {}).items():
            tenants.setdefault(name, {}).update(row)
        conf = rec.get("conformance") or conf
    if tenants:
        lines.append("")
        lines.append(f"{'tenant':<12}{'act':>4}{'q':>4}{'rej':>5}"
                     f"{'done':>6}{'ttft_p99':>10}{'lat_p99':>9}"
                     f"{'tok/s':>7}{'burn':>6}{'pfx_hit':>8}"
                     f"{'spec_acc':>9}{'coll_wait':>10}")
        for name, t in sorted(tenants.items()):
            lines.append(
                f"{name:<12}{t.get('active', 0):>4}"
                f"{t.get('queued', 0):>4}{t.get('rejected', 0):>5}"
                f"{t.get('completed', 0):>6}"
                f"{_fmt(t.get('ttft_p99_ms')):>10}"
                f"{_fmt(t.get('latency_p99_ms')):>9}"
                f"{_fmt(t.get('tok_s_p50'), 0):>7}"
                f"{_fmt(t.get('slo_burn')):>6}"
                f"{_fmt(t.get('prefix_hit')):>8}"
                f"{_fmt(t.get('spec_acc')):>9}"
                f"{_fmt(t.get('coll_wait_p99_ms')):>10}")
    if fleet:
        # per-replica fleet table (ptc-route): occupancy, prefix hit
        # rate and the migration ledger, straight off Router.stats()
        lines.append("")
        lines.append(f"{'replica':<10}{'role':<9}{'ok':>3}{'act':>4}"
                     f"{'q':>4}{'burn':>6}{'pfx_hit':>8}{'frozen':>7}"
                     f"{'imp':>5}{'exp':>5}{'mig_in_kb':>10}")
        for name, row in sorted(
                (fleet.get("replicas") or {}).items(),
                key=lambda kv: kv[1].get("index", 0)):
            lines.append(
                f"{name:<10}{row.get('role', '?'):<9}"
                f"{('y' if row.get('healthy') else 'N'):>3}"
                f"{row.get('active_pools', 0):>4}"
                f"{row.get('queue_depth', 0):>4}"
                f"{_fmt(row.get('slo_burn_rate')):>6}"
                f"{_fmt(row.get('pfx_hit')):>8}"
                f"{row.get('frozen_live', 0):>7}"
                f"{row.get('imported', 0):>5}"
                f"{row.get('exported', 0):>5}"
                f"{row.get('migrated_in_bytes', 0) // 1024:>10}")
        r = fleet.get("router") or {}
        lines.append(
            f"router: placed={r.get('placed', 0)} "
            f"rerouted={r.get('rerouted', 0)} "
            f"reroute_failed={r.get('reroute_failed', 0)} "
            f"prefill_jobs={r.get('prefill_jobs', 0)} "
            f"migrated={r.get('migrated_pages', 0)}p/"
            f"{r.get('migrated_bytes', 0) // 1024}kb "
            f"dups={r.get('migration_dups', 0)}")
    if conf:
        lines.append("")
        lines.append(
            f"conformance: coverage={_fmt(conf.get('coverage'))} "
            f"makespan_ratio_p50={_fmt(conf.get('makespan_ratio_p50'))} "
            f"comm_bound={_fmt(conf.get('comm_sound'))}")
    if control and control.get("enabled"):
        # ptc-pilot controller panel: drift vs threshold, the retune /
        # hot-swap ledger and the live per-tenant resource levers
        lines.append("")
        lines.append(
            f"control: drift={_fmt(control.get('drift_now'))}"
            f"/{_fmt(control.get('drift_ratio'))} "
            f"window={control.get('window_n', 0)}"
            f"/{control.get('window', 0)} "
            f"retunes={control.get('retunes', 0)} "
            f"swaps={control.get('swaps', 0)} "
            f"interrupts={control.get('interrupts', 0)} "
            f"decisions={control.get('decisions', 0)}")
        last = control.get("last_swap")
        if last:
            lines.append(
                f"  last swap [{last.get('trigger')}]: "
                f"{_fmt((last.get('before_ns') or 0) / 1e6)}ms -> "
                f"{_fmt((last.get('after_ns') or 0) / 1e6)}ms "
                f"knobs={','.join(sorted(last.get('knobs') or {}))}")
        spec = control.get("spec_k") or {}
        if spec.get("auto"):
            ks = " ".join(f"{t}={k}" for t, k in
                          sorted((spec.get("tenants") or {}).items()))
            lines.append(f"  spec_k[auto max={spec.get('max')}]: "
                         f"{ks or '-'}")
        shares = control.get("budget_shares") or {}
        if shares:
            lines.append("  cache shares: " + " ".join(
                f"{t}={_fmt(v)}" for t, v in sorted(shares.items())))
        press = control.get("pressure") or {}
        if press:
            lines.append("  admission pressure: " + " ".join(
                f"{t}={_fmt(v)}" for t, v in sorted(press.items())))
    return "\n".join(lines)


def render_url(stats, health_code, health):
    lines = []
    c = stats.get("counters") or {}
    lines.append(f"rank {stats.get('rank', '?')}  "
                 f"healthz={'503 DEGRADED' if health_code == 503 else health_code}")
    sc = {k: v for k, v in c.items()
          if k.startswith(("ptc_scope_", "ptc_serve_prefix_",
                           "ptc_serve_spec_"))}
    for k in sorted(sc):
        lines.append(f"  {k} = {sc[k]}")
    wd = (health or {}).get("events") or []
    for ev in wd[-4:]:
        lines.append(f"  watchdog: {ev.get('type')} "
                     + json.dumps({k: v for k, v in ev.items()
                                   if k in ('tenant', 'rid', 'scope_id',
                                            'task_class', 'burn_rate')}))
    slo = (health or {}).get("slo") or {}
    for name, st in sorted(slo.items()):
        lines.append(f"  slo[{name}]: burn={st.get('burn_rate')} "
                     f"breached={st.get('breached')}")
    return "\n".join(lines)


def render_fleet(snap):
    """One frame of the FleetView /fleet.json snapshot: the replica
    table then the fleet-merged tenant table (ptc-blackbox)."""
    lines = []
    if not (snap or {}).get("enabled"):
        return "fleet: no snapshot yet (is a FleetView attached?)"
    reps = snap.get("replicas") or []
    lines.append(f"fleet  replicas={len(reps)} "
                 f"healthy={snap.get('healthy_replicas')} "
                 f"scrapes={snap.get('scrapes')} "
                 f"errors={snap.get('errors')}")
    if reps:
        lines.append(f"  {'replica':<24} {'ok':>3} {'pools':>6} "
                     f"{'queue':>6} {'burn':>8} {'adm.press':>9}")
        for r in reps:
            lines.append(
                f"  {str(r.get('name'))[:24]:<24} "
                f"{'y' if r.get('healthy') else 'N':>3} "
                f"{_fmt(r.get('active_pools'), 0):>6} "
                f"{_fmt(r.get('queue_depth'), 0):>6} "
                f"{_fmt(r.get('slo_burn_rate')):>8} "
                f"{_fmt(r.get('admission_pressure')):>9}")
    tens = snap.get("tenants") or {}
    if tens:
        lines.append(f"  {'tenant':<16} {'burn':>8} {'agg tok/s':>10} "
                     f"{'ttft p99':>10} {'done':>8}")
        for name, row in sorted(tens.items()):
            lines.append(
                f"  {name[:16]:<16} {_fmt(row.get('slo_burn_rate')):>8} "
                f"{_fmt(row.get('agg_tokens_per_s'), 1):>10} "
                f"{_fmt(row.get('ttft_ms_p99'), 1):>10} "
                f"{_fmt((row.get('counters') or {}).get('completed'), 0):>8}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--live", default=None,
                    help="comma-separated LiveMonitor JSONL sinks "
                         "(default: /tmp/ptc_live_*.jsonl)")
    ap.add_argument("--url", default=None,
                    help="metrics exporter base url (polls /stats.json)")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no screen clear)")
    ap.add_argument("--fleet", action="store_true",
                    help="render the FleetView federation table "
                         "(polls --url /fleet.json)")
    args = ap.parse_args(argv)

    def paths():
        if args.live:
            return args.live.split(",")
        return sorted(glob.glob("/tmp/ptc_live_*.jsonl"))

    while True:
        frames = []
        samples = {}
        for p in paths():
            rec = _last_json_line(p)
            if rec is not None:
                samples[rec.get("rank", p)] = rec
        if samples:
            frames.append(render_live(samples))
        if args.url:
            code, health = _fetch(args.url, "/healthz")
            _, stats = _fetch(args.url, "/stats.json")
            frames.append(render_url(stats if isinstance(stats, dict)
                                     else {}, code, health))
            if args.fleet:
                _, fleet = _fetch(args.url, "/fleet.json")
                frames.append(render_fleet(fleet
                                           if isinstance(fleet, dict)
                                           else {}))
        elif args.fleet:
            frames.append("fleet: --fleet needs --url "
                          "(the exporter serves /fleet.json)")
        if not frames:
            frames.append("ptc_top: no live sinks found "
                          "(PTC_MCA_runtime_live=<secs> writes "
                          "/tmp/ptc_live_<rank>.jsonl; or pass --url)")
        out = "\n\n".join(frames)
        if args.once:
            print(out)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + out + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
