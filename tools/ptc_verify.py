#!/usr/bin/env python
"""ptc-verify CLI: static dataflow verification of PTG task graphs
(rules V001-V008, parsec_tpu/analysis/verify.py).

Input is either a .jdf file (compiled, never executed) or the name of
an in-tree graph generator from tools/verify_graphs.py:

    python tools/ptc_verify.py prog.jdf --global N=10
    python tools/ptc_verify.py potrf
    python tools/ptc_verify.py prog.jdf --json report.json --dot g.dot

Exit status: 0 clean (or warnings only with --ok-warn), 1 when any
error-severity finding exists, 2 on usage errors.  `--dot` writes the
concretized instance DAG with findings overlaid in red.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import parsec_tpu as pt  # noqa: E402


def _verify_jdf(args):
    from parsec_tpu.analysis import (extract_flowgraph, flowgraph_to_dot,
                                     verify_graph)
    from parsec_tpu.dsl.jdf import compile_jdf
    src = open(args.target).read()
    globs = {}
    for g in args.globs:
        k, v = g.split("=", 1)
        globs[k.strip()] = int(v)
    globs.setdefault("NB", 10)
    globs.setdefault("N", 10)
    with pt.Context(nb_workers=1) as ctx:
        buf = np.zeros(args.size, dtype=np.int64)
        ctx.register_linear_collection(args.collection, buf, elem_size=8)
        ctx.register_arena("default", 64)
        b = compile_jdf(src, ctx, globals=globs, dtype=np.int64,
                        arenas={"A": "default"},
                        filename=os.path.basename(args.target))
        fg = extract_flowgraph(b.tp)
        report, cg = verify_graph(fg, max_instances=args.max_instances)
        if args.dot:
            with open(args.dot, "w") as f:
                f.write(flowgraph_to_dot(cg, report.findings) + "\n")
        return {os.path.basename(args.target): report}


def _verify_intree(args):
    import verify_graphs
    if args.target != "all" and args.target not in verify_graphs.GENERATORS:
        print(f"ptc-verify: no file and no in-tree generator named "
              f"{args.target!r}; generators: "
              f"{', '.join(sorted(verify_graphs.GENERATORS))}",
              file=sys.stderr)
        sys.exit(2)
    only = None if args.target == "all" else [args.target]
    return dict(verify_graphs.verify_all(only=only))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("target",
                    help=".jdf file, in-tree generator name, or 'all'")
    ap.add_argument("--global", dest="globs", action="append", default=[],
                    metavar="NAME=VALUE")
    ap.add_argument("--collection", default="mydata",
                    help="collection name bound to memory references")
    ap.add_argument("--size", type=int, default=256,
                    help="elements in the throwaway collection")
    ap.add_argument("--max-instances", type=int, default=200_000,
                    help="concrete-enumeration budget (past it the "
                         "instance-level rules degrade to symbolic)")
    ap.add_argument("--json", dest="json_out", metavar="PATH", default=None)
    ap.add_argument("--dot", metavar="PATH", default=None,
                    help="write the instance DAG with findings in red "
                         "(.jdf targets only)")
    ap.add_argument("--ok-warn", action="store_true",
                    help="exit 0 when only warnings remain")
    args = ap.parse_args(argv)

    if os.path.exists(args.target):
        reports = _verify_jdf(args)
    else:
        if args.dot:
            print("ptc-verify: --dot needs a .jdf target",
                  file=sys.stderr)
            return 2
        reports = _verify_intree(args)

    errors = warnings = 0
    for name, report in reports.items():
        if len(reports) > 1:
            print(f"=== {name}")
        print(report.text())
        errors += len(report.errors)
        warnings += len(report.warnings)
    if args.json_out:
        payload = {n: r.to_json() for n, r in reports.items()}
        with open(args.json_out, "w") as f:
            json.dump(payload, f, indent=1)
    if errors:
        return 1
    if warnings and not args.ok_warn:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
