#!/usr/bin/env python
"""Enumerate a JDF program's task DAG and emit DOT + per-class counts
(reference: tools/dagenum.c + the --parsec dot grapher), optionally with
a weighted list-scheduling simulation (reference: the JDF body `weight`
property feeding the simulation/dagenum cost model, parsec.y body
properties).

Usage: python tools/jdf2dot.py prog.jdf out.dot [--global N=10 ...]
                [--simulate P]

The DAG comes from the SAME symbolic flow-graph extraction the static
verifier uses (parsec_tpu/analysis/flowgraph.py): the program is
compiled but never executed — dep targets, guards, broadcast ranges and
control gathers are enumerated over the execution space exactly as the
native engine would resolve them.  Verifier findings (rules V001-V008)
overlay the DOT in red; dynamically-guarded maybe-edges draw dashed.
--simulate P list-schedules the extracted DAG on P virtual workers
using per-task costs from `BODY [weight = <expr>]` (a Python expression
over the task's first two parameters; default cost 1) and reports total
work, critical path, makespan, speedup, and efficiency.
"""
import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import parsec_tpu as pt  # noqa: E402
from parsec_tpu.analysis import (extract_flowgraph, flowgraph_to_dot,
                                 verify_graph)  # noqa: E402
from parsec_tpu.dsl.jdf import compile_jdf  # noqa: E402


def _noopify(src: str) -> str:
    """Replace every BODY block's code with 'pass', preserving the body
    properties ([type=..] selection and [weight=..] simulation costs)."""
    return re.sub(
        r"BODY(\s*\[[^\]]*\])?\s*(?:\{.*?\}\s*)?END",
        lambda m: f"BODY{m.group(1) or ''}\n{{\npass\n}}\nEND",
        src, flags=re.S)


def simulate(nodes_edges, prog, gvals, nb_workers):
    """List-schedule the extracted DAG on `nb_workers` virtual workers.

    `nodes_edges` is ((cid, l0, l1) node list, (src, dst) edge list).
    Costs come from each class's first BODY carrying a `weight` property
    (a Python expression over the task's first two declared parameters
    and the program globals; default 1).  Returns a dict with total
    work, weighted critical path, greedy makespan, speedup, and
    efficiency — the JDF-simulation cost model (reference: body weight
    properties + the simulation dag enumerators)."""
    import heapq

    node_list, edge_list = nodes_edges
    weight_src = {}
    pnames = {}
    for i, jt in enumerate(prog.tasks):
        pnames[i] = jt.params[:2]
        for body in jt.bodies:
            w = body.props.get("weight")
            if w is not None:
                weight_src[i] = compile(w, f"<weight-{jt.name}>", "eval")
                break

    def cost(cid, l0, l1):
        code = weight_src.get(cid)
        if code is None:
            return 1
        env = dict(gvals)
        names = pnames.get(cid, [])
        if len(names) > 0:
            env[names[0]] = l0
        if len(names) > 1:
            env[names[1]] = l1
        return max(1, int(eval(code, {}, env)))

    nodes = {}
    for (cid, l0, l1) in node_list:
        nodes[(cid, l0, l1)] = cost(cid, l0, l1)
    succs = {n: [] for n in nodes}
    npred = {n: 0 for n in nodes}
    for src, dst in edge_list:
        if src in nodes and dst in nodes:
            succs[src].append(dst)
            npred[dst] += 1
    # weighted critical path (DAG longest path, reverse topological)
    order = []
    stack = [n for n in nodes if npred[n] == 0]
    indeg = dict(npred)
    while stack:
        n = stack.pop()
        order.append(n)
        for s in succs[n]:
            indeg[s] -= 1
            if indeg[s] == 0:
                stack.append(s)
    dist = {n: nodes[n] for n in nodes}
    for n in order:
        for s in succs[n]:
            if dist[n] + nodes[s] > dist[s]:
                dist[s] = dist[n] + nodes[s]
    critical = max(dist.values(), default=0)
    total = sum(nodes.values())
    # greedy list scheduling on P workers
    ready = [(0, n) for n in nodes if npred[n] == 0]
    heapq.heapify(ready)
    workers = [0] * max(1, nb_workers)
    heapq.heapify(workers)
    indeg = dict(npred)
    avail = {}
    makespan = 0
    scheduled = 0
    while ready:
        t_ready, n = heapq.heappop(ready)
        scheduled += 1
        t_start = max(t_ready, heapq.heappop(workers))
        t_end = t_start + nodes[n]
        heapq.heappush(workers, t_end)
        makespan = max(makespan, t_end)
        for s in succs[n]:
            avail[s] = max(avail.get(s, 0), t_end)
            indeg[s] -= 1
            if indeg[s] == 0:
                heapq.heappush(ready, (avail[s], s))
    if scheduled != len(nodes):
        # classes with >2 parameters alias to one (cid, l0, l1) node and
        # can fabricate cycles — silent makespan underestimates lie
        print(f"simulate: WARNING {len(nodes) - scheduled} of "
              f"{len(nodes)} tasks never became ready (node aliasing "
              "on classes with >2 parameters?); makespan/critical-path "
              "are lower bounds", file=sys.stderr)
    return {
        "tasks": len(nodes),
        "total_work": total,
        "critical_path": critical,
        "workers": nb_workers,
        "makespan": makespan,
        "speedup": round(total / makespan, 3) if makespan else 0.0,
        "efficiency": round(total / (makespan * nb_workers), 3)
                      if makespan else 0.0,
    }


def _sim_view(cg):
    """(cid, l0, l1) nodes + deduped edges from a concretized flow
    graph (the shape the trace-based enumerator used to produce)."""

    def key(node):
        cid, params = node
        p = tuple(params) + (0, 0)
        return (cid, p[0], p[1])

    nodes = [key((cid, params))
             for cid, plist in cg.instances.items() for params in plist]
    edges = [(key(src), key(dst))
             for src, outs in cg.succ.items() for dst, _ in outs]
    return nodes, edges


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("jdf")
    ap.add_argument("out")
    ap.add_argument("--global", dest="globs", action="append", default=[],
                    metavar="NAME=VALUE")
    ap.add_argument("--collection", default="mydata",
                    help="name bound to memory references (default mydata)")
    ap.add_argument("--size", type=int, default=256,
                    help="elements in the throwaway collection")
    ap.add_argument("--simulate", type=int, default=0, metavar="P",
                    help="list-schedule the DAG on P virtual workers "
                         "using BODY [weight=..] costs")
    args = ap.parse_args(argv)

    src = _noopify(open(args.jdf).read())
    globs = {}
    for g in args.globs:
        k, v = g.split("=", 1)
        globs[k.strip()] = int(v)
    globs.setdefault("NB", 10)
    globs.setdefault("N", 10)

    with pt.Context(nb_workers=1) as ctx:
        buf = np.zeros(args.size, dtype=np.int64)
        ctx.register_linear_collection(args.collection, buf, elem_size=8)
        ctx.register_arena("default", 64)
        b = compile_jdf(src, ctx, globals=globs, dtype=np.int64,
                        arenas={"A": "default"},
                        filename=os.path.basename(args.jdf))
        fg = extract_flowgraph(b.tp)
        report, cg = verify_graph(fg)

    dot = flowgraph_to_dot(cg, report.findings,
                           name=re.sub(r"\W", "_",
                                       os.path.basename(args.jdf)))
    with open(args.out, "w") as f:
        f.write(dot + "\n")
    print(f"{cg.nb_instances()} tasks, {cg.nb_edges} edges -> "
          f"{args.out}; findings: {len(report.findings)}")
    if report.findings:
        print(report.text(), file=sys.stderr)
    if args.simulate > 0:
        import json
        sim = simulate(_sim_view(cg), b.prog, b.gvals, args.simulate)
        print("simulate: " + json.dumps(sim))
    return 0


if __name__ == "__main__":
    sys.exit(main())
