#!/usr/bin/env python
"""Enumerate a JDF program's task DAG and emit DOT + per-class counts
(reference: tools/dagenum.c + the --parsec dot grapher).

Usage: python tools/jdf2dot.py prog.jdf out.dot [--global N=10 ...]
Bodies are replaced with no-ops; the program runs once on a throwaway
context with full tracing and the executed DAG is captured from EDGE
events.
"""
import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import parsec_tpu as pt  # noqa: E402
from parsec_tpu.dsl.jdf import compile_jdf  # noqa: E402
from parsec_tpu.profiling import take_trace, to_dot  # noqa: E402


def _noopify(src: str) -> str:
    """Replace every BODY{...}END block's code with 'pass'."""
    return re.sub(r"BODY\s*\{.*?\}\s*END", "BODY\n{\npass\n}\nEND", src,
                  flags=re.S)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("jdf")
    ap.add_argument("out")
    ap.add_argument("--global", dest="globs", action="append", default=[],
                    metavar="NAME=VALUE")
    ap.add_argument("--collection", default="mydata",
                    help="name bound to memory references (default mydata)")
    ap.add_argument("--size", type=int, default=256,
                    help="elements in the throwaway collection")
    args = ap.parse_args(argv)

    src = _noopify(open(args.jdf).read())
    globs = {}
    for g in args.globs:
        k, v = g.split("=", 1)
        globs[k.strip()] = int(v)
    globs.setdefault("NB", 10)
    globs.setdefault("N", 10)

    with pt.Context(nb_workers=1) as ctx:
        ctx.profile_enable(True)
        buf = np.zeros(args.size, dtype=np.int64)
        ctx.register_linear_collection(args.collection, buf, elem_size=8)
        ctx.register_arena("default", 64)
        b = compile_jdf(src, ctx, globals=globs, dtype=np.int64,
                        arenas={"A": "default"})
        tp = b.run()
        tp.wait()
        names = [t.name for t in b.prog.tasks]
        tr = take_trace(ctx, class_names=names)

    dot = to_dot(tr)
    with open(args.out, "w") as f:
        f.write(dot + "\n")
    counts = tr.counts()
    print(f"{tp.nb_total_tasks} tasks, {dot.count('->')} edges -> "
          f"{args.out}; events: {counts}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
