#!/bin/bash
# Probe the TPU tunnel; whenever it is up, run the next unfinished rung
# of the spotrf ladder, recording results in /tmp/spotrf_r4.jsonl.  A
# mid-ladder wedge keeps completed rungs and re-arms on the next probe
# cycle; the script exits when every rung has completed (or probes are
# exhausted).  The outer probe doubles as the pre-rung liveness check —
# exactly one JAX init per attempt.
#
# The smallest rung (N=8192) leads: it completes even on a slow tunnel,
# so a brief tunnel window still yields a driver-grade NB=512 number.
cd /root/repo
OUT=/tmp/spotrf_r4.jsonl
STATE=/tmp/spotrf_r4.done
touch $STATE
for i in $(seq 1 200); do
  remaining=0
  for cfg in "8192 512" "16384 512" "32768 512" "65536 512"; do
    grep -q "^$cfg$" $STATE || remaining=$((remaining + 1))
  done
  if [ $remaining -eq 0 ]; then
    echo "$(date -u +%H:%M:%S) ladder complete" >> $OUT
    exit 0
  fi
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    for cfg in "8192 512" "16384 512" "32768 512" "65536 512"; do
      grep -q "^$cfg$" $STATE && continue
      set -- $cfg
      echo "$(date -u +%H:%M:%S) rung N=$1 NB=$2 start" >> $OUT
      PTC_BENCH_PROFILE=1 timeout 2400 python bench.py --spotrf-child \
        --n $1 --nb $2 >> $OUT 2>&1
      rc=$?
      echo "$(date -u +%H:%M:%S) rung N=$1 NB=$2 rc=$rc" >> $OUT
      if [ $rc -eq 0 ]; then
        echo "$cfg" >> $STATE
      else
        break  # wedge/failure: back to probing, completed rungs kept
      fi
    done
  else
    sleep 300
  fi
done
echo "watcher gave up" >> $OUT
