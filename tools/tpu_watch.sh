#!/bin/bash
# Probe the TPU tunnel; whenever it is up, run the next unfinished step
# of the round-4 measurement plan, recording results in
# /tmp/spotrf_r4.jsonl.  A mid-step wedge keeps completed steps and
# re-arms on the next probe cycle; the script exits when every step has
# completed (or probes are exhausted).  The outer probe doubles as the
# pre-step liveness check — exactly one JAX init per attempt.
#
# Step order (value-per-tunnel-minute): the smallest NB=512 spotrf rung
# first (driver-grade headline number), then the ring-attention
# runtime-vs-GSPMD point (VERDICT #9), then the cross-process device
# data-plane table (VERDICT #5), then the larger spotrf rungs.
cd /root/repo
# log path shared with bench.py's cached-capture fallback
OUT=${PTC_WATCH_LOG:-/tmp/spotrf_r4.jsonl}
STATE=${PTC_WATCH_STATE:-/tmp/spotrf_r4.done}
touch $STATE

run_step() {  # name, command...
  local name="$1"; shift
  grep -q "^$name$" $STATE && return 0
  # 2-strike rule: a step that failed twice (bad rung for this chip,
  # persistent crash) is retired so it cannot eat every future tunnel
  # window retrying; later steps still get their chance
  local fails
  fails=$(grep -c "^$name$" $STATE.fail 2>/dev/null)
  fails=${fails:-0}
  if [ "$fails" -ge 2 ]; then
    echo "$(date -u +%H:%M:%S) step $name retired after $fails failures" >> $OUT
    echo "$name" >> $STATE
    return 0
  fi
  echo "$(date -u +%H:%M:%S) step $name start" >> $OUT
  timeout 2400 "$@" >> $OUT 2>&1
  local rc=$?
  echo "$(date -u +%H:%M:%S) step $name rc=$rc" >> $OUT
  if [ $rc -eq 0 ]; then
    echo "$name" >> $STATE
    return 0
  fi
  echo "$name" >> $STATE.fail
  return 1
}

STEPS="launch spotrf_4096 spotrf_8192 spotrf_8192_tiled ring dataplane dtdgemm spotrf_16384 spotrf_32768 spotrf_65536"

for i in $(seq 1 200); do
  # the driver's end-of-round bench claims the chip via this stop file
  [ -f /tmp/tpu_watch.stop ] && { echo "stopped by driver" >> $OUT; exit 0; }
  remaining=0
  for s in $STEPS; do
    grep -q "^$s$" $STATE || remaining=$((remaining + 1))
  done
  if [ $remaining -eq 0 ]; then
    echo "$(date -u +%H:%M:%S) plan complete" >> $OUT
    exit 0
  fi
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    run_step launch python tools/probe_launch_overhead.py || { sleep 300; continue; }
    PTC_BENCH_PROFILE=1 run_step spotrf_4096 \
      python bench.py --spotrf-child --n 4096 --nb 512 || { sleep 300; continue; }
    PTC_BENCH_PROFILE=1 run_step spotrf_8192 \
      python bench.py --spotrf-child --n 8192 --nb 512 || { sleep 300; continue; }
    # tiled-vs-panel comparison point at one size (honest dataflow cost)
    PTC_BENCH_PROFILE=1 run_step spotrf_8192_tiled \
      python bench.py --spotrf-child --n 8192 --nb 512 --tiled || { sleep 300; continue; }
    run_step ring python bench.py --ring || { sleep 300; continue; }
    run_step dataplane python tools/bench_dataplane.py || { sleep 300; continue; }
    run_step dtdgemm python tools/bench_dtd_gemm.py || { sleep 300; continue; }
    PTC_BENCH_PROFILE=1 run_step spotrf_16384 \
      python bench.py --spotrf-child --n 16384 --nb 512 || { sleep 300; continue; }
    PTC_BENCH_PROFILE=1 run_step spotrf_32768 \
      python bench.py --spotrf-child --n 32768 --nb 512 || { sleep 300; continue; }
    PTC_BENCH_PROFILE=1 run_step spotrf_65536 \
      python bench.py --spotrf-child --n 65536 --nb 512 || { sleep 300; continue; }
  else
    sleep 300
  fi
done
echo "watcher gave up" >> $OUT
