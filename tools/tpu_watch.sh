#!/bin/bash
# Probe the TPU tunnel; when it comes back, run the spotrf bench ladder
# and leave results in /tmp/spotrf_r3.jsonl.  Re-probe before each rung
# so a mid-ladder wedge stops the ladder (keeping the rungs already
# recorded) instead of burning the per-rung timeout on a dead tunnel.
cd /root/repo
OUT=/tmp/spotrf_r3.jsonl
for i in $(seq 1 200); do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel alive" >> $OUT
    for cfg in "16384 512" "32768 512" "65536 512"; do
      set -- $cfg
      if ! timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1
      then
        echo "$(date -u +%H:%M:%S) tunnel dropped before N=$1" >> $OUT
        break
      fi
      echo "$(date -u +%H:%M:%S) rung N=$1 NB=$2 start" >> $OUT
      PTC_BENCH_PROFILE=1 timeout 2400 python bench.py --spotrf-child \
        --n $1 --nb $2 >> $OUT 2>&1
      echo "$(date -u +%H:%M:%S) rung N=$1 NB=$2 rc=$?" >> $OUT
    done
    exit 0
  fi
  sleep 300
done
echo "watcher gave up" >> $OUT
