#!/usr/bin/env python
"""Cross-PROCESS device data-plane measurement (VERDICT r3 #5).

Two separate OS processes (the real multi-host shape — no shared jax
client, so the colocated by-reference shortcut cannot apply), one
device-resident tile per size rung crossing rank 0 -> rank 1 through the
PK_DEVICE rendezvous: producing-side lazy d2h at serve time, TCP, h2d on
the consumer.  This is the fallback path whose cost decides whether a
platform-level cross-host device transfer is worth building (reference
seam: transport-native payload movement end to end,
parsec/parsec_comm_engine.h:139-160; SURVEY §7 hard-part 2).

Emits one JSON line per tile size:
  {"tile_mb": M, "xfer_ms": t, "gbps": g, "d2h_bytes": ..., "h2d_bytes": ...}

Run (needs the real chip; each rank owns the whole chip in turn — the
axon tunnel serializes, which is itself part of the measured reality):
  python tools/bench_dataplane.py            # all rungs
  python tools/bench_dataplane.py --mb 16    # one rung
"""
import json
import multiprocessing as mp
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _prod_kernel(x):
    # module-level: the process-wide jit cache keys on kernel identity,
    # so every rep reuses one compiled executable (a per-rep lambda
    # re-traced and re-compiled EVERY rep — ~100 ms of setup charged to
    # each "transfer" in the old 118 ms/4 MiB baseline row)
    return x + 1.0


def _cons_kernel(x):
    return x * 1.0


def _worker(rank, nodes, port, mb, reps, q, transfer=False):
    try:
        import jax
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            # smoke runs: the axon plugin overrides the env var — force
            # the platform BEFORE backend init or a dead tunnel hangs us
            jax.config.update("jax_platforms", "cpu")
        import parsec_tpu as pt
        from parsec_tpu.device import TpuDevice

        os.environ["PTC_MCA_comm_eager_limit"] = "65536"
        if transfer:
            os.environ["PTC_MCA_device_dp_transfer"] = "1"
        ctx = pt.Context(nb_workers=1)
        ctx.set_rank(rank, nodes)
        ctx.comm_init(port)
        elems = mb * (1 << 20) // 4
        esize = elems * 4
        arr = np.zeros((nodes, elems), dtype=np.float32)
        ctx.register_linear_collection("A", arr, elem_size=esize,
                                       nodes=nodes, myrank=rank)
        ctx.register_arena("t", esize)
        dev = TpuDevice(ctx)
        k = pt.L("k")
        times = []
        for rep in range(reps + 1):  # rep 0 = compile warmup
            tp = pt.Taskpool(ctx, globals={"R": rep})
            prod = tp.task_class("Prod")
            prod.param("k", 0, 0)
            prod.affinity("A", 0)
            cons = tp.task_class("Cons")
            cons.param("k", 0, 0)
            cons.affinity("A", 1)
            prod.flow("X", "RW", pt.In(pt.Mem("A", 0)),
                      pt.Out(pt.Ref("Cons", k, flow="X")))
            cons.flow("X", "R", pt.In(pt.Ref("Prod", k, flow="X")),
                      arena="t")
            cons.flow("Y", "W", pt.Out(pt.Mem("A", 1)), arena="t")
            dev.attach(prod, tp, kernel=_prod_kernel, reads=["X"],
                       writes=["X"], shapes={"X": (elems,)},
                       dtype=np.float32)
            dev.attach(cons, tp, kernel=_cons_kernel, reads=["X"],
                       writes=["Y"], shapes={"X": (elems,), "Y": (elems,)},
                       dtype=np.float32)
            ctx.comm_fence()  # both ranks ready: isolate the transfer
            t0 = time.perf_counter()
            tp.run()
            tp.wait()
            ctx.comm_fence()
            dt = time.perf_counter() - t0
            if rep == 0:
                base = dict(dev.stats)  # exclude compile-warmup traffic
            else:
                times.append(dt)
        end = dict(dev.stats)
        st = {k: (end.get(k, 0) - base.get(k, 0)) / reps
              for k in ("d2h_bytes", "h2d_bytes")}
        dev.stop()
        ctx.comm_fini()
        ctx.destroy()
        st["dp_xfer_bytes"] = (end.get("dp_xfer_bytes", 0)
                               - base.get("dp_xfer_bytes", 0)) / reps
        q.put(("ok", rank, min(times), st["d2h_bytes"], st["h2d_bytes"],
               st["dp_xfer_bytes"]))
    except Exception:
        import traceback
        q.put(("err", rank, traceback.format_exc(), 0, 0, 0))


def run_rung(mb, port, reps=3, transfer=False):
    mpctx = mp.get_context("spawn")
    q = mpctx.Queue()
    procs = [mpctx.Process(target=_worker,
                           args=(r, 2, port, mb, reps, q, transfer))
             for r in range(2)]
    for p in procs:
        p.start()
    try:
        res = [q.get(timeout=1200) for _ in range(2)]
    finally:
        # a wedged tunnel must not orphan children holding the TPU
        # client and the rung's ports (they would block every later step
        # of the watch plan)
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    errs = [r for r in res if r[0] != "ok"]
    if errs:
        raise RuntimeError(str(errs))
    wall = max(r[2] for r in res)  # transfer completes on the slower side
    xfer_b = sum(r[5] for r in res)
    return {
        "tile_mb": mb,
        "path": "transfer" if transfer else "bytes",
        # what actually moved the payload: a pull-incapable PJRT (probe
        # failed) degrades a requested transfer run to bytes — report it
        "path_taken": "transfer" if xfer_b > 0 else "bytes",
        "xfer_ms": round(wall * 1e3, 2),
        "gbps": round(mb / 1024 / wall * 8, 3),
        "d2h_bytes": sum(r[3] for r in res),
        "h2d_bytes": sum(r[4] for r in res),
        "dp_xfer_bytes": xfer_b,
    }


def main():
    mbs = [1, 4, 16, 64]
    if "--mb" in sys.argv:
        mbs = [int(sys.argv[sys.argv.index("--mb") + 1])]
    base = int(os.environ.get("PTC_PORT", "31100"))
    i = 0
    for mb in mbs:
        for transfer in (False, True):
            try:
                print(json.dumps(run_rung(mb, base + 2 * i,
                                          transfer=transfer)), flush=True)
            except Exception as e:
                print(json.dumps({"tile_mb": mb,
                                  "path": "transfer" if transfer
                                  else "bytes",
                                  "error": str(e)[:300]}), flush=True)
            i += 1


if __name__ == "__main__":
    main()
