#!/usr/bin/env python
"""Run ptc-plan (parsec_tpu.analysis.plan) over every in-tree graph
generator — the same GENERATORS table `make verify-graphs` walks — and
assert the plan baseline: every graph plans CLEAN (no enumeration
refusal at the default tilings, finite residency/makespan bounds) and
the potrf bench tiling (NT=16, the BENCH_r05 rung-5 grid) plans inside
its latency budget.

`make plan-graphs` runs this; the tier-1 test
tests/analysis/test_plan_intree.py locks the baseline, and the emitted
PLAN_graphs.json feeds a bench_check trajectory row guarding analyzer
runtime (potrf_nt16_ms).

Usage: python tools/plan_graphs.py [--json out.json] [-v] [only ...]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import parsec_tpu as pt  # noqa: E402
from parsec_tpu.data.collections import TwoDimBlockCyclic  # noqa: E402

import verify_graphs  # noqa: E402  (the shared GENERATORS table)

# analyzer latency budget on the potrf bench tiling (seconds); the
# tier-1 baseline test asserts the same bound
POTRF_NT16_BUDGET_S = 5.0


def plan_all(only=None, verbose=False):
    """Build + plan every generator.  Yields (name, Plan)."""
    from parsec_tpu.analysis import plan_taskpool
    for gname, gen in verify_graphs.GENERATORS.items():
        if only and gname not in only:
            continue
        with pt.Context(nb_workers=1) as ctx:
            for tpname, tp in gen(ctx):
                plan = plan_taskpool(tp)
                if verbose:
                    print(f"--- {tpname}:\n{plan.text()}")
                yield tpname, plan


def plan_issues(plan) -> list:
    """Baseline violations for one graph's plan: enumeration refusals,
    unbounded/absent residency or makespan numbers, waves without an
    explicit fusability verdict (certify/refuse — silent skips are a
    baseline violation, refusals are not)."""
    issues = []
    if plan.bounded:
        issues.append("enumeration refused (symbolic fallback)")
        return issues
    if not plan.per_rank:
        issues.append("no per-rank rows")
    if plan.est_bytes() is None:
        issues.append("unbounded residency estimate")
    if plan.stats.get("waves", 0) <= 0:
        issues.append("no wave schedule")
    m = plan.makespan
    if not m or m.get("lower_bound_ns", 0) <= 0:
        issues.append("no finite makespan lower bound")
    waves = {(r, row["wave"]) for r, rows in plan.waves.items()
             for row in rows}
    certified = {(c["rank"], c["wave"]) for c in plan.fusability}
    missing = waves - certified
    if missing:
        issues.append(f"{len(missing)} wave(s) without a fusability "
                      "verdict")
    return issues


def potrf_nt16_ms() -> float:
    """Plan the potrf bench tiling (NT=16 -> 816 instances; tiles
    shrunk to 8 wide — analysis cost depends only on the tile grid)."""
    from parsec_tpu.algos.potrf import build_potrf
    from parsec_tpu.analysis import plan_taskpool
    with pt.Context(nb_workers=1) as ctx:
        A = TwoDimBlockCyclic(16 * 8, 16 * 8, 8, 8, dtype=np.float32)
        A.register(ctx, "A")
        tp = build_potrf(ctx, A)
        t0 = time.perf_counter()
        plan = plan_taskpool(tp)
        dt = time.perf_counter() - t0
    assert plan.stats["instances"] == 816, plan.stats
    return dt * 1e3


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("only", nargs="*", help="generator names (default all)")
    ap.add_argument("--json", metavar="PATH", default=None)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    dirty = 0
    results = {}
    for name, plan in plan_all(args.only or None, args.verbose):
        issues = plan_issues(plan)
        peak = plan.peak_bytes()
        status = ("clean" if not issues else "; ".join(issues))
        fus = plan.fusable_waves()
        chained = plan.chained_waves()
        print(f"{name:24s} {status}  "
              f"[{plan.stats.get('instances', 0)} inst, "
              f"{plan.stats.get('waves', 0)} wave(s), "
              f"{fus} fusable, {chained} chained, peak {peak} B, "
              f"{plan.stats.get('elapsed_ms', 0):.0f} ms]")
        if issues:
            dirty += 1
        results[name] = {
            "issues": issues,
            "instances": plan.stats.get("instances", 0),
            "waves": plan.stats.get("waves", 0),
            "fusable_waves": fus,
            "chained_waves": chained,
            "chain_pairs": len(plan.chains),
            "certified_waves": len(plan.fusability),
            "peak_bytes": peak,
            "est_bytes": plan.est_bytes(),
            "comm_bytes": plan.comm_bytes(),
            "coll_bytes": plan.coll_bytes(),
            "coll_legs": len(plan.coll_legs()),
            "makespan_lower_ns": plan.makespan.get("lower_bound_ns", 0),
            "elapsed_ms": round(plan.stats.get("elapsed_ms", 0), 2),
        }
    timing_ms = None
    if not args.only:
        timing_ms = potrf_nt16_ms()
        over = timing_ms / 1e3 > POTRF_NT16_BUDGET_S
        print(f"potrf NT=16 plan: {timing_ms:.1f} ms "
              f"(budget {POTRF_NT16_BUDGET_S:.0f} s)"
              + (" OVER BUDGET" if over else ""))
        if over:
            dirty += 1
    if args.json:
        try:
            import bench
            prov = bench.host_provenance()
        except Exception:
            prov = {}
        payload = {
            "graphs": results,
            "potrf_nt16_ms": (round(timing_ms, 1)
                              if timing_ms is not None else None),
            "potrf_nt16_budget_s": POTRF_NT16_BUDGET_S,
        }
        payload.update(prov)
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
    print(f"plan-graphs: {len(results)} graph(s), {dirty} with issues")
    return 1 if dirty else 0


if __name__ == "__main__":
    sys.exit(main())
