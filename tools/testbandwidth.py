#!/usr/bin/env python
"""Comm-engine bandwidth/latency microbench (reference roles:
tests/apps/pingpong/bandwidth.jdf for the transport and
tools/gpu/testbandwidth for the device staging path).

Two SPMD processes over loopback TCP run a rank-hopping RW chain whose
datum is a tile of the given size: each hop is one full payload transfer
(eager inline, or GET rendezvous above the eager limit).  Reported per
size: hop latency (wall / hops) and payload bandwidth.  With --device,
the same chain runs with device chores so every hop additionally pays
device stage-out/stage-in (the h2d/d2h testbandwidth role; uses the real
chip when the tunnel is up, else the CPU jax backend).

  python tools/testbandwidth.py                 # host path, 4K..16M
  python tools/testbandwidth.py --sizes 1048576 --hops 64
  python tools/testbandwidth.py --device
"""
import json
import multiprocessing as mp
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _bump(x):
    # module-level: the device executable cache keys on kernel identity,
    # so the warmup build really pre-compiles for the timed build
    return x + 1.0


def _worker(rank, port, size, hops, device, q):
    try:
        import jax
        if os.environ.get("JAX_PLATFORMS") == "cpu" or not device:
            jax.config.update("jax_platforms", "cpu")
        import parsec_tpu as pt

        ctx = pt.Context(nb_workers=1)
        ctx.set_rank(rank, 2)
        ctx.comm_init(port)
        elems = size // 4
        arr = np.zeros((2, elems), dtype=np.float32)
        ctx.register_linear_collection("A", arr, elem_size=size,
                                       nodes=2, myrank=rank)
        ctx.register_arena("t", size)
        dev = None
        if device:
            from parsec_tpu.device import TpuDevice
            dev = TpuDevice(ctx)
        k = pt.L("k")

        def build():
            tp = pt.Taskpool(ctx, globals={"NB": hops})
            tc = tp.task_class("Hop")
            tc.param("k", 0, pt.G("NB"))
            tc.affinity("A", k % 2)
            tc.flow("A", "RW",
                    pt.In(pt.Mem("A", 0), guard=(k == 0)),
                    pt.In(pt.Ref("Hop", k - 1, flow="A")),
                    pt.Out(pt.Ref("Hop", k + 1, flow="A"),
                           guard=(k < pt.G("NB"))),
                    arena="t")
            if dev is not None:
                dev.attach(tc, tp, kernel=_bump, reads=["A"],
                           writes=["A"], shapes={"A": (elems,)},
                           dtype=np.float32)
            tc.body_noop()
            return tp

        tp = build()  # warmup: connections + (device) compile
        tp.run()
        tp.wait()
        ctx.comm_fence()
        tp = build()
        t0 = time.perf_counter()
        tp.run()
        tp.wait()
        ctx.comm_fence()
        dt = time.perf_counter() - t0
        if dev is not None:
            dev.stop()
        ctx.comm_fini()
        ctx.destroy()
        q.put(("ok", rank, dt))
    except Exception:
        import traceback
        q.put(("err", rank, traceback.format_exc()))


def run_size(size, hops, port, device=False):
    mpctx = mp.get_context("spawn")
    q = mpctx.Queue()
    procs = [mpctx.Process(target=_worker,
                           args=(r, port, size, hops, device, q))
             for r in range(2)]
    for p in procs:
        p.start()
    try:
        res = [q.get(timeout=900) for _ in range(2)]
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    errs = [r for r in res if r[0] != "ok"]
    if errs:
        raise RuntimeError(str(errs))
    wall = max(r[2] for r in res)
    return {
        "size_bytes": size,
        "hops": hops,
        "hop_latency_us": round(wall / hops * 1e6, 2),
        "bandwidth_gbps": round(size * hops / wall * 8 / 1e9, 3),
        "path": "device" if device else "host",
    }


def main():
    sizes = [4096, 65536, 1048576, 16777216]
    hops = 32
    device = "--device" in sys.argv
    if "--sizes" in sys.argv:
        sizes = [int(x) for x in
                 sys.argv[sys.argv.index("--sizes") + 1].split(",")]
    if "--hops" in sys.argv:
        hops = int(sys.argv[sys.argv.index("--hops") + 1])
    base = int(os.environ.get("PTC_PORT", "31300"))
    for i, size in enumerate(sizes):
        try:
            print(json.dumps(run_size(size, hops, base + 2 * i,
                                      device=device)), flush=True)
        except Exception as e:
            print(json.dumps({"size_bytes": size, "error": str(e)[:300]}),
                  flush=True)


if __name__ == "__main__":
    main()
