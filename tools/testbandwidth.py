#!/usr/bin/env python
"""Transfer-economics harness (reference roles: tests/apps/pingpong/
bandwidth.jdf for the transport, tools/gpu/testbandwidth for the device
staging path) — the project's tunnel-independent way to validate
dispatch/transfer economics on loopback.

Two SPMD processes over loopback TCP run rank-hopping RW chains whose
datum is a tile of the given size; every hop is one full cross-rank
payload transfer.  ONE persistent process pair serves an entire path
sweep — all sizes and reps share the TCP mesh, the device, the jit
cache and (for PK_DEVICE) the transfer sessions — so the numbers
measure steady-state per-transfer cost, with the first (warmup) rep's
wall reported separately as `setup_ms` (session establishment, first
compile, first staging).  That split is the point: the old
per-process-pair, per-rep-recompile measurement charged ~100 ms of
setup to every transfer (BASELINE.md row 1d, 118 ms / 4 MiB).

Paths swept (each in its own process pair, selected by env knobs):
  eager   — payloads ride inline in ACTIVATE frames (eager_limit huge)
  rdv     — every payload pulled via GET rendezvous (eager_limit 0);
            payloads above comm.chunk_size stream as pipelined chunks
  device  — TpuDevice attached (jax CPU backend on loopback, the real
            chip when PTC_BENCH_TPU=1): payloads ride the PK_DEVICE
            device data plane (d2h at serve / h2d at deliver)

Per path the harness fits  t(size) = fixed_overhead + size * per_byte
by least squares over the per-size minima and reports both legs — the
same two quantities the adaptive eager threshold is derived from, so
the model is checkable against the engine's own calibration (also
reported, from a dedicated eager_limit=auto run).

ptc-topo: `--classed` (or an explicit `--classes ici,dcn`) re-runs the
wire paths per LINK CLASS and publishes the per-class fits under
doc["classes"] = {cls: {path: {"fit": ...}}} — exactly the shape
TransferEconomics.load consumes for class-aware pricing.  On loopback
the dcn class is EMULATED with the native per-peer fault delay map
(PTC_COMM_FAULT_DELAY_MAP, --dcn-delay-us µs per recv) — the same
deterministic island emulator the topology tests use; on a real
multi-host deployment run the harness once per link class between
hosts of that class and merge the docs.

  python tools/testbandwidth.py                        # full sweep
  python tools/testbandwidth.py --paths device --sizes 4194304
  python tools/testbandwidth.py --quick --json /tmp/comm.json
  python tools/testbandwidth.py --quick --classed      # + per-class fits
  make bench-comm                                      # BENCH-style file
"""
import json
import multiprocessing as mp
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

_PATH_ENV = {
    # eager: everything inline (rendezvous never engages)
    "eager": {"PTC_MCA_comm_eager_limit": str(1 << 30)},
    # rdv: everything pulled (chunked above comm.chunk_size)
    "rdv": {"PTC_MCA_comm_eager_limit": "0"},
    # device: rendezvous forced so device-resident payloads advertise
    # PK_DEVICE transfer tags
    "device": {"PTC_MCA_comm_eager_limit": "0"},
}


def _bump(x):
    # module-level ON PURPOSE: the process-wide jit cache keys on kernel
    # identity, so every taskpool of a sweep reuses ONE compiled
    # executable per shape.  A per-rep lambda would recompile each rep —
    # exactly the setup cost the old 118 ms/4 MiB number was paying.
    return x + 1.0


def _worker(rank, port, sizes, hops, reps, path, env, q):
    try:
        for k, v in env.items():
            os.environ[k] = v
        import jax
        if not os.environ.get("PTC_BENCH_TPU"):
            jax.config.update("jax_platforms", "cpu")
        import parsec_tpu as pt

        ctx = pt.Context(nb_workers=1)
        ctx.set_rank(rank, 2)
        ctx.comm_init(port)
        dev = None
        if path == "device":
            from parsec_tpu.device import TpuDevice
            dev = TpuDevice(ctx)
        k = pt.L("k")
        out = []
        for si, size in enumerate(sizes):
            elems = max(1, size // 4)
            arr = np.zeros((2, elems), dtype=np.float32)
            ctx.register_linear_collection(f"A{si}", arr, elem_size=size,
                                           nodes=2, myrank=rank)
            ctx.register_arena(f"t{si}", size)

            def build():
                tp = pt.Taskpool(ctx, globals={"NB": hops})
                tc = tp.task_class("Hop")
                tc.param("k", 0, pt.G("NB"))
                tc.affinity(f"A{si}", k % 2)
                tc.flow("A", "RW",
                        pt.In(pt.Mem(f"A{si}", 0), guard=(k == 0)),
                        pt.In(pt.Ref("Hop", k - 1, flow="A")),
                        pt.Out(pt.Ref("Hop", k + 1, flow="A"),
                               guard=(k < pt.G("NB"))),
                        arena=f"t{si}")
                if dev is not None:
                    dev.attach(tc, tp, kernel=_bump, reads=["A"],
                               writes=["A"], shapes={"A": (elems,)},
                               dtype=np.float32)
                else:
                    tc.body_noop()
                return tp

            walls = []
            for rep in range(reps + 1):  # rep 0 = setup (reported apart)
                tp = build()
                ctx.comm_fence()  # both ranks ready: isolate the chain
                t0 = time.perf_counter()
                tp.run()
                tp.wait()
                ctx.comm_fence()
                walls.append(time.perf_counter() - t0)
            out.append({"size_bytes": size, "setup_ms": walls[0] * 1e3,
                        "walls": walls[1:]})
        tuning = ctx.comm_tuning()
        dstats = dict(dev.stats) if dev is not None else None
        if dev is not None:
            dev.stop()
        ctx.comm_fini()
        ctx.destroy()
        q.put(("ok", rank, out, tuning, dstats))
    except Exception:
        import traceback
        q.put(("err", rank, traceback.format_exc(), None, None))


# the fit lives in parsec_tpu/comm/economics.py now: the topology
# selector consumes exactly the model this harness publishes, so the
# two can never diverge (and ROADMAP item 5's per-link-class routing
# reuses the same loader)
from parsec_tpu.comm.economics import fit_points as _fit  # noqa: E402


def run_path(path, sizes, hops, reps, port, extra_env=None):
    """Sweep all `sizes` on one persistent 2-process pair; returns the
    path's report dict (latencies, setup costs, fit, tunables)."""
    env = dict(_PATH_ENV[path])
    env.update(extra_env or {})
    mpctx = mp.get_context("spawn")
    q = mpctx.Queue()
    procs = [mpctx.Process(target=_worker,
                           args=(r, port, sizes, hops, reps, path, env, q))
             for r in range(2)]
    for p in procs:
        p.start()
    try:
        res = [q.get(timeout=1800) for _ in range(2)]
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    errs = [r for r in res if r[0] != "ok"]
    if errs:
        raise RuntimeError(str(errs))
    # per size: the transfer completes on the slower side
    by_rank = {r[1]: r for r in res}
    rows, points = [], []
    for si, size in enumerate(sizes):
        walls = [max(by_rank[0][2][si]["walls"][i],
                     by_rank[1][2][si]["walls"][i])
                 for i in range(len(by_rank[0][2][si]["walls"]))]
        per_transfer = [w / hops for w in walls]
        best = min(per_transfer)
        rows.append({
            "size_bytes": size,
            "setup_ms": round(max(by_rank[0][2][si]["setup_ms"],
                                  by_rank[1][2][si]["setup_ms"]), 2),
            "per_transfer_ms": round(best * 1e3, 3),
            "per_transfer_ms_all": [round(t * 1e3, 3)
                                    for t in per_transfer],
            "gbps": round(size * 8 / best / 1e9, 3),
        })
        points.append((size, best))
    return {
        "sizes": rows,
        "fit": _fit(points),
        "tunables": by_rank[0][3],
        "device_stats": by_rank[0][4],
    }


def run_adaptive_probe(port):
    """One tiny eager_limit=auto job, reported so every sweep records
    what threshold the engine would derive on this host (the measured
    RTT and memcpy legs come back via comm_tuning)."""
    rep = run_path("eager", [4096], hops=8, reps=1, port=port,
                   extra_env={"PTC_MCA_comm_eager_limit": "auto"})
    t = rep["tunables"]
    return {"derived_eager_limit": t["eager_limit"],
            "rtt_ns": t["rtt_ns"], "memcpy_bps": t["memcpy_bps"]}


def _arg(flag, default=None):
    if flag in sys.argv:
        return sys.argv[sys.argv.index(flag) + 1]
    return default


def main():
    quick = "--quick" in sys.argv
    sizes = [65536, 1048576, 4194304] if not quick else [4096, 65536]
    hops = int(_arg("--hops", 8 if quick else 16))
    reps = int(_arg("--reps", 2 if quick else 3))
    paths = ["eager", "rdv", "device"]
    if "--device" in sys.argv:  # legacy spelling
        paths = ["device"]
    if _arg("--paths"):
        paths = _arg("--paths").split(",")
    if _arg("--sizes"):
        sizes = [int(x) for x in _arg("--sizes").split(",")]
    base = int(os.environ.get("PTC_PORT", "31300"))
    # shared provenance/oversubscription capture (bench.host_provenance
    # replaced this harness's private copy): 2 ranks x (worker + comm
    # thread [+ device lanes on the device path])
    from bench import host_provenance
    doc = {
        "bench": "transfer_economics",
        "when": time.strftime("%Y-%m-%d %H:%M:%S"),
        **host_provenance(threads=2 * 2),
        "meta": {"hops": hops, "reps": reps, "sizes": sizes,
                 "nodes": 2,
                 "platform": ("tpu" if os.environ.get("PTC_BENCH_TPU")
                              else "cpu-loopback")},
        "paths": {},
    }
    port = base
    try:
        doc["adaptive_eager"] = run_adaptive_probe(port)
    except Exception as e:
        doc["adaptive_eager"] = {"error": str(e)[:300]}
    port += 4
    for path in paths:
        try:
            doc["paths"][path] = run_path(path, sizes, hops, reps, port)
        except Exception as e:
            doc["paths"][path] = {"error": str(e)[:300]}
        print(json.dumps({path: doc["paths"][path]}), flush=True)
        port += 4
    # ptc-topo classed sweep: the wire paths again, once per link
    # class.  ici = the plain loopback wire; dcn = the same wire under
    # the per-peer fault delay map (deterministic island emulation).
    # The device path is skipped — staging is class-independent.
    if "--classed" in sys.argv or _arg("--classes"):
        cls_list = [c for c in (_arg("--classes") or "ici,dcn").split(",")
                    if c]
        dcn_us = int(_arg("--dcn-delay-us", "150"))
        doc["meta"]["dcn_delay_us"] = dcn_us
        doc["classes"] = {}
        for cls_name in cls_list:
            extra = {}
            if cls_name == "dcn":
                extra = {"PTC_COMM_FAULT_DELAY_MAP":
                         f"0:{dcn_us},1:{dcn_us}"}
            doc["classes"][cls_name] = {}
            for path in paths:
                if path == "device":
                    continue
                try:
                    doc["classes"][cls_name][path] = run_path(
                        path, sizes, hops, reps, port, extra_env=extra)
                except Exception as e:
                    doc["classes"][cls_name][path] = \
                        {"error": str(e)[:300]}
                print(json.dumps(
                    {f"{cls_name}.{path}":
                     doc["classes"][cls_name][path]}), flush=True)
                port += 4
    out = _arg("--json")
    if out:
        with open(out, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"wrote {out}", file=sys.stderr)
    else:
        print(json.dumps(doc), flush=True)


if __name__ == "__main__":
    main()
