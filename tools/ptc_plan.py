#!/usr/bin/env python
"""ptc-plan CLI: static resource & schedule analysis of PTG task graphs
(parsec_tpu/analysis/plan.py — peak tile residency, wave decomposition,
comm volume, makespan lower bounds).

Input is either a .jdf file (compiled, never executed) or the name of
an in-tree graph generator from tools/verify_graphs.py:

    python tools/ptc_plan.py potrf
    python tools/ptc_plan.py prog.jdf --global N=10 --waves
    python tools/ptc_plan.py gemm --json plan.json
    python tools/ptc_plan.py potrf --profile prof.json --trace run.ptt

`--waves` prints the per-rank wave table (the ready fronts grouped by
task class — the mega-kernelization prep artifact).  `--profile` seeds
the cost model from a recorded {"classes": {name: ns}} JSON; `--trace`
loads a level-2 .ptt and prints predicted-vs-EXECUTED critical path —
the regression signal that keeps the model honest.

Exit status: 0 on a finite plan, 1 when enumeration was refused
(symbolic fallback) or the analysis found nothing to bound, 2 on usage
errors.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import parsec_tpu as pt  # noqa: E402


def _plan_jdf(args, cost):
    from parsec_tpu.analysis import extract_flowgraph, plan_graph
    from parsec_tpu.dsl.jdf import compile_jdf
    src = open(args.target).read()
    globs = {}
    for g in args.globs:
        k, v = g.split("=", 1)
        globs[k.strip()] = int(v)
    globs.setdefault("NB", 10)
    globs.setdefault("N", 10)
    with pt.Context(nb_workers=1) as ctx:
        buf = np.zeros(args.size, dtype=np.int64)
        ctx.register_linear_collection(args.collection, buf, elem_size=8)
        ctx.register_arena("default", 64)
        b = compile_jdf(src, ctx, globals=globs, dtype=np.int64,
                        arenas={"A": "default"},
                        filename=os.path.basename(args.target))
        fg = extract_flowgraph(b.tp)
        plan = plan_graph(fg, max_instances=args.max_instances, cost=cost)
        return {os.path.basename(args.target): plan}


def _plan_intree(args, cost):
    import plan_graphs
    import verify_graphs
    if args.target != "all" and args.target not in verify_graphs.GENERATORS:
        print(f"ptc-plan: no file and no in-tree generator named "
              f"{args.target!r}; generators: "
              f"{', '.join(sorted(verify_graphs.GENERATORS))}",
              file=sys.stderr)
        sys.exit(2)
    only = None if args.target == "all" else [args.target]
    # the shared driver ignores `cost` (generator pools are cold); a
    # --profile cost model only applies to .jdf targets
    return dict(plan_graphs.plan_all(only=only))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("target",
                    help=".jdf file, in-tree generator name, or 'all'")
    ap.add_argument("--global", dest="globs", action="append", default=[],
                    metavar="NAME=VALUE")
    ap.add_argument("--collection", default="mydata",
                    help="collection name bound to memory references")
    ap.add_argument("--size", type=int, default=256,
                    help="elements in the throwaway collection")
    ap.add_argument("--max-instances", type=int, default=200_000,
                    help="concrete-enumeration budget (past it the "
                         "analysis degrades to interval bounds)")
    ap.add_argument("--profile", metavar="PATH", default=None,
                    help="cost-model JSON ({'classes': {name: ns}})")
    ap.add_argument("--trace", metavar="PATH", default=None,
                    help="level-2 .ptt: print predicted vs EXECUTED "
                         "critical path")
    ap.add_argument("--waves", action="store_true",
                    help="print the per-rank wave table")
    ap.add_argument("--json", dest="json_out", metavar="PATH",
                    default=None)
    args = ap.parse_args(argv)

    cost = None
    if args.profile:
        from parsec_tpu.analysis import CostModel
        cost = CostModel.from_json(args.profile)

    if os.path.exists(args.target):
        plans = _plan_jdf(args, cost)
    else:
        plans = _plan_intree(args, cost)

    rc = 0
    for name, plan in plans.items():
        if len(plans) > 1:
            print(f"=== {name}")
        print(plan.text(waves=args.waves))
        if plan.bounded or not plan.per_rank:
            rc = 1
        if args.trace:
            from parsec_tpu.analysis import compare_critpath
            from parsec_tpu.profiling.trace import Trace
            cmp = compare_critpath(plan, Trace.load(args.trace))
            print(f"  critpath predicted {cmp['predicted_ns'] / 1e6:.3f} ms "
                  f"vs executed {cmp['executed_ns'] / 1e6:.3f} ms "
                  f"(ratio {cmp['ratio']}; predicted path "
                  f"{cmp['predicted_path_len']} task(s), executed "
                  f"{cmp['executed_path_len']})")
    if args.json_out:
        payload = {n: p.to_json() for n, p in plans.items()}
        with open(args.json_out, "w") as f:
            json.dump(payload, f, indent=1)
    return rc


if __name__ == "__main__":
    sys.exit(main())
