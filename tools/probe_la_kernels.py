#!/usr/bin/env python
"""Per-kernel cost probe for the dense-LA tile kernels (potrf.py) on
whatever chip JAX sees — answers WHERE the spotrf wall time goes before
any optimization is attempted (VERDICT r3 weak #1 follow-through: make
perf work data-driven).

Times, per tile shape (NB x NB) and batch width B:
  chol      jnp.linalg.cholesky           (POTRF diagonal, B=1)
  trsm      vmapped solve_triangular      (TRSM panel wave)
  trsm_inv  tri inverse once + vmapped GEMM against it (the MXU-friendly
            TRSM replacement: solve_triangular(L, I) -> batched matmul)
  syrk      vmapped A@A^T subtract        (SYRK wave)
  gemm      vmapped A@B^T subtract        (GEMM wave, the FLOPs bulk)
  launch    empty-ish kernel (x+1 on 8 floats) — per-call dispatch floor
            through whatever transport fronts the chip (axon tunnel RTT)

Emits one JSON line per measurement:
  {"kernel": k, "nb": NB, "batch": B, "ms": t, "gflops": g, "chip": kind}
"""
import json
import sys
import time

import numpy as np


def _force(out):
    """Force completion with a scalar readback: block_until_ready can
    return early through the axon tunnel (same workaround as
    bench.py _chip_info)."""
    import jax
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(leaf.ravel()[0])


def _time(f, *args, reps=5):
    """Median wall of reps calls, forcing the result each time."""
    _force(f(*args))  # compile + settle
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _force(f(*args))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def main():
    import os

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # the axon TPU plugin overrides the env var; config.update wins
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    from parsec_tpu.algos.potrf import k_gemm, k_potrf, k_syrk, k_trsm

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu")
    nbs = [512]
    if "--nb" in sys.argv:
        nbs = [int(sys.argv[sys.argv.index("--nb") + 1])]
    batches = [8, 32]
    if "--batch" in sys.argv:
        batches = [int(sys.argv[sys.argv.index("--batch") + 1])]

    def emit(kernel, nb, batch, dt, flops):
        print(json.dumps({"kernel": kernel, "nb": nb, "batch": batch,
                          "ms": round(dt * 1e3, 3),
                          "gflops": round(flops / dt / 1e9, 1),
                          "chip": kind}), flush=True)

    # dispatch floor: what does ANY call cost end to end?
    tiny = jnp.ones((8,), jnp.float32)
    f_launch = jax.jit(lambda x: x + 1.0)
    emit("launch", 0, 1, _time(f_launch, tiny), 0.0)

    for nb in nbs:
        rng = np.random.default_rng(0)
        spd = rng.standard_normal((nb, nb), dtype=np.float32)
        spd = spd @ spd.T + nb * np.eye(nb, dtype=np.float32)
        t_d = jax.device_put(spd, dev)
        l_d = jax.device_put(np.linalg.cholesky(spd), dev)

        emit("chol", nb, 1, _time(jax.jit(k_potrf), t_d), nb ** 3 / 3)
        emit("trsm", nb, 1, _time(jax.jit(k_trsm), l_d, t_d), nb ** 3)

        for b in batches:
            c_b = jax.device_put(
                rng.standard_normal((b, nb, nb), dtype=np.float32), dev)
            a_b = jax.device_put(
                rng.standard_normal((b, nb, nb), dtype=np.float32), dev)
            t_b = jax.device_put(
                np.broadcast_to(spd, (b, nb, nb)).copy(), dev)

            emit("trsm", nb, b,
                 _time(jax.jit(jax.vmap(k_trsm, in_axes=(None, 0))),
                       l_d, c_b), b * nb ** 3)

            # the MXU-friendly TRSM: invert the (tiny) triangle once,
            # then the whole wave is one batched GEMM
            def trsm_inv(l, cs):
                linv = jax.scipy.linalg.solve_triangular(
                    l, jnp.eye(l.shape[0], dtype=l.dtype), lower=True)
                return jax.lax.dot_general(
                    cs, linv, (((2,), (1,)), ((), ())),
                    preferred_element_type=cs.dtype)
            emit("trsm_inv", nb, b, _time(jax.jit(trsm_inv), l_d, c_b),
                 b * nb ** 3)

            emit("syrk", nb, b,
                 _time(jax.jit(jax.vmap(k_syrk)), a_b, t_b),
                 b * nb ** 3)
            emit("gemm", nb, b,
                 _time(jax.jit(jax.vmap(k_gemm)), a_b, c_b, t_b),
                 2 * b * nb ** 3)


if __name__ == "__main__":
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
