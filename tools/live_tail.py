#!/usr/bin/env python
"""Render LiveMonitor counter streams (profiling/live.py JSONL) as a
compact terminal table — the CLI face of the aggregator_visu role
(reference: tools/aggregator_visu/aggregator.py, which socket-aggregates
per-rank counters for a GUI; here the per-rank JSONL files ARE the
transport and any dashboard can consume them).

  python tools/live_tail.py /tmp/ptc_live_rank0.jsonl           # one rank
  python tools/live_tail.py /tmp/ptc_live_rank0.jsonl --follow  # tail -f
  python tools/live_tail.py '/tmp/ptc_live_rank*.jsonl' --merge # all ranks
  python tools/live_tail.py '/tmp/ptc_live_rank*.jsonl' --merge --follow

--merge shows ONE view with a line per rank (latest sample each) plus a
cluster totals line; ranks whose stream appears later JOIN the view on
the next refresh.
"""
import glob
import json
import sys
import time


def _fmt(snap):
    t = snap.get("t", 0.0)
    workers = snap.get("workers", [])
    steals = snap.get("steals", [])
    line = (f"t={t:8.2f}s r{snap.get('rank', 0)} "
            f"tasks={sum(workers):8d} workers={workers} "
            f"steals={sum(steals) if steals else 0} "
            f"rss={snap.get('maxrss_kb', 0) >> 10}MiB")
    i = 0
    while f"dev{i}_tasks" in snap:
        line += (f" | dev{i} tasks={snap[f'dev{i}_tasks']}"
                 f" q={snap.get(f'dev{i}_qdepth', '?')}"
                 f" cache={snap.get(f'dev{i}_cache_bytes', 0) >> 20}MiB")
        i += 1
    c = snap.get("comm")
    if c:
        line += (f" | comm tx={c.get('bytes_sent', 0) >> 10}KiB "
                 f"rx={c.get('bytes_recv', 0) >> 10}KiB")
    return line


def read_latest(path, tail_bytes=65536):
    """Last valid snapshot in one rank's stream, or None.  Reads only a
    bounded tail window: the follow loop polls every second and streams
    grow without bound, so a full re-parse per poll would be quadratic
    cumulative work."""
    last = None
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            f.seek(max(0, size - tail_bytes))
            chunk = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    lines = chunk.splitlines()
    if size > tail_bytes and lines:
        lines = lines[1:]  # first line of a mid-file window is partial
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            last = json.loads(line)
        except ValueError:
            continue
    return last


def merge_latest(paths):
    """Rank-keyed latest snapshots across N per-rank streams (the
    aggregator_visu join): {rank: snapshot}.  Ranks appear as their
    stream files appear — a late-joining rank shows up on the next
    call."""
    merged = {}
    for p in paths:
        snap = read_latest(p)
        if snap is None:
            continue
        merged[int(snap.get("rank", 0))] = snap
    return merged


def render_merged(merged):
    """One view: a line per rank + cluster totals."""
    lines = []
    tot_tasks = 0
    tot_tx = tot_rx = 0
    for rank in sorted(merged):
        snap = merged[rank]
        lines.append(_fmt(snap))
        tot_tasks += sum(snap.get("workers", []))
        c = snap.get("comm") or {}
        tot_tx += c.get("bytes_sent", 0)
        tot_rx += c.get("bytes_recv", 0)
    lines.append(f"== {len(merged)} rank(s) tasks={tot_tasks} "
                 f"tx={tot_tx >> 10}KiB rx={tot_rx >> 10}KiB")
    return "\n".join(lines)


def _expand(args):
    paths = []
    for a in args:
        if any(ch in a for ch in "*?["):
            paths.extend(sorted(glob.glob(a)))
        else:
            paths.append(a)
    return paths


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    follow = "--follow" in sys.argv
    # a glob pattern implies the multi-rank view even without --merge
    # (the literal pattern is not an openable path)
    merge = ("--merge" in sys.argv or len(args) > 1
             or any(ch in a for a in args for ch in "*?["))
    if not args:
        sys.stderr.write(__doc__)
        return 2
    if merge:
        patterns = args
        while True:
            merged = merge_latest(_expand(patterns))
            print(render_merged(merged))
            if not follow:
                return 0
            time.sleep(1.0)
            print()
    path = args[0]
    with open(path) as f:
        while True:
            line = f.readline()
            if line:
                line = line.strip()
                if not line:
                    continue
                try:
                    print(_fmt(json.loads(line)))
                except ValueError:
                    continue
            elif follow:
                time.sleep(0.5)
            else:
                return 0


if __name__ == "__main__":
    sys.exit(main())
