#!/usr/bin/env python
"""Render a LiveMonitor counter stream (profiling/live.py JSONL) as a
compact terminal table — the CLI face of the aggregator_visu role (the
reference's GUI itself stays out of scope; any dashboard can consume
the same file).

  python tools/live_tail.py /tmp/ptc_live_rank0.jsonl          # snapshot
  python tools/live_tail.py /tmp/ptc_live_rank0.jsonl --follow # tail -f
"""
import json
import sys
import time


def _fmt(snap):
    t = snap.get("t", 0.0)
    workers = snap.get("workers", [])
    steals = snap.get("steals", [])
    line = (f"t={t:8.2f}s r{snap.get('rank', 0)} "
            f"tasks={sum(workers):8d} workers={workers} "
            f"steals={sum(steals) if steals else 0} "
            f"rss={snap.get('maxrss_kb', 0) >> 10}MiB")
    i = 0
    while f"dev{i}_tasks" in snap:
        line += (f" | dev{i} tasks={snap[f'dev{i}_tasks']}"
                 f" q={snap.get(f'dev{i}_qdepth', '?')}"
                 f" cache={snap.get(f'dev{i}_cache_bytes', 0) >> 20}MiB")
        i += 1
    c = snap.get("comm")
    if c:
        line += (f" | comm tx={c.get('bytes_sent', 0) >> 10}KiB "
                 f"rx={c.get('bytes_recv', 0) >> 10}KiB")
    return line


def main():
    if len(sys.argv) < 2:
        sys.stderr.write(__doc__)
        return 2
    path = sys.argv[1]
    follow = "--follow" in sys.argv
    with open(path) as f:
        while True:
            line = f.readline()
            if line:
                line = line.strip()
                if not line:
                    continue
                try:
                    print(_fmt(json.loads(line)))
                except ValueError:
                    continue
            elif follow:
                time.sleep(0.5)
            else:
                return 0


if __name__ == "__main__":
    sys.exit(main())
