#!/usr/bin/env python
"""Per-executable-call overhead on the real chip (tunnel-fronted PJRT).

The spotrf wall tracks the number of device dispatches, not FLOPs — this
probe separates the two candidate explanations:

  * serialized per-call overhead (each execute round-trips the tunnel):
    dependent-chain time/call ~= independent-burst time/call ~= RTT
  * pipelined enqueue (client streams executions, device runs them
    back-to-back): independent-burst time/call << dependent-chain
    time/call, and both well under RTT for tiny kernels

Emits one JSON line:
  {"metric": "launch_overhead", "dep_us_per_call": ..,
   "indep_us_per_call": .., "tiny_flops_ms": .., "chip_kind": ..}

Method: jit(x -> x + 1) on a 128x128 f32.  Dependent chain feeds each
call's output to the next (no host sync between calls); independent
burst reuses the same input 100 times; one final block_until_ready
closes each timing.  A third number times a single big 4096^3 matmul
for scale.  Everything is warmed before timing.
"""
import json
import sys
import time


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    n = 100

    @jax.jit
    def bump(x):
        return x + 1.0

    x0 = jax.device_put(jnp.zeros((128, 128), jnp.float32), dev)
    bump(x0).block_until_ready()  # warm/compile

    # dependent chain: each call consumes the previous result
    x = x0
    t0 = time.perf_counter()
    for _ in range(n):
        x = bump(x)
    x.block_until_ready()
    dep_us = (time.perf_counter() - t0) / n * 1e6

    # independent burst: same input every time (client may pipeline)
    t0 = time.perf_counter()
    ys = [bump(x0) for _ in range(n)]
    ys[-1].block_until_ready()
    for y in ys:
        y.block_until_ready()
    indep_us = (time.perf_counter() - t0) / n * 1e6

    # scale bar: one large matmul (MXU-bound)
    a = jax.device_put(jnp.ones((4096, 4096), jnp.float32), dev)
    mm = jax.jit(lambda p: p @ p)
    mm(a).block_until_ready()
    t0 = time.perf_counter()
    mm(a).block_until_ready()
    big_ms = (time.perf_counter() - t0) * 1e3

    print(json.dumps({
        "metric": "launch_overhead",
        "dep_us_per_call": round(dep_us, 1),
        "indep_us_per_call": round(indep_us, 1),
        "big_matmul_4096_ms": round(big_ms, 2),
        "chip_kind": getattr(dev, "device_kind", "?"),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
