#!/usr/bin/env python
"""Per-rank executed-DAG capture -> one merged DOT file (reference:
tools/parsec-dotmerger + parsec/parsec_prof_grapher.c).

Usage: python tools/ptt2dot.py out.dot rank0.ptt [rank1.ptt ...] \
           [--classes Name0,Name1,...]
Needs traces taken at profile level 2 (EDGE events)."""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.profiling import Trace, to_dot  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("out")
    ap.add_argument("traces", nargs="+")
    ap.add_argument("--classes", default=None,
                    help="comma-separated class names for node labels")
    args = ap.parse_args(argv)
    traces = [Trace.load(p) for p in args.traces]
    merged = Trace.merge(traces) if len(traces) > 1 else traces[0]
    if args.classes:
        merged.class_names = args.classes.split(",")
    dot = to_dot(merged)
    with open(args.out, "w") as f:
        f.write(dot + "\n")
    print(f"{dot.count('->')} edges -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
