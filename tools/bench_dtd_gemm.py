#!/usr/bin/env python
"""BASELINE ladder rung 2: DTD tiled GEMM on one TPU chip.

Runtime-discovered DAG (every (m,n,k) product inserted through the DTD
accessor-chain machinery), device chores dispatching cached XLA
executables, host tiles staged h2d on first touch — the honest DTD
bring-up number, reference shape: tests/dsl/dtd task-insertion GEMMs.

Emits one JSON line:
  {"metric": "dtd_gemm", "gflops": .., "tasks_per_s": .., "config": ..}

Run on the chip:  python tools/bench_dtd_gemm.py [--n 4096] [--nb 512]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _arg(flag, default):
    if flag in sys.argv:
        return int(sys.argv[sys.argv.index(flag) + 1])
    return default


def main():
    N = _arg("--n", 4096)
    nb = _arg("--nb", 512)
    nt = N // nb
    import parsec_tpu as pt
    from parsec_tpu.data import TwoDimBlockCyclic
    from parsec_tpu.device import TpuDevice
    from parsec_tpu.dsl.dtd import DtdTaskpool

    rng = np.random.default_rng(7)

    def k_gemm(a, b, c):
        return c + a @ b

    def run():
        with pt.Context(nb_workers=4) as ctx:
            A = TwoDimBlockCyclic(N, N, nb, nb, dtype=np.float32)
            B = TwoDimBlockCyclic(N, N, nb, nb, dtype=np.float32)
            C = TwoDimBlockCyclic(N, N, nb, nb, dtype=np.float32)
            A.from_dense(rng.standard_normal((N, N), dtype=np.float32))
            B.from_dense(rng.standard_normal((N, N), dtype=np.float32))
            C.from_dense(np.zeros((N, N), dtype=np.float32))
            A.register(ctx, "A")
            B.register(ctx, "B")
            C.register(ctx, "C")
            dev = TpuDevice(ctx)
            dtd = DtdTaskpool(ctx)
            t0 = time.perf_counter()
            for m in range(nt):
                for n in range(nt):
                    for k in range(nt):
                        dtd.insert_tpu_task(
                            dev, k_gemm,
                            (dtd.tile_of(A, m, k), "INPUT"),
                            (dtd.tile_of(B, k, n), "INPUT"),
                            (dtd.tile_of(C, m, n), "INOUT"),
                            shapes={i: (nb, nb) for i in range(3)})
            dtd.wait()
            from parsec_tpu.device.bench_utils import wait_device_tiles
            wait_device_tiles(dev, C)
            dt = time.perf_counter() - t0
            dev.stop()
            dtd.destroy()
            return dt

    run()  # warm: compiles the executable + the insert path
    dt = min(run() for _ in range(2))
    tasks = nt ** 3
    flops = 2.0 * N * N * N
    import jax
    print(json.dumps({
        "metric": "dtd_gemm",
        "gflops": round(flops / dt / 1e9, 1),
        "tasks_per_s": round(tasks / dt, 1),
        "config": {"N": N, "nb": nb, "tasks": tasks},
        "chip_kind": getattr(jax.devices()[0], "device_kind", "?"),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
