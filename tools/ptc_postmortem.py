#!/usr/bin/env python
"""ptc_postmortem — cross-rank incident report from a journal directory.

Input is the artifact directory the ptc-blackbox recorder writes
(`PTC_MCA_runtime_journal=<dir>`):

  journal.<rank>.jsonl[.1]          per-rank schema-v1 JSONL journals
  crash.<rank>.ptt                  fatal-signal flight-recorder dumps
  *.watchdog.<run>.<rank>.<n>.ptt   watchdog anomaly dumps

The assembler merges every rank's records onto rank 0's clock (each
rank's checkpointed `clock.offset_ns`, same convention as Trace.merge),
then answers the three on-call questions:

  1. WHO died / misbehaved first (`dead_ranks`, `first_cause`) —
     peer-loss observations name the dead rank; its own last record is
     causally BEFORE every survivor's observation of the loss, so the
     dead rank's final activity wins first-cause even when survivor
     clocks lag.
  2. WHAT the dead rank held (`holdings`) — live scopes, inflight EXEC
     bodies, QoS census and registered inventory (e.g. frozen page
     keys), recovered from the checkpoint blob each peer replicated
     BEFORE the death (MSG_BLOB) and from crash-dump INFLIGHT events.
     The dead rank's own journal is NOT required.
  3. WHEN — a merged last-N-seconds timeline ending at the incident.

Usage:
  python tools/ptc_postmortem.py <dir>              # text report
  python tools/ptc_postmortem.py <dir> --json       # machine-readable
  python tools/ptc_postmortem.py <dir> --window 30  # timeline seconds
  python tools/ptc_postmortem.py <dir> --expect expected.json
        # assert report fields match (smoke harness; exit 1 on drift)

Exit status: 0 ok, 1 --expect mismatch, 2 no journals found.
"""
import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCHEMA = "ptc-postmortem-v1"

#: inventory keys that are structural; everything else in a checkpoint
#: inventory is a registered provider (e.g. frozen page keys)
_INV_CORE = ("rank", "live_scopes", "qos_pools", "inflight", "clock")

#: journal record types that count as anomalies for first-cause ranking
_ANOMALY = ("watchdog", "peer_loss")


def load_journals(d):
    """{rank: [records sorted by seq]} from journal.<rank>.jsonl[.1]."""
    ranks = {}
    for path in sorted(glob.glob(os.path.join(d, "journal.*.jsonl*"))):
        m = re.match(r"journal\.(\d+)\.jsonl(\.1)?$", os.path.basename(path))
        if not m:
            continue
        rank = int(m.group(1))
        for line in open(path, errors="replace"):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line of a crashed writer
            if isinstance(rec, dict) and rec.get("v") == 1:
                ranks.setdefault(rank, []).append(rec)
    for rank in ranks:
        ranks[rank].sort(key=lambda r: r.get("seq", 0))
    return ranks


def load_dumps(d):
    """{rank: [trace,...]} for crash.*.ptt + *.watchdog.*.ptt (best
    effort: unreadable dumps are skipped, never fatal)."""
    try:
        from parsec_tpu.profiling.trace import Trace
    except Exception:
        return {}
    out = {}
    for path in sorted(glob.glob(os.path.join(d, "crash.*.ptt"))
                       + glob.glob(os.path.join(d, "*.watchdog.*.ptt"))):
        try:
            t = Trace.load(path)
        except Exception:
            continue
        t.meta["_path"] = os.path.basename(path)
        out.setdefault(int(t.rank), []).append(t)
    return out


def clock_offsets(journals, dumps):
    """Per-rank additive offset onto rank 0's clock: the newest
    checkpointed comm_clock estimate, else the dump header's."""
    off = {}
    for rank, recs in journals.items():
        for rec in recs:
            if rec.get("type") != "checkpoint":
                continue
            ck = (rec.get("inventory") or {}).get("clock") or {}
            if ck.get("measured"):
                off[rank] = int(ck.get("offset_ns", 0))
    for rank, traces in dumps.items():
        for t in traces:
            if rank not in off and "clock_offset_ns" in t.meta:
                off[rank] = int(t.meta["clock_offset_ns"])
    return off


def _aligned(rec, offsets):
    return int(rec.get("t_ns", 0)) + offsets.get(int(rec.get("rank", 0)), 0)


def _unwrap_blob(blob):
    """peer_loss embeds the MSG_BLOB wrapper {rank, t_ns, inventory}."""
    if not isinstance(blob, dict):
        return None, None
    return blob.get("inventory"), blob.get("t_ns")


def holdings_from_inventory(inv):
    """The recovery-relevant holdings view of one checkpoint inventory."""
    inv = inv or {}
    providers = {k: v for k, v in inv.items() if k not in _INV_CORE}
    frozen = []
    for name, v in providers.items():
        if "frozen" in name and isinstance(v, (list, tuple)):
            frozen.extend(v)
    return {
        "live_scopes": inv.get("live_scopes") or [],
        "inflight": inv.get("inflight") or [],
        "qos_pools": inv.get("qos_pools") or [],
        "frozen_keys": frozen,
        "providers": providers,
    }


def assemble(d, window_s=30.0):
    journals = load_journals(d)
    if not journals:
        return None
    dumps = load_dumps(d)
    offsets = clock_offsets(journals, dumps)

    # -- dead ranks: every peer a survivor journalled a peer_loss for —
    #    UNLESS that peer's own journal ends with a clean journal_close
    #    (an orderly exit drops the connection too, and a survivor that
    #    outlives it still records the disconnect; closed = not dead)
    closed_ranks = {rank for rank, recs in journals.items()
                    if any(r.get("type") == "journal_close" for r in recs)}
    dead, losses = set(), []
    for rank, recs in journals.items():
        for rec in recs:
            if rec.get("type") == "peer_loss":
                losses.append(rec)
                if int(rec["peer"]) not in closed_ranks:
                    dead.add(int(rec["peer"]))
    # a rank that left a crash dump but no journal_close also died
    for rank, traces in dumps.items():
        for t in traces:
            if t.meta.get("crash"):
                closed = any(r.get("type") == "journal_close"
                             for r in journals.get(rank, []))
                if not closed:
                    dead.add(rank)

    # -- holdings of each dead rank: newest replicated checkpoint blob
    #    (survivor-held), overlaid with crash-dump INFLIGHT spans
    holdings = {}
    for rank in sorted(dead):
        best, best_t = None, -1
        for rec in losses:
            if int(rec["peer"]) != rank:
                continue
            inv, t_ns = _unwrap_blob(rec.get("inventory"))
            if inv is not None and int(t_ns or 0) >= best_t:
                best, best_t = inv, int(t_ns or 0)
        if best is None:  # fall back to the dead rank's own journal
            for rec in journals.get(rank, []):
                if rec.get("type") == "checkpoint":
                    best = rec.get("inventory")
        h = holdings_from_inventory(best)
        h["checkpoint_t_ns"] = best_t if best_t >= 0 else None
        for t in dumps.get(rank, []):
            if not t.meta.get("crash"):
                continue
            try:
                from parsec_tpu.profiling.trace import KEY_INFLIGHT
                ev = t.events
                for row in ev[ev[:, 0] == KEY_INFLIGHT]:
                    if int(row[1]) != 0:  # begin phase only
                        continue
                    h.setdefault("crash_inflight", []).append(
                        {"worker": int(row[3]), "class_id": int(row[2]),
                         "scope_id": int(row[6]), "begin_ns": int(row[7])})
            except Exception:
                pass
            h["crash_dump"] = t.meta.get("_path")
        holdings[rank] = h

    # -- undelivered wire expectations the survivors still hold
    for rec in losses:
        rdv = rec.get("rdv")
        if rdv and int(rec["peer"]) in holdings:
            holdings[int(rec["peer"])].setdefault(
                "survivor_rdv", {})[str(rec["rank"])] = rdv

    # -- first cause: the dead rank's last aligned record beats every
    #    survivor observation (causality); else earliest anomaly record
    first = None
    if dead:
        cand = []
        for rank in sorted(dead):
            last = None
            for rec in journals.get(rank, []):
                last = rec
            for rec in losses:
                if int(rec["peer"]) == rank:
                    inv, t_ns = _unwrap_blob(rec.get("inventory"))
                    if t_ns and (last is None
                                 or int(t_ns) > int(last.get("t_ns", 0))):
                        last = {"type": "checkpoint(replicated)",
                                "t_ns": int(t_ns), "rank": rank}
            cand.append((rank, last))
        rank, last = min(cand, key=lambda c: _aligned(c[1] or {}, offsets))
        first = {"rank": rank, "kind": "rank_death",
                 "t_ns": _aligned(last or {}, offsets),
                 "last_record": (last or {}).get("type")}
    else:
        anomalies = [r for recs in journals.values() for r in recs
                     if r.get("type") in _ANOMALY]
        if anomalies:
            a = min(anomalies, key=lambda r: _aligned(r, offsets))
            first = {"rank": int(a["rank"]), "kind": a["type"],
                     "t_ns": _aligned(a, offsets),
                     "last_record": a.get("reason") or a.get("type")}

    # -- merged timeline: last `window_s` before the incident end
    merged = sorted((r for recs in journals.values() for r in recs),
                    key=lambda r: _aligned(r, offsets))
    end = _aligned(merged[-1], offsets) if merged else 0
    if first:
        end = max(end, first["t_ns"])
    lo = end - int(window_s * 1e9)
    timeline = [{"t_ns": _aligned(r, offsets), "rank": int(r["rank"]),
                 "type": r["type"], "seq": r.get("seq"),
                 "detail": _detail(r)}
                for r in merged if _aligned(r, offsets) >= lo]

    return {
        "schema": SCHEMA,
        "dir": os.path.abspath(d),
        "ranks": sorted(journals),
        "dead_ranks": sorted(dead),
        "clock_offsets_ns": {str(k): v for k, v in sorted(offsets.items())},
        "first_cause": first,
        "holdings": {str(k): v for k, v in sorted(holdings.items())},
        "anomalies": [{"rank": int(r["rank"]), "type": r["type"],
                       "t_ns": _aligned(r, offsets), "detail": _detail(r)}
                      for recs in journals.values() for r in recs
                      if r.get("type") in _ANOMALY],
        "timeline": timeline,
    }


def _detail(rec):
    """One-line summary of a record for the timeline."""
    t = rec.get("type")
    if t == "peer_loss":
        return f"peer {rec.get('peer')} lost"
    if t == "watchdog":
        return str(rec.get("reason") or rec.get("kind") or "watchdog")
    if t == "serve":
        return f"{rec.get('op')} tenant={rec.get('tenant')} " \
               f"scope={rec.get('scope_id')}"
    if t == "scope_event":
        return f"{rec.get('event')} scope={rec.get('scope_id')}"
    if t == "fence":
        return f"epoch={rec.get('epoch')}" + \
               (f" error={rec['error']}" if rec.get("error") else "")
    if t == "checkpoint":
        inv = rec.get("inventory") or {}
        return f"{len(inv.get('live_scopes') or [])} live scopes, " \
               f"{len(inv.get('inflight') or [])} inflight"
    if t == "fleet":
        return f"{rec.get('healthy')}/{rec.get('replicas')} healthy"
    return ""


def render_text(rep, out=sys.stdout):
    w = out.write
    w(f"ptc postmortem — {rep['dir']}\n")
    w(f"  ranks seen : {rep['ranks']}\n")
    w(f"  dead ranks : {rep['dead_ranks'] or 'none'}\n")
    fc = rep["first_cause"]
    if fc:
        w(f"  first cause: rank {fc['rank']} ({fc['kind']}, "
          f"last={fc['last_record']}, t={fc['t_ns']} ns)\n")
    else:
        w("  first cause: no anomaly recorded\n")
    for rank, h in rep["holdings"].items():
        w(f"\n  rank {rank} holdings (from survivor-replicated "
          f"checkpoint):\n")
        for s in h["live_scopes"]:
            w(f"    scope {s.get('scope_id')} [{s.get('state')}] "
              f"tenant={s.get('tenant')} kind={s.get('kind')}\n")
        if not h["live_scopes"]:
            w("    (no live scopes)\n")
        if h["frozen_keys"]:
            w(f"    frozen keys ({len(h['frozen_keys'])}): "
              f"{', '.join(map(str, h['frozen_keys'][:8]))}"
              f"{' ...' if len(h['frozen_keys']) > 8 else ''}\n")
        for fl in h["inflight"]:
            w(f"    inflight: worker={fl[0]} class={fl[1]} "
              f"scope={fl[3] if len(fl) > 3 else '?'}\n")
        for fl in h.get("crash_inflight", []):
            w(f"    crash-dump inflight: worker={fl['worker']} "
              f"scope={fl['scope_id']}\n")
        if h.get("crash_dump"):
            w(f"    crash dump: {h['crash_dump']}\n")
    if rep["anomalies"]:
        w("\n  anomalies:\n")
        for a in rep["anomalies"]:
            w(f"    t={a['t_ns']} rank={a['rank']} {a['type']}: "
              f"{a['detail']}\n")
    w(f"\n  timeline (last {len(rep['timeline'])} records, "
      f"rank-0 clock):\n")
    for r in rep["timeline"][-40:]:
        w(f"    {r['t_ns']:>16} r{r['rank']} {r['type']:<12} "
          f"{r['detail']}\n")


def check_expected(rep, expect):
    """Compare the report against an expectation file: exact match for
    scalars/lists, subset match for dicts (recursing one level into
    holdings rows).  Returns a list of mismatch strings."""
    errs = []

    def _cmp(path, want, got):
        if isinstance(want, dict):
            if not isinstance(got, dict):
                errs.append(f"{path}: expected dict, got {type(got).__name__}")
                return
            for k, v in want.items():
                _cmp(f"{path}.{k}", v, got.get(k))
        elif want != got:
            errs.append(f"{path}: expected {want!r}, got {got!r}")

    for k, v in expect.items():
        _cmp(k, v, rep.get(k))
    return errs


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", help="journal directory")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--window", type=float, default=30.0,
                    help="timeline window in seconds (default 30)")
    ap.add_argument("--expect", help="expected.json to assert against")
    args = ap.parse_args(argv)

    rep = assemble(args.dir, window_s=args.window)
    if rep is None:
        sys.stderr.write(f"ptc_postmortem: no journals in {args.dir}\n")
        return 2
    if args.as_json:
        json.dump(rep, sys.stdout, indent=1, default=str)
        sys.stdout.write("\n")
    else:
        render_text(rep)
    if args.expect:
        expect = json.load(open(args.expect))
        errs = check_expected(rep, expect)
        if errs:
            sys.stderr.write("ptc_postmortem: expectation mismatches:\n")
            for e in errs:
                sys.stderr.write(f"  {e}\n")
            return 1
        sys.stderr.write("ptc_postmortem: expectations met\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
