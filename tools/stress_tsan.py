#!/usr/bin/env python
"""Concurrency stress for the native core under ThreadSanitizer.

Targets the paths that only ever ran effectively single-threaded on this
1-core box: the lock-free Chase-Lev work-stealing deque (lws), the dense
and hashed dependency engines under concurrent release, DTD accessor
chains, and the comm thread's delivery path against worker releases
(colocated 2-rank job in one process).  TSan's happens-before analysis
finds missing synchronization even when the kernel timeslices, so this
is meaningful on one core.

Run:
    make tsan
    PTC_NATIVE_LIB=build/libparsec_core_tsan.so \
    LD_PRELOAD=$(g++ -print-file-name=libtsan.so) \
    TSAN_OPTIONS="suppressions=tools/tsan.supp exitcode=66 \
                  report_thread_leaks=0" \
    timeout 900 python tools/stress_tsan.py

Exit 0 + "stress ok" and no "WARNING: ThreadSanitizer" lines = clean.
(reference practice: the PARANOID/NOISIER debug CI matrix,
.github/workflows/build_cmake.yml:33-34)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import parsec_tpu as pt  # noqa: E402


def ep_burst(sched: str, workers: int, n: int) -> None:
    """Independent tasks: pure produce/steal churn on the deques."""
    with pt.Context(nb_workers=workers, scheduler=sched) as ctx:
        tp = pt.Taskpool(ctx, globals={"NB": n - 1})
        tc = tp.task_class("EP")
        tc.param("k", 0, pt.G("NB"))
        tc.body_noop()
        tp.run()
        tp.wait()
        assert tp.nb_total_tasks == n


def chain_mesh(sched: str, workers: int, nb: int, lanes: int) -> None:
    """`lanes` independent RW chains: concurrent release_deps traffic
    through the dense dependency engine while workers steal."""
    with pt.Context(nb_workers=workers, scheduler=sched) as ctx:
        ctx.register_arena("t", 8)
        tp = pt.Taskpool(ctx, globals={"NB": nb - 1, "L": lanes - 1})
        k, l = pt.L("k"), pt.L("l")
        tc = tp.task_class("Chain")
        tc.param("l", 0, pt.G("L"))
        tc.param("k", 0, pt.G("NB"))
        tc.flow("A", "RW",
                pt.In(None, guard=(k == 0)),
                pt.In(pt.Ref("Chain", l, k - 1, flow="A")),
                pt.Out(pt.Ref("Chain", l, k + 1, flow="A"),
                       guard=(k < pt.G("NB"))),
                arena="t")
        tc.body_noop()
        tp.run()
        tp.wait()
        assert tp.nb_total_tasks == nb * lanes


def dtd_churn(workers: int, tiles: int, rounds: int) -> None:
    """Dynamic insertion racing execution: accessor-chain updates, window
    throttling, freelist reuse."""
    with pt.Context(nb_workers=workers, scheduler="lws") as ctx:
        from parsec_tpu.dsl.dtd import DtdTaskpool
        datas = [ctx.data(i, np.zeros(8, dtype=np.int64))
                 for i in range(tiles)]
        dtp = DtdTaskpool(ctx, window=32)
        tls = [dtp.tile_of(d, owner=0) for d in datas]

        def bump(view):
            view.data(0, dtype=np.int64)[0] += 1

        for _ in range(rounds):
            for t in range(tiles):
                dtp.insert_task(bump, (tls[t], "INOUT"))
        dtp.wait()
        for i, d in enumerate(datas):
            v = np.frombuffer(d.array, dtype=np.int64)[0]
            assert v == rounds, (i, v)
        dtp.destroy()


def colocated_comm(workers: int, nb: int = 64, port: int = 29900,
                   elems: int = 1, env=None) -> None:
    """Two ranks in ONE process (a thread per rank, loopback TCP): the
    comm threads' delivery paths run against both ranks' workers on a
    cross-rank RW chain, all inside one TSan-observed address space.

    elems > 1 (with `env` forcing rendezvous + small chunks + 2 rails)
    drives the wire-v4 socket/session paths — ranged-chunk sessions,
    shared_ptr-pinned zero-copy sendmsg frames, multi-rail striping —
    under TSan's happens-before analysis."""
    import threading

    env = env or {}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    errs = []

    def rank_prog(rank):
        try:
            ctx = pt.Context(nb_workers=workers, scheduler="lws")
            ctx.set_rank(rank, 2)
            ctx.comm_init(port)
            with ctx:
                size = 8 * elems
                arr = np.zeros((2, elems), dtype=np.int64)
                ctx.register_linear_collection("A", arr, elem_size=size,
                                               nodes=2, myrank=rank)
                ctx.register_arena("t", size)
                tp = pt.Taskpool(ctx, globals={"NB": nb})
                k = pt.L("k")
                tc = tp.task_class("Task")
                tc.param("k", 0, pt.G("NB"))
                tc.affinity("A", k % 2)
                tc.flow("A", "RW",
                        pt.In(pt.Mem("A", 0), guard=(k == 0)),
                        pt.In(pt.Ref("Task", k - 1, flow="A")),
                        pt.Out(pt.Ref("Task", k + 1, flow="A"),
                               guard=(k < pt.G("NB"))),
                        arena="t")

                def body(view):
                    a = view.data("A", dtype=np.int64, shape=(elems,))
                    assert (a == view["k"]).all()
                    a += 1

                tc.body(body)
                tp.run()
                tp.wait()
                ctx.comm_fence()
                ctx.comm_fini()
        except Exception as e:  # pragma: no cover - stress harness
            errs.append((rank, repr(e)))

    try:
        ts = [threading.Thread(target=rank_prog, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        hung = [t.name for t in ts if t.is_alive()]
        assert not hung, f"deadlocked rank threads: {hung}"
        assert not errs, errs
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def colocated_coll(workers: int, elems: int, port: int, env=None) -> None:
    """Two ranks in ONE process running runtime-native streamed
    collectives (ptc_coll_* task classes, parsec_tpu.comm.coll): the
    reduction/fan-out step deliveries, the coll-stats counters, the
    native bcast-tree switches and (with `env` forcing rendezvous +
    small chunks) the chunked wire sessions under TSan's happens-before
    analysis — every topology exercised."""
    import threading

    from parsec_tpu.comm import coll

    env = env or {}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    errs = []

    def rank_prog(rank):
        try:
            ctx = pt.Context(nb_workers=workers, scheduler="lws")
            ctx.set_rank(rank, 2)
            ctx.comm_init(port)
            with ctx:
                alls = [np.arange(elems, dtype=np.float32) + 100.0 * r
                        for r in range(2)]
                total = alls[0] + alls[1]
                for topo in ("ring", "binomial", "star"):
                    got = coll.all_reduce(ctx, alls[rank], topo=topo)
                    assert (got == total).all(), topo
                got = coll.broadcast(ctx, alls[rank].copy(), root=1)
                assert (got == alls[1]).all()
                st = ctx.coll_stats()
                assert st["steps"] > 0, st
                ctx.comm_fence()
                ctx.comm_fini()
        except Exception as e:  # pragma: no cover - stress harness
            errs.append((rank, repr(e)))

    try:
        ts = [threading.Thread(target=rank_prog, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        hung = [t.name for t in ts if t.is_alive()]
        assert not hung, f"deadlocked rank threads: {hung}"
        assert not errs, errs
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def colocated_hier_coll(workers: int, elems: int, port: int,
                        env=None) -> None:
    """ptc-topo: FOUR ranks in one process on a two-island topology
    spec running the hierarchical two-level collectives (intra-island
    binomial reduce onto heads, leaders-only exchange, follower fan-
    out) plus the per-class counter folds — the island-leader step
    deliveries, the -1 route-table deactivations and the classed
    counter reads all under TSan's happens-before analysis."""
    import threading

    from parsec_tpu.comm import coll

    env = dict(env or {})
    env.setdefault("PTC_MCA_comm_topology", "0,1;2,3")
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    nodes = 4
    errs = []

    def rank_prog(rank):
        try:
            ctx = pt.Context(nb_workers=workers, scheduler="lws")
            ctx.set_rank(rank, nodes)
            ctx.comm_init(port)
            with ctx:
                alls = [np.arange(elems, dtype=np.float32) + 100.0 * r
                        for r in range(nodes)]
                total = np.sum(np.stack(alls), axis=0,
                               dtype=np.float32)
                for _ in range(2):
                    got = coll.all_reduce(ctx, alls[rank], topo="hier")
                    assert (got == total).all()
                got = coll.broadcast(ctx, alls[rank].copy(), root=1,
                                     topo="hier")
                assert (got == alls[1]).all()
                st = ctx.coll_stats()
                assert st["by_topo"].get("hier", 0) >= 3, st
                ts = ctx.comm_topo_stats()
                assert ts["n_islands"] == 2, ts
                ctx.comm_fence()
                ctx.comm_fini()
        except Exception as e:  # pragma: no cover - stress harness
            errs.append((rank, repr(e)))

    try:
        ts = [threading.Thread(target=rank_prog, args=(r,))
              for r in range(nodes)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        hung = [t.name for t in ts if t.is_alive()]
        assert not hung, f"deadlocked rank threads: {hung}"
        assert not errs, errs
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def metrics_watchdog_coll(workers: int, elems: int, port: int,
                          env=None) -> None:
    """PR 7 observability paths under TSan: the lock-free metrics hot
    path (per-class EXEC records from CB bodies on every worker, h2d/
    release/comm-wait records), the watchdog thread scanning inflight
    slots + histograms, the Prometheus scrape endpoint serializing
    snapshots, and the fence-time MSG_METRICS rank-wide merge — all
    concurrently with a 2-rank streamed collective over the chunked
    wire."""
    import threading
    import urllib.request

    from parsec_tpu.comm import coll
    from parsec_tpu.profiling.metrics import (MetricsExporter,
                                              MetricsRegistry, Watchdog)

    env = env or {}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    errs = []

    def rank_prog(rank):
        try:
            ctx = pt.Context(nb_workers=workers, scheduler="lws")
            ctx.set_rank(rank, 2)
            ctx.comm_init(port)
            with ctx:
                wd = Watchdog(ctx, interval=0.05, floor_s=30.0)
                exporter = MetricsExporter(ctx, 0) if rank == 0 else None
                stop_scrape = threading.Event()

                def scrape():
                    while not stop_scrape.is_set():
                        try:
                            urllib.request.urlopen(
                                f"http://127.0.0.1:{exporter.port}"
                                "/metrics", timeout=5).read()
                        except Exception:
                            pass
                        stop_scrape.wait(0.02)

                scraper = None
                if exporter is not None:
                    scraper = threading.Thread(target=scrape, daemon=True)
                    scraper.start()
                alls = [np.arange(elems, dtype=np.float32) + 100.0 * r
                        for r in range(2)]
                total = alls[0] + alls[1]
                for _ in range(3):
                    got = coll.all_reduce(ctx, alls[rank], topo="ring")
                    assert (got == total).all()
                    ctx.comm_fence()  # fires the MSG_METRICS merge
                reg = MetricsRegistry(ctx)
                assert reg.prometheus_text(merged=(rank == 0))
                assert not wd.events, wd.events  # no false positives
                stop_scrape.set()
                if scraper is not None:
                    scraper.join(timeout=10)
                if exporter is not None:
                    exporter.stop()
                wd.stop()
                ctx.comm_fence()
                ctx.comm_fini()
        except Exception as e:  # pragma: no cover - stress harness
            errs.append((rank, repr(e)))

    try:
        ts = [threading.Thread(target=rank_prog, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        hung = [t.name for t in ts if t.is_alive()]
        assert not hung, f"deadlocked rank threads: {hung}"
        assert not errs, errs
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def prefix_spec_churn(workers: int, reqs_per_thread: int = 6,
                      env=None) -> None:
    """ptc-share churn (PR 14): 2 QoS tenants x 2 submitter threads
    hammer OVERLAPPING prompts through a live InferenceEngine with
    speculative decoding ON and a page pool small enough to force the
    whole shared-prefix life cycle — concurrent `acquire_prefix`
    check-and-reserve against pump-thread retirement (the admission
    race fix), freeze/hit/refcount churn, COW clones, cached-frozen
    eviction and speculative page rollback — while the driver thread
    runs the continuous-batching loop and a reader scrapes the pool
    counters, stats()["serve"] and the tenant-labelled Prometheus text.
    TSan watches the pool lock discipline, the engine/server/scope
    locks and the native QoS-pool churn underneath in one address
    space; a final bit-exactness spot check keeps the stress honest."""
    import threading
    import time

    from parsec_tpu.serve import (InferenceEngine, PagedLM,
                                  PagedLMConfig, TenantConfig)

    env = env or {}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        model = PagedLM(PagedLMConfig(vocab=24, d=8, page=4, seed=5))
        rng0 = np.random.RandomState(3)
        common = [list(rng0.randint(0, 24, size=12)) for _ in range(3)]
        with pt.Context(nb_workers=workers, scheduler="lws") as ctx:
            eng = InferenceEngine(
                ctx, model, n_pages=40, max_seqs=8,
                tenants=[TenantConfig("hi", priority=4, weight=3,
                                      max_pools=4, max_queue=128),
                         TenantConfig("lo", max_pools=4,
                                      max_queue=128)],
                spec_k=2)
            reg = ctx.metrics_registry()
            handles, hlock = [], threading.Lock()

            def submitter(tenant, seed):
                rng = np.random.RandomState(seed)
                for _ in range(reqs_per_thread):
                    c = common[rng.randint(len(common))]
                    tail = list(rng.randint(0, 24,
                                            size=rng.randint(0, 3)))
                    h = eng.submit(c[:rng.randint(4, 13)] + tail,
                                   int(rng.randint(2, 5)), tenant)
                    with hlock:
                        handles.append(h)

            stop = threading.Event()

            def reader():
                while not stop.is_set():
                    eng.pool.stats()
                    ctx.stats()["serve"]
                    reg.prometheus_text()
                    stop.wait(0.005)

            subs = [threading.Thread(target=submitter, args=(t, s))
                    for s, t in enumerate(("hi", "lo", "hi", "lo"))]
            rd = threading.Thread(target=reader, daemon=True)
            rd.start()
            for t in subs:
                t.start()
            deadline = time.monotonic() + 300
            while any(t.is_alive() for t in subs) or eng.pending() \
                    or eng._inflight:
                assert time.monotonic() < deadline, "churn deadlocked"
                eng.run(timeout_s=240)
                time.sleep(0.001)
            for t in subs:
                t.join(timeout=60)
            stop.set()
            rd.join(timeout=10)
            st = eng.pool.stats()
            assert st["free"] + st["cached_free"] == st["n_pages"], st
            assert st["prefix_hits"] > 0, st
            with hlock:
                done = [h for h in handles if h.state == "done"]
                assert len(done) == len(handles), \
                    [(h.state, h.tenant) for h in handles]
            for h in done[:4]:
                rt, ro = model.reference_generate(h.prompt,
                                                  h.max_new)
                assert h.tokens == rt
                assert np.array_equal(np.stack(h.outputs), ro)
            eng.close()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def fleet_churn(workers: int, reqs_per_thread: int = 5,
                env=None) -> None:
    """ptc-route fleet churn (PR 16): TWO replica engines (own contexts,
    one address space) behind one Router; two submitter threads route
    OVERLAPPING shared-prefix prompts through the scored placement path
    (advertise -> digest -> placement_cost) while a migration thread
    hammers content-hash page migration in BOTH directions between the
    live pools — concurrent with each engine's own freeze/acquire/
    eviction churn and the pump-thread retirements underneath — and a
    reader scrapes router.stats() (which walks every replica's
    advertise + pool counters).  TSan watches the router handle-list
    lock, both pool locks under cross-pool export/import, the
    server/scope locks and the native QoS churn in one address space;
    bit-exactness spot checks and exact page accounting on both pools
    keep the stress honest."""
    import threading
    import time

    from parsec_tpu.ops.paged_attention import prefix_page_keys
    from parsec_tpu.serve import (InferenceEngine, PagedLM,
                                  PagedLMConfig, Replica, Router,
                                  TenantConfig)

    env = env or {}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        model = PagedLM(PagedLMConfig(vocab=24, d=8, page=4, seed=5))
        rng0 = np.random.RandomState(11)
        common = [list(rng0.randint(0, 24, size=12)) for _ in range(3)]
        ckeys = [prefix_page_keys(model.model_id, c, 4) for c in common]
        ctxs = [pt.Context(nb_workers=workers, scheduler="lws")
                for _ in range(2)]
        try:
            reps = [Replica(InferenceEngine(
                c, model, n_pages=28, max_seqs=6,
                tenants=[TenantConfig("t", max_pools=4,
                                      max_queue=128)],
                name=f"r{i}")) for i, c in enumerate(ctxs)]
            router = Router(reps)
            handles, hlock = [], threading.Lock()

            def submitter(seed):
                rng = np.random.RandomState(seed)
                for _ in range(reqs_per_thread):
                    c = common[rng.randint(len(common))]
                    tail = list(rng.randint(0, 24,
                                            size=rng.randint(0, 3)))
                    fh = router.submit(c + tail,
                                       int(rng.randint(2, 5)), "t")
                    with hlock:
                        handles.append(fh)

            stop = threading.Event()

            def migrator():
                i = 0
                while not stop.is_set():
                    keys = ckeys[i % len(ckeys)]
                    dst = reps[i % 2]
                    src = reps[(i + 1) % 2]
                    router.migrate(keys, dst=dst, src=src)
                    i += 1
                    stop.wait(0.002)

            def reader():
                while not stop.is_set():
                    router.stats()
                    stop.wait(0.005)

            subs = [threading.Thread(target=submitter, args=(s,))
                    for s in (1, 2)]
            aux = [threading.Thread(target=migrator, daemon=True),
                   threading.Thread(target=reader, daemon=True)]
            for t in aux:
                t.start()
            for t in subs:
                t.start()
            deadline = time.monotonic() + 300
            while any(t.is_alive() for t in subs) or router._busy():
                assert time.monotonic() < deadline, "fleet deadlocked"
                router.run(timeout_s=240)
                time.sleep(0.001)
            for t in subs:
                t.join(timeout=60)
            stop.set()
            for t in aux:
                t.join(timeout=10)
            for rep in reps:
                st = rep.pool.stats()
                assert st["free"] + st["cached_free"] == \
                    st["n_pages"], st
            with hlock:
                done = [fh for fh in handles if fh.state == "done"]
                assert len(done) == len(handles), \
                    [fh.state for fh in handles]
            for fh in done[:4]:
                rt, ro = model.reference_generate(fh.prompt,
                                                  fh.max_new)
                assert fh.tokens == rt
                assert np.array_equal(np.stack(fh.outputs), ro)
            router.close()
        finally:
            for c in ctxs:
                c.destroy()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def journal_churn(workers: int, port: int, pools_per_tenant: int = 10,
                  env=None) -> None:
    """ptc-blackbox stress under a 2-rank context: each rank runs a
    Server + a crash-armed Journal with aggressive cadences — record()
    from submitter/pump/worker threads (serve + scope-event hooks)
    racing the cadence thread's drain/fsync/rotation, inventory
    checkpoints snapshotting live scopes + inflight slots + MSG_BLOB
    replication riding the comm engine, crash-header refreshes
    (ptc_crash_update_meta reading the clock/ring atomics) racing
    fence-time clock sync, a FleetView scraping the local server and a
    reader thread on stats()/prometheus (with the ptc_fleet_* family)
    — all in one TSan-observed address space.  The fatal-signal writer
    itself never fires here: its bounded-spin ProfBuf read is
    crash-path-only by design (a deliberate data race TSan must not
    see in healthy runs)."""
    import tempfile
    import threading
    import time

    from parsec_tpu.profiling.blackbox import FleetView, Journal
    from parsec_tpu.serve import Server, TenantConfig

    env = env or {}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    errs = []

    def rank_prog(rank, jdir):
        try:
            ctx = pt.Context(nb_workers=workers, scheduler="lws")
            ctx.set_rank(rank, 2)
            ctx.comm_init(port)
            with ctx:
                ctx.register_arena("t", 8)
                ctx.profile_enable(1)
                ctx.profile_ring(1 << 16)
                jr = Journal(ctx, dirpath=jdir, max_bytes=1 << 16,
                             fsync_s=0.02, checkpoint_s=0.05)
                jr.register_inventory(
                    "frozen_page_keys",
                    lambda: [f"page:{rank}:{i}" for i in range(4)])
                srv = Server(ctx, [
                    TenantConfig("hi", priority=4, weight=3,
                                 max_pools=3, max_queue=64),
                    TenantConfig("lo", priority=0, weight=1,
                                 max_pools=3, max_queue=64),
                ])
                fv = FleetView(ctx=ctx, servers=[srv], interval_s=0.01)
                reg = ctx.metrics_registry()

                def mk(priority, weight):
                    tp = ctx.taskpool(globals={"N": 15},
                                      priority=priority, weight=weight)
                    tc = tp.task_class("C")
                    tc.param("k", 0, pt.G("N"))
                    tc.flow("X", "RW",
                            pt.In(None, guard=(pt.L("k") == 0)),
                            pt.In(pt.Ref("C", pt.L("k") - 1, flow="X")),
                            pt.Out(pt.Ref("C", pt.L("k") + 1, flow="X"),
                                   guard=(pt.L("k") < pt.G("N"))),
                            arena="t")
                    tc.body_noop()
                    return tp

                def submitter(tenant):
                    for _ in range(pools_per_tenant):
                        srv.submit(tenant, mk)

                subs = [threading.Thread(target=submitter, args=(t,))
                        for t in ("hi", "lo")]
                stop = threading.Event()

                def reader():
                    while not stop.is_set():
                        ctx.stats()["fleet"]
                        reg.prometheus_text()
                        jr.stats()
                        jr.lost_peers()
                        stop.wait(0.005)

                rd = threading.Thread(target=reader, daemon=True)
                rd.start()
                for t in subs:
                    t.start()
                # fences interleave the MSG_BLOB checkpoints with
                # clock sync + MSG_METRICS merges
                for _ in range(3):
                    ctx.comm_fence()
                    time.sleep(0.05)
                for t in subs:
                    t.join(timeout=120)
                assert srv.drain(timeout=120)
                stop.set()
                rd.join(timeout=10)
                fv.stop()
                srv.close()
                ctx.comm_fence()
                jr.stop()
                st = jr.stats()
                assert st["records"] > 0 and st["checkpoints"] >= 0, st
                ctx.comm_fini()
        except Exception as e:  # pragma: no cover - stress harness
            errs.append((rank, repr(e)))

    try:
        with tempfile.TemporaryDirectory() as td:
            dirs = [os.path.join(td, f"r{r}") for r in range(2)]
            ts = [threading.Thread(target=rank_prog, args=(r, dirs[r]))
                  for r in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            hung = [t.name for t in ts if t.is_alive()]
            assert not hung, f"deadlocked rank threads: {hung}"
            assert not errs, errs
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def control_churn(workers: int, reqs_per_thread: int = 5,
                  env=None) -> None:
    """ptc-pilot churn (PR 19): a live InferenceEngine with ADAPTIVE
    speculation (spec_k='auto') and a feedback Controller bound to it,
    under concurrent fire from every side at once — submitter threads
    on two tenants (per-tenant bandit windows + the page-pressure
    pause/resume gate against a small pool), an observer thread feeding
    drifted makespan ratios and watchdog interrupts (retune evaluation
    + the pool-boundary hot-swap's hold_knobs snapshot/restore racing
    everything), and a scraper hammering ctrl.stats() /
    Context.stats()['control'] / ctrl.poll() (budget-share pushes into
    the pool and admission-pressure pushes into the server) while the
    driver runs the continuous-batching loop (whose _reap also calls
    poll).  TSan watches the controller lock against the engine, pool,
    server and scope locks in one address space; a final bit-exactness
    spot check keeps the adaptive path honest."""
    import threading
    import time

    from parsec_tpu.analysis.control import Controller
    from parsec_tpu.serve import (InferenceEngine, PagedLM,
                                  PagedLMConfig, TenantConfig)

    env = env or {}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        model = PagedLM(PagedLMConfig(vocab=24, d=8, page=4, seed=5))
        rng0 = np.random.RandomState(7)
        common = [list(rng0.randint(0, 24, size=10)) for _ in range(3)]
        with pt.Context(nb_workers=workers, scheduler="lws") as ctx:
            ctrl = Controller(ctx, window=4, cooldown=2,
                              drift_ratio=1.25)
            # a small never-run chain as the retune target: evaluate()
            # re-simulates it concurrently with the serving loop
            ctx.register_arena("t", 8)
            tp = pt.Taskpool(ctx, globals={"NB": 199})
            kk = pt.L("k")
            tc = tp.task_class("Task")
            tc.param("k", 0, pt.G("NB"))
            tc.flow("A", "RW",
                    pt.In(None, guard=(kk == 0)),
                    pt.In(pt.Ref("Task", kk - 1, flow="A")),
                    pt.Out(pt.Ref("Task", kk + 1, flow="A"),
                           guard=(kk < pt.G("NB"))),
                    arena="t")
            tc.body_noop()
            ctrl.attach_target(tp, workers=workers)
            eng = InferenceEngine(          # auto-binds to ctx._controller
                ctx, model, n_pages=40, max_seqs=8,
                tenants=[TenantConfig("hi", priority=4, weight=3,
                                      max_pools=4, max_queue=128),
                         TenantConfig("lo", max_pools=4,
                                      max_queue=128)],
                spec_k="auto")
            handles, hlock = [], threading.Lock()

            def submitter(tenant, seed):
                rng = np.random.RandomState(seed)
                for _ in range(reqs_per_thread):
                    c = common[rng.randint(len(common))]
                    h = eng.submit(c[:rng.randint(4, 11)],
                                   int(rng.randint(2, 5)), tenant)
                    with hlock:
                        handles.append(h)

            stop = threading.Event()

            def observer():
                i = 0
                while not stop.is_set():
                    ctrl.observe_pool(2.5 if i % 3 else 0.9)
                    if i % 17 == 11:
                        ctrl.interrupt("stuck_task", key=f"Pool#{i}")
                    i += 1
                    stop.wait(0.002)

            def scraper():
                while not stop.is_set():
                    ctrl.stats()
                    ctx.stats()["control"]
                    ctrl.poll()
                    stop.wait(0.004)

            subs = [threading.Thread(target=submitter, args=(t, s))
                    for s, t in enumerate(("hi", "lo", "hi", "lo"))]
            obs = threading.Thread(target=observer, daemon=True)
            scr = threading.Thread(target=scraper, daemon=True)
            obs.start()
            scr.start()
            for t in subs:
                t.start()
            deadline = time.monotonic() + 300
            while any(t.is_alive() for t in subs) or eng.pending() \
                    or eng._inflight:
                assert time.monotonic() < deadline, "churn deadlocked"
                eng.run(timeout_s=240)
                time.sleep(0.001)
            for t in subs:
                t.join(timeout=60)
            stop.set()
            obs.join(timeout=10)
            scr.join(timeout=10)
            s = ctrl.stats()
            assert s["retunes"] >= 1, s
            assert s["decisions"] >= 1, s
            with hlock:
                done = [h for h in handles if h.state == "done"]
                assert len(done) == len(handles), \
                    [(h.state, h.tenant) for h in handles]
            for h in done[:4]:
                rt, ro = model.reference_generate(h.prompt, h.max_new)
                assert h.tokens == rt
                assert np.array_equal(np.stack(h.outputs), ro)
            eng.close()
            ctrl.stop()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def serve_churn(workers: int, port: int, pools_per_tenant: int = 24,
                env=None) -> None:
    """Serving-runtime stress under a 2-rank context (one process, a
    thread per rank): each rank runs a Server with two QoS tenants and
    TWO concurrent submitter threads hammering admission — per-pool QoS
    lane pushes/pops from every worker, concurrent taskpool
    creation/retirement (pump-thread destroys racing worker
    completions), admission queue/reject churn, and qos_stats reads
    from the stats thread — while comm fences run.  TSan watches the
    new lane machinery, the tp->qos counters, and the grow-only lane
    table publication in one address space.

    ptc-scope (PR 11) rides along: every admitted pool is
    scope-stamped by the Server (tp->scope_id relaxed loads on the
    EXEC span path + the u64 scope word on every cross-rank ACTIVATE),
    tracing level 1 keeps those paths hot, and the reader thread
    scrapes the full surface — Context.stats()["scope"] (registry lock
    vs submitter/pump writers) and the tenant-labelled Prometheus
    text — while pools churn."""
    import threading

    from parsec_tpu.serve import Server, TenantConfig

    env = env or {}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    errs = []

    def rank_prog(rank):
        try:
            ctx = pt.Context(nb_workers=workers, scheduler="lws")
            ctx.set_rank(rank, 2)
            ctx.comm_init(port)
            with ctx:
                ctx.register_arena("t", 8)
                # scope span stamps + SCOPE wire instants stay hot
                # (ring mode bounds the buffers over the pool churn)
                ctx.profile_enable(1)
                ctx.profile_ring(1 << 16)
                srv = Server(ctx, [
                    TenantConfig("hi", priority=4, weight=3,
                                 max_pools=3, max_queue=64,
                                 slo_ms=60_000),
                    TenantConfig("lo", priority=0, weight=1,
                                 max_pools=3, max_queue=64),
                ])
                reg = ctx.metrics_registry()

                def mk(priority, weight):
                    tp = ctx.taskpool(globals={"N": 15},
                                      priority=priority, weight=weight)
                    tc = tp.task_class("C")
                    tc.param("k", 0, pt.G("N"))
                    tc.flow("X", "RW",
                            pt.In(None, guard=(pt.L("k") == 0)),
                            pt.In(pt.Ref("C", pt.L("k") - 1, flow="X")),
                            pt.Out(pt.Ref("C", pt.L("k") + 1, flow="X"),
                                   guard=(pt.L("k") < pt.G("N"))),
                            arena="t")
                    tc.body_noop()
                    return tp

                def submitter(tenant):
                    for _ in range(pools_per_tenant):
                        srv.submit(tenant, mk)

                subs = [threading.Thread(target=submitter, args=(t,))
                        for t in ("hi", "lo")]
                stop = threading.Event()

                def stats_reader():
                    while not stop.is_set():
                        ctx.sched_stats()
                        srv.stats()
                        # scrape surface: scope registry rollup +
                        # tenant-labelled exposition text race the
                        # submitters/pump mutating the same records
                        ctx.stats()["scope"]
                        reg.prometheus_text()
                        stop.wait(0.005)

                rd = threading.Thread(target=stats_reader, daemon=True)
                rd.start()
                for t in subs:
                    t.start()
                for t in subs:
                    t.join(timeout=120)
                assert srv.drain(timeout=120)
                stop.set()
                rd.join(timeout=10)
                st = srv.stats()["totals"]
                assert st["completed"] == 2 * pools_per_tenant, st
                srv.close()
                ctx.comm_fence()
                ctx.comm_fini()
        except Exception as e:  # pragma: no cover - stress harness
            errs.append((rank, repr(e)))

    try:
        ts = [threading.Thread(target=rank_prog, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        hung = [t.name for t in ts if t.is_alive()]
        assert not hung, f"deadlocked rank threads: {hung}"
        assert not errs, errs
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def wave_fuse_gemm(workers: int, port: int, N: int = 32, nb: int = 8,
                   env=None) -> None:
    """ptc-fuse under TSan: two colocated ranks (a thread per rank) run
    a distributed GEMM with the WAVE COMPILER ON over the streamed wire
    — the fuse cache and online certification on each device manager
    thread, the prefetch lane's peeks/hint staging, and the comm
    threads' deliveries all race in one TSan-observed address space.
    The chain path legitimately refuses on gemm_dist (task-sourced
    panels); the certification + counter paths are what this job
    drives concurrently with wire deliveries."""
    import threading

    env = dict(env or {})
    env.setdefault("PTC_MCA_device_wave_fuse", "1")
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    errs = []

    def rank_prog(rank):
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
            from parsec_tpu.algos.gemm import build_gemm_dist
            from parsec_tpu.data.collections import TwoDimBlockCyclic
            from parsec_tpu.device.tpu import TpuDevice

            ctx = pt.Context(nb_workers=workers, scheduler="lws")
            ctx.set_rank(rank, 2)
            ctx.comm_init(port)
            with ctx:
                rng = np.random.default_rng(3)
                a = rng.normal(size=(N, N)).astype(np.float32)
                b = rng.normal(size=(N, N)).astype(np.float32)
                c0 = rng.normal(size=(N, N)).astype(np.float32)
                mk = lambda: TwoDimBlockCyclic(
                    N, N, nb, nb, P=2, Q=1, nodes=2, myrank=rank,
                    dtype=np.float32)
                A, B, C = mk(), mk(), mk()
                A.register(ctx, "A"); A.from_dense(a)
                B.register(ctx, "B"); B.from_dense(b)
                C.register(ctx, "C"); C.from_dense(c0)
                dev = TpuDevice(ctx)
                tp = build_gemm_dist(ctx, A, B, C, dev=dev)
                tp.run()
                tp.wait()
                ctx.comm_fence()
                dev.flush()
                ref = c0.astype(np.float64) + a.astype(np.float64) \
                    @ b.astype(np.float64)
                nt = C.mt
                for m in range(nt):
                    for n in range(nt):
                        if C.rank_of(m, n) != rank:
                            continue
                        lo = np.abs(
                            C.tile(m, n)
                            - ref[m * nb:(m + 1) * nb,
                                  n * nb:(n + 1) * nb]).max()
                        assert lo < 2e-3, (m, n, lo)
                dev.stop()
                ctx.comm_fence()
                ctx.comm_fini()
        except Exception as e:  # pragma: no cover - stress harness
            errs.append((rank, repr(e)))

    try:
        ts = [threading.Thread(target=rank_prog, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=600)
        hung = [t.name for t in ts if t.is_alive()]
        assert not hung, f"deadlocked rank threads: {hung}"
        assert not errs, errs
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def tp_decode_churn(workers: int, port: int, max_new: int = 5,
                    env=None) -> None:
    """ptc-shard (PR 18): TWO colocated ranks serve ONE tensor-parallel
    PagedLM — qkv/ffn rows and KV pages sharded by head (one PagePool
    per rank), every prefill/decode/verify pool embedding a RefReduce
    ptc_coll_* chain whose slice-granular step deliveries race the
    wave compiler (per-rank shard-wave certification + fused dispatch
    on the device manager thread) and the prefetch lane's peeks, all
    over the streamed (rendezvous + chunked, 2-rail) wire.  A reader
    thread per rank concurrently scrapes the head-sharded pool
    counters, stats()["serve"]["tp"] (the coll_wait fold readers) and
    device_stats() while the SPMD step loop and both comm threads
    mutate them in one TSan-observed address space.  A final bitwise
    check against the single-rank reference and the fused_waves>0 /
    coll_pools>0 floors keep the stress honest."""
    import threading
    import time

    from parsec_tpu.serve import InferenceEngine, PagedLM, PagedLMConfig

    env = dict(env or {})
    env.setdefault("PTC_MCA_device_wave_fuse", "1")
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    errs = []
    prompts = [[1, 2, 3, 4, 5, 6, 7, 8], [1, 2, 3, 4, 5, 6, 7, 8, 9],
               [3, 1, 4, 1, 5]]

    def rank_prog(rank):
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
            from parsec_tpu.device import TpuDevice

            ctx = pt.Context(nb_workers=workers, scheduler="lws")
            ctx.set_rank(rank, 2)
            ctx.comm_init(port)
            ctx.comm_set_colocated([1 - rank])
            with ctx:
                model = PagedLM(PagedLMConfig(heads=4, qlog=True))
                dev = TpuDevice(ctx)
                try:
                    eng = InferenceEngine(ctx, model, n_pages=64,
                                          max_seqs=4, tp=2, spec_k=2,
                                          dev=dev)
                    stop = threading.Event()

                    def reader():
                        while not stop.is_set():
                            eng.pool.stats()
                            ctx.stats()["serve"]
                            ctx.device_stats()
                            stop.wait(0.003)

                    rd = threading.Thread(target=reader, daemon=True)
                    rd.start()
                    hs = []
                    t0 = time.monotonic()
                    for p in prompts:
                        h = eng.submit(p, max_new)
                        hs.append(h)
                        while h.state == "submitted":
                            assert time.monotonic() - t0 < 240, \
                                "prefill stuck"
                            time.sleep(0.001)
                    while eng.pending() or eng._inflight:
                        assert time.monotonic() - t0 < 240, \
                            "decode stuck"
                        eng.step()
                    stop.set()
                    rd.join(timeout=10)
                    tp_st = eng._tp_stats()
                    assert tp_st["coll_pools"] > 0, tp_st
                    fuse = ctx.device_stats().get("fuse", {})
                    assert fuse.get("fused_waves", 0) > 0, fuse
                    for h in hs:
                        rt, ro = model.reference_generate(h.prompt,
                                                          h.max_new)
                        assert list(h.tokens) == rt
                        for j, o in enumerate(h.outputs):
                            assert np.array_equal(
                                o, model.pre_logits(ro[j]))
                    eng.close()
                finally:
                    dev.stop()
                ctx.comm_fence()
                ctx.comm_fini()
        except Exception as e:  # pragma: no cover - stress harness
            errs.append((rank, repr(e)))

    try:
        ts = [threading.Thread(target=rank_prog, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=600)
        hung = [t.name for t in ts if t.is_alive()]
        assert not hung, f"deadlocked rank threads: {hung}"
        assert not errs, errs
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def reshape_churn(workers: int, fanout: int, rounds: int) -> None:
    """Concurrent consumers of the same (copy, [type]) — the memoized
    reshape cache's create/hit race — plus write-back version bumps that
    trigger the stale-entry eviction path against racing readers
    (round-4 machinery: ptc_reshape_get / ReshapeCache)."""
    n = 16
    tile = np.arange(n * n, dtype=np.int32).reshape(n, n)
    with pt.Context(nb_workers=workers) as ctx:
        segs = [(i * n * 4, (i + 1) * 4) for i in range(n)]
        ctx.register_datatype_indexed("LOW", segs)
        ctx.register_datatype_cast("I2L", np.int32, np.int64)
        ctx.register_linear_collection("A", tile, elem_size=tile.nbytes)
        tp = pt.Taskpool(ctx, globals={"NR": rounds - 1, "NF": fanout - 1})
        r = pt.L("r")
        w = tp.task_class("W")
        w.param("r", 0, pt.G("NR"))
        w.flow("A", "RW",
               pt.In(pt.Mem("A", 0), guard=(r == 0)),
               pt.In(pt.Ref("W", r - 1, flow="A")),
               pt.Out(pt.Ref("R", r, pt.Range(0, pt.G("NF")), flow="X")),
               pt.Out(pt.Ref("C", r, pt.Range(0, pt.G("NF")), flow="X")),
               pt.Out(pt.Ref("W", r + 1, flow="A"),
                      guard=(r < pt.G("NR"))),
               pt.Out(pt.Mem("A", 0), ltype="LOW", guard=(r == pt.G("NR"))))

        def wbody(t):
            t.data("A", np.int32)[0] += 1  # version churn per round

        w.body(wbody)
        rd = tp.task_class("R")
        rd.param("r", 0, pt.G("NR"))
        rd.param("f", 0, pt.G("NF"))
        rd.flow("X", "READ", pt.In(pt.Ref("W", r, flow="A"), ltype="LOW"))
        rd.body_noop()
        cc = tp.task_class("C")
        cc.param("r", 0, pt.G("NR"))
        cc.param("f", 0, pt.G("NF"))
        cc.flow("X", "READ", pt.In(pt.Ref("W", r, flow="A"), ltype="I2L"))
        cc.body_noop()
        tp.run()
        tp.wait()


def main():
    reps = int(os.environ.get("STRESS_REPS", "3"))
    for rep in range(reps):
        for sched in ("lws", "lfq", "ll", "lhq"):
            ep_burst(sched, workers=8, n=20000)
            chain_mesh(sched, workers=8, nb=200, lanes=16)
        dtd_churn(workers=8, tiles=8, rounds=100)
        reshape_churn(workers=8, fanout=8, rounds=60)
        # ptc-tune magazine-batch knob (PR 12): non-default batches
        # stress the task/arena refill-spill crossings — a tiny batch
        # maximizes free_lock traffic, a big one maximizes per-spill
        # move size; the knob binds at context create, so each job
        # runs its own contexts under the env
        os.environ["PTC_MCA_runtime_mag_batch"] = "4"
        chain_mesh("lws", workers=8, nb=120, lanes=16)
        os.environ["PTC_MCA_runtime_mag_batch"] = "512"
        chain_mesh("lws", workers=8, nb=120, lanes=16)
        os.environ.pop("PTC_MCA_runtime_mag_batch", None)
        colocated_comm(workers=4, port=29900 + rep)
        # wire-v4 socket/session paths: chunk sessions, zero-copy
        # sendmsg pins, 2-rail striping (16 KiB payloads, 2 KiB chunks)
        colocated_comm(workers=4, nb=24, port=29940 + rep, elems=2048,
                       env={"PTC_MCA_comm_eager_limit": "0",
                            "PTC_MCA_comm_chunk_size": "2048",
                            "PTC_MCA_comm_inflight": "3",
                            "PTC_MCA_comm_rails": "2"})
        # runtime-native collectives over the chunked wire: ptc_coll_*
        # step deliveries + coll counters + per-op bcast-tree switches,
        # every topology, sliced contributions riding 2 KiB chunks
        colocated_coll(workers=4, elems=4096, port=29960 + rep,
                       env={"PTC_MCA_comm_eager_limit": "0",
                            "PTC_MCA_comm_chunk_size": "2048",
                            "PTC_MCA_coll_slice": "4096",
                            "PTC_MCA_comm_rails": "2"})
        # tracing v2 under load: level-2 tracing + flight-recorder RING
        # on a 2-rank job — worker pushes racing the ring's wraparound,
        # comm-thread COMM instants + clock-sync PONG handling on buffer
        # 0, PINS-off trace path (the observability PR's new code under
        # TSan's happens-before analysis)
        colocated_comm(workers=4, nb=48, port=29980 + rep,
                       env={"PTC_MCA_runtime_profile": "1",
                            "PTC_MCA_runtime_trace_ring": "16384"})
        # always-on metrics + watchdog + Prometheus scrape concurrent
        # with a streamed 2-rank collective (PR 7): lock-free histogram
        # records from every worker, inflight-slot scans, snapshot
        # serialization on the scrape thread, fence-time MSG_METRICS
        # merge — TSan watches all of it in one address space
        metrics_watchdog_coll(workers=4, elems=4096, port=30000 + rep,
                              env={"PTC_MCA_comm_eager_limit": "0",
                                   "PTC_MCA_comm_chunk_size": "2048",
                                   "PTC_MCA_comm_rails": "2"})
        # ptc-topo (PR 17): two-island hierarchical collectives, 4
        # colocated ranks — island-leader exchange + follower fan-out
        # step deliveries over the chunked wire + per-class counter
        # folds, one TSan-observed address space
        colocated_hier_coll(workers=2, elems=4096, port=30060 + rep,
                            env={"PTC_MCA_comm_eager_limit": "0",
                                 "PTC_MCA_comm_chunk_size": "2048",
                                 "PTC_MCA_comm_rails": "2"})
        # serving runtime (PR 9): QoS lanes + concurrent pool
        # creation/retirement + admission churn under a 2-rank context
        serve_churn(workers=4, port=30020 + rep)
        # ptc-blackbox (PR 20): crash-armed journal + checkpoint blob
        # replication + FleetView scrapes racing the serve churn
        journal_churn(workers=4, port=30100 + rep)
        # ptc-share (PR 14): shared-prefix COW/eviction + speculative
        # rollback under concurrent submitters, retirement and scrapes
        prefix_spec_churn(workers=4)
        # ptc-route (PR 16): 2 replicas behind the fleet router —
        # scored placement + cross-pool page migration racing both
        # engines' freeze/acquire/eviction churn and stats scrapes
        fleet_churn(workers=4)
        # ptc-pilot (PR 19): feedback controller vs the serving loop —
        # drift observations, interrupts and hot-swaps racing adaptive
        # speculation, budget-share/pressure pushes and stats scrapes
        control_churn(workers=4)
        # wave mega-kernelization (PR 13): fuse cache + online
        # certification on the device manager threads, prefetch-lane
        # peeks, and streamed wire deliveries, 2 colocated ranks
        wave_fuse_gemm(workers=2, port=30040 + rep,
                       env={"PTC_MCA_comm_eager_limit": "0",
                            "PTC_MCA_comm_chunk_size": "2048",
                            "PTC_MCA_comm_rails": "2"})
        # ptc-shard (PR 18): 2-rank tensor-parallel decode — embedded
        # RefReduce coll chains + wave compiler + prefetch lane under
        # the streamed wire, concurrent stats readers on both ranks
        tp_decode_churn(workers=1, port=30080 + rep,
                        env={"PTC_MCA_comm_eager_limit": "0",
                             "PTC_MCA_comm_chunk_size": "2048",
                             "PTC_MCA_comm_rails": "2"})
        sys.stderr.write(f"rep {rep + 1}/{reps} done\n")
    print("stress ok")


if __name__ == "__main__":
    main()
