au BufRead,BufNewFile *.jdf set filetype=jdf
