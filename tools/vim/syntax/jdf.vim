" Vim syntax for JDF (parameterized task graph) files as accepted by
" parsec_tpu.dsl.jdf (reference role: tools/vim_syntax — written against
" THIS front-end's grammar: parsec_tpu/dsl/jdf.py lexer + parser).
"
" Install:  cp -r tools/vim ~/.vim  (or add tools/vim to runtimepath)

if exists("b:current_syntax")
  finish
endif

" task structure
syn keyword jdfKeyword BODY END NEW NULL
syn keyword jdfAccess READ WRITE RW CTL
syn match   jdfOption "^%option\>"

" dependency arrows and the priority clause
syn match jdfArrow "<-\|->"
syn match jdfPriorityClause "^\s*;"

" affinity line   : coll(expr, ...)
syn match jdfAffinity "^\s*:\s*\w\+\s*("he=e-1

" dep/task/global properties  [type = X hidden = on ...]
syn region jdfProps start="\[" end="\]" contains=jdfPropKey,jdfString
syn keyword jdfPropKey contained type type_remote type_data hidden default
syn keyword jdfPropKey contained profile priority batch startup_fn
syn keyword jdfPropKey contained make_key_fn hash_struct

" inline escapes  %{ ... %}  (Python here, C in the reference)
syn region jdfEscape start="%{" end="%}" keepend

" ranges and numbers
syn match jdfRange "\.\."
syn match jdfNumber "\<\d\+\>"
syn region jdfString start=+"+ end=+"+

" comments (C and C++ style pass the lexer as whitespace)
syn region jdfComment start="/\*" end="\*/"
syn match  jdfComment "//.*$"

hi def link jdfKeyword        Keyword
hi def link jdfAccess         Type
hi def link jdfOption         PreProc
hi def link jdfArrow          Operator
hi def link jdfPriorityClause Operator
hi def link jdfAffinity       Identifier
hi def link jdfPropKey        Special
hi def link jdfEscape         Macro
hi def link jdfRange          Operator
hi def link jdfNumber         Number
hi def link jdfString         String
hi def link jdfComment        Comment

let b:current_syntax = "jdf"
