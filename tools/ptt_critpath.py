#!/usr/bin/env python
"""Critical-path / lost-time / wire-latency report over .ptt traces
(reference role: the trace-table analyses PaRSEC runs on merged dbp
files — "where did the time go" for a distributed run).

Usage:
  python tools/ptt_critpath.py r0.ptt [r1.ptt ...] [--json out.json]

Multiple per-rank files are merged with cross-rank clock sync (header-v2
clock_offset_ns) and causal enforcement, then:
  - the executed DAG's critical path (needs level-2 traces: EDGE pairs),
  - a per-(rank, worker) lost-time breakdown
    (compute / release / h2d stall / comm wait / coll wait / idle),
  - the matched-flow wire-latency summary per (src, dst) pair.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from parsec_tpu.profiling import Trace, critical_path, lost_time  # noqa: E402


def _fmt_ns(ns: int) -> str:
    if ns >= 1_000_000_000:
        return f"{ns / 1e9:.3f}s"
    if ns >= 1_000_000:
        return f"{ns / 1e6:.3f}ms"
    return f"{ns / 1e3:.1f}us"


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("traces", nargs="+")
    ap.add_argument("--json", help="also write the report as JSON")
    ap.add_argument("--scope", default=None,
                    help="restrict the analysis to ONE request scope: a "
                         "scope id, or 'list' to enumerate the scopes "
                         "present (ptc-scope; the critical-path and "
                         "lost-time splits then describe that request "
                         "alone)")
    args = ap.parse_args(argv)
    traces = [Trace.load(p) for p in args.traces]
    merged = Trace.merge(traces) if len(traces) > 1 else traces[0]
    if args.scope == "list":
        legend = merged.meta.get("scopes") or {}
        for t in traces:
            legend.update(t.meta.get("scopes") or {})
        for sid in merged.scope_ids():
            who = legend.get(str(sid), {})
            extra = "".join(f" {k}={who[k]}" for k in
                            ("tenant", "kind", "rid") if who.get(k)
                            is not None)
            print(f"scope {sid}{extra}")
        return 0
    scope = None
    if args.scope is not None:
        scope = int(args.scope)
        merged = merged.filter_scope(scope)
        print(f"scope {scope}: {len(merged.events)} event(s)")
    report = {"files": list(args.traces),
              "ranks": sorted({int(t.rank) for t in traces}),
              "events": int(len(merged.events)),
              "scope": scope,
              "clock_offsets_ns": merged.meta.get("clock_offsets_ns", {}),
              "clamped_recvs": merged.meta.get("clamped_recvs", 0)}

    # ---------------------------------------------------- critical path
    try:
        cp = critical_path(merged)
    except ValueError as e:
        cp = None
        print(f"critical path: unavailable ({e})")
    if cp is not None:
        if cp["path"]:
            print(f"critical path: {len(cp['path'])} task(s), "
                  f"{_fmt_ns(cp['total_ns'])} "
                  f"({cp['coverage'] * 100:.1f}% of total EXEC time)")
            for cname, l0, l1, d in cp["path"]:
                print(f"  {cname}({l0},{l1})  {_fmt_ns(d)}")
            print("per-class time on the critical path:")
            for cname, ns in sorted(cp["per_class_ns"].items(),
                                    key=lambda kv: -kv[1]):
                print(f"  {cname}: {_fmt_ns(ns)}")
        else:
            print("critical path: no EXEC/EDGE events (trace level < 2?)")
        report["critical_path"] = cp

    # -------------------------------------------------------- lost time
    lt = lost_time(merged)
    if lt["workers"]:
        print("lost time per (rank, worker):")
        for (rank, worker), b in sorted(lt["workers"].items()):
            print(f"  r{rank}/w{worker}: "
                  f"compute {_fmt_ns(b['compute'])}  "
                  f"release {_fmt_ns(b['release'])}  "
                  f"h2d_stall {_fmt_ns(b['h2d_stall'])}  "
                  f"comm_wait {_fmt_ns(b['comm_wait'])}  "
                  f"coll_wait {_fmt_ns(b['coll_wait'])}  "
                  f"idle {_fmt_ns(b['idle'])}")
        report["lost_time_totals"] = lt["totals"]
        report["lost_time"] = {f"r{r}_w{w}": b
                               for (r, w), b in lt["workers"].items()}

    # ----------------------------------------------------- wire latency
    fl = merged.flows()
    if len(fl):
        print(f"wire latency ({len(fl)} matched message(s)):")
        pairs = {}
        for row in fl:
            pairs.setdefault((int(row[0]), int(row[1])), []).append(
                (int(row[6]), int(row[3])))
        wl = {}
        for (src, dst), items in sorted(pairs.items()):
            lats = np.array([i[0] for i in items], dtype=np.int64)
            byt = sum(i[1] for i in items)
            print(f"  {src} -> {dst}: n={len(lats)} "
                  f"p50={_fmt_ns(int(np.percentile(lats, 50)))} "
                  f"max={_fmt_ns(int(lats.max()))} bytes={byt}")
            wl[f"{src}->{dst}"] = {
                "n": int(len(lats)),
                "p50_ns": int(np.percentile(lats, 50)),
                "max_ns": int(lats.max()), "bytes": int(byt)}
        report["wire_latency"] = wl
    else:
        print("wire latency: no matched flows "
              "(single-rank trace, or pre-v2 files)")

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, default=str)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
