#!/usr/bin/env python
"""ptc-tune CLI: static schedule simulation + plan-driven knob search
over PTG task graphs (parsec_tpu/analysis/tune.py).

Targets are in-tree graph generator names from tools/verify_graphs.py
(or 'all'):

    python tools/ptc_tune.py potrf              # simulate + certify
    python tools/ptc_tune.py gemm_dist --search # rank knob proposals
    python tools/ptc_tune.py all --json out.json
    python tools/ptc_tune.py --check            # the make tune-check gate

`--check` (no target) runs the full in-tree sweep as a gate: every
graph must plan concretely (NO enumeration refusal), every wave must
carry an explicit fusability certify/refuse verdict (no silent skips),
and the simulator must price the default knob vector to a finite,
reproducible makespan (priced twice, compared bit-for-bit — the
determinism contract).  Exit 1 on any violation.

Real-run validation of proposals lives where workloads are runnable:
the bench harnesses (bench.py --dispatch / --collective tuned
sections) and `autotune(tp, measure=...)` for user pools.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import parsec_tpu as pt  # noqa: E402


def tune_all(only=None, topk=3, search=False):
    """Build + plan + simulate every generator.  Yields
    (name, plan, sim_result, proposals|None, issues)."""
    import verify_graphs
    from parsec_tpu.analysis import plan_taskpool
    from parsec_tpu.analysis.tune import ScheduleSimulator
    for gname, gen in verify_graphs.GENERATORS.items():
        if only and gname not in only:
            continue
        with pt.Context(nb_workers=1) as ctx:
            for tpname, tp in gen(ctx):
                plan = plan_taskpool(tp)
                issues = []
                sim_res = None
                props = None
                if plan.bounded:
                    issues.append("enumeration refused "
                                  "(symbolic fallback): cannot simulate")
                else:
                    sim = ScheduleSimulator(plan, workers=1)
                    sim_res = sim.simulate()
                    again = sim.simulate()
                    if sim_res != again:
                        issues.append("simulator non-deterministic")
                    if not sim_res["makespan_ns"] > 0:
                        issues.append("non-finite simulated makespan")
                    # verdict completeness: every (rank, wave) with
                    # members carries an explicit certificate
                    waves = {(r, row["wave"])
                             for r, rows in plan.waves.items()
                             for row in rows}
                    certified = {(c["rank"], c["wave"])
                                 for c in plan.fusability}
                    missing = waves - certified
                    if missing:
                        issues.append(
                            f"{len(missing)} wave(s) without a "
                            f"fusability verdict: {sorted(missing)[:4]}")
                    if search:
                        props = sim.propose(topk=topk)
                yield tpname, plan, sim_res, props, issues


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("target", nargs="?", default=None,
                    help="in-tree generator name or 'all'")
    ap.add_argument("--search", action="store_true",
                    help="run the coordinate-descent knob search and "
                         "print the ranked proposals")
    ap.add_argument("--topk", type=int, default=3)
    ap.add_argument("--check", action="store_true",
                    help="gate mode over all graphs (make tune-check)")
    ap.add_argument("--json", metavar="PATH", default=None)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if not args.check and args.target is None:
        print("ptc-tune: a target generator (or 'all' / --check) is "
              "required", file=sys.stderr)
        return 2
    import verify_graphs
    if args.target and args.target != "all" \
            and args.target not in verify_graphs.GENERATORS:
        print(f"ptc-tune: no in-tree generator named {args.target!r}; "
              f"generators: {', '.join(sorted(verify_graphs.GENERATORS))}",
              file=sys.stderr)
        return 2
    only = None if (args.check or args.target == "all") \
        else [args.target]

    dirty = 0
    results = {}
    for name, plan, sim_res, props, issues in tune_all(
            only, args.topk, search=args.search and not args.check):
        fus = plan.fusable_waves()
        nwaves = len(plan.fusability)
        status = "clean" if not issues else "; ".join(issues)
        mk = (f"{sim_res['makespan_ns'] / 1e6:.3f} ms"
              if sim_res else "-")
        print(f"{name:24s} {status}  [sim {mk}, fusable {fus}/{nwaves} "
              f"wave(s)]")
        if issues:
            dirty += 1
        if args.verbose:
            for c in plan.fusability:
                why = "" if c["fusable"] else \
                    f"  ({'; '.join(c['reasons'])})"
                print(f"    rank {c['rank']} wave {c['wave']:3d} "
                      f"{(c['cls'] or '<mixed>'):16s} x{c['width']:<4d} "
                      f"{'fusable' if c['fusable'] else 'refused'}{why}")
        row = {
            "issues": issues,
            "fusable_waves": fus,
            "waves": nwaves,
            "simulated_makespan_ns": (sim_res or {}).get("makespan_ns"),
        }
        if props:
            row["proposals"] = [
                {"knobs": p["knobs"],
                 "predicted_ns": p["predicted_ns"]} for p in props]
            for p in props[:args.topk]:
                print(f"    proposal {p['predicted_ns'] / 1e6:9.3f} ms  "
                      + ", ".join(f"{k.split('.')[-1]}={v}"
                                  for k, v in sorted(p["knobs"].items())))
        results[name] = row
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
            f.write("\n")
    verb = "tune-check" if args.check else "ptc-tune"
    print(f"{verb}: {len(results)} graph(s), {dirty} with refusals")
    return 1 if dirty else 0


if __name__ == "__main__":
    sys.exit(main())
