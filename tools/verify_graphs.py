#!/usr/bin/env python
"""Run ptc-verify (parsec_tpu.analysis) over every in-tree graph
generator: the algos/ PTG builders, the collective (ptc_coll_*) step
classes from comm/coll.py, and the ops-backed DAGs (ring attention over
ops/flash_attention kernels).  `make verify-graphs` runs this; the
tier-1 test tests/analysis/test_verify_intree.py asserts the clean
baseline stays clean.

Each generator builds its taskpool(s) in a fresh Context — nothing is
executed; verification happens on the task-class tables alone.

Usage: python tools/verify_graphs.py [--json out.json] [-v] [only ...]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import parsec_tpu as pt  # noqa: E402
from parsec_tpu.data.collections import TwoDimBlockCyclic  # noqa: E402


def _sq(ctx, name="A", nt=6, nb=8, dtype=np.float32):
    A = TwoDimBlockCyclic(nt * nb, nt * nb, nb, nb, dtype=dtype)
    A.register(ctx, name)
    return A


# ------------------------------------------------------------- generators
def g_potrf(ctx):
    from parsec_tpu.algos.potrf import build_potrf
    return [("potrf", build_potrf(ctx, _sq(ctx)))]


def g_potrf_textbook(ctx):
    from parsec_tpu.algos.potrf import build_potrf
    return [("potrf_textbook",
             build_potrf(ctx, _sq(ctx), trsm_via_inverse=False))]


def g_potrf_panels(ctx):
    from parsec_tpu.algos.potrf import build_potrf_panels
    nt, nb = 6, 8
    A = TwoDimBlockCyclic(nt * nb, nt * nb, nt * nb, nb, dtype=np.float32)
    A.register(ctx, "A")
    return [("potrf_panels", build_potrf_panels(ctx, A))]


def g_potrs_panels(ctx):
    from parsec_tpu.algos.potrf import build_potrs_panels
    nt, nb, nrhs = 6, 8, 8
    A = TwoDimBlockCyclic(nt * nb, nt * nb, nt * nb, nb, dtype=np.float32)
    A.register(ctx, "A")
    B = TwoDimBlockCyclic(nt * nb, nrhs, nt * nb, nrhs, dtype=np.float32)
    B.register(ctx, "B")
    return [("potrs_panels", build_potrs_panels(ctx, A, B))]


def g_gemm(ctx):
    from parsec_tpu.algos.gemm import build_gemm
    A = _sq(ctx, "A", 4)
    B = _sq(ctx, "B", 4)
    C = _sq(ctx, "C", 4)
    return [("gemm", build_gemm(ctx, A, B, C))]


def g_gemm_dist(ctx):
    from parsec_tpu.algos.gemm import build_gemm_dist
    A = _sq(ctx, "A", 4)
    B = _sq(ctx, "B", 4)
    C = _sq(ctx, "C", 4)
    return [("gemm_dist", build_gemm_dist(ctx, A, B, C))]


def g_trsm(ctx):
    from parsec_tpu.algos.trsm import build_trsm
    nt, nb, nrhs = 6, 8, 16
    L = _sq(ctx, "L", nt, nb)
    B = TwoDimBlockCyclic(nt * nb, nrhs, nb, nb, dtype=np.float32)
    B.register(ctx, "B")
    return [("trsm", build_trsm(ctx, L, B))]


def g_qr(ctx):
    from parsec_tpu.algos.qr import build_geqrf
    return [("geqrf", build_geqrf(ctx, _sq(ctx)))]


def g_lu(ctx):
    from parsec_tpu.algos.lu import build_getrf_nopiv
    return [("getrf_nopiv", build_getrf_nopiv(ctx, _sq(ctx)))]


def g_lu_panels(ctx):
    from parsec_tpu.algos.lu import build_getrf_panels
    nt, nb = 6, 8
    A = TwoDimBlockCyclic(nt * nb, nt * nb, nt * nb, nb, dtype=np.float32)
    A.register(ctx, "A")
    return [("getrf_panels", build_getrf_panels(ctx, A))]


def g_inverse(ctx):
    from parsec_tpu.algos.inverse import build_lauum, build_trtri
    L = _sq(ctx, "L", 5)
    W = _sq(ctx, "W", 5)
    C = _sq(ctx, "C", 5)
    return [("trtri", build_trtri(ctx, L, W)),
            ("lauum", build_lauum(ctx, W, C, names=("W", "C")))]


def g_matrix_ops(ctx):
    from parsec_tpu.algos.matrix_ops import (build_apply,
                                             build_reduce_col,
                                             build_reduce_row)
    A = _sq(ctx, "A", 5)

    def op(coll, m, n, tile):
        tile += 1

    def rop(acc, tile):
        return acc + tile

    out = []
    for uplo in ("full", "lower", "upper"):
        out.append((f"apply_{uplo}", build_apply(ctx, A, op, uplo=uplo)))
    out.append(("reduce_col", build_reduce_col(ctx, A, rop)))
    out.append(("reduce_row", build_reduce_row(ctx, A, rop)))
    return out


def g_map_operator(ctx):
    from parsec_tpu.algos.matrix_ops import build_map_operator
    S = _sq(ctx, "S", 4)
    D = _sq(ctx, "D", 4)

    def op(s, d, m, n):
        return s + d

    return [("map_operator",
             build_map_operator(ctx, S, D, op))]


def g_reshape(ctx):
    from parsec_tpu.algos.reshape import build_reshape_dtype
    src = _sq(ctx, "RSsrc", 4, dtype=np.float32)
    dst = TwoDimBlockCyclic(4 * 8, 4 * 8, 8, 8, dtype=np.float64)
    dst.register(ctx, "RSdst")
    return [("reshape_dtype", build_reshape_dtype(ctx, src, dst))]


def g_moe(ctx):
    from parsec_tpu.algos.moe import build_moe, make_moe_collections
    S, T, d, f, E, K = 2, 8, 4, 6, 3, 2
    Xc, Yc, WGc, WUc, WDc = make_moe_collections(S, T, d, f, E)
    return [("moe", build_moe(ctx, Xc, Yc, WGc, WUc, WDc, E, k=K))]


def g_ring_attention(ctx):
    from parsec_tpu.algos.ring_attention import (build_ring_attention,
                                                 make_collections)
    S, T, d = 4, 8, 4
    Qc, KVc, ACCc, Oc = make_collections(S, T, d)
    return [("ring_attention",
             build_ring_attention(ctx, Qc, KVc, ACCc, Oc))]


def g_ops_rms_norm(ctx):
    from parsec_tpu.ops.rms_norm import build_rms_norm
    R, T, d = 4, 8, 16
    Xc = TwoDimBlockCyclic(R * T, d, T, d, dtype=np.float32)
    Wc = TwoDimBlockCyclic(1, d, 1, d, dtype=np.float32)
    Oc = TwoDimBlockCyclic(R * T, d, T, d, dtype=np.float32)
    return [("ops_rms_norm", build_rms_norm(ctx, Xc, Wc, Oc))]


def g_ops_flash_attention(ctx):
    from parsec_tpu.ops.flash_attention import build_flash_attention
    NQ, T, d = 4, 8, 16
    Qc = TwoDimBlockCyclic(NQ * T, d, T, d, dtype=np.float32)
    Kc = TwoDimBlockCyclic(NQ * T, d, NQ * T, d, dtype=np.float32)
    Vc = TwoDimBlockCyclic(NQ * T, d, NQ * T, d, dtype=np.float32)
    Oc = TwoDimBlockCyclic(NQ * T, d, T, d, dtype=np.float32)
    return [("ops_flash_attention",
             build_flash_attention(ctx, Qc, Kc, Vc, Oc, causal=True))]


def g_paged_attention(ctx):
    """The serving runtime's paged KV-cache attention builders
    (ops/paged_attention): a ragged multi-sequence DECODE step (1/2/3
    pages per sequence — the pure-call lookup tables must verify
    exactly), a PREFILL with a partial last page, a WARM prefill whose
    shared-prefix pages read straight from the KV collections
    (ptc-share: PFILL's domain starts at the cold tail, one sequence
    fully warm prefilling ZERO pages), and the speculative VERIFY WAVE
    (pure fold chains over host-staged pages — the one-fused-launch
    batched verification graph)."""
    from parsec_tpu.ops.paged_attention import (PagePool, SeqSpec,
                                                build_paged_decode,
                                                build_paged_prefill,
                                                build_paged_verify,
                                                make_slot_collections)
    pool = PagePool(ctx, 16, 4, 8, name="KV")
    _, _, _, _, names = make_slot_collections(ctx, 4, 8, name="PA")
    seqs = [SeqSpec(0, [0, 1, 2], 1), SeqSpec(1, [3], 0),
            SeqSpec(2, [4, 5], 3)]
    dec = build_paged_decode(ctx, pool, seqs, names)
    PRc = TwoDimBlockCyclic(8 * 4, 16, 4, 16, dtype=np.float32)
    PRc.register(ctx, "PR")
    pseqs = [SeqSpec(0, [6, 7], 2), SeqSpec(1, [8], 4)]
    pre = build_paged_prefill(ctx, pool, pseqs, names, "PR",
                              [[0, 1], [2]])
    # warm prefill: seq 0 shares its first page (cold tail = 1 page),
    # seq 1 is FULLY warm (PFILL empty; the fold still runs whole)
    wseqs = [SeqSpec(0, [9, 10], 3), SeqSpec(1, [11, 12], 4)]
    warm = build_paged_prefill(ctx, pool, wseqs, names, "PR",
                               [[3, 4], [5, 6]], warm=[1, 2])
    # speculative verify wave: 3 virtual queries over a shared frozen
    # prefix [13] with ragged private windows — the engine's k-token
    # batched verification shape
    vseqs = [SeqSpec(0, [13, 14], 2), SeqSpec(1, [13, 14, 15], 3),
             SeqSpec(2, [13], 4)]
    ver = build_paged_verify(ctx, pool, vseqs, names)
    return [("ops_paged_decode", dec), ("ops_paged_prefill", pre),
            ("ops_paged_prefill_warm", warm),
            ("ops_paged_spec_verify", ver)]


def g_coll(ctx):
    """The ptc_coll_* step/leaf/src/gw classes (comm/coll.py) for every
    reduction topology plus the fan-out leg, planned for a 4-rank shape
    on this single-rank context (nothing runs; class tables only)."""
    from parsec_tpu.comm.coll import (_emit_fanout, _emit_reduce,
                                      _next_uid, _plan_reduce)
    R, nseg, ns = 4, 4, 2
    out = []
    for topo in ("star", "ring", "binomial"):
        uid = _next_uid(ctx)
        arena = f"__ptc_coll_{uid}"
        ctx.register_arena(arena, 64)
        plan = _plan_reduce(nseg, R, lambda s: s % R,
                            lambda s: [(r, r) for r in range(R)],
                            topo, ext=False)
        tp = pt.Taskpool(ctx)
        _emit_reduce(ctx, tp, uid, plan, ns, arena, np.add, np.float32,
                     local_read=lambda cid, seg, s: np.zeros(4,
                                                             np.float32),
                     final_sink=lambda seg, s, arr: None)
        out.append((f"coll_reduce_{topo}", tp))
    uid = _next_uid(ctx)
    arena = f"__ptc_coll_{uid}"
    ctx.register_arena(arena, 64)
    tp = pt.Taskpool(ctx)
    _emit_fanout(ctx, tp, uid, nseg, ns, R, lambda s: s % R, arena,
                 np.float32,
                 src_read=lambda s, slc: np.zeros(4, np.float32),
                 sink=lambda s, slc, arr: None)
    out.append(("coll_fanout", tp))
    return out


def g_tp_paged(ctx):
    """Tensor-parallel sharded serving graphs (ptc-shard): the DECODE
    and speculative-VERIFY builders with an embedded RefReduce
    all-reduce over the per-rank partial pre-logit projections.  Built
    for a 1-rank tp group on this single-rank context — the SPMD shape
    each rank compiles is IDENTICAL up to the contributor-id base, so
    the R=1 degenerate chain (local fold + fan-out, producer-domain
    selection, no dynamic guards on the coll step IN deps — V008)
    verifying exactly certifies the per-rank shard wave shape."""
    from parsec_tpu.ops.paged_attention import (PagePool, SeqSpec,
                                                build_paged_decode,
                                                build_paged_verify,
                                                make_slot_collections)
    d, nh, dm = 8, 2, 16
    pool = PagePool(ctx, 16, 4, d, name="TKV")
    _, _, _, _, names = make_slot_collections(ctx, 4, d, name="TPA",
                                              nh=nh)
    wo = np.zeros((d, dm), np.float32)

    def mk_shard():
        return {"rank": 0, "nranks": 1, "dm": dm,
                "project": lambda o, w=wo: o @ w,
                "sink": lambda seg, slc, x: None}

    seqs = [SeqSpec(0, [0, 1], 2), SeqSpec(1, [2], 1)]
    dec = build_paged_decode(ctx, pool, seqs, names, nh=nh,
                             shard=mk_shard())
    vseqs = [SeqSpec(0, [3, 4], 2), SeqSpec(1, [3], 3)]
    ver = build_paged_verify(ctx, pool, vseqs, names, nh=nh,
                             shard=mk_shard())
    return [("ops_tp_paged_decode", dec), ("ops_tp_paged_verify", ver)]


GENERATORS = {
    "potrf": g_potrf,
    "potrf_textbook": g_potrf_textbook,
    "potrf_panels": g_potrf_panels,
    "potrs_panels": g_potrs_panels,
    "gemm": g_gemm,
    "gemm_dist": g_gemm_dist,
    "trsm": g_trsm,
    "qr": g_qr,
    "lu": g_lu,
    "lu_panels": g_lu_panels,
    "inverse": g_inverse,
    "matrix_ops": g_matrix_ops,
    "map_operator": g_map_operator,
    "reshape": g_reshape,
    "moe": g_moe,
    "ring_attention": g_ring_attention,
    "ops_rms_norm": g_ops_rms_norm,
    "ops_flash_attention": g_ops_flash_attention,
    "paged_attention": g_paged_attention,
    "coll": g_coll,
    "tp_paged": g_tp_paged,
}


def verify_all(only=None, verbose=False):
    """Build + verify every generator.  Yields (name, Report)."""
    from parsec_tpu.analysis import verify_taskpool
    for gname, gen in GENERATORS.items():
        if only and gname not in only:
            continue
        with pt.Context(nb_workers=1) as ctx:
            for tpname, tp in gen(ctx):
                report = verify_taskpool(tp)
                if verbose:
                    print(f"--- {tpname}: {report.text()}")
                yield tpname, report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("only", nargs="*", help="generator names (default all)")
    ap.add_argument("--json", metavar="PATH", default=None)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    dirty = 0
    results = {}
    for name, report in verify_all(args.only or None, args.verbose):
        n_err, n_warn = len(report.errors), len(report.warnings)
        status = "clean" if report.ok() else (
            f"{n_err} error(s), {n_warn} warning(s)")
        print(f"{name:24s} {status}")
        if not report.ok():
            dirty += 1
            if not args.verbose:
                print(report.text())
        results[name] = report.to_json()
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
    print(f"verify-graphs: {len(results)} graph(s), {dirty} with findings")
    return 1 if dirty else 0


if __name__ == "__main__":
    sys.exit(main())
