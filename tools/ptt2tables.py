#!/usr/bin/env python
"""Trace(s) -> pandas trace tables on disk (reference: the Cython
pbt2ptt converter + profile2h5.py, tools/profiling/python/).

Usage: python tools/ptt2tables.py out.h5 rank0.ptt rank1.ptt ...
Merges per-rank traces and writes one table; falls back to CSV when no
HDF5 backend is available in the environment.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.profiling import Trace  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("out")
    ap.add_argument("traces", nargs="+")
    args = ap.parse_args(argv)
    traces = [Trace.load(p) for p in args.traces]
    merged = Trace.merge(traces) if len(traces) > 1 else traces[0]
    df = merged.to_pandas()
    if args.out.endswith(".csv"):
        df.to_csv(args.out, index=False)
    else:
        try:
            df.to_hdf(args.out, key="events", mode="w")
        except ImportError:
            csv = args.out.rsplit(".", 1)[0] + ".csv"
            print(f"no HDF5 backend; writing {csv}", file=sys.stderr)
            df.to_csv(csv, index=False)
    print(f"{len(df)} spans from {len(traces)} rank(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
