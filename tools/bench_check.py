#!/usr/bin/env python
"""Bench-trajectory regression guard (`make bench-check`).

Compares the current BENCH_*.json set against the committed copies and
fails when a guarded metric regressed past its tolerance — the CI gate
that keeps the measurement ladder (BASELINE.md) monotone: dispatch p50,
stream overlap fraction, trace ring ratio and level-0 cost, collective
ratios, device stall reduction.

Baselines come from `git show <ref>:<file>` (default ref HEAD) or from
an explicit `--baseline-dir`.  Current values come from the working
tree (or `--current-dir`).

Oversubscription honesty: the bench suite records an `oversubscribed`
flag when the run timeshared more threads than cores (bench.py
host_provenance).  Timing-sensitive metrics from an oversubscribed run
(current OR baseline) are judged against `--oversub-slack` times the
tolerance — the number measures context-switch luck, so a tight gate
would flap — but they are still judged: a 3x regression fails even on a
1-core box.  Correctness metrics (bit-exactness flags) are never
relaxed.

Exit 0 = all guarded metrics within tolerance; 1 = regression; files
missing on either side are skipped with a note (a bench not yet run is
not a regression).
"""
import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (file, json.path, direction, rel_tol, timing_sensitive)
#   direction: "lower" = lower is better, "higher" = higher is better,
#              "equal" = must match exactly (correctness flags)
CHECKS = [
    ("BENCH_dispatch.json", "single_chain.p50_us", "lower", 0.15, True),
    ("BENCH_dispatch.json", "contended.p50_us", "lower", 0.20, True),
    ("BENCH_stream.json", "streamed.overlap_fraction", "higher", 0.35,
     True),
    ("BENCH_stream.json", "rails2_vs_rails1_throughput", "higher", 0.15,
     True),
    ("BENCH_trace.json", "ns_per_task.0", "lower", 0.05, True),
    ("BENCH_trace.json", "overhead_ns_per_task.level1", "lower", 0.50,
     True),
    ("BENCH_trace.json", "ring.vs_unbounded_level1", "lower", 0.10, True),
    # ptc-blackbox (PR 20): the crash-durable journal must stay
    # invisible to the level-0 dispatch hot path — the on/off ratio is
    # an oversubscription-slacked timing trajectory row, the <= 1.05
    # within_gate verdict an equal-direction flag, never relaxed
    ("BENCH_trace.json", "journal.overhead_ratio", "lower", 0.05, True),
    ("BENCH_trace.json", "journal.within_gate", "equal", 0.0, False),
    ("BENCH_collective.json", "coll_vs_chain_ratio", "lower", 0.25, True),
    ("BENCH_collective.json", "gemm_panel.overlap_fraction_gain",
     "higher", 0.50, True),
    ("BENCH_device.json", "wave_pipeline.hit_wave_stall_reduction",
     "higher", 0.15, True),
    ("BENCH_device.json", "out_of_core_gemm.correct", "equal", 0.0,
     False),
    # ptc-fuse (PR 13): wave mega-kernelization launch economics —
    # launches/task and the fused-vs-unfused launch ratio are
    # trajectory rows (timing-sensitive: partial wave pops under
    # oversubscription split launches, so the slack convention
    # applies); the fused-vs-unfused bit-exactness verdict is a
    # correctness flag, never relaxed
    ("BENCH_device.json", "wave_fuse.launches_per_task", "lower", 0.50,
     True),
    ("BENCH_device.json", "wave_fuse.fused_vs_unfused_ratio", "higher",
     0.35, True),
    ("BENCH_device.json", "wave_fuse.bit_identical", "equal", 0.0,
     False),
    # serving runtime (PR 9): hi-tenant p99 improvement over the no-QoS
    # control is timing (trajectory-guarded, oversubscription-slacked);
    # the in-document beats-control verdict and the continuous-vs-
    # sequential bit-exactness are correctness flags — never relaxed
    ("BENCH_serve.json", "hi_p99_improvement", "higher", 0.50, True),
    ("BENCH_serve.json", "qos.hi_p99_beats_control", "equal", 0.0,
     False),
    ("BENCH_serve.json", "decode.bit_identical", "equal", 0.0, False),
    # ptc-scope (PR 11): tenant SLO trajectory rows (timing,
    # oversubscription-slacked per convention) + the conformance
    # soundness verdict — full plan coverage and no pool beating its
    # makespan lower bound is CORRECTNESS, never relaxed
    ("BENCH_serve.json", "scope.ttft_p99_ms.hi", "lower", 0.50, True),
    ("BENCH_serve.json", "scope.tokens_per_s_p50.hi", "higher", 0.50,
     True),
    ("BENCH_serve.json", "scope.conformance.sound", "equal", 0.0, False),
    # ptc-share (PR 14): prefix-cache hit rate + warm tokens/s and the
    # k=4 speculative tokens/s are oversubscription-slacked timing
    # trajectory rows; warm-run and speculative bit-exactness vs the
    # cold / non-speculative baselines are equal-direction correctness
    # flags — never relaxed — as are the fewer-prefill-waves and
    # single-fused-verify-launch evidence verdicts
    ("BENCH_serve.json", "prefix.hit_rate", "higher", 0.50, True),
    ("BENCH_serve.json", "prefix.warm_tokens_per_s", "higher", 0.50,
     True),
    ("BENCH_serve.json", "prefix.bit_identical", "equal", 0.0, False),
    ("BENCH_serve.json", "prefix.fewer_prefill_than_cold", "equal", 0.0,
     False),
    ("BENCH_serve.json", "spec.k4.tokens_per_s", "higher", 0.50, True),
    ("BENCH_serve.json", "spec.bit_identical", "equal", 0.0, False),
    ("BENCH_serve.json", "spec.verify_wave.single_fused_launch",
     "equal", 0.0, False),
    # ptc-route (PR 16): 2-replica fleet scaling and global prefix hit
    # rate are oversubscription-slacked timing trajectory rows (both
    # replicas timeshare one process's cores); the routed-vs-single
    # bit_identical verdict is an equal-direction correctness flag —
    # never relaxed
    ("BENCH_serve.json", "fleet.scaling", "higher", 0.50, True),
    ("BENCH_serve.json", "fleet.hit_rate", "higher", 0.50, True),
    ("BENCH_serve.json", "fleet.bit_identical", "equal", 0.0, False),
    # ptc-blackbox (PR 20): one FleetView federation refresh over both
    # replicas (tenant histogram merge + advertise) — timing row
    ("BENCH_serve.json", "fleet.fleet_scrape_ms", "lower", 0.50, True),
    # ptc-shard (PR 18): 2-/4-rank tensor-parallel decode vs the
    # single-rank reference — bit_identical (tokens AND exact f32
    # pre-logit bytes, prefix cache + speculative decoding live) and
    # the fused_waves>0-on-every-rank verdict are equal-direction
    # correctness flags, never relaxed; the tp4-vs-tp1 per-token wall
    # ratio is a timing trajectory row, oversubscription-slacked (all
    # ranks timeshare one host)
    ("BENCH_serve.json", "tp.bit_identical", "equal", 0.0, False),
    ("BENCH_serve.json", "tp.all_ranks_fused", "equal", 0.0, False),
    ("BENCH_serve.json", "tp.tp4_vs_tp1_ms_per_token", "lower", 0.50,
     True),
    # ptc-tune (PR 12): autotuned-vs-default ratios on the dispatch
    # chain and the 2-rank collective — timing trajectory rows,
    # oversubscription-slacked per convention; the beats_default
    # verdicts are equal-direction correctness flags, never relaxed
    ("BENCH_dispatch.json", "tuned.tuned_vs_default", "lower", 0.25,
     True),
    ("BENCH_dispatch.json", "tuned.beats_default", "equal", 0.0, False),
    ("BENCH_collective.json", "tuned.tuned_vs_default", "lower", 0.25,
     True),
    ("BENCH_collective.json", "tuned.beats_default", "equal", 0.0,
     False),
    ("BENCH_stream.json", "tuned.tuned_vs_default", "lower", 0.25,
     True),
    ("BENCH_stream.json", "tuned.beats_default", "equal", 0.0, False),
    # ptc-pilot (PR 19): the drift-soak recovery ratio is a timing
    # trajectory row (oversubscription-slacked), but the in-document
    # `recovered` verdict (>= 50% of incident-lost throughput clawed
    # back by the hot-swap, no restart) is an equal-direction
    # correctness flag — never relaxed — as are the adaptive-vs-fixed
    # spec_k verdict (deterministic wave/waste counts, not wall time)
    # and the every-k bit-identity of the token streams
    ("BENCH_control.json", "soak.recovery_ratio", "higher", 0.50, True),
    ("BENCH_control.json", "soak.recovered", "equal", 0.0, False),
    ("BENCH_control.json", "spec.adaptive_ge_best_fixed", "equal", 0.0,
     False),
    ("BENCH_control.json", "spec.bit_identical", "equal", 0.0, False),
    ("BENCH_control.json", "spec.adaptive_score", "higher", 0.25,
     False),
    # ptc-topo (PR 17): bit_identical and predicted_sound are
    # equal-direction correctness flags — the remapped run and the
    # hierarchical collectives must stay bit-exact and the plan's
    # per-class byte split must never under-bound the wire — never
    # relaxed.  dcn_reduction and the hier-vs-ring byte ratio are
    # deterministic byte-count trajectories (small control-plane
    # jitter only); the hier-vs-ring wall is a timing row,
    # oversubscription-slacked (4 ranks timeshare one host).
    ("BENCH_topo.json", "bit_identical", "equal", 0.0, False),
    ("BENCH_topo.json", "remap.predicted_sound", "equal", 0.0, False),
    ("BENCH_topo.json", "remap.dcn_reduction", "higher", 0.25, False),
    ("BENCH_topo.json", "allreduce.dcn_ratio_hier_vs_ring", "lower",
     0.25, False),
    ("BENCH_topo.json", "allreduce.hier_vs_ring", "lower", 0.50, True),
    # ptc-plan analyzer runtime on the potrf bench tiling (NT=16, 816
    # instances; PR 10): `make plan-graphs` emits the number, the 5 s
    # absolute budget lives in tools/plan_graphs.py — this row guards
    # the trajectory so the analyzer cannot quietly get 2x slower
    ("PLAN_graphs.json", "potrf_nt16_ms", "lower", 1.0, True),
]


def dig(obj, path):
    cur = obj
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def is_oversubscribed(doc) -> bool:
    """The recorded flag, wherever the suite put it (top level for the
    stream/trace/device/collective suites; per-section for dispatch)."""
    if not isinstance(doc, dict):
        return False
    if doc.get("oversubscribed"):
        return True
    for v in doc.values():
        if isinstance(v, dict) and v.get("oversubscribed"):
            return True
    return False


def load_current(fname, current_dir):
    path = os.path.join(current_dir, fname)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def load_baseline(fname, baseline_dir, ref):
    if baseline_dir:
        path = os.path.join(baseline_dir, fname)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)
    try:
        out = subprocess.run(["git", "show", f"{ref}:{fname}"], cwd=REPO,
                             capture_output=True, check=True)
        return json.loads(out.stdout)
    except (subprocess.CalledProcessError, json.JSONDecodeError, OSError):
        return None


def check_all(current_dir, baseline_dir=None, ref="HEAD",
              oversub_slack=3.0):
    """Returns (rows, failures): rows are report dicts per metric."""
    cur_docs, base_docs = {}, {}
    rows, failures = [], 0
    for fname, path, direction, tol, timing in CHECKS:
        if fname not in cur_docs:
            cur_docs[fname] = load_current(fname, current_dir)
            base_docs[fname] = load_baseline(fname, baseline_dir, ref)
        cur_doc, base_doc = cur_docs[fname], base_docs[fname]
        row = {"file": fname, "metric": path, "direction": direction,
               "tol": tol}
        if cur_doc is None or base_doc is None:
            row["verdict"] = "skip"
            row["note"] = ("no current file" if cur_doc is None
                           else "no baseline")
            rows.append(row)
            continue
        cur, base = dig(cur_doc, path), dig(base_doc, path)
        row["current"], row["baseline"] = cur, base
        if cur is None or base is None:
            row["verdict"] = "skip"
            row["note"] = "metric missing"
            rows.append(row)
            continue
        if direction == "equal":
            ok = cur == base
            row["verdict"] = "ok" if ok else "FAIL"
            failures += 0 if ok else 1
            rows.append(row)
            continue
        eff_tol = tol
        oversub = is_oversubscribed(cur_doc) or is_oversubscribed(base_doc)
        if timing and oversub:
            eff_tol = tol * oversub_slack
            row["oversubscribed"] = True
            row["tol"] = eff_tol
        try:
            cur_f, base_f = float(cur), float(base)
        except (TypeError, ValueError):
            row["verdict"] = "skip"
            row["note"] = "non-numeric"
            rows.append(row)
            continue
        if base_f == 0:
            # regression direction still checkable against an absolute
            # epsilon of the tolerance itself
            delta = cur_f - base_f
            regressed = (delta > eff_tol if direction == "lower"
                         else delta < -eff_tol)
            row["delta"] = round(delta, 4)
        else:
            rel = (cur_f - base_f) / abs(base_f)
            regressed = (rel > eff_tol if direction == "lower"
                         else rel < -eff_tol)
            row["delta_rel"] = round(rel, 4)
        row["verdict"] = "FAIL" if regressed else "ok"
        failures += 1 if regressed else 0
        rows.append(row)
    return rows, failures


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--current-dir", default=REPO,
                    help="directory holding the fresh BENCH_*.json set")
    ap.add_argument("--baseline-dir", default=None,
                    help="directory of baseline copies (default: git)")
    ap.add_argument("--ref", default="HEAD",
                    help="git ref for baselines (default HEAD)")
    ap.add_argument("--oversub-slack", type=float, default=3.0,
                    help="tolerance multiplier for timing metrics from "
                         "oversubscribed runs (default 3.0)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    args = ap.parse_args(argv)

    rows, failures = check_all(args.current_dir, args.baseline_dir,
                               args.ref, args.oversub_slack)
    if args.json:
        print(json.dumps({"failures": failures, "checks": rows},
                         indent=2))
    else:
        for r in rows:
            extra = ""
            if "delta_rel" in r:
                extra = f" ({r['delta_rel']:+.1%})"
            elif "delta" in r:
                extra = f" ({r['delta']:+g})"
            if r.get("oversubscribed"):
                extra += " [oversubscribed: slacked]"
            if r["verdict"] == "skip":
                print(f"skip  {r['file']}:{r['metric']} — {r['note']}")
            else:
                print(f"{r['verdict']:<5} {r['file']}:{r['metric']} "
                      f"{r.get('baseline')} -> {r.get('current')}{extra}")
        print(f"bench-check: {failures} regression(s)"
              if failures else "bench-check: ok")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
