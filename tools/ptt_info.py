#!/usr/bin/env python
"""Trace inspector (reference: tools/profiling/dbpinfos.c).

Usage: python tools/ptt_info.py trace.ptt [more.ptt ...]
Prints per-file dictionary, event counts, span statistics per task class.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.profiling import Trace  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("traces", nargs="+")
    args = ap.parse_args(argv)
    for path in args.traces:
        t = Trace.load(path)
        print(f"== {path} (rank {t.rank}, {len(t.events)} events)")
        for k, v in sorted(t.dict.keys.items()):
            print(f"   key {k}: {v['name']} {v['color']}")
        for name, cnt in sorted(t.counts().items()):
            print(f"   {name}: {cnt}")
        df = t.to_pandas()
        if len(df):
            g = df.groupby("class_name")["dur_ns"]
            for cname, stats in g.agg(["count", "median", "sum"]).iterrows():
                print(f"   {cname}: n={int(stats['count'])} "
                      f"p50={stats['median'] / 1e3:.2f}us "
                      f"total={stats['sum'] / 1e6:.3f}ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
